#!/usr/bin/env python
"""Render one telemetry directory into a single markdown run report.

Reads the three sinks a `train.py --telemetry-dir DIR` run writes —
`spans.jsonl` (Chrome-trace phase events), `resources.jsonl` (RSS /
device memory / XLA recompiles), `events.jsonl` (health + lifecycle
events) — plus the run's `--metrics` JSONL when present, and prints a
markdown report with the per-phase time breakdown the ISSUE's freeze
post-mortems needed (which phase ate the wall clock, whether memory
crept, which health events fired).

    python scripts/run_report.py /tmp/t
    python scripts/run_report.py /tmp/t --metrics runs/m.jsonl
    python scripts/run_report.py /tmp/t --trace          # + trace.json

`--trace` additionally wraps the span lines into `{"traceEvents":
[...]}` at DIR/trace.json, the file Perfetto (https://ui.perfetto.dev)
and chrome://tracing open directly; the JSONL itself is one event per
line so a torn final line (stall-kill teardown) costs one event, not
the file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def read_jsonl(path: str) -> list[dict]:
    """Rows of a JSONL file. A torn FINAL line (process killed
    mid-write — the exact scenario telemetry exists to explain) is
    dropped silently; undecodable lines anywhere else mean real
    corruption, so they are dropped with one stderr note naming the
    file and count instead of aborting the report."""
    rows: list[dict] = []
    if not os.path.exists(path):
        return rows
    # Streamed, not materialized: a sharded run's spans.jsonl can be
    # hundreds of MB (one relayed span per worker per batch step), and
    # holding raw lines AND parsed rows would double peak memory. A bad
    # line is only counted once a LATER non-blank line proves it wasn't
    # the file's final (torn) one.
    bad_interior = 0
    last_bad = False
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if last_bad:
                bad_interior += 1
                last_bad = False
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                last_bad = True
    if bad_interior:
        print(
            f"warning: {path}: dropped {bad_interior} undecodable "
            "non-final line(s)",
            file=sys.stderr,
        )
    return rows


def np_mean(xs: list) -> float:
    """Mean without numpy (this script must render anywhere)."""
    return sum(xs) / len(xs) if xs else 0.0


def _fmt_s(seconds: float) -> str:
    if seconds >= 60:
        return f"{seconds / 60:.1f}m"
    if seconds >= 1:
        return f"{seconds:.2f}s"
    return f"{seconds * 1e3:.1f}ms"


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} GiB"


def _self_durations(complete: list[dict]) -> list[tuple[str, float]]:
    """(name, self_us) per complete event: its duration minus the time
    covered by spans nested inside it (same process/thread, interval
    containment). Phases can nest — the fused loop's `eval` runs inside
    the `log` span — and raw durations would count the inner seconds in
    BOTH rows (and twice in a summed-phase denominator); self time
    attributes every host second to exactly one phase."""
    groups: dict[tuple, list[dict]] = {}
    for e in complete:
        groups.setdefault((e.get("pid"), e.get("tid")), []).append(e)
    out: list[list] = []
    for evs in groups.values():
        # Spans are written at EXIT, so file order is end order; sort by
        # start, parents (longer) before the children they open with.
        evs.sort(
            key=lambda e: (float(e.get("ts", 0.0)), -float(e.get("dur", 0.0)))
        )
        stack: list[tuple[float, list]] = []  # (end_us, row)
        for e in evs:
            ts = float(e.get("ts", 0.0))
            dur = float(e.get("dur", 0.0))
            while stack and ts >= stack[-1][0]:
                stack.pop()
            row = [e.get("name", "?"), dur]
            if stack:
                parent = stack[-1][1]
                parent[1] = max(0.0, parent[1] - dur)
            out.append(row)
            stack.append((ts + dur, row))
    return [(name, self_us) for name, self_us in out]


def phase_breakdown(spans: list[dict]) -> list[str]:
    """Markdown lines for the per-phase table. `iteration` is the
    enclosing span (one per loop iteration); every other complete event
    is a phase nested inside it, so phase %s are of summed iteration
    wall, the denominator a freeze post-mortem cares about. Phase time
    is SELF time (nested spans subtracted, see `_self_durations`)."""
    complete = [e for e in spans if e.get("ph") == "X"]
    instants = [e for e in spans if e.get("ph") == "i"]
    if not complete and not instants:
        return ["*(no span events)*"]
    # Relayed worker-lane spans run in W processes CONCURRENT with the
    # parent's iteration wall: summing them into a table whose shares
    # are of parent wall would print >100% rows. Summarize them apart.
    workers = [e for e in complete if e.get("name") == "env_step_worker"]
    complete = [e for e in complete if e.get("name") != "env_step_worker"]
    iters = [e for e in complete if e.get("name") == "iteration"]
    iter_total_us = sum(float(e.get("dur", 0.0)) for e in iters)
    phases: dict[str, dict] = {}
    for name, self_us in _self_durations(complete):
        if name == "iteration":
            continue
        p = phases.setdefault(name, {"count": 0, "total_us": 0.0, "max_us": 0.0})
        p["count"] += 1
        p["total_us"] += self_us
        p["max_us"] = max(p["max_us"], self_us)
    denom_us = iter_total_us or sum(p["total_us"] for p in phases.values())
    out = []
    if iters:
        out.append(
            f"{len(iters)} iteration span(s), "
            f"{_fmt_s(iter_total_us / 1e6)} total "
            f"({_fmt_s(iter_total_us / 1e6 / len(iters))}/iter mean); "
            f"shares are of summed iteration wall."
        )
    else:
        out.append(
            "No enclosing iteration spans (fused loop); shares are of "
            "summed phase time."
        )
    out.append("")
    out.append("| phase | count | total | mean | max | share |")
    out.append("|---|---:|---:|---:|---:|---:|")
    for name, p in sorted(
        phases.items(), key=lambda kv: -kv[1]["total_us"]
    ):
        pct = 100.0 * p["total_us"] / denom_us if denom_us else 0.0
        out.append(
            f"| {name} | {p['count']} | {_fmt_s(p['total_us'] / 1e6)} "
            f"| {_fmt_s(p['total_us'] / 1e6 / p['count'])} "
            f"| {_fmt_s(p['max_us'] / 1e6)} | {pct:.1f}% |"
        )
    if workers:
        by_pid: dict = {}
        for e in workers:
            p = by_pid.setdefault(e.get("pid"), [0, 0.0])
            p[0] += 1
            p[1] += float(e.get("dur", 0.0))
        out.append("")
        out.append(
            f"Env-pool worker lanes (concurrent with the table above, "
            f"so not in its shares): {len(by_pid)} worker process(es), "
            + ", ".join(
                f"pid {pid}: {n} steps / {_fmt_s(d / 1e6)} busy"
                for pid, (n, d) in sorted(by_pid.items())
            )
            + " — per-step detail in the Perfetto trace."
        )
    if instants:
        by_name: dict[str, int] = {}
        for e in instants:
            by_name[e.get("name", "?")] = by_name.get(e.get("name", "?"), 0) + 1
        marks = ", ".join(f"{k} ×{v}" for k, v in sorted(by_name.items()))
        out.append("")
        out.append(
            f"Instant marks (phases fused into the XLA program, no "
            f"separable host duration): {marks}."
        )
    return out


def resource_summary(rows: list[dict]) -> list[str]:
    if not rows:
        return ["*(no resource samples)*"]
    out = [f"{len(rows)} samples over {_fmt_s(rows[-1]['ts'] - rows[0]['ts'])}."]
    rss = [r["rss_bytes"] for r in rows if "rss_bytes" in r]
    if rss:
        out.append(
            f"- **RSS**: start {_fmt_bytes(rss[0])}, end {_fmt_bytes(rss[-1])}, "
            f"peak {_fmt_bytes(max(rss))} "
            f"(drift {_fmt_bytes(rss[-1] - rss[0])})"
        )
    # Startup compilation is expected; compiles in the LAST HALF of the
    # samples are the recompile-storm signal (the silent throughput
    # killer this sampler exists to catch). The counter is per-process
    # and the files append across resume retries, so a decrease marks a
    # new process: sum positive deltas, never raw endpoints.
    rec = [r.get("recompiles", 0) for r in rows]

    def growth(seq):
        return (seq[0] if seq else 0) + sum(
            max(0, b - a) for a, b in zip(seq, seq[1:])
        )

    late = growth(rec[len(rec) // 2:]) - rec[len(rec) // 2]
    # A handful of mid-run compiles is legitimate (first eval jit, a
    # chunk re-jit); a storm re-compiles every iteration. Flag only past
    # the legitimate-singles scale.
    storm = " — RECOMPILE STORM?" if late >= 10 else ""
    out.append(
        f"- **XLA recompiles**: {growth(rec)} total; {late} in the last "
        f"half of the samples{storm}"
    )
    # Async actor–learner trajectory queue (algos/traj_queue.py gauge):
    # depth says whether actors outrun the learner, observe-staleness is
    # the behavior-version lag of consumed blocks, drops are the
    # back-pressure record (full = drop-oldest recycles, stale = aged
    # past --max-staleness), learner idle is the decoupling's residual
    # wait. Counters reset per process, so the LAST row is the run's
    # cumulative tally (matching the recompile convention above).
    q_rows = [
        r["traj_queue"] for r in rows
        if isinstance(r.get("traj_queue"), dict)
    ]
    if q_rows:
        depths = [q.get("depth", 0) for q in q_rows]
        last_q = q_rows[-1]
        out.append(
            f"- **traj queue**: depth mean {np_mean(depths):.1f} / max "
            f"{max(depths)} (capacity {last_q.get('capacity', '?')}); "
            f"staleness last {last_q.get('observe_staleness', 0)} / max "
            f"{last_q.get('staleness_max', 0)}; drops "
            f"{last_q.get('drops_full', 0)} full + "
            f"{last_q.get('drops_stale', 0)} stale; learner idle "
            f"{_fmt_s(float(last_q.get('learner_idle_s', 0.0)))}"
        )
    # Off-policy replay ring (host_loop's static gauge, ISSUE 8): ring
    # size, bytes/transition vs the fp32 reference, and the per-leaf
    # codec mix — the capacity-per-HBM-byte evidence behind
    # --replay-dtype. Static facts, so the LAST row suffices.
    rp_rows = [
        r["replay"] for r in rows if isinstance(r.get("replay"), dict)
    ]
    if rp_rows:
        rp = rp_rows[-1]
        out.append(
            f"- **replay ring**: {rp.get('capacity', '?')} slots x "
            f"{rp.get('bytes_per_transition', '?')} B/transition "
            f"({_fmt_bytes(rp.get('ring_bytes', 0))} total, mode "
            f"{rp.get('mode', 'fp32')}); fp32 reference "
            f"{rp.get('fp32_bytes_per_transition', '?')} B — "
            f"{rp.get('capacity_multiplier', 1.0)}x transitions/byte; "
            f"codecs {rp.get('codec_mix', '?')}"
        )
    # Device trajectory ring (data_plane/ring.py gauge, ISSUE 13):
    # slots x encoded bytes/block x codec mix is the static shape of
    # the HBM data plane; the enqueue-byte total vs the raw figure
    # shows what the codec saved, and the TrajQueue-compatible counters
    # carry the same back-pressure story as the traj-queue row. Static
    # + cumulative facts, so the LAST row suffices.
    dr_rows = [
        r["device_ring"] for r in rows
        if isinstance(r.get("device_ring"), dict)
    ]
    if dr_rows:
        dr = dr_rows[-1]
        out.append(
            f"- **device ring**: {dr.get('slots', '?')} slots x "
            f"{dr.get('bytes_per_block', '?')} B/block encoded "
            f"(raw {dr.get('raw_bytes_per_block', '?')} B; codecs "
            f"{dr.get('codec_mix', '?')}); enqueue transfers "
            f"{_fmt_bytes(dr.get('enqueue_bytes', 0))} total, consume "
            f"transfers {dr.get('consume_transfer_bytes', 0)} B; "
            f"staleness last {dr.get('observe_staleness', 0)} / max "
            f"{dr.get('staleness_max', 0)}; drops "
            f"{dr.get('drops_full', 0)} full + "
            f"{dr.get('drops_stale', 0)} stale; learner idle "
            f"{_fmt_s(float(dr.get('learner_idle_s', 0.0)))}"
        )
    # Policy-serving gateway (serving/batcher.py gauge, ISSUE 10):
    # latency percentiles and occupancy say whether the micro-batch
    # window is tuned right; rejected counts are the 503 back-pressure
    # record. Counters are cumulative, so the LAST row is the tally
    # (recompile convention above); queue depth trends across rows.
    sv_rows = [
        r["serving"] for r in rows if isinstance(r.get("serving"), dict)
    ]
    if sv_rows:
        depths = [s.get("queue_depth", 0) for s in sv_rows]
        last_s = sv_rows[-1]
        out.append(
            f"- **serving**: {last_s.get('requests_total', 0)} requests / "
            f"{last_s.get('actions_total', 0)} actions "
            f"({last_s.get('flushes_total', 0)} flushes, occupancy "
            f"{last_s.get('batch_occupancy', 0.0):.2f}); latency p50 "
            f"{last_s.get('latency_p50_ms', 0.0)} ms / p99 "
            f"{last_s.get('latency_p99_ms', 0.0)} ms; queue depth mean "
            f"{np_mean(depths):.1f} / max {max(depths)}; rejected "
            f"{last_s.get('rejected_total', 0)}, errors "
            f"{last_s.get('errors_total', 0)}"
        )
    # Scenario-mixture per-type eval gauge (envs/mixture.py, ISSUE 11):
    # flat `<member>_return` / `<member>_solved` fields; the LAST row is
    # the latest eval matrix. The metrics section renders the full
    # per-round matrix; this line keeps it visible on telemetry alone.
    mx_rows = [
        r["mixture_eval"] for r in rows
        if isinstance(r.get("mixture_eval"), dict)
    ]
    if mx_rows and mx_rows[-1]:
        last_m = mx_rows[-1]
        cells = []
        for key in sorted(last_m):
            if not key.endswith("_return"):
                continue
            name = key[: -len("_return")]
            solved = last_m.get(f"{name}_solved")
            tag = " (solved)" if solved else ""
            cells.append(f"{name} {last_m[key]:g}{tag}")
        if cells:
            out.append("- **mixture eval matrix**: " + ", ".join(cells))
    # Per-device peaks across the run (devices without allocator stats,
    # e.g. CPU, appear with no byte fields and are reported as such).
    dev_peak: dict[int, dict] = {}
    for r in rows:
        for d in r.get("devices", []):
            cur = dev_peak.setdefault(d["id"], dict(d))
            for k in ("live_bytes", "peak_bytes"):
                if k in d:
                    cur[k] = max(cur.get(k, 0), d[k])
    for did in sorted(dev_peak):
        d = dev_peak[did]
        if "peak_bytes" in d or "live_bytes" in d:
            out.append(
                f"- **device {did}** ({d.get('platform', '?')}): "
                f"peak {_fmt_bytes(d.get('peak_bytes', d.get('live_bytes', 0)))}, "
                f"max live {_fmt_bytes(d.get('live_bytes', 0))}"
            )
        else:
            out.append(
                f"- **device {did}** ({d.get('platform', '?')}): "
                f"no allocator stats on this backend"
            )
    return out


def compile_attribution(rows: list[dict]) -> list[str]:
    """Markdown lines for the recompile-attribution table: `compile`
    events (telemetry/profiler.py's compile listener) grouped by jitted
    function, with compile wall, cost_analysis() FLOPs, and — the
    recompile-storm diagnosis — the DISTINCT abstract argument
    signatures seen, so a function compiled 40 times shows exactly which
    arg shape/dtype kept changing."""
    comps = [r for r in rows if r.get("kind") == "compile"]
    if not comps:
        return [
            "*(no `compile` events — run predates the compile listener, "
            "or the JAX compile funnel was unavailable; the resource "
            "sampler's recompile counter above still applies)*"
        ]
    by_name: dict[str, dict] = {}
    for r in comps:
        g = by_name.setdefault(
            r.get("name", "?"),
            {"count": 0, "hits": 0, "total_s": 0.0, "flops": None,
             "sigs": []},
        )
        g["count"] += 1
        if r.get("cache_hit"):
            g["hits"] += 1
        g["total_s"] += float(r.get("compile_s", 0.0))
        if r.get("flops") is not None:
            g["flops"] = float(r["flops"])  # last compile's program
        sig = r.get("signature")
        if sig is not None and sig not in g["sigs"]:
            g["sigs"].append(sig)
    # The listener hooks the compile funnel, which persistent-cache HITS
    # also pass through: attributed events carry an explicit `cache_hit`
    # flag (ISSUE 4); for older runs without the flag, fall back to the
    # near-zero-wall signal — either way a warm-cache run must not be
    # misread as a recompile storm when the jax.monitoring counter
    # (Resources section) stays low.
    attributed_hits = sum(1 for r in comps if r.get("cache_hit"))
    if attributed_hits:
        fast_note = (
            f" ({attributed_hits} persistent-cache hit(s) — "
            "deserialized, not recompiled)"
        )
    else:
        fast = sum(
            1 for r in comps if float(r.get("compile_s", 0.0)) < 0.01
        )
        fast_note = (
            f" ({fast} under 10 ms — likely compilation-cache hits, "
            "not real recompiles)" if fast else ""
        )
    out = [
        f"{len(comps)} XLA compilation(s), "
        f"{_fmt_s(sum(g['total_s'] for g in by_name.values()))} total "
        f"compile wall{fast_note}.",
        "",
        "| function | compiles | cache hits | compile wall | FLOPs/call "
        "| distinct arg signatures |",
        "|---|---:|---:|---:|---:|---:|",
    ]
    for name, g in sorted(by_name.items(), key=lambda kv: -kv[1]["total_s"]):
        flops = f"{g['flops']:.3g}" if g["flops"] is not None else "n/a"
        out.append(
            f"| `{name}` | {g['count']} | {g['hits']} "
            f"| {_fmt_s(g['total_s'])} | {flops} | {len(g['sigs'])} |"
        )
    # Name the churn: a function with one signature compiled once is
    # startup; several signatures is shape/dtype churn worth reading.
    for name, g in sorted(by_name.items(), key=lambda kv: -kv[1]["total_s"]):
        if len(g["sigs"]) > 1:
            out.append("")
            out.append(
                f"`{name}` recompiled under {len(g['sigs'])} argument "
                "signatures (shape/dtype churn):"
            )
            out.extend(f"- `{s}`" for s in g["sigs"][:8])
            if len(g["sigs"]) > 8:
                out.append(f"- … {len(g['sigs']) - 8} more")
    return out


def slowest_spans(spans: list[dict], k: int = 10) -> list[str]:
    """Top-K complete spans by raw duration — the individual stalls a
    phase MEAN hides (one 40 s checkpoint inside 500 × 80 ms ones).
    Container spans are excluded: an `iteration` always outlasts every
    phase inside it (and a `profile` window spans several iterations),
    so ranking them would fill the table with enclosures instead of the
    slow phases the section exists to surface."""
    containers = {"iteration", "profile"}
    complete = [
        e for e in spans
        if e.get("ph") == "X" and e.get("name") not in containers
    ]
    if not complete:
        return ["*(no span events)*"]
    top = sorted(
        complete, key=lambda e: -float(e.get("dur", 0.0))
    )[:max(k, 1)]
    out = [
        "| rank | phase | duration | start | pid | args |",
        "|---:|---|---:|---:|---:|---|",
    ]
    for i, e in enumerate(top, 1):
        args = json.dumps(e.get("args", {}), default=str)
        if len(args) > 60:
            args = args[:57] + "…"
        out.append(
            f"| {i} | {e.get('name', '?')} "
            f"| {_fmt_s(float(e.get('dur', 0.0)) / 1e6)} "
            f"| +{_fmt_s(float(e.get('ts', 0.0)) / 1e6)} "
            f"| {e.get('pid', '?')} | `{args}` |"
        )
    return out


def request_traces(spans: list[dict], k: int = 10) -> list[str]:
    """Markdown lines for the per-request critical-path table (ISSUE
    16): every traced /v1/act request's hop durations, joined on the
    `trace` span arg (parse/queue/respond) and on the `flush` arg
    (queue_wait → the serve_dispatch flush that actually served it).
    Empty when the run has no serving spans, so training-only reports
    don't grow a no-op section."""
    complete = [e for e in spans if e.get("ph") == "X"]

    def by_trace(name: str) -> dict:
        out: dict = {}
        for e in complete:
            if e.get("name") == name:
                t = (e.get("args") or {}).get("trace")
                if t is not None and t not in out:
                    out[t] = e
        return out

    reqs = by_trace("serve_request")
    if not reqs:
        return []
    parses = by_trace("serve_parse")
    queues = by_trace("serve_queue_wait")
    responds = by_trace("serve_respond")
    flushes: dict = {}
    for e in complete:
        if e.get("name") == "serve_dispatch":
            fl = (e.get("args") or {}).get("flush")
            if fl is not None and fl not in flushes:
                flushes[fl] = e

    def ms(e) -> str:
        if e is None:
            return "—"
        return f"{float(e.get('dur', 0.0)) / 1e3:.2f}"

    top = sorted(
        reqs.items(), key=lambda kv: -float(kv[1].get("dur", 0.0))
    )[:max(k, 1)]
    out = [
        f"{len(reqs)} traced request(s); the {len(top)} slowest by "
        "total, hop durations in ms (dispatch is the whole micro-batch "
        "flush the request rode — shared with its batchmates; respond "
        "is the post-handler socket write, outside total):",
        "",
        "| trace | status | total | parse | queue wait | dispatch "
        "| flush | occupancy | respond |",
        "|---|---:|---:|---:|---:|---:|---:|---:|---:|",
    ]
    for tid, e in top:
        q = queues.get(tid)
        fl = (q.get("args") or {}).get("flush") if q else None
        d = flushes.get(fl)
        occ = (d.get("args") or {}).get("occupancy") if d else None
        out.append(
            f"| `{tid}` | {(e.get('args') or {}).get('status', '?')} "
            f"| {ms(e)} | {ms(parses.get(tid))} | {ms(q)} | {ms(d)} "
            f"| {fl if fl is not None else '—'} "
            f"| {occ if occ is not None else '—'} "
            f"| {ms(responds.get(tid))} |"
        )
    out.append("")
    out.append(
        "*Flow-linked in Perfetto: `--trace`, then follow a request's "
        "arrows from its gateway-thread slice through the dispatcher "
        "flush that served it.*"
    )
    return out


def flight_summary(telemetry_dir: str, window_s: float = 5.0) -> list[str]:
    """Markdown lines for the flight-recorder section (ISSUE 16): the
    newest `flight_dump_*.json` in the directory — a stalled/killed
    run's last-N-records ring, dumped by the session on stall/divergence
    or harvested post-mortem by fleetsan — rendered as the final
    `window_s` seconds before the dump. Empty when no dump exists."""
    try:
        names = os.listdir(telemetry_dir)
    except OSError:
        return []
    dumps = sorted(
        os.path.join(telemetry_dir, n) for n in names
        if n.startswith("flight_dump_") and n.endswith(".json")
    )
    if not dumps:
        return []
    path = max(dumps, key=os.path.getmtime)
    try:
        with open(path) as f:
            body = json.load(f)
    except (OSError, ValueError):
        return [f"*(malformed flight dump: `{path}`)*"]
    records = [r for r in body.get("records", []) if isinstance(r, dict)]
    out = [
        f"Dump `{os.path.basename(path)}` (reason: "
        f"**{body.get('reason', '?')}**"
        + (f", of {len(dumps)} dumps" if len(dumps) > 1 else "")
        + f"); meta `{json.dumps(body.get('meta', {}), default=str)}`; "
        f"{len(records)} ring record(s)."
    ]
    if not records:
        return out
    tmax = max(float(r.get("t", 0.0)) for r in records)
    recent = [
        r for r in records if float(r.get("t", 0.0)) >= tmax - window_s
    ]
    kinds: dict[str, int] = {}
    for r in recent:
        kinds[r.get("kind", "?")] = kinds.get(r.get("kind", "?"), 0) + 1
    out.append(
        f"Last {window_s:g}s before the dump: {len(recent)} record(s) — "
        + ", ".join(f"{k} ×{v}" for k, v in sorted(kinds.items()))
        + "."
    )
    out.append("")
    out.append("| t (s before dump) | kind | detail |")
    out.append("|---:|---|---|")
    for r in recent[-15:]:
        detail = {
            k: v for k, v in r.items() if k not in ("t", "kind")
        }
        txt = json.dumps(detail, default=str)
        if len(txt) > 80:
            txt = txt[:77] + "…"
        out.append(
            f"| -{tmax - float(r.get('t', 0.0)):.3f} "
            f"| **{r.get('kind', '?')}** | `{txt}` |"
        )
    return out


def profile_captures(rows: list[dict], telemetry_dir: str) -> list[str]:
    """Links to on-demand profile captures: `profile_done` events plus
    any profile_* directories present on disk that lack an event (a
    capture cut short by a kill still leaves its directory)."""
    # Keyed by BASENAME, not raw path: the events record the path the
    # training process used, which may be relative (or under a
    # since-moved root) while the report runs against the absolute dir —
    # a raw-string match would list one capture twice, once mislabeled
    # as interrupted. profile_NNN names are unique per telemetry dir.
    seen: dict[str, dict] = {}
    for r in rows:
        if r.get("kind") == "profile_done" and r.get("path"):
            seen[os.path.basename(os.path.normpath(str(r["path"])))] = r
    import glob as _glob

    on_disk = {
        os.path.basename(os.path.normpath(p)): p
        for p in _glob.glob(os.path.join(telemetry_dir, "profile_*"))
    }
    if not seen and not on_disk:
        return [
            "*(no captures — arm one on a live run with "
            "`curl localhost:PORT/profile?iters=5` or `kill -USR2 <pid>`)*"
        ]
    out = []
    for base in sorted(set(seen) | set(on_disk)):
        r = seen.get(base)
        path = on_disk.get(base) or str(r["path"])
        detail = (
            f" — {_fmt_s(float(r['wall_s']))} captured"
            if r is not None and "wall_s" in r
            else " — no profile_done event (capture interrupted?)"
        )
        out.append(f"- `{path}`{detail}")
    out.append("")
    out.append(
        "*Open a capture: `tensorboard --logdir <dir>` (Profile tab) or "
        "load its `perfetto_trace.json.gz` at https://ui.perfetto.dev.*"
    )
    return out


def event_summary(rows: list[dict]) -> list[str]:
    # Diagnostic streams get their own report sections; listing each
    # compile/profile row here would drown the health table.
    lifecycle = {
        "session_start", "session_end", "exporter_start",
        "compile", "profile_start", "profile_done", "profile_failed",
    }
    health = [r for r in rows if r.get("kind") not in lifecycle]
    starts = [r for r in rows if r.get("kind") == "session_start"]
    out = []
    if starts:
        # The sinks append across resume retries (run_resumable.sh /
        # exit-42 loops): each process adds a session_start. Report the
        # LAST one's config (the live session) and the segment count.
        info = {k: v for k, v in starts[-1].items() if k not in ("ts", "kind")}
        if info:
            out.append("Run: `" + json.dumps(info, default=str) + "`")
        if len(starts) > 1:
            out.append(
                f"{len(starts)} session segments (resumed/retried run)."
            )
        if info or len(starts) > 1:
            out.append("")
    if not health:
        out.append("No health events — no throughput regression, no "
                   "divergence, no stall.")
        return out
    out.append("| ts | kind | detail |")
    out.append("|---|---|---|")
    t0 = rows[0].get("ts", 0.0) if rows else 0.0
    for r in health:
        detail = {k: v for k, v in r.items() if k not in ("ts", "kind")}
        out.append(
            f"| +{_fmt_s(r.get('ts', t0) - t0)} | **{r.get('kind')}** "
            f"| `{json.dumps(detail, default=str)}` |"
        )
    return out


def static_findings() -> list[str]:
    """Markdown lines for the "Static findings" section: the jaxlint
    analyzer's `--json` output over the working tree (ISSUE 5). A run
    report is usually read while diagnosing a misbehaving run — if the
    tree ALSO carries un-baselined static hazards (a donated restored
    buffer, a recompile-hazard call site), that belongs next to the
    telemetry. Empty when the tree is clean (the section is omitted) or
    when the analyzer cannot run (reports must render anywhere).

    `warmup-registry` is skipped here: it imports the live registry
    (seconds of jax import) and has its own tier-1 gate; the AST passes
    are import-free and fast."""
    import subprocess

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "jaxlint.py")
    try:
        proc = subprocess.run(
            [sys.executable, script, "--json", "--skip", "warmup-registry"],
            capture_output=True, text=True, timeout=120,
        )
        payload = json.loads(proc.stdout)
    except Exception:
        return []  # analyzer unavailable/broken: telemetry still renders
    new = payload.get("new") or []
    stale = payload.get("stale_baseline_entries") or []
    if not new and not stale:
        return []
    out = [
        f"{len(new)} un-baselined jaxlint finding(s) in the working tree "
        "(`python scripts/jaxlint.py` for the full report):",
        "",
    ]
    conc = [
        f for f in new
        if f.get("check")
        in ("lock-discipline", "publish-aliasing", "check-then-act")
    ]
    if conc:
        # Concurrency row (ISSUE 7): thread-safety hazards deserve their
        # own line — a run being diagnosed for corruption/stalls should
        # surface "the tree has unaudited races" before the per-finding
        # list.
        out += [
            f"- **concurrency**: {len(conc)} of these are thread-safety "
            "hazards (lock-discipline / publish-aliasing / "
            "check-then-act) — `python scripts/racesan.py` exercises "
            "the queue/publisher units under deterministic schedules",
        ]
    num = [
        f for f in new
        if f.get("check")
        in ("precision-discipline", "nonfinite-hazard", "sink-guard")
    ]
    if num:
        # Numerics row (ISSUE 14): a run being diagnosed for a NaN loss
        # or silent precision drift should surface "the tree has
        # unaudited numerics hazards" before the per-finding list.
        out += [
            f"- **numerics**: {len(num)} of these are precision/"
            "non-finite hazards (precision-discipline / "
            "nonfinite-hazard / sink-guard) — `python scripts/"
            "numsan.py` poisons the real update/codec/publish/"
            "checkpoint objects under deterministic schedules",
        ]
    perf = [
        f for f in new
        if f.get("check")
        in (
            "transfer-discipline", "donation-discipline",
            "dispatch-granularity",
        )
    ]
    if perf:
        # Performance row (ISSUE 15): a crossing/undonated-buffer/
        # granularity hazard in a steady-state body is a silent
        # throughput regression — a run being diagnosed for "it got
        # slower" should see it before the per-finding list.
        out += [
            f"- **performance**: {len(perf)} of these are steady-state "
            "perf hazards (transfer-discipline / donation-discipline "
            "/ dispatch-granularity) — `python scripts/perfsan.py` "
            "meters the real programs against perf_budgets.json",
        ]
    dist = [
        f for f in new
        if f.get("check")
        in ("collective-discipline", "mailbox-protocol", "rank-affinity")
    ]
    if dist:
        # Distributed row (ISSUE 12): fleet-protocol hazards — a
        # desynced collective or torn mailbox shows up as a cross-host
        # hang/clobber, the most expensive class to diagnose from logs.
        out += [
            f"- **distributed**: {len(dist)} of these are fleet-protocol "
            "hazards (collective-discipline / mailbox-protocol / "
            "rank-affinity) — `python scripts/fleetsan.py` exercises "
            "the mailbox/gossip/gateway stack under deterministic "
            "chaos schedules",
        ]
    shapes = [
        f for f in new
        if f.get("check")
        in ("pad-mask-discipline", "mask-propagation",
            "slice-before-commit")
    ]
    if shapes:
        # Shapes row (ISSUE 20): a padded-lane hazard is a silently
        # rescaled loss or a junk row reaching a commit point — a run
        # being diagnosed for "the gradient is subtly wrong" should see
        # it before the per-finding list.
        out += [
            f"- **shapes**: {len(shapes)} of these are padding/mask "
            "hazards (pad-mask-discipline / mask-propagation / "
            "slice-before-commit) — `python scripts/padsan.py` poisons "
            "the pad lanes of the real programs and asserts valid-lane "
            "outputs are bitwise unchanged",
        ]
    out += [
        f"- `{f.get('path')}:{f.get('line')}` **[{f.get('check')}]** "
        f"{f.get('message')}"
        for f in new[:20]
    ]
    if len(new) > 20:
        out.append(f"- … {len(new) - 20} more")
    if stale:
        out.append(
            f"- plus {len(stale)} stale baseline entr"
            f"{'y' if len(stale) == 1 else 'ies'} (flagged lines changed "
            "— rerun `scripts/jaxlint.py --write-baseline` after review)"
        )
    return out


def perf_budget_table() -> list[str]:
    """Markdown lines for the "Perf budgets" section (ISSUE 15):
    the committed `perf_budgets.json` manifest rendered as a table,
    with measured actuals joined when a `perfsan_actuals.json` report
    sits next to it (written by `scripts/perfsan.py --quick
    --out perfsan_actuals.json`). Empty when no manifest is present —
    reports must render in any checkout."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    manifest_path = os.path.join(repo, "perf_budgets.json")
    if not os.path.exists(manifest_path):
        return []
    try:
        with open(manifest_path, encoding="utf-8") as f:
            programs = json.load(f)["programs"]
    except (OSError, ValueError, KeyError, TypeError):
        return [f"*(malformed manifest: `{manifest_path}`)*"]
    actuals: dict = {}
    actuals_path = os.path.join(repo, "perfsan_actuals.json")
    if os.path.exists(actuals_path):
        try:
            with open(actuals_path, encoding="utf-8") as f:
                actuals = json.load(f).get("programs") or {}
        except (OSError, ValueError, AttributeError):
            actuals = {}
    fields = (
        ("max_dispatches_per_block", "dispatches"),
        ("max_transfers_per_block", "transfers"),
        ("max_transferred_bytes_per_block", "transferred_bytes"),
        ("max_recompiles", "recompiles"),
    )
    out = [
        "per steady-state block, budget (measured) — `python "
        "scripts/perfsan.py --quick` gates these in tier-1:",
        "",
        "| program | dispatches | transfers | bytes | recompiles |",
        "|---|---|---|---|---|",
    ]
    for name in sorted(programs):
        budget = programs[name]
        if not isinstance(budget, dict):
            cells = ["?"] * len(fields)
        else:
            # A "<program>.enqueue" manifest row's actuals ride the
            # parent program's report as `enqueue_actuals`.
            if name.endswith(".enqueue"):
                parent = actuals.get(name.rsplit(".", 1)[0]) or {}
                measured = parent.get("enqueue_actuals") or {}
            else:
                measured = (actuals.get(name) or {}).get("actuals") or {}
            cells = []
            for bkey, akey in fields:
                b = budget.get(bkey, "-")
                a = measured.get(akey)
                cells.append(f"{b} ({a})" if a is not None else f"{b}")
        out.append(f"| `{name}` | " + " | ".join(map(str, cells)) + " |")
    return out


def metrics_summary(rows: list[dict]) -> list[str]:
    if not rows:
        return ["*(no metrics rows)*"]
    last = rows[-1]
    out = [
        f"{len(rows)} logged rows; final iter {last.get('iter')}, "
        f"env_steps {last.get('env_steps', 'n/a')}, "
        f"wall {_fmt_s(float(last.get('wall_s', 0.0)))}."
    ]
    for key in ("recent_return", "avg_return_ema", "loss"):
        if isinstance(last.get(key), (int, float)):
            out.append(f"- final `{key}`: {last[key]:.4g}")
    evals = [r for r in rows if isinstance(r.get("eval_return"), (int, float))]
    if evals:
        best = max(evals, key=lambda r: r["eval_return"])
        out.append(
            f"- eval: best {best['eval_return']:.1f} @ iter {best.get('iter')}, "
            f"final {evals[-1]['eval_return']:.1f} ({len(evals)} evals)"
        )
    # Per-type eval matrix (scenario-mixture runs, ISSUE 11): rows carry
    # `eval_return_<member>` per eval — render best/final per type, plus
    # the curriculum stage trace when the run scheduled one.
    prefix = "eval_return_"
    types: list[str] = []
    for r in rows:
        for k in r:
            if (
                k.startswith(prefix)
                and isinstance(r[k], (int, float))
                and k[len(prefix):] not in types
            ):
                types.append(k[len(prefix):])
    if types:
        out.append("")
        out.append("Per-type eval matrix (scenario mixture):")
        out.append("")
        out.append("| type | final | best | evals |")
        out.append("|---|---:|---:|---:|")
        for name in types:
            vals = [
                r[prefix + name] for r in rows
                if isinstance(r.get(prefix + name), (int, float))
            ]
            out.append(
                f"| {name} | {vals[-1]:.1f} | {max(vals):.1f} "
                f"| {len(vals)} |"
            )
        stages = [
            r["curriculum_stage"] for r in rows
            if isinstance(r.get("curriculum_stage"), (int, float))
        ]
        if stages:
            out.append("")
            out.append(
                f"- curriculum: stage {int(stages[-1])} at run end "
                f"(started this segment at {int(stages[0])})"
            )
    return out


def write_trace(spans: list[dict], path: str) -> None:
    """Wrap span lines into the `{"traceEvents": [...]}` container.

    Span `ts` is zeroed at each process's tracer creation, and the file
    appends across resume retries — rendering segments unadjusted would
    overlap them all at t=0. Each segment's `clock_sync` metadata event
    carries the unix epoch of its ts=0, so later segments are shifted
    onto the first segment's clock and Perfetto shows retries end to
    end (restore/compile gaps included)."""
    out = []
    base_epoch = None
    offset_us = 0.0
    for e in spans:
        if e.get("ph") == "M" and e.get("name") == "clock_sync":
            epoch = (e.get("args") or {}).get("unix_epoch_at_ts0")
            if epoch is not None:
                if base_epoch is None:
                    base_epoch = epoch
                offset_us = (epoch - base_epoch) * 1e6
        if offset_us and "ts" in e:
            e = dict(e, ts=e["ts"] + offset_us)
        out.append(e)
    with open(path, "w") as f:
        json.dump({"traceEvents": out}, f)


def render(
    telemetry_dir: str,
    metrics_path: str | None = None,
    spans: list[dict] | None = None,
) -> str:
    if spans is None:
        spans = read_jsonl(os.path.join(telemetry_dir, "spans.jsonl"))
    resources = read_jsonl(os.path.join(telemetry_dir, "resources.jsonl"))
    events = read_jsonl(os.path.join(telemetry_dir, "events.jsonl"))
    lines = [f"# Run report — `{telemetry_dir}`", ""]
    lines += ["## Events & health", ""] + event_summary(events) + [""]
    lines += ["## Phase breakdown", ""] + phase_breakdown(spans) + [""]
    lines += ["## Slowest spans", ""] + slowest_spans(spans) + [""]
    traces = request_traces(spans)
    if traces:
        # Only for serving runs: a training-only report must not grow a
        # permanently empty requests section.
        lines += ["## Request traces (serving)", ""] + traces + [""]
    flight = flight_summary(telemetry_dir)
    if flight:
        # Only when a dump exists: its presence already means the run
        # ended badly (stall/divergence dump or post-mortem harvest).
        lines += (
            ["## Flight recorder (last seconds before death)", ""]
            + flight + [""]
        )
    lines += ["## Resources", ""] + resource_summary(resources) + [""]
    lines += (
        ["## Recompile attribution", ""] + compile_attribution(events) + [""]
    )
    lines += (
        ["## Profile captures", ""]
        + profile_captures(events, telemetry_dir)
        + [""]
    )
    statics = static_findings()
    if statics:
        # Only when the tree actually carries findings: a clean tree
        # must not grow a no-op section in every report.
        lines += ["## Static findings", ""] + statics + [""]
    budgets = perf_budget_table()
    if budgets:
        # Rendered whenever the committed manifest is present
        # (ISSUE 15): the budget table is a contract summary, not a
        # finding — it belongs in every report of this repo.
        lines += ["## Perf budgets", ""] + budgets + [""]
    if metrics_path is None:
        cand = os.path.join(telemetry_dir, "metrics.jsonl")
        metrics_path = cand if os.path.exists(cand) else None
    if metrics_path:
        lines += (
            [f"## Metrics (`{metrics_path}`)", ""]
            + metrics_summary(read_jsonl(metrics_path))
            + [""]
        )
    lines.append(
        "*Open the trace in Perfetto: `python scripts/run_report.py "
        f"{telemetry_dir} --trace` then load `trace.json` at "
        "https://ui.perfetto.dev.*"
    )
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("telemetry_dir", help="directory a --telemetry-dir run wrote")
    p.add_argument(
        "--metrics",
        help="metrics JSONL of the same run (default: "
        "TELEMETRY_DIR/metrics.jsonl when present)",
    )
    p.add_argument(
        "--trace", action="store_true",
        help="also write TELEMETRY_DIR/trace.json ({traceEvents: [...]}) "
        "for Perfetto / chrome://tracing",
    )
    p.add_argument("-o", "--output", help="write the markdown here instead of stdout")
    args = p.parse_args(argv)
    if not os.path.isdir(args.telemetry_dir):
        print(f"not a directory: {args.telemetry_dir}", file=sys.stderr)
        return 2
    spans = None
    if args.trace:
        # Parse once; a long run's spans.jsonl is the report's dominant
        # I/O, so the rows are shared with render().
        spans = read_jsonl(os.path.join(args.telemetry_dir, "spans.jsonl"))
        out = os.path.join(args.telemetry_dir, "trace.json")
        write_trace(spans, out)
        print(f"wrote {out} ({len(spans)} events)", file=sys.stderr)
    report = render(args.telemetry_dir, args.metrics, spans=spans)
    if args.output:
        with open(args.output, "w") as f:
            f.write(report)
    else:
        print(report, end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
