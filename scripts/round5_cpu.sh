#!/usr/bin/env bash
# Round-5 CPU evidence queue (VERDICT r4 missing #6): second seeds for the
# two remaining single-seed rows, sequential on the 1-core host.
#   1. DDPG Walker2d-v5 seed 1 (~95 min) — DDPG's instability band is the
#      row that benefits most from a second seed.
#   2. PPO HalfCheetah-v5 seed 1 at hidden=256,256 (~45 min) — run 3's
#      exact recipe (scripts/round4_queue.sh), new seed.
# Both use --fresh: evidence runs must start from empty ckpt dirs
# (ADVICE.md r4 #1; run_resumable.sh refuses otherwise).
set -u
cd "$(dirname "$0")/.."
export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu
mkdir -p runs results

echo "[q5] DDPG Walker2d seed 1 on CPU"
nice -n 5 scripts/run_resumable.sh --preset ddpg_walker2d --fresh \
  --ckpt-dir runs/ddpg_w2_s1 --save-every 2000 --eval-every 500 --eval-envs 16 \
  --metrics runs/ddpg_walker2d_run2_seed1.jsonl --seed 1 --quiet \
  > runs/ddpg_w2_s1_stdout.log 2>&1
echo "[q5] ddpg seed1 rc=$?"

echo "[q5] PPO HalfCheetah seed 1 (hidden=256,256) on CPU"
nice -n 5 scripts/run_resumable.sh --preset ppo_halfcheetah --fresh \
  --iterations 2500 --set hidden=256,256 --set num_envs=16 --set anneal_iters=2500 \
  --ckpt-dir runs/hc4_s1 --save-every 250 --eval-every 125 --eval-envs 8 \
  --metrics runs/ppo_halfcheetah_run4_seed1.jsonl --seed 1 --quiet \
  > runs/hc4_s1_stdout.log 2>&1
echo "[q5] ppo hc seed1 rc=$?"
