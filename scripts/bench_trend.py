#!/usr/bin/env python
"""Round-over-round bench trend: the multi-metric view of BENCH_r*.json.

The round driver's artifact (`BENCH_rNN.json`, one JSON line per round)
used to be read headline-only — a dead-tunnel round looked like "0.0"
even though PR 6 started attaching a CPU-measured `cpu_metrics` block to
EVERY record. This script is the second half of ROADMAP's "Bench
resilience" item: it trends the WHOLE block across rounds, so
regressions in host_pool_scaling / startup_to_first_step /
async_decoupling / update_wall / fused_update_wall /
replay_sample_throughput / multihost_scaling are visible even across
rounds whose TPU headline never ran. The multihost record additionally expands into
per-process-count sub-rows (its sync scaling curve) and the straggler
gossip-over-sync ratio.

Usage:
    python scripts/bench_trend.py            # repo-root BENCH_r*.json
    python scripts/bench_trend.py --root DIR # a fixture/scratch tree
    python scripts/bench_trend.py --json     # machine-readable rows

Output: one markdown table, rounds as columns — headline first
(dead-tunnel rounds show `code-dead`, with `last_green` carried when the
record embeds it), then one row per cpu_metrics entry ever seen (`-`
before a metric existed, `err` where a round's subprocess failed).
Tolerant of malformed files: a round that cannot be parsed shows as a
column of `?` rather than taking the report down.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys


def round_files(root: str) -> list[tuple[int, str]]:
    """(round number, path) sorted by round, from BENCH_r*.json names."""
    out = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    return sorted(out)


def load_record(path: str) -> dict | None:
    """The bench record inside one round file, else None.

    Two shapes exist: the driver's wrapper object ({"n", "cmd", "rc",
    "tail", "parsed": <record>} — pretty-printed, multi-line; `parsed`
    holds the bench.py JSON line, with `tail` as the raw fallback) and
    bench.py's own one-record-per-line output."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None
    try:
        rec = json.loads(text)
    except json.JSONDecodeError:
        rec = None
    if isinstance(rec, dict):
        if isinstance(rec.get("parsed"), dict):
            return rec["parsed"]
        if "metric" in rec:
            return rec
        # Wrapper without a parsed record (e.g. a crashed child): the
        # tail may still carry bench.py's JSON line.
        tail = rec.get("tail")
        if isinstance(tail, str):
            for ln in reversed(tail.strip().splitlines()):
                try:
                    inner = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if isinstance(inner, dict) and "metric" in inner:
                    return inner
        return None
    # Line-oriented fallback (bench.py's direct output).
    for ln in reversed([l for l in text.splitlines() if l.strip()]):
        try:
            inner = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(inner, dict):
            return inner
    return None


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, (int, float)):
        if v == 0:
            return "0"
        if abs(v) >= 10000:
            return f"{v:.3g}"
        return f"{v:g}"
    return str(v)[:12]


def headline_cell(rec: dict | None) -> str:
    if rec is None:
        return "?"
    value = rec.get("value")
    if rec.get("error") or not value:
        green = rec.get("last_green") or {}
        lg = green.get("value")
        return f"dead (lg {_fmt(lg)})" if lg else "dead"
    return _fmt(value)


def cpu_cell(rec: dict | None, name: str) -> str:
    if rec is None:
        return "?"
    block = rec.get("cpu_metrics")
    if not isinstance(block, dict):
        return "-"
    entry = block.get(name)
    if entry is None:
        return "-"
    if not isinstance(entry, dict):
        return _fmt(entry)
    if "error" in entry:
        return "err"
    return _fmt(entry.get("value"))


def _metric_entry(rec: dict | None, name: str):
    """(entry, None) when the round carries a well-formed cpu_metrics
    dict for `name`, else (None, sentinel cell) — the shared
    presence/malformed ladder of every sub-row: `?` for an unparseable
    round, `-` before the metric existed, `err` for a failed
    subprocess, `?` for a present-but-malformed entry."""
    if rec is None:
        return None, "?"
    block = rec.get("cpu_metrics")
    if not isinstance(block, dict) or name not in block:
        return None, "-"
    entry = block[name]
    if not isinstance(entry, dict):
        return None, "?"
    if "error" in entry:
        return None, "err"
    return entry, None


def _multihost_entry(rec: dict | None):
    return _metric_entry(rec, "multihost_scaling")


def _numeric_cell(value) -> str:
    return _fmt(value) if isinstance(value, (int, float)) else "?"


def multihost_proc_counts(recs: list[dict | None]) -> list[int]:
    """Union of sync-curve process counts across rounds (the ISSUE 9
    record nests per-process-count runs under `sync`)."""
    counts: set[int] = set()
    for rec in recs:
        entry, _ = _multihost_entry(rec)
        sync = entry.get("sync") if entry else None
        if isinstance(sync, dict):
            for k in sync:
                if str(k).isdigit():
                    counts.add(int(k))
    return sorted(counts)


def multihost_proc_cell(rec: dict | None, n: int) -> str:
    """Aggregate consumed env-steps/s of the n-process sync run."""
    entry, cell = _multihost_entry(rec)
    if entry is None:
        return cell
    sync = entry.get("sync")
    if not isinstance(sync, dict):
        return "?"
    sub = sync.get(str(n))
    if sub is None:
        return "-"
    if not isinstance(sub, dict):
        return "?"
    return _numeric_cell(sub.get("aggregate_steps_per_s"))


def serving_cell(rec: dict | None, field: str) -> str:
    """One micro-batched sub-metric of the serving SLO record (ISSUE 10
    satellite: the p50/p99/actions-per-s curve trends per round)."""
    entry, cell = _metric_entry(rec, "serving_latency")
    if entry is None:
        return cell
    sub = entry.get("micro_batched")
    if not isinstance(sub, dict):
        return "?"
    return _numeric_cell(sub.get(field))


def pad_overhead_cell(rec: dict | None, group: str, key: str) -> str:
    """One padded-vs-exact shape pair of the pad-overhead record
    (ISSUE 20 satellite: the shape-stabilization tax — Pallas ragged
    lanes, serving bucket backfill — trends per round)."""
    entry, cell = _metric_entry(rec, "pad_overhead")
    if entry is None:
        return cell
    sub = entry.get(group)
    if not isinstance(sub, dict):
        return "?"
    pair = sub.get(key)
    if not isinstance(pair, dict):
        return "?"
    return _numeric_cell(pair.get("overhead_x"))


def fleet_replica_counts(recs: list[dict | None]) -> list[int]:
    """Union of fleet-curve replica counts across rounds (the ISSUE 17
    record nests per-count runs under `points`, keyed by `replicas`)."""
    counts: set[int] = set()
    for rec in recs:
        entry, _ = _metric_entry(rec, "serving_fleet_scaling")
        points = entry.get("points") if entry else None
        if isinstance(points, list):
            for p in points:
                if isinstance(p, dict) and isinstance(
                    p.get("replicas"), int
                ):
                    counts.add(p["replicas"])
    return sorted(counts)


def fleet_point_cell(rec: dict | None, n: int, field: str) -> str:
    """One field of the n-replica fleet point (ISSUE 17: actions/s and
    p99 per replica count trend per round)."""
    entry, cell = _metric_entry(rec, "serving_fleet_scaling")
    if entry is None:
        return cell
    points = entry.get("points")
    if not isinstance(points, list):
        return "?"
    for p in points:
        if isinstance(p, dict) and p.get("replicas") == n:
            return _numeric_cell(p.get(field))
    return "-"


def scenario_mixture_types(recs: list[dict | None]) -> list[str]:
    """Union of mixture member names across rounds (the ISSUE 11 record
    nests per-type steps/s under `mixture.per_type_steps_per_s`)."""
    names: list[str] = []
    for rec in recs:
        entry, _ = _metric_entry(rec, "scenario_fleet")
        mix = entry.get("mixture") if entry else None
        per_type = mix.get("per_type_steps_per_s") if isinstance(mix, dict) else None
        if isinstance(per_type, dict):
            for k in per_type:
                if k not in names:
                    names.append(k)
    return names


def scenario_type_cell(rec: dict | None, name: str) -> str:
    """One member type's homogeneous-fleet steps/s (`-` before the
    mixture block existed, `?` where it is present but malformed)."""
    entry, cell = _metric_entry(rec, "scenario_fleet")
    if entry is None:
        return cell
    mix = entry.get("mixture")
    if mix is None:
        return "-"
    if not isinstance(mix, dict):
        return "?"
    per_type = mix.get("per_type_steps_per_s")
    if not isinstance(per_type, dict):
        return "?"
    if name not in per_type:
        return "-"
    return _numeric_cell(per_type[name])


def scenario_mixture_cell(rec: dict | None, field: str) -> str:
    """A scalar field of the heterogeneous-mixture block."""
    entry, cell = _metric_entry(rec, "scenario_fleet")
    if entry is None:
        return cell
    mix = entry.get("mixture")
    if mix is None:
        return "-"
    if not isinstance(mix, dict):
        return "?"
    return _numeric_cell(mix.get(field))


def scenario_sweep_cell(rec: dict | None) -> str:
    """Peak steps/s of the instance-count sweep (the rollover curve's
    summit; the full curve lives in the round record)."""
    entry, cell = _metric_entry(rec, "scenario_fleet")
    if entry is None:
        return cell
    sweep = entry.get("instance_sweep")
    if sweep is None:
        return "-"
    if not isinstance(sweep, dict):
        return "?"
    return _numeric_cell(sweep.get("peak_steps_per_s"))


def update_wall_guarded_cell(rec: dict | None) -> str:
    """The ISSUE 14 finite-gate overhead wall (`guarded_ms`) of the
    update-wall record (`-` before the field existed, `?` malformed)."""
    entry, cell = _metric_entry(rec, "update_wall")
    if entry is None:
        return cell
    if "guarded_ms" not in entry:
        return "-"
    return _numeric_cell(entry.get("guarded_ms"))


def update_wall_field_cell(rec: dict | None, field: str) -> str:
    """A budget-counter actual of the update-wall record (ISSUE 15:
    `dispatches_per_block` / `device_transferred_bytes_per_block`, the
    same meters perfsan gates tier-1 with; `-` before the field
    existed, `?` malformed)."""
    entry, cell = _metric_entry(rec, "update_wall")
    if entry is None:
        return cell
    if field not in entry:
        return "-"
    return _numeric_cell(entry.get(field))


def fused_update_wall_cell(rec: dict | None, field: str) -> str:
    """A field of the ISSUE 19 fused-consume record (`fused_ms` /
    `bf16_ms` / `speedup_x`; `-` before the metric existed, `?`
    malformed)."""
    entry, cell = _metric_entry(rec, "fused_update_wall")
    if entry is None:
        return cell
    if field not in entry:
        return "-"
    return _numeric_cell(entry.get(field))


def data_plane_measured_cell(rec: dict | None, field: str) -> str:
    """A METERED transfer actual from the data-plane record's
    `per_block_transfer_bytes` row (ISSUE 15: `host_measured` /
    `enqueue_measured`, counted at perfsan's device_put/jnp.array
    seams rather than computed; `-` before the field existed, `?`
    malformed)."""
    entry, cell = _metric_entry(rec, "consumed_env_steps_per_s")
    if entry is None:
        return cell
    bytes_row = entry.get("per_block_transfer_bytes")
    if bytes_row is None:
        return "-"
    if not isinstance(bytes_row, dict):
        return "?"
    if field not in bytes_row:
        return "-"
    return _numeric_cell(bytes_row.get(field))


def data_plane_cell(rec: dict | None, plane: str) -> str:
    """One plane's consumed env-steps/s from the ISSUE 13 data-plane
    A/B record (`-` before the metric existed, `?` malformed)."""
    entry, cell = _metric_entry(rec, "consumed_env_steps_per_s")
    if entry is None:
        return cell
    sub = entry.get(plane)
    if sub is None:
        return "-"
    if not isinstance(sub, dict):
        return "?"
    return _numeric_cell(sub.get("consumed_steps_per_s"))


def data_plane_bytes_cell(rec: dict | None) -> str:
    """Per-consumed-block enqueue bytes of the device plane (the host
    plane's per-block figure rides the same record; consume-side
    transfer is 0 by construction)."""
    entry, cell = _metric_entry(rec, "consumed_env_steps_per_s")
    if entry is None:
        return cell
    bytes_row = entry.get("per_block_transfer_bytes")
    if bytes_row is None:
        return "-"
    if not isinstance(bytes_row, dict):
        return "?"
    return _numeric_cell(bytes_row.get("device_enqueue_per_block"))


def multihost_straggler_cell(rec: dict | None) -> str:
    """The straggler A/B ratio (gossip over sync fleet throughput)."""
    entry, cell = _multihost_entry(rec)
    if entry is None:
        return cell
    straggler = entry.get("straggler")
    if not isinstance(straggler, dict):
        return "?"
    return _numeric_cell(straggler.get("gossip_over_sync"))


def multihost_recover_cell(rec: dict | None) -> str:
    """Wall time-to-recover after an injected host kill (ISSUE 12's
    fault-injection block; `-` before the block existed, `?`/`err`
    where it is malformed or the chaos run failed)."""
    entry, cell = _multihost_entry(rec)
    if entry is None:
        return cell
    fault = entry.get("fault_injection")
    if fault is None:
        return "-"
    if not isinstance(fault, dict):
        return "?"
    if "error" in fault:
        return "err"
    return _numeric_cell(fault.get("time_to_recover_s"))


def trend_rows(root: str) -> tuple[list[int], list[tuple[str, list[str]]]]:
    """(round numbers, [(row label, cells per round)]) — the table body.

    The row set is the UNION of cpu_metrics names across all rounds, so
    a metric added in round N trends as `-` before N instead of
    silently starting the table late."""
    files = round_files(root)
    rounds = [n for n, _ in files]
    recs = [load_record(p) for _, p in files]
    names: list[str] = []
    for rec in recs:
        if rec and isinstance(rec.get("cpu_metrics"), dict):
            for k in rec["cpu_metrics"]:
                if k != "error" and k not in names:
                    names.append(k)
    rows = [("tpu_headline", [headline_cell(r) for r in recs])]
    for name in names:
        rows.append((name, [cpu_cell(r, name) for r in recs]))
        if name == "multihost_scaling":
            # Per-process-count sub-rows (ISSUE 9): the sync scaling
            # curve, one row per process count ever benchmarked, plus
            # the straggler A/B ratio — so a scaling regression at one
            # fleet size is visible even when the headline ratio holds.
            for n in multihost_proc_counts(recs):
                rows.append((
                    f"multihost_scaling.p{n}",
                    [multihost_proc_cell(r, n) for r in recs],
                ))
            rows.append((
                "multihost_scaling.straggler_gossip_x",
                [multihost_straggler_cell(r) for r in recs],
            ))
            rows.append((
                "multihost_scaling.recover_s",
                [multihost_recover_cell(r) for r in recs],
            ))
        if name == "update_wall":
            # Numerics-guard sub-row (ISSUE 14): the update wall with
            # the per-update finite-gate on, so the guard overhead
            # trends as a measured number next to the wall it taxes.
            rows.append((
                "update_wall.guarded_ms",
                [update_wall_guarded_cell(r) for r in recs],
            ))
            # Budget-counter sub-rows (ISSUE 15): dispatches and the
            # device-gather transfer bytes per steady-state block —
            # the same counters perfsan gates, trended so a program
            # quietly splitting into two dispatches (or the slot
            # scalar growing into a block re-upload) is visible next
            # to the wall it would tax.
            for field in (
                "dispatches_per_block",
                "device_transferred_bytes_per_block",
            ):
                rows.append((
                    f"update_wall.{field}",
                    [update_wall_field_cell(r, field) for r in recs],
                ))
        if name == "fused_update_wall":
            # Fused-consume sub-rows (ISSUE 19): the one-program
            # gather+decode+advantages+update wall, the bf16 update
            # wall behind --update-dtype, and the fused-vs-unfused
            # speedup — so the fusion silently splitting back into two
            # dispatches (speedup collapsing) or the bf16 path
            # regressing trends next to the walls they tax.
            for field in ("fused_ms", "bf16_ms", "speedup_x"):
                rows.append((
                    f"fused_update_wall.{field}",
                    [fused_update_wall_cell(r, field) for r in recs],
                ))
        if name == "scenario_fleet":
            # Scenario-universe sub-rows (ISSUE 11): the heterogeneous
            # mixture fleet's steps/s, each member type's homogeneous
            # steps/s at the same shape, and the instance-sweep peak —
            # so a per-type regression (one member's step got slow) is
            # visible even when the homogeneous headline holds.
            rows.append((
                "scenario_fleet.mixture",
                [scenario_mixture_cell(r, "steps_per_s") for r in recs],
            ))
            for t in scenario_mixture_types(recs):
                rows.append((
                    f"scenario_fleet.{t}",
                    [scenario_type_cell(r, t) for r in recs],
                ))
            rows.append((
                "scenario_fleet.sweep_peak",
                [scenario_sweep_cell(r) for r in recs],
            ))
        if name == "serving_latency":
            # Micro-batched gateway sub-rows (ISSUE 10): the SLO curve
            # (p50/p99 at saturating closed-loop concurrency) and the
            # absolute actions/s, so a latency regression is visible
            # even when the headline speedup ratio holds. The hist_*
            # quantiles + burn rate (ISSUE 16) are the server-side
            # histogram-derived view — the mergeable fleet metric —
            # trending next to the loadgen's client-side point
            # percentiles; rounds predating them render `?`.
            for field in ("actions_per_s", "p50_ms", "p99_ms",
                          "slo_burn", "hist_p50_ms", "hist_p99_ms"):
                rows.append((
                    f"serving_latency.{field}",
                    [serving_cell(r, field) for r in recs],
                ))
        if name == "serving_fleet_scaling":
            # Fleet scale-out sub-rows (ISSUE 17): absolute actions/s
            # and p99 at every replica count ever benchmarked, so a
            # flat curve (replicas stopped helping) or a tail-latency
            # regression at one fleet size is visible even when the
            # headline 3-vs-1 ratio holds.
            for n in fleet_replica_counts(recs):
                rows.append((
                    f"serving_fleet_scaling.r{n}",
                    [fleet_point_cell(r, n, "actions_per_s")
                     for r in recs],
                ))
                rows.append((
                    f"serving_fleet_scaling.r{n}.p99_ms",
                    [fleet_point_cell(r, n, "p99_ms") for r in recs],
                ))
        if name == "consumed_env_steps_per_s":
            # Data-plane A/B sub-rows (ISSUE 13): each plane's absolute
            # consumed env-steps/s and the device plane's per-block
            # enqueue bytes, so a regression in either plane (or a
            # codec silently fattening the enqueue) is visible even
            # when the headline device figure holds.
            for plane in ("host", "device"):
                rows.append((
                    f"consumed_env_steps_per_s.{plane}",
                    [data_plane_cell(r, plane) for r in recs],
                ))
            rows.append((
                "consumed_env_steps_per_s.enqueue_bytes",
                [data_plane_bytes_cell(r) for r in recs],
            ))
            # Metered actuals (ISSUE 15): the host plane's per-block
            # upload and the device enqueue as perfsan's counters saw
            # them — drift between these and the computed rows above
            # means the accounting lied.
            for field in ("host_measured", "enqueue_measured"):
                rows.append((
                    f"consumed_env_steps_per_s.{field}",
                    [data_plane_measured_cell(r, field) for r in recs],
                ))
        if name == "pad_overhead":
            # Pad-tax sub-rows (ISSUE 20): the padded-vs-exact dispatch
            # overhead at every guarded shape — the Pallas ragged env
            # batches and the serving backfill sizes — so one pad seam
            # quietly growing a copy is attributable even when the
            # worst-case headline is carried by a different seam.
            for key in ("E7", "E96", "E200"):
                rows.append((
                    f"pad_overhead.pallas_{key}",
                    [pad_overhead_cell(r, "pallas", key) for r in recs],
                ))
            for key in ("n3", "n5"):
                rows.append((
                    f"pad_overhead.serving_{key}",
                    [pad_overhead_cell(r, "serving", key) for r in recs],
                ))
    return rounds, rows


def render(rounds: list[int], rows: list[tuple[str, list[str]]]) -> str:
    if not rounds:
        return "(no BENCH_r*.json rounds found)"
    head = ["metric"] + [f"r{n:02d}" for n in rounds]
    widths = [
        max(len(head[i]), *(len(r[1][i - 1]) if i else len(r[0]) for r in rows))
        for i in range(len(head))
    ]
    lines = [
        " | ".join(h.ljust(w) for h, w in zip(head, widths)),
        "-|-".join("-" * w for w in widths),
    ]
    for label, cells in rows:
        lines.append(
            " | ".join(
                c.ljust(w) for c, w in zip([label, *cells], widths)
            )
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="emit {rounds, rows} as JSON instead of the table",
    )
    args = p.parse_args(argv)
    rounds, rows = trend_rows(args.root)
    if args.json:
        print(json.dumps({
            "rounds": rounds,
            "rows": {label: cells for label, cells in rows},
        }))
    else:
        print(render(rounds, rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
