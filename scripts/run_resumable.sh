#!/usr/bin/env bash
# Self-healing wrapper for long host-training runs (SURVEY.md §5.3).
#
# Pair with train.py's stall watchdog: when the axon device tunnel wedges
# mid-run, the watchdog exits 42, and this wrapper restarts the run with
# --resume from the last orbax checkpoint. Any other exit code passes
# through. The retry budget counts CONSECUTIVE no-progress attempts: a
# resume that advanced the checkpoint resets it, so a multi-day run that
# wedges many times — but always past a fresh checkpoint — keeps going,
# while a wedge that recurs before ANY checkpoint lands gives up after
# MAX_RETRIES instead of replaying the same prefix forever.
#
#   scripts/run_resumable.sh --preset sac_humanoid --ckpt-dir runs/hum \
#       --save-every 1000 --stall-timeout 300 --eval-every 1000
#
# --fresh (consumed here, not passed to train.py): refuse to start if the
# ckpt-dir already holds a checkpoint. Evidence runs want this — reusing a
# dir from an earlier leg would silently resume foreign state (worst case
# a --no-save-replay checkpoint, whose replay-free resume measurably
# degrades the actor; ADVICE.md round 4 #1).
set -u
MAX_RETRIES=${MAX_RETRIES:-10}

ckpt_dir=""
cache_dir="auto"  # sentinel: flag not passed (train.py's default)
fresh=0
prev=""
args=()
# train.py options that take a VALUE: a literal "--fresh" right after one
# of these is that option's argument, not our flag (e.g. a metrics file
# named --fresh), and must pass through untouched. Mirrors train.py's
# argparse spec; boolean flags (--quiet, --resume, --warmup ...) are
# absent on purpose.
takes_value() {
  case "$1" in
    --preset|--algo|--env|--iterations|--seed|--set|--env-set|--metrics|\
    --telemetry-dir|--telemetry-port|--telemetry-sample-s|--log-every|\
    --chunk|--eval-every|--eval-envs|--eval-steps|--workers|--ckpt-dir|\
    --compile-cache-dir|--save-every|--stall-timeout|--async-actors|\
    --updates-per-block|--max-staleness|--queue-depth|--async-correction|\
    --replay-dtype|--curriculum|--data-plane|--data-plane-codec|\
    --serve-port|--serve-buckets)
      return 0 ;;
  esac
  return 1
}
for a in "$@"; do
  if [ "$a" = "--fresh" ] && ! takes_value "$prev"; then
    fresh=1; prev="$a"; continue
  fi
  if [ "$prev" = "--ckpt-dir" ]; then ckpt_dir="$a"; fi
  if [ "$prev" = "--compile-cache-dir" ]; then cache_dir="$a"; fi
  args+=("$a")
  prev="$a"
done
# Every leg shares the persistent compilation cache: train.py's 'auto'
# default already resolves to the <ckpt-dir>/xla_cache sidecar, so leg
# N>0 demonstrably skips XLA compile. Mirror resolve_cache_dir exactly
# so --fresh knows which directory to wipe: an unpassed flag means
# 'auto'; 'none'/'off' (ANY case — python lowercases) and an explicit
# empty value mean DISABLED, never a literal path (wiping a "None"
# directory would delete unrelated cwd state).
cache_lc=$(printf '%s' "$cache_dir" | tr '[:upper:]' '[:lower:]')
case "$cache_lc" in
  auto)
    if [ -n "$ckpt_dir" ]; then cache_dir="$ckpt_dir/xla_cache"
    else cache_dir=""; fi ;;
  ""|none|off) cache_dir="" ;;
esac
# ${args[@]+...}: bash < 4.4 treats expanding an EMPTY array as an unset-
# variable error under `set -u`; the parameter-expansion guard is the
# portable spelling (a bare "${args[@]}" aborts the wrapper when train.py
# is invoked with --fresh as its only argument).
set -- ${args[@]+"${args[@]}"}

if [ "$fresh" -eq 1 ] && [ -n "$ckpt_dir" ] && [ -d "$ckpt_dir" ] \
    && ls "$ckpt_dir" 2>/dev/null | grep -qE '^[0-9]+$'; then
  echo "[run_resumable] --fresh: $ckpt_dir already contains a checkpoint;" \
       "refusing to start an evidence run over foreign state" >&2
  exit 3
fi
if [ "$fresh" -eq 1 ] && [ -n "$cache_dir" ] && [ -d "$cache_dir" ]; then
  # A fresh evidence run must also start compile-fresh: stale cache
  # entries (old jax/XLA flags, a since-edited model) would make leg 0's
  # "cold" startup measurement quietly warm.
  echo "[run_resumable] --fresh: wiping compile cache $cache_dir" >&2
  rm -rf "$cache_dir"
fi

latest_step() {
  [ -n "$ckpt_dir" ] && [ -d "$ckpt_dir" ] || { echo -1; return; }
  ls "$ckpt_dir" 2>/dev/null | grep -E '^[0-9]+$' | sort -n | tail -1 || echo -1
}

python train.py "$@"
rc=$?
tries=0
last_seen=$(latest_step)
while [ "$rc" -eq 42 ] && [ "$tries" -lt "$MAX_RETRIES" ]; do
  tries=$((tries + 1))
  echo "[run_resumable] stall exit 42 — resuming (no-progress attempt $tries/$MAX_RETRIES)" >&2
  python train.py "$@" --resume
  rc=$?
  now_seen=$(latest_step)
  if [ "${now_seen:-"-1"}" != "${last_seen:-"-1"}" ]; then
    tries=0  # the checkpoint advanced: this was not a futile retry
    last_seen="$now_seen"
  fi
done
exit "$rc"
