#!/usr/bin/env bash
# Self-healing wrapper for long host-training runs (SURVEY.md §5.3).
#
# Pair with train.py's stall watchdog: when the axon device tunnel wedges
# mid-run, the watchdog exits 42, and this wrapper restarts the run with
# --resume from the last orbax checkpoint. Any other exit code passes
# through. The retry budget counts CONSECUTIVE no-progress attempts: a
# resume that advanced the checkpoint resets it, so a multi-day run that
# wedges many times — but always past a fresh checkpoint — keeps going,
# while a wedge that recurs before ANY checkpoint lands gives up after
# MAX_RETRIES instead of replaying the same prefix forever.
#
#   scripts/run_resumable.sh --preset sac_humanoid --ckpt-dir runs/hum \
#       --save-every 1000 --stall-timeout 300 --eval-every 1000
set -u
MAX_RETRIES=${MAX_RETRIES:-10}

ckpt_dir=""
prev=""
for a in "$@"; do
  if [ "$prev" = "--ckpt-dir" ]; then ckpt_dir="$a"; fi
  prev="$a"
done

latest_step() {
  [ -n "$ckpt_dir" ] && [ -d "$ckpt_dir" ] || { echo -1; return; }
  ls "$ckpt_dir" 2>/dev/null | grep -E '^[0-9]+$' | sort -n | tail -1 || echo -1
}

python train.py "$@"
rc=$?
tries=0
last_seen=$(latest_step)
while [ "$rc" -eq 42 ] && [ "$tries" -lt "$MAX_RETRIES" ]; do
  tries=$((tries + 1))
  echo "[run_resumable] stall exit 42 — resuming (no-progress attempt $tries/$MAX_RETRIES)" >&2
  python train.py "$@" --resume
  rc=$?
  now_seen=$(latest_step)
  if [ "${now_seen:-"-1"}" != "${last_seen:-"-1"}" ]; then
    tries=0  # the checkpoint advanced: this was not a futile retry
    last_seen="$now_seen"
  fi
done
exit "$rc"
