#!/usr/bin/env python
"""Local multi-process launcher for the distributed actor–learner
(`parallel/multihost.py`, ISSUE 9).

Spawns N worker processes against a localhost `jax.distributed`
coordinator — the CPU-drivable stand-in for a TPU pod launch — runs the
per-process learner in sync (global all-reduce) or gossip (peer-to-peer
ring) mode, and aggregates fleet throughput. One JSON line on stdout.

    python scripts/launch_multihost.py --processes 2              # sync
    python scripts/launch_multihost.py --processes 4 --mode gossip
    python scripts/launch_multihost.py --processes 2 --straggler-rank 0 \
        --straggler-extra-s 0.006                # inject a slow host
    python scripts/launch_multihost.py --smoke   # tier-1 2-process check
    python scripts/launch_multihost.py --bench   # the multihost_scaling
                                                 # grid (results/ record)

Envs are the sleep-padded CartPole testbed (`envs/sleep_pad.py`): real
dynamics under a simulator-shaped wall cost, so fleet scaling is
measurable on any host (the same rationale as `host_pool_scaling`).
`--straggler-rank R` pads rank R's envs further: sync mode stalls the
fleet at the all-reduce barrier; gossip mode degrades only R's own
contribution — the straggler-does-not-stall acceptance row.

On a real pod, run one `train.py --distributed --coordinator ...`
process per host instead; this launcher exists so tier-1 and the bench
cover the stack with no TPU present.

Exit codes: 0 ok; 1 a worker failed or a consistency check tripped.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# worker (one per process)
# ---------------------------------------------------------------------------


def run_worker(args) -> int:
    # Backend-affecting setup BEFORE any jax backend init.
    from actor_critic_tpu.parallel import multihost

    if args.mode == "sync":
        multihost.distributed_init(
            coordinator=f"127.0.0.1:{args.port}",
            num_processes=args.processes,
            process_id=args.rank,
        )
    import numpy as np

    from actor_critic_tpu import telemetry
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.algos.host_loop import host_evaluate
    from actor_critic_tpu.envs.host_pool import HostEnvPool
    from actor_critic_tpu.envs.sleep_pad import QUALIFIED_CARTPOLE_ID
    from actor_critic_tpu.models import host_actor

    session = None
    if args.telemetry_dir:
        host_dir = os.path.join(args.telemetry_dir, f"host{args.rank}")
        session = telemetry.TelemetrySession(
            host_dir,
            run_info={
                "multihost_rank": args.rank, "mode": args.mode,
                "seed": args.seed,
            },
            serve_port=0,
        )
        telemetry.set_current(session)
        multihost.host_lane(args.rank)
        if args.mailbox_dir:
            # Announce this rank's exporter into the gossip mailbox so
            # any process sharing it (rank 0's rollup below, a serving
            # gateway's /fleetz) can discover and scrape the fleet.
            from actor_critic_tpu.telemetry import fleet as tfleet

            tfleet.announce_endpoint(
                args.mailbox_dir, args.rank,
                f"http://127.0.0.1:{session.exporter_port}",
            )

    sleep_s = args.sleep_s
    if args.rank == args.straggler_rank:
        sleep_s += args.straggler_extra_s
    cfg = ppo.PPOConfig(
        num_envs=args.num_envs,
        rollout_steps=args.rollout_steps,
        epochs=args.epochs,
        num_minibatches=args.minibatches,
        lr=args.lr,
        hidden=(32,),
        entropy_coef=0.001,
    )
    E_a = args.num_envs // args.actors
    pools = [
        HostEnvPool(
            QUALIFIED_CARTPOLE_ID, E_a,
            seed=args.seed + (args.rank * args.actors + i) * 100_003,
            env_kwargs={"sleep_s": sleep_s},
        )
        for i in range(args.actors)
    ]
    try:
        np_params, history, summary = multihost.train_multihost(
            pools, cfg,
            args.iterations if args.duration_s <= 0 else 1_000_000,
            duration_s=args.duration_s if args.duration_s > 0 else None,
            rank=args.rank, world=args.processes, mode=args.mode,
            seed=args.seed, log_every=0,
            queue_depth=args.queue_depth, max_staleness=args.max_staleness,
            gossip=multihost.GossipConfig(
                every=args.gossip_every, weight=args.gossip_weight,
            ),
            mailbox_dir=args.mailbox_dir or None,
        )
        eval_return = None
        if args.eval_steps > 0:
            greedy = host_actor.make_ppo_host_greedy(pools[-1].spec, cfg)
            eval_pool = pools[-1].eval_pool(4)
            try:
                eval_return = host_evaluate(
                    eval_pool,
                    lambda o: np.asarray(greedy(np_params, o)),
                    max_steps=args.eval_steps,
                )
            finally:
                eval_pool.close()
        summary["eval_return"] = eval_return
        last = history[-1][1] if history else {}
        summary["last_loss"] = last.get("loss")
        if session is not None and args.mailbox_dir and args.rank == 0:
            # Fleet rollup (ISSUE 16): rank 0 scrapes every announced
            # exporter once before exiting. Best-effort — peers that
            # already exited degrade to `unreachable` entries, never a
            # worker failure.
            try:
                from actor_critic_tpu.telemetry import fleet as tfleet

                fz = tfleet.FleetAggregator(
                    mailbox_dir=args.mailbox_dir, timeout_s=2.0
                ).fleetz()
                summary["fleet"] = {
                    "size": fz["fleet_size"],
                    "reachable": fz["reachable"],
                    "counters": fz["counters"],
                }
            except Exception:
                pass
        print(json.dumps(summary), flush=True)
        return 0
    finally:
        for p in pools:
            p.close()
        if session is not None:
            session.close()


# ---------------------------------------------------------------------------
# parent: spawn a cluster, aggregate
# ---------------------------------------------------------------------------


def worker_env() -> dict:
    """CPU-pinned, axon-disarmed child environment (the cpu-without-
    disarm combination deadlocks inside the site hook)."""
    from __graft_entry__ import disarm_axon

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    disarm_axon(env)
    return env


def run_cluster(
    processes: int,
    mode: str,
    *,
    iterations: int = 30,
    duration_s: float = 0.0,
    rollout_steps: int = 16,
    num_envs: int = 4,
    actors: int = 1,
    sleep_s: float = 0.002,
    straggler_rank: int = -1,
    straggler_extra_s: float = 0.0,
    gossip_every: int = 1,
    gossip_weight: float = 0.5,
    seed: int = 0,
    eval_steps: int = 0,
    telemetry_dir: str = "",
    timeout_s: float = 600.0,
    extra_args: tuple = (),
) -> dict:
    """One N-process local-cluster run; returns the aggregated fleet
    record (raises on worker failure)."""
    port = free_port()
    env = worker_env()
    with tempfile.TemporaryDirectory(prefix="mh_mailbox_") as mailbox:
        cmd_base = [
            sys.executable, os.path.abspath(__file__), "--worker",
            "--processes", str(processes), "--mode", mode,
            "--port", str(port), "--mailbox-dir", mailbox,
            "--iterations", str(iterations),
            "--duration-s", str(duration_s),
            "--rollout-steps", str(rollout_steps),
            "--num-envs", str(num_envs), "--actors", str(actors),
            "--sleep-s", str(sleep_s),
            "--straggler-rank", str(straggler_rank),
            "--straggler-extra-s", str(straggler_extra_s),
            "--gossip-every", str(gossip_every),
            "--gossip-weight", str(gossip_weight),
            "--seed", str(seed), "--eval-steps", str(eval_steps),
            "--telemetry-dir", telemetry_dir,
            *extra_args,
        ]
        t0 = time.perf_counter()
        procs = [
            subprocess.Popen(
                cmd_base + ["--rank", str(rank)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env,
            )
            for rank in range(processes)
        ]
        # Drain every worker CONCURRENTLY: with sequential communicate()
        # a later rank filling its 64 KiB stderr pipe would block before
        # its next collective, stall the fleet at the barrier, and burn
        # the whole timeout with no diagnostics.
        import threading

        outs: list = [None] * processes

        def drain(i: int, p) -> None:
            try:
                out, err = p.communicate(timeout=timeout_s)
                outs[i] = (p.returncode, out, err)
            except subprocess.TimeoutExpired:
                p.kill()
                out, err = p.communicate()
                outs[i] = (None, out, err)

        threads = [
            threading.Thread(target=drain, args=(i, p), daemon=True)
            for i, p in enumerate(procs)
        ]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=timeout_s + 30)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        wall = time.perf_counter() - t0
    summaries = []
    for rank, entry in enumerate(outs):
        if entry is None:
            raise RuntimeError(f"worker {rank} never finished draining")
        rc, out, err = entry
        if rc is None:
            tail = (err or out or "").strip().splitlines()
            raise RuntimeError(
                f"worker {rank} exceeded {timeout_s:.0f}s and was killed: "
                + ("\n".join(tail[-8:]) if tail else "no output")
            )
        line = next(
            (ln for ln in reversed(out.strip().splitlines())
             if ln.startswith("{")),
            None,
        )
        if rc != 0 or line is None:
            tail = (err or out).strip().splitlines()
            raise RuntimeError(
                f"worker {rank} failed rc={rc}: "
                + ("\n".join(tail[-12:]) if tail else "no output")
            )
        summaries.append(json.loads(line))
    total = sum(s["consumed_env_steps"] for s in summaries)
    slowest = max(s["wall_s"] for s in summaries)
    record = {
        "processes": processes,
        "mode": mode,
        "aggregate_steps_per_s": round(total / slowest, 1) if slowest else 0.0,
        "consumed_env_steps": total,
        "fleet_wall_s": round(slowest, 2),
        "launcher_wall_s": round(wall, 2),
        "version_consistent": all(
            s.get("version_consistent", True) for s in summaries
        ),
        "fingerprint_consistent": all(
            s.get("fingerprint_consistent", True) for s in summaries
        ),
        "per_rank_steps_per_s": [
            s["consumed_steps_per_s"] for s in summaries
        ],
        "gossip_mixes": sum(s.get("gossip_mixes", 0) for s in summaries),
        "gossip_lag_max": max(
            (s.get("gossip_lag_max", 0) for s in summaries), default=0
        ),
        "eval_returns": [s.get("eval_return") for s in summaries],
    }
    if straggler_rank >= 0:
        record["straggler"] = {
            "rank": straggler_rank, "extra_s": straggler_extra_s,
        }
    if telemetry_dir:
        merged = merge_host_traces(telemetry_dir, processes)
        if merged:
            record["trace"] = merged
    return record


def merge_host_traces(telemetry_dir: str, processes: int) -> str:
    """Merge the per-host spans.jsonl files into ONE Chrome-trace JSONL
    (`<telemetry-dir>/fleet_spans.jsonl`): every host keeps its own pid
    lane (named host<rank> by `multihost.host_lane`), and each host's
    span timestamps are shifted onto a common axis using the clock_sync
    metadata its tracer recorded (per-process ts is zeroed at tracer
    creation; the unix epoch anchor is the shared clock)."""
    hosts = []
    for rank in range(processes):
        path = os.path.join(telemetry_dir, f"host{rank}", "spans.jsonl")
        if not os.path.exists(path):
            continue
        events = []
        epoch0 = None
        with open(path) as f:
            for ln in f:
                try:
                    evt = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if evt.get("name") == "clock_sync":
                    epoch0 = evt.get("args", {}).get("unix_epoch_at_ts0")
                events.append(evt)
        if epoch0 is not None:
            hosts.append((epoch0, events))
    if not hosts:
        return ""
    base = min(e for e, _ in hosts)
    out_path = os.path.join(telemetry_dir, "fleet_spans.jsonl")
    with open(out_path, "w") as f:
        for epoch0, events in hosts:
            shift_us = (epoch0 - base) * 1e6
            for evt in events:
                if "ts" in evt:
                    evt = dict(evt, ts=round(evt["ts"] + shift_us, 1))
                f.write(json.dumps(evt) + "\n")
    return out_path


# ---------------------------------------------------------------------------
# smoke + bench drivers
# ---------------------------------------------------------------------------


def run_smoke(args) -> int:
    """Tier-1 gate: a 2-process sync cluster must come up on localhost,
    train a few blocks, and agree bit-exactly on the broadcast version
    counter and the replicated-params fingerprint."""
    rec = run_cluster(
        2, "sync", iterations=args.iterations or 5, rollout_steps=8,
        num_envs=2, actors=1, sleep_s=0.0, seed=args.seed,
        timeout_s=args.run_timeout,
    )
    ok = rec["version_consistent"] and rec["fingerprint_consistent"]
    print(json.dumps({"smoke": "multihost_sync_2proc", "ok": ok, **rec}))
    return 0 if ok else 1


def run_bench(args) -> dict:
    """The `multihost_scaling` grid (ROADMAP multi-host item): sync
    aggregate consumed env-steps/s at 1/2/4 processes, the gossip
    variant at 4, and the straggler A/B (sync stalls at the barrier,
    gossip degrades) at 2 processes. Every run is WALL-bounded
    (`--duration-s`): fleets consume whatever blocks fit in the same
    window, so a straggler's cost is measured as missing consumption
    rather than stretched wall. Headline value = sync aggregate
    speedup at 4 processes over 1 (target >= 1.5x). Env steps are
    sleep-padded (wall-bound, CPU-idle), so process-level overlap is
    measurable even on a 1-2 core CI host — the same testbed rationale
    as `host_pool_scaling`."""
    duration = args.duration_s if args.duration_s > 0 else 12.0
    # Bench pad default (8 ms) is larger than the generic-run default:
    # at 4 sync processes on a small CI host the gloo collectives spin
    # against oversubscribed cores, and the pad must keep collection —
    # the thing being scaled — the pipeline's bottleneck stage.
    sleep_s = args.sleep_s if args.sleep_s is not None else 0.008
    base = dict(
        duration_s=duration, iterations=0,
        rollout_steps=16, num_envs=4, actors=1,
        sleep_s=sleep_s, seed=args.seed,
        timeout_s=args.run_timeout,
        # One minibatch per update: the collective count per consumed
        # block stays O(param leaves), not O(epochs × minibatches).
        extra_args=("--epochs", "1", "--minibatches", "1"),
    )
    sync = {}
    for p in (1, 2, 4):
        sync[str(p)] = run_cluster(p, "sync", **base)
    gossip = {"4": run_cluster(4, "gossip", **base)}
    straggle = dict(base, straggler_rank=0, straggler_extra_s=sleep_s * 3)
    straggler = {
        "sync": run_cluster(2, "sync", **straggle),
        "gossip": run_cluster(2, "gossip", **straggle),
    }
    # Fault injection (ISSUE 12 satellite): SIGKILL a REAL gossip
    # worker mid-run, restart it, and measure wall time-to-recover —
    # fleetsan's process injector reused as the bench driver. Malformed
    # or failed runs degrade to an error entry (bench_trend renders
    # `?`), never take the whole grid down.
    from actor_critic_tpu.analysis import fleetsan

    try:
        fault = fleetsan.run_process_chaos(
            world=2, duration_s=max(duration * 2, 12.0),
            kill_after_s=max(duration / 3, 3.0),
            timeout_s=args.run_timeout, seed=args.seed,
        )
    except Exception as e:
        fault = {"error": f"{type(e).__name__}: {e}"}
    agg = lambda r: r["aggregate_steps_per_s"]  # noqa: E731
    record = {
        "metric": "multihost_scaling",
        "value": round(agg(sync["4"]) / agg(sync["1"]), 2),
        "fault_injection": fault,
        "unit": "x aggregate consumed env-steps/s, 4 processes vs 1 "
                "(sync all-reduce, sleep-padded CartPole, CPU local "
                "cluster)",
        "sync": sync,
        "gossip": gossip,
        "straggler": {
            **straggler,
            "gossip_over_sync": round(
                agg(straggler["gossip"]) / agg(straggler["sync"]), 2
            ),
        },
        "gossip_over_sync_4proc": round(
            agg(gossip["4"]) / agg(sync["4"]), 2
        ),
        "version_consistent": all(
            sync[p]["version_consistent"] for p in sync
        ),
        "config": {
            "duration_s": duration,
            "rollout_steps": base["rollout_steps"],
            "num_envs_per_process": base["num_envs"],
            "sleep_s": sleep_s,
            "straggler_extra_s": straggle["straggler_extra_s"],
        },
    }
    return record


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    p.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--rank", type=int, default=0, help=argparse.SUPPRESS)
    p.add_argument("--port", type=int, default=0, help=argparse.SUPPRESS)
    p.add_argument(
        "--processes", type=int, default=2,
        help="cluster size (local processes, one learner each)",
    )
    p.add_argument(
        "--mode", choices=("sync", "gossip"), default="sync",
        help="sync = global-mesh all-reduce learner (a straggler stalls "
        "the fleet); gossip = independent learners + ring param exchange "
        "(a straggler degrades only itself)",
    )
    p.add_argument("--iterations", type=int, default=0,
                   help="blocks consumed per learner (0 = mode default)")
    p.add_argument(
        "--duration-s", type=float, default=0.0,
        help="wall-bounded run: consume as many blocks as fit in this "
        "window instead of a fixed count (the bench's measurement mode "
        "— a straggler shows up as blocks NOT consumed). Sync fleets "
        "all-reduce the stop vote so every host exits together.",
    )
    p.add_argument("--rollout-steps", type=int, default=16)
    p.add_argument("--num-envs", type=int, default=4,
                   help="envs per process (split across --actors)")
    p.add_argument("--actors", type=int, default=1,
                   help="actor threads per process")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--minibatches", type=int, default=2)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument(
        "--sleep-s", type=float, default=None,
        help="per-env-step wall pad (simulator-shaped cost; see "
        "envs/sleep_pad.py). Default 0.002 for generic runs, 0.008 "
        "under --bench",
    )
    p.add_argument(
        "--straggler-rank", type=int, default=-1,
        help="rank whose envs get --straggler-extra-s more pad (-1 off)",
    )
    p.add_argument("--straggler-extra-s", type=float, default=0.006)
    p.add_argument("--gossip-every", type=int, default=1,
                   help="consumed blocks between gossip exchanges")
    p.add_argument("--gossip-weight", type=float, default=0.5,
                   help="peer mixing weight in [0, 1]")
    p.add_argument("--queue-depth", type=int, default=4)
    p.add_argument("--max-staleness", type=int, default=8)
    p.add_argument("--mailbox-dir", default="",
                   help="shared gossip mailbox dir (auto tempdir when "
                   "launched by this script)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--eval-steps", type=int, default=0,
                   help="final greedy eval sweep per worker (0 = off)")
    p.add_argument("--telemetry-dir", default="",
                   help="per-host telemetry under <dir>/host<rank>; the "
                   "parent merges spans into <dir>/fleet_spans.jsonl")
    p.add_argument("--run-timeout", type=float, default=600.0,
                   help="per-cluster-run kill budget (seconds)")
    p.add_argument("--smoke", action="store_true",
                   help="tier-1 2-process sync smoke (exit 1 on failure)")
    p.add_argument("--bench", action="store_true",
                   help="run the multihost_scaling grid; one JSON record")
    p.add_argument("--out", default="",
                   help="with --bench: also write the record to this path")
    args = p.parse_args(argv)

    if args.worker:
        if args.max_staleness < 0:
            args.max_staleness = None
        if args.sleep_s is None:
            args.sleep_s = 0.002
        return run_worker(args)
    if args.smoke:
        return run_smoke(args)
    if args.bench:
        record = run_bench(args)
        print(json.dumps(record))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(record, f, indent=1)
        return 0
    rec = run_cluster(
        args.processes, args.mode,
        iterations=args.iterations or 30,
        duration_s=args.duration_s,
        rollout_steps=args.rollout_steps, num_envs=args.num_envs,
        actors=args.actors,
        sleep_s=args.sleep_s if args.sleep_s is not None else 0.002,
        straggler_rank=args.straggler_rank,
        straggler_extra_s=(
            args.straggler_extra_s if args.straggler_rank >= 0 else 0.0
        ),
        gossip_every=args.gossip_every, gossip_weight=args.gossip_weight,
        seed=args.seed, eval_steps=args.eval_steps,
        telemetry_dir=args.telemetry_dir, timeout_s=args.run_timeout,
    )
    print(json.dumps(rec))
    return 0 if rec["version_consistent"] else 1


if __name__ == "__main__":
    sys.exit(main())
