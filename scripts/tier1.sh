#!/usr/bin/env bash
# Blessed tier-1 verify entry point: the ROADMAP.md "Tier-1 verify"
# command, verbatim, runnable from anywhere (builders and CI invoke
# this one script so the command can never drift between callers —
# update ROADMAP.md and this file together).
#
# Budget policy (ISSUE 14 satellite): every step prints its own wall
# seconds and pytest runs with --durations=20, so when the 870 s pytest
# budget is tight on a slow box (the PR 13 caveat: the FULL suite no
# longer fits there) the overrun is ATTRIBUTABLE to named steps/tests
# instead of anecdotal. The per-step sanitizer timeouts below are part
# of the same policy: a hung analyzer exits 124 in its own window and
# can never eat the pytest budget.
cd "$(dirname "$0")/.." || exit 1
# Cumulative wall clock vs the 870 s budget (ISSUE 20 satellite): the
# PR 13/14 caveat — the full stack of steps no longer fits the pytest
# budget on a slow box — made visible. Every step prints the running
# total and the script warns (without failing) once 80% is spent, so a
# creeping sanitizer step is caught the run it creeps, not when the
# budget finally bursts.
tstart=$(date +%s)
BUDGET=870
cum() {
  local c=$(( $(date +%s) - tstart ))
  echo "tier1: cumulative wall ${c}s / ${BUDGET}s budget"
  if (( c * 5 >= BUDGET * 4 )); then
    echo "tier1: WARNING: cumulative wall ${c}s past 80% of the ${BUDGET}s budget" >&2
  fi
}
t0=$(date +%s)
# Static analysis first (ISSUE 5): an un-baselined jaxlint finding fails
# tier-1 before any test runs (exit 1 = findings, 2 = analyzer crash —
# distinct so CI logs tell them apart).
env JAX_PLATFORMS=cpu python scripts/jaxlint.py actor_critic_tpu train.py bench --error-on-new || exit $?
echo "tier1: jaxlint wall $(( $(date +%s) - t0 ))s"; cum
t0=$(date +%s)
# Race sanitizer quick profile (ISSUE 7): 100 fixed-seed cooperative
# schedules over the queue/publisher/mailbox units, under its OWN
# timeout so a schedule hang (exit 124) cannot eat the pytest budget
# below (exit 1 = race detected, 2 = exerciser crash).
timeout -k 5 120 env JAX_PLATFORMS=cpu python scripts/racesan.py --schedules 100 || exit $?
echo "tier1: racesan wall $(( $(date +%s) - t0 ))s"; cum
t0=$(date +%s)
# Fleet chaos sanitizer quick profile (ISSUE 12): 30 fixed-seed chaos
# schedules over the gossip-fleet + gateway-swap units (real mailbox
# objects, injected kills/torn files/reordered delivery), under its
# OWN timeout like the racesan step (exit 1 = protocol violation
# detected, 2 = exerciser crash). --flight-dump (ISSUE 16) adds one
# REAL SIGKILL schedule with per-host telemetry and asserts the
# victim's crash flight ring was harvested into a rendered dump — the
# post-mortem path must produce evidence, not just not crash.
fleetdir=$(mktemp -d /tmp/tier1_flight.XXXXXX)
timeout -k 5 180 env JAX_PLATFORMS=cpu python scripts/fleetsan.py --schedules 30 --flight-dump "$fleetdir" || { rc=$?; rm -rf "$fleetdir"; exit $rc; }
ls "$fleetdir"/host*/flight_dump_*.json >/dev/null 2>&1 || { echo "tier1: fleetsan left no flight dump in $fleetdir" >&2; rm -rf "$fleetdir"; exit 1; }
rm -rf "$fleetdir"
echo "tier1: fleetsan wall $(( $(date +%s) - t0 ))s"; cum
t0=$(date +%s)
# Replica-kill-mid-swap schedule (ISSUE 17 leg b): 30 fixed-seed
# schedules over the horizontal scale-out propagation path — N
# MailboxPolicySyncer replicas consuming a publisher's mailbox under
# replica SIGKILL/restart + torn/replayed snapshots; proves a torn
# policy is never served and every replica (incl. the rejoiner)
# converges. Own timeout like the other sanitizer steps.
timeout -k 5 120 env JAX_PLATFORMS=cpu python scripts/fleetsan.py --scenario replica --schedules 30 || exit $?
echo "tier1: fleetsan-replica wall $(( $(date +%s) - t0 ))s"; cum
t0=$(date +%s)
# Numerics fault sanitizer quick profile (ISSUE 14): 16 fixed-seed
# poison schedules (nan/±inf/denormal/int8-saturating) through the REAL
# update/codec/publish/checkpoint objects — every poison must be
# blocked by its named guard (divergence event, checkpoint refusal,
# publish/mailbox/swap rejection, codec saturation) and the tolerated
# poisons must not over-fire. Own timeout like the other sanitizers
# (exit 1 = a guard failed/over-fired, 2 = exerciser crash).
timeout -k 5 240 env JAX_PLATFORMS=cpu python scripts/numsan.py --schedules 16 || exit $?
echo "tier1: numsan wall $(( $(date +%s) - t0 ))s"; cum
t0=$(date +%s)
# Performance budget sanitizer quick profile (ISSUE 15): the five
# steady-state programs (async PPO update host+device plane, off-policy
# ingest, serving dispatch, mixture fleet step) measured for
# dispatches/transfers/transferred-bytes/recompiles per block against
# the committed perf_budgets.json — a stray host round-trip, an extra
# dispatch, or a recompiling swap fails here before any test runs. Own
# timeout like the other sanitizers (exit 1 = budget violation
# detected, 2 = exerciser/manifest crash).
timeout -k 5 300 env JAX_PLATFORMS=cpu python scripts/perfsan.py --quick || exit $?
echo "tier1: perfsan wall $(( $(date +%s) - t0 ))s"; cum
t0=$(date +%s)
# Padding-lane poison sanitizer quick profile (ISSUE 20): 16 fixed-seed
# poison schedules through the REAL shape-stabilization seams (masked
# chunk tail, Pallas ragged-lane pad, parked mixture members, serving
# bucket backfill, non-leased ring slots) — each program runs twice,
# pad lanes zeroed vs poisoned (nan/±3e38/int8-saturating), and the
# valid-lane outputs must be BITWISE identical. Own timeout like the
# other sanitizers (exit 1 = a junk lane is observable, 2 = exerciser
# crash).
timeout -k 5 180 env JAX_PLATFORMS=cpu python scripts/padsan.py --quick || exit $?
echo "tier1: padsan wall $(( $(date +%s) - t0 ))s"; cum
t0=$(date +%s)
# Multi-process CPU smoke (ISSUE 9): a 2-process jax.distributed local
# cluster must come up against a localhost coordinator, train a few
# blocks through the global-mesh learner, and agree bit-exactly on the
# broadcast version counter + replicated-params fingerprint. Its OWN
# timeout, like the racesan step: a hung coordinator (wedged port,
# dead worker) must exit 124 here, not eat the pytest budget.
timeout -k 10 300 env JAX_PLATFORMS=cpu python scripts/launch_multihost.py --smoke || exit $?
echo "tier1: multihost-smoke wall $(( $(date +%s) - t0 ))s"; cum
t0=$(date +%s)
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --durations=20 --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); echo "tier1: pytest wall $(( $(date +%s) - t0 ))s"; cum; exit $rc
