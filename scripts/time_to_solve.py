"""Wall-clock-to-solve harness for fused (pure-JAX env) presets.

Measures the BASELINE.json:2 primary metric "wall-clock to target return
(CartPole)": from COLD process start (t0 is taken before jax is even
imported, so backend init and XLA compilation are charged to the number)
to the first time the greedy-eval return clears the threshold on
`--consecutive` consecutive evals (two by default — a single lucky eval
must not count as a solve, cf. the round-2 oscillation 397→148→429).

Usage:
    python scripts/time_to_solve.py --preset ppo_cartpole \
        --threshold 475 --chunk 10 --out results/cartpole_solve.json

Prints one JSON line per eval and a final summary JSON; with --out the
full trace is written to disk (checked-in evidence for BASELINE.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from functools import partial

T0 = time.perf_counter()  # cold start: before jax import


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="ppo_cartpole")
    p.add_argument("--threshold", type=float, default=475.0)
    p.add_argument("--chunk", type=int, default=10, help="iterations per eval")
    p.add_argument("--max-iters", type=int, default=0, help="0 = preset default")
    p.add_argument("--consecutive", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--eval-envs", type=int, default=64)
    p.add_argument("--eval-steps", type=int, default=512)
    p.add_argument("--out", default="")
    p.add_argument("--set", action="append", default=[], metavar="KEY=VALUE")
    args = p.parse_args()

    import dataclasses

    import jax

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from actor_critic_tpu.config import PRESETS, apply_overrides, parse_set_args
    from train import build_env, fused_module

    preset = PRESETS[args.preset]
    if args.set:
        preset = dataclasses.replace(
            preset, config=apply_overrides(preset.config, parse_set_args(args.set))
        )
    env, fused = build_env(
        preset.env, preset.algo, preset.config, args.seed,
        env_kwargs=preset.env_kwargs,
    )
    if not fused:
        raise SystemExit("time_to_solve drives fused presets only")
    mod = fused_module(preset.algo)
    cfg = preset.config
    max_iters = args.max_iters or preset.iterations

    state = mod.init_state(env, cfg, jax.random.key(args.seed))
    step = mod.make_train_step(env, cfg)
    eval_fn = jax.jit(mod.make_eval_fn(env, cfg), static_argnums=(2, 3))
    eval_key = jax.random.key(args.seed + 1)

    @partial(jax.jit, donate_argnums=0)
    def run_chunk(state):
        def body(s, _):
            s, m = step(s)
            return s, None

        s, _ = jax.lax.scan(body, state, None, length=args.chunk - 1)
        return step(s)  # last iteration reports metrics

    spi = (
        cfg.rollout_steps * cfg.num_envs
        if hasattr(cfg, "rollout_steps")
        else cfg.steps_per_iter * cfg.num_envs
    )
    trace: list[dict] = []
    streak = 0
    solved_at = None
    it = 0
    while it < max_iters:
        state, metrics = run_chunk(state)
        it += args.chunk
        # Fresh subkey per eval: consecutive solve evals must draw
        # INDEPENDENT initial-state sets, or the anti-luck guard is
        # defeated by perfectly correlated draws.
        eval_key, ekey = jax.random.split(eval_key)
        ev = float(eval_fn(state, ekey, args.eval_envs, args.eval_steps))
        row = {
            "iter": it,
            "env_steps": it * spi,
            "wall_s": round(time.perf_counter() - T0, 2),
            "eval_return": round(ev, 1),
            "train_return_ema": round(float(metrics["avg_return_ema"]), 1),
        }
        trace.append(row)
        print(json.dumps(row), flush=True)
        streak = streak + 1 if ev >= args.threshold else 0
        if streak >= args.consecutive:
            solved_at = row
            break

    summary = {
        "preset": args.preset,
        "platform": jax.default_backend(),
        "threshold": args.threshold,
        "consecutive": args.consecutive,
        "solved": solved_at is not None,
        "wall_s_to_solve": solved_at["wall_s"] if solved_at else None,
        "env_steps_to_solve": solved_at["env_steps"] if solved_at else None,
        "iters_to_solve": solved_at["iter"] if solved_at else None,
        "final_eval": trace[-1]["eval_return"] if trace else None,
        "config": {
            k: v
            for k, v in vars(cfg).items()
            if isinstance(v, (int, float, bool, str))
        },
    }
    print(json.dumps(summary), flush=True)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump({"summary": summary, "trace": trace}, f, indent=1)
    return 0 if solved_at is not None else 2


if __name__ == "__main__":
    sys.exit(main())
