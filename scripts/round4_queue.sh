#!/usr/bin/env bash
# Round-4 result-run chain (VERDICT r3 next #1): waits for the SAC
# Humanoid run to finish, then runs the TD3 Walker2d raw-obs rerun and
# the PPO HalfCheetah big-net attempt SEQUENTIALLY (1-core host — two
# trainers would thrash each other). All on XLA:CPU with the axon site
# hook disarmed; switch to the TPU commands in TODO_NEXT_ROUND.md if the
# tunnel returns.
set -u
cd "$(dirname "$0")/.."
export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu

echo "[queue] waiting for SAC Humanoid (wrapper + trainer patterns)"
# Watch BOTH the run_resumable wrapper and train.py: the wrapper's
# stall-restart cycle has moments with no live train.py, and a poll
# landing in that gap must not conclude the run finished.
while pgrep -f "run_resumable.sh --preset sac_humanoid" >/dev/null 2>&1 \
   || pgrep -f "python train.py --preset sac_humanoid" >/dev/null 2>&1; do
  sleep 60
done

echo "[queue] SAC done; starting TD3 Walker2d raw-obs rerun (seed 0)"
nice -n 10 scripts/run_resumable.sh --preset td3_walker2d --ckpt-dir runs/td3_w2 \
  --save-every 2000 --eval-every 500 --eval-envs 16 \
  --metrics runs/td3_walker2d_run2_cpu.jsonl --seed 0 --quiet \
  > runs/td3_w2_cpu_stdout.log 2>&1
echo "[queue] TD3 rc=$?"

echo "[queue] starting PPO HalfCheetah 256x256 attempt (seed 0)"
nice -n 10 scripts/run_resumable.sh --preset ppo_halfcheetah --iterations 2500 \
  --set hidden=256,256 --set num_envs=16 --set anneal_iters=2500 \
  --ckpt-dir runs/hc3 --save-every 250 --eval-every 125 --eval-envs 8 \
  --metrics runs/ppo_halfcheetah_run3_cpu.jsonl --seed 0 --quiet \
  > runs/hc3_cpu_stdout.log 2>&1
echo "[queue] PPO HC rc=$?"
