"""Generator for results/td3_nstep_pendulum_cpu.json: fused TD3 on pure-JAX
Pendulum at nstep=1 vs nstep=3 (the DDPGConfig.nstep /
replay.sample_sequences consumer), same budget and seed. Run on CPU:

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/td3_nstep_compare.py
"""
import json, os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
from actor_critic_tpu.algos import ddpg
from actor_critic_tpu.envs import make_pendulum
from actor_critic_tpu.algos.common import evaluate

results = {}
for nstep in (1, 3):
    env = make_pendulum()
    cfg = ddpg.td3_config(
        num_envs=1, steps_per_iter=64, updates_per_iter=64,
        buffer_capacity=100_000, batch_size=256, warmup_steps=1_000,
        exploration_noise=0.1, nstep=nstep,
    )
    t0 = time.monotonic()
    state, m = ddpg.train(env, cfg, num_iterations=1200, seed=0)
    actor, _ = ddpg._modules(env.spec.action_dim, cfg)
    ret = float(evaluate(env, actor.apply, state.learner.actor_params,
                         jax.random.key(99), num_envs=32, num_steps=200))
    results[f"nstep{nstep}"] = {
        "greedy_eval": round(ret, 1),
        "env_steps": 1200 * 64,
        "wall_s": round(time.monotonic() - t0, 1),
        "critic_loss": round(float(m["critic_loss"]), 4),
    }
    print(nstep, results[f"nstep{nstep}"], flush=True)
with open("results/td3_nstep_pendulum_cpu.json", "w") as f:
    json.dump({"config": "fused TD3 JAX-Pendulum, E=1, 76.8k steps/updates, seed 0",
               "note": "nstep=3 uses replay.sample_sequences n-step targets",
               **results}, f, indent=1)
print("saved")
