"""Export a metrics.jsonl run log to TensorBoard event files.

The trainers' primary sink is JSONL (utils/logging.py, SURVEY.md §5.5);
this converts one or more run logs into `tf.summary` scalars so the
installed TensorBoard can plot them:

    python scripts/tb_export.py runs/hc_metrics.jsonl --logdir runs/tb
    tensorboard --logdir runs/tb
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def export(jsonl_path: str, logdir: str) -> int:
    import tensorflow as tf  # lazy: the framework itself never needs TF

    run = os.path.splitext(os.path.basename(jsonl_path))[0]
    writer = tf.summary.create_file_writer(os.path.join(logdir, run))
    n = 0
    with open(jsonl_path) as f, writer.as_default():
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            # The framework's JsonlLogger writes "iter" (utils/logging.py);
            # accept the generic spellings too, else fall back to line no.
            step = int(rec.get("iter", rec.get("iteration", rec.get("step", n))))
            for k, v in rec.items():
                if isinstance(v, (int, float)) and k not in (
                    "iter", "iteration", "step",
                ):
                    tf.summary.scalar(k, float(v), step=step)
            n += 1
    writer.flush()
    return n


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("jsonl", nargs="+", help="metrics.jsonl file(s)")
    p.add_argument("--logdir", default="runs/tb")
    args = p.parse_args(argv)
    for path in args.jsonl:
        n = export(path, args.logdir)
        print(f"{path}: {n} records -> {args.logdir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
