#!/usr/bin/env bash
# Round-4 second-session TPU queue: waits for the in-flight pong
# extension to release the single-client tunnel, then runs the
# remaining TPU-dependent result runs SEQUENTIALLY:
#   1. A2C CartPole wall-clock-to-solve on TPU, seeds 0/1 (the retuned
#      preset certifies ≥475 from CPU; this records the TPU cold-start
#      wall-clock next to PPO's 57-71.5 s row)
#   2. DDPG Walker2d 1M — BASELINE.json:9's weaker-algorithm variant,
#      never measured (TD3 currently carries config 4)
#   3. TD3 Walker2d seed 1 — turns the single-seed 4,414 row into
#      mean±range
#   4. SAC Humanoid seed 1 — same for the 5,205 row (longest, last)
# pgrep patterns deliberately avoid strings present in the driving
# session's own cmdline (see tpu-tunnel-playbook memory).
set -u
cd "$(dirname "$0")/.."
mkdir -p runs results

echo "[q4b] waiting for the pong extension to release the tunnel"
while pgrep -f "run_resumable.sh --preset impala_pong_learn" >/dev/null 2>&1; do
  sleep 60
done
sleep 10

for seed in 0 1; do
  echo "[q4b] A2C time-to-solve TPU seed $seed"
  timeout 1200 python scripts/time_to_solve.py --preset a2c_cartpole \
    --threshold 475 --chunk 25 --seed "$seed" \
    --out "results/a2c_cartpole_solve_tpu_seed${seed}.json" \
    > "runs/a2c_solve_tpu_s${seed}.log" 2>&1
  echo "[q4b] a2c seed $seed rc=$?"
done

echo "[q4b] DDPG Walker2d 1M (TPU learner)"
nice -n 5 scripts/run_resumable.sh --preset ddpg_walker2d \
  --ckpt-dir runs/ddpg_w2 --save-every 2000 --eval-every 500 --eval-envs 16 \
  --no-save-replay --stall-timeout 300 --metrics runs/ddpg_walker2d_run1_tpu.jsonl --seed 0 --quiet \
  > runs/ddpg_w2_stdout.log 2>&1
echo "[q4b] ddpg rc=$?"

echo "[q4b] TD3 Walker2d seed 1 (TPU learner)"
nice -n 5 scripts/run_resumable.sh --preset td3_walker2d \
  --ckpt-dir runs/td3_w2_s1 --save-every 2000 --eval-every 500 --eval-envs 16 \
  --no-save-replay --stall-timeout 300 --metrics runs/td3_walker2d_run3_seed1.jsonl --seed 1 --quiet \
  > runs/td3_w2_s1_stdout.log 2>&1
echo "[q4b] td3 rc=$?"

echo "[q4b] SAC Humanoid seed 1 (TPU learner)"
nice -n 5 scripts/run_resumable.sh --preset sac_humanoid \
  --ckpt-dir runs/sac_hum_s1 --save-every 2000 --eval-every 500 --eval-envs 16 \
  --no-save-replay --stall-timeout 300 --metrics runs/sac_humanoid_run2_seed1.jsonl --seed 1 --quiet \
  > runs/sac_hum_s1_stdout.log 2>&1
echo "[q4b] sac rc=$?"
echo "[q4b] all done"
