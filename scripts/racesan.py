#!/usr/bin/env python
"""racesan: deterministic-schedule race exerciser for the async
actor–learner stack (ISSUE 7).

    python scripts/racesan.py                      # quick profile
    python scripts/racesan.py --schedules 500      # wider sweep
    python scripts/racesan.py --scenario queue --consumer alias
                                                   # reproduce the PR 6
                                                   # zero-copy consumer
    python scripts/racesan.py --json               # machine output

Exit codes (scripts/tier1.sh runs the quick profile between jaxlint and
pytest, under its own timeout):
    0  clean: every seeded schedule swept without a detected race
    1  race: a schedule detected corruption, or the poisoner crashed a
       write into published/leased storage (the sanitizer working)
    2  crash: unexpected error (including a schedule hang past the
       scheduler deadline — a participant blocked for real)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[1].strip())
    p.add_argument(
        "--schedules", type=int, default=100,
        help="seeded interleavings to sweep (default 100, the tier-1 "
        "quick profile)",
    )
    p.add_argument(
        "--seed0", type=int, default=0,
        help="first seed of the sweep (default 0 — fixed seeds keep "
        "tier-1 deterministic)",
    )
    p.add_argument(
        "--scenario",
        choices=(
            "all", "queue", "publisher", "mailbox", "batcher",
            "device_ring",
        ),
        default="all",
        help="which unit to exercise (default: all four jax-light units, "
        "split evenly; device_ring drives the ISSUE 13 HBM trajectory "
        "ring's enqueue-vs-gather interleavings — it dispatches real "
        "jitted programs, so it runs only when asked for)",
    )
    p.add_argument(
        "--consumer", choices=("snapshot", "alias"), default="snapshot",
        help="queue consumer mode: 'alias' reproduces the reverted "
        "PR 6 copy-on-transfer consumer (expected exit 1). For "
        "--scenario device_ring, 'alias' maps to the release-before-"
        "read consumer (same bug class; expected exit 1)",
    )
    p.add_argument(
        "--writer", choices=("correct", "buggy"), default="correct",
        help="device_ring writer mode: 'buggy' reverts the leased-slot "
        "protection (drop-oldest reclaims a slot the learner still "
        "holds) — the ring poisoner catches it at the claim site "
        "(expected exit 1)",
    )
    p.add_argument(
        "--submit", choices=("copy", "alias"), default="copy",
        help="batcher submit mode: 'alias' reproduces a zero-copy "
        "payload submit under client buffer reuse (expected exit 1)",
    )
    p.add_argument(
        "--no-poison", action="store_true",
        help="disable the write-after-publish poisoner (schedule "
        "permutation only)",
    )
    p.add_argument("--json", action="store_true", help="machine output")
    args = p.parse_args(argv)

    from actor_critic_tpu.analysis import racesan

    poison = not args.no_poison
    try:
        if args.scenario == "all":
            out = racesan.quick_profile(
                schedules=args.schedules, seed0=args.seed0
            )
        elif args.scenario == "queue":
            out = racesan.exercise_sweep(
                range(args.seed0, args.seed0 + args.schedules),
                lambda s: racesan.exercise_queue(
                    s, poison=poison, consumer=args.consumer
                ),
            )
        elif args.scenario == "mailbox":
            out = racesan.exercise_sweep(
                range(args.seed0, args.seed0 + args.schedules),
                lambda s: racesan.exercise_mailbox(s, poison=poison),
            )
        elif args.scenario == "device_ring":
            out = racesan.exercise_sweep(
                range(args.seed0, args.seed0 + args.schedules),
                lambda s: racesan.exercise_device_ring(
                    s, poison=poison,
                    consumer=(
                        "released" if args.consumer == "alias" else "leased"
                    ),
                    buggy_writer=(args.writer == "buggy"),
                ),
            )
        elif args.scenario == "batcher":
            out = racesan.exercise_sweep(
                range(args.seed0, args.seed0 + args.schedules),
                lambda s: racesan.exercise_batcher(
                    s, poison=poison, alias_submit=(args.submit == "alias")
                ),
            )
        else:
            out = racesan.exercise_sweep(
                range(args.seed0, args.seed0 + args.schedules),
                lambda s: racesan.exercise_publisher(s, poison=poison),
            )
    except racesan.RacesanError as e:
        # A detected race names its seed: rerun that single seed to
        # replay the interleaving bit-identically.
        print(f"racesan: RACE DETECTED: {e}", file=sys.stderr)
        return 1
    except ValueError as e:
        if "read-only" not in str(e):
            # Only numpy's read-only write error is a detection; any
            # other ValueError is a broken exerciser (exit 2), not a
            # race to go hunting for.
            print(
                f"racesan: error: {type(e).__name__}: {e}",
                file=sys.stderr,
            )
            return 2
        # The poisoner's write-site crash surfaces as numpy's read-only
        # ValueError at the racing write.
        print(
            f"racesan: RACE DETECTED (poisoned write): {e}",
            file=sys.stderr,
        )
        return 1
    except Exception as e:
        print(f"racesan: error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(f"racesan: {out.get('schedules', 0)} schedule(s) clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
