#!/usr/bin/env python
"""Policy-serving gateway CLI (ISSUE 10): micro-batched act() over HTTP.

    # random-init PPO CartPole policy on an ephemeral port (demo/bench)
    python scripts/serve.py --preset ppo_cartpole --random-init --port 0

    # two resident checkpoints, hot-swappable via POST /v1/swap
    python scripts/serve.py --algo ppo --env jax:cartpole \
        --policy champ=runs/champ --policy canary=runs/canary \
        --default champ --port 8000 --buckets 1,4,16,64 --max-wait-us 2000

Checkpoints are params-only trees written by
`serving.export_policy_params` (a training run exports its actor/policy
params; the full trainer save tree carries optimizer/env state a server
has no use for). Startup: the serving warmup planner AOT-compiles every
act bucket on a background thread (`--compile-cache-dir` makes that a
persistent-cache prewarm), then each architecture is warmed with one
concrete dispatch per bucket BEFORE the gateway binds — steady-state
serving is 0-recompile. `--port 0` binds an OS-assigned port and prints
the actual one (the load generator and CI never race for a fixed port).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def spec_for(env: str, env_kwargs: dict):
    """EnvSpec for an env selector without building a training pool:
    jax:<name> reads the maker's spec (cheap — no device rollout state);
    host:<id> builds a 1-env pool just long enough to read the spaces."""
    from actor_critic_tpu import envs as E

    if env.startswith("jax:"):
        makers = {
            "cartpole": E.make_cartpole,
            "pendulum": E.make_pendulum,
            "pong": E.make_pong,
            "point_mass": E.make_point_mass,
            "bandit": E.make_bandit,
            "two_state_mdp": E.make_two_state_mdp,
        }
        name = env[4:]
        if name not in makers:
            raise SystemExit(
                f"unknown jax env {name!r}; valid: {sorted(makers)}"
            )
        return makers[name](**env_kwargs).spec
    if env.startswith("host:"):
        from actor_critic_tpu.envs.host_pool import HostEnvPool

        pool = HostEnvPool(env[5:], 1, seed=0, workers=1)
        try:
            return pool.spec
        finally:
            pool.close()
    raise SystemExit(f"env must be jax:<name> or host:<gym id>, got {env!r}")


def parse_policies(pairs: list[str]) -> dict[str, str]:
    out: dict[str, str] = {}
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"--policy wants ID=CKPT_DIR, got {pair!r}")
        pid, path = pair.split("=", 1)
        out[pid] = path
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[1].strip())
    p.add_argument("--preset", help="config preset (see train.py --list)")
    p.add_argument("--algo", help="algo when not using --preset")
    p.add_argument("--env", help="env selector when not using --preset")
    p.add_argument(
        "--set", action="append", default=[], metavar="K=V",
        help="config overrides (train.py --set semantics)",
    )
    p.add_argument(
        "--env-set", action="append", default=[], metavar="K=V",
        help="env maker kwargs (train.py --env-set semantics)",
    )
    p.add_argument(
        "--policy", action="append", default=[], metavar="ID=CKPT_DIR",
        help="resident policy from a params-only checkpoint (repeatable)",
    )
    p.add_argument(
        "--default", default=None, metavar="ID",
        help="default policy id (default: first --policy / the random one)",
    )
    p.add_argument(
        "--random-init", action="store_true",
        help="add a freshly-initialized 'default' policy (demo/bench)",
    )
    p.add_argument(
        "--port", type=int, default=8000,
        help="gateway port; 0 binds an OS-assigned ephemeral port "
        "(default 8000)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument(
        "--buckets", default="1,2,4,8,16,32,64",
        help="act bucket sizes, comma list (default 1,2,...,64)",
    )
    p.add_argument(
        "--max-wait-us", action="append", default=[], metavar="[ID=]US",
        help="micro-batch window: max µs the dispatcher holds a flush "
        "while rows accumulate (p99 vs occupancy knob; default 2000). "
        "Repeatable; ID=US sets a per-policy window that rides the "
        "policy handle across hot-swaps (the SLO-class batching tier)",
    )
    p.add_argument(
        "--queue-limit", type=int, default=256,
        help="bounded request queue capacity; overflow answers 503",
    )
    p.add_argument(
        "--max-inflight", type=int, default=1,
        help="overlapping in-flight dispatches: >1 packs flush N+1 "
        "while flush N is on device (default 1 — classic single-"
        "dispatcher loop)",
    )
    p.add_argument(
        "--shed-burn-threshold", type=float, default=None,
        help="admission control: shed (503) new requests to an SLO-"
        "classed policy whose burn rate is at/over this once the queue "
        "passes half capacity, instead of queueing certain violations "
        "(default off; 1.0 = shed once the policy eats budget at "
        "exactly the budget rate)",
    )
    p.add_argument(
        "--sample", action="store_true",
        help="serve sampled (stochastic) actions instead of greedy "
        "(PPO only)",
    )
    p.add_argument(
        "--backend", choices=("xla", "mirror", "auto"), default="xla",
        help="acting backend: 'mirror' serves MLP policies through the "
        "numpy host mirror (models/host_actor) — no XLA dispatch, the "
        "right trade on CPU-only serving hosts; 'auto' measures batch-1 "
        "dispatch walls of both at startup and picks the faster",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--telemetry-dir", default=None,
        help="attach a TelemetrySession: /metrics serves the full "
        "exporter exposition and the serving gauge is sampled to disk",
    )
    p.add_argument(
        "--telemetry-bind", default="127.0.0.1", metavar="HOST",
        help="bind address for the session's telemetry exporter "
        "(default 127.0.0.1; non-loopback refused unless --distributed "
        "— /metrics has no auth)",
    )
    p.add_argument(
        "--slo-ms", action="append", default=[], metavar="[ID=]MS",
        help="per-policy latency SLO class in ms (repeatable; plain MS "
        "applies to every policy without its own). Rides the policy "
        "handle across hot-swaps; /metrics exports slo_burn per policy",
    )
    p.add_argument(
        "--compile-cache-dir", default=None,
        help="persistent XLA compile cache (warm restarts skip bucket "
        "compiles entirely)",
    )
    p.add_argument(
        "--no-warmup", action="store_true",
        help="skip startup bucket compilation (first flushes compile)",
    )
    p.add_argument(
        "--distributed", action="store_true",
        help="this gateway serves one host of a gossip fleet: /healthz "
        "surfaces fleet membership (rank, world, per-peer mailbox age) "
        "read from --mailbox-dir and answers 503 when a peer's last "
        "publish is older than --stale-after-s",
    )
    p.add_argument(
        "--mailbox-dir", default=None,
        help="the fleet's shared gossip mailbox directory "
        "(train.py/launch_multihost --mailbox-dir)",
    )
    p.add_argument("--rank", type=int, default=0,
                   help="this host's fleet rank (default 0)")
    p.add_argument("--world", type=int, default=None,
                   help="fleet size (required with --distributed)")
    p.add_argument(
        "--stale-after-s", type=float, default=30.0,
        help="peer mailbox age bound before /healthz degrades to 503 "
        "(default 30)",
    )
    p.add_argument(
        "--sync-mailbox", default=None, metavar="DIR",
        help="replica-to-replica policy propagation (ISSUE 17): poll "
        "this mailbox directory for published (version, params) "
        "snapshots and hot-swap them into --sync-policy — version "
        "updates reach every replica without a restart. Independent "
        "of --distributed/--mailbox-dir (that one is fleet HEALTH; "
        "this one is the params feed)",
    )
    p.add_argument(
        "--sync-policy", default=None, metavar="ID",
        help="--sync-mailbox: resident policy the snapshots swap into "
        "(default: the default policy)",
    )
    p.add_argument(
        "--sync-rank", type=int, default=0, metavar="R",
        help="--sync-mailbox: publisher's mailbox rank to read "
        "(default 0)",
    )
    p.add_argument(
        "--sync-poll-s", type=float, default=0.25, metavar="S",
        help="--sync-mailbox: poll interval in seconds (default 0.25)",
    )
    args = p.parse_args(argv)

    if args.distributed and (args.mailbox_dir is None or args.world is None):
        raise SystemExit(
            "--distributed wants --mailbox-dir and --world (the fleet "
            "this gateway is a member of)"
        )
    from actor_critic_tpu.telemetry.exporter import validate_bind

    try:
        validate_bind(args.telemetry_bind, distributed=args.distributed)
    except ValueError as e:
        raise SystemExit(str(e))

    def parse_classed(items: list[str], flag: str, unit: str):
        default = None
        by_id: dict[str, float] = {}
        for item in items:
            try:
                if "=" in item:
                    pid, v = item.split("=", 1)
                    by_id[pid] = float(v)
                else:
                    default = float(item)
            except ValueError:
                raise SystemExit(f"{flag} wants [ID=]{unit}, got {item!r}")
        return default, by_id

    slo_default, slo_by_id = parse_classed(args.slo_ms, "--slo-ms", "MS")
    # The GLOBAL window feeds the batcher; per-policy ones ride handles.
    wait_default, wait_by_id = parse_classed(
        args.max_wait_us, "--max-wait-us", "US"
    )
    if wait_default is None:
        wait_default = 2000.0

    from actor_critic_tpu import config as config_mod
    from actor_critic_tpu import serving
    from actor_critic_tpu.utils import compile_cache

    preset = config_mod.resolve(
        args.preset, args.algo, args.env,
        config_mod.parse_set_args(args.set),
        config_mod.parse_env_set_args(args.env_set),
    )
    buckets = tuple(int(b) for b in args.buckets.split(",") if b.strip())
    spec = spec_for(preset.env, preset.env_kwargs)

    if args.compile_cache_dir:
        compile_cache.enable_persistent_cache(args.compile_cache_dir)

    session = None
    if args.telemetry_dir:
        from actor_critic_tpu import telemetry

        session = telemetry.TelemetrySession(
            args.telemetry_dir,
            run_info={"mode": "serve", "algo": preset.algo,
                      "env": preset.env, "buckets": list(buckets)},
            # Exporter sidecar on --telemetry-bind: the fleet
            # aggregation path (/fleetz on any member) scrapes THIS
            # per-rank endpoint, announced below under --distributed.
            serve_port=0,
            serve_host=args.telemetry_bind,
        )
        telemetry.set_current(session)

    runner = None
    if not args.no_warmup and args.backend in ("xla", "auto"):
        ctx = compile_cache.WarmupContext(
            algo=preset.algo, fused=False, spec=spec, cfg=preset.config,
            serving_buckets=buckets, serving_sample=args.sample,
        )
        runner = compile_cache.start_warmup(ctx)

    engine = serving.PolicyEngine(
        spec, preset.config, algo=preset.algo, buckets=buckets,
        sample=args.sample, seed=args.seed, backend=args.backend,
    )
    store = serving.PolicyStore()
    policies = parse_policies(args.policy)
    if not policies and not args.random_init:
        raise SystemExit("no policies: pass --policy ID=CKPT_DIR or "
                         "--random-init")
    resident = set(policies) | ({"default"} if args.random_init else set())
    if args.default is not None and args.default not in resident:
        raise SystemExit(
            f"--default {args.default!r} names no policy; resident: "
            f"{sorted(resident)}"
        )
    template = serving.init_params(spec, preset.config, preset.algo,
                                   seed=args.seed)
    if args.backend == "auto":
        # Fix the backend from measured batch-1 walls BEFORE any
        # policy installs (prepare_params needs a concrete backend).
        # The init template shares the checkpoints' architecture, so
        # the measurement transfers.
        choice = engine.resolve_backend(template)
        print(f"auto backend: {choice} ({engine.auto_choice})", flush=True)
    for pid, ckpt_dir in policies.items():
        params = serving.restore_policy_params(ckpt_dir, template)
        store.register(pid, engine, params, default=(pid == args.default),
                       slo_ms=slo_by_id.get(pid, slo_default),
                       max_wait_us=wait_by_id.get(pid))
        print(f"policy {pid!r} <- {ckpt_dir}", flush=True)
    if args.random_init:
        # Without --default the FIRST registration keeps the route (a
        # loaded checkpoint, when any was given): the random policy
        # must never silently steal traffic from a real one.
        store.register("default", engine, template,
                       default=(args.default == "default"),
                       slo_ms=slo_by_id.get("default", slo_default),
                       max_wait_us=wait_by_id.get("default"))
        print("policy 'default' <- random init", flush=True)
    for flag, by_id in (("--slo-ms", slo_by_id),
                        ("--max-wait-us", wait_by_id)):
        unknown = set(by_id) - set(store.ids())
        if unknown:
            raise SystemExit(
                f"{flag} names no resident policy: {sorted(unknown)}"
            )

    if runner is not None:
        runner.wait(timeout=120)
    if not args.no_warmup:
        # One concrete dispatch per bucket so the live jit cache is hot
        # (hits the persistent-cache entries the planner just wrote);
        # 0 on the mirror backend, where nothing compiles.
        n_warm = engine.warm(store.get(store.default_id).params)
        print(f"warm: {n_warm} act buckets compiled", flush=True)

    fleet = None
    aggregator = None
    if args.distributed:
        from actor_critic_tpu.parallel.multihost import FleetMonitor
        from actor_critic_tpu.telemetry.fleet import (
            FleetAggregator,
            announce_endpoint,
        )

        fleet = FleetMonitor(
            args.mailbox_dir, args.rank, args.world,
            stale_after_s=args.stale_after_s,
        )
        # Fleet metrics plane (ISSUE 16): announce this rank's exporter
        # into the shared mailbox and serve merged /fleetz views from
        # every member's discovered endpoint.
        if session is not None and session.exporter_port is not None:
            announce_endpoint(
                args.mailbox_dir, args.rank,
                f"http://{args.telemetry_bind}:{session.exporter_port}",
            )
        aggregator = FleetAggregator(mailbox_dir=args.mailbox_dir)

    syncer = None
    if args.sync_mailbox:
        sync_pid = args.sync_policy or store.default_id
        if sync_pid not in store.ids():
            raise SystemExit(
                f"--sync-policy {sync_pid!r} names no resident policy; "
                f"resident: {sorted(store.ids())}"
            )
        syncer = serving.MailboxPolicySyncer(
            store, sync_pid, args.sync_mailbox, rank=args.sync_rank,
            template=template, poll_s=args.sync_poll_s,
        ).start()
        print(
            f"policy sync: {sync_pid!r} <- {args.sync_mailbox} "
            f"(rank {args.sync_rank}, every {args.sync_poll_s:g}s)",
            flush=True,
        )

    gateway = serving.ServeGateway(
        store, port=args.port, host=args.host, session=session,
        max_wait_us=wait_default, queue_limit=args.queue_limit,
        fleet=fleet, aggregator=aggregator,
        max_inflight=args.max_inflight,
        shed_burn_threshold=args.shed_burn_threshold,
    )
    # The ACTUAL bound port — with --port 0 this is the OS-assigned one.
    routes = "/v1/swap /v1/policies /metrics /healthz" + (
        " /fleetz /fleetz/metrics" if aggregator is not None else ""
    )
    print(
        f"serving gateway: {gateway.url}/v1/act "
        f"(policies: {sorted(store.ids())}, default {store.default_id!r}; "
        f"also {routes})",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("shutting down", flush=True)
    finally:
        gateway.close()
        if syncer is not None:
            syncer.close()
        if session is not None:
            session.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
