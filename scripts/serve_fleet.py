#!/usr/bin/env python
"""Fleet fronting-proxy CLI (ISSUE 17 leg b): N gateway replicas
behind one address.

    # two replicas already serving (scripts/serve.py --port 8001/8002)
    python scripts/serve_fleet.py \
        --replica http://127.0.0.1:8001 --replica http://127.0.0.1:8002 \
        --port 8000

    # ephemeral port + round-robin + fast health probing (bench/CI)
    python scripts/serve_fleet.py --replica ... --port 0 \
        --policy round_robin --health-interval 0.25

The proxy relays each request to one healthy replica (least-loaded by
default) over kept-alive upstream connections, fails over on transport
errors, and evicts replicas whose /healthz fails --unhealthy-after
consecutive probes (a 200 readmits immediately). Application answers —
including a replica's 503 shed — relay verbatim; GET /proxyz serves the
proxy's own per-replica stats. The proxy holds no policy state: version
updates propagate replica-to-replica through the mailbox transport
(scripts/serve.py --sync-mailbox on each replica), never through here.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[1].strip())
    p.add_argument(
        "--replica", action="append", default=[], metavar="URL",
        help="upstream gateway base URL, e.g. http://127.0.0.1:8001 "
        "(repeatable; at least one required)",
    )
    p.add_argument(
        "--port", type=int, default=8000,
        help="proxy port; 0 binds an OS-assigned ephemeral port and "
        "prints it (default 8000)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument(
        "--policy", choices=("least_loaded", "round_robin"),
        default="least_loaded",
        help="replica selection: least_loaded picks the fewest in-"
        "flight relays (default); round_robin rotates",
    )
    p.add_argument(
        "--health-interval", type=float, default=1.0, metavar="S",
        help="seconds between /healthz probe rounds (default 1.0)",
    )
    p.add_argument(
        "--unhealthy-after", type=int, default=2, metavar="N",
        help="consecutive failed probes before a replica is evicted "
        "(default 2); one 200 readmits it",
    )
    p.add_argument(
        "--timeout", type=float, default=30.0, metavar="S",
        help="upstream relay timeout in seconds (default 30)",
    )
    args = p.parse_args(argv)
    if not args.replica:
        raise SystemExit("no replicas: pass --replica URL at least once")

    from actor_critic_tpu.serving import FleetProxy

    proxy = FleetProxy(
        args.replica, port=args.port, host=args.host, policy=args.policy,
        health_interval_s=args.health_interval,
        unhealthy_after=args.unhealthy_after, timeout_s=args.timeout,
    )
    print(
        f"fleet proxy on {proxy.url} -> {len(args.replica)} replicas "
        f"({args.policy}); GET /proxyz for stats",
        flush=True,
    )
    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    try:
        while not stop:
            signal.pause()
    finally:
        proxy.close()
        print("fleet proxy closed", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
