#!/usr/bin/env python
"""fleetsan: deterministic multi-process chaos sanitizer for the
mailbox/gossip/gateway stack (ISSUE 12).

    python scripts/fleetsan.py                       # quick profile
    python scripts/fleetsan.py --schedules 100       # wider sweep
    python scripts/fleetsan.py --scenario fleet --writer direct
                                                     # reproduce the
                                                     # torn-publish bug
    python scripts/fleetsan.py --scenario gateway --poller naive
                                                     # reproduce the
                                                     # version-regress bug
    python scripts/fleetsan.py --scenario process    # REAL subprocess
                                                     # kill/restart TTR
    python scripts/fleetsan.py --json                # machine output

Exit codes (scripts/tier1.sh runs the quick profile between racesan
and pytest, under its own timeout):
    0  clean: every seeded chaos schedule swept without a violation
    1  violation: a schedule detected a protocol break (torn publish,
       tempfile collision, version regression, unbounded recovery) —
       the sanitizer working
    2  crash: unexpected error (a broken exerciser, not a detection)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[1].strip())
    p.add_argument(
        "--schedules", type=int, default=30,
        help="seeded chaos schedules to sweep (default 30, the tier-1 "
        "quick profile: half fleet, half gateway)",
    )
    p.add_argument(
        "--seed0", type=int, default=0,
        help="first seed of the sweep (fixed seeds keep tier-1 "
        "deterministic; a detected violation names its seed for replay)",
    )
    p.add_argument(
        "--scenario",
        choices=("all", "fleet", "gateway", "replica", "process"),
        default="all",
        help="which unit to exercise (default: the quick profile; "
        "'replica' is the ISSUE 17 replica-kill-mid-swap schedule — "
        "N MailboxPolicySyncer replicas under kill/restart + the "
        "fault menu; 'process' spawns REAL gossip workers and "
        "SIGKILLs one)",
    )
    p.add_argument(
        "--writer", choices=("atomic", "direct", "shared-tmp"),
        default="atomic",
        help="fleet publish mode: 'direct'/'shared-tmp' are the "
        "reverted-bug writers (expected exit 1)",
    )
    p.add_argument(
        "--poller", choices=("guarded", "naive"), default="guarded",
        help="gateway consume mode: 'naive' is the reverted "
        "no-per-peer-clock consumer (expected exit 1)",
    )
    p.add_argument(
        "--world", type=int, default=3,
        help="fleet scenario rank count (default 3 — ring rotation "
        "needs >= 3 to distinguish per-peer clocks from global ones)",
    )
    p.add_argument(
        "--duration-s", type=float, default=8.0,
        help="process scenario: per-worker wall window",
    )
    p.add_argument(
        "--flight-dump", default="", metavar="DIR",
        help="ALSO run one short real-SIGKILL schedule with per-host "
        "telemetry under DIR and harvest the victim's crash flight "
        "ring into DIR/host<rank>/flight_dump_*.json (tier-1 asserts "
        "the dump exists; violation exit 1 if the ring is empty)",
    )
    p.add_argument("--json", action="store_true", help="machine output")
    args = p.parse_args(argv)

    from actor_critic_tpu.analysis import fleetsan

    try:
        if args.scenario == "all":
            out = fleetsan.quick_profile(
                schedules=args.schedules, seed0=args.seed0
            )
        elif args.scenario == "fleet":
            out = fleetsan.exercise_sweep(
                range(args.seed0, args.seed0 + args.schedules),
                lambda s: fleetsan.exercise_fleet(
                    s, world=args.world,
                    writer=args.writer.replace("-", "_"),
                ),
            )
        elif args.scenario == "gateway":
            out = fleetsan.exercise_sweep(
                range(args.seed0, args.seed0 + args.schedules),
                lambda s: fleetsan.exercise_gateway(
                    s, poller=args.poller
                ),
            )
        elif args.scenario == "replica":
            out = fleetsan.exercise_sweep(
                range(args.seed0, args.seed0 + args.schedules),
                lambda s: fleetsan.exercise_replica_fleet(
                    s, replicas=args.world
                ),
            )
        else:
            out = fleetsan.run_process_chaos(
                duration_s=args.duration_s, seed=args.seed0
            )
        if args.flight_dump:
            # One short REAL kill/restart schedule with telemetry on:
            # the acceptance check that a SIGKILL'd rank's flight ring
            # is harvestable post-mortem (duration trimmed to fit the
            # tier-1 step budget next to the sim sweep above).
            os.makedirs(args.flight_dump, exist_ok=True)
            chaos = fleetsan.run_process_chaos(
                duration_s=6.0, kill_after_s=2.5,
                seed=args.seed0, telemetry_dir=args.flight_dump,
            )
            out = dict(out) if isinstance(out, dict) else {"sweep": out}
            out.update(
                flight_dump=chaos.get("flight_dump"),
                flight_records=chaos.get("flight_records"),
                flight_ttr_s=chaos.get("time_to_recover_s"),
            )
    except fleetsan.FleetSanError as e:
        # A detected violation names its seed: rerun that single seed
        # to replay the schedule (and its faults) bit-identically.
        print(f"fleetsan: VIOLATION DETECTED: {e}", file=sys.stderr)
        return 1
    except Exception as e:
        print(f"fleetsan: error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    if args.flight_dump and not args.json:
        print(
            f"fleetsan: flight dump harvested — {out.get('flight_dump')} "
            f"({out.get('flight_records')} ring records, TTR "
            f"{out.get('flight_ttr_s')}s)"
        )
    if args.json:
        print(json.dumps(out, indent=2, default=str))
    elif args.scenario == "process":
        print(
            f"fleetsan: host kill/restart clean — time-to-recover "
            f"{out.get('time_to_recover_s')}s "
            f"(survivor mixes {out.get('survivor_gossip_mixes')})"
        )
    else:
        print(f"fleetsan: {out.get('schedules', 0)} chaos schedule(s) clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
