#!/usr/bin/env python
"""Load generator for the policy-serving gateway (ISSUE 10/17).

    python scripts/serve_loadgen.py --url http://127.0.0.1:8000 \
        --concurrency 16 --duration 10 --obs-dim 4 [--rows 1] [--json]

    # open-loop: 200 requests/s fixed arrival schedule (ISSUE 17)
    python scripts/serve_loadgen.py --url ... --rate 200 --duration 10

Closed loop (default): N worker threads each POST /v1/act, wait for the
reply, repeat — over ONE keep-alive connection each, so measured
latency is the gateway's (queue wait + micro-batch window + dispatch),
not TCP setup. Closed-loop at saturating concurrency is the SLO-bench
shape: offered load adapts to service rate, and p50/p99 come from the
per-request walls the workers record.

Open loop (`--rate R`): arrivals are pinned to a fixed schedule —
request k fires at `k / R` seconds regardless of how the previous one
fared (worker w takes arrivals w, w+C, w+2C, ...). Offered load does
NOT adapt, so saturation shows up as queueing/shedding instead of a
silently slowed generator: `late` counts arrivals that fired behind
schedule (every connection busy past its slot — the open-loop
saturation signal), and 503s are split into `shed` (the gateway's
admission-control answer, body `shed: true`) vs plain `rejected_503`
(queue-full). `run_load` is the library entry `bench/suite.py`
drives."""

from __future__ import annotations

import argparse
import http.client
import json
import math
import os
import socket
import sys
import threading
import time
from urllib.parse import urlparse

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _percentile(sorted_vals: list, p: float) -> float:
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   math.ceil(p / 100.0 * len(sorted_vals)) - 1))
    return float(sorted_vals[k])


def _worker(
    url: str,
    body: bytes,
    rows: int,
    deadline: float,
    timeout_s: float,
    out: dict,
    start: threading.Event,
    arrivals: tuple | None = None,
) -> None:
    """One load worker. `arrivals=None` is the closed loop; an
    `(offset_s, step_s)` pair is this worker's slice of the open-loop
    schedule: its k-th request fires at `start + offset + k*step`."""
    parsed = urlparse(url)
    lat_ms: list[float] = []
    errors = 0
    late = 0
    shed = 0
    rejected_503 = 0

    def connect() -> http.client.HTTPConnection:
        c = http.client.HTTPConnection(
            parsed.hostname, parsed.port, timeout=timeout_s
        )
        c.connect()
        # Nagle off, matching the gateway handler: small POST bodies
        # otherwise pay the ~40 ms delayed-ACK stall per round trip.
        c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return c

    conn = None
    headers = {"Content-Type": "application/json"}
    start.wait()
    t_base = time.monotonic()
    k = 0
    try:
        while time.monotonic() < deadline:
            if arrivals is not None:
                # Fixed-arrival-rate pacing: sleep until this worker's
                # next slot; firing past it means the previous request
                # overran — the open-loop saturation signal.
                t_next = t_base + arrivals[0] + k * arrivals[1]
                if t_next >= deadline:
                    break
                now = time.monotonic()
                if t_next > now:
                    time.sleep(t_next - now)
                else:
                    late += 1
                k += 1
            if conn is None:
                # Inside the loop and counted: a dead/refusing gateway
                # must surface as errors, not kill the worker before it
                # records anything (a zero-request, zero-error result
                # would read as a clean measurement).
                try:
                    conn = connect()
                except Exception:
                    errors += 1
                    time.sleep(0.05)
                    continue
            t0 = time.monotonic()
            try:
                conn.request("POST", "/v1/act", body=body, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()  # must drain for keep-alive reuse
                if resp.will_close:
                    # HTTP/1.0 server (the sequential baseline): no
                    # keep-alive — reconnect per request, which is part
                    # of that architecture's cost; the reconnect happens
                    # at the top of the next iteration.
                    conn.close()
                    conn = None
                if resp.status != 200:
                    errors += 1
                    if resp.status == 503:
                        # Discriminate the gateway's two 503 classes
                        # (ISSUE 17): admission-control shed marks its
                        # body; a plain 503 is queue-full/down.
                        try:
                            if json.loads(payload).get("shed"):
                                shed += 1
                            else:
                                rejected_503 += 1
                        except Exception:
                            rejected_503 += 1
                    continue
                json.loads(payload)
            except Exception:
                errors += 1
                # The connection state is unknown after a failure;
                # drop it and let the loop top rebuild (counted there
                # if the gateway is down).
                try:
                    conn.close()
                except Exception:
                    pass
                conn = None
                continue
            lat_ms.append((time.monotonic() - t0) * 1e3)
    finally:
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
        # Inside finally: even an unexpected worker death must leave
        # its partial tallies readable instead of a silent clean zero.
        out["lat_ms"] = lat_ms
        out["errors"] = errors
        out["late"] = late
        out["shed"] = shed
        out["rejected_503"] = rejected_503
        out["rows"] = rows


def run_load(
    url: str,
    concurrency: int = 16,
    duration_s: float = 10.0,
    obs=None,
    obs_dim: int = 4,
    rows: int = 1,
    policy: str | None = None,
    timeout_s: float = 30.0,
    rate: float | None = None,
) -> dict:
    """Drive the gateway; returns the SLO summary (requests,
    actions_per_s, p50/p99/max ms, errors). `obs` overrides the
    generated [rows, obs_dim] zero observation batch. `rate` switches
    to the open loop: requests/s offered on a fixed arrival schedule
    striped across the workers (module docstring)."""
    if rate is not None and rate <= 0:
        raise ValueError(f"rate must be > 0 req/s, got {rate!r}")
    if obs is None:
        obs = [[0.1] * obs_dim for _ in range(rows)]
    body_obj: dict = {"obs": obs}
    if policy is not None:
        body_obj["policy"] = policy
    body = json.dumps(body_obj).encode()
    start = threading.Event()
    deadline = time.monotonic() + duration_s
    results: list[dict] = [{} for _ in range(concurrency)]
    threads = [
        threading.Thread(
            target=_worker,
            args=(url, body, rows, deadline, timeout_s, results[i], start,
                  None if rate is None
                  else (i / rate, concurrency / rate)),
            name=f"loadgen-{i}",
            daemon=True,
        )
        for i in range(concurrency)
    ]
    for t in threads:
        t.start()
    t_start = time.monotonic()
    start.set()
    for t in threads:
        t.join(duration_s + timeout_s + 10)
    wall = time.monotonic() - t_start
    lat = sorted(x for r in results for x in r.get("lat_ms", []))
    requests = len(lat)
    errors = sum(r.get("errors", 0) for r in results)
    return {
        "mode": "closed" if rate is None else "open",
        "requests": requests,
        "errors": errors,
        "late": sum(r.get("late", 0) for r in results),
        "shed": sum(r.get("shed", 0) for r in results),
        "rejected_503": sum(r.get("rejected_503", 0) for r in results),
        "offered_per_s": None if rate is None else float(rate),
        "wall_s": round(wall, 3),
        "requests_per_s": round(requests / wall, 2) if wall > 0 else 0.0,
        "actions_per_s": round(requests * rows / wall, 2) if wall > 0 else 0.0,
        "p50_ms": round(_percentile(lat, 50), 3),
        "p99_ms": round(_percentile(lat, 99), 3),
        "max_ms": round(lat[-1], 3) if lat else 0.0,
        "config": {
            "concurrency": concurrency,
            "duration_s": duration_s,
            "rows": rows,
            "rate": rate,
        },
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[1].strip())
    p.add_argument("--url", required=True, help="gateway base URL")
    p.add_argument("--concurrency", type=int, default=16)
    p.add_argument("--duration", type=float, default=10.0, metavar="S")
    p.add_argument(
        "--obs-dim", type=int, default=4,
        help="flat observation dimension of the generated payload",
    )
    p.add_argument(
        "--rows", type=int, default=1,
        help="observations per request (default 1 — the GA3C shape)",
    )
    p.add_argument("--policy", default=None, help="policy id to route to")
    p.add_argument("--timeout", type=float, default=30.0)
    p.add_argument(
        "--rate", type=float, default=None, metavar="R",
        help="open-loop mode: offer R requests/s on a fixed arrival "
        "schedule striped across --concurrency connections (default: "
        "closed loop — each worker waits for its reply)",
    )
    p.add_argument("--json", action="store_true", help="machine output")
    args = p.parse_args(argv)
    out = run_load(
        args.url,
        concurrency=args.concurrency,
        duration_s=args.duration,
        obs_dim=args.obs_dim,
        rows=args.rows,
        policy=args.policy,
        timeout_s=args.timeout,
        rate=args.rate,
    )
    if args.json:
        print(json.dumps(out))
    else:
        extra = (
            f"; offered {out['offered_per_s']}/s, late {out['late']}, "
            f"shed {out['shed']}, rejected {out['rejected_503']}"
            if out["mode"] == "open" else ""
        )
        print(
            f"{out['requests']} requests ({out['errors']} errors) in "
            f"{out['wall_s']}s -> {out['actions_per_s']} actions/s; "
            f"p50 {out['p50_ms']} ms, p99 {out['p99_ms']} ms, "
            f"max {out['max_ms']} ms{extra}"
        )
    return 0 if out["errors"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
