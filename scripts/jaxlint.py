#!/usr/bin/env python
"""jaxlint: repo-wide JAX correctness analyzer (ISSUE 5).

    python scripts/jaxlint.py                         # default scan set
    python scripts/jaxlint.py actor_critic_tpu train.py bench
    python scripts/jaxlint.py --list-checks
    python scripts/jaxlint.py --select lock-discipline,check-then-act
    python scripts/jaxlint.py --diff HEAD             # changed files only
    python scripts/jaxlint.py --since HEAD~2          # + untracked files,
                                                      # fixture-pair re-lint
    python scripts/jaxlint.py --json                  # machine output
    python scripts/jaxlint.py --write-baseline        # regenerate
    python scripts/jaxlint.py --prune-stale           # drop dead entries
    python scripts/jaxlint.py --show-baselined        # audit accepted

Exit codes (tier-1 tells them apart — scripts/tier1.sh):
    0  clean: zero un-baselined findings
    1  findings: at least one finding not covered by the baseline
    2  crash: parse error, unreadable path, malformed baseline, bad
       check name

`--error-on-new` names the default gate explicitly for CI readability;
it is always on. Suppress a single line in source with
`# jaxlint: disable=<check>[,<check>]` (put the why in the same
comment); accept a finding repo-wide by adding it to
`jaxlint_baseline.json` with a reason (`--write-baseline` drafts
entries, reasons must be filled in by hand).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULT_PATHS = ("actor_critic_tpu", "train.py", "bench")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[1].strip())
    p.add_argument(
        "paths", nargs="*", default=list(DEFAULT_PATHS),
        help=f"files/dirs to scan (default: {' '.join(DEFAULT_PATHS)})",
    )
    p.add_argument(
        "--list-checks", action="store_true",
        help="print the registered checks with one-line docs and exit 0",
    )
    p.add_argument(
        "--json", action="store_true",
        help="machine-readable output (consumed by scripts/run_report.py)",
    )
    p.add_argument(
        "--baseline", default=None,
        help="baseline file (default: <repo>/jaxlint_baseline.json)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding as new",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite the baseline from the current findings (existing "
        "reasons are preserved; new entries get a NEEDS-REASON "
        "placeholder) and exit 0",
    )
    p.add_argument(
        "--show-baselined", action="store_true",
        help="also print baselined findings with their reasons",
    )
    p.add_argument(
        "--select", "--checks", dest="select", default=None,
        help="comma-separated subset of checks to run (e.g. "
        "--select lock-discipline,check-then-act; --checks is the "
        "original spelling, kept as an alias)",
    )
    p.add_argument(
        "--skip", default=None,
        help="comma-separated checks to skip (e.g. warmup-registry to "
        "stay fully import-free)",
    )
    p.add_argument(
        "--diff", metavar="REF", default=None,
        help="lint only .py files changed vs the given git ref (working "
        "tree vs REF, e.g. --diff HEAD or --diff origin/main), "
        "intersected with the scanned paths — the pre-commit fast "
        "path: repo-scope checks see only the changed files, so the "
        "whole-repo model builds are skipped (cross-file findings may "
        "be missed; the full run stays the tier-1 gate). Exit codes "
        "unchanged; zero changed files is a clean exit 0",
    )
    p.add_argument(
        "--since", metavar="REV", default=None,
        help="like --diff, with the pre-commit ergonomics on top: REV "
        "is resolved through `git rev-parse` first (HEAD~2, branch "
        "names, tags — a typo'd rev is a clear exit-2 error, not an "
        "empty diff), untracked .py files count as changed (a "
        "brand-new module is linted before its first commit), and a "
        "change touching only a check's FIXTURE pair "
        "(tests/jaxlint_fixtures/<check>_{flag,ok}.py) re-lints the "
        "module implementing that check — editing the pinned contract "
        "re-examines the pass it pins",
    )
    p.add_argument(
        "--prune-stale", action="store_true",
        help="rewrite the baseline WITHOUT the stale entries this run "
        "can see (scanned paths × selected checks) and exit 0 — stale "
        "fingerprints otherwise linger as warnings forever",
    )
    p.add_argument(
        "--error-on-new", action="store_true",
        help="fail (exit 1) when un-baselined findings exist — the "
        "default, named explicitly for CI invocations",
    )
    args = p.parse_args(argv)

    from actor_critic_tpu import analysis

    if args.list_checks:
        checks = analysis.registered_checks()
        width = max(len(c.name) for c in checks)
        for c in checks:
            print(f"{c.name:<{width}}  {c.doc}")
        return 0

    if (args.write_baseline or args.prune_stale) and args.no_baseline:
        # --no-baseline empties the loaded entries, so combining it with
        # a baseline-rewriting mode would rewrite the file from nothing
        # — every audited reason silently destroyed. Refuse loudly.
        print(
            "jaxlint: error: --write-baseline/--prune-stale cannot be "
            "combined with --no-baseline (it would discard every "
            "existing audited entry)",
            file=sys.stderr,
        )
        return 2

    checks = args.select.split(",") if args.select else None
    skip = args.skip.split(",") if args.skip else ()
    baseline_path = args.baseline or analysis.default_baseline_path(REPO)

    if args.diff is not None and args.since is not None:
        print(
            "jaxlint: error: --diff and --since are the same fast path "
            "with different ergonomics — pass one",
            file=sys.stderr,
        )
        return 2

    paths = list(args.paths)
    ref = args.since if args.since is not None else args.diff
    if ref is not None:
        import subprocess

        flag = "--since" if args.since is not None else "--diff"
        if args.since is not None:
            # Resolve the rev up front: `git diff` against a typo'd rev
            # fails with the same message an empty tree would, so the
            # pre-commit path names the bad input explicitly.
            try:
                proc = subprocess.run(
                    ["git", "rev-parse", "--verify",
                     f"{ref}^{{commit}}"],
                    capture_output=True, text=True, cwd=REPO, check=True,
                )
                ref = proc.stdout.strip()
            except (OSError, subprocess.CalledProcessError) as e:
                detail = (getattr(e, "stderr", "") or str(e)).strip()
                print(
                    f"jaxlint: error: --since {args.since}: not a "
                    f"resolvable rev ({detail.splitlines()[-1]})",
                    file=sys.stderr,
                )
                return 2
        try:
            proc = subprocess.run(
                ["git", "diff", "--name-only", ref, "--", "*.py"],
                capture_output=True, text=True, cwd=REPO, check=True,
            )
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            print(
                f"jaxlint: error: {flag} {ref}: {detail.strip()}",
                file=sys.stderr,
            )
            return 2
        changed = {
            ln.strip() for ln in proc.stdout.splitlines() if ln.strip()
        }
        if args.since is not None:
            # Untracked modules are "changed vs REV" for pre-commit
            # purposes: a brand-new file must be linted before its
            # first commit, and `git diff REV` cannot see it.
            try:
                proc = subprocess.run(
                    ["git", "ls-files", "--others", "--exclude-standard",
                     "--", "*.py"],
                    capture_output=True, text=True, cwd=REPO, check=True,
                )
            except (OSError, subprocess.CalledProcessError) as e:
                detail = getattr(e, "stderr", "") or str(e)
                print(
                    f"jaxlint: error: --since untracked scan: "
                    f"{detail.strip()}",
                    file=sys.stderr,
                )
                return 2
            changed |= {
                ln.strip() for ln in proc.stdout.splitlines() if ln.strip()
            }
            # Fixture-pair rule: the fixture files pin a check's
            # flag/ok contract, so a change touching ONLY
            # tests/jaxlint_fixtures/<check>_{flag,ok}.py re-lints the
            # module IMPLEMENTING that check — the pass and its pinned
            # contract are one unit of review.
            import re as _re

            fixture_re = _re.compile(
                r"^tests/jaxlint_fixtures/(.+)_(?:flag|ok)\.py$"
            )
            registry = {c.name: c for c in analysis.registered_checks()}
            for f in sorted(changed):
                m = fixture_re.match(f)
                if not m:
                    continue
                check = analysis.core.resolve_check_name(
                    m.group(1).replace("_", "-")
                )
                c = registry.get(check)
                if c is None:
                    continue  # a fixture with no registered pass
                mod_file = getattr(
                    sys.modules.get(c.fn.__module__), "__file__", None
                )
                if mod_file:
                    changed.add(
                        os.path.relpath(mod_file, REPO).replace(
                            os.sep, "/"
                        )
                    )
        # Intersect with the scan set: a changed file outside the
        # requested paths (tests, scripts) stays out, exactly as in a
        # full run over the same paths.
        try:
            scan_set = {
                os.path.relpath(p, REPO).replace(os.sep, "/")
                for p in analysis.core.iter_python_files(paths, REPO)
            }
        except analysis.AnalysisError as e:
            print(f"jaxlint: error: {e}", file=sys.stderr)
            return 2
        paths = sorted(
            f for f in changed
            if f in scan_set and os.path.exists(os.path.join(REPO, f))
        )
        if not paths:
            print(
                f"jaxlint: no scanned .py files changed vs "
                f"{args.since or args.diff} — nothing to lint"
            )
            return 0

    try:
        modules = analysis.load_modules(paths, REPO)
        findings = analysis.run_checks(modules, checks=checks, skip=skip)
        entries = (
            [] if args.no_baseline else analysis.load_baseline(baseline_path)
        )
    except analysis.AnalysisError as e:
        print(f"jaxlint: error: {e}", file=sys.stderr)
        return 2

    scanned = {m.relpath for m in modules}
    # Alias-resolved, exactly as run_checks resolves them: `--skip
    # host-sync` must deselect transfer-discipline HERE too, or the
    # stale-scoping below would call its audited baseline entries
    # stale (and --prune-stale would delete them).
    resolve = analysis.core.resolve_check_name
    selected = (
        {resolve(c) for c in checks}
        if checks
        else {c.name for c in analysis.registered_checks()}
    )
    selected -= {resolve(c) for c in skip}

    if args.write_baseline:
        # A scoped run (path subset, --checks/--skip) regenerates only
        # what it could SEE; entries outside the scanned files or the
        # selected checks are retained verbatim, so a partial rewrite
        # can never silently delete another file's audited reasons.
        retained = [
            e
            for e in entries
            if e.get("path") not in scanned or e.get("check") not in selected
        ]
        entries_out = analysis.regenerate(findings, entries)
        have = {
            analysis.baseline.entry_fingerprint(e) for e in entries_out
        }
        entries_out += [
            e
            for e in retained
            if analysis.baseline.entry_fingerprint(e) not in have
        ]
        analysis.save_baseline(baseline_path, entries_out)
        placeholders = sum(
            1 for e in entries_out if str(e["reason"]).startswith("NEEDS-")
        )
        print(
            f"jaxlint: wrote {len(entries_out)} baseline entr"
            f"{'y' if len(entries_out) == 1 else 'ies'} to {baseline_path}"
            + (
                f" — fill in {placeholders} NEEDS-REASON placeholder(s)"
                if placeholders
                else ""
            )
        )
        return 0

    new, matched, stale = analysis.apply_baseline(findings, entries)
    # Stale = "matches no finding" is only meaningful for files this
    # run actually scanned AND checks it actually ran; a path- or
    # check-subset run must not call the rest of the baseline stale.
    stale = [
        e
        for e in stale
        if e.get("path") in scanned and e.get("check") in selected
    ]

    if args.prune_stale:
        # Drop exactly the stale-in-scope entries; everything else
        # (matched entries, out-of-scope files/checks) is retained
        # verbatim — pruning is scoped the same way stale REPORTING is.
        drop = {analysis.baseline.entry_fingerprint(e) for e in stale}
        kept = [
            e
            for e in entries
            if analysis.baseline.entry_fingerprint(e) not in drop
        ]
        analysis.save_baseline(baseline_path, kept)
        print(
            f"jaxlint: pruned {len(stale)} stale baseline entr"
            f"{'y' if len(stale) == 1 else 'ies'} "
            f"({len(kept)} kept) from {baseline_path}"
        )
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "new": [f.to_dict() for f in new],
                    "baselined": [
                        {**f.to_dict(), "reason": e.get("reason")}
                        for f, e in matched
                    ],
                    "stale_baseline_entries": stale,
                    "counts": {
                        "new": len(new),
                        "baselined": len(matched),
                        "stale": len(stale),
                    },
                },
                indent=2,
            )
        )
        return 1 if new else 0

    for f in new:
        print(f.render())
    if args.show_baselined:
        for f, e in matched:
            print(f"{f.render()}  [baselined: {e.get('reason')}]")
    for e in stale:
        print(
            "jaxlint: warning: stale baseline entry "
            f"{analysis.baseline.entry_fingerprint(e)!r} matches no "
            "finding — remove it (or rerun --write-baseline)",
            file=sys.stderr,
        )
    summary = (
        f"jaxlint: {len(new)} new finding(s), {len(matched)} baselined, "
        f"{len(stale)} stale baseline entr"
        f"{'y' if len(stale) == 1 else 'ies'}"
    )
    print(summary, file=sys.stderr if new else sys.stdout)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
