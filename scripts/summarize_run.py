#!/usr/bin/env python
"""Summarize a training-metrics JSONL into BASELINE.md row numbers.

Reads the JSONL a `train.py --metrics` run streams (one dict per logged
iteration; eval rows carry `eval_return`) and prints best/final eval
return, the env-step and wall-clock positions where they happened, and
effective steps/sec — the numbers BASELINE.md's measured table records
for the MuJoCo configs (BASELINE.json:2,8-10).

    python scripts/summarize_run.py runs/sac_humanoid_run1.jsonl
    python scripts/summarize_run.py runs/*.jsonl   # one block per file

Wall-clock caveat: `wall_s` is per-process. A run that was resumed
(scripts/run_resumable.sh) restarts the counter, so this script sums the
segments: a wall_s decrease or a non-increasing iter marks a new
process, and the reported total adds each segment's max (restore/compile
time between segments is NOT counted — the printed total is optimistic
by the restart overhead; the segment count is printed so a reader can
see it). Eval positions are reported in resume-summed wall-clock.
"""

from __future__ import annotations

import json
import sys


def summarize(path: str) -> dict:
    rows = []
    bad_lines = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                # A process killed mid-write (the run_resumable.sh
                # stall-kill scenario) leaves a torn line; count it
                # rather than aborting the whole summary.
                bad_lines += 1
    if not rows:
        return {"path": path, "empty": True, "bad_lines": bad_lines}

    # Sum wall-clock across resume segments (wall_s resets per process).
    # A new process shows as a wall_s decrease OR a non-increasing iter
    # (resume restarts from the last checkpoint, which is <= the last
    # logged iteration) — wall_s alone misses a restart whose first
    # logged wall_s already exceeds the previous segment's last.
    base = 0.0  # sum of completed segments' maxima
    seg_max = 0.0
    segments = 1
    prev_w, prev_it = -1.0, -1
    for r in rows:
        w = float(r.get("wall_s", 0.0))
        it = int(r.get("iter", prev_it + 1))
        if w < prev_w or it <= prev_it:  # new process
            base += seg_max
            seg_max = 0.0
            segments += 1
        seg_max = max(seg_max, w)
        r["_cum_wall_s"] = base + w  # resume-summed position of this row
        prev_w, prev_it = w, it
    total_wall = base + seg_max

    last = rows[-1]
    # JsonlLogger scrubs non-finite metrics to null — a diverged run logs
    # eval_return=null, which must not crash the max() below.
    evals = [
        r for r in rows
        if isinstance(r.get("eval_return"), (int, float))
    ]
    out = {
        "path": path,
        "rows": len(rows),
        **({"bad_lines": bad_lines} if bad_lines else {}),
        "segments": segments,
        "final_iter": last.get("iter"),
        "env_steps": last.get("env_steps"),
        "wall_s_sum": round(total_wall, 1),
        "steps_per_sec": (
            round(float(last["env_steps"]) / total_wall, 1)
            if total_wall > 0 and "env_steps" in last
            else None
        ),
        "final_train_return": last.get("recent_return", last.get("avg_return_ema")),
    }
    if evals:
        best = max(evals, key=lambda r: r["eval_return"])
        out.update(
            eval_count=len(evals),
            best_eval=round(float(best["eval_return"]), 1),
            best_eval_at_steps=best.get("env_steps"),
            best_eval_at_wall_s=round(best["_cum_wall_s"], 1),
            final_eval=round(float(evals[-1]["eval_return"]), 1),
            final_eval_at_steps=evals[-1].get("env_steps"),
        )
    return out


def main() -> None:
    paths = sys.argv[1:]
    if not paths:
        sys.exit("usage: summarize_run.py metrics.jsonl [...]")
    for p in paths:
        print(json.dumps(summarize(p)))


if __name__ == "__main__":
    main()
