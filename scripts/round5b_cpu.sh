#!/usr/bin/env bash
# Round-5 depth queue: third seeds for the TD3 Walker2d and SAC Humanoid
# rows (upgrades mean±range over 2 seeds to 3-seed statistics). Launch
# AFTER scripts/round5_cpu.sh drains — the 1-core host serializes
# everything. Same recipe as the recorded seeds, new seed, --fresh dirs.
set -u
cd "$(dirname "$0")/.."
export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu
mkdir -p runs results

echo "[q5b] TD3 Walker2d seed 2 on CPU"
nice -n 5 scripts/run_resumable.sh --preset td3_walker2d --fresh \
  --ckpt-dir runs/td3_w2_s2 --save-every 2000 --eval-every 500 --eval-envs 16 \
  --metrics runs/td3_walker2d_run4_seed2.jsonl --seed 2 --quiet \
  > runs/td3_w2_s2_stdout.log 2>&1
echo "[q5b] td3 seed2 rc=$?"

echo "[q5b] SAC Humanoid seed 2 on CPU"
nice -n 5 scripts/run_resumable.sh --preset sac_humanoid --fresh \
  --ckpt-dir runs/sac_hum_s2 --save-every 2000 --eval-every 500 --eval-envs 16 \
  --no-save-replay --metrics runs/sac_humanoid_run3_seed2.jsonl --seed 2 --quiet \
  > runs/sac_hum_s2_stdout.log 2>&1
echo "[q5b] sac seed2 rc=$?"
