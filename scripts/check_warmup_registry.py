#!/usr/bin/env python
"""Lint: every `jax.jit` entry point in `actor_critic_tpu/algos/` and
`actor_critic_tpu/models/` must be registered for AOT warmup
(utils/compile_cache.py) or exempted there with a reason (ISSUE 4).

The compile-once contract only holds if the warmup registry keeps up
with the code: a new jitted entry point that nobody registers silently
reintroduces first-dispatch compile into time-to-first-step. This lint
makes that a tier-1 failure (tests/test_warmup_registry.py) instead of
a perf regression someone notices weeks later.

Mechanics: AST-scan the two packages for `jax.jit` references (direct
calls, decorators, and `partial(jax.jit, ...)` all contain the same
`jax.jit` attribute node), key each site by
"<module>.<enclosing top-level function>", and require every key to be
in `compile_cache.registered_warmups()` or `compile_cache.EXEMPT`.
Stale EXEMPT keys (naming no existing jit site) are errors too, so
refactors can't leave dead exemptions shadowing future sites. The
registry is deliberately allowed to hold MORE keys than there are jit
sites: several factories (make_train_step / make_eval_fn /
make_greedy_act) contain no `jax.jit` themselves — their CALLERS jit
them (train.py's run_fused, the host loops) — yet still need warmup
planners; a registration whose factory was deleted outright fails
loudly at plan time instead (`plan_warmup` prints the planner error
and emits a `warmup_plan_error` telemetry event).

Exit 0 when clean; 1 with a per-site report otherwise.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_DIRS = ("actor_critic_tpu/algos", "actor_critic_tpu/models")


def jit_sites(path: str) -> list[tuple[str, int]]:
    """(enclosing top-level function name, lineno) for each `jax.jit`
    reference in the file ("<module>" when at module scope)."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    sites: list[tuple[str, int]] = []

    def is_jax_jit(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "jit"
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax"
        )

    def scan(node: ast.AST, enclosing: str) -> None:
        for child in ast.iter_child_nodes(node):
            name = enclosing
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and enclosing == "<module>":
                name = child.name
            if is_jax_jit(child):
                sites.append((enclosing, child.lineno))
            scan(child, name)

    scan(tree, "<module>")
    return sites


def collect_sites() -> dict[str, list[str]]:
    """registry key -> ['path:line', ...] over the scanned packages."""
    out: dict[str, list[str]] = {}
    for rel in SCAN_DIRS:
        root = os.path.join(REPO, rel)
        for fname in sorted(os.listdir(root)):
            if not fname.endswith(".py") or fname == "__init__.py":
                continue
            mod = fname[:-3]
            path = os.path.join(root, fname)
            for func, lineno in jit_sites(path):
                key = f"{mod}.{func}"
                out.setdefault(key, []).append(
                    f"{os.path.relpath(path, REPO)}:{lineno}"
                )
    return out


def main(argv=None) -> int:
    sys.path.insert(0, REPO)
    import actor_critic_tpu.config  # noqa: F401 — imports every algo module,
    # which registers its warmup planners as an import side effect
    from actor_critic_tpu.utils import compile_cache

    registered = set(compile_cache.registered_warmups())
    exempt = dict(compile_cache.EXEMPT)
    sites = collect_sites()

    problems: list[str] = []
    for key, locations in sorted(sites.items()):
        if key in registered or key in exempt:
            continue
        problems.append(
            f"UNREGISTERED jax.jit entry point {key!r} at "
            f"{', '.join(locations)} — register an AOT warmup planner "
            "in its module (compile_cache.register_warmup) or add it to "
            "compile_cache.EXEMPT with a reason"
        )
    # Stale exemptions rot fastest (a refactor renames the function and
    # the exemption silently stops covering anything).
    for key in sorted(exempt):
        if key not in sites:
            problems.append(
                f"STALE exemption {key!r} in compile_cache.EXEMPT — "
                "no such jax.jit site exists anymore"
            )

    if problems:
        print("\n".join(problems), file=sys.stderr)
        print(
            f"\ncheck_warmup_registry: {len(problems)} problem(s); "
            f"{len(sites)} jit site(s), {len(registered)} registered, "
            f"{len(exempt)} exempt.",
            file=sys.stderr,
        )
        return 1
    print(
        f"check_warmup_registry: OK — {len(sites)} jax.jit site(s) in "
        f"algos//models/ all covered ({len(registered)} registered "
        f"warmups, {len(exempt)} exemptions)."
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
