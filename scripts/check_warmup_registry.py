#!/usr/bin/env python
"""Thin shim (ISSUE 5): the warmup-registry lint now lives in
`actor_critic_tpu/analysis/warmup.py` as jaxlint's `warmup-registry`
pass (run `python scripts/jaxlint.py` for the full analyzer). This
entry point keeps the original CLI and API — `main` and `jit_sites` —
so existing callers and tests/test_warmup_registry.py work unchanged."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from actor_critic_tpu.analysis.warmup import (  # noqa: E402,F401
    collect_sites,
    jit_sites,
    main,
)

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
