#!/usr/bin/env bash
# TPU tunnel watcher (round 4): the axon tunnel has been dead since
# ~04:51 UTC 2026-07-30. Probe it every 10 min with bench.py's 60 s
# structured preflight; the moment a probe succeeds, capture the
# driver-contract bench evidence while the window lasts:
#   1. the headline bench line (with flops_per_step self-qualification)
#   2. bench/suite.py pallas per-op rows (kernel-engagement asserted)
#   3. bench/suite.py impala throughput at the learnable-pong settings
# then leave runs/TPU_ALIVE as a flag and exit so a human (or the
# driving session) can take over the tunnel for training runs.
set -u
cd "$(dirname "$0")/.."
mkdir -p runs

while true; do
  if ! pgrep -f "python bench.py" >/dev/null 2>&1; then
    timeout 240 python bench.py > runs/tpu_probe.json 2> runs/tpu_probe.err
    if ! grep -q '"error"' runs/tpu_probe.json && grep -q '"value"' runs/tpu_probe.json; then
      cp runs/tpu_probe.json runs/bench_tpu_green.json
      echo "$(date -u +%FT%TZ) tunnel ALIVE — capturing per-op rows" >> runs/tpu_watch.log
      timeout 900 python bench/suite.py pallas > runs/pallas_rows.json 2>> runs/tpu_watch.log
      timeout 600 python bench/suite.py impala > runs/impala_rows.json 2>> runs/tpu_watch.log
      date -u +%FT%TZ > runs/TPU_ALIVE
      # Round-4 addendum: with the short captures banked, spend the rest
      # of the window on the queued pong seed-1 curve (chunked dispatch:
      # ~25 min for the full 205M decisions; resumable if the window
      # closes first). stall-timeout generously above one chunk's wall
      # time per the --chunk watchdog contract.
      echo "$(date -u +%FT%TZ) launching pong seed-1 chunked run" >> runs/tpu_watch.log
      scripts/run_resumable.sh --preset impala_pong_learn --seed 1 \
        --iterations 160000 --chunk 20 --eval-every 1000 --log-every 100 \
        --ckpt-dir runs/pong_s1 --save-every 10000 --stall-timeout 300 \
        --metrics runs/impala_pong_learn_tpu_s1.jsonl --quiet \
        >> runs/tpu_watch.log 2>&1
      echo "$(date -u +%FT%TZ) pong seed-1 rc=$?" >> runs/tpu_watch.log
      exit 0
    fi
    echo "$(date -u +%FT%TZ) probe: dead" >> runs/tpu_watch.log
  fi
  sleep 600
done
