#!/usr/bin/env python
"""padsan: deterministic padding-lane poison sanitizer for the
shape-stabilization seams (ISSUE 20).

    python scripts/padsan.py                        # quick profile
    python scripts/padsan.py --schedules 64         # wider sweep
    python scripts/padsan.py --scenario pallas --revert no-slice
                                                    # reproduce a
                                                    # missing slice-back
                                                    # (exit 1)
    python scripts/padsan.py --scenario serving --revert unmasked-mean
                                                    # reverted masked
                                                    # summary (exit 1)
    python scripts/padsan.py --json                 # machine output

Each schedule runs a REAL steady-state program twice — pad lanes
zeroed vs poisoned (nan / ±3e38 / int8-saturating) — and asserts the
valid-lane outputs are BITWISE identical. Exit codes (scripts/tier1.sh
runs `--quick` between perfsan and the multihost smoke, under its own
timeout):
    0  clean: no pad seam leaked a single byte into a valid lane
    1  violation: a junk lane is observable — or a reverted mask/slice
       guard was detected (the sanitizer working)
    2  crash: unexpected error (a broken exerciser, not a detection)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[1].strip())
    p.add_argument(
        "--schedules", type=int, default=16,
        help="seeded poison schedules to sweep (default 16, the tier-1 "
        "quick profile: split across chunked/pallas/mixture/serving/"
        "device-plane)",
    )
    p.add_argument(
        "--seed0", type=int, default=0,
        help="first seed of the sweep (fixed seeds keep tier-1 "
        "deterministic; a violation names its seed for bit-identical "
        "replay)",
    )
    p.add_argument(
        "--scenario",
        choices=(
            "all", "chunked", "pallas", "mixture", "serving",
            "device-plane",
        ),
        default="all",
        help="which pad seam to exercise (default: the quick profile; "
        "'chunked' drives make_chunked_step's masked tail program, "
        "'pallas' the GAE/λ/V-trace kernels at ragged env batches, "
        "'mixture' the lax.switch fleet's parked members, 'serving' "
        "PolicyEngine.act's bucket backfill rows, 'device-plane' the "
        "ring slots outside the leased gather)",
    )
    p.add_argument(
        "--revert", choices=("unmasked-mean", "no-slice"), default=None,
        help="reverted-guard mode (expected exit 1): 'unmasked-mean' "
        "swaps the masked where-select summary for a plain mean (any "
        "scenario); 'no-slice' commits the full padded width instead "
        "of the valid slice (pallas, serving) — padsan must detect "
        "the junk lanes on every schedule",
    )
    p.add_argument(
        "--quick", action="store_true",
        help="alias for the default quick profile (the tier-1 entry "
        "point; explicit so the tier-1 line documents what it runs)",
    )
    p.add_argument("--json", action="store_true", help="machine output")
    args = p.parse_args(argv)

    from actor_critic_tpu.analysis import padsan

    if args.revert is not None:
        if args.scenario == "all":
            print(
                "padsan: error: --revert needs a single --scenario "
                "(the quick profile only sweeps the guarded modes)",
                file=sys.stderr,
            )
            return 2
        if args.revert not in padsan.SCENARIO_REVERTS[args.scenario]:
            print(
                f"padsan: error: scenario {args.scenario!r} supports "
                f"revert modes {padsan.SCENARIO_REVERTS[args.scenario]}"
                f", got {args.revert!r}",
                file=sys.stderr,
            )
            return 2

    try:
        if args.scenario == "all":
            out = padsan.quick_profile(
                schedules=args.schedules, seed0=args.seed0
            )
        else:
            exerciser = {
                "chunked": padsan.exercise_chunked,
                "pallas": padsan.exercise_pallas,
                "mixture": padsan.exercise_mixture,
                "serving": padsan.exercise_serving,
                "device-plane": padsan.exercise_device_plane,
            }[args.scenario]
            out = padsan.exercise_sweep(
                range(args.seed0, args.seed0 + args.schedules),
                lambda s: exerciser(s, revert=args.revert),
            )
    except padsan.PadSanError as e:
        # A detection names its seed: rerun that single seed to replay
        # the poison schedule bit-identically.
        print(f"padsan: VIOLATION DETECTED: {e}", file=sys.stderr)
        return 1
    except Exception as e:
        print(f"padsan: error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(out, indent=2, default=str))
    else:
        print(
            f"padsan: {out.get('schedules', 0)} poison schedule(s) "
            "clean — no pad lane leaked a byte into a valid-lane output"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
