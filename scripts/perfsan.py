#!/usr/bin/env python
"""perfsan: dispatch/transfer budget sanitizer for the steady-state
programs (ISSUE 15).

    python scripts/perfsan.py --quick              # tier-1 profile
    python scripts/perfsan.py --program ppo_update_device
    python scripts/perfsan.py --revert host-gather # pre-PR-13 host
                                                   # gather (exit 1)
    python scripts/perfsan.py --revert unfused     # split advantage
                                                   # dispatch (exit 1)
    python scripts/perfsan.py --revert uncommit    # uncommit-less swap
                                                   # (exit 1)
    python scripts/perfsan.py --json               # machine output
    python scripts/perfsan.py --json --out results/perfsan_actuals.json

Exit codes (scripts/tier1.sh runs --quick between numsan and pytest,
under its own timeout):
    0  clean: every steady-state program inside its committed
       perf_budgets.json budget (dispatches / transfers / transferred
       bytes / recompiles per block)
    1  violation: a program exceeded a budget — or a reverted mode's
       regression was detected (the sanitizer working)
    2  crash: missing/malformed manifest, unknown program, or a broken
       exerciser (not a detection)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[1].strip())
    p.add_argument(
        "--quick", action="store_true",
        help="run every steady-state program against the committed "
        "manifest (the tier-1 profile; also the default)",
    )
    p.add_argument(
        "--program", default=None,
        help="run ONE program (ppo_update_host / ppo_update_device / "
        "offpolicy_ingest / serving_dispatch / mixture_fleet_step)",
    )
    p.add_argument(
        "--revert", choices=("host-gather", "unfused", "uncommit"),
        default=None,
        help="reverted-regression mode (expected exit 1): re-introduce "
        "the pre-PR-13 per-block host gather, split the ISSUE-19 fused "
        "consume back into a separate advantage dispatch, or install a "
        "committed orbax restore into the gateway without "
        "checkpoint.uncommit — perfsan must catch any of them on every "
        "run",
    )
    p.add_argument(
        "--manifest", default=None,
        help="budget manifest (default: <repo>/perf_budgets.json)",
    )
    p.add_argument(
        "--seed", type=int, default=0,
        help="fixture seed (counters are structural — any seed "
        "measures the same budgets)",
    )
    p.add_argument("--json", action="store_true", help="machine output")
    p.add_argument(
        "--out", default=None,
        help="also write the JSON report to this path (what "
        "scripts/run_report.py renders as the budget-actuals table)",
    )
    args = p.parse_args(argv)

    from actor_critic_tpu.analysis import perfsan

    if args.revert and args.program:
        print(
            "perfsan: error: --revert and --program are exclusive",
            file=sys.stderr,
        )
        return 2

    try:
        if args.revert:
            perfsan.run_reverted(args.revert, args.manifest)
            print(
                f"perfsan: error: reverted mode {args.revert!r} was NOT "
                "detected — the meter is blind",
                file=sys.stderr,
            )
            return 2
        programs = perfsan.PROGRAMS
        if args.program:
            if args.program not in perfsan.PROGRAMS:
                print(
                    f"perfsan: error: unknown program {args.program!r} "
                    f"(have: {', '.join(perfsan.PROGRAMS)})",
                    file=sys.stderr,
                )
                return 2
            programs = (args.program,)
        out = perfsan.quick_profile(
            manifest_path=args.manifest, seed=args.seed,
            programs=programs,
        )
    except perfsan.ManifestError as e:
        print(f"perfsan: error: {e}", file=sys.stderr)
        return 2
    except perfsan.PerfSanError as e:
        print(f"perfsan: VIOLATION DETECTED: {e}", file=sys.stderr)
        return 1
    except Exception as e:
        print(
            f"perfsan: error: {type(e).__name__}: {e}", file=sys.stderr
        )
        return 2

    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        for name, entry in out["programs"].items():
            a = entry["actuals"]
            print(
                f"perfsan: {name}: {a['dispatches']} dispatch(es), "
                f"{a['transfers']} transfer(s), "
                f"{a['transferred_bytes']} B, "
                f"{a['recompiles']} recompile(s) per block — within "
                "budget"
            )
        print(
            f"perfsan: {len(out['programs'])} steady-state program(s) "
            "green against perf_budgets.json"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
