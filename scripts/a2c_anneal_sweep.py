"""A2C CartPole solve-gap sweep (VERDICT r3 missing #6 / next #4).

The flagship `a2c_cartpole` preset reaches greedy eval 465/458 — under
the 475 solve bar that PPO clears. This harness sweeps the anneal
schedule/rollout shape at CPU-calibration scale (E=256, the same shape
tests/test_a2c.py guards) and reports greedy eval at several points, so
the winning schedule can be promoted into the preset and re-certified at
E=4096.

Usage:
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/a2c_anneal_sweep.py \
        [--configs NAME ...] [--seeds 0 1 2] [--out results/a2c_sweep.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONFIGS: dict[str, dict] = {
    # The shipped preset's schedule, at calibration scale (baseline).
    "preset400": dict(iterations=400, anneal_iters=400),
    # Longer schedule: the 465-eval curve was still creeping at iter 400.
    "preset600": dict(iterations=600, anneal_iters=600),
    "preset800": dict(iterations=800, anneal_iters=800),
    # Longer rollouts: T=64 halves GAE truncation bias per update.
    "t64_400": dict(iterations=400, anneal_iters=400, rollout_steps=64),
    "t64_600": dict(iterations=600, anneal_iters=600, rollout_steps=64),
    # Keep a little entropy/lr at the end instead of full decay.
    "lrfloor600": dict(iterations=600, anneal_iters=600, lr_final=1e-4),
    # Tighter GAE (lower variance targets late in training).
    "lam90_600": dict(iterations=600, anneal_iters=600, gae_lambda=0.90),
    # E=4096 preset-scale grid: the E=256 winner (t64_400) ceilinged at
    # ~465 at E=4096/lr=1e-3 — a 16× batch at the same lr is underfit
    # per update, so scale lr (and optionally keep exploration alive
    # longer with a slower entropy anneal).
    "big_lr15": dict(iterations=400, anneal_iters=400, num_envs=4096,
                     rollout_steps=64, lr=1.5e-3),
    "big_lr2": dict(iterations=400, anneal_iters=400, num_envs=4096,
                    rollout_steps=64, lr=2e-3),
    "big_lr3": dict(iterations=400, anneal_iters=400, num_envs=4096,
                    rollout_steps=64, lr=3e-3),
    "big_lr2_t32": dict(iterations=400, anneal_iters=400, num_envs=4096,
                        rollout_steps=32, lr=2e-3),
    # Stabilizers for the lr=3e-3 winner's seed sensitivity (seed 2
    # oscillated 452->256->443->251 and never settled).
    "big_lr3_nadv": dict(iterations=400, anneal_iters=400, num_envs=4096,
                         rollout_steps=64, lr=3e-3, normalize_adv=True),
    "big_lr25": dict(iterations=400, anneal_iters=400, num_envs=4096,
                     rollout_steps=64, lr=2.5e-3),
    # normalize_adv collapsed to ~230 at this scale (it rescales the
    # advantage signal the big batch already denoises); try taming
    # lr=3e-3's oscillation with a tighter grad clip instead.
    "big_lr3_clip25": dict(iterations=400, anneal_iters=400, num_envs=4096,
                           rollout_steps=64, lr=3e-3, max_grad_norm=0.25),
}


def run_one(name: str, spec: dict, seed: int) -> dict:
    import dataclasses

    import jax

    from actor_critic_tpu.algos import a2c
    from actor_critic_tpu.envs import make_cartpole

    spec = dict(spec)
    iterations = spec.pop("iterations")
    base = dict(
        num_envs=256, rollout_steps=32, lr=1e-3, lr_final=0.0,
        entropy_coef=0.01, entropy_coef_final=0.0,
    )  # sweep default; configs override num_envs for preset-scale runs
    base.update(spec)
    cfg = a2c.A2CConfig(**base)
    env = make_cartpole()
    state = a2c.init_state(env, cfg, jax.random.key(seed))
    step = jax.jit(a2c.make_train_step(env, cfg), donate_argnums=0)
    eval_fn = jax.jit(a2c.make_eval_fn(env, cfg), static_argnums=(2, 3))
    ekey = jax.random.key(seed + 1)
    t0 = time.perf_counter()
    evals = {}
    checkpoints = sorted({iterations // 2, 3 * iterations // 4, iterations})
    it = 0
    for target in checkpoints:
        while it < target:
            state, m = step(state)
            it += 1
        ekey, sub = jax.random.split(ekey)
        evals[it] = round(float(eval_fn(state, sub, 64, 512)), 1)
    row = {
        "config": name, "seed": seed,
        "final_train_ema": round(float(m["avg_return_ema"]), 1),
        "evals": evals, "wall_s": round(time.perf_counter() - t0, 1),
        "cfg": {k: v for k, v in dataclasses.asdict(cfg).items()
                if not isinstance(v, tuple)},
    }
    print(json.dumps(row), flush=True)
    return row


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--configs", nargs="*", default=list(CONFIGS))
    p.add_argument("--seeds", nargs="*", type=int, default=[0])
    p.add_argument("--out", default="")
    args = p.parse_args()
    rows = [
        run_one(name, CONFIGS[name], seed)
        for name in args.configs
        for seed in args.seeds
    ]
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(rows, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
