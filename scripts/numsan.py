#!/usr/bin/env python
"""numsan: deterministic NaN/Inf/saturation fault sanitizer for the
training-serving stack (ISSUE 14).

    python scripts/numsan.py                       # quick profile
    python scripts/numsan.py --schedules 64        # wider sweep
    python scripts/numsan.py --scenario publish --revert
                                                   # reproduce a
                                                   # reverted publish
                                                   # gate (exit 1)
    python scripts/numsan.py --scenario checkpoint --revert
                                                   # reverted commit
                                                   # gate (exit 1)
    python scripts/numsan.py --scenario codec --revert
                                                   # pre-fix wrapping
                                                   # encoder (exit 1)
    python scripts/numsan.py --scenario bf16-update --revert
                                                   # reverted gates on
                                                   # the bf16 update's
                                                   # params (exit 1)
    python scripts/numsan.py --json                # machine output

Exit codes (scripts/tier1.sh runs the quick profile between fleetsan
and pytest, under its own timeout):
    0  clean: every poisoned schedule was blocked by its named guard
       (divergence event, checkpoint refusal, publish/mailbox/swap
       rejection, codec saturation) and no guard over-fired on the
       tolerated poisons
    1  violation: a poison crossed a guard — or a reverted-guard mode
       was detected (the sanitizer working)
    2  crash: unexpected error (a broken exerciser, not a detection)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[1].strip())
    p.add_argument(
        "--schedules", type=int, default=16,
        help="seeded fault schedules to sweep (default 16, the tier-1 "
        "quick profile: split across update/bf16-update/publish/"
        "checkpoint/codec)",
    )
    p.add_argument(
        "--seed0", type=int, default=0,
        help="first seed of the sweep (fixed seeds keep tier-1 "
        "deterministic; a violation names its seed for bit-identical "
        "replay)",
    )
    p.add_argument(
        "--scenario",
        choices=(
            "all", "update", "bf16-update", "publish", "checkpoint",
            "codec",
        ),
        default="all",
        help="which unit to exercise (default: the quick profile; "
        "'update' drives the real jitted PPO update + "
        "DivergenceMonitor, 'bf16-update' the bf16_compute update "
        "program against every publish/checkpoint/serve gate "
        "(ISSUE 19), 'publish' the PolicyPublisher/mailbox/"
        "PolicyStore gates, 'checkpoint' a real orbax commit, 'codec' "
        "the int8/f16 saturation contract)",
    )
    p.add_argument(
        "--revert", action="store_true",
        help="reverted-guard mode (expected exit 1): no-op the "
        "check_finite gates (publish/checkpoint/bf16-update) or run "
        "the pre-fix wrapping encoder (codec) — numsan must detect "
        "the leak on every schedule",
    )
    p.add_argument("--json", action="store_true", help="machine output")
    args = p.parse_args(argv)

    from actor_critic_tpu.analysis import numsan

    if args.revert and args.scenario in ("all", "update"):
        print(
            "numsan: error: --revert needs --scenario "
            "bf16-update|publish|checkpoint|codec (the update "
            "scenario's guard is the DivergenceMonitor itself)",
            file=sys.stderr,
        )
        return 2

    try:
        if args.scenario == "all":
            out = numsan.quick_profile(
                schedules=args.schedules, seed0=args.seed0
            )
        else:
            scenario = {
                "update": lambda s: numsan.exercise_update(s),
                "bf16-update": lambda s: numsan.exercise_bf16_update(
                    s, revert=args.revert
                ),
                "publish": lambda s: numsan.exercise_publish(
                    s, revert=args.revert
                ),
                "checkpoint": lambda s: numsan.exercise_checkpoint(
                    s, revert=args.revert
                ),
                "codec": lambda s: numsan.exercise_codec(
                    s, revert=args.revert
                ),
            }[args.scenario]
            out = numsan.exercise_sweep(
                range(args.seed0, args.seed0 + args.schedules), scenario
            )
    except numsan.NumSanError as e:
        # A detection names its seed: rerun that single seed to replay
        # the poison schedule bit-identically.
        print(f"numsan: VIOLATION DETECTED: {e}", file=sys.stderr)
        return 1
    except Exception as e:
        print(f"numsan: error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(out, indent=2, default=str))
    else:
        print(
            f"numsan: {out.get('schedules', 0)} fault schedule(s) "
            "clean — every poison blocked by its named guard"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
