#!/usr/bin/env bash
# CPU fallback for the round-4b queue: the tunnel died again ~06:03 UTC
# 2026-07-31 (DDPG run wedged at iter 5360; three watchdog/resume
# cycles confirmed dead). Result runs on XLA:CPU, sequential on the
# 1-core host, watchdog off (CPU cannot wedge).
#
# DDPG restarts FRESH rather than resuming the TPU leg: resuming its
# replay-free checkpoint put 500 iterations of updates against a thin
# refilled buffer and measurably degraded the restored actor (greedy
# eval 433 -> 138, q_mean 254 -> 404 overestimation spike) — exactly
# the documented cost of --no-save-replay resume semantics, fine for
# crash recovery, wrong for a first-measurement evidence row. Walker2d
# rings are ~160 MB so replay rides the checkpoint here; only the
# ~3 GB Humanoid ring warrants --no-save-replay.
set -u
cd "$(dirname "$0")/.."
export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu
mkdir -p runs results

echo "[q4c] DDPG Walker2d 1M fresh on CPU"
nice -n 5 scripts/run_resumable.sh --preset ddpg_walker2d \
  --ckpt-dir runs/ddpg_w2_cpu --save-every 2000 --eval-every 500 --eval-envs 16 \
  --metrics runs/ddpg_walker2d_run1_cpu.jsonl --seed 0 --quiet \
  > runs/ddpg_w2_cpu_stdout.log 2>&1
echo "[q4c] ddpg rc=$?"

echo "[q4c] TD3 Walker2d seed 1 on CPU"
# --fresh: these dirs were also named by the (wedged) round-4b TPU legs;
# an evidence run must never silently resume that foreign state
# (ADVICE.md round 4 #1 — run_resumable.sh refuses if a checkpoint exists).
nice -n 5 scripts/run_resumable.sh --preset td3_walker2d --fresh \
  --ckpt-dir runs/td3_w2_s1 --save-every 2000 --eval-every 500 --eval-envs 16 \
  --metrics runs/td3_walker2d_run3_seed1.jsonl --seed 1 --quiet \
  > runs/td3_w2_s1_stdout.log 2>&1
echo "[q4c] td3 rc=$?"

echo "[q4c] SAC Humanoid seed 1 on CPU"
nice -n 5 scripts/run_resumable.sh --preset sac_humanoid --fresh \
  --ckpt-dir runs/sac_hum_s1 --save-every 2000 --eval-every 500 --eval-envs 16 \
  --no-save-replay --metrics runs/sac_humanoid_run2_seed1.jsonl --seed 1 --quiet \
  > runs/sac_hum_s1_stdout.log 2>&1
echo "[q4c] sac rc=$?"
echo "[q4c] all done"
