"""Headline benchmark: A2C CartPole-v1 fused-trainer throughput.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "env-steps/sec/chip", "vs_baseline": N}

`vs_baseline` is relative to the BASELINE.json:5 north-star target of
1,000,000 env-steps/sec (the reference publishes no numbers of its own —
empty mount, SURVEY.md §0 / BASELINE.md).

Design: the entire rollout(T)×E + GAE + update is one jitted program, and
ITERS_PER_CALL iterations are scanned inside a single dispatch so the
host↔device (tunnel) latency is amortized away. Steps/sec counts actual
environment transitions: calls × iters × T × E.
"""

from __future__ import annotations

import json
import os
import time


def main() -> None:
    import jax

    from actor_critic_tpu.algos import a2c
    from actor_critic_tpu.envs import make_cartpole

    E = int(os.environ.get("BENCH_ENVS", 4096))
    T = int(os.environ.get("BENCH_ROLLOUT", 32))
    iters_per_call = int(os.environ.get("BENCH_ITERS_PER_CALL", 50))
    calls = int(os.environ.get("BENCH_CALLS", 5))

    env = make_cartpole()
    cfg = a2c.A2CConfig(num_envs=E, rollout_steps=T, lr=1e-3)
    state = a2c.init_state(env, cfg, jax.random.key(0))
    train_step = a2c.make_train_step(env, cfg)

    def run_block(state):
        def body(s, _):
            s, _m = train_step(s)
            return s, None

        s, _ = jax.lax.scan(body, state, None, length=iters_per_call)
        return s

    run_block_donating = jax.jit(run_block, donate_argnums=0)

    # Warm-up / compile.
    state = run_block_donating(state)
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    for _ in range(calls):
        state = run_block_donating(state)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    steps = calls * iters_per_call * T * E
    sps = steps / dt
    print(
        json.dumps(
            {
                "metric": "a2c_cartpole_fused_throughput",
                "value": round(sps, 1),
                "unit": "env-steps/sec/chip",
                "vs_baseline": round(sps / 1_000_000, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
