"""Headline benchmark: A2C CartPole-v1 fused-trainer throughput, plus a
CPU-measurable multi-metric record that survives a dead TPU tunnel.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "env-steps/sec/chip", "vs_baseline": N,
   "cpu_metrics": {"host_pool_scaling": {...}, "startup_to_first_step": {...},
                   "async_decoupling": {...}, "update_wall": {...}}}
or, when the headline cannot run (dead/held TPU tunnel, backend error):
  {"metric": ..., "value": 0.0, ..., "error": "...",
   "cpu_metrics": {...}}  (exit code 1)

`cpu_metrics` (ROADMAP "Bench resilience", ISSUE 6 satellite) is
measured on the disarmed CPU backend EVERY run — the TPU headline is an
optional layer on top, so a tunnel-dead round still lands real numbers
(each metric in its own subprocess with its own timeout; see
DEFAULT_CPU_METRICS / BENCH_CPU_METRICS / BENCH_CPU_METRIC_TIMEOUT).
Budget note for callers: the CPU block adds roughly 2-3 minutes on this
host on top of the preflight+bench ceiling documented in supervise().

`vs_baseline` is relative to the BASELINE.json:5 north-star target of
1,000,000 env-steps/sec (the reference publishes no numbers of its own —
empty mount, SURVEY.md §0 / BASELINE.md).

Robustness contract (VERDICT.md round 1, "What's weak" #1): the axon TPU
tunnel is single-client and can be dead or held by another process, in
which case backend initialization hangs *forever* — round 1's official
record was a 9-minute hang killed by the driver. So this script runs as a
two-process watchdog:

  parent (this file, default mode)
    ├─ preflight: `jax.devices()` in a subprocess, killed after
    │  BENCH_PREFLIGHT_TIMEOUT (default 75s) → fast {"error": ...} JSON
    │  when the tunnel is dead instead of a hang
    └─ child (`bench.py --child`): the real benchmark, killed after
       BENCH_TIMEOUT (default 600s) → {"error": ...} JSON if the tunnel
       dies mid-run

The child is a fresh process on purpose: earlier device allocations in the
same process depress later benchmark numbers (see bench/suite.py, which
subprocess-isolates every case for the same reason).

Design: the entire rollout(T)×E + GAE + update is one jitted program, and
ITERS_PER_CALL iterations are scanned inside a single dispatch so the
host↔device (tunnel) latency is amortized away. Steps/sec counts actual
environment transitions: calls × iters × T × E.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

METRIC = "a2c_cartpole_fused_throughput"
UNIT = "env-steps/sec/chip"
NORTH_STAR = 1_000_000.0


def _record_timestamp(rec: dict) -> float | None:
    """The capture timestamp recorded INSIDE a green-evidence JSON line:
    a positive numeric unix `ts`, else an ISO-8601 `captured_at`. None
    when the line carries neither (callers then fall back to file mtime
    — which for COMMITTED results is checkout time, not capture time,
    hence the in-record preference)."""
    import datetime

    ts = rec.get("ts")
    if isinstance(ts, (int, float)) and not isinstance(ts, bool) and ts > 0:
        return float(ts)
    cap = rec.get("captured_at")
    if isinstance(cap, str):
        try:
            return datetime.datetime.fromisoformat(
                cap.replace("Z", "+00:00")
            ).timestamp()
        except ValueError:
            pass
    return None


def _last_green(root: str | None = None) -> dict | None:
    """The most recent committed/captured green benchmark line, embedded in
    tunnel-dead error payloads so a red BENCH_r*.json is never evidence-free
    at the artifact the driver reads (VERDICT.md round 4, weak #1). Scans
    the watcher's capture (`runs/bench_tpu_green.json`) and the committed
    round evidence (`results/bench_tpu_green_r*.json`) for the newest
    parseable line with a real value; recency prefers a timestamp recorded
    in the line itself (`_record_timestamp`) over file mtime. `root`
    overrides the repo root (tests point it at a fixture tree)."""
    import glob
    import datetime

    here = root or os.path.dirname(os.path.abspath(__file__))
    candidates = glob.glob(os.path.join(here, "runs", "bench_tpu_green*.json"))
    candidates += glob.glob(os.path.join(here, "results", "bench_tpu_green*.json"))
    best = None
    for path in candidates:
        try:
            with open(path) as f:
                rec = json.loads(f.read().strip().splitlines()[-1])
            value = rec.get("value") if isinstance(rec, dict) else None
            # bool is excluded explicitly: JSON `true` is a Python bool,
            # which IS an int — `isinstance(True, (int, float))` passes
            # and `True > 0` holds, so a `{"value": true}` line would
            # otherwise masquerade as green evidence.
            if not (
                isinstance(value, (int, float))
                and not isinstance(value, bool)
                and value > 0
            ):
                continue
            ts = _record_timestamp(rec)
            if ts is None:
                ts = os.path.getmtime(path)
            if best is None or ts > best[0]:
                best = (ts, path, rec)
        except Exception:
            # One malformed evidence file must never crash the error-
            # reporting path (this runs precisely when the tunnel is
            # dead and the contract is ONE parseable JSON line).
            continue
    if best is None:
        return None
    ts, path, rec = best
    return {
        "value": rec["value"],
        "unit": rec.get("unit", UNIT),
        "vs_baseline": rec.get("vs_baseline"),
        "captured_at": datetime.datetime.fromtimestamp(
            ts, datetime.timezone.utc
        ).strftime("%Y-%m-%dT%H:%M:%SZ"),
        "evidence_path": os.path.relpath(path, here),
    }


def _error_record(msg: str, root: str | None = None) -> dict:
    record = {
        "metric": METRIC,
        "value": 0.0,
        "unit": UNIT,
        "vs_baseline": 0.0,
        "error": msg,
    }
    green = _last_green(root)
    if green is not None:
        record["last_green"] = green
    return record


def _error_line(msg: str, root: str | None = None) -> str:
    return json.dumps(_error_record(msg, root))


# CPU-runnable bench/suite.py metrics promoted into every bench.py
# record (ROADMAP "Bench resilience"; ISSUE 6 satellite, extended by
# ISSUE 8's replay_sample_throughput, ISSUE 9's multihost_scaling,
# ISSUE 10's serving_latency, ISSUE 11's scenario_fleet and ISSUE 13's
# consumed_env_steps_per_s data-plane A/B):
# the TPU headline stays on top when the tunnel is alive, but a dead
# tunnel no longer means an evidence-free round — host_pool_scaling,
# startup_to_first_step, async_decoupling, update_wall,
# replay_sample_throughput, multihost_scaling, serving_latency,
# serving_fleet_scaling (N gateway replicas behind the fleet proxy),
# scenario_fleet (heterogeneous mixture + the steps/s-vs-instance-count
# sweep) and consumed_env_steps_per_s (host vs device data plane) are
# measured on the CPU backend regardless. BENCH_CPU_METRICS overrides the set (comma
# list of bench/suite.py names); "0"/"none"/"off" disables. Trend the
# block across rounds with scripts/bench_trend.py. Budget note: the
# multihost grid adds ~2 minutes of multi-process cluster runs and the
# scenario_fleet mixture/sweep adds ~4-5 minutes (bounded by
# BENCH_FLEET_MAX_E) on top of the 2-3 minutes the rest of the block
# costs on this host — hence the 480 s default per-metric timeout.
DEFAULT_CPU_METRICS = (
    "host_pool_scaling,startup_to_first_step,async_decoupling,update_wall,"
    "fused_update_wall,replay_sample_throughput,multihost_scaling,"
    "serving_latency,serving_fleet_scaling,scenario_fleet,"
    "consumed_env_steps_per_s,pad_overhead"
)


def _cpu_metric_names() -> list[str]:
    raw = os.environ.get("BENCH_CPU_METRICS", "").strip()
    if raw.lower() in ("0", "none", "off"):
        return []
    if not raw:
        raw = DEFAULT_CPU_METRICS
    return [n for n in (s.strip() for s in raw.split(",")) if n]


def collect_cpu_metrics() -> dict:
    """{suite name: its JSON record (or {'error': ...})} for each
    configured CPU metric, each in its own subprocess (the suite's own
    isolation rationale) on the disarmed-CPU backend with a per-metric
    timeout — one wedged bench must not take the record down."""
    from __graft_entry__ import disarm_axon

    names = _cpu_metric_names()
    if not names:
        return {}
    suite = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench", "suite.py"
    )
    timeout_s = float(os.environ.get("BENCH_CPU_METRIC_TIMEOUT", 480))
    env = dict(os.environ)
    disarm_axon(env)
    out: dict = {}
    for name in names:
        try:
            proc = subprocess.run(
                [sys.executable, suite, name],
                capture_output=True, text=True, timeout=timeout_s, env=env,
            )
        except subprocess.TimeoutExpired:
            out[name] = {"error": f"exceeded {timeout_s:.0f}s"}
            continue
        lines = [
            ln for ln in (proc.stdout or "").strip().splitlines()
            if ln.startswith("{")
        ]
        if proc.returncode != 0 or not lines:
            tail = (proc.stderr or proc.stdout or "").strip().splitlines()
            out[name] = {
                "error": f"rc={proc.returncode}: "
                + (tail[-1] if tail else "no output")
            }
            continue
        try:
            out[name] = json.loads(lines[-1])
        except json.JSONDecodeError:
            out[name] = {"error": "unparseable JSON"}
    return out


def _with_cpu_metrics(record: dict) -> dict:
    """Attach the CPU multi-metric block; measurement failure must never
    break the one-parseable-JSON-line contract."""
    try:
        metrics = collect_cpu_metrics()
    except Exception as e:  # pragma: no cover - defensive
        metrics = {"error": str(e)[:200]}
    if metrics:
        record["cpu_metrics"] = metrics
    return record


def _allow_cpu() -> bool:
    # "0"/"false"/"no"/"" all mean OFF — raw truthiness would treat
    # BENCH_ALLOW_CPU=0 as enabled and defeat the honest-platform guard.
    return os.environ.get("BENCH_ALLOW_CPU", "").strip().lower() not in (
        "", "0", "false", "no",
    )


def _sub_env() -> dict:
    """Environment for bench subprocesses. With BENCH_ALLOW_CPU the axon
    site hook must be disarmed alongside JAX_PLATFORMS=cpu (shared
    `disarm_axon` helper — the cpu-without-disarm combination deadlocks
    a fresh interpreter inside the hook's plugin registration)."""
    env = dict(os.environ)
    if _allow_cpu():
        from __graft_entry__ import disarm_axon

        disarm_axon(env)
    return env


def _run_sub(code_or_args: list[str], timeout_s: float):
    """Run a python subprocess; returns (rc_or_None_on_timeout, stdout, stderr)."""
    try:
        proc = subprocess.run(
            [sys.executable, *code_or_args],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=_sub_env(),
        )
    except subprocess.TimeoutExpired as e:
        out = e.stdout or b""
        err = e.stderr or b""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        if isinstance(err, bytes):
            err = err.decode(errors="replace")
        return None, out, err
    return proc.returncode, proc.stdout, proc.stderr


def supervise() -> int:
    # Outer-timeout floor for callers: worst case is preflight + bench
    # ≈ 60 + 420 = 480s; any external kill budget must exceed that or the
    # watchdog can't emit its structured-error JSON first. (Round 1's TPU
    # bench completed in <2 min; 420s is generous headroom.)
    preflight_s = float(os.environ.get("BENCH_PREFLIGHT_TIMEOUT", 60))
    bench_s = float(os.environ.get("BENCH_TIMEOUT", 420))

    def emit_error(msg: str) -> int:
        # Even a dead-tunnel round lands a measured record: the CPU
        # multi-metric block rides every error line.
        print(json.dumps(_with_cpu_metrics(_error_record(msg))))
        return 1

    rc, out, err = _run_sub(
        ["-c", "import jax; print('platform:', jax.devices()[0].platform)"],
        preflight_s,
    )
    if rc is None:
        return emit_error(
            f"backend preflight exceeded {preflight_s:.0f}s — TPU tunnel "
            "dead or held by another process; no benchmark run"
        )
    if rc != 0:
        tail = (err or out).strip().splitlines()
        return emit_error(
            "backend preflight failed: " + (tail[-1] if tail else f"rc={rc}")
        )
    platform = next(
        (
            ln.split("platform:", 1)[1].strip()
            for ln in out.splitlines()
            if "platform:" in ln
        ),
        "unknown",
    )
    if platform not in ("axon", "tpu") and not _allow_cpu():
        # Refuse to pass a CPU fallback off as a per-chip TPU number
        # (VERDICT.md round-1 weakness #2: the perf story must be honest).
        return emit_error(
            f"backend resolved to {platform!r}, not a TPU — set "
            "BENCH_ALLOW_CPU=1 to benchmark it anyway"
        )

    rc, out, err = _run_sub([os.path.abspath(__file__), "--child"], bench_s)
    if rc is None:
        return emit_error(
            f"benchmark exceeded {bench_s:.0f}s (preflight had passed — "
            "tunnel died or was claimed mid-run)"
        )
    lines = [ln for ln in out.strip().splitlines() if ln.startswith("{")]
    if rc != 0 or not lines:
        tail = (err or out).strip().splitlines()
        return emit_error(
            f"benchmark child rc={rc}: " + (tail[-1] if tail else "no output")
        )
    try:
        record = json.loads(lines[-1])
    except json.JSONDecodeError:
        return emit_error("benchmark child emitted unparseable JSON")
    # Re-check the platform the child ACTUALLY ran on: a tunnel that dies
    # between preflight and child can silently fall back to CPU, and a CPU
    # number must never pass as a per-chip TPU figure.
    child_platform = record.get("platform", "unknown")
    if child_platform not in ("axon", "tpu") and not _allow_cpu():
        return emit_error(
            f"benchmark ran on {child_platform!r}, not a TPU (backend "
            "changed after preflight) — set BENCH_ALLOW_CPU=1 to accept"
        )
    print(json.dumps(_with_cpu_metrics(record)))
    return 0


def main() -> None:
    import jax

    from actor_critic_tpu.algos import a2c
    from actor_critic_tpu.envs import make_cartpole

    E = int(os.environ.get("BENCH_ENVS", 4096))
    T = int(os.environ.get("BENCH_ROLLOUT", 32))
    iters_per_call = int(os.environ.get("BENCH_ITERS_PER_CALL", 50))
    calls = int(os.environ.get("BENCH_CALLS", 5))

    env = make_cartpole()
    cfg = a2c.A2CConfig(num_envs=E, rollout_steps=T, lr=1e-3)
    state = a2c.init_state(env, cfg, jax.random.key(0))
    train_step = a2c.make_train_step(env, cfg)

    def run_block(state):
        def body(s, _):
            s, _m = train_step(s)
            return s, None

        s, _ = jax.lax.scan(body, state, None, length=iters_per_call)
        return s

    run_block_donating = jax.jit(run_block, donate_argnums=0)

    # Warm-up / compile.
    state = run_block_donating(state)
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    for _ in range(calls):
        state = run_block_donating(state)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    steps = calls * iters_per_call * T * E
    sps = steps / dt

    # FLOPs sanity line (round-2 verdict weak #1): per-env-step compute is
    # 5 forward-equivalents of the ACTUAL bench network (rollout fwd = 1,
    # update fwd+bwd ≈ 3, truncation final-obs values fwd = 1) at
    # 2·Σ(in·out) FLOPs each — derived from cfg/env so the emitted model
    # can never silently drift from what ran. The implied sustained-FLOPs
    # figure lets a reader check the number against real silicon: a v5e
    # peaks at ~197 TFLOP/s (bf16); an implied figure far above that means
    # the axon device's wall-times must be read longitudinally, not as
    # v5e silicon.
    dims = (env.spec.obs_shape[0], *cfg.hidden)
    fwd_flops = 2 * sum(a * b for a, b in zip(dims, dims[1:]))
    fwd_flops += 2 * cfg.hidden[-1] * (env.spec.action_dim + 1)
    flops_per_step = 5 * fwd_flops
    implied_tflops = sps * flops_per_step / 1e12
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": round(sps, 1),
                "unit": UNIT,
                "vs_baseline": round(sps / NORTH_STAR, 4),
                "platform": jax.default_backend(),
                "flops_per_step": flops_per_step,
                "implied_tflops": round(implied_tflops, 1),
                "v5e_peak_bf16_tflops": 197,
                "implied_over_v5e_peak": round(implied_tflops / 197, 2),
            }
        )
    )


if __name__ == "__main__":
    if "--child" in sys.argv[1:]:
        main()
    else:
        sys.exit(supervise())
