"""Per-config benchmark suite (BASELINE.json:7-11; BASELINE.md).

Each bench prints one JSON line {"metric", "value", "unit", ...}. The
headline A2C number is also what repo-root bench.py reports for the
driver. Usage:

    python bench/suite.py            # all throughput benches
    python bench/suite.py a2c impala # subset

Throughput benches fuse many train iterations per dispatch (lax.scan) so
the host<->device tunnel latency is amortized; host-env benches measure
the real host-stepping path (the wall-clock-limiting one on this 1-core
host, SURVEY.md §7.0).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _fused_steps_per_sec(mod, env, cfg, steps_per_iter, iters_per_call=20, calls=5):
    state = mod.init_state(env, cfg, jax.random.key(0))
    train_step = mod.make_train_step(env, cfg)

    def block(s):
        def body(c, _):
            c, _m = train_step(c)
            return c, None

        s, _ = jax.lax.scan(body, s, None, length=iters_per_call)
        return s

    run = jax.jit(block, donate_argnums=0)
    state = run(state)
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for _ in range(calls):
        state = run(state)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0
    return calls * iters_per_call * steps_per_iter / dt


def _xla_flops_per_iter(mod, env, cfg):
    """Exact per-iteration FLOPs of the fused train step, from XLA's own
    cost model (`Compiled.cost_analysis()['flops']`) on the program that
    actually runs — no hand conv arithmetic to drift out of date
    (VERDICT round 4, missing #5: throughput rows must carry enough
    FLOPs accounting to be believed or disbelieved on sight). Returns
    None when the backend exposes no cost analysis."""
    state = mod.init_state(env, cfg, jax.random.key(0))
    try:
        compiled = jax.jit(mod.make_train_step(env, cfg)).lower(state).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


# v5e bf16 peak, same reference figure the headline A2C qualification
# uses (BASELINE.md FLOPs-sanity note). These programs run float32, whose
# silicon peak is lower — so implied_mfu computed against the bf16 peak
# is a LOWER bound on implausibility: mfu >> 1 is impossible either way.
V5E_PEAK_BF16_TFLOPS = 197.0


def bench_a2c():
    from actor_critic_tpu.algos import a2c
    from actor_critic_tpu.envs import make_cartpole

    cfg = a2c.A2CConfig(num_envs=4096, rollout_steps=32)
    sps = _fused_steps_per_sec(
        a2c, make_cartpole(), cfg, cfg.num_envs * cfg.rollout_steps,
        iters_per_call=50,
    )
    return {
        "metric": "a2c_cartpole_fused_throughput",
        "value": round(sps, 1),
        "unit": "env-steps/sec/chip",
        "vs_baseline": round(sps / 1_000_000, 4),
    }


def bench_ppo():
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.envs import make_cartpole

    cfg = ppo.PPOConfig(num_envs=2048, rollout_steps=32)
    sps = _fused_steps_per_sec(
        ppo, make_cartpole(), cfg, cfg.num_envs * cfg.rollout_steps,
        iters_per_call=10,
    )
    return {
        "metric": "ppo_cartpole_fused_throughput",
        "value": round(sps, 1),
        "unit": "env-steps/sec/chip",
    }


def bench_impala():
    # Measured at the `impala_pong_learn` preset's exact settings
    # (opp_skill=0.5, frame_skip=4, 36px, E=64 T=20 — the config that
    # demonstrably learns; BASELINE.json:11), so the throughput row and
    # the learning curve describe the same program. One agent decision
    # drives frame_skip=4 physics frames.
    from actor_critic_tpu.algos import impala
    from actor_critic_tpu.config import PRESETS
    from actor_critic_tpu.envs import make_pong

    preset = PRESETS["impala_pong_learn"]
    cfg = preset.config
    env = make_pong(**preset.env_kwargs)
    sps = _fused_steps_per_sec(
        impala, env, cfg, cfg.num_envs * cfg.rollout_steps,
        iters_per_call=10, calls=3,
    )
    out = {
        # Renamed from impala_jaxpong_fused_throughput (which measured
        # default pong at E=64 T=32 in env-steps): same key would make
        # cross-round trackers compare different quantities.
        "metric": "impala_pong_learn_fused_throughput",
        "value": round(sps, 1),
        "unit": "agent-decisions/sec/chip "
                f"(x{preset.env_kwargs['frame_skip']} physics frames)",
        "config": {"num_envs": cfg.num_envs,
                   "rollout_steps": cfg.rollout_steps,
                   **preset.env_kwargs},
    }
    # Self-qualification: real conv FLOPs make this the one TPU
    # throughput row a skeptic can sanity-check. flops_per_decision
    # covers the WHOLE iteration (rollout fwd + env physics + V-trace +
    # learner fwd/bwd) straight from XLA's cost model.
    flops_iter = _xla_flops_per_iter(impala, env, cfg)
    if flops_iter is not None:
        per_decision = flops_iter / (cfg.num_envs * cfg.rollout_steps)
        implied_tflops = sps * per_decision / 1e12
        out.update(
            flops_per_decision=round(per_decision),
            implied_tflops=round(implied_tflops, 3),
            v5e_peak_bf16_tflops=V5E_PEAK_BF16_TFLOPS,
            implied_mfu=round(implied_tflops / V5E_PEAK_BF16_TFLOPS, 4),
        )
    return out


def bench_sac_updates():
    """Off-policy update throughput: HBM replay sample + twin-Q/actor/alpha
    update, batch 256 (the device-side hot path of BASELINE.json:10)."""
    from actor_critic_tpu.algos import sac
    from actor_critic_tpu.envs import make_point_mass

    env = make_point_mass()
    cfg = sac.SACConfig(num_envs=32, steps_per_iter=4, batch_size=256)
    sps = _fused_steps_per_sec(
        sac, env, cfg, cfg.num_envs * cfg.steps_per_iter, iters_per_call=20
    )
    # steps/sec of the fused collect+update iteration; updates/sec is the
    # same rate divided by steps-per-iter.
    return {
        "metric": "sac_fused_env_steps",
        "value": round(sps, 1),
        "unit": "env-steps/sec/chip",
        "updates_per_sec": round(sps / (cfg.num_envs * cfg.steps_per_iter), 1),
    }


def bench_ddpg_updates():
    from actor_critic_tpu.algos import ddpg
    from actor_critic_tpu.envs import make_point_mass

    env = make_point_mass()
    cfg = ddpg.DDPGConfig(num_envs=32, steps_per_iter=4, batch_size=256)
    sps = _fused_steps_per_sec(
        ddpg, env, cfg, cfg.num_envs * cfg.steps_per_iter, iters_per_call=20
    )
    return {
        "metric": "ddpg_fused_env_steps",
        "value": round(sps, 1),
        "unit": "env-steps/sec/chip",
        "updates_per_sec": round(sps / (cfg.num_envs * cfg.steps_per_iter), 1),
    }


def bench_host_native():
    from actor_critic_tpu.envs.host_pool import HostEnvPool

    E, T = 256, 300
    out = {}
    for backend in ("native", "gym"):
        pool = HostEnvPool("CartPole-v1", E, backend=backend,
                           normalize_obs=False, normalize_reward=False)
        pool.reset()
        acts = np.zeros(E, np.int64)
        pool.step(acts)
        t0 = time.perf_counter()
        for _ in range(T):
            pool.step(acts)
        out[backend] = E * T / (time.perf_counter() - t0)
    return {
        "metric": "host_env_stepping",
        "value": round(out["native"], 1),
        "unit": "env-steps/sec (native C++)",
        "gym_baseline": round(out["gym"], 1),
        "speedup": round(out["native"] / out["gym"], 1),
    }


def bench_pallas_ops():
    """Per-op evidence for the Pallas scan kernels (round-2 verdict #5):
    time the lax.scan reference (`ops.returns`) against the Pallas
    kernels (`ops.pallas_scan`) under identical jit + block_until_ready
    fencing. The headline metric/value is the LONG-T V-trace speedup;
    the GAE pair and the short (headline-trainer) shape ride along in
    the extra fields. Every per-shape record carries the kernel tile
    each op would use (`*_kernel_block`, via pallas_scan.kernel_block) —
    0 there means the Pallas call silently fell back to lax.scan, and a
    'speedup' would be measurement noise, not kernel evidence."""
    from actor_critic_tpu.ops import pallas_scan, returns

    def timeit(fn, *args, reps=50):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps

    def shape_case(T, E):
        rng = np.random.default_rng(0)
        r = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
        d = jnp.asarray(rng.random((T, E)) < 0.02, jnp.float32)
        b = jnp.asarray(rng.normal(size=(E,)), jnp.float32)
        tlp = jnp.asarray(rng.normal(size=(T, E)) * 0.3, jnp.float32)
        blp = jnp.asarray(rng.normal(size=(T, E)) * 0.3, jnp.float32)

        gae_scan = jax.jit(lambda *a: returns.gae(*a, 0.99, 0.95))
        gae_pl = jax.jit(lambda *a: pallas_scan.gae(*a, 0.99, 0.95))
        vt_scan = jax.jit(lambda *a: returns.vtrace(*a, 0.99))
        vt_pl = jax.jit(lambda *a: pallas_scan.vtrace(*a, 0.99))
        return {
            "gae_kernel_block": pallas_scan.kernel_block("gae", T, E),
            "vtrace_kernel_block": pallas_scan.kernel_block("vtrace", T, E),
            "gae_scan_us": round(timeit(gae_scan, r, v, d, b) * 1e6, 1),
            "gae_pallas_us": round(timeit(gae_pl, r, v, d, b) * 1e6, 1),
            "vtrace_scan_us": round(timeit(vt_scan, tlp, blp, r, v, d, b) * 1e6, 1),
            "vtrace_pallas_us": round(timeit(vt_pl, tlp, blp, r, v, d, b) * 1e6, 1),
        }

    # Headline bench shape (T=32): both implementations sit at dispatch
    # latency — the Pallas win there is the FUSED trainer's elimination
    # of T sequential scan steps, not this isolated op. Long-T (the
    # IMPALA/seqpar regime) is where the per-op gap can show; T=1024 is
    # the longest T where the 11-array V-trace kernel still fits a
    # 128-lane tile in VMEM (kernel_block > 0 — larger T falls back).
    short = shape_case(32, 4096)
    long = shape_case(1024, 256)
    assert long["vtrace_kernel_block"] > 0, "vtrace kernel must engage"
    assert long["gae_kernel_block"] > 0, "gae kernel must engage"
    return {
        "metric": "pallas_vtrace_speedup_longT",
        "value": round(long["vtrace_scan_us"] / long["vtrace_pallas_us"], 2),
        "unit": "x over lax.scan (T=1024, E=256)",
        "T32_E4096": short,
        "T1024_E256": long,
        "gae_speedup_longT": round(
            long["gae_scan_us"] / long["gae_pallas_us"], 2
        ),
    }


def bench_host_pool_scaling():
    """Sharded host-pool scaling (ISSUE 2 acceptance row): steps/s of the
    SAME pool at workers ∈ {1, 2, 4} on the sleep-padded testbed env
    (envs/sleep_pad.py). The 10 ms/step sleep models a simulator bound by
    per-env WALL time (MuJoCo-shaped), not CPU, so worker overlap is
    measurable in CI on a single-core host with no TPU tunnel — and it is
    long enough that sleep() timer slack and IPC costs (measured ~1 ms/env
    and ~5 ms/batch-step here) don't mask the overlap. The headline value
    is the workers=4 speedup over workers=1 (target >= 2x).
    """
    from actor_critic_tpu.envs.host_pool import HostEnvPool
    from actor_critic_tpu.envs.sleep_pad import QUALIFIED_ENV_ID

    E, T, sleep_s = 8, 30, 0.010
    rates = {}
    for W in (1, 2, 4):
        pool = HostEnvPool(
            QUALIFIED_ENV_ID, E, seed=0, workers=W,
            normalize_obs=False, normalize_reward=False,
            env_kwargs={"sleep_s": sleep_s},
        )
        pool.reset()
        acts = np.zeros(E, np.int64)
        pool.step(acts)  # warm the worker pipes / first-step costs
        t0 = time.perf_counter()
        for _ in range(T):
            pool.step(acts)
        rates[W] = E * T / (time.perf_counter() - t0)
        pool.close()
    return {
        "metric": "host_pool_scaling",
        "value": round(rates[4] / rates[1], 2),
        "unit": "x steps/s at workers=4 vs workers=1 (sleep-padded testbed)",
        "steps_per_s": {f"workers={w}": round(r, 1) for w, r in rates.items()},
        "speedup_w2": round(rates[2] / rates[1], 2),
        "config": {"num_envs": E, "steps": T, "sleep_s": sleep_s},
    }


def bench_async_decoupling():
    """Lockstep vs async actor–learner PPO under ONE sleep-padded
    straggler worker (ISSUE 6 acceptance row), on the CartPole/sleep_pad
    testbed (`envs/sleep_pad.py SleepPadCartPole-v0` — real CartPole
    dynamics, wall-padded steps).

    Lockstep: one sharded pool, worker 0's shard padded — every
    collection block waits for the straggler at the shard barrier, and
    every SGD step waits for collection. Async: the SAME env fleet
    partitioned per actor (actor 0 = the padded half), a bounded
    trajectory queue, V-trace-corrected learner. Both modes consume the
    same total env-steps (async runs 2x blocks at half width) and
    finish with a greedy eval, so the speedup is at comparable final
    return. The headline value is async/lockstep consumed env-steps/s
    (target >= 1.5x)."""
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.algos.host_loop import host_evaluate
    from actor_critic_tpu.envs.host_pool import HostEnvPool
    from actor_critic_tpu.envs.sleep_pad import QUALIFIED_CARTPOLE_ID
    from actor_critic_tpu.models import host_actor

    E, K, iters, pad = 8, 32, 60, 0.002
    cfg = ppo.PPOConfig(
        num_envs=E, rollout_steps=K, epochs=4, num_minibatches=4,
        lr=3e-3, hidden=(64, 64), entropy_coef=0.001,
    )

    def greedy_eval(spec, params, pool):
        greedy = host_actor.make_ppo_host_greedy(spec, cfg)
        np_params = jax.device_get(params)
        try:
            return host_evaluate(
                pool, lambda o: np.asarray(greedy(np_params, o)),
                max_steps=520,
            )
        finally:
            pool.close()

    # Lockstep: straggler worker 0 pads E/2 envs; the shard barrier
    # drags the whole batch to its pace.
    pool = HostEnvPool(
        QUALIFIED_CARTPOLE_ID, E, seed=0, workers=2,
        worker_env_kwargs=[{"sleep_s": pad}, None],
    )
    t0 = time.perf_counter()
    params, _, _ = ppo.train_host(
        pool, cfg, num_iterations=iters, seed=0, log_every=0
    )
    lock_wall = time.perf_counter() - t0
    lock_eval = greedy_eval(pool.spec, params, pool.eval_pool(8))
    pool.close()
    lock_sps = iters * K * E / lock_wall

    # Async: same fleet split per actor; the padded actor slows only
    # its own contribution. 2x blocks at E/2 = equal consumed steps.
    pools = [
        HostEnvPool(
            QUALIFIED_CARTPOLE_ID, E // 2, seed=0,
            env_kwargs={"sleep_s": pad},
        ),
        HostEnvPool(QUALIFIED_CARTPOLE_ID, E // 2, seed=100003),
    ]
    t0 = time.perf_counter()
    params, _, _ = ppo.train_host_async(
        pools, cfg, iters * 2, seed=0, log_every=0,
        updates_per_block=1, queue_depth=4, max_staleness=8,
        correction="vtrace",
    )
    async_wall = time.perf_counter() - t0
    async_eval = greedy_eval(pools[1].spec, params, pools[1].eval_pool(8))
    for p in pools:
        p.close()
    async_sps = iters * 2 * K * (E // 2) / async_wall
    return {
        "metric": "async_decoupling_speedup",
        "value": round(async_sps / lock_sps, 2),
        "unit": "x consumed env-steps/s, async vs lockstep, one "
                "sleep-padded straggler worker (equal consumed steps)",
        "lockstep": {
            "steps_per_s": round(lock_sps, 1),
            "wall_s": round(lock_wall, 2),
            "eval_return": round(float(lock_eval), 1),
        },
        "async": {
            "steps_per_s": round(async_sps, 1),
            "wall_s": round(async_wall, 2),
            "eval_return": round(float(async_eval), 1),
        },
        "config": {
            "num_envs": E, "rollout_steps": K, "iterations": iters,
            "sleep_s": pad, "correction": "vtrace",
        },
    }


def bench_update_wall():
    """Steady-state learner update wall at the host-PPO hot shape: the
    plain lockstep update program and the V-trace-corrected async one
    on an identical [K, E] CartPole-shaped block (epochs x minibatches
    in-jit), each timed with a block_until_ready fence — the
    denominator of every updates/s claim, and the corrected program's
    overhead made visible (ROADMAP 'Bench resilience': a CPU-measurable
    multi-metric record every round)."""
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.envs.jax_env import EnvSpec

    spec = EnvSpec(
        obs_shape=(4,), action_dim=2, discrete=True,
        obs_dtype=np.float32, can_truncate=True,
    )
    cfg = ppo.PPOConfig(
        num_envs=8, rollout_steps=64, epochs=4, num_minibatches=4,
        hidden=(64, 64),
    )
    T, E = cfg.rollout_steps, cfg.num_envs
    rng = np.random.default_rng(0)
    key = jax.random.key(0)
    params, opt_state = ppo.init_host_params(spec, cfg, key)
    obs = jnp.asarray(rng.normal(size=(T, E, 4)), jnp.float32)
    last_obs = jnp.asarray(rng.normal(size=(E, 4)), jnp.float32)
    args = dict(
        action=jnp.asarray(rng.integers(0, 2, (T, E))),
        log_prob=jnp.asarray(rng.normal(size=(T, E)) * 0.1 - 0.69, jnp.float32),
        value=jnp.asarray(rng.normal(size=(T, E)), jnp.float32),
        reward=jnp.ones((T, E), jnp.float32),
        done=jnp.zeros((T, E), jnp.float32),
        terminated=jnp.zeros((T, E), jnp.float32),
    )

    def timeit(update, reps=20):
        out = update(
            params, opt_state, obs, args["action"], args["log_prob"],
            args["value"], args["reward"], args["done"],
            args["terminated"], obs, last_obs, key,
        )
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = update(
                params, opt_state, obs, args["action"], args["log_prob"],
                args["value"], args["reward"], args["done"],
                args["terminated"], obs, last_obs, key,
            )
            jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps

    plain_update = ppo.make_host_update_step(spec, cfg)
    plain_s = timeit(plain_update)

    # Guard-overhead measurement (ISSUE 14 satellite): the SAME compiled
    # update plus the numguard finite-gate sweep over the updated params
    # — what a per-update gate costs. The async drivers DO pay a gate on
    # this cadence (PolicyPublisher.publish runs check_finite once per
    # published update); the checkpoint gate runs on the save cadence.
    # This row prices the per-update sweep directly so the overhead is
    # a measured number, not a guess. Trended as `update_wall.guarded_ms`.
    from actor_critic_tpu.utils import numguard

    def guarded_update(*args):
        out = plain_update(*args)
        numguard.check_finite(
            jax.device_get(out[0]), "bench finite-gate", name="params"
        )
        return out

    guarded_s = timeit(guarded_update)

    vtrace_s = timeit(
        ppo.make_async_update_step(spec, cfg, correction="vtrace")
    )

    # Device-data-plane re-measurement (ISSUE 13): the same V-trace
    # update with the block gathered + decoded from the HBM trajectory
    # ring INSIDE the program — the gather/decode prefix is the only
    # delta, so this wall is the honest denominator of the device
    # plane's updates/s (and its overhead vs the argument-fed program
    # is the in-jit staging cost).
    from actor_critic_tpu.data_plane import ring as dp_ring

    block_spec = ppo.async_block_spec(spec, cfg, 1, "vtrace")
    ring = dp_ring.DeviceTrajRing(
        depth=2, block_spec=block_spec, codec="fp32",
        register_gauge=False,
    )
    block = {
        "obs": np.asarray(obs), "action": np.asarray(args["action"]),
        "log_prob": np.asarray(args["log_prob"]),
        "value": np.asarray(args["value"]),
        "reward": np.asarray(args["reward"]),
        "done": np.asarray(args["done"]),
        "terminated": np.asarray(args["terminated"]),
        "final_obs": np.asarray(obs), "last_obs": np.asarray(last_obs),
    }
    ring.put(block, version=0)
    lease = ring.get(timeout=1.0)
    dev_update = ppo.make_device_update_step(
        spec, cfg, ring.codecs, correction="vtrace"
    )
    slot = np.int32(lease.slot)

    def dev_call():
        return ring.run(
            lambda state: dev_update(params, opt_state, state, slot, key)
        )

    out = dev_call()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    reps = 20
    for _ in range(reps):
        out = dev_call()
        jax.block_until_ready(out)
    device_s = (time.perf_counter() - t0) / reps

    # Budget-counter actuals (ISSUE 15): the SAME dispatch/transfer
    # meters perfsan gates tier-1 with, read on one fenced dispatch of
    # each program — so the wall rows above travel with the structural
    # counts that explain them (plain: 1 program, 0 transfers — the
    # args are device-resident; device-gather: 1 program, the staged
    # slot scalar's 4 bytes).
    from actor_critic_tpu.analysis import perfsan as _perfsan

    with _perfsan.measure() as c_plain:
        out = plain_update(
            params, opt_state, obs, args["action"], args["log_prob"],
            args["value"], args["reward"], args["done"],
            args["terminated"], obs, last_obs, key,
        )
        jax.block_until_ready(out)
    # Warm the staged-slot signature first: the meter reads the C++
    # fastpath's post_hook, which only fires on cache-hit dispatches —
    # a cold signature would read as zero dispatches.
    slot_dev = jax.device_put(np.int32(lease.slot))
    out = ring.run(
        lambda state: dev_update(params, opt_state, state, slot_dev, key)
    )
    jax.block_until_ready(out)
    with _perfsan.measure() as c_dev:
        slot_dev = jax.device_put(np.int32(lease.slot))
        out = ring.run(
            lambda state: dev_update(params, opt_state, state, slot_dev, key)
        )
        jax.block_until_ready(out)
    ring.release(lease)
    ring.close()

    return {
        "metric": "steady_state_update_wall",
        "value": round(plain_s * 1e3, 2),
        "dispatches_per_block": c_plain.dispatches,
        "transferred_bytes_per_block": c_plain.transferred_bytes,
        "device_dispatches_per_block": c_dev.dispatches,
        "device_transferred_bytes_per_block": c_dev.transferred_bytes,
        "unit": "ms per host-PPO update ([64, 8] block, 4 epochs x 4 "
                "minibatches, fenced)",
        "updates_per_s": round(1.0 / plain_s, 1),
        "guarded_ms": round(guarded_s * 1e3, 2),
        "guard_overhead_x": round(guarded_s / plain_s, 2),
        "vtrace_corrected_ms": round(vtrace_s * 1e3, 2),
        "vtrace_overhead_x": round(vtrace_s / plain_s, 2),
        "device_plane_ms": round(device_s * 1e3, 2),
        "device_gather_overhead_x": round(device_s / vtrace_s, 2),
    }


def bench_fused_update_wall():
    """ISSUE 19: the fused consume wall — gather + decode + advantages
    (the `common.gae_targets` seam lowering through ops/pallas_scan) +
    update as ONE device-plane program under `correction="none"` —
    against the same consume with the advantage scan split into its own
    dispatch (the pre-fusion two-program shape, perfsan's `--revert
    unfused`), plus the bf16-vs-fp32 host update walls behind
    `train.py --update-dtype`. CPU numbers run the lax fallback /
    interpret path (the *_auto contract); the TPU re-measure is the
    results/pallas_rows_tpu rider."""
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.algos.common import gae_targets
    from actor_critic_tpu.analysis import perfsan as _perfsan
    from actor_critic_tpu.data_plane import ring as dp_ring
    from actor_critic_tpu.envs.jax_env import EnvSpec

    spec = EnvSpec(
        obs_shape=(4,), action_dim=2, discrete=True,
        obs_dtype=np.float32, can_truncate=True,
    )
    cfg = ppo.PPOConfig(
        num_envs=8, rollout_steps=64, epochs=4, num_minibatches=4,
        hidden=(64, 64),
    )
    T, E = cfg.rollout_steps, cfg.num_envs
    rng = np.random.default_rng(0)
    key = jax.random.key(0)
    params, opt_state = ppo.init_host_params(spec, cfg, key)
    obs = np.asarray(rng.normal(size=(T, E, 4)), np.float32)
    block = {
        "obs": obs,
        "action": rng.integers(0, 2, (T, E)),
        "log_prob": np.asarray(rng.normal(size=(T, E)) * 0.1 - 0.69,
                               np.float32),
        "value": np.asarray(rng.normal(size=(T, E)), np.float32),
        "reward": np.ones((T, E), np.float32),
        "done": np.zeros((T, E), np.float32),
        "terminated": np.zeros((T, E), np.float32),
        "final_obs": obs.copy(),
        "last_obs": np.asarray(rng.normal(size=(E, 4)), np.float32),
        "final_values": np.asarray(rng.normal(size=(T, E)), np.float32),
        "bootstrap_value": np.asarray(rng.normal(size=(E,)), np.float32),
    }

    block_spec = ppo.async_block_spec(spec, cfg, 1, "none")
    ring = dp_ring.DeviceTrajRing(
        depth=2, block_spec=block_spec, codec="fp32",
        register_gauge=False,
    )
    ring.put(block, version=0)
    lease = ring.get(timeout=1.0)
    dev_update = ppo.make_device_update_step(
        spec, cfg, ring.codecs, correction="none"
    )
    slot = np.int32(lease.slot)

    @jax.jit
    def advantages_only(state, c_slot):
        blk = dp_ring.gather_block(state, c_slot, ring.codecs)
        return gae_targets(
            blk["reward"], blk["value"], blk["done"],
            blk["bootstrap_value"], cfg.gamma, cfg.gae_lambda,
        )

    def fused_call():
        return ring.run(
            lambda state: dev_update(params, opt_state, state, slot, key)
        )

    def unfused_call():
        adv = ring.run(lambda state: advantages_only(state, slot))
        jax.block_until_ready(adv)
        return fused_call()

    def timeit(call, reps=20):
        jax.block_until_ready(call())  # warm
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(call())
        return (time.perf_counter() - t0) / reps

    fused_s = timeit(fused_call)
    unfused_s = timeit(unfused_call)

    # Budget-counter actuals on one fenced fused consume (the meters
    # perfsan gates tier-1 with). Warm the staged-slot signature first:
    # the meter reads the C++ fastpath's post_hook, which only fires on
    # cache-hit dispatches — the timing loop above fed a host scalar,
    # which is a different jit signature.
    slot_dev = jax.device_put(np.int32(lease.slot))
    out = ring.run(
        lambda state: dev_update(params, opt_state, state, slot_dev, key)
    )
    jax.block_until_ready(out)
    with _perfsan.measure() as c_fused:
        slot_dev = jax.device_put(np.int32(lease.slot))
        out = ring.run(
            lambda state: dev_update(params, opt_state, state, slot_dev, key)
        )
        jax.block_until_ready(out)
    ring.release(lease)
    ring.close()

    # bf16-vs-fp32 update compute (--update-dtype) on the HOST update
    # program at the same shape — params/accumulators fp32 both ways.
    dtype_walls = {}
    for mode, bf16 in (("fp32", False), ("bf16", True)):
        mcfg = dataclasses.replace(cfg, bf16_compute=bf16)
        mparams, mopt = ppo.init_host_params(spec, mcfg, key)
        update = ppo.make_host_update_step(spec, mcfg)
        # jaxlint: disable=transfer-discipline (one-time bench staging
        # per dtype mode, OUTSIDE the timed region)
        jobs = jnp.asarray(block["obs"])
        # jaxlint: disable=transfer-discipline (one-time bench staging
        # per dtype mode, OUTSIDE the timed region)
        jargs = (
            mparams, mopt, jobs, jnp.asarray(block["action"]),
            jnp.asarray(block["log_prob"]), jnp.asarray(block["value"]),
            jnp.asarray(block["reward"]), jnp.asarray(block["done"]),
            jnp.asarray(block["terminated"]), jobs,
            jnp.asarray(block["last_obs"]), key,
        )
        dtype_walls[mode] = timeit(lambda: update(*jargs))

    return {
        "metric": "fused_update_wall",
        "value": round(fused_s * 1e3, 2),
        "unit": "ms per fused device-plane consume ([64, 8] block, "
                "gather + decode + advantages + update, fenced)",
        "fused_ms": round(fused_s * 1e3, 2),
        "unfused_ms": round(unfused_s * 1e3, 2),
        "speedup_x": round(unfused_s / fused_s, 2),
        "dispatches_per_block": c_fused.dispatches,
        "transferred_bytes_per_block": c_fused.transferred_bytes,
        "fp32_ms": round(dtype_walls["fp32"] * 1e3, 2),
        "bf16_ms": round(dtype_walls["bf16"] * 1e3, 2),
        "bf16_speedup_x": round(
            dtype_walls["fp32"] / dtype_walls["bf16"], 2
        ),
    }


def bench_data_plane():
    """End-to-end async-pipeline A/B, host vs device data plane
    (ISSUE 13 acceptance row): the SAME async PPO run — two actor
    services, V-trace learner, identical consumed env-steps — once
    through the host-numpy TrajQueue (one host→device transfer per
    consumed block on the learner thread) and once through the HBM
    DeviceTrajRing (actors enqueue int8-encoded blocks at collection
    time; the learner gathers + decodes in-jit, transferring only the
    slot index).

    Testbed: every block transfer is padded with a 10 ms wall sleep
    (`transfer_pad_s`, the serving bench's dispatch-pad discipline
    modeling the ~26 ms axon tunnel round trip) — on the host plane
    that wall lands on the LEARNER per consumed block; on the device
    plane it lands on ACTOR threads at collection time, overlapped
    with learning. That relocation is the architectural win a CPU-local
    jnp.asarray (~µs) cannot exhibit; the UNPADDED A/B rides along for
    transparency. Per-consumed-block transfer bytes are recorded for
    both planes (device consume = 0 by construction — acceptance), and
    a depth-1 `correction="none"` bitwise-equivalence check between the
    planes runs inside the record so the speed row and the correctness
    claim travel together."""
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.data_plane import ring as dp_ring
    from actor_critic_tpu.envs.host_pool import HostEnvPool

    E, K, iters, pad = 8, 32, 50, 0.010
    cfg = ppo.PPOConfig(
        num_envs=E, rollout_steps=K, epochs=4, num_minibatches=4,
        lr=3e-3, hidden=(64, 64),
    )

    def pools():
        return [
            HostEnvPool("CartPole-v1", E // 2, seed=0),
            HostEnvPool("CartPole-v1", E // 2, seed=100003),
        ]

    def run(plane: str, pad_s: float) -> float:
        ps = pools()
        try:
            t0 = time.perf_counter()
            ppo.train_host_async(
                ps, cfg, iters, seed=0, log_every=0,
                queue_depth=4, max_staleness=8, correction="vtrace",
                data_plane=plane,
                plane_codec="int8" if plane == "device" else "fp32",
                transfer_pad_s=pad_s,
            )
            return time.perf_counter() - t0
        finally:
            for p in ps:
                p.close()

    consumed = iters * K * (E // 2)

    def ab(pad_s: float) -> dict:
        host_wall = run("host", pad_s)
        device_wall = run("device", pad_s)
        host_sps = consumed / host_wall
        device_sps = consumed / device_wall
        return {
            "host": {
                "consumed_steps_per_s": round(host_sps, 1),
                "wall_s": round(host_wall, 2),
            },
            "device": {
                "consumed_steps_per_s": round(device_sps, 1),
                "wall_s": round(device_wall, 2),
            },
            "device_over_host_x": round(device_sps / host_sps, 2),
        }

    # Transfer-byte accounting straight from the ring (no estimates).
    ps = pools()
    spec = ps[0].spec
    for p in ps:
        p.close()
    block_spec = ppo.async_block_spec(spec, cfg, 2, "vtrace")
    acct = dp_ring.DeviceTrajRing(
        depth=1, block_spec=block_spec, codec="int8", register_gauge=False
    )
    bytes_row = {
        "host_per_consumed_block": acct.raw_bytes_per_block(),
        "device_per_consumed_block": 0,  # slot index only — acceptance
        "device_enqueue_per_block": acct.bytes_per_block(),
        "codec_mix": acct.codec_mix(),
    }
    # Measured actuals from perfsan's counters (ISSUE 15): the host
    # plane's per-block upload and the device plane's encoded enqueue,
    # METERED rather than computed — the same dispatch/transfer seams
    # tier-1's budget sanitizer gates, so the accounting row above and
    # the runtime meter can never drift apart silently.
    from actor_critic_tpu.analysis import perfsan as _perfsan
    from actor_critic_tpu.data_plane import ring as _ring_mod

    probe = {
        name: np.zeros(
            leaf.shape, _ring_mod.canonical_dtype(leaf.dtype)
        )
        for name, leaf in block_spec.items()
    }
    with _perfsan.measure() as c_host:
        staged = {k: jnp.array(v) for k, v in probe.items()}
        jax.block_until_ready(staged)
    with _perfsan.measure() as c_enq:
        acct.put(probe, version=0)
    bytes_row["host_measured"] = c_host.transferred_bytes
    bytes_row["host_upload_dispatches"] = c_host.dispatches
    bytes_row["enqueue_measured"] = c_enq.transferred_bytes
    acct.close()

    # Depth-1 bitwise equivalence rides in the record: the device plane
    # must be a pure relocation, not a silent algorithm change.
    eq_cfg = ppo.PPOConfig(
        num_envs=4, rollout_steps=8, epochs=2, num_minibatches=2,
        hidden=(16,),
    )

    def strict(plane: str):
        pool = HostEnvPool("CartPole-v1", 4, seed=0)
        try:
            p, o, _ = ppo.train_host_async(
                [pool], eq_cfg, 3, seed=0, log_every=0,
                queue_depth=1, correction="none", strict_lockstep=True,
                data_plane=plane, plane_codec="fp32",
            )
            return p, o
        finally:
            pool.close()

    ph, oh = strict("host")
    pd, od = strict("device")
    bitwise = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves((ph, oh)), jax.tree.leaves((pd, od))
        )
    )

    padded = ab(pad)
    raw = ab(0.0)
    return {
        "metric": "consumed_env_steps_per_s",
        "value": padded["device"]["consumed_steps_per_s"],
        "unit": "consumed env-steps/s, async PPO device data plane "
                f"({pad * 1e3:.0f} ms tunnel-padded transfers; host "
                "TrajQueue A/B inline)",
        **padded,
        "raw_transfer": raw,
        "per_block_transfer_bytes": bytes_row,
        "depth1_bitwise_equal": bool(bitwise),
        "config": {
            "num_envs": E, "rollout_steps": K, "iterations": iters,
            "actors": 2, "transfer_pad_ms": pad * 1e3,
            "device_codec": "int8", "correction": "vtrace",
        },
    }


def bench_replay_sample_throughput():
    """On-device replay sampling rate, fp32 vs quantized (ROADMAP "Bench
    resilience" replay-sample-throughput; ISSUE 8 satellite): a filled
    Pendulum-shaped ring is sampled at batch 256, many draws scanned
    inside ONE jitted program (summed to force materialization), fenced
    with block_until_ready. The headline value is the MIXED-codec
    samples/s (gather + int8 decode — the path every quantized update
    pays); fp32 rides along so the decode overhead is visible, and the
    bytes/transition block carries the capacity-per-HBM-byte evidence."""
    from actor_critic_tpu import replay
    from actor_critic_tpu.algos.common import OffPolicyTransition
    from actor_critic_tpu.replay import quantize

    capacity, batch, draws, reps = 65536, 256, 64, 10
    rng = np.random.default_rng(0)
    n = capacity
    fill = OffPolicyTransition(
        obs=jnp.asarray(rng.normal(0, 2, (n, 3)), jnp.float32),
        action=jnp.asarray(np.tanh(rng.normal(size=(n, 1))), jnp.float32),
        reward=jnp.asarray(rng.normal(-5, 4, (n,)), jnp.float32),
        next_obs=jnp.asarray(rng.normal(0, 2, (n, 3)), jnp.float32),
        terminated=jnp.asarray(rng.random(n) < 0.05, jnp.float32),
        done=jnp.asarray(rng.random(n) < 0.05, jnp.float32),
    )
    example = jax.tree.map(lambda x: x[0], fill)

    def measure(mode: str) -> dict:
        # One jit per MODE (each codec spec is a different program by
        # construction); built here, outside any loop, per the
        # recompile-hazard discipline.
        codecs = quantize.offpolicy_codecs(mode)
        state = replay.add_batch(
            replay.init(example, capacity, codecs), fill, codecs
        )

        @jax.jit
        def run(state, key):
            def body(acc, k):
                s = replay.sample(state, k, batch, codecs)
                return acc + sum(
                    jnp.sum(leaf.astype(jnp.float32))
                    for leaf in jax.tree.leaves(s)
                ), None

            keys = jax.random.split(key, draws)
            acc, _ = jax.lax.scan(body, jnp.zeros(()), keys)
            return acc

        acc = run(state, jax.random.key(0))
        jax.block_until_ready(acc)
        t0 = time.perf_counter()
        for r in range(reps):
            acc = run(state, jax.random.key(r))
        jax.block_until_ready(acc)
        dt = time.perf_counter() - t0
        return {
            "samples_per_s": round(reps * draws * batch / dt, 1),
            **{k: v for k, v in quantize.capacity_report(state, codecs).items()
               if k != "capacity"},
        }

    out = {mode: measure(mode) for mode in ("fp32", "mixed")}
    return {
        "metric": "replay_sample_throughput",
        "value": out["mixed"]["samples_per_s"],
        "unit": f"sampled transitions/s (batch {batch}, mixed codec, "
                "gather+decode, fenced)",
        "fp32_samples_per_s": out["fp32"]["samples_per_s"],
        "decode_overhead_x": round(
            out["fp32"]["samples_per_s"] / out["mixed"]["samples_per_s"], 2
        ),
        "bytes_per_transition": {
            m: out[m]["bytes_per_transition"] for m in out
        },
        "capacity_multiplier_mixed": out["mixed"]["capacity_multiplier"],
        "config": {"capacity": capacity, "batch": batch, "draws": draws,
                   "reps": reps, "obs_dim": 3},
    }


def bench_scenario_fleet():
    """Scenario-universe fleet bench (ISSUE 8 + ISSUE 11 acceptance
    rows), three blocks in one record:

    1. The PR 8 homogeneous rows: >=1k CartPole instances with
       per-instance randomized physics step inside ONE fused A2C XLA
       program; uniform fleet on the same shape makes the randomization
       overhead visible.
    2. `mixture` (ISSUE 11): a heterogeneous 4-type fleet — CartPole +
       Pendulum + Acrobot + procedural maze behind the padded shared
       obs/action interface (envs/mixture.py) — in one program, plus
       each member as a homogeneous fleet at the same shape, so the
       per-type cost and the batched-`lax.switch` heterogeneity
       overhead (every instance pays the summed branch cost under vmap)
       are separately visible. `per_type_steps_per_s` feeds
       scripts/bench_trend.py's per-type sub-rows.
    3. `instance_sweep` (ISSUE 11): the mixture fleet's steps/s at
       doubling instance counts until throughput rolls over — the
       published steps/s-vs-instance-count curve. The sweep stops one
       doubling past the peak (or at BENCH_FLEET_MAX_E, default 8192)
       so a CPU run stays bounded; `truncated` records which."""
    from actor_critic_tpu.algos import a2c
    from actor_critic_tpu.envs import make_cartpole, make_mixture
    from actor_critic_tpu.envs import mixture as mixture_mod

    E, T = 2048, 32
    cfg = a2c.A2CConfig(num_envs=E, rollout_steps=T, hidden=(64,))
    rates = {}
    for name, env in (
        ("randomized", make_cartpole(randomize=0.3)),
        ("uniform", make_cartpole()),
    ):
        rates[name] = _fused_steps_per_sec(
            a2c, env, cfg, E * T, iters_per_call=10, calls=3
        )

    # --- mixture mode (ISSUE 11) ---
    members = "cartpole,pendulum,acrobot,maze"
    member_names = tuple(n for n, _ in mixture_mod.parse_mixture_spec(members))
    E_m = 1024
    cfg_m = a2c.A2CConfig(num_envs=E_m, rollout_steps=T, hidden=(64,))
    mix_env = make_mixture(members, randomize=0.3)
    mix_sps = _fused_steps_per_sec(
        a2c, mix_env, cfg_m, E_m * T, iters_per_call=5, calls=3
    )
    per_type = {}
    for name in member_names:
        env_t = mixture_mod.member_makers()[name](randomize=0.3)
        per_type[name] = round(_fused_steps_per_sec(
            a2c, env_t, cfg_m, E_m * T, iters_per_call=5, calls=3
        ), 1)
    mixture_block = {
        "steps_per_s": round(mix_sps, 1),
        "per_type_steps_per_s": per_type,
        "n_types": len(member_names),
        # Batched lax.switch computes every branch and selects, so the
        # honest overhead reference is the SUM of the members' costs at
        # this shape (1/sum(1/r_i) is the series rate of stepping each
        # homogeneous fleet in turn).
        "overhead_vs_series_x": round(
            # audited: the rates are measured steps/s of runs that
            # completed — strictly positive, neither division can be /0
            (1.0 / sum(1.0 / r for r in per_type.values())) / mix_sps, 2  # jaxlint: disable=nonfinite-hazard
        ),
    }

    # --- instance-count sweep (ISSUE 11 rollover curve) ---
    max_e = int(os.environ.get("BENCH_FLEET_MAX_E", "8192"))
    curve = {}
    peak_e, peak_sps = 0, 0.0
    e = 256
    truncated = True
    while e <= max_e:
        cfg_e = a2c.A2CConfig(num_envs=e, rollout_steps=T, hidden=(64,))
        sps = _fused_steps_per_sec(
            a2c, mix_env, cfg_e, e * T, iters_per_call=5, calls=2
        )
        curve[str(e)] = round(sps, 1)
        if sps > peak_sps:
            peak_e, peak_sps = e, sps
        elif sps < 0.85 * peak_sps:
            # Rolled over decisively: one more doubling would only
            # confirm the downslope at real CPU cost.
            truncated = False
            break
        e *= 2
    sweep = {
        "curve": curve,
        "peak_instances": peak_e,
        "peak_steps_per_s": round(peak_sps, 1),
        "truncated": truncated and e > max_e,
    }

    return {
        "metric": "scenario_fleet_throughput",
        "value": round(rates["randomized"], 1),
        "unit": f"env-steps/sec/chip ({E} domain-randomized CartPole "
                "instances, fused A2C, one XLA program)",
        "uniform_steps_per_s": round(rates["uniform"], 1),
        "randomization_overhead_x": round(
            rates["uniform"] / rates["randomized"], 2
        ),
        "mixture": mixture_block,
        "instance_sweep": sweep,
        "config": {"num_envs": E, "rollout_steps": T, "randomize": 0.3,
                   "mixture_members": members,
                   "mixture_num_envs": E_m},
    }


def bench_multihost_scaling():
    """Multi-host distributed learner scaling (ISSUE 9 acceptance row):
    the `scripts/launch_multihost.py --bench` grid — aggregate consumed
    env-steps/s of a CPU local cluster at 1/2/4 processes (sync
    all-reduce over the global mesh), the gossip/ring variant, and the
    straggler A/B in which the synchronous fleet stalls at the barrier
    while gossip degrades only the slow host. Wall-bounded runs on the
    sleep-padded CartPole testbed; headline value = sync aggregate
    speedup at 4 processes vs 1 (target >= 1.5x), with
    straggler.gossip_over_sync carrying the straggler-does-not-stall
    evidence. BENCH_MULTIHOST_DURATION overrides the per-run window
    (seconds; default 6 keeps the 6-run grid inside the cpu_metrics
    per-metric timeout)."""
    import subprocess

    launcher = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "launch_multihost.py",
    )
    duration = os.environ.get("BENCH_MULTIHOST_DURATION", "6")
    proc = subprocess.run(
        [sys.executable, launcher, "--bench", "--duration-s", duration],
        capture_output=True, text=True, check=True,
    )
    lines = [
        ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")
    ]
    return json.loads(lines[-1])


def bench_mujoco_host():
    """Raw MuJoCo host-stepping rate through HostEnvPool (E=8,
    HalfCheetah-v5) — the 1-core host bound that caps every host-env
    config's wall-clock (SURVEY.md §7.2 item 2); measured so the
    BASELINE.md MuJoCo rows have a reproducible denominator."""
    import importlib.util

    if importlib.util.find_spec("mujoco") is None:
        return {"metric": "mujoco_host_stepping", "value": 0.0,
                "unit": "env-steps/sec", "error": "mujoco not installed"}
    from actor_critic_tpu.envs.host_pool import HostEnvPool

    E, T = 8, 500
    pool = HostEnvPool(
        "HalfCheetah-v5", num_envs=E, seed=0,
        normalize_obs=True, normalize_reward=True,
    )
    pool.reset()
    acts = np.zeros((E, pool.spec.action_dim), np.float32)
    pool.step(acts)
    t0 = time.perf_counter()
    for _ in range(T):
        pool.step(acts)
    sps = E * T / (time.perf_counter() - t0)
    pool.close()
    return {
        "metric": "mujoco_host_stepping",
        "value": round(sps, 1),
        "unit": "env-steps/sec (HalfCheetah-v5, E=8, incl. normalization)",
    }


def _startup_leg(cache_dir: str) -> dict:
    """One subprocess leg of the startup bench: enable the persistent
    cache, then measure process-ready → first completed train step
    (env + state init, trace, XLA compile-or-cache-hit, first run).
    Interpreter/jax import is excluded — both legs pay it identically,
    and it is exactly the part the compile cache cannot help.

    The measured program is pixel PPO with the unrolled epoch/minibatch
    nest (the `should_unroll_update` XLA:CPU conv regime) — the
    compile-DOMINATED configuration this subsystem exists for; MLP-sized
    programs compile in ~3s against a ~4s trace+init floor the cache
    cannot touch, which would understate the win the flagship conv
    configs actually see."""
    from actor_critic_tpu.utils import compile_cache

    t0 = time.perf_counter()
    compile_cache.enable_persistent_cache(cache_dir)
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.envs import make_pong

    env = make_pong(opp_skill=0.5, frame_skip=4, size=36)
    cfg = ppo.PPOConfig(
        num_envs=8, rollout_steps=16, epochs=6, num_minibatches=2,
        hidden=(64,),
    )
    state = ppo.init_state(env, cfg, jax.random.key(0))
    step = jax.jit(ppo.make_train_step(env, cfg), donate_argnums=0)
    state, metrics = step(state)
    jax.block_until_ready(metrics)
    return {
        "first_step_s": round(time.perf_counter() - t0, 4),
        "cache": compile_cache.cache_stats(),
    }


def bench_startup_to_first_step():
    """Cold-vs-warm startup through the persistent compilation cache
    (ISSUE 4 acceptance row): two fresh subprocesses run the same
    env-init → first-train-step sequence against one cache dir — the
    first (cold) compiles and fills it, the second (warm) deserializes.
    The headline value is the cold/warm wall ratio (target >= 3x); this
    is exactly what a `run_resumable.sh` leg N>0 skips with the default
    <ckpt-dir>/xla_cache sidecar."""
    import subprocess
    import tempfile

    def leg(cache):
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "_startup_leg", cache],
            capture_output=True, text=True, check=True,
        )
        return json.loads(out.stdout.strip().splitlines()[-1])

    with tempfile.TemporaryDirectory() as td:
        cache = os.path.join(td, "xla_cache")
        cold = leg(cache)
        warm = leg(cache)
    return {
        "metric": "startup_to_first_step",
        "value": round(cold["first_step_s"] / warm["first_step_s"], 2),
        "unit": "x cold/warm first-step wall (persistent XLA cache)",
        "cold_s": cold["first_step_s"],
        "warm_s": warm["first_step_s"],
        "cold_cache": cold["cache"],
        "warm_cache": warm["cache"],
    }


def bench_serving_latency():
    """Policy-serving gateway SLO bench (ISSUE 10 acceptance row):
    micro-batched act() over HTTP vs sequential batch=1 request
    handling, at saturating closed-loop concurrency on CPU.

    Both modes serve the SAME engine (PPO CartPole MLP, bucket ladder
    1..64) to the same closed-loop client fleet (scripts/serve_loadgen,
    its own subprocess so client and server Python don't share a GIL):
    micro-batched = the threaded gateway + GA3C dispatcher
    (max_wait_us=2000); sequential = `ServeGateway(threaded=False)` —
    one request handled end-to-end at a time, batch 1 per dispatch, the
    pre-GA3C predictor architecture. The headline value is
    micro/sequential actions/s (target >= 4x), with the p50/p99 curve
    of both modes and the steady-state compile count (must be 0 after
    warmup — the AOT-warm bucket contract).

    Testbed: each dispatch is padded with a 10 ms wall sleep
    (`PolicyEngine(dispatch_pad_s=...)`) modeling the host<->accelerator
    round trip of a real serving deployment — the axon TPU tunnel
    measures ~26 ms per act() round trip (models/host_actor.py), a
    fixed per-DISPATCH cost a CPU-local jit (~0.3 ms) cannot exhibit;
    this is envs/sleep_pad.py's discipline (host_pool_scaling,
    async_decoupling) pointed at serving. The pad is exactly the cost
    micro-batching amortizes, so it is what makes the A/B meaningful on
    a 2-core host; the UNPADDED raw-dispatch A/B rides along as a
    secondary block for transparency (HTTP-envelope-bound on CPU, so
    its ratio understates the accelerator case)."""
    import subprocess

    from actor_critic_tpu import serving
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.envs import make_cartpole
    from actor_critic_tpu.telemetry import profiler

    scripts_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    )
    loadgen = os.path.join(scripts_dir, "serve_loadgen.py")
    pad_ms, concurrency, duration_s = 10.0, 32, 6.0
    buckets = (1, 2, 4, 8, 16, 32, 64)
    spec = make_cartpole().spec
    cfg = ppo.PPOConfig(hidden=(64, 64))
    params = serving.init_params(spec, cfg, "ppo", seed=0)
    profiler.ensure_compile_introspection()

    def drive(engine, threaded: bool) -> dict:
        from actor_critic_tpu.telemetry import histo

        store = serving.PolicyStore()
        # SLO class on the bench policy (ISSUE 16): the bench reports
        # the server-side burn rate and histogram-derived quantiles
        # next to the loadgen's client-side point percentiles, so a
        # trend regression shows up in the mergeable fleet metric too.
        store.register("default", engine, params, slo_ms=100.0)
        gw = serving.ServeGateway(
            store, port=0, max_wait_us=2000.0, threaded=threaded
        )
        try:
            out = subprocess.run(
                [sys.executable, loadgen, "--url", gw.url,
                 "--concurrency", str(concurrency),
                 "--duration", str(duration_s),
                 "--obs-dim", str(spec.obs_shape[0]),
                 "--json", "--timeout", "60"],
                capture_output=True, text=True, timeout=180,
            )
            if not out.stdout.strip():
                # loadgen legitimately exits non-zero when it COUNTED
                # request errors (still a measurement), so only a
                # missing report line means the subprocess itself died.
                raise RuntimeError(
                    f"loadgen produced no report (rc {out.returncode}): "
                    + (out.stderr or "").strip()[-500:]
                )
            rec = json.loads(out.stdout.strip().splitlines()[-1])
            gauge = gw.batcher.gauge()
            rec["batch_occupancy"] = gauge.get("batch_occupancy", 0.0)
            rec["slo_burn"] = gauge.get("slo_burn", 0.0)
            snap = gw.batcher.metrics.histogram_snapshots().get("default")
            for key, q in (("hist_p50_ms", 0.5), ("hist_p99_ms", 0.99)):
                v = histo.quantile(snap, q) if snap else None
                rec[key] = None if v is None else round(v, 3)
        finally:
            gw.close()
        return rec

    def ab(pad_s: float) -> dict:
        engine = serving.PolicyEngine(
            spec, cfg, algo="ppo", buckets=buckets, dispatch_pad_s=pad_s
        )
        engine.warm(engine.prepare_params(params))
        # Monotonic counter, NOT len(compile_records()): the record
        # ring caps at 256 entries and would silently undercount.
        c0 = profiler.compile_event_count()
        micro = drive(engine, threaded=True)
        seq = drive(engine, threaded=False)
        compiles = profiler.compile_event_count() - c0
        return {
            "speedup_x": round(
                micro["actions_per_s"] / max(seq["actions_per_s"], 1e-9), 2
            ),
            "micro_batched": {
                k: micro[k] for k in
                ("actions_per_s", "p50_ms", "p99_ms", "requests", "errors",
                 "batch_occupancy", "slo_burn", "hist_p50_ms",
                 "hist_p99_ms")
            },
            "sequential": {
                k: seq[k] for k in
                ("actions_per_s", "p50_ms", "p99_ms", "requests", "errors")
            },
            "steady_state_compiles": compiles,
        }

    padded = ab(pad_ms / 1e3)
    raw = ab(0.0)
    return {
        "metric": "serving_latency",
        "value": padded["speedup_x"],
        "unit": "x actions/s, micro-batched vs sequential batch=1 "
                f"({pad_ms:.0f} ms tunnel-padded dispatch, closed-loop "
                f"concurrency {concurrency})",
        **padded,
        "raw_dispatch": raw,
        "config": {
            "dispatch_pad_ms": pad_ms,
            "concurrency": concurrency,
            "duration_s": duration_s,
            "buckets": list(buckets),
            "max_wait_us": 2000.0,
            "hidden": [64, 64],
        },
    }


def bench_serving_fleet_scaling():
    """Horizontal serving scale-out curve (ISSUE 17 acceptance row):
    fleet actions/s at N gateway replicas behind the `FleetProxy`
    fronting hop, same closed-loop client fleet, fixed concurrency.

    Each replica owns its engine + dispatcher (exactly the process
    shape of N `scripts/serve.py` instances; in-process here so one
    bench subprocess hosts the whole fleet), every dispatch padded with
    a 10 ms wall sleep modeling the host<->accelerator round trip
    (`serving_latency`'s testbed: the pad releases the GIL, so replica
    dispatchers genuinely overlap — what real tunnel round trips do).
    Buckets cap at 8 rows so a single replica saturates at
    ~max_rows/pad actions/s and the curve measures DISPATCHER
    parallelism, not packing headroom. The headline value is the
    3-replica / 1-replica actions/s ratio (target >= 1.6x); per-point
    rows carry p50/p99, proxy relay stats, and the loadgen errors."""
    import subprocess

    from actor_critic_tpu import serving
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.envs import make_cartpole

    scripts_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    )
    loadgen = os.path.join(scripts_dir, "serve_loadgen.py")
    pad_ms, concurrency, duration_s = 10.0, 32, 6.0
    buckets = (1, 2, 4, 8)
    replica_counts = (1, 2, 3)
    spec = make_cartpole().spec
    cfg = ppo.PPOConfig(hidden=(64, 64))
    params = serving.init_params(spec, cfg, "ppo", seed=0)

    def fleet_point(replicas: int) -> dict:
        gateways = []
        proxy = None
        try:
            for _ in range(replicas):
                engine = serving.PolicyEngine(
                    spec, cfg, algo="ppo", buckets=buckets,
                    dispatch_pad_s=pad_ms / 1e3,
                )
                engine.warm(engine.prepare_params(params))
                store = serving.PolicyStore()
                store.register("default", engine, params, slo_ms=100.0)
                gateways.append(
                    serving.ServeGateway(store, port=0, max_wait_us=2000.0)
                )
            proxy = serving.FleetProxy(
                [gw.url for gw in gateways], port=0, probe=False
            )
            out = subprocess.run(
                [sys.executable, loadgen, "--url", proxy.url,
                 "--concurrency", str(concurrency),
                 "--duration", str(duration_s),
                 "--obs-dim", str(spec.obs_shape[0]),
                 "--json", "--timeout", "60"],
                capture_output=True, text=True, timeout=180,
            )
            if not out.stdout.strip():
                raise RuntimeError(
                    f"loadgen produced no report (rc {out.returncode}): "
                    + (out.stderr or "").strip()[-500:]
                )
            rec = json.loads(out.stdout.strip().splitlines()[-1])
            stats = proxy.stats()
            return {
                "replicas": replicas,
                "actions_per_s": rec["actions_per_s"],
                "p50_ms": rec["p50_ms"],
                "p99_ms": rec["p99_ms"],
                "requests": rec["requests"],
                "errors": rec["errors"],
                "proxy_relayed": stats["relayed"],
                "proxy_failovers": stats["failovers"],
                "replica_forwards": [
                    r["forwards"] for r in stats["replicas"]
                ],
            }
        finally:
            if proxy is not None:
                proxy.close()
            for gw in gateways:
                gw.close()

    points = [fleet_point(r) for r in replica_counts]
    by_r = {p["replicas"]: p for p in points}
    scaling = round(
        by_r[3]["actions_per_s"] / max(by_r[1]["actions_per_s"], 1e-9), 2
    )
    return {
        "metric": "serving_fleet_scaling",
        "value": scaling,
        "unit": "x actions/s, 3 replicas vs 1 behind the fleet proxy "
                f"({pad_ms:.0f} ms tunnel-padded dispatch, closed-loop "
                f"concurrency {concurrency})",
        "points": points,
        "config": {
            "dispatch_pad_ms": pad_ms,
            "concurrency": concurrency,
            "duration_s": duration_s,
            "buckets": list(buckets),
            "replica_counts": list(replica_counts),
            "max_wait_us": 2000.0,
            "hidden": [64, 64],
            "proxy_policy": "least_loaded",
        },
    }


def bench_pad_overhead():
    """Shape-stabilization tax (ISSUE 20 satellite): the dispatch wall
    of the REAL padded paths vs the same program at the exact aligned
    shape, at both pad seams padsan guards. Pallas side: `gae` at the
    ragged env batches E=7/96/200 (the kernel pads to the 128-lane tile
    and slices back) vs the already-aligned Ep width — the gap is what
    the pad/slice machinery plus the dead lanes cost. Serving side:
    `PolicyEngine.act` at a non-bucket n (backfill rows engage) vs an
    engine whose bucket IS n — the gap is what the bucket ladder costs
    per dispatch. The headline value is the WORST overhead ratio across
    all measured shapes, so a pad path quietly growing a copy (or a
    bucket ladder over-padding) trends as one number; the per-shape
    walls ride along for attribution."""
    from actor_critic_tpu import serving
    from actor_critic_tpu.algos.ddpg import DDPGConfig
    from actor_critic_tpu.envs.testbeds import make_point_mass
    from actor_critic_tpu.ops import pallas_scan

    def timeit(fn, *args, reps=30):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps * 1e6

    T = 32
    gae = jax.jit(lambda *a: pallas_scan.gae(*a, 0.99, 0.95))
    pallas = {}
    for E in (7, 96, 200):
        Ep = pallas_scan._pad_env(E)
        block = pallas_scan.kernel_block("gae", T, E)
        assert block > 0, f"gae kernel must engage at E={E}"

        def wall(width):
            rng = np.random.default_rng(width)
            r = jnp.asarray(rng.normal(size=(T, width)), jnp.float32)
            v = jnp.asarray(rng.normal(size=(T, width)), jnp.float32)
            d = jnp.asarray(rng.random((T, width)) < 0.02, jnp.float32)
            b = jnp.asarray(rng.normal(size=(width,)), jnp.float32)
            return timeit(gae, r, v, d, b)

        padded_us, exact_us = wall(E), wall(Ep)
        pallas[f"E{E}"] = {
            "padded_us": round(padded_us, 1),
            "exact_us": round(exact_us, 1),
            "pad_lanes": Ep - E,
            "kernel_block": block,
            "overhead_x": round(padded_us / max(exact_us, 1e-9), 2),
        }

    spec = make_point_mass().spec
    cfg = DDPGConfig(hidden=(16, 16))
    params = serving.init_params(spec, cfg, "ddpg", seed=0)
    ladder = (1, 2, 4, 8)
    bucketed = {}
    for n in (3, 5):
        bucket = next(b for b in ladder if b >= n)
        eng_pad = serving.PolicyEngine(
            spec, cfg, algo="ddpg", buckets=ladder
        )
        eng_exact = serving.PolicyEngine(
            spec, cfg, algo="ddpg", buckets=(n,)
        )
        eng_pad.warm(params)
        eng_exact.warm(params)
        rng = np.random.default_rng(n)
        obs = (rng.normal(size=(n, *spec.obs_shape)) * 0.7).astype(
            np.float32
        )

        def act_wall(eng, reps=50):
            eng.act(params, obs)  # act blocks (device_get), no fence
            t0 = time.perf_counter()
            for _ in range(reps):
                eng.act(params, obs)
            return (time.perf_counter() - t0) / reps * 1e6

        padded_us, exact_us = act_wall(eng_pad), act_wall(eng_exact)
        bucketed[f"n{n}"] = {
            "padded_us": round(padded_us, 1),
            "exact_us": round(exact_us, 1),
            "bucket": bucket,
            "backfill_rows": bucket - n,
            "overhead_x": round(padded_us / max(exact_us, 1e-9), 2),
        }

    worst_key, worst = max(
        [*((f"pallas {k}", v) for k, v in pallas.items()),
         *((f"serving {k}", v) for k, v in bucketed.items())],
        key=lambda kv: kv[1]["overhead_x"],
    )
    return {
        "metric": "pad_overhead",
        "value": worst["overhead_x"],
        "unit": "x padded vs exact-shape dispatch wall "
                f"(worst: {worst_key})",
        "pallas": pallas,
        "serving": bucketed,
    }


BENCHES = {
    "a2c": bench_a2c,
    "ppo": bench_ppo,
    "impala": bench_impala,
    "sac": bench_sac_updates,
    "ddpg": bench_ddpg_updates,
    "host": bench_host_native,
    "host_pool_scaling": bench_host_pool_scaling,
    "async_decoupling": bench_async_decoupling,
    "update_wall": bench_update_wall,
    "fused_update_wall": bench_fused_update_wall,
    "consumed_env_steps_per_s": bench_data_plane,
    "replay_sample_throughput": bench_replay_sample_throughput,
    "multihost_scaling": bench_multihost_scaling,
    "serving_latency": bench_serving_latency,
    "serving_fleet_scaling": bench_serving_fleet_scaling,
    "scenario_fleet": bench_scenario_fleet,
    "mujoco": bench_mujoco_host,
    "pallas": bench_pallas_ops,
    "pad_overhead": bench_pad_overhead,
    "startup_to_first_step": bench_startup_to_first_step,
}


def main(argv: list[str]) -> None:
    if argv and argv[0] == "_startup_leg":
        # Internal child entry of bench_startup_to_first_step: one
        # measured leg against the given cache dir, JSON on stdout.
        print(json.dumps(_startup_leg(argv[1])), flush=True)
        return
    names = argv or list(BENCHES)
    if len(names) > 1:
        # One subprocess per bench: sharing a process lets earlier benches'
        # device allocations depress later ones (measured 60x on the
        # replay-path benches when run after the E=4096 A2C bench).
        import subprocess

        for n in names:
            subprocess.run(
                [sys.executable, os.path.abspath(__file__), n], check=True
            )
        return
    print(json.dumps(BENCHES[names[0]]()), flush=True)


if __name__ == "__main__":
    main(sys.argv[1:])
