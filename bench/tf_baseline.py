"""TF2-CPU reference-shaped PPO baseline — BASELINE HARNESS, NOT FRAMEWORK CODE.

The north-star claim (BASELINE.json:5) is "match TF-GPU PPO HalfCheetah
return in <=0.5x wall-clock". No GPU and no reference code exist on this
host (SURVEY.md section 0: empty reference mount), so that ratio was
unfalsifiable for four rounds. This harness makes it a measurement: a
minimal TensorFlow 2 PPO — the reference's execution model (graph-mode
TF via `tf.function`, MLP encoders, GAE-lambda, clipped surrogate;
BASELINE.json:5,8) — run on the SAME host, the SAME gymnasium
HalfCheetah-v5 pipeline, the SAME hyperparameters, and the SAME eval
protocol as the framework's recorded PPO run 3 (BASELINE.md: 7,872.7 @
10.24M steps; crosses 3,000 at 2.05M steps / 8.7 min on the 1-core CPU
host).

Controlled-comparison design: the env side (HostEnvPool — SyncVectorEnv
SAME_STEP autoreset, running mean/std obs normalization, discounted-
return reward scaling, greedy frozen-stats eval) is IMPORTED from the
framework, so both arms see byte-identical data pipelines and the
measured difference is the learner execution path alone: TF2 tf.function
graphs vs JAX/XLA jitted programs.

Faithful-mirror details (matched to algos/ppo.py + the run-3 CLI in
scripts/round4_queue.sh):
  E=16 envs, T=256 (4,096 steps/iter), 10 epochs x 32 minibatches of 128,
  gamma .99, GAE-lambda .95, clip .2 (flat), value-clip .2, value_coef .5,
  entropy 0, global-norm clip .5, Adam(eps=1e-5), lr 3e-4 -> 0 linear over
  2500 iters x 320 optimizer steps, hidden (256,256) tanh with orthogonal
  init (sqrt(2) torsos, 0.01 policy head, 1.0 value head), separate
  actor/critic torsos, state-independent log_std init 0, per-minibatch
  advantage normalization, truncation-aware GAE (reward + gamma *
  V(final_obs) on truncation), V(last_obs) bootstrap, raw actions clipped
  to the Box by the pool.

TF is given its idiomatic best shot: the rollout policy step, the
minibatch update, and the greedy eval action are all `tf.function`
graphs (traced once per shape); GAE runs in numpy exactly as the TF1-era
genre did. TF's default CPU threading is left untouched. Run with an
otherwise-idle host, like the JAX run it is compared against:

  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python bench/tf_baseline.py \
      --metrics runs/tf_baseline_ppo_hc.jsonl

Emits per-iteration JSONL and a final one-line summary JSON with
steps/sec, wall-clock-to-3000 (if crossed), and the ratio against the
recorded JAX-arm numbers.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import tensorflow as tf  # noqa: E402

from actor_critic_tpu.algos.host_loop import (  # noqa: E402
    EpisodeTracker,
    host_collect,
    host_evaluate,
)
from actor_critic_tpu.envs.host_pool import HostEnvPool  # noqa: E402

# The JAX arm this baseline is measured against (BASELINE.md PPO run 3,
# 1-core CPU host, identical config): effective env-steps/sec and
# wall-clock to the 3,000 greedy-eval target.
JAX_ARM = {
    "steps_per_sec": 10_240_000 / (42.8 * 60.0),  # ~3,988
    "secs_to_3000": 8.7 * 60.0,
    "steps_to_3000": 2_048_000,
}


def ortho_init(shape, gain, rng):
    """Orthogonal initializer matching flax.nn.initializers.orthogonal."""
    a = rng.normal(size=(shape[0], shape[1]))
    q, r = np.linalg.qr(a if shape[0] >= shape[1] else a.T)
    q = q * np.sign(np.diag(r))
    if shape[0] < shape[1]:
        q = q.T
    return (gain * q[: shape[0], : shape[1]]).astype(np.float32)


class PPONet(tf.Module):
    """Separate-torso Gaussian actor-critic MLP (mirrors
    models/networks.py ActorCriticGaussian: tanh torsos, orthogonal init,
    state-independent log_std)."""

    def __init__(self, obs_dim: int, act_dim: int, hidden=(256, 256), seed=0):
        super().__init__()
        rng = np.random.default_rng(seed)
        self.vars_pi, self.vars_vf = [], []
        for torso, store in (("pi", self.vars_pi), ("vf", self.vars_vf)):
            d_in = obs_dim
            for i, h in enumerate(hidden):
                store.append(
                    tf.Variable(
                        ortho_init((d_in, h), np.sqrt(2.0), rng),
                        name=f"{torso}_w{i}",
                    )
                )
                store.append(tf.Variable(tf.zeros([h]), name=f"{torso}_b{i}"))
                d_in = h
        self.w_mean = tf.Variable(
            ortho_init((hidden[-1], act_dim), 0.01, rng), name="policy_w"
        )
        self.b_mean = tf.Variable(tf.zeros([act_dim]), name="policy_b")
        self.w_v = tf.Variable(ortho_init((hidden[-1], 1), 1.0, rng), name="value_w")
        self.b_v = tf.Variable(tf.zeros([1]), name="value_b")
        self.log_std = tf.Variable(tf.zeros([act_dim]), name="log_std")

    @staticmethod
    def _torso(x, store):
        for w, b in zip(store[0::2], store[1::2]):
            x = tf.tanh(tf.linalg.matmul(x, w) + b)
        return x

    def dist_value(self, obs):
        mean = tf.linalg.matmul(self._torso(obs, self.vars_pi), self.w_mean) + self.b_mean
        value = tf.linalg.matmul(self._torso(obs, self.vars_vf), self.w_v) + self.b_v
        return mean, self.log_std, value[:, 0]


LOG_2PI = float(np.log(2.0 * np.pi))


def gaussian_log_prob(mean, log_std, x):
    z = (x - mean) * tf.exp(-log_std)
    return tf.reduce_sum(-0.5 * (z * z + LOG_2PI) - log_std, axis=-1)


def gae_numpy(rewards, values, dones, bootstrap, gamma, lam):
    """Truncation-folded GAE (mirror of ops/returns.gae): `rewards`
    already carry the gamma*V(final_obs) truncation bootstrap."""
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    adv_next = np.zeros(rewards.shape[1], rewards.dtype)
    v_next = bootstrap
    for t in range(T - 1, -1, -1):
        nonterm = 1.0 - dones[t]
        delta = rewards[t] + gamma * v_next * nonterm - values[t]
        adv_next = delta + gamma * lam * nonterm * adv_next
        adv[t] = adv_next
        v_next = values[t]
    return adv, adv + values


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--iterations", type=int, default=625,
                   help="4,096 env-steps each (default 625 = 2.56M steps, "
                        "just past the JAX arm's 2.05M crossing point)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--eval-every", type=int, default=125,
                   help="JAX run-3 cadence (512k steps)")
    p.add_argument("--eval-envs", type=int, default=8)
    p.add_argument("--log-every", type=int, default=5)
    p.add_argument("--max-minutes", type=float, default=150.0,
                   help="hard wall cap; summary marks capped=true")
    p.add_argument("--metrics", type=str, default="runs/tf_baseline_ppo_hc.jsonl")
    p.add_argument("--hidden", type=str, default="256,256")
    args = p.parse_args()

    E, T, EPOCHS, MB = 16, 256, 10, 32
    GAMMA, LAM, CLIP, VF_CLIP, VCOEF, MAXGN = 0.99, 0.95, 0.2, 0.2, 0.5, 0.5
    LR0, TOTAL_OPT_STEPS = 3e-4, 2500 * EPOCHS * MB
    hidden = tuple(int(h) for h in args.hidden.split(","))
    B = T * E
    mb_size = B // MB

    np.random.seed(args.seed)
    tf.random.set_seed(args.seed)
    rng = np.random.default_rng(args.seed + 0x5EED)

    pool = HostEnvPool("HalfCheetah-v5", E, seed=args.seed)
    obs_dim = pool.spec.obs_shape[0]
    act_dim = pool.spec.action_dim
    net = PPONet(obs_dim, act_dim, hidden, seed=args.seed)
    opt = tf.keras.optimizers.Adam(learning_rate=LR0, epsilon=1e-5)
    tf_gen = tf.random.Generator.from_seed(args.seed)

    @tf.function
    def policy_step(obs):
        mean, log_std, value = net.dist_value(obs)
        eps = tf_gen.normal(tf.shape(mean))
        action = mean + tf.exp(log_std) * eps
        return action, gaussian_log_prob(mean, log_std, action), value

    @tf.function
    def values_of(obs):
        return net.dist_value(obs)[2]

    @tf.function
    def greedy_act(obs):
        return net.dist_value(obs)[0]

    @tf.function
    def train_minibatch(obs, action, logp_old, v_old, adv, ret, lr):
        a_mean = tf.reduce_mean(adv)
        a_std = tf.math.reduce_std(adv)
        adv_n = (adv - a_mean) / (a_std + 1e-8)
        with tf.GradientTape() as tape:
            mean, log_std, value = net.dist_value(obs)
            logp = gaussian_log_prob(mean, log_std, action)
            ratio = tf.exp(logp - logp_old)
            surr1 = ratio * adv_n
            surr2 = tf.clip_by_value(ratio, 1.0 - CLIP, 1.0 + CLIP) * adv_n
            pg_loss = -tf.reduce_mean(tf.minimum(surr1, surr2))
            v_clipped = v_old + tf.clip_by_value(value - v_old, -VF_CLIP, VF_CLIP)
            v_loss = 0.5 * tf.reduce_mean(
                tf.maximum((value - ret) ** 2, (v_clipped - ret) ** 2)
            )
            loss = pg_loss + VCOEF * v_loss
        grads = tape.gradient(loss, net.trainable_variables)
        grads, _ = tf.clip_by_global_norm(grads, MAXGN)
        opt.learning_rate.assign(lr)
        opt.apply_gradients(zip(grads, net.trainable_variables))
        return loss, pg_loss, v_loss

    eval_pool = pool.eval_pool(args.eval_envs)
    tracker = EpisodeTracker(E)
    metrics_path = Path(args.metrics)
    metrics_path.parent.mkdir(parents=True, exist_ok=True)
    log_f = metrics_path.open("a")

    def act_fn(o):
        a, lp, v = policy_step(tf.constant(o, tf.float32))
        return np.asarray(a), {"log_prob": np.asarray(lp), "value": np.asarray(v)}

    obs = pool.reset()
    t0 = time.monotonic()
    opt_step = 0
    iter_times: list[float] = []
    crossed_at = None  # (env_steps, wall_secs)
    capped = False

    for it in range(args.iterations):
        it_t0 = time.monotonic()
        obs, block = host_collect(pool, obs, T, act_fn, tracker)
        t_collect = time.monotonic() - it_t0

        bootstrap = np.asarray(values_of(tf.constant(obs, tf.float32)))
        fobs = block["final_obs"].reshape(B, obs_dim)
        final_values = np.asarray(
            values_of(tf.constant(fobs, tf.float32))
        ).reshape(T, E)
        truncated = block["done"] * (1.0 - block["terminated"])
        rewards = block["reward"] + GAMMA * final_values * truncated
        adv, ret = gae_numpy(
            rewards, block["value"], block["done"], bootstrap, GAMMA, LAM
        )

        flat = {
            "obs": block["obs"].reshape(B, obs_dim),
            "action": block["action"].reshape(B, act_dim),
            "logp": block["log_prob"].reshape(B),
            "v_old": block["value"].reshape(B),
            "adv": adv.reshape(B),
            "ret": ret.reshape(B),
        }
        tensors = {k: tf.constant(v, tf.float32) for k, v in flat.items()}
        for _ in range(EPOCHS):
            perm = rng.permutation(B)
            for m in range(MB):
                idx = tf.constant(perm[m * mb_size : (m + 1) * mb_size])
                lr = LR0 * max(0.0, 1.0 - opt_step / TOTAL_OPT_STEPS)
                train_minibatch(
                    tf.gather(tensors["obs"], idx),
                    tf.gather(tensors["action"], idx),
                    tf.gather(tensors["logp"], idx),
                    tf.gather(tensors["v_old"], idx),
                    tf.gather(tensors["adv"], idx),
                    tf.gather(tensors["ret"], idx),
                    tf.constant(lr, tf.float32),
                )
                opt_step += 1
        iter_wall = time.monotonic() - it_t0
        iter_times.append(iter_wall)
        env_steps = (it + 1) * B

        row = None
        if (it + 1) % args.eval_every == 0:
            ev = host_evaluate(
                eval_pool, lambda o: np.asarray(greedy_act(tf.constant(o, tf.float32)))
            )
            row = {"eval_return": ev}
            if ev >= 3000.0 and crossed_at is None:
                crossed_at = (env_steps, time.monotonic() - t0)
        if row is not None or (it + 1) % args.log_every == 0:
            rec = {
                "iter": it + 1,
                "env_steps": env_steps,
                "wall_secs": round(time.monotonic() - t0, 2),
                "iter_secs": round(iter_wall, 3),
                "collect_secs": round(t_collect, 3),
                **tracker.report(),
                **(row or {}),
            }
            log_f.write(json.dumps(rec) + "\n")
            log_f.flush()
        if (time.monotonic() - t0) / 60.0 > args.max_minutes:
            capped = True
            break

    wall = time.monotonic() - t0
    final_eval = host_evaluate(
        eval_pool, lambda o: np.asarray(greedy_act(tf.constant(o, tf.float32)))
    )
    steady = iter_times[1:] or iter_times  # drop the tracing iteration
    sps = B / float(np.median(steady))
    summary = {
        "arm": "tf2_cpu_reference_shaped_ppo",
        "tf_version": tf.__version__,
        "env_steps": (it + 1) * B,
        "wall_secs": round(wall, 1),
        "steps_per_sec_median": round(sps, 1),
        "final_eval_return": round(final_eval, 1),
        "secs_to_3000": round(crossed_at[1], 1) if crossed_at else None,
        "steps_to_3000": crossed_at[0] if crossed_at else None,
        "capped": capped,
        "jax_arm": JAX_ARM,
        "tf_over_jax_steps_per_sec": round(sps / JAX_ARM["steps_per_sec"], 3),
        "jax_over_tf_wall_to_3000": (
            round(JAX_ARM["secs_to_3000"] / crossed_at[1], 3) if crossed_at else None
        ),
    }
    log_f.write(json.dumps({"summary": summary}) + "\n")
    log_f.close()
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
