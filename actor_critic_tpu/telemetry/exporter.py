"""Live run introspection over HTTP (ISSUE 3 tentpole).

A stdlib-only daemon HTTP thread (`http.server.ThreadingHTTPServer`, no
new dependencies) that makes a LIVE training process answer the
questions PR 1's telemetry could only answer post-mortem from files:

    GET /metrics        Prometheus text format: RSS, per-device memory,
                        the XLA recompile counter, every registered
                        sampler gauge (e.g. the shard pool's
                        utilization), the last observe() training row,
                        and iters/s + env-steps/s.
    GET /healthz        JSON liveness: uptime, watchdog staleness, the
                        innermost open telemetry span, age of the last
                        logged row. HTTP 503 once the watchdog is past
                        its timeout — `curl -f` probing from
                        scripts/tpu_watch.sh-style watchers just works.
    GET /profile?iters=N   Arm an on-demand windowed jax.profiler
                        capture (telemetry/profiler.py): the next N
                        training iterations are traced into
                        <telemetry-dir>/profile_XXX/ without restarting
                        the run. Returns the profiler status as JSON.

Enabled by `train.py --telemetry-port PORT` (0 picks an ephemeral port,
printed at startup and recorded as an `exporter_start` event). Binds
127.0.0.1 — remote scraping goes through an SSH tunnel like everything
else on these machines.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import TYPE_CHECKING, Optional
from urllib.parse import parse_qs, urlparse

if TYPE_CHECKING:  # import cycle: session constructs the exporter
    from actor_critic_tpu.telemetry.session import TelemetrySession

_PREFIX = "actor_critic"
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_ESC = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}

LOOPBACK_HOSTS = frozenset({"127.0.0.1", "localhost", "::1"})


def validate_bind(host: str, distributed: bool = False) -> str:
    """Gate non-loopback telemetry binds (ISSUE 16 satellite): the
    exporter serves process internals with no auth, so exposing it
    beyond the host must be an explicit fleet decision — a distributed
    run whose ranks scrape each other (`/fleetz`), stated by the caller
    passing `distributed=True`. Raises ValueError otherwise; returns
    the host unchanged when acceptable."""
    if host not in LOOPBACK_HOSTS and not distributed:
        raise ValueError(
            f"refusing non-loopback telemetry bind {host!r} without "
            "--distributed: /metrics exposes process internals with no "
            "auth — bind 127.0.0.1 and scrape through an SSH tunnel, "
            "or pass --distributed for a fleet whose ranks scrape "
            "each other"
        )
    return host


def _metric_name(*parts: str) -> str:
    return "_".join(
        _NAME_RE.sub("_", str(p)) for p in (_PREFIX, *parts) if p != ""
    )


def _escape_label(v: object) -> str:
    return "".join(_LABEL_ESC.get(c, c) for c in str(v))


def _line(name: str, value: float, labels: Optional[dict] = None) -> str:
    lbl = ""
    if labels:
        inner = ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in labels.items()
        )
        lbl = "{" + inner + "}"
    # numpy scalars repr as np.float64(...); coerce to a plain number.
    value = float(value)
    text = repr(int(value)) if value.is_integer() else repr(value)
    return f"{name}{lbl} {text}"


def render_metrics(session: "TelemetrySession") -> str:
    """One Prometheus text-format exposition of the session's live state.
    Pure function of (sampler row, session) so tests can render without
    a socket. A CLOSED session renders a tombstone (`up 0` and nothing
    else): the gauge registry and last-observation rows are process
    state that outlives the session, and re-serving them after close()
    is exactly the stale-last-event bug of ISSUE 16's small fix."""
    from actor_critic_tpu.telemetry import histo
    from actor_critic_tpu.telemetry.sampler import sample_row

    out: list[str] = []

    def emit(name: str, mtype: str, help_: str, rows: list) -> None:
        out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} {mtype}")
        out.extend(rows)

    if getattr(session, "closed", False):
        emit(
            _metric_name("up"), "gauge",
            "1 while the telemetry session is live",
            [_line(_metric_name("up"), 0)],
        )
        return "\n".join(out) + "\n"

    row = sample_row()
    emit(
        _metric_name("up"), "gauge", "1 while the telemetry session is live",
        [_line(_metric_name("up"), 1)],
    )
    emit(
        _metric_name("uptime_seconds"), "gauge",
        "seconds since the telemetry session started",
        [_line(_metric_name("uptime_seconds"), round(session.uptime_s(), 3))],
    )
    emit(
        _metric_name("xla_recompiles_total"), "counter",
        "XLA backend compilations observed by jax.monitoring",
        [_line(_metric_name("xla_recompiles_total"), row.get("recompiles", 0))],
    )
    # Persistent compilation cache (utils/compile_cache.py): hit/miss
    # counters plus whether a cache dir is enabled — the live view of
    # "is this leg compile-free" (ISSUE 4).
    from actor_critic_tpu.utils import compile_cache

    cstats = compile_cache.cache_stats()
    for field in ("hits", "misses"):
        name = _metric_name("compile_cache", f"{field}_total")
        emit(
            name, "counter",
            f"persistent compilation cache {field} "
            "(jax.monitoring cache events)",
            [_line(name, cstats[field])],
        )
    name = _metric_name("compile_cache_enabled")
    emit(
        name, "gauge",
        "1 when a persistent compilation cache dir is configured",
        [_line(name, int(compile_cache.enabled_dir() is not None))],
    )
    if "rss_bytes" in row:
        emit(
            _metric_name("rss_bytes"), "gauge", "process resident set size",
            [_line(_metric_name("rss_bytes"), row["rss_bytes"])],
        )
    dev_rows: dict[str, list[str]] = {"live_bytes": [], "peak_bytes": []}
    for d in row.get("devices", []):
        labels = {"device": d.get("id"), "platform": d.get("platform")}
        for field in dev_rows:
            if field in d:
                dev_rows[field].append(
                    _line(_metric_name("device", field), d[field], labels)
                )
    for field, rows in dev_rows.items():
        if rows:
            emit(
                _metric_name("device", field), "gauge",
                f"per-device {field} from memory_stats()", rows,
            )
    # Registered sampler gauges (dict-valued rows flatten one level:
    # host_pool -> actor_critic_host_pool_utilization etc.). Histogram
    # snapshots (telemetry/histo.py marker dicts, e.g. the serving
    # gauge's per-policy latency histograms) render as one
    # `_bucket/_sum/_count` family per metric name, all label sets
    # grouped under a single TYPE header.
    skip = {"ts", "recompiles", "rss_bytes", "devices"}
    hist_rows: dict[str, list[str]] = {}
    for key, value in row.items():
        if key in skip:
            continue
        if histo.is_snapshot(value):
            fields = [("", value)]
        elif isinstance(value, dict):
            fields = value.items()
        else:
            fields = [("", value)]
        for fk, fv in fields:
            if histo.is_snapshot(fv):
                name = _metric_name(key, fv.get("metric") or fk)
                hist_rows.setdefault(name, []).extend(
                    histo.render_prometheus(name, fv)
                )
                continue
            if isinstance(fv, bool) or not isinstance(fv, (int, float)):
                continue
            name = _metric_name(key, fk)
            emit(name, "gauge", f"registered gauge {key}", [_line(name, fv)])
    for name in sorted(hist_rows):
        emit(
            name, "histogram",
            "cumulative fixed-boundary histogram (mergeable across "
            "ranks: buckets sum exactly)",
            hist_rows[name],
        )
    for rk, rv in sorted(session.rates().items()):
        name = _metric_name(rk)
        emit(
            name, "gauge", "rate from the last two logged iterations",
            [_line(name, round(rv, 6))],
        )
    age = session.last_observe_age_s()
    if age is not None:
        # Without this a wedged run keeps exporting its LAST healthy
        # rates forever; scrapers alert on this age going flat-out.
        name = _metric_name("last_observe_age_seconds")
        emit(
            name, "gauge",
            "seconds since the last logged training row (rates above "
            "are stale once this grows past the log cadence)",
            [_line(name, round(age, 3))],
        )
    last = session.last_observation
    if last is not None:
        name = _metric_name("train_iteration")
        emit(
            name, "gauge", "iteration of the last logged training row",
            [_line(name, last["it"])],
        )
        name = _metric_name("train_metric")
        rows = [
            _line(name, v, {"metric": k})
            for k, v in sorted(last.items())
            if k not in ("it", "age_t")
            and not isinstance(v, bool)
            and isinstance(v, (int, float))
            and v == v  # NaN breaks the text format; drop the sample
        ]
        if rows:
            emit(name, "gauge", "last observe() training row", rows)
    return "\n".join(out) + "\n"


def healthz(session: "TelemetrySession") -> tuple[int, dict]:
    """(http_status, body) for /healthz: 503 only when an armed watchdog
    is past its timeout outside the startup grace — the same condition
    that is about to exit 42."""
    from actor_critic_tpu import telemetry
    from actor_critic_tpu.utils import watchdog

    body: dict = {
        "status": "ok",
        "uptime_s": round(session.uptime_s(), 3),
    }
    age = session.last_observe_age_s()
    if age is not None:
        body["last_observe_age_s"] = round(age, 3)
        body["last_iteration"] = session.last_observation["it"]
    last = telemetry.last_open_span()
    if last is not None:
        body["open_span"] = {"name": last[0], "open_s": round(last[1], 3)}
    if session.profiler is not None:
        body["profiler"] = session.profiler.status()
    wd = watchdog.status()
    status = 200
    if wd is not None:
        body["watchdog"] = wd
        if wd["staleness_s"] > wd["timeout_s"] and not wd["in_grace"]:
            body["status"] = "stalled"
            status = 503
    return status, body


class _Handler(BaseHTTPRequestHandler):
    # The exporter is a diagnostics sidecar: it must never write to the
    # run's stdout/stderr (stderr noise per scrape would swamp logs).
    def log_message(self, *args) -> None:
        pass

    def _respond(self, status: int, content_type: str, payload: str) -> None:
        data = payload.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _respond_json(self, status: int, body: dict) -> None:
        self._respond(
            status, "application/json", json.dumps(body, default=str) + "\n"
        )

    def do_GET(self) -> None:  # noqa: N802 (http.server contract)
        session = self.server.telemetry_session  # type: ignore[attr-defined]
        url = urlparse(self.path)
        try:
            if url.path == "/metrics":
                self._respond(
                    200,
                    "text/plain; version=0.0.4; charset=utf-8",
                    render_metrics(session),
                )
            elif url.path == "/healthz":
                self._respond_json(*healthz(session))
            elif url.path == "/profile":
                if session.profiler is None:
                    self._respond_json(
                        503, {"error": "profiling disabled for this session"}
                    )
                    return
                from actor_critic_tpu.telemetry.profiler import (
                    DEFAULT_PROFILE_ITERS,
                )

                q = parse_qs(url.query)
                try:
                    iters = int(q.get("iters", [DEFAULT_PROFILE_ITERS])[0])
                    if iters < 1:
                        raise ValueError
                except ValueError:
                    self._respond_json(
                        400, {"error": "iters must be a positive integer"}
                    )
                    return
                self._respond_json(202, session.profiler.arm(iters))
            else:
                self._respond_json(
                    404,
                    {"error": f"no route {url.path!r}",
                     "routes": ["/metrics", "/healthz", "/profile?iters=N"]},
                )
        except Exception as e:  # introspection must never kill the run
            try:
                self._respond_json(500, {"error": str(e)[:500]})
            except Exception:
                pass


class TelemetryExporter:
    """Owns the HTTP server + its daemon thread for one session."""

    def __init__(
        self,
        session: "TelemetrySession",
        port: int = 0,
        host: str = "127.0.0.1",
    ):
        self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        self._server.daemon_threads = True
        self._server.telemetry_session = session  # type: ignore[attr-defined]
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="telemetry-exporter",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5.0)
