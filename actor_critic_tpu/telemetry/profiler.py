"""On-demand device profiling + compile attribution (ISSUE 3).

Two introspection tools that run INSIDE a live training process:

- `WindowedProfiler` — an armable, windowed `jax.profiler` capture
  (the start/stop plumbing is `utils/profiling.start_trace` /
  `stop_trace`, the same pair `utils/profiling.trace` wraps). Arm it
  with `arm(iters)` — from the exporter's `/profile?iters=N` endpoint,
  from SIGUSR2 (`install_sigusr2`), or programmatically — and the next
  `tick()` (the training loops call one per iteration/dispatch) starts
  a capture that stops `iters` ticks later, leaving a Perfetto-openable
  trace under `<telemetry-dir>/profile_<n>/` and a `profile_done` event
  naming it. The training loop never blocks on an idle profiler: an
  unarmed `tick()` is one lock-free attribute read.

- a compile listener (`ensure_compile_introspection`) — wraps JAX's
  single compile funnel so every XLA compilation becomes a structured
  `compile` event carrying the jitted function's name, the abstract
  argument signature (the MLIR main function type — shapes AND dtypes),
  compile seconds, and the executable's `cost_analysis()` FLOPs/bytes.
  A recompile storm stops being a bare counter: consecutive `compile`
  events for the same name with different signatures name exactly which
  argument shape/dtype changed (scripts/run_report.py renders the
  attribution table). The funnel is internal JAX API, so the hook is
  best-effort: if the import shape changes, telemetry degrades to the
  `jax.monitoring` counter (sampler.py) instead of breaking the run.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Optional

DEFAULT_PROFILE_ITERS = 5

# Process-global compile log: like sampler.py's counter, the funnel wrap
# cannot be undone, so records accumulate per process (bounded ring) and
# any current session additionally gets each record as a `compile` event.
_COMPILE_RING_MAX = 256
_compile_records: list[dict] = []
_compile_total = 0  # monotonic; the ring above is capped
_compile_lock = threading.Lock()
_introspection_installed = False


def introspection_active() -> bool:
    """Whether the compile-funnel listener is installed (consumers like
    the chunk-wall ratchet fall back to heuristics when it isn't)."""
    return _introspection_installed


def compile_event_count() -> int:
    """Total compile events observed since the listener was installed
    (monotonic — unlike the capped record ring). Sampling this around a
    dispatch tells whether the dispatch paid XLA compile."""
    return _compile_total


class WindowedProfiler:
    """Armable N-iteration `jax.profiler` capture bound to one telemetry
    directory.

    States: idle → armed (`arm(iters)`) → active (first `tick()` after
    arming starts the trace) → idle (after `iters` more ticks, or
    `close()`). All transitions are lock-guarded; `arm` is safe from the
    exporter's HTTP thread and from a signal handler, `tick` runs on the
    training thread.
    """

    def __init__(self, directory: str):
        self.directory = os.fspath(directory)
        self._lock = threading.Lock()
        self._armed_iters = 0
        # Signal-safe arm request: SIGUSR2 runs its handler ON the main
        # (training) thread, which may already hold self._lock inside
        # tick() — taking the non-reentrant lock there would deadlock
        # the run. The handler therefore only WRITES (_pending_arm,
        # then the request counter), and tick() only READS, comparing
        # the counter against the last value it consumed: a
        # read-and-clear of a shared slot would race the handler (a
        # signal landing between tick's read and its zeroing store
        # would be silently discarded).
        self._pending_arm = DEFAULT_PROFILE_ITERS
        # jaxlint: thread-owned=signal (single writer BY DESIGN: only
        # the signal handler bumps the request counter — taking the
        # non-reentrant lock there would deadlock a handler landing
        # inside tick(); see the comment block above)
        self._arm_requests = 0
        self._arm_seen = 0
        self._remaining = 0
        self._active_dir: Optional[str] = None
        self._captures = 0
        self._t_start = 0.0

    # -- control surface (HTTP thread) ------------------------------------
    def arm(self, iters: int = DEFAULT_PROFILE_ITERS) -> dict:
        """Request a capture of the next `iters` training ticks. Returns
        the status dict; arming while armed/active is a no-op report,
        not an error (two probes racing must not corrupt a capture).
        Safe from other threads, NOT from a signal handler on the
        training thread — that's `request_arm`."""
        iters = max(int(iters), 1)
        with self._lock:
            if (
                self._armed_iters == 0
                and self._arm_requests == self._arm_seen
                and self._active_dir is None
            ):
                self._armed_iters = iters
            return self._status_locked()

    def request_arm(self, iters: int = DEFAULT_PROFILE_ITERS) -> None:
        """Lock-free arm request for signal handlers: two plain
        attribute stores (value, then counter — the handler is the only
        writer of both); the next tick() folds it into the armed state
        (ignored there if a window is already armed/active)."""
        self._pending_arm = max(int(iters), 1)
        self._arm_requests += 1

    def status(self) -> dict:
        with self._lock:
            return self._status_locked()

    def _status_locked(self) -> dict:
        requested = self._arm_requests != self._arm_seen
        armed = self._armed_iters or (requested and self._pending_arm)
        if self._active_dir is not None:
            state = "active"
        elif armed:
            state = "armed"
        else:
            state = "idle"
        out = {"state": state, "captures": self._captures}
        if armed:
            out["iters"] = armed
        if self._active_dir is not None:
            out["directory"] = self._active_dir
            out["remaining_iters"] = self._remaining
        return out

    # -- training-thread surface ------------------------------------------
    def tick(self) -> None:
        """One training iteration boundary. Starts a pending capture or
        counts an active one down; free when idle."""
        requests = self._arm_requests
        with self._lock:
            if (
                requests != self._arm_seen
                and self._armed_iters == 0
                and self._active_dir is None
            ):
                self._armed_iters = self._pending_arm
            self._arm_seen = requests
            if self._active_dir is not None:
                self._remaining -= 1
                if self._remaining > 0:
                    return
                path, dur = self._active_dir, time.perf_counter() - self._t_start
                self._active_dir = None
            elif self._armed_iters > 0:
                self._start_locked()
                return
            else:
                return
        self._stop(path, dur)

    def _start_locked(self) -> None:
        n, self._armed_iters = self._armed_iters, 0
        self._captures += 1
        path = os.path.join(self.directory, f"profile_{self._captures:03d}")
        try:
            from actor_critic_tpu.utils.profiling import start_trace

            start_trace(path)
        except Exception as e:  # profiler unavailable: report, don't die
            from actor_critic_tpu.telemetry import session as _session

            _session.event("profile_failed", error=str(e)[:500])
            return
        self._active_dir = path
        self._remaining = n
        self._t_start = time.perf_counter()
        from actor_critic_tpu.telemetry import session as _session

        _session.event("profile_start", path=path, iters=n)

    def _stop(self, path: str, dur_s: float) -> None:
        from actor_critic_tpu.telemetry import session as _session

        try:
            from actor_critic_tpu.utils.profiling import stop_trace

            stop_trace()
        except Exception as e:
            _session.event("profile_failed", path=path, error=str(e)[:500])
            return
        _session.complete_span(
            "profile", time.perf_counter() - dur_s, dur_s, path=path
        )
        _session.event(
            "profile_done", path=path, wall_s=round(dur_s, 3)
        )

    def close(self) -> None:
        """Stop a capture left active (session teardown mid-window)."""
        with self._lock:
            self._armed_iters = 0
            self._arm_seen = self._arm_requests
            if self._active_dir is None:
                return
            path, dur = self._active_dir, time.perf_counter() - self._t_start
            self._active_dir = None
        self._stop(path, dur)


def tick() -> None:
    """Per-iteration hook the training loops call: routes to the current
    session's profiler (no-op — one import-free attribute read — when no
    session or no profiler is installed)."""
    from actor_critic_tpu.telemetry import session as _session

    s = _session.current()
    if s is not None and s.profiler is not None:
        s.profiler.tick()


def install_sigusr2(iters: int = DEFAULT_PROFILE_ITERS) -> bool:
    """`kill -USR2 <pid>` arms a capture on the live run — the escape
    hatch when no --telemetry-port was passed. Main-thread only (POSIX
    signal contract); returns False where unsupported."""
    if threading.current_thread() is not threading.main_thread():
        return False
    usr2 = getattr(signal, "SIGUSR2", None)
    if usr2 is None:  # pragma: no cover - non-POSIX
        return False

    def _handler(signum, frame):
        from actor_critic_tpu.telemetry import session as _session

        s = _session.current()
        if s is not None and s.profiler is not None:
            # request_arm, not arm(): the handler runs ON the training
            # thread, which may hold the profiler lock inside tick().
            s.profiler.request_arm(iters)

    signal.signal(usr2, _handler)
    return True


# ---------------------------------------------------------------- compile
def _signature_of(computation) -> Optional[str]:
    """The MLIR main function type of a module about to be compiled —
    '(tensor<8x3xf32>, tensor<f32>) -> tensor<8x8xf32>' — i.e. the
    abstract shapes/dtypes this program is specialized to."""
    try:
        for op in computation.body.operations:
            try:
                if str(op.operation.attributes["sym_name"]) == '"main"':
                    return str(op.operation.attributes["function_type"])
            except KeyError:
                continue
    except Exception:
        pass
    return None


def _module_name(computation) -> str:
    try:
        return str(computation.operation.attributes["sym_name"]).strip('"')
    except Exception:
        return "?"


def _cost_fields(executable) -> dict:
    """FLOPs / bytes-accessed from the loaded executable's
    cost_analysis(); absent (not zero) where the backend reports none."""
    out: dict = {}
    try:
        ca = executable.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = ca.get("flops")
        if flops:
            out["flops"] = float(flops)
        nbytes = ca.get("bytes accessed")
        if nbytes:
            out["bytes_accessed"] = float(nbytes)
    except Exception:
        pass
    return out


def ensure_compile_introspection() -> bool:
    """Idempotently wrap JAX's compile funnel
    (`jax._src.compiler.compile_or_get_cached`) so each XLA compilation
    produces one structured record: process-global ring + a `compile`
    event on any installed session. Best-effort — returns False (and
    changes nothing) if the internal funnel moved."""
    global _introspection_installed
    with _compile_lock:
        if _introspection_installed:
            return True
        try:
            from jax._src import compiler as _jax_compiler

            original = _jax_compiler.compile_or_get_cached
        except (ImportError, AttributeError):
            return False
        # Cache-hit attribution needs the persistent-cache hit/miss
        # counters live even when no --compile-cache-dir was set.
        from actor_critic_tpu.utils.compile_cache import (
            ensure_cache_stats_listener,
        )

        ensure_cache_stats_listener()

        def _wrapped(*args, **kwargs):
            # Fully generic pass-through: the funnel is internal JAX
            # API, so a version that reorders parameters or goes
            # keyword-only must still compile — introspection extracts
            # what it can and never changes the call.
            name = sig = None
            try:
                computation = kwargs.get("computation", None)
                if computation is None and len(args) > 1:
                    computation = args[1]
                if computation is not None:
                    name = _module_name(computation)
                    sig = _signature_of(computation)
            except Exception:
                pass
            from actor_critic_tpu.utils.compile_cache import cache_stats

            hits_before = cache_stats()["hits"]
            t0 = time.perf_counter()
            executable = original(*args, **kwargs)
            record = {
                "name": name if name is not None else "?",
                "compile_s": round(time.perf_counter() - t0, 4),
                **_cost_fields(executable),
            }
            # Persistent-cache attribution: a hit event during the call
            # means this "compile" deserialized a cached executable, not
            # recompiled (concurrent compiles — e.g. the AOT warmup
            # thread — can misattribute a hit across threads; that skews
            # report cosmetics only, never the run).
            if cache_stats()["hits"] > hits_before:
                record["cache_hit"] = True
            if sig is not None:
                record["signature"] = sig[:2000]
            _record_compile(record)
            return executable

        _jax_compiler.compile_or_get_cached = _wrapped
        _introspection_installed = True
        return True


def _record_compile(record: dict) -> None:
    global _compile_total
    with _compile_lock:
        _compile_total += 1
        _compile_records.append(record)
        del _compile_records[:-_COMPILE_RING_MAX]
    from actor_critic_tpu.telemetry import session as _session

    try:
        _session.event("compile", **record)
    except Exception:
        pass  # telemetry must never take the run down


def compile_records() -> list[dict]:
    """Recent structured compile records (process-global ring)."""
    with _compile_lock:
        return list(_compile_records)
