"""Crash flight recorder: a bounded mmap'd ring of the last N telemetry
records per process (ISSUE 16).

fleetsan's SIGKILL schedules expose the observability gap this closes:
a killed rank's line-buffered JSONL sinks keep everything up to the
last flush, but the question a postmortem actually asks — *what was the
process doing in its final seconds* — needs the records that were still
in flight. The recorder keeps a fixed-size ring of recent spans, health
events, and gauge ticks in a file-backed ``mmap`` (MAP_SHARED): every
``record()`` lands in the kernel page cache immediately, so the ring
survives the PROCESS dying by any means, including SIGKILL, and a
survivor can ``harvest()`` it from the dead rank's telemetry directory.
(Page cache, not storage: a machine losing power is the checkpoint
layer's problem, not this one's.)

Ring layout (little-endian):

    [0:8)    magic  b"ACFR0001"
    [8:12)   u32 slot_size
    [12:16)  u32 nslots
    [16:24)  u64 seq  — records ever written; slot = (seq-1) % nslots
    then nslots slots of slot_size bytes, each
    [0:4)    u32 payload length (0 = never written)
    [4:4+len) UTF-8 JSON record, truncated to fit

The writer fills the slot BEFORE bumping ``seq`` so a reader that races
a live writer sees at most one torn slot, and a torn slot fails JSON
decode and is skipped — harvest never propagates garbage.

``dump()`` turns the ring into a durable (fsynced) ``flight_dump_*.json``
— called on watchdog stall, divergence, and fatal signals by the
session wiring; ``harvest()`` + ``write_dump()`` do the same for a ring
whose owner is already dead (the fleetsan driver).
"""

from __future__ import annotations

import itertools
import json
import mmap
import os
import signal
import struct
import threading
import time
from typing import Optional

from actor_critic_tpu.utils.numguard import safe_json_row

_MAGIC = b"ACFR0001"
_HEADER = struct.Struct("<8sII")   # magic, slot_size, nslots
_SEQ = struct.Struct("<Q")
_SEQ_OFF = _HEADER.size
_RING_OFF = _SEQ_OFF + _SEQ.size
_LEN = struct.Struct("<I")

DEFAULT_SLOTS = 512
DEFAULT_SLOT_SIZE = 768
RING_FILENAME = "flight.ring"


class FlightRecorder:
    """Writer side: one per process, owning one ring file."""

    def __init__(
        self,
        path: str | os.PathLike,
        slots: int = DEFAULT_SLOTS,
        slot_size: int = DEFAULT_SLOT_SIZE,
        meta: Optional[dict] = None,
    ):
        self.path = os.fspath(path)
        self._slots = int(slots)
        self._slot_size = int(slot_size)
        if self._slots < 8 or self._slot_size < 64:
            raise ValueError("ring too small to be a useful recorder")
        self._lock = threading.Lock()
        self._closed = False
        size = _RING_OFF + self._slots * self._slot_size
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # O_CREAT without O_TRUNC + explicit truncate: recreate the ring
        # fresh for THIS process (a stale ring from a previous run must
        # not mix its records into this run's final-seconds window).
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._mm[0:_RING_OFF] = (
            _HEADER.pack(_MAGIC, self._slot_size, self._slots)
            + _SEQ.pack(0)
        )
        # Dump bookkeeping + identifying metadata (seed, rank, ...)
        # recorded as slot 0 so even a harvested ring names its run.
        self._meta = dict(meta or {})
        # Dump numbering via itertools.count: next() is atomic at the C
        # level, and dump() must stay lock-free — it runs inside fatal
        # signal handlers that may have interrupted a record() holding
        # self._lock on this very thread (a plain Lock would deadlock).
        self._dump_count = itertools.count(1)
        if self._meta:
            self.record("meta", **self._meta)

    # -- write side ---------------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        """Append one record. Never raises (a telemetry mirror must not
        take the instrumented path down); oversize payloads truncate by
        dropping fields, keeping at least {t, kind}."""
        if self._closed:
            return
        row = {"t": round(time.time(), 6), "kind": kind, **fields}
        try:
            data = safe_json_row(row, default=str).encode()
        except Exception:
            return
        limit = self._slot_size - _LEN.size
        if len(data) > limit:
            try:
                data = safe_json_row(
                    {"t": row["t"], "kind": kind, "truncated": True},
                    default=str,
                ).encode()[:limit]
            except Exception:
                return
        try:
            with self._lock:
                if self._closed:
                    return
                seq = _SEQ.unpack_from(self._mm, _SEQ_OFF)[0]
                off = _RING_OFF + (seq % self._slots) * self._slot_size
                self._mm[off:off + _LEN.size] = _LEN.pack(len(data))
                self._mm[off + _LEN.size:off + _LEN.size + len(data)] = data
                # seq LAST: a harvester racing this write sees the old
                # count (missing the newest record) or the new count
                # with the slot already complete — never a half-record
                # counted as valid.
                _SEQ.pack_into(self._mm, _SEQ_OFF, seq + 1)
        except (ValueError, OSError):
            pass  # closed mmap / ENOSPC on a hole-y fs: drop the record

    def mirror(self, evt: dict) -> None:
        """SpanTracer mirror hook: one completed span/flow event dict
        becomes a compact ring record (args ride along — they carry the
        trace ids a postmortem joins on)."""
        kind = "span" if evt.get("ph") == "X" else "trace_evt"
        fields = {
            k: evt[k] for k in ("name", "ph", "ts", "dur", "args")
            if k in evt
        }
        self.record(kind, **fields)

    def record_gauges(self, row: dict) -> None:
        """ResourceSampler mirror hook: one sampler row (flattened to
        numbers only — device dicts and nested gauges are the sinks'
        job; the ring wants the trend, cheap)."""
        flat = {}
        for k, v in row.items():
            if isinstance(v, bool) or k == "ts":
                continue
            if isinstance(v, (int, float)):
                flat[k] = v
            elif isinstance(v, dict):
                for fk, fv in v.items():
                    if not isinstance(fv, bool) and isinstance(
                        fv, (int, float)
                    ):
                        flat[f"{k}_{fk}"] = fv
        self.record("gauges", **flat)

    # -- dump side ----------------------------------------------------------

    def dump(self, reason: str, directory: Optional[str] = None) -> str:
        """Write the ring's current contents as a durable JSON dump next
        to the ring (or into `directory`); returns the dump path ("" on
        failure — the stall path must never raise)."""
        try:
            records = _decode(bytes(self._mm))
            out_dir = directory or os.path.dirname(self.path) or "."
            path = os.path.join(
                out_dir,
                f"flight_dump_{reason}_{next(self._dump_count)}.json",
            )
            return write_dump(path, records, reason=reason, meta=self._meta)
        except Exception:
            return ""

    def install_signal_dump(
        self, signals: tuple = (signal.SIGTERM,), directory: Optional[str] = None
    ) -> None:
        """Chain a dump onto fatal-signal delivery (main thread only —
        signal.signal raises elsewhere, reported as a no-op). SIGKILL
        needs no handler: that is what post-mortem harvest() is for."""
        for sig in signals:
            try:
                prev = signal.getsignal(sig)

                def _handler(signum, frame, _prev=prev):
                    self.dump(f"signal_{signum}", directory)
                    if callable(_prev):
                        _prev(signum, frame)
                    else:
                        signal.signal(signum, signal.SIG_DFL)
                        signal.raise_signal(signum)

                signal.signal(sig, _handler)
            except (ValueError, OSError):
                pass  # not the main thread / unsupported signal

    def close(self) -> None:
        with self._lock:
            self._closed = True
            try:
                self._mm.flush()
                self._mm.close()
            except (ValueError, OSError):
                pass


# -- read side (works on a live or dead process's ring) ----------------------


def _decode(buf: bytes) -> list[dict]:
    if len(buf) < _RING_OFF:
        return []
    magic, slot_size, nslots = _HEADER.unpack_from(buf, 0)
    if magic != _MAGIC or slot_size <= _LEN.size or nslots <= 0:
        return []
    if len(buf) < _RING_OFF + nslots * slot_size:
        return []
    seq = _SEQ.unpack_from(buf, _SEQ_OFF)[0]
    n = min(seq, nslots)
    records: list[dict] = []
    # Oldest surviving record first: slots [seq-n, seq) in write order.
    for s in range(seq - n, seq):
        off = _RING_OFF + (s % nslots) * slot_size
        length = _LEN.unpack_from(buf, off)[0]
        if not 0 < length <= slot_size - _LEN.size:
            continue
        raw = buf[off + _LEN.size:off + _LEN.size + length]
        try:
            rec = json.loads(raw)
        except (ValueError, UnicodeDecodeError):
            continue  # torn slot (writer died mid-write): skip, keep rest
        if isinstance(rec, dict):
            records.append(rec)
    return records


def harvest(ring_path: str | os.PathLike) -> list[dict]:
    """Decode a ring file — typically a DEAD process's (the fleetsan
    SIGKILL driver): returns its surviving records oldest-first.
    Empty list when the file is missing/foreign/empty."""
    try:
        with open(ring_path, "rb") as f:
            buf = f.read()
    except OSError:
        return []
    return _decode(buf)


def write_dump(
    path: str | os.PathLike,
    records: list[dict],
    reason: str = "harvest",
    meta: Optional[dict] = None,
) -> str:
    """Durably (write + fsync + rename) persist harvested records as a
    flight dump run_report.py renders. Returns the final path."""
    path = os.fspath(path)
    body = {
        "flight_dump": True,
        "reason": reason,
        "dumped_at": round(time.time(), 3),
        "meta": dict(meta or {}),
        "records": records,
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(body, f, default=str)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def find_dumps(directory: str | os.PathLike) -> list[str]:
    """flight_dump_*.json paths under `directory` (sorted) — the
    run_report/tier-1 discovery helper."""
    directory = os.fspath(directory)
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(
        os.path.join(directory, n)
        for n in names
        if n.startswith("flight_dump_") and n.endswith(".json")
    )
