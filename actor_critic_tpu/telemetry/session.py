"""`TelemetrySession` and the module-level current-session API.

The training loops are instrumented UNCONDITIONALLY with the functions
here (`span`, `instant`, `observe`); each call is near-free when no
session is installed — a span is two `time.perf_counter()` reads plus a
list push/pop, kept even without a session so the stall watchdog can
always name the phase that hung. Installing a session (`train.py
--telemetry-dir`) turns the same calls into JSONL emission:

    <telemetry-dir>/spans.jsonl      Chrome-trace phase events
    <telemetry-dir>/resources.jsonl  RSS / device memory / recompiles
    <telemetry-dir>/events.jsonl     health + lifecycle events

Open-span stacks are PER-THREAD (the async actor–learner services run
collection spans on actor threads — ISSUE 6); the sampler and watchdog
threads read a snapshot across all of them, so a diagnosis line names
the most recently entered phase anywhere in the process.
"""

from __future__ import annotations

import os
import threading
import time
from typing import IO, Optional

from actor_critic_tpu.telemetry.health import (
    DivergenceMonitor,
    ThroughputMonitor,
)
from actor_critic_tpu.telemetry.sampler import (
    ResourceSampler,
    ensure_compile_listener,
)
from actor_critic_tpu.telemetry.spans import SpanTracer
from actor_critic_tpu.utils.numguard import safe_json_row

_SESSION: Optional["TelemetrySession"] = None

# Event kinds that are a run's last words: after writing one, all three
# sinks are flushed AND fsynced so a SIGKILL'd run (or a machine losing
# power mid-stall) keeps its final stall/divergence evidence on disk —
# line buffering alone only guarantees the row reached the page cache.
DURABLE_EVENT_KINDS = frozenset(
    {"stall", "divergence", "throughput_regression"}
)

# Open-span stacks, one per thread: (name, entry perf_counter). A
# single global list was correct while only the training thread opened
# spans, but the async actor–learner services (algos/traj_queue.py,
# ISSUE 6) run collection spans on actor THREADS — interleaved
# push/pops on one list leave permanently stranded entries. Each thread
# pushes/pops its own stack; the watchdog/exporter threads read a
# snapshot across all of them. The registry lock guards only
# stack creation/removal (the per-span hot path is an append/pop on a
# list no other thread mutates).
_OPEN_STACKS: dict[int, list[tuple[str, float]]] = {}
_OPEN_LOCK = threading.Lock()


def _thread_stack() -> list[tuple[str, float]]:
    ident = threading.get_ident()
    stack = _OPEN_STACKS.get(ident)
    if stack is None:
        with _OPEN_LOCK:
            stack = _OPEN_STACKS.setdefault(ident, [])
    return stack


class _Span:
    """Context manager for one phase span. Always tracks the open-span
    stack; emits a Chrome-trace complete event only while a session is
    installed at EXIT time (so a session installed mid-span still
    records it)."""

    __slots__ = ("_name", "_args", "_t0")

    def __init__(self, name: str, args: Optional[dict]):
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        _thread_stack().append((self._name, self._t0))
        return self

    def __exit__(self, *exc) -> None:
        dur = time.perf_counter() - self._t0
        stack = _thread_stack()
        if stack and stack[-1][0] == self._name:
            stack.pop()
        if not stack:
            # Drop the empty stack so short-lived actor threads don't
            # accumulate registry entries across a run.
            with _OPEN_LOCK:
                if not _OPEN_STACKS.get(threading.get_ident()):
                    _OPEN_STACKS.pop(threading.get_ident(), None)
        s = _SESSION
        if s is not None:
            s.tracer.complete(self._name, self._t0, dur, self._args)


def span(name: str, **args) -> _Span:
    """`with telemetry.span("update", it=12):` around a loop phase."""
    return _Span(name, args or None)


def instant(name: str, **args) -> None:
    """Mark a phase with no separable host duration (fused rollouts)."""
    s = _SESSION
    if s is not None:
        s.tracer.instant(name, args or None)


def complete_span(name: str, start_pc: float, dur_s: float, **args) -> None:
    """Emit a Chrome-trace complete event for a span measured EXTERNALLY
    (e.g. a sharded-pool worker's busy time within a collection block,
    aggregated host-side). `start_pc` is a `perf_counter()` reading.
    Unlike `span()`, it does not touch the open-span stack — the
    measured work happened in another process."""
    s = _SESSION
    if s is not None:
        s.tracer.complete(name, start_pc, dur_s, args or None)


def event(kind: str, **fields) -> None:
    """Append a structured event row to events.jsonl (no-op untracked)."""
    s = _SESSION
    if s is not None:
        s.event(kind, **fields)


def observe(it: int, metrics: dict) -> None:
    """Feed one logged iteration to the health monitors (no-op when no
    session is installed)."""
    s = _SESSION
    if s is not None:
        s.observe(it, metrics)


def current() -> Optional["TelemetrySession"]:
    return _SESSION


def set_current(session: Optional["TelemetrySession"]) -> None:
    global _SESSION
    _SESSION = session


def open_spans() -> list[str]:
    """Names of THIS thread's currently open spans, outermost first."""
    return [
        name
        for name, _ in list(_OPEN_STACKS.get(threading.get_ident(), []))
    ]


def last_open_span() -> Optional[tuple[str, float]]:
    """(name, seconds open) of the innermost open span across EVERY
    thread — the most recently entered phase is the one executing when
    a watchdog/exporter thread asks what the process is doing."""
    with _OPEN_LOCK:
        stacks = [list(s) for s in _OPEN_STACKS.values()]
    candidates = [s[-1] for s in stacks if s]
    if not candidates:
        return None
    name, t0 = max(candidates, key=lambda x: x[1])
    return name, time.perf_counter() - t0


def stall_report(stalled_s: float = 0.0) -> str:
    """One diagnosis clause for the watchdog's exit-42 message: names the
    phase that was open when progress stopped. Also records a `stall`
    event while a session is installed (the files are line-buffered, so
    the row survives the `os._exit` that follows)."""
    last = last_open_span()
    s = _SESSION
    if s is not None:
        fields = {"stalled_s": round(stalled_s, 1)}
        if last is not None:
            fields.update(phase=last[0], phase_open_s=round(last[1], 1))
        try:
            s.event("stall", **fields)
        except Exception:
            pass
    if last is None:
        return ""
    return (
        f"; last open telemetry span: {last[0]!r} "
        f"(open {last[1]:.1f}s)"
    )


class TelemetrySession:
    """Owns the three telemetry sinks for one run.

    `directory` is created; the files are opened line-buffered append so
    every completed write survives even an `os._exit` teardown. Install
    with `set_current` (or use as a context manager) to route the
    module-level `span`/`instant`/`event`/`observe` calls here.
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        run_info: Optional[dict] = None,
        resource_interval_s: float = 5.0,
        sample_resources: bool = True,
        throughput_drop_threshold: float = 0.5,
        serve_port: Optional[int] = None,
        serve_host: str = "127.0.0.1",
        profile: bool = True,
        flight: bool = True,
    ):
        self.directory = os.fspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        # Tombstone flag the exporter keys off: once close() runs, a
        # still-scraping /metrics must see `up 0`, not the process-global
        # gauges of a session that no longer exists (ISSUE 16).
        self.closed = False
        self._spans_fh = self._open("spans.jsonl")
        self._resources_fh = self._open("resources.jsonl")
        self._events_fh = self._open("events.jsonl")
        # events.jsonl has MULTIPLE writers (health monitors on the
        # training thread, stall_report on the watchdog thread);
        # unlocked writes could interleave into torn lines and lose the
        # stall evidence the sink exists to preserve.
        self._events_lock = threading.Lock()
        self.tracer = SpanTracer(self._spans_fh)
        # Crash flight recorder (telemetry/flight.py): an mmap'd ring of
        # the last N spans/events/gauge ticks that survives SIGKILL for
        # post-mortem harvest, dumped to JSON on stall/divergence. The
        # mirrors feed it; line-buffered sinks stay the durable record.
        # jaxlint: thread-owned=owner (only the session-owning thread
        # writes this — set in __init__, cleared in close(); sampler and
        # event() callers on other threads READ it, and FlightRecorder's
        # record/dump/close are individually no-ops after close, so a
        # stale read during shutdown degrades to a dropped mirror record)
        self.flight = None
        if flight:
            from actor_critic_tpu.telemetry.flight import (
                RING_FILENAME,
                FlightRecorder,
            )

            try:
                self.flight = FlightRecorder(
                    os.path.join(self.directory, RING_FILENAME),
                    meta={"pid": os.getpid(), **(run_info or {})},
                )
                self.tracer.mirror = self.flight.mirror
            except Exception:
                self.flight = None  # ring creation failing never blocks a run
        self._t0 = time.monotonic()
        # Live-introspection state the exporter reads: the most recent
        # observe() row and the rates derived from consecutive rows.
        self.last_observation: Optional[dict] = None
        # jaxlint: thread-owned=train (single writer: observe() runs on
        # the training thread only; the exporter thread snapshots via
        # rates()'s dict() copy, one C-level call under the GIL, and a
        # one-row-stale read is fine for a metrics scrape)
        self._rates: dict[str, float] = {}
        self._prev_observe: Optional[tuple[int, Optional[float], float]] = None
        # The recompile counter must count even when the sampler thread
        # is off (the exporter's /metrics reads it directly).
        ensure_compile_listener()
        self.event("session_start", **(run_info or {}))
        self._monitors = [
            ThroughputMonitor(
                self._emit_health, drop_threshold=throughput_drop_threshold
            ),
            DivergenceMonitor(self._emit_health),
        ]
        # jaxlint: thread-owned=train (session lifecycle — install and
        # close() — is owned by the run-owning thread; daemon threads
        # only read these handles, and a close() racing itself is a
        # caller bug the None-ing below keeps idempotent anyway)
        self.profiler = None
        if profile:
            from actor_critic_tpu.telemetry.profiler import (
                WindowedProfiler,
                ensure_compile_introspection,
            )

            self.profiler = WindowedProfiler(self.directory)
            ensure_compile_introspection()
        # jaxlint: thread-owned=train (same lifecycle contract as
        # profiler above)
        self.sampler: Optional[ResourceSampler] = None
        if sample_resources:
            self.sampler = ResourceSampler(
                self._resources_fh,
                interval_s=resource_interval_s,
                mirror=(
                    None if self.flight is None
                    else self.flight.record_gauges
                ),
            ).start()
        # jaxlint: thread-owned=train (same lifecycle contract as
        # profiler above)
        self.exporter = None
        if serve_port is not None:
            from actor_critic_tpu.telemetry.exporter import TelemetryExporter

            self.exporter = TelemetryExporter(
                self, port=serve_port, host=serve_host
            )
            self.event("exporter_start", port=self.exporter.port)

    @property
    def exporter_port(self):
        """The exporter's ACTUAL bound port (with serve_port=0 the
        OS-assigned ephemeral one — ISSUE 10 satellite: scripts and CI
        read it here instead of racing for a fixed port), or None when
        no exporter is serving."""
        return None if self.exporter is None else self.exporter.port

    def _open(self, name: str) -> IO[str]:
        return open(os.path.join(self.directory, name), "a", buffering=1)

    def _emit_health(self, kind: str, **fields) -> None:
        self.event(kind, **fields)

    def event(self, kind: str, **fields) -> None:
        row = {"ts": round(time.time(), 3), "kind": kind, **fields}
        try:
            # safe_json_row: a non-finite event field (a NaN loss in a
            # divergence event's payload!) becomes null instead of the
            # WHOLE event vanishing — losing exactly the forensic row
            # the run needed (ISSUE 14).
            line = safe_json_row(row, default=str) + "\n"
        except (TypeError, ValueError):
            return  # unserializable field; never raise
        # Bounded acquire, not `with`: the watchdog thread calls this
        # from the stall path while the training thread may be wedged
        # INSIDE an events write (hung filesystem — the very stall class
        # the watchdog escapes). Blocking here would stop the exit-42
        # escape; dropping the row after 1s cannot.
        if not self._events_lock.acquire(timeout=1.0):
            return
        try:
            self._events_fh.write(line)
        except (OSError, ValueError):
            pass  # disk full / closed mid-shutdown
        finally:
            self._events_lock.release()
        if self.flight is not None:
            self.flight.record(f"event_{kind}", **fields)
        if kind in DURABLE_EVENT_KINDS:
            # Last-words path: dump the flight ring BEFORE the fsync so
            # a stall that ends in os._exit leaves both the durable
            # sinks and a rendered flight_dump_*.json behind.
            if self.flight is not None:
                self.flight.dump(kind)
            self._durable_flush()

    def _durable_flush(self, timeout_s: float = 2.0) -> None:
        """Flush + fsync all three sinks so the row that was just written
        survives a SIGKILL. Runs in a bounded side thread: the stall path
        calls event() from the watchdog thread moments before os._exit,
        and an fsync hanging on the very filesystem stall being reported
        must not block the exit-42 escape."""

        def _sync():
            for fh in (self._spans_fh, self._resources_fh, self._events_fh):
                try:
                    fh.flush()
                    os.fsync(fh.fileno())
                except (OSError, ValueError):
                    pass  # closed, or a sink on a non-fsyncable fs

        t = threading.Thread(target=_sync, daemon=True)
        t.start()
        t.join(timeout=timeout_s)

    def observe(self, it: int, metrics: dict) -> None:
        now = time.monotonic() - self._t0
        for m in self._monitors:
            try:
                m.observe(it, metrics, now)
            except Exception:
                pass  # telemetry must never take the run down
        # Live-introspection snapshot for /metrics: the row itself plus
        # iters/s and env-steps/s from consecutive observe() calls.
        env_steps = metrics.get("env_steps")
        try:
            env_steps = None if env_steps is None else float(env_steps)
        except (TypeError, ValueError):
            env_steps = None
        prev = self._prev_observe
        if prev is not None:
            p_it, p_steps, p_t = prev
            dt = now - p_t
            if it > p_it and dt > 0:
                self._rates["iters_per_s"] = (it - p_it) / dt
                if env_steps is not None and p_steps is not None:
                    self._rates["env_steps_per_s"] = (
                        env_steps - p_steps
                    ) / dt
        self._prev_observe = (it, env_steps, now)
        # Reserved keys LAST: a training metric named "it"/"age_t" must
        # not overwrite the bookkeeping /healthz and /metrics read.
        self.last_observation = {**metrics, "it": it, "age_t": now}

    def rates(self) -> dict[str, float]:
        """{'iters_per_s', 'env_steps_per_s'} from the last two observe()
        calls (empty until two logged iterations have landed)."""
        return dict(self._rates)

    def uptime_s(self) -> float:
        return time.monotonic() - self._t0

    def last_observe_age_s(self) -> Optional[float]:
        if self.last_observation is None:
            return None
        return self.uptime_s() - self.last_observation["age_t"]

    def close(self) -> None:
        if self.exporter is not None:
            self.exporter.close()
            self.exporter = None
        if self.profiler is not None:
            self.profiler.close()
            self.profiler = None
        if self.sampler is not None:
            self.sampler.stop()
            self.sampler = None
        self.event("session_end")
        if self.flight is not None:
            self.tracer.mirror = None
            self.flight.close()
            self.flight = None
        # Tombstone BEFORE closing the sinks: a /metrics scrape racing
        # shutdown (the exporter above is gone, but a standalone serving
        # exporter may still hold this session) must flip to `up 0`
        # rather than re-serve the dead run's last rates and gauges.
        self.closed = True
        self.last_observation = None
        self._rates = {}
        self._prev_observe = None
        for fh in (self._spans_fh, self._resources_fh, self._events_fh):
            try:
                fh.close()
            except Exception:
                pass
        if _SESSION is self:
            set_current(None)

    def __enter__(self) -> "TelemetrySession":
        set_current(self)
        return self

    def __exit__(self, *exc) -> None:
        self.close()
