"""`TelemetrySession` and the module-level current-session API.

The training loops are instrumented UNCONDITIONALLY with the functions
here (`span`, `instant`, `observe`); each call is near-free when no
session is installed — a span is two `time.perf_counter()` reads plus a
list push/pop, kept even without a session so the stall watchdog can
always name the phase that hung. Installing a session (`train.py
--telemetry-dir`) turns the same calls into JSONL emission:

    <telemetry-dir>/spans.jsonl      Chrome-trace phase events
    <telemetry-dir>/resources.jsonl  RSS / device memory / recompiles
    <telemetry-dir>/events.jsonl     health + lifecycle events

The open-span stack is a plain module-global (the training loop is
single-threaded; the sampler and watchdog threads only read it), so a
cross-thread reader always sees a consistent-enough snapshot for a
diagnosis line.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import IO, Optional

from actor_critic_tpu.telemetry.health import (
    DivergenceMonitor,
    ThroughputMonitor,
)
from actor_critic_tpu.telemetry.sampler import ResourceSampler
from actor_critic_tpu.telemetry.spans import SpanTracer

_SESSION: Optional["TelemetrySession"] = None

# Open-span stack: (name, entry perf_counter). Appended/popped by _Span
# on the training thread; read by the watchdog thread on a stall.
_OPEN: list[tuple[str, float]] = []


class _Span:
    """Context manager for one phase span. Always tracks the open-span
    stack; emits a Chrome-trace complete event only while a session is
    installed at EXIT time (so a session installed mid-span still
    records it)."""

    __slots__ = ("_name", "_args", "_t0")

    def __init__(self, name: str, args: Optional[dict]):
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        _OPEN.append((self._name, self._t0))
        return self

    def __exit__(self, *exc) -> None:
        dur = time.perf_counter() - self._t0
        if _OPEN and _OPEN[-1][0] == self._name:
            _OPEN.pop()
        s = _SESSION
        if s is not None:
            s.tracer.complete(self._name, self._t0, dur, self._args)


def span(name: str, **args) -> _Span:
    """`with telemetry.span("update", it=12):` around a loop phase."""
    return _Span(name, args or None)


def instant(name: str, **args) -> None:
    """Mark a phase with no separable host duration (fused rollouts)."""
    s = _SESSION
    if s is not None:
        s.tracer.instant(name, args or None)


def complete_span(name: str, start_pc: float, dur_s: float, **args) -> None:
    """Emit a Chrome-trace complete event for a span measured EXTERNALLY
    (e.g. a sharded-pool worker's busy time within a collection block,
    aggregated host-side). `start_pc` is a `perf_counter()` reading.
    Unlike `span()`, it does not touch the open-span stack — the
    measured work happened in another process."""
    s = _SESSION
    if s is not None:
        s.tracer.complete(name, start_pc, dur_s, args or None)


def event(kind: str, **fields) -> None:
    """Append a structured event row to events.jsonl (no-op untracked)."""
    s = _SESSION
    if s is not None:
        s.event(kind, **fields)


def observe(it: int, metrics: dict) -> None:
    """Feed one logged iteration to the health monitors (no-op when no
    session is installed)."""
    s = _SESSION
    if s is not None:
        s.observe(it, metrics)


def current() -> Optional["TelemetrySession"]:
    return _SESSION


def set_current(session: Optional["TelemetrySession"]) -> None:
    global _SESSION
    _SESSION = session


def open_spans() -> list[str]:
    """Names of currently open spans, outermost first."""
    return [name for name, _ in list(_OPEN)]


def last_open_span() -> Optional[tuple[str, float]]:
    """(name, seconds open) of the innermost open span, if any."""
    snapshot = list(_OPEN)
    if not snapshot:
        return None
    name, t0 = snapshot[-1]
    return name, time.perf_counter() - t0


def stall_report(stalled_s: float = 0.0) -> str:
    """One diagnosis clause for the watchdog's exit-42 message: names the
    phase that was open when progress stopped. Also records a `stall`
    event while a session is installed (the files are line-buffered, so
    the row survives the `os._exit` that follows)."""
    last = last_open_span()
    s = _SESSION
    if s is not None:
        fields = {"stalled_s": round(stalled_s, 1)}
        if last is not None:
            fields.update(phase=last[0], phase_open_s=round(last[1], 1))
        try:
            s.event("stall", **fields)
        except Exception:
            pass
    if last is None:
        return ""
    return (
        f"; last open telemetry span: {last[0]!r} "
        f"(open {last[1]:.1f}s)"
    )


class TelemetrySession:
    """Owns the three telemetry sinks for one run.

    `directory` is created; the files are opened line-buffered append so
    every completed write survives even an `os._exit` teardown. Install
    with `set_current` (or use as a context manager) to route the
    module-level `span`/`instant`/`event`/`observe` calls here.
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        run_info: Optional[dict] = None,
        resource_interval_s: float = 5.0,
        sample_resources: bool = True,
        throughput_drop_threshold: float = 0.5,
    ):
        self.directory = os.fspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._spans_fh = self._open("spans.jsonl")
        self._resources_fh = self._open("resources.jsonl")
        self._events_fh = self._open("events.jsonl")
        # events.jsonl has MULTIPLE writers (health monitors on the
        # training thread, stall_report on the watchdog thread);
        # unlocked writes could interleave into torn lines and lose the
        # stall evidence the sink exists to preserve.
        self._events_lock = threading.Lock()
        self.tracer = SpanTracer(self._spans_fh)
        self._t0 = time.monotonic()
        self.event("session_start", **(run_info or {}))
        self._monitors = [
            ThroughputMonitor(
                self._emit_health, drop_threshold=throughput_drop_threshold
            ),
            DivergenceMonitor(self._emit_health),
        ]
        self.sampler: Optional[ResourceSampler] = None
        if sample_resources:
            self.sampler = ResourceSampler(
                self._resources_fh, interval_s=resource_interval_s
            ).start()

    def _open(self, name: str) -> IO[str]:
        return open(os.path.join(self.directory, name), "a", buffering=1)

    def _emit_health(self, kind: str, **fields) -> None:
        self.event(kind, **fields)

    def event(self, kind: str, **fields) -> None:
        row = {"ts": round(time.time(), 3), "kind": kind, **fields}
        try:
            line = json.dumps(row, allow_nan=False, default=str) + "\n"
        except (TypeError, ValueError):
            return  # non-finite / unserializable field; never raise
        # Bounded acquire, not `with`: the watchdog thread calls this
        # from the stall path while the training thread may be wedged
        # INSIDE an events write (hung filesystem — the very stall class
        # the watchdog escapes). Blocking here would stop the exit-42
        # escape; dropping the row after 1s cannot.
        if not self._events_lock.acquire(timeout=1.0):
            return
        try:
            self._events_fh.write(line)
        except ValueError:
            pass  # closed mid-shutdown
        finally:
            self._events_lock.release()

    def observe(self, it: int, metrics: dict) -> None:
        now = time.monotonic() - self._t0
        for m in self._monitors:
            try:
                m.observe(it, metrics, now)
            except Exception:
                pass  # telemetry must never take the run down

    def close(self) -> None:
        if self.sampler is not None:
            self.sampler.stop()
            self.sampler = None
        self.event("session_end")
        for fh in (self._spans_fh, self._resources_fh, self._events_fh):
            try:
                fh.close()
            except Exception:
                pass
        if _SESSION is self:
            set_current(None)

    def __enter__(self) -> "TelemetrySession":
        set_current(self)
        return self

    def __exit__(self, *exc) -> None:
        self.close()
