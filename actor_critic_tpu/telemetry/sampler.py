"""Daemon resource sampler (`resources.jsonl`).

A background thread records, on a fixed cadence, the signals that
explain the two classic silent run-killers — memory creep and recompile
storms:

- process RSS (``/proc/self/statm``; peak-RSS ``getrusage`` fallback),
- per-device live/peak bytes from ``jax.local_devices()[i]
  .memory_stats()`` (``None`` on backends without allocator stats, e.g.
  CPU — recorded as absent, not zero),
- a monotonically increasing XLA recompile counter fed by
  ``jax.monitoring`` backend-compile events,
- any registered gauges (``register_gauge``) — e.g. the sharded host
  env pool's utilization row (envs/shard_pool.py), so pool-vs-device
  bottleneck attribution rides the same 5s cadence.

Sampling never touches the device (``memory_stats()`` is a host-side
allocator query), so the cadence costs the training loop nothing.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import IO, Callable, Optional

from actor_critic_tpu.utils.numguard import safe_json_row

_PAGE = 4096
try:
    import resource as _resource

    _PAGE = _resource.getpagesize()
except Exception:  # pragma: no cover - non-POSIX
    _resource = None

# Process-global compile counter: jax.monitoring listeners cannot be
# individually unregistered, so registration happens once per process and
# the listener outlives any session (an int increment, harmless).
_compile_count = 0
_listener_registered = False
_listener_lock = threading.Lock()


def _on_event_duration(name: str, *args, **kwargs) -> None:
    if name.endswith("backend_compile_duration"):
        global _compile_count
        # Compiles can be reported from more than one thread (the AOT
        # warmup runner compiles concurrently with the training thread
        # since PR 4); an unlocked += loses increments. Compile events
        # are rare, so the lock costs nothing measurable.
        with _listener_lock:
            _compile_count += 1


def ensure_compile_listener() -> None:
    """Idempotently hook the XLA backend-compile event stream."""
    global _listener_registered
    with _listener_lock:
        if _listener_registered:
            return
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                _on_event_duration
            )
            _listener_registered = True
        except Exception:
            pass  # telemetry must never take a run down


def compile_count() -> int:
    """Backend compiles observed since the listener was installed."""
    return _compile_count


# Gauge registry: components with run-long state (e.g. the sharded env
# pool) register a zero-argument callable whose return value rides every
# resources.jsonl row under the registered key. Process-global like the
# compile counter — gauges outlive sessions, and sample_row() is also
# called synchronously from tests.
_gauges: dict[str, Callable[[], object]] = {}
_gauges_lock = threading.Lock()


def register_gauge(name: str, fn: Callable[[], object]) -> str:
    """Register `fn` under `name` (suffixed `_2`, `_3`, ... on collision,
    e.g. a train pool and its eval pool both registering "host_pool").
    Returns the unique key actually used — pass it to unregister_gauge."""
    with _gauges_lock:
        key, i = name, 1
        while key in _gauges:
            i += 1
            key = f"{name}_{i}"
        _gauges[key] = fn
        return key


def unregister_gauge(name: str) -> None:
    with _gauges_lock:
        _gauges.pop(name, None)


def rss_bytes() -> Optional[int]:
    """Current resident set size; peak RSS when /proc is unavailable."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        if _resource is None:
            return None
        # ru_maxrss is kilobytes on Linux but bytes on macOS (both are
        # peak, the documented degraded mode).
        maxrss = _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss
        return maxrss if sys.platform == "darwin" else maxrss * 1024


def device_memory() -> list[dict]:
    """[{id, platform, live_bytes, peak_bytes}] per local device; devices
    whose backend exposes no allocator stats are reported without the
    byte fields rather than with fake zeros."""
    out: list[dict] = []
    try:
        import jax

        for d in jax.local_devices():
            row: dict = {"id": int(d.id), "platform": str(d.platform)}
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if stats:
                live = stats.get("bytes_in_use")
                peak = stats.get("peak_bytes_in_use")
                if live is not None:
                    row["live_bytes"] = int(live)
                if peak is not None:
                    row["peak_bytes"] = int(peak)
            out.append(row)
    except Exception:
        pass
    return out


def sample_row() -> dict:
    """One resources.jsonl row (also usable synchronously from tests)."""
    row: dict = {
        "ts": round(time.time(), 3),
        "recompiles": compile_count(),
    }
    rss = rss_bytes()
    if rss is not None:
        row["rss_bytes"] = rss
    devs = device_memory()
    if devs:
        row["devices"] = devs
    with _gauges_lock:
        gauges = list(_gauges.items())
    for name, fn in gauges:
        try:
            row[name] = fn()
        except Exception:
            pass  # a broken gauge must never take the sampler down
    return row


class ResourceSampler:
    """Daemon thread appending `sample_row()` to `fh` every `interval_s`
    seconds (plus once at start and once at stop, so even a short run
    gets a first/last pair)."""

    def __init__(
        self,
        fh: IO[str],
        interval_s: float = 5.0,
        mirror: Optional[Callable[[dict], None]] = None,
    ):
        self._fh = fh
        self._interval = max(float(interval_s), 0.01)
        # Optional tap fed every sampled row — the session points this
        # at the flight recorder so gauge trends ride the crash ring.
        self._mirror = mirror
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="telemetry-sampler", daemon=True
        )

    def start(self) -> "ResourceSampler":
        ensure_compile_listener()
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def _emit(self) -> None:
        row = sample_row()
        try:
            # safe_json_row, not json.dumps(allow_nan=False): one NaN
            # gauge (a diverged loss ridden into a registered gauge)
            # would otherwise raise ValueError on EVERY tick and
            # silently end resource sampling for the rest of the run —
            # the ISSUE 14 telemetry crash class. Non-finite values
            # serialize as null and the key is reported once on stderr.
            self._fh.write(safe_json_row(row) + "\n")
        except (OSError, ValueError):
            # OSError (disk full) would otherwise kill the daemon thread
            # and silently end sampling for the rest of the run; skip
            # the row and keep ticking — the disk may come back.
            pass
        if self._mirror is not None:
            try:
                self._mirror(row)
            except Exception:
                pass  # same contract: a broken mirror never ends sampling

    def _run(self) -> None:
        self._emit()
        while not self._stop.wait(self._interval):
            self._emit()
        self._emit()
