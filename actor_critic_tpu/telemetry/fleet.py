"""Fleet-level metrics aggregation (ISSUE 16 tentpole, fleet half).

Per-rank exporters (telemetry/exporter.py) answer for ONE process; a
gossip fleet is N processes, and "what is the fleet's p99" should not
require N curls and a notebook. This module adds:

- **endpoint announce/discover** over the SAME mailbox directory the
  gossip exchange already shares (parallel/multihost.py): each rank
  atomically publishes ``telemetry_endpoint_host<rank>.json`` with its
  exporter URL, and any process that can see the mailbox can enumerate
  the fleet. Same crash contract as the params mailbox — write→fsync→
  rename with a pid-unique tmp, torn reads tolerated by the consumer.

- **FleetAggregator** — scrapes every discovered rank's ``/metrics``
  and serves two merged views through the gateway (``/fleetz`` JSON,
  ``/fleetz/metrics`` Prometheus text). Counters, histogram buckets,
  ``_sum`` and ``_count`` rows merge by EXACT addition (cumulative
  fixed-boundary histograms sum bucket-wise with zero error — the
  reason telemetry/histo.py fixes the boundaries fleet-wide); point
  gauges get min/max rollups, because averaging a gauge across ranks
  manufactures a number no rank ever reported.

Scrapes are on-demand (each /fleetz request), bounded by a per-rank
timeout, and a dead rank degrades to an entry in ``unreachable`` rather
than failing the whole view.
"""

from __future__ import annotations

import json
import os
import re
import time
import urllib.request
from typing import Optional

from actor_critic_tpu.telemetry import histo
from actor_critic_tpu.telemetry.exporter import _line

_ENDPOINT_RE = re.compile(r"^telemetry_endpoint_host(\d+)\.json$")


def endpoint_file(mailbox_dir: str, rank: int) -> str:
    return os.path.join(
        mailbox_dir, f"telemetry_endpoint_host{int(rank)}.json"
    )


def announce_endpoint(
    mailbox_dir: str, rank: int, url: str, **extra
) -> str:
    """Atomically publish this rank's exporter URL into the shared
    mailbox directory (write→fsync→rename, pid-unique tmp: two ranks
    sharing the dir must never interleave into one tmp file)."""
    path = endpoint_file(mailbox_dir, rank)
    body = {
        "rank": int(rank),
        "url": str(url),
        "pid": os.getpid(),
        "ts": round(time.time(), 3),
        **extra,
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(body, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_endpoint(mailbox_dir: str, rank: int) -> Optional[dict]:
    """One rank's announcement, or None on absent/torn file (same
    retry-next-poll contract as the params mailbox)."""
    try:
        with open(endpoint_file(mailbox_dir, rank)) as f:
            out = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    return out if isinstance(out, dict) and "url" in out else None


def discover_endpoints(mailbox_dir: str) -> dict[int, str]:
    """{rank: exporter url} for every announced rank in the mailbox."""
    try:
        names = os.listdir(mailbox_dir)
    except OSError:
        return {}
    out: dict[int, str] = {}
    for name in names:
        m = _ENDPOINT_RE.match(name)
        if not m:
            continue
        ann = read_endpoint(mailbox_dir, int(m.group(1)))
        if ann is not None:
            out[int(m.group(1))] = str(ann["url"])
    return out


# Families whose rows are exact-summable across ranks: monotone counters
# and the three histogram series (cumulative buckets sum bucket-wise).
_SUM_SUFFIXES = ("_total", "_bucket", "_sum", "_count")


def _labels_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def snapshots_from_parsed(
    entries: list[tuple[str, dict, float]]
) -> dict[tuple[str, tuple], dict]:
    """Reconstruct histo snapshot dicts from parsed `_bucket/_sum/_count`
    exposition rows: {(family, labels-sans-le key): snapshot}. The
    round-trip is exact — the exposition IS the cumulative counts."""
    acc: dict[tuple[str, tuple], dict] = {}
    for name, labels, value in entries:
        if name.endswith("_bucket") and "le" in labels:
            fam = name[: -len("_bucket")]
            rest = {k: v for k, v in labels.items() if k != "le"}
            slot = acc.setdefault(
                (fam, _labels_key(rest)),
                {"bounds": {}, "sum": 0.0, "count": 0, "labels": rest},
            )
            slot["bounds"][labels["le"]] = value
        elif name.endswith("_sum"):
            fam = name[: -len("_sum")]
            key = (fam, _labels_key(labels))
            if key in acc:
                acc[key]["sum"] = value
        elif name.endswith("_count"):
            fam = name[: -len("_count")]
            key = (fam, _labels_key(labels))
            if key in acc:
                acc[key]["count"] = value
    out: dict[tuple[str, tuple], dict] = {}
    for key, slot in acc.items():
        finite = sorted(
            (float(le) for le in slot["bounds"] if le != "+Inf")
        )
        if not finite or "+Inf" not in slot["bounds"]:
            continue  # not a complete histogram family
        buckets = [int(slot["bounds"][_le_str(b)]) for b in finite]
        buckets.append(int(slot["bounds"]["+Inf"]))
        out[key] = {
            "histogram": True,
            "boundaries": finite,
            "buckets": buckets,
            "sum": float(slot["sum"]),
            "count": int(slot["count"]),
            "labels": dict(slot["labels"]),
        }
    return out


def _le_str(bound: float) -> str:
    """The exposition string for a finite boundary (render_prometheus
    drops the trailing `.0` on integral bounds — mirror that)."""
    return repr(int(bound)) if float(bound).is_integer() else repr(float(bound))


class FleetAggregator:
    """Scrape-and-merge across every rank's exporter.

    `mailbox_dir` enables discovery via announce files; an explicit
    `endpoints` dict ({rank: url}) overrides/augments it (tests, static
    fleets). Discovery re-runs per scrape, so ranks joining late appear
    without restarting the gateway.
    """

    def __init__(
        self,
        mailbox_dir: Optional[str] = None,
        endpoints: Optional[dict[int, str]] = None,
        timeout_s: float = 2.0,
    ):
        self.mailbox_dir = mailbox_dir
        self._static = dict(endpoints or {})
        self.timeout_s = float(timeout_s)

    def endpoints(self) -> dict[int, str]:
        out: dict[int, str] = {}
        if self.mailbox_dir is not None:
            out.update(discover_endpoints(self.mailbox_dir))
        out.update(self._static)
        return out

    def _fetch(self, url: str) -> Optional[str]:
        try:
            with urllib.request.urlopen(
                url.rstrip("/") + "/metrics", timeout=self.timeout_s
            ) as resp:
                return resp.read().decode("utf-8", "replace")
        except Exception:
            return None

    def scrape(self) -> dict[int, Optional[str]]:
        """{rank: /metrics exposition text, or None if unreachable}."""
        return {
            rank: self._fetch(url)
            for rank, url in sorted(self.endpoints().items())
        }

    # -- merged Prometheus text ---------------------------------------------

    def merged_metrics(self) -> str:
        """One exposition: every rank's rows re-labeled `rank="<r>"`,
        plus `rank="fleet"` rollups — exact sums for counters/histogram
        series, min/max for point gauges."""
        scraped = self.scrape()
        per_rank: list[str] = []
        sums: dict[tuple[str, tuple], float] = {}
        gauges: dict[tuple[str, tuple], list[float]] = {}
        reachable = 0
        for rank, text in scraped.items():
            if text is None:
                continue
            reachable += 1
            for name, labels, value in histo.parse_prometheus(text):
                per_rank.append(
                    _line(name, value, {**labels, "rank": str(rank)})
                )
                key = (name, _labels_key(labels))
                if name.endswith(_SUM_SUFFIXES):
                    sums[key] = sums.get(key, 0.0) + value
                else:
                    gauges.setdefault(key, []).append(value)
        out = [
            "# fleet-merged exposition: per-rank rows plus rank=\"fleet\" "
            "rollups (exact sums for counters/histograms, min/max for "
            "gauges)",
            _line("actor_critic_fleet_size", len(scraped)),
            _line("actor_critic_fleet_reachable", reachable),
        ]
        out.extend(per_rank)
        for (name, lkey) in sorted(sums):
            out.append(
                _line(name, sums[(name, lkey)],
                      {**dict(lkey), "rank": "fleet"})
            )
        for (name, lkey) in sorted(gauges):
            vals = gauges[(name, lkey)]
            base = dict(lkey)
            out.append(
                _line(name, min(vals),
                      {**base, "rank": "fleet", "agg": "min"})
            )
            out.append(
                _line(name, max(vals),
                      {**base, "rank": "fleet", "agg": "max"})
            )
        return "\n".join(out) + "\n"

    # -- merged JSON summary ------------------------------------------------

    def fleetz(self) -> dict:
        """The /fleetz body: per-rank reachability + headline gauges,
        and fleet-merged latency histograms with hist-derived p50/p99
        (merged bucket-wise first, THEN quantiled — quantiles of merged
        buckets are the fleet quantiles; averaging per-rank p99s is not)."""
        scraped = self.scrape()
        endpoints = self.endpoints()
        ranks: dict[str, dict] = {}
        counters: dict[str, float] = {}
        hists: dict[tuple[str, tuple], list[dict]] = {}
        for rank, text in scraped.items():
            entry: dict = {"url": endpoints.get(rank)}
            if text is None:
                entry["up"] = False
            else:
                parsed = histo.parse_prometheus(text)
                flat = {
                    name: value
                    for name, labels, value in parsed
                    if not labels
                }
                entry["up"] = flat.get("actor_critic_up", 0.0) == 1.0
                for k in (
                    "actor_critic_uptime_seconds",
                    "actor_critic_serving_requests_total",
                    "actor_critic_serving_slo_burn",
                    "actor_critic_iters_per_s",
                ):
                    if k in flat:
                        entry[k.removeprefix("actor_critic_")] = flat[k]
                for name, labels, value in parsed:
                    if name.endswith("_total") and not labels:
                        counters[name] = counters.get(name, 0.0) + value
                for key, snap in snapshots_from_parsed(parsed).items():
                    hists.setdefault(key, []).append(snap)
            ranks[str(rank)] = entry
        merged_hists: dict[str, dict] = {}
        for (fam, lkey), snaps in sorted(hists.items()):
            merged = histo.merge(snaps)
            if merged is None:
                continue
            label = ",".join(f"{k}={v}" for k, v in lkey) or "all"
            merged_hists[f"{fam}{{{label}}}"] = {
                "count": merged["count"],
                "sum": merged["sum"],
                "p50": histo.quantile(merged, 0.5),
                "p99": histo.quantile(merged, 0.99),
                "buckets": merged["buckets"],
                "boundaries": merged["boundaries"],
            }
        return {
            "fleet_size": len(scraped),
            "reachable": sorted(
                r for r, t in scraped.items() if t is not None
            ),
            "unreachable": sorted(
                r for r, t in scraped.items() if t is None
            ),
            "ranks": ranks,
            "counters": counters,
            "histograms": merged_hists,
        }
