"""Unified run telemetry (ISSUE 1): phase spans, resource sampling, and
health events behind one `TelemetrySession`.

The framework could already *detect* a wedged device tunnel
(utils/watchdog.py) and log scalar metrics (utils/logging.py); this
package is the layer that can *explain* a run — which loop phase
stalled, whether device memory crept, when throughput regressed:

- `spans`   — host-side span tracer emitting Chrome-trace-format events
              (`spans.jsonl`, one event per line; Perfetto-viewable via
              `scripts/run_report.py --trace`).
- `sampler` — daemon resource sampler (`resources.jsonl`): process RSS,
              per-device live/peak bytes, XLA recompile counter.
- `health`  — throughput-regression and divergence detectors emitting
              structured events (`events.jsonl`).
- `session` — `TelemetrySession` owning the three sinks, plus the
              module-level current-session API the training loops call.
- `exporter`— live-introspection HTTP daemon (ISSUE 3): `/metrics`
              (Prometheus text), `/healthz` (watchdog staleness + open
              span), `/profile?iters=N` (arm an on-demand capture);
              `train.py --telemetry-port`.
- `profiler`— armable windowed `jax.profiler` capture (endpoint or
              SIGUSR2) and the compile listener that turns every XLA
              compilation into a structured `compile` event with
              cost_analysis() FLOPs/bytes and the abstract argument
              signature.

Instrumentation is ALWAYS on (a span is two `time.perf_counter()` calls
and a list push/pop — no device syncs); the three JSONL sinks only
exist while a session is installed (`train.py --telemetry-dir`). The
open-span stack is maintained even without a session so the stall
watchdog can name the hung phase in its exit-42 diagnosis.
"""

from actor_critic_tpu.telemetry.profiler import (  # noqa: F401
    tick as profiler_tick,
)
from actor_critic_tpu.telemetry.session import (  # noqa: F401
    TelemetrySession,
    complete_span,
    current,
    event,
    instant,
    last_open_span,
    observe,
    open_spans,
    set_current,
    span,
    stall_report,
)
from actor_critic_tpu.telemetry.spans import CANONICAL_PHASES  # noqa: F401
