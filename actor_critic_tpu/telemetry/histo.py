"""Fixed-boundary cumulative histograms for the SLO layer (ISSUE 16).

The serving stack's latency view was a 2048-sample deque with
nearest-rank point percentiles — fine for one process's eyeball check,
wrong for a fleet: point percentiles from different ranks cannot be
merged (the p99 of per-rank p99s is not the fleet p99), and a deque
forgets everything older than its window. A fixed-boundary cumulative
histogram has neither problem: bucket counts are plain counters, so

- merging ranks is exact bucket-wise addition (`merge` — the fleet
  aggregator's rollup sums to precisely the per-rank totals), and
- any quantile is recoverable to bucket resolution at read time
  (`quantile` — linear interpolation inside the landing bucket).

Snapshots are plain dicts carrying a `"histogram": True` marker so the
exporter's gauge-flattening loop can recognize one inside a registered
gauge row and render the Prometheus `_bucket`/`_sum`/`_count` triplet
(`render_prometheus`) instead of skipping it as a non-numeric value.

Import-light (stdlib only): rides the serving modules racesan drives
without jax.
"""

from __future__ import annotations

import math
import threading
from typing import Optional, Sequence

# Default serving-latency ladder (milliseconds). Chosen to straddle the
# measured gateway range: sub-ms mirror-backend acts up through the
# multi-second timeout cliff, roughly log-spaced like the Prometheus
# client defaults. +Inf is implicit (the last cumulative bucket).
DEFAULT_LATENCY_BOUNDARIES_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0,
)


class Histogram:
    """Thread-safe fixed-boundary cumulative histogram.

    `boundaries` are the upper bounds of the finite buckets, strictly
    increasing; an implicit +Inf bucket catches the overflow. Counts are
    stored PER-BUCKET internally and cumulated at snapshot time (one
    add per observe, not one per bucket).
    """

    __slots__ = ("boundaries", "_counts", "_sum", "_count", "_lock")

    def __init__(
        self, boundaries: Sequence[float] = DEFAULT_LATENCY_BOUNDARIES_MS
    ):
        bounds = tuple(float(b) for b in boundaries)
        if not bounds or any(
            b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])
        ):
            raise ValueError(
                f"boundaries must be non-empty and strictly increasing, "
                f"got {bounds}"
            )
        self.boundaries = bounds
        self._counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def _index(self, value: float) -> int:
        # Linear scan beats bisect at this ladder length (11 bounds) and
        # keeps the hot path allocation-free.
        for i, b in enumerate(self.boundaries):
            if value <= b:
                return i
        return len(self.boundaries)

    def observe(self, value: float) -> None:
        v = float(value)
        if math.isnan(v):
            return  # a NaN latency must not poison _sum (ISSUE 14)
        i = self._index(v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def observe_many(self, values: Sequence[float]) -> None:
        """Batched observe: one lock acquisition per flush, not per
        request (the dispatcher records a whole flush's latencies)."""
        clean = [float(v) for v in values]
        clean = [v for v in clean if not math.isnan(v)]
        if not clean:
            return
        idx = [self._index(v) for v in clean]
        with self._lock:
            for i in idx:
                self._counts[i] += 1
            self._sum += sum(clean)
            self._count += len(clean)

    def snapshot(self, labels: Optional[dict] = None) -> dict:
        """One mergeable/renderable view: CUMULATIVE bucket counts (the
        Prometheus `_bucket{le=...}` convention — the +Inf bucket equals
        `count`), plus sum/count and the marker key the exporter keys
        rendering off."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum, acc = [], 0
        for c in counts:
            acc += c
            cum.append(acc)
        snap = {
            "histogram": True,
            "boundaries": list(self.boundaries),
            "buckets": cum,
            "sum": round(s, 6),
            "count": total,
        }
        if labels:
            snap["labels"] = dict(labels)
        return snap


def is_snapshot(obj: object) -> bool:
    """Whether `obj` is a histogram snapshot dict (the exporter's
    recognition test — cheap and explicit, no isinstance gymnastics)."""
    return (
        isinstance(obj, dict)
        and obj.get("histogram") is True
        and isinstance(obj.get("buckets"), list)
        and isinstance(obj.get("boundaries"), list)
    )


def merge(snapshots: Sequence[dict]) -> Optional[dict]:
    """Exact bucket-wise merge of same-boundary snapshots (the fleet
    rollup): merged bucket k == sum of every input's bucket k, merged
    sum/count likewise. Returns None for an empty/boundary-mismatched
    input set — a fleet mixing histogram shapes is a deploy skew the
    caller should surface, not silently blend."""
    snaps = [s for s in snapshots if is_snapshot(s)]
    if not snaps:
        return None
    bounds = snaps[0]["boundaries"]
    if any(s["boundaries"] != bounds for s in snaps[1:]):
        return None
    n = len(bounds) + 1
    if any(len(s["buckets"]) != n for s in snaps):
        return None
    merged = [0] * n
    for s in snaps:
        for i, c in enumerate(s["buckets"]):
            merged[i] += int(c)
    return {
        "histogram": True,
        "boundaries": list(bounds),
        "buckets": merged,
        "sum": round(sum(float(s["sum"]) for s in snaps), 6),
        "count": sum(int(s["count"]) for s in snaps),
    }


def quantile(snap: dict, q: float) -> Optional[float]:
    """Histogram-derived quantile estimate from a snapshot: find the
    cumulative bucket the rank lands in and interpolate linearly inside
    it (lower edge = previous boundary, or 0 for the first bucket; the
    +Inf bucket clamps to the last finite boundary — the honest answer
    a bounded ladder can give). None while the histogram is empty."""
    if not is_snapshot(snap) or not 0.0 <= q <= 1.0:
        return None
    total = int(snap["count"])
    if total <= 0:
        return None
    bounds = snap["boundaries"]
    cum = snap["buckets"]
    rank = q * total
    prev_cum = 0
    for i, c in enumerate(cum):
        if rank <= c or i == len(cum) - 1:
            if i >= len(bounds):
                return float(bounds[-1])  # +Inf bucket: clamp
            lo = 0.0 if i == 0 else float(bounds[i - 1])
            hi = float(bounds[i])
            in_bucket = c - prev_cum
            if in_bucket <= 0:
                return hi
            frac = (rank - prev_cum) / in_bucket
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        prev_cum = c
    return float(bounds[-1])


def render_prometheus(
    name: str, snap: dict, labels: Optional[dict] = None
) -> list[str]:
    """Prometheus text lines for one snapshot: `<name>_bucket{le=...}`
    ascending (+Inf last), `<name>_sum`, `<name>_count`. `labels` merge
    with any labels the snapshot itself carries (snapshot wins on
    collision — it is closer to the data)."""
    from actor_critic_tpu.telemetry.exporter import _line

    lbl = dict(labels or {})
    lbl.update(snap.get("labels") or {})
    out = []
    for b, c in zip(snap["boundaries"], snap["buckets"]):
        le = repr(int(b)) if float(b).is_integer() else repr(float(b))
        out.append(_line(f"{name}_bucket", c, {**lbl, "le": le}))
    out.append(_line(f"{name}_bucket", snap["buckets"][-1],
                     {**lbl, "le": "+Inf"}))
    out.append(_line(f"{name}_sum", snap["sum"], lbl or None))
    out.append(_line(f"{name}_count", snap["count"], lbl or None))
    return out


def parse_prometheus(text: str) -> list[tuple[str, dict, float]]:
    """Parse Prometheus text exposition into (name, labels, value)
    triples, skipping comments/blank/malformed lines — the fleet
    aggregator's scrape decoder (stdlib only, handles exactly the
    subset our own exporter emits)."""
    out: list[tuple[str, dict, float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            head, val = line.rsplit(None, 1)
            value = float(val)
        except ValueError:
            continue
        labels: dict = {}
        name = head
        if "{" in head and head.endswith("}"):
            name, _, inner = head.partition("{")
            inner = inner[:-1]
            ok = True
            for part in _split_labels(inner):
                if "=" not in part:
                    ok = False
                    break
                k, _, v = part.partition("=")
                v = v.strip()
                if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
                    v = v[1:-1].replace('\\"', '"').replace("\\n", "\n")
                    v = v.replace("\\\\", "\\")
                labels[k.strip()] = v
            if not ok:
                continue
        out.append((name, labels, value))
    return out


def _split_labels(inner: str) -> list[str]:
    """Split a label body on commas OUTSIDE quoted values (a policy id
    containing a comma must not shear the pair list)."""
    parts, buf, in_q, prev = [], [], False, ""
    for ch in inner:
        if ch == '"' and prev != "\\":
            in_q = not in_q
        if ch == "," and not in_q:
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
        prev = ch
    if buf:
        parts.append("".join(buf))
    return [p for p in (p.strip() for p in parts) if p]
