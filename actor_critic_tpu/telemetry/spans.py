"""Chrome-trace span emission (`spans.jsonl`).

One JSON object per line, each a valid Chrome Trace Event Format entry
(the `{"traceEvents": [...]}` wrapper is added by
`scripts/run_report.py --trace`, or with `jq -s '{traceEvents:.}'`).
Spans are emitted as complete ("ph":"X") events at EXIT time — children
close before parents, and the format is order-independent, so nesting
reconstructs from the ts/dur containment Perfetto renders natively.

Timestamps are microseconds on the `perf_counter` clock, zeroed at
tracer creation; a clock-sync metadata event records the corresponding
unix epoch so wall-clock can be recovered.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import IO, Optional


class SpanTracer:
    """Serializes span/instant events to a line-buffered JSONL handle."""

    def __init__(self, fh: IO[str]):
        self._fh = fh
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._t0 = time.perf_counter()
        self._write({
            "name": "process_name", "ph": "M", "pid": self._pid, "tid": 0,
            "args": {"name": "train"},
        })
        self._write({
            "name": "clock_sync", "ph": "M", "pid": self._pid, "tid": 0,
            "args": {"unix_epoch_at_ts0": time.time()},
        })

    def now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _write(self, evt: dict) -> None:
        line = json.dumps(evt, allow_nan=False)
        with self._lock:
            self._fh.write(line + "\n")

    def complete(
        self, name: str, start_pc: float, dur_s: float,
        args: Optional[dict] = None,
    ) -> None:
        """Emit a ph:"X" complete event; `start_pc` is the span's entry
        `perf_counter()` reading, `dur_s` its duration in seconds."""
        evt = {
            "name": name,
            "ph": "X",
            "ts": round((start_pc - self._t0) * 1e6, 1),
            "dur": round(dur_s * 1e6, 1),
            "pid": self._pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "cat": "phase",
        }
        if args:
            evt["args"] = args
        self._write(evt)

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        """Emit a ph:"i" instant event (thread scope) — used to mark
        phases that exist but have no separable host duration (e.g. the
        env rollout fused into the XLA update program)."""
        evt = {
            "name": name,
            "ph": "i",
            "s": "t",
            "ts": round(self.now_us(), 1),
            "pid": self._pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "cat": "phase",
        }
        if args:
            evt["args"] = args
        self._write(evt)
