"""Chrome-trace span emission (`spans.jsonl`).

One JSON object per line, each a valid Chrome Trace Event Format entry
(the `{"traceEvents": [...]}` wrapper is added by
`scripts/run_report.py --trace`, or with `jq -s '{traceEvents:.}'`).
Spans are emitted as complete ("ph":"X") events at EXIT time — children
close before parents, and the format is order-independent, so nesting
reconstructs from the ts/dur containment Perfetto renders natively.

Timestamps are microseconds on the `perf_counter` clock, zeroed at
tracer creation; a clock-sync metadata event records the corresponding
unix epoch so wall-clock can be recovered.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from typing import IO, Optional

from actor_critic_tpu.utils.numguard import safe_json_row

# Canonical phase-span vocabulary. Every `telemetry.span(...)` /
# `complete_span(...)` / `instant(...)` name in the codebase must come
# from this set (tests/test_span_names.py statically enforces it): the
# per-phase breakdown in scripts/run_report.py groups rows by name, so a
# typo'd phase would not error anywhere — it would just silently grow a
# one-off row nobody aggregates. Add new phases HERE first.
CANONICAL_PHASES = frozenset({
    "iteration",        # one host-loop iteration (encloses the rest)
    "env_step",         # host env collection block (or fused instant)
    "env_step_worker",  # sharded-pool worker simulator time (relayed)
    "host_to_device",   # block transfer onto the device
    "queue_wait",       # async learner waiting on the trajectory queue
    "update",           # jitted learner update (async dispatch)
    "eval",             # greedy eval sweep
    "log",              # metrics materialization + sinks
    "checkpoint",       # orbax save boundary
    "profile",          # on-demand jax.profiler capture window
    # Serving-gateway request hops (ISSUE 16): one /v1/act request
    # renders as a flow-linked track across these.
    "serve_request",    # whole request on its gateway handler thread
    "serve_parse",      # HTTP body read + obs validation
    "serve_queue_wait", # enqueue -> dispatcher pops it into a flush
    "serve_dispatch",   # one micro-batch flush through engine.act
    "serve_respond",    # response serialization + socket write
})


def flow_id_of(trace_id: str) -> int:
    """Stable 32-bit Chrome-trace flow id for a request trace id (hex
    or arbitrary client-minted text — crc32 keeps it deterministic
    either way, so the same id links across processes)."""
    return zlib.crc32(str(trace_id).encode()) & 0x7FFFFFFF


class SpanTracer:
    """Serializes span/instant events to a line-buffered JSONL handle."""

    def __init__(self, fh: IO[str]):
        self._fh = fh
        self._lock = threading.Lock()
        # Optional tap fed every emitted event dict — the session points
        # this at the flight recorder's ring (telemetry/flight.py) so
        # the last N spans survive a SIGKILL. Called OUTSIDE _lock (it
        # has its own) and must never raise.
        self.mirror = None
        self._pid = os.getpid()
        self._t0 = time.perf_counter()
        # Epoch of ts=0, kept for converting FOREIGN timestamps (worker
        # processes report wall-clock epochs; time.time() is the one
        # clock all processes on the host share).
        self._epoch0 = time.time()
        self._named_pids: set[int] = set()
        self._write({
            "name": "process_name", "ph": "M", "pid": self._pid, "tid": 0,
            "args": {"name": "train"},
        })
        self._write({
            "name": "clock_sync", "ph": "M", "pid": self._pid, "tid": 0,
            "args": {"unix_epoch_at_ts0": self._epoch0},
        })

    def now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def pc_to_us(self, pc: float) -> float:
        """Convert a raw `perf_counter()` reading onto this tracer's ts
        axis (callers that stamped an event before emission time)."""
        return (pc - self._t0) * 1e6

    def _write(self, evt: dict) -> None:
        try:
            # safe_json_row: a non-finite span arg (e.g. a NaN metric
            # riding an `update` span) serializes as null instead of
            # ValueError-dropping the whole event (ISSUE 14).
            line = safe_json_row(evt)
            with self._lock:
                self._fh.write(line + "\n")
        except (OSError, ValueError):
            # ENOSPC / closed handle: telemetry must never take the run
            # down — a span emission failing on the training thread
            # would otherwise crash a multi-day run over a full disk.
            pass
        mirror = self.mirror
        if mirror is not None:
            try:
                mirror(evt)
            except Exception:
                pass

    def complete(
        self, name: str, start_pc: float, dur_s: float,
        args: Optional[dict] = None,
    ) -> None:
        """Emit a ph:"X" complete event; `start_pc` is the span's entry
        `perf_counter()` reading, `dur_s` its duration in seconds."""
        evt = {
            "name": name,
            "ph": "X",
            "ts": round((start_pc - self._t0) * 1e6, 1),
            "dur": round(dur_s * 1e6, 1),
            "pid": self._pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "cat": "phase",
        }
        if args:
            evt["args"] = args
        self._write(evt)

    def name_process(self, pid: int, name: str) -> None:
        """Emit a process_name metadata event for a FOREIGN pid (e.g. an
        env-shard worker) so Perfetto labels its lane; idempotent per
        pid so the relay can call it on every drain."""
        # Test-and-set under the lock: the relay drains from the
        # training thread today, but nothing stops a second drain site
        # (async actors relaying their own pools), and two threads
        # passing the membership test together would emit duplicate
        # metadata rows. _write reacquires the same lock AFTER this
        # block releases it — never nested.
        with self._lock:
            if pid in self._named_pids:
                return
            self._named_pids.add(pid)
        self._write({
            "name": "process_name", "ph": "M", "pid": int(pid), "tid": 0,
            "args": {"name": name},
        })

    def _foreign_evt(
        self, name: str, epoch_start: float, dur_s: float,
        pid: int, tid: int, args: Optional[dict],
    ) -> dict:
        evt = {
            "name": name,
            "ph": "X",
            "ts": round((epoch_start - self._epoch0) * 1e6, 1),
            "dur": round(dur_s * 1e6, 1),
            "pid": int(pid),
            "tid": int(tid),
            "cat": "phase",
        }
        if args:
            evt["args"] = args
        return evt

    def complete_foreign(
        self, name: str, epoch_start: float, dur_s: float,
        pid: int, tid: int = 0, args: Optional[dict] = None,
    ) -> None:
        """Emit a ph:"X" event measured in ANOTHER process. `epoch_start`
        is a `time.time()` reading from that process — converted onto
        this tracer's ts axis via the epoch anchor recorded at creation,
        so worker lanes line up with the parent's spans. The record
        keeps the worker's real pid (its own Perfetto lane)."""
        self._write(self._foreign_evt(name, epoch_start, dur_s, pid, tid, args))

    def complete_foreign_many(
        self, items: list[tuple[str, float, float, int, int, Optional[dict]]]
    ) -> None:
        """Batched `complete_foreign`: one lock acquisition and ONE write
        for the whole list of (name, epoch_start, dur_s, pid, tid, args)
        tuples. The shard-pool relay drains hundreds of per-step records
        per collection block on the training thread — a write syscall
        per record would be real hot-loop overhead."""
        try:
            lines = [
                safe_json_row(self._foreign_evt(*item))
                for item in items
            ]
            if not lines:
                return
            with self._lock:
                self._fh.write("\n".join(lines) + "\n")
        except (OSError, ValueError):
            pass  # same never-take-the-run-down contract as _write

    def flow(
        self,
        flow_id: int,
        phase: str = "s",
        ts_us: Optional[float] = None,
        name: str = "serve_flow",
    ) -> None:
        """Emit one Chrome-trace flow event (`ph` "s" start / "t" step /
        "f" end). Flow events with the same `id` draw as connecting
        arrows between the slices that CONTAIN their timestamps — which
        is how one request's gateway-thread span, its queue wait, and
        the dispatcher's flush render as a single connected track
        (ISSUE 16). Pass `ts_us` (via `pc_to_us`) to bind to a slice
        stamped earlier than the emission call."""
        evt = {
            "name": name,
            "cat": "flow",
            "ph": phase,
            "id": int(flow_id) & 0xFFFFFFFF,
            "ts": round(self.now_us() if ts_us is None else ts_us, 1),
            "pid": self._pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if phase == "f":
            evt["bp"] = "e"  # bind to the enclosing slice, not the next
        self._write(evt)

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        """Emit a ph:"i" instant event (thread scope) — used to mark
        phases that exist but have no separable host duration (e.g. the
        env rollout fused into the XLA update program)."""
        evt = {
            "name": name,
            "ph": "i",
            "s": "t",
            "ts": round(self.now_us(), 1),
            "pid": self._pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "cat": "phase",
        }
        if args:
            evt["args"] = args
        self._write(evt)
