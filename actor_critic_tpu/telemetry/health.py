"""Health monitors — structured run-health events (`events.jsonl`).

Both monitors consume the same per-iteration `observe` feed the session
routes from the training loops (`telemetry.observe(it, metrics)` inside
the log callbacks) and emit JSONL events through a supplied `emit(kind,
**fields)` callable. They share one signature — `observe(it, metrics,
now_s)` — so the session dispatches to every monitor uniformly (each
ignores the argument it doesn't need). They never raise and never touch
the device: a health check is a handful of float compares per logged
iteration.

- `ThroughputMonitor`: EMA of iterations/s; fires `throughput_regression`
  when the rate stays below `(1 - drop_threshold)` of the EMA for
  `confirm_observations` CONSECUTIVE observations (after a warmup) —
  one isolated slow window (a checkpoint save or an eval inside the
  observation interval inflates dt) recovers on the next observation
  and stays quiet — then re-arms only after the rate recovers so a
  sustained slowdown produces one event, not one per iteration.
- `DivergenceMonitor`: fires `divergence` on (a) a non-finite value for
  any `*loss*` metric (the SAC alpha-runaway signature), or (b) a
  tracked return metric collapsing below `collapse_frac` of its best
  observed value once the run had made real progress.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Sequence

Emit = Callable[..., None]


class ThroughputMonitor:
    """Iterations/s EMA with a configurable regression threshold."""

    def __init__(
        self,
        emit: Emit,
        drop_threshold: float = 0.5,
        ema_alpha: float = 0.2,
        warmup_observations: int = 3,
        confirm_observations: int = 2,
    ):
        """`confirm_observations`: consecutive sub-floor rates required
        before firing. The default of 2 makes the monitor blind to the
        periodic one-window blips a healthy run produces (checkpoint
        saves, evals) while a sustained regression still fires on its
        second observation."""
        if not 0.0 < drop_threshold < 1.0:
            raise ValueError("drop_threshold must be in (0, 1)")
        self._emit = emit
        self._drop = float(drop_threshold)
        self._alpha = float(ema_alpha)
        self._warmup = int(warmup_observations)
        self._confirm = max(int(confirm_observations), 1)
        self._ema: Optional[float] = None
        self._seen = 0
        self._below = 0
        self._last_it: Optional[int] = None
        self._last_t: Optional[float] = None
        self._tripped = False

    def observe(self, it: int, metrics: dict, now_s: float) -> None:
        """Feed one observation; only (it, now_s) matter here, `metrics`
        rides the uniform monitor signature."""
        if self._last_it is not None and it > self._last_it:
            dt = now_s - self._last_t
            if dt <= 0:
                return  # same-timestamp double log; no rate to measure
            rate = (it - self._last_it) / dt
            self._seen += 1
            if self._ema is not None and self._seen > self._warmup:
                floor = (1.0 - self._drop) * self._ema
                if rate < floor:
                    self._below += 1
                    if self._below >= self._confirm and not self._tripped:
                        self._tripped = True
                        self._emit(
                            "throughput_regression",
                            iter=it,
                            iters_per_s=round(rate, 4),
                            ema_iters_per_s=round(self._ema, 4),
                            drop_threshold=self._drop,
                        )
                else:
                    self._below = 0
                    self._tripped = False
            self._ema = (
                rate
                if self._ema is None
                else self._alpha * rate + (1.0 - self._alpha) * self._ema
            )
        self._last_it = it
        self._last_t = now_s


class DivergenceMonitor:
    """Non-finite-loss and return-collapse detector."""

    def __init__(
        self,
        emit: Emit,
        return_keys: Sequence[str] = (
            "avg_return_ema", "recent_return", "eval_return",
        ),
        collapse_frac: float = 0.1,
        min_progress: float = 1.0,
    ):
        """`min_progress`: the best-return watermark must exceed this
        before collapse can fire — a run still at its random-policy floor
        has nothing to collapse from (and near-zero watermarks would make
        the fraction test fire on noise)."""
        self._emit = emit
        self._return_keys = tuple(return_keys)
        self._collapse = float(collapse_frac)
        self._min_progress = float(min_progress)
        self._best: dict[str, float] = {}
        self._fired_nonfinite = False
        self._fired_collapse: set[str] = set()

    def observe(self, it: int, metrics: dict, now_s: float = 0.0) -> None:
        for k, v in metrics.items():
            if "loss" not in k:
                continue
            try:
                f = float(v)
            except (TypeError, ValueError):
                continue
            if not math.isfinite(f):
                if not self._fired_nonfinite:
                    self._fired_nonfinite = True
                    self._emit(
                        "divergence", iter=it, reason="non_finite_loss",
                        metric=k,
                    )
                return  # one event covers the row; collapse is moot now
        for k in self._return_keys:
            v = metrics.get(k)
            try:
                f = float(v)
            except (TypeError, ValueError):
                continue
            if not math.isfinite(f):
                continue
            best = self._best.get(k)
            if best is None or f > best:
                self._best[k] = f
                self._fired_collapse.discard(k)  # recovered: re-arm
                continue
            if (
                best > self._min_progress
                and f < self._collapse * best
                and k not in self._fired_collapse
            ):
                self._fired_collapse.add(k)
                self._emit(
                    "divergence", iter=it, reason="return_collapse",
                    metric=k, value=round(f, 4), best=round(best, 4),
                    collapse_frac=self._collapse,
                )
