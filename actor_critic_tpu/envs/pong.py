"""Pure-JAX Pong-like pixel environment for the IMPALA/A3C config.

The reference's fifth config runs A3C/IMPALA on Atari Pong through the ALE
C++ emulator (BASELINE.json:11; reference mount empty at survey, SURVEY.md
§0).  `ale-py` is not installed in this environment (SURVEY.md §7.0), so —
as prescribed by SURVEY.md §2.2 — the TPU build ships a pure-JAX pixel env
of Pong-like shape instead: two paddles, a bouncing ball, ±1 scoring
rewards, and stacked-frame uint8 observations that feed the Nature-CNN
encoder exactly like preprocessed Atari frames would.

Being pure JAX, thousands of instances vmap onto one device and fuse into
the training step — the same on-device rollout design as cartpole.py,
which is what lets the IMPALA config report steps/sec on TPU at all
(a host-stepped ALE on this 1-CPU machine could not).

Game rules:
- The agent is the RIGHT paddle: actions {0: stay, 1: up, 2: down}.
- The LEFT paddle is a scripted opponent tracking the ball with capped
  speed (slower than the ball's max vertical speed, so it is beatable).
- Ball bounces off top/bottom walls; paddle hits reflect it and add
  "english" proportional to the hit offset, so rallies vary.
- Reward +1 when the opponent misses, −1 when the agent misses.  First to
  `points_to_win` points terminates the episode; `max_steps` truncates.
- Observation: [size, size, 2] uint8 — previous and current rendered
  frame stacked on the channel axis (the frame-stack preprocessing the
  reference applies host-side, done here in the env itself).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from actor_critic_tpu.envs.jax_env import EnvSpec, JaxEnv, auto_reset


class PongState(NamedTuple):
    ball_x: jax.Array
    ball_y: jax.Array
    vel_x: jax.Array
    vel_y: jax.Array
    player_y: jax.Array  # agent paddle center (right side)
    opp_y: jax.Array     # scripted paddle center (left side)
    player_score: jax.Array
    opp_score: jax.Array
    t: jax.Array
    prev_frame: jax.Array  # last rendered frame, for the 2-frame stack
    key: jax.Array


def make_pong(
    size: int = 84,
    points_to_win: int = 5,
    max_steps: int = 1000,
    paddle_hh: float = 6.0,
    ball_speed: float = 1.0,
    opp_skill: float = 1.0,
    frame_skip: int = 1,
) -> JaxEnv:
    """Build the Pong-like env. `size` ≥ 36 keeps the Nature CNN's VALID
    conv stack non-degenerate (84 is the canonical Atari shape).

    Difficulty knobs (both at their hardest by default — the canonical
    config): `paddle_hh` is the agent/opponent paddle half-height in
    84-scale pixels, `ball_speed` scales the serve/vertical ball
    velocities AND, deliberately, the opponent's paddle speed and the
    hit-offset english (keeping opp_speed < vy_max, so the opponent
    stays beatable at every difficulty). `opp_skill` scales the
    opponent's tracking speed alone — the knob that actually controls
    scoring density: at 1.0 an ORACLE ball-tracker only beats the
    opponent via accumulated english (measured ~+1..+3 per 1000 steps,
    with points hundreds of steps apart — a brutally sparse target for
    γ=0.99 credit assignment), while at ~0.5 placed shots score within
    ~100 steps, the regime where pixel-pong is learnable at single-
    digit millions of frames (like ALE Pong's beatable computer
    paddle). `frame_skip` is ALE's action repeat: one agent decision
    drives k physics frames and rewards sum over the window — without
    it the ball moves sub-pixel between the two stacked frames (its
    VELOCITY is invisible to the CNN) and credit horizons stretch k×
    past every published pong recipe, which all assume skip=4. Default
    1 preserves the recorded throughput rows; learning configs want 4.
    `max_steps` counts agent decisions (windows), not physics frames. Pixel-pong from ±1 terminal rewards is a sparse-signal
    task that needs tens of millions of frames at the defaults (as real
    Pong does); a larger paddle / slower ball densify the reward signal
    for learning demos and CI-budget learning tests."""
    if size < 36:
        raise ValueError("size must be >= 36 for the Nature-CNN conv stack")
    if frame_skip < 1:
        raise ValueError("frame_skip must be >= 1 (0 would freeze the env)")
    if not 0.0 <= opp_skill < 2.0:
        # opp_speed = 1.1·scale·ball_speed·opp_skill must stay below
        # vy_max = 2.2·scale·ball_speed, or the opponent tracks every
        # ball perfectly and the env becomes unwinnable.
        raise ValueError("opp_skill must be in [0, 2) to keep the opponent beatable")
    scale = size / 84.0
    hh = paddle_hh * scale      # paddle half-height (pixels)
    paddle_speed = 2.0 * scale
    opp_speed = 1.1 * scale * ball_speed * opp_skill  # < max |vel_y| ⇒ beatable
    serve_speed_x = 1.8 * scale * ball_speed
    vy_max = 2.2 * scale * ball_speed
    english = 1.2 * scale * ball_speed  # vy gain per unit of hit offset
    player_x = float(size - 3)  # paddle planes
    opp_x = 2.0
    lo, hi = hh, float(size - 1) - hh  # paddle-center travel range

    ys = jnp.arange(size, dtype=jnp.float32)[:, None]
    xs = jnp.arange(size, dtype=jnp.float32)[None, :]

    def render(ball_x, ball_y, player_y, opp_y) -> jax.Array:
        ball = (jnp.abs(ys - ball_y) <= 1.0) & (jnp.abs(xs - ball_x) <= 1.0)
        player = (jnp.abs(ys - player_y) <= hh) & (jnp.abs(xs - player_x) <= 1.0)
        opp = (jnp.abs(ys - opp_y) <= hh) & (jnp.abs(xs - opp_x) <= 1.0)
        return jnp.where(ball | player | opp, jnp.uint8(255), jnp.uint8(0))

    def serve(key):
        """Center the ball with a random direction (both axes)."""
        kx, ky = jax.random.split(key)
        dir_x = jnp.where(jax.random.bernoulli(kx), 1.0, -1.0)
        vy = jax.random.uniform(ky, (), jnp.float32, -1.0, 1.0) * scale
        c = (size - 1) / 2.0
        return (
            jnp.float32(c), jnp.float32(c),
            dir_x * serve_speed_x, vy,
        )

    def reset(key):
        key, skey = jax.random.split(key)
        ball_x, ball_y, vel_x, vel_y = serve(skey)
        c = jnp.float32((size - 1) / 2.0)
        frame = render(ball_x, ball_y, c, c)
        state = PongState(
            ball_x=ball_x, ball_y=ball_y, vel_x=vel_x, vel_y=vel_y,
            player_y=c, opp_y=c,
            player_score=jnp.zeros((), jnp.int32),
            opp_score=jnp.zeros((), jnp.int32),
            t=jnp.zeros((), jnp.int32),
            prev_frame=frame, key=key,
        )
        obs = jnp.stack([frame, frame], axis=-1)
        return state, obs

    def physics_substep(core, move):
        """One physics frame with the agent's move held fixed (the action
        repeats across a frame-skip window, ALE-style)."""
        (ball_x0, ball_y0, vel_x, vel_y, player_y, opp_y,
         player_score, opp_score, key) = core
        player_y = jnp.clip(player_y + move * paddle_speed, lo, hi)
        opp_y = jnp.clip(
            opp_y + jnp.clip(ball_y0 - opp_y, -opp_speed, opp_speed), lo, hi
        )

        ball_x = ball_x0 + vel_x
        ball_y = ball_y0 + vel_y

        # Top/bottom wall bounce (positions reflect, vy flips).
        top = jnp.float32(size - 1)
        bounced = (ball_y < 0.0) | (ball_y > top)
        ball_y = jnp.where(ball_y < 0.0, -ball_y, ball_y)
        ball_y = jnp.where(ball_y > top, 2.0 * top - ball_y, ball_y)
        vel_y = jnp.where(bounced, -vel_y, vel_y)

        # Paddle hits: reflect off the paddle plane, add english.
        hit_player = (ball_x >= player_x) & (jnp.abs(ball_y - player_y) <= hh + 1.0)
        hit_opp = (ball_x <= opp_x) & (jnp.abs(ball_y - opp_y) <= hh + 1.0)
        ball_x = jnp.where(hit_player, 2.0 * player_x - ball_x, ball_x)
        ball_x = jnp.where(hit_opp, 2.0 * opp_x - ball_x, ball_x)
        vel_x = jnp.where(hit_player | hit_opp, -vel_x, vel_x)
        offset = jnp.where(
            hit_player, (ball_y - player_y) / hh,
            jnp.where(hit_opp, (ball_y - opp_y) / hh, 0.0),
        )
        vel_y = jnp.clip(
            vel_y + jnp.where(hit_player | hit_opp, english * offset, 0.0),
            -vy_max, vy_max,
        )

        # Scoring: ball got past a paddle plane without a hit.
        player_point = ball_x < 0.0          # opponent missed
        opp_point = ball_x > jnp.float32(size - 1)  # agent missed
        reward = jnp.where(player_point, 1.0, jnp.where(opp_point, -1.0, 0.0))
        player_score = player_score + player_point.astype(jnp.int32)
        opp_score = opp_score + opp_point.astype(jnp.int32)

        key, skey = jax.random.split(key)
        sx, sy, svx, svy = serve(skey)
        scored = player_point | opp_point
        ball_x = jnp.where(scored, sx, ball_x)
        ball_y = jnp.where(scored, sy, ball_y)
        vel_x = jnp.where(scored, svx, vel_x)
        vel_y = jnp.where(scored, svy, vel_y)

        return (
            ball_x, ball_y, vel_x, vel_y, player_y, opp_y,
            player_score, opp_score, key,
        ), reward

    def raw_step(state: PongState, action: jax.Array):
        move = jnp.where(action == 1, -1.0, jnp.where(action == 2, 1.0, 0.0))
        core = (
            state.ball_x, state.ball_y, state.vel_x, state.vel_y,
            state.player_y, state.opp_y,
            state.player_score, state.opp_score, state.key,
        )
        if frame_skip == 1:
            core, reward = physics_substep(core, move)
        else:
            # ALE-style action repeat: the same move drives `frame_skip`
            # physics frames; rewards sum over the window. (Play continues
            # within a window even if the final point lands mid-window —
            # the rally after match point is unobserved and harmless,
            # matching how ALE's skip can overrun a terminal frame.)
            def sub(carry, _):
                c, rew = carry
                c, r = physics_substep(c, move)
                return (c, rew + r), None

            (core, reward), _ = jax.lax.scan(
                sub, (core, jnp.zeros(())), None, length=frame_skip
            )
        (ball_x, ball_y, vel_x, vel_y, player_y, opp_y,
         player_score, opp_score, key) = core

        t = state.t + 1
        terminated = (
            (player_score >= points_to_win) | (opp_score >= points_to_win)
        ).astype(jnp.float32)
        truncated = (t >= max_steps).astype(jnp.float32) * (1.0 - terminated)

        frame = render(ball_x, ball_y, player_y, opp_y)
        nstate = PongState(
            ball_x=ball_x, ball_y=ball_y, vel_x=vel_x, vel_y=vel_y,
            player_y=player_y, opp_y=opp_y,
            player_score=player_score, opp_score=opp_score,
            t=t, prev_frame=frame, key=key,
        )
        obs = jnp.stack([state.prev_frame, frame], axis=-1)
        return nstate, obs, reward, terminated, truncated

    spec = EnvSpec(
        obs_shape=(size, size, 2), action_dim=3, discrete=True,
        obs_dtype=jnp.uint8, episode_horizon=max_steps,
    )
    step = auto_reset(reset, raw_step, key_of_state=lambda s: s.key)
    return JaxEnv(spec=spec, reset=reset, step=step)
