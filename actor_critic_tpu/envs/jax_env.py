"""Pure-JAX environment protocol — the on-device rollout substrate.

The reference steps host environments (gym classic-control / MuJoCo / ALE)
one process boundary away from the device (SURVEY.md §3.1 boundary
analysis; reference mount empty, SURVEY.md §0). On TPU that ping-pong is
the throughput killer, so the framework's first-class env interface is a
*functional* one: `reset` and `step` are pure jit-safe functions over an
explicit state pytree, vmapped over thousands of env instances and fused
into the training step (north star ≥1M steps/s, BASELINE.json:5).

Conventions:
- `reset(key) -> (state, obs)`;
  `step(state, action) -> (state, obs, reward, done, info)`.
- `done` is 1.0 at a step that *ends* the episode (termination OR
  truncation); `info["terminated"]` distinguishes true termination so GAE
  can bootstrap through time-limit truncations.
- `step` must auto-reset: when an episode ends, the returned state/obs are
  from a fresh episode (the returned `obs` is the new episode's first obs;
  the pre-reset terminal obs is in `info["final_obs"]`). This keeps the
  vmapped batch rectangular with no host intervention.
- Everything is float32; shapes static; randomness via explicit keys
  threaded in `state`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp


class StepOutput(NamedTuple):
    state: Any  # env state pytree (post auto-reset)
    obs: jax.Array
    reward: jax.Array
    done: jax.Array  # 1.0 where episode ended this step (term or trunc)
    info: dict[str, jax.Array]


@dataclasses.dataclass(frozen=True)
class EnvSpec:
    """Static metadata a trainer needs to build networks."""

    obs_shape: tuple[int, ...]
    action_dim: int  # num discrete actions, or continuous action dims
    discrete: bool
    obs_dtype: Any = jnp.float32
    # False ⇒ episodes only ever terminate (never time-limit truncate), so
    # trainers can statically skip the truncation-bootstrap forward pass.
    can_truncate: bool = True
    # Upper bound on episode length (the time-limit), 0 = unknown. Eval
    # programs size their rollout horizon from this so a good policy's
    # still-running episodes are never cut (and then wrongly excluded
    # from the finished-episode mean — common.evaluate docstring).
    episode_horizon: int = 0

    @property
    def pixel_obs(self) -> bool:
        """Whether observations are image-shaped ([H, W, C]) — the single
        rule every algorithm's make_network uses to pick the Nature CNN
        over the MLP torso (keep it here, not copy-pasted per algo)."""
        return len(self.obs_shape) == 3


@dataclasses.dataclass(frozen=True)
class JaxEnv:
    """A pure-functional environment: a spec plus reset/step closures.

    Instances are static (hashable) so they can be closed over by jitted
    trainers without retracing.
    """

    spec: EnvSpec
    reset: Callable[[jax.Array], tuple[Any, jax.Array]]
    step: Callable[[Any, jax.Array], StepOutput]

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other


def auto_reset(
    reset_fn: Callable[[jax.Array], tuple[Any, jax.Array]],
    raw_step: Callable[[Any, jax.Array], tuple[Any, jax.Array, jax.Array, jax.Array, jax.Array]],
    key_of_state: Callable[[Any], jax.Array],
) -> Callable[[Any, jax.Array], StepOutput]:
    """Wrap a raw step (no reset logic) into the auto-resetting protocol.

    `raw_step(state, action) -> (state, obs, reward, terminated, truncated)`.
    On done, replaces state/obs with a fresh `reset` (keyed off the env
    state's PRNG key) via `lax.cond`-free `tree.map(where)` select — branchless,
    so the vmapped batch stays a single fused program.
    """

    def step(state, action) -> StepOutput:
        nstate, obs, reward, terminated, truncated = raw_step(state, action)
        done = jnp.maximum(terminated, truncated)
        key = key_of_state(nstate)
        reset_key, _ = jax.random.split(key)
        rstate, robs = reset_fn(reset_key)

        def select(a, b):
            d = done.reshape(done.shape + (1,) * (a.ndim - done.ndim))
            return jnp.where(d.astype(jnp.bool_), a, b)

        out_state = jax.tree.map(select, rstate, nstate)
        out_obs = select(robs, obs)
        return StepOutput(
            state=out_state,
            obs=out_obs,
            reward=reward,
            done=done,
            info={"terminated": terminated, "final_obs": obs},
        )

    return step
