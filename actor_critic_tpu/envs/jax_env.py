"""Pure-JAX environment protocol — the on-device rollout substrate.

The reference steps host environments (gym classic-control / MuJoCo / ALE)
one process boundary away from the device (SURVEY.md §3.1 boundary
analysis; reference mount empty, SURVEY.md §0). On TPU that ping-pong is
the throughput killer, so the framework's first-class env interface is a
*functional* one: `reset` and `step` are pure jit-safe functions over an
explicit state pytree, vmapped over thousands of env instances and fused
into the training step (north star ≥1M steps/s, BASELINE.json:5).

Conventions:
- `reset(key) -> (state, obs)`;
  `step(state, action) -> (state, obs, reward, done, info)`.
- `done` is 1.0 at a step that *ends* the episode (termination OR
  truncation); `info["terminated"]` distinguishes true termination so GAE
  can bootstrap through time-limit truncations.
- `step` must auto-reset: when an episode ends, the returned state/obs are
  from a fresh episode (the returned `obs` is the new episode's first obs;
  the pre-reset terminal obs is in `info["final_obs"]`). This keeps the
  vmapped batch rectangular with no host intervention.
- Everything is float32; shapes static; randomness via explicit keys
  threaded in `state`.

Scenario fleet (ISSUE 8): envs that support domain randomization carry a
per-instance `ScenarioParams`-style NamedTuple of physics scalars INSIDE
their state pytree, drawn in `reset` from configurable ranges
(`scenario_ranges` / `draw_scenario` below). Because the params live in
the state, the existing `jax.vmap(env.reset)` / `jax.vmap(env.step)`
fleet path needs no protocol change: thousands of instances with
different masses/lengths/force scales step inside ONE fused XLA program,
and `auto_reset`'s end-of-episode reset re-draws a fresh scenario from
the instance's own PRNG stream — per-episode re-randomization, the
standard domain-randomization regime. Same key ⇒ same draw (tested in
tests/test_scenarios.py), so fleets are reproducible.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp


class StepOutput(NamedTuple):
    state: Any  # env state pytree (post auto-reset)
    obs: jax.Array
    reward: jax.Array
    done: jax.Array  # 1.0 where episode ended this step (term or trunc)
    info: dict[str, jax.Array]


@dataclasses.dataclass(frozen=True)
class EnvSpec:
    """Static metadata a trainer needs to build networks."""

    obs_shape: tuple[int, ...]
    action_dim: int  # num discrete actions, or continuous action dims
    discrete: bool
    obs_dtype: Any = jnp.float32
    # False ⇒ episodes only ever terminate (never time-limit truncate), so
    # trainers can statically skip the truncation-bootstrap forward pass.
    can_truncate: bool = True
    # Upper bound on episode length (the time-limit), 0 = unknown. Eval
    # programs size their rollout horizon from this so a good policy's
    # still-running episodes are never cut (and then wrongly excluded
    # from the finished-episode mean — common.evaluate docstring).
    episode_horizon: int = 0

    @property
    def pixel_obs(self) -> bool:
        """Whether observations are image-shaped ([H, W, C]) — the single
        rule every algorithm's make_network uses to pick the Nature CNN
        over the MLP torso (keep it here, not copy-pasted per algo)."""
        return len(self.obs_shape) == 3


@dataclasses.dataclass(frozen=True)
class JaxEnv:
    """A pure-functional environment: a spec plus reset/step closures.

    Instances are static (hashable) so they can be closed over by jitted
    trainers without retracing.
    """

    spec: EnvSpec
    reset: Callable[[jax.Array], tuple[Any, jax.Array]]
    step: Callable[[Any, jax.Array], StepOutput]

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other


def scenario_ranges(
    defaults: dict[str, float],
    randomize: float = 0.0,
    overrides: dict[str, Any] | None = None,
) -> dict[str, tuple[float, float]]:
    """Resolve per-parameter (lo, hi) draw ranges for a scenario fleet.

    `randomize=r` widens every default d to [d·(1−r), d·(1+r)] — the one
    knob that makes a whole fleet heterogeneous (`--env-set
    randomize=0.3`). `overrides` then pins individual params: a (lo, hi)
    pair / list, a "lo,hi" string (the `--env-set masspole=0.05,0.5`
    spelling — env-set coerces unrecognized values to str), or a bare
    number to FIX the param at a non-default value. randomize <= 0 with
    no overrides returns degenerate [d, d] ranges (the deterministic
    single-scenario env).
    """
    if randomize < 0:
        raise ValueError(f"randomize must be >= 0, got {randomize}")
    out = {}
    for name, d in defaults.items():
        r = abs(d) * randomize
        out[name] = (d - r, d + r)
    for name, val in (overrides or {}).items():
        if name not in defaults:
            raise ValueError(
                f"unknown scenario parameter {name!r}; "
                f"valid: {sorted(defaults)}"
            )
        if val is None:
            continue
        if isinstance(val, str):
            parts = [p for p in val.split(",") if p.strip()]
            vals = tuple(float(p) for p in parts)
        elif isinstance(val, (tuple, list)):
            vals = tuple(float(v) for v in val)
        else:
            vals = (float(val),)
        if len(vals) == 1:
            out[name] = (vals[0], vals[0])
        elif len(vals) == 2:
            out[name] = (min(vals), max(vals))
        else:
            raise ValueError(
                f"scenario range for {name!r} must be a number or "
                f"lo,hi pair, got {val!r}"
            )
    return out


def draw_scenario(key: jax.Array, ranges: dict[str, tuple[float, float]]) -> dict[str, jax.Array]:
    """One uniform draw per parameter from `ranges`, each from its own
    stream folded on a stable CRC32 of the parameter NAME — not a
    positional index, so adding or removing a parameter never perturbs
    the draws of the others. Deterministic in `key`: the scenario-fleet
    reproducibility contract. Returns {name: f32 scalar}."""
    import zlib

    out = {}
    for name in sorted(ranges):
        lo, hi = ranges[name]
        if lo == hi:
            # Degenerate range: emit the exact constant — float blends
            # like (1−u)·lo + u·hi need not round back to it, and the
            # gymnasium-parity tests compare against exact constants.
            out[name] = jnp.asarray(lo, jnp.float32)
            continue
        sub = jax.random.fold_in(
            key, zlib.crc32(name.encode()) & 0x7FFFFFFF
        )
        out[name] = jax.random.uniform(
            sub, (), jnp.float32, minval=lo, maxval=hi
        )
    return out


def is_randomized(ranges: dict[str, tuple[float, float]]) -> bool:
    """Whether any parameter's range is non-degenerate (lo < hi)."""
    return any(lo != hi for lo, hi in ranges.values())


def auto_reset(
    reset_fn: Callable[[jax.Array], tuple[Any, jax.Array]],
    raw_step: Callable[[Any, jax.Array], tuple[Any, jax.Array, jax.Array, jax.Array, jax.Array]],
    key_of_state: Callable[[Any], jax.Array],
) -> Callable[[Any, jax.Array], StepOutput]:
    """Wrap a raw step (no reset logic) into the auto-resetting protocol.

    `raw_step(state, action) -> (state, obs, reward, terminated, truncated)`.
    On done, replaces state/obs with a fresh `reset` (keyed off the env
    state's PRNG key) via `lax.cond`-free `tree.map(where)` select — branchless,
    so the vmapped batch stays a single fused program.
    """

    def step(state, action) -> StepOutput:
        nstate, obs, reward, terminated, truncated = raw_step(state, action)
        done = jnp.maximum(terminated, truncated)
        key = key_of_state(nstate)
        reset_key, _ = jax.random.split(key)
        rstate, robs = reset_fn(reset_key)

        def select(a, b):
            d = done.reshape(done.shape + (1,) * (a.ndim - done.ndim))
            return jnp.where(d.astype(jnp.bool_), a, b)

        out_state = jax.tree.map(select, rstate, nstate)
        out_obs = select(robs, obs)
        return StepOutput(
            state=out_state,
            obs=out_obs,
            reward=reward,
            done=done,
            info={"terminated": terminated, "final_obs": obs},
        )

    return step
