"""Heterogeneous scenario-mixture fleet: many env TYPES, one XLA program.

The tentpole of ISSUE 11. PR 8's scenario fleet randomizes parameters of
ONE env type; this module steps a fleet that mixes different env TYPES —
CartPole + Pendulum + Acrobot + the procedural maze family — inside a
single fused program, the GA3C/Accelerated-Methods move (arxiv
1611.06256, 1803.02811: the parallelism lives in large-batch device-side
heterogeneous batching) applied to training:

- **Padded shared obs interface**: each member's vector obs is
  zero-padded to the width of the widest member; the per-type validity
  mask is a static [n_types, obs_max] table (`MixtureEnv.obs_masks`,
  indexable by the per-instance type ids) so consumers can distinguish
  "this lane is zero" from "this lane does not exist". Padding is
  mask-MULTIPLIED, not just concatenated, so padded lanes are exactly
  0.0 by construction regardless of member behavior — the collection
  blocks the fused rollout scan gathers are mask-clean without any
  per-algo special-casing.
- **Discrete/continuous action adapter**: the mixture presents ONE
  discrete action space of width A = max over members (a discrete
  member's action count; `action_bins` levels for a continuous member).
  A discrete member takes `action % n_i`; a continuous member maps the
  index onto `linspace(-1, 1, action_bins)` in its normalized action
  convention — discretized control, the standard adapter for mixing a
  torque env into a discrete-policy fleet.
- **`lax.switch` over per-type step/reset fns**: every instance carries
  an int32 `type_id` in its state plus one state slot PER member type;
  branch i steps member i (through its own auto-resetting, scenario
  re-drawing `step`) and passes the other slots through untouched.
  Under `vmap` the switch lowers to a select over all branches — each
  instance pays the summed member step cost, the known price of SIMD
  heterogeneity (measured by `bench/suite.py scenario_fleet`'s
  mixture_overhead_x row); the win is that the WHOLE fleet stays inside
  one compiled program with zero host round-trips.
- **Type-preserving auto-reset**: an episode end re-rolls the member's
  scenario params from the instance's own PRNG stream (the member's
  `auto_reset` does this already) while the type id is preserved. With
  `redraw_types=True` (the curriculum mode) the end of an episode
  additionally re-draws the instance's TYPE from the `weights`
  distribution carried in the state — a traced input, so shifting the
  distribution never recompiles — and fresh-resets the newly drawn
  member; when the draw lands on the same type, the member's own
  auto-reset result is kept bit-for-bit, which is what makes a
  single-type mixture exactly equal to the homogeneous member fleet
  (tested in tests/test_mixture.py).

Curriculum (ISSUE 11): `Curriculum`/`CurriculumController` implement the
host-side schedule — stage s advances to s+1 when learner eval progress
crosses `thresholds[s]`, installing `stage_weights[s]` into the fleet
via `set_fleet_weights` (weights AND stage ride the env state inside the
train state, so orbax checkpoints carry them and a resumed run continues
the schedule; `CurriculumController.sync` re-aligns the host counter
from the restored state). `parse_curriculum` owns the `--curriculum`
spec grammar: `"THRESHOLD:w0,w1,..;THRESHOLD:w0,w1,.."`.

Per-type eval matrix: `make_typed_eval` builds ONE jitted eval program
whose fleet is pinned to a traced `type_id` (`reset_typed`), so the
per-type return/solved matrix costs one compile total, not one per
type; `scripts/run_report.py` renders it and the sampler-registry gauge
`mixture_eval` exports it at `/metrics`. The program is AOT-warmed via
the `mixture.make_typed_eval` registry planner below.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from actor_critic_tpu.envs.jax_env import EnvSpec, JaxEnv, StepOutput


def member_makers() -> dict[str, Callable[..., JaxEnv]]:
    """Name → maker for every env type a mixture can include (vector-obs
    members only; lazy so importing this module stays light)."""
    from actor_critic_tpu.envs.acrobot import make_acrobot
    from actor_critic_tpu.envs.cartpole import make_cartpole
    from actor_critic_tpu.envs.maze import make_maze
    from actor_critic_tpu.envs.pendulum import make_pendulum

    return {
        "cartpole": make_cartpole,
        "pendulum": make_pendulum,
        "acrobot": make_acrobot,
        "maze": make_maze,
    }


# Per-member "solved" bars for the eval matrix gauges (greedy eval
# return at or above the bar counts as solved). CartPole's is the
# repo's 475 certification bar; the others are the conventional
# classic-control bars / a reached-the-goal maze return.
SOLVE_BARS: dict[str, float] = {
    "cartpole": 475.0,
    "pendulum": -300.0,
    "acrobot": -100.0,
    "maze": 0.0,
}


def parse_mixture_spec(spec) -> list[tuple[str, float]]:
    """`"cartpole*2,pendulum,acrobot"` → [(name, weight), ...].

    Weights default to 1; `name*W` sets the type's draw weight (the
    `--env mixture:cartpole*2,pendulum` spelling). Order defines the
    type-id numbering. Duplicates are rejected (one state slot per
    TYPE; weight the draw instead of repeating the member)."""
    if isinstance(spec, str):
        parts = [p.strip() for p in spec.split(",") if p.strip()]
    else:
        parts = [str(p) for p in spec]
    if not parts:
        raise ValueError("mixture spec names no members")
    valid = member_makers()
    out: list[tuple[str, float]] = []
    for part in parts:
        name, _, w = part.partition("*")
        name = name.strip()
        if name not in valid:
            raise ValueError(
                f"unknown mixture member {name!r}; valid: {sorted(valid)}"
            )
        if any(name == n for n, _ in out):
            raise ValueError(
                f"duplicate mixture member {name!r} — weight the draw "
                f"('{name}*2') instead of repeating the member"
            )
        try:
            weight = float(w) if w else 1.0
        except ValueError:
            raise ValueError(f"bad weight in mixture member {part!r}")
        if weight < 0 or (w and weight != weight):
            raise ValueError(f"mixture weight must be >= 0, got {part!r}")
        out.append((name, weight))
    if not any(weight > 0 for _, weight in out):
        raise ValueError("mixture weights must not all be zero")
    return out


class MixtureState(NamedTuple):
    """Per-instance fleet state: the active type, one state slot per
    member type (only the active slot is live; the others are parked at
    their last episode start), the mixture-level PRNG key (type
    re-draws only — member streams stay untouched, preserving bitwise
    equivalence with homogeneous fleets), and the curriculum-controlled
    draw distribution + stage (traced, so re-weighting never
    recompiles; checkpointed with the train state)."""

    type_id: jax.Array
    members: tuple
    key: jax.Array
    weights: jax.Array  # [n_types] f32 draw weights
    stage: jax.Array    # int32 curriculum stage


@dataclasses.dataclass(frozen=True, eq=False)
class MixtureEnv(JaxEnv):
    """A JaxEnv whose fleet mixes member types, plus the mixture-only
    surface: member metadata, the static obs-validity mask table,
    type-pinned resets for the per-type eval matrix, and the initial
    draw weights (`eq=False` keeps JaxEnv's identity hash)."""

    member_names: tuple[str, ...] = ()
    member_specs: tuple[EnvSpec, ...] = ()
    obs_masks: Any = None            # [n_types, obs_max] f32
    init_weights: tuple[float, ...] = ()
    reset_typed: Optional[Callable] = None  # (key, type_id) -> (state, obs)
    redraw_types: bool = False

    @property
    def n_types(self) -> int:
        return len(self.member_names)


def make_mixture(
    members: Any = "cartpole,pendulum,acrobot,maze",
    randomize: float = 0.0,
    action_bins: int = 5,
    redraw_types: bool = False,
    member_kwargs: Optional[dict] = None,
) -> MixtureEnv:
    """Build the heterogeneous mixture fleet env.

    `members` is a spec string (`"cartpole*2,pendulum,acrobot"`) or a
    name sequence; `randomize` is forwarded to every member's scenario
    draw; `action_bins` sets the discretization of continuous members'
    action range; `redraw_types` re-draws an instance's TYPE from the
    state-carried weights at each episode end (required for the
    curriculum; off by default so types are preserved across
    auto-reset). `member_kwargs` maps member name → extra maker kwargs
    (e.g. {"maze": {"size": 6}}).
    """
    if action_bins < 2:
        raise ValueError(f"action_bins must be >= 2, got {action_bins}")
    parsed = parse_mixture_spec(members)
    names = tuple(n for n, _ in parsed)
    init_weights = tuple(w for _, w in parsed)
    makers = member_makers()
    member_kwargs = dict(member_kwargs or {})
    unknown = sorted(set(member_kwargs) - set(names))
    if unknown:
        raise ValueError(
            f"member_kwargs for non-member(s) {unknown}; members: {names}"
        )
    envs = tuple(
        makers[n](randomize=randomize, **member_kwargs.get(n, {}))
        for n in names
    )
    n = len(envs)
    for name, e in zip(names, envs):
        if len(e.spec.obs_shape) != 1:
            raise ValueError(
                f"mixture members need vector obs; {name!r} has shape "
                f"{e.spec.obs_shape}"
            )
    widths = tuple(e.spec.obs_shape[0] for e in envs)
    obs_max = max(widths)
    masks = jnp.asarray(
        [[1.0] * w + [0.0] * (obs_max - w) for w in widths], jnp.float32
    )
    n_actions = tuple(
        e.spec.action_dim if e.spec.discrete else action_bins for e in envs
    )
    action_dim = max(n_actions)
    levels = jnp.linspace(-1.0, 1.0, action_bins, dtype=jnp.float32)

    def _pad(i: int, obs: jax.Array) -> jax.Array:
        # Mask-multiplied zero pad: padded lanes are exactly 0.0 even if
        # a member emitted NaN/garbage outside its width (there is no
        # such member today; the multiply is the contract, not a patch).
        return jnp.pad(obs, (0, obs_max - widths[i])) * masks[i]

    def _adapt(i: int, action: jax.Array):
        a = action.astype(jnp.int32)
        if envs[i].spec.discrete:
            return a % n_actions[i]
        # Continuous member: discretized normalized action. Members use
        # the scale-to-bounds convention (e.g. pendulum maps [-1, 1]
        # onto its torque range), matching levels' range.
        u = levels[a % action_bins]
        return jnp.full((envs[i].spec.action_dim,), u, jnp.float32)

    def _make_step_branch(i: int):
        def branch(members_tuple, action):
            out = envs[i].step(members_tuple[i], _adapt(i, action))
            new_members = (
                members_tuple[:i] + (out.state,) + members_tuple[i + 1:]
            )
            return (
                new_members,
                _pad(i, out.obs),
                out.reward.astype(jnp.float32),
                out.done,
                out.info["terminated"],
                _pad(i, out.info["final_obs"]),
            )
        return branch

    def _make_reset_branch(i: int):
        def branch(members_tuple, key):
            s, o = envs[i].reset(key)
            return (
                members_tuple[:i] + (s,) + members_tuple[i + 1:],
                _pad(i, o),
            )
        return branch

    step_branches = [_make_step_branch(i) for i in range(n)]
    reset_branches = [_make_reset_branch(i) for i in range(n)]

    def _fresh(key: jax.Array, type_id: jax.Array, weights: jax.Array):
        ks = jax.random.split(key, n + 1)
        states, obss = [], []
        for i, e in enumerate(envs):
            s, o = e.reset(ks[i])
            states.append(s)
            obss.append(_pad(i, o))
        obs = jnp.stack(obss)[type_id]
        state = MixtureState(
            type_id=type_id.astype(jnp.int32),
            members=tuple(states),
            key=ks[n],
            weights=weights,
            stage=jnp.zeros((), jnp.int32),
        )
        return state, obs

    init_w = jnp.asarray(init_weights, jnp.float32)

    def reset(key: jax.Array):
        key, tkey = jax.random.split(key)
        # Guarded normalization (ISSUE 14, nonfinite-hazard): an
        # all-zero weight vector (a curriculum stage zeroing every
        # type) would make the draw probabilities 0/0 = nan — and a
        # bare denominator floor would silently bias every draw to
        # type 0; degrade to a UNIFORM draw instead (visible, unbiased).
        # Bit-identical for any real (positive-sum) weight vector.
        s = jnp.sum(init_w)
        type_id = jax.random.choice(
            tkey, n,
            p=jnp.where(s > 0, init_w / jnp.maximum(s, 1e-6), 1.0 / n),
        )
        return _fresh(key, type_id, init_w)

    def reset_typed(key: jax.Array, type_id: jax.Array):
        # Type-pinned fleet for the per-type eval matrix: one-hot
        # weights so redraw_types keeps the pin across episode ends.
        # type_id is TRACED — one compiled eval program covers every
        # type (the compile-once contract, tests/test_compile_cache.py).
        type_id = jnp.asarray(type_id, jnp.int32)
        return _fresh(key, type_id, jax.nn.one_hot(type_id, n))

    def step(state: MixtureState, action: jax.Array) -> StepOutput:
        new_members, obs, reward, done, terminated, final_obs = jax.lax.switch(
            state.type_id, step_branches, state.members, action
        )
        info = {"terminated": terminated, "final_obs": final_obs}
        if not redraw_types:
            out_state = state._replace(members=new_members)
            info["type_id"] = out_state.type_id
            return StepOutput(out_state, obs, reward, done, info)

        # Curriculum mode: at an episode end, re-draw the instance's
        # type from the state-carried weights and fresh-reset the new
        # member. A draw landing on the SAME type keeps the member's
        # own auto-reset result untouched (the bitwise single-type
        # equivalence contract); only a genuine type change swaps in
        # the mixture-keyed reset.
        key, tkey, rkey = jax.random.split(state.key, 3)
        # Same guarded normalization as reset(): uniform on a zeroed
        # weight vector, bit-identical otherwise.
        ws = jnp.sum(state.weights)
        drawn = jax.random.choice(
            tkey, n,
            p=jnp.where(
                ws > 0, state.weights / jnp.maximum(ws, 1e-6), 1.0 / n
            ),
        ).astype(jnp.int32)
        new_type = jnp.where(done > 0, drawn, state.type_id)
        changed = (done > 0) & (new_type != state.type_id)
        r_members, r_obs = jax.lax.switch(
            new_type, reset_branches, new_members, rkey
        )

        def sel(a, b):
            c = changed.reshape(changed.shape + (1,) * (a.ndim - changed.ndim))
            return jnp.where(c, a, b)

        out_state = MixtureState(
            type_id=new_type,
            members=jax.tree.map(sel, r_members, new_members),
            key=key,
            weights=state.weights,
            stage=state.stage,
        )
        info["type_id"] = new_type
        return StepOutput(out_state, sel(r_obs, obs), reward, done, info)

    spec = EnvSpec(
        obs_shape=(obs_max,),
        action_dim=action_dim,
        discrete=True,
        can_truncate=any(e.spec.can_truncate for e in envs),
        episode_horizon=max(e.spec.episode_horizon for e in envs),
    )
    return MixtureEnv(
        spec=spec, reset=reset, step=step,
        member_names=names,
        member_specs=tuple(e.spec for e in envs),
        obs_masks=masks,
        init_weights=init_weights,
        reset_typed=reset_typed,
        redraw_types=redraw_types,
    )


def set_fleet_weights(env_state: MixtureState, weights, stage: int) -> MixtureState:
    """Install curriculum weights + stage into a (vmapped) fleet state —
    the host-side application point between dispatches. Shapes/dtypes
    are preserved exactly, so the jitted train step never retraces."""
    w = jnp.asarray(weights, jnp.float32)
    return env_state._replace(
        weights=jnp.broadcast_to(w, env_state.weights.shape).astype(
            env_state.weights.dtype
        ),
        stage=jnp.full_like(env_state.stage, stage),
    )


def fleet_stage(env_state: MixtureState) -> int:
    """The curriculum stage carried by a (vmapped) fleet state — the
    resume hook `CurriculumController.sync` reads."""
    return int(jnp.asarray(env_state.stage).reshape(-1)[0])


# ---------------------------------------------------------------------------
# Curriculum schedule
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Curriculum:
    """Stage s advances to s+1 when eval progress crosses
    `thresholds[s]`; entering stage s+1 installs `stage_weights[s]`
    (stage 0 runs the mixture's own init weights)."""

    thresholds: tuple[float, ...]
    stage_weights: tuple[tuple[float, ...], ...]

    def __post_init__(self):
        if len(self.thresholds) != len(self.stage_weights):
            raise ValueError(
                "curriculum needs one weight vector per threshold"
            )
        if any(
            b <= a for a, b in zip(self.thresholds, self.thresholds[1:])
        ):
            raise ValueError(
                f"curriculum thresholds must be strictly increasing, "
                f"got {self.thresholds}"
            )
        for w in self.stage_weights:
            if not any(x > 0 for x in w):
                raise ValueError("curriculum stage weights all zero")

    @property
    def n_stages(self) -> int:
        return len(self.thresholds) + 1


def parse_curriculum(spec: str, member_names: tuple[str, ...]) -> Curriculum:
    """`--curriculum` grammar: `"THR:w0,w1,..;THR:w0,w1,.."` — one
    `threshold:weights` stage per semicolon-separated entry, weights in
    member order (as many as the mixture has members)."""
    thresholds: list[float] = []
    weights: list[tuple[float, ...]] = []
    for entry in (e.strip() for e in spec.split(";")):
        if not entry:
            continue
        thr, sep, ws = entry.partition(":")
        if not sep:
            raise ValueError(
                f"curriculum stage {entry!r} is not 'THRESHOLD:w0,w1,..'"
            )
        try:
            thresholds.append(float(thr))
            w = tuple(float(x) for x in ws.split(","))
        except ValueError:
            raise ValueError(f"bad curriculum stage {entry!r}")
        if len(w) != len(member_names):
            raise ValueError(
                f"curriculum stage {entry!r} has {len(w)} weights; the "
                f"mixture has {len(member_names)} members {member_names}"
            )
        weights.append(w)
    if not thresholds:
        raise ValueError(f"curriculum spec {spec!r} names no stages")
    return Curriculum(tuple(thresholds), tuple(weights))


class CurriculumController:
    """Host-side schedule state: feed it each eval's progress metric and
    apply what it returns. Single-threaded by design (the fused loop's
    log path owns it)."""

    def __init__(self, curriculum: Curriculum):
        self.curriculum = curriculum
        self.stage = 0

    def sync(self, stage: int) -> None:
        """Re-align from a restored fleet state (resume continues the
        schedule instead of replaying stage 0)."""
        self.stage = max(self.stage, min(int(stage), self.curriculum.n_stages - 1))

    def update(self, progress: float) -> Optional[tuple[int, tuple[float, ...]]]:
        """Advance through every threshold `progress` has crossed;
        returns (new stage, weights to install) when the stage moved,
        None otherwise. Stages only ever move forward — a later bad
        eval never demotes the fleet."""
        advanced = None
        cur = self.curriculum
        while (
            self.stage < len(cur.thresholds)
            and progress >= cur.thresholds[self.stage]
        ):
            self.stage += 1
            advanced = (self.stage, cur.stage_weights[self.stage - 1])
        return advanced


# ---------------------------------------------------------------------------
# Per-type eval matrix
# ---------------------------------------------------------------------------

def make_typed_eval(env: MixtureEnv, net):
    """Greedy per-type eval program: `eval_fn(state, key, type_id,
    num_envs=16, num_steps=...)` evaluates the CURRENT policy on a
    fleet pinned to `type_id` (traced — one program serves every type;
    jit with static_argnums=(3, 4)). `net` is the actor-critic network
    whose `apply(params, obs) → (dist, value)` and whose params live at
    `state.params` (a2c/ppo/impala)."""
    from actor_critic_tpu.algos.common import default_eval_steps, evaluate

    default_steps = default_eval_steps(env)

    def act(params, obs):
        dist, _ = net.apply(params, obs)
        return dist.mode()

    def eval_fn(state, key, type_id, num_envs: int = 16,
                num_steps: int = default_steps):
        type_id = jnp.asarray(type_id, jnp.int32)
        return evaluate(
            env, act, state.params, key, num_envs, num_steps,
            reset_fn=lambda k: env.reset_typed(k, type_id),
        )

    return eval_fn


def eval_matrix_row(name: str, ret: float) -> dict[str, float]:
    """Flat gauge fields for one member's eval result (flat so the
    Prometheus exporter's one-level dict flattening renders them)."""
    bar = SOLVE_BARS.get(name)
    row = {f"{name}_return": round(float(ret), 3)}
    if bar is not None:
        row[f"{name}_solved"] = float(ret >= bar)
    return row


# -- AOT warmup registry (utils/compile_cache.py, ISSUE 4) ------------------
from actor_critic_tpu.utils import compile_cache as _compile_cache  # noqa: E402


@_compile_cache.register_warmup("mixture.make_typed_eval")
def _typed_eval_planner(ctx):
    """Warm the per-type eval program for fused mixture runs with eval
    on (the train/eval step programs themselves are warmed by the
    per-algo `<algo>.make_train_step`/`make_eval_fn` planners, which
    already take the mixture env through `ctx.env`)."""
    if not ctx.fused or ctx.eval_every <= 0:
        return None
    if not isinstance(ctx.env, MixtureEnv):
        return None
    modules = {"a2c": "a2c", "ppo": "ppo", "impala": "impala",
               "a3c": "impala"}
    if ctx.algo not in modules:
        return None
    import importlib

    mod = importlib.import_module(
        f"actor_critic_tpu.algos.{modules[ctx.algo]}"
    )
    state_abs = _compile_cache.fused_state_struct(ctx, mod.init_state)
    ev = jax.jit(
        make_typed_eval(ctx.env, mod.make_network(ctx.env, ctx.cfg)),
        static_argnums=(3, 4),
    )
    k = _compile_cache.key_struct()
    t = _compile_cache.scalar_struct(jnp.int32)
    return lambda: _compile_cache.aot_compile(ev, state_abs, k, t)
