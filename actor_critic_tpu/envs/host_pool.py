"""Host environment pool: gymnasium/MuJoCo behind the JaxEnv-like protocol.

The reference steps single host envs inline with the session loop
(SURVEY.md §3.1-3.2; reference mount empty, §0). Here host envs are a
batched pool (SyncVectorEnv, SAME_STEP autoreset) whose step/reset
semantics mirror envs/jax_env.py exactly — `done` marks the ending step,
`final_obs` carries the pre-reset observation, the returned obs is the
new episode's — so trainers see one protocol regardless of backend.

Includes the genre-standard MuJoCo preprocessing (SURVEY §2.1 "Env
wrappers"): running mean/std observation normalization (clipped) and
discounted-return-scale reward normalization, both checkpointable via
`get_state`/`set_state`.

On this machine the host has a single CPU core (SURVEY §7.0), so the pool
is the throughput-limiting path by design; the trainers overlap device
compute with host stepping where it matters (SURVEY §7.2 item 2).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

from actor_critic_tpu.envs.jax_env import EnvSpec


class RunningMeanStd:
    """Welford-style running mean/variance over batches (float64 host-side)."""

    def __init__(self, shape: tuple[int, ...]):
        self.mean = np.zeros(shape, np.float64)
        self.var = np.ones(shape, np.float64)
        self.count = 1e-4

    def update(self, x: np.ndarray) -> None:
        bmean = x.mean(axis=0)
        bvar = x.var(axis=0)
        bcount = x.shape[0]
        delta = bmean - self.mean
        tot = self.count + bcount
        self.mean = self.mean + delta * bcount / tot
        m_a = self.var * self.count
        m_b = bvar * bcount
        m2 = m_a + m_b + delta**2 * self.count * bcount / tot
        self.var = m2 / tot
        self.count = tot

    def normalize(self, x: np.ndarray, clip: float) -> np.ndarray:
        z = (x - self.mean) / np.sqrt(self.var + 1e-8)
        return np.clip(z, -clip, clip).astype(np.float32)

    def state_dict(self) -> dict[str, Any]:
        return {"mean": self.mean, "var": self.var, "count": self.count}

    def load_state_dict(self, d: dict[str, Any]) -> None:
        self.mean = np.asarray(d["mean"], np.float64)
        self.var = np.asarray(d["var"], np.float64)
        self.count = float(d["count"])


@dataclasses.dataclass
class HostStepOutput:
    obs: np.ndarray          # post-reset obs (normalized)
    reward: np.ndarray       # normalized reward
    raw_reward: np.ndarray   # unnormalized (for episode-return reporting)
    done: np.ndarray         # 1.0 where episode ended this step
    terminated: np.ndarray   # true termination (cuts bootstrap)
    final_obs: np.ndarray    # pre-reset obs (normalized); == obs if not done


def scalable_bounds(discrete: bool, low, high) -> bool:
    """Whether an action space supports the [-1,1]→Box affine map: a
    continuous Box with finite bounds (an infinite bound would make the
    mid/half-range constants inf/nan and every scaled action nan)."""
    return not discrete and bool(
        np.isfinite(low).all() and np.isfinite(high).all()
    )


class HostEnvPool:
    """Batched gymnasium envs with normalization, one `step(actions)` call.

    Actions: for Box spaces the policy's raw (Gaussian) actions are clipped
    to the space bounds; for Discrete they pass through as int arrays.
    With `scale_actions=True` the pool instead treats policy actions as
    normalized [-1, 1] and affine-maps them onto the Box bounds — the
    standard tanh-policy convention. This keeps the REPLAYED action
    consistent with the EXECUTED one on envs whose bounds are narrower
    than [-1, 1] (Humanoid-v5's ±0.4: clipping executes ±0.4 while the
    buffer stores the raw sample, so Q(s,a) trains on actions that were
    never taken; scaling removes the mismatch and restores full actuator
    authority). Off by default: recorded runs used clip semantics, and
    the flag must never change under a resumed process.

    `workers=W > 1` shards the gym backend's E envs across W worker
    processes (envs/shard_pool.py): shared-memory step exchange, global
    per-env seeding, SAME_STEP autoreset per shard — trajectories AND
    normalization statistics identical to `workers=1` at fixed seeds,
    but slow simulator steps overlap across workers. `workers=1`
    (default) is the in-process SyncVectorEnv, unchanged.
    """

    def __init__(
        self,
        env_id: str,
        num_envs: int,
        seed: int = 0,
        normalize_obs: bool = True,
        normalize_reward: bool = True,
        clip_obs: float = 10.0,
        clip_reward: float = 10.0,
        gamma: float = 0.99,
        backend: str = "gym",
        pixel_preprocess: bool = False,
        scale_actions: bool = False,
        env_kwargs: dict | None = None,
        workers: int = 1,
        worker_env_kwargs: list[dict | None] | None = None,
    ):
        self.env_id = env_id
        self.num_envs = num_envs
        env_kwargs = dict(env_kwargs or {})
        if pixel_preprocess and backend != "gym":
            raise ValueError("pixel_preprocess applies to the gym backend only")
        if worker_env_kwargs is not None and workers <= 1:
            raise ValueError(
                "worker_env_kwargs needs the sharded gym backend "
                "(workers > 1); with one process pass env_kwargs"
            )
        if env_kwargs and backend != "gym":
            raise ValueError(
                "env_kwargs go to gym.make; the native engine takes none"
            )
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        if workers > 1 and backend != "gym":
            raise ValueError(
                "workers applies to the gym backend only (the native "
                "engine already steps the whole batch in one C call)"
            )
        self._workers = int(workers)
        if backend == "native":
            # First-party C++ batched engine: one C call per batch step
            # (envs/native_pool.py; native/vecenv.cpp).
            from actor_critic_tpu.envs.native_pool import NativeVecEnv

            self._envs = NativeVecEnv(env_id, num_envs)
        elif backend == "gym":
            if self._workers > 1:
                # Sharded multi-process pool (envs/shard_pool.py): same
                # env factory, same SAME_STEP semantics per shard, global
                # per-env seeding — trajectories match the workers=1 path
                # bit-for-bit at fixed seeds (tests/test_shard_pool.py).
                from actor_critic_tpu.envs.shard_pool import ShardedVecEnv

                self._envs = ShardedVecEnv(
                    env_id, num_envs, workers=self._workers,
                    env_kwargs=env_kwargs,
                    pixel_preprocess=pixel_preprocess,
                    worker_env_kwargs=worker_env_kwargs,
                )
            else:
                from gymnasium.vector import AutoresetMode, SyncVectorEnv

                from actor_critic_tpu.envs.shard_pool import make_host_env

                self._envs = SyncVectorEnv(
                    [
                        (lambda: make_host_env(
                            env_id, env_kwargs, pixel_preprocess
                        ))
                        for _ in range(num_envs)
                    ],
                    autoreset_mode=AutoresetMode.SAME_STEP,
                )
        else:
            raise ValueError(f"backend must be 'gym' or 'native', got {backend!r}")
        try:
            space = self._envs.single_action_space
            obs_space = self._envs.single_observation_space
            self._discrete = hasattr(space, "n")
            if self._discrete:
                action_dim = int(space.n)
                self._act_low = self._act_high = None
            else:
                action_dim = int(np.prod(space.shape))
                self._act_low = np.asarray(space.low, np.float32)
                self._act_high = np.asarray(space.high, np.float32)
            if scale_actions and not scalable_bounds(
                self._discrete, self._act_low, self._act_high
            ):
                raise ValueError(
                    "scale_actions needs a finite continuous action Box"
                )
        except Exception:
            # The backend is already live (sharded pools hold worker
            # PROCESSES and a registered sampler gauge) — a validation
            # failure must tear it down, not leak it.
            self._envs.close()
            raise
        self._scale_actions = scale_actions
        if scale_actions:
            self._act_mid = 0.5 * (self._act_high + self._act_low)
            self._act_half = 0.5 * (self._act_high - self._act_low)
        # uint8 pixel obs keep their dtype (the CNN's /255 branch fires on
        # it); everything else is delivered as float32 regardless of the
        # env's native dtype — MuJoCo emits float64, and letting that flow
        # into host buffers/transfers would double memory for no benefit.
        raw_dtype = np.dtype(obs_space.dtype)
        self.spec = EnvSpec(
            obs_shape=tuple(obs_space.shape),
            action_dim=action_dim,
            discrete=self._discrete,
            can_truncate=True,
            obs_dtype=raw_dtype if raw_dtype == np.uint8 else np.dtype(np.float32),
        )
        self._seed = seed
        self._normalize_obs = normalize_obs
        self._normalize_reward = normalize_reward
        self._clip_obs = clip_obs
        self._clip_reward = clip_reward
        self._gamma = gamma
        self._frozen_stats = False
        self.obs_rms = RunningMeanStd(tuple(obs_space.shape))
        self.ret_rms = RunningMeanStd(())
        self._returns = np.zeros(num_envs, np.float64)
        self._backend = backend
        self._pixel_preprocess = pixel_preprocess
        self._env_kwargs = env_kwargs

    @property
    def normalizes_obs(self) -> bool:
        """Whether observations are normalized with running stats — part of
        the pool's public contract because resume-time compatibility checks
        (algos/host_loop.host_resume) depend on it."""
        return self._normalize_obs

    @property
    def scales_actions(self) -> bool:
        """Whether policy actions are affine-mapped from [-1,1] onto the
        action Box (vs clipped) — public for the same resume-time
        compatibility checks as `normalizes_obs`."""
        return self._scale_actions

    def eval_pool(self, num_envs: int = 4, seed: int = 1234) -> "HostEnvPool":
        """A companion pool for greedy evaluation: same env/backend and the
        SAME obs-normalization statistics (shared by reference, read-only —
        eval must see the training policy's input distribution), raw
        rewards (no reward normalization), fresh episodes. Per-worker
        constructor overrides (`worker_env_kwargs`) do NOT carry over:
        eval pools are uniform — a sleep-padded straggler shard is a
        collection testbed, not an eval condition."""
        pool = HostEnvPool(
            self.env_id, num_envs, seed=seed,
            normalize_obs=self._normalize_obs, normalize_reward=False,
            clip_obs=self._clip_obs, gamma=self._gamma,
            backend=self._backend, pixel_preprocess=self._pixel_preprocess,
            scale_actions=self._scale_actions,
            env_kwargs=self._env_kwargs,
            # Eval pools inherit the sharding (capped by their smaller E).
            workers=min(self._workers, num_envs),
        )
        pool.obs_rms = self.obs_rms  # aliased on purpose; frozen below
        pool._frozen_stats = True
        return pool

    # -- normalization ----------------------------------------------------
    def _norm_obs(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        if not self._normalize_obs:
            # uint8 pixel obs must reach the CNN encoder as uint8 so its
            # /255 branch fires (models/networks.py; same contract as
            # envs/pong.py); any other dtype is cast to float32 to match
            # spec.obs_dtype (float64 MuJoCo obs must not reach buffers).
            obs = np.asarray(obs)
            return obs if obs.dtype == np.uint8 else obs.astype(np.float32)
        obs = np.asarray(obs, np.float32)
        if update and not self._frozen_stats:
            self.obs_rms.update(obs)
        return self.obs_rms.normalize(obs, self._clip_obs)

    def _norm_reward(self, reward: np.ndarray, done: np.ndarray) -> np.ndarray:
        reward = np.asarray(reward, np.float64)
        if not self._normalize_reward:
            return reward.astype(np.float32)
        self._returns = self._returns * self._gamma * (1.0 - done) + reward
        self.ret_rms.update(self._returns)
        scaled = reward / np.sqrt(self.ret_rms.var + 1e-8)
        return np.clip(scaled, -self._clip_reward, self._clip_reward).astype(
            np.float32
        )

    # -- protocol ---------------------------------------------------------
    def reset(self) -> np.ndarray:
        obs, _ = self._envs.reset(seed=self._seed)
        self._returns[:] = 0.0
        return self._norm_obs(obs)

    def step(self, actions: np.ndarray) -> HostStepOutput:
        actions = np.asarray(actions)
        if self._discrete:
            actions = actions.astype(np.int64)
        elif self._scale_actions:
            a = np.clip(actions.astype(np.float32), -1.0, 1.0)
            actions = self._act_mid + self._act_half * a
        else:
            actions = np.clip(
                actions.astype(np.float32), self._act_low, self._act_high
            )
        obs, reward, term, trunc, info = self._envs.step(actions)
        term = np.asarray(term)
        trunc = np.asarray(trunc)
        done = (term | trunc).astype(np.float32)

        raw_obs = np.asarray(obs)
        fos = info.get("final_obs")
        if isinstance(fos, np.ndarray) and fos.dtype != object:
            # Native engine and the sharded pool: full [E, ...] numeric
            # array, already correct for non-done envs — no obs copy, no
            # per-env loop. Dtype-preserving (astype to the env's obs
            # dtype): uint8 pixel final_obs must stay uint8 here.
            final_obs = fos.astype(raw_obs.dtype, copy=False)
        else:
            # gymnasium object array of Optional rows (or no done envs):
            # start from a dtype-preserving obs copy, patch done rows.
            final_obs = raw_obs.copy()
            if fos is not None:
                for i, fo in enumerate(fos):
                    if fo is not None:
                        final_obs[i] = fo

        nobs = self._norm_obs(obs)
        # final_obs normalized with the SAME stats, not updating them twice.
        if self._normalize_obs:
            nfinal = self.obs_rms.normalize(final_obs, self._clip_obs)
        elif final_obs.dtype == np.uint8:  # same dtype policy as _norm_obs
            nfinal = final_obs
        else:
            nfinal = final_obs.astype(np.float32)
        nreward = self._norm_reward(reward, done)
        return HostStepOutput(
            obs=nobs,
            reward=nreward,
            raw_reward=np.asarray(reward, np.float32),
            done=done,
            terminated=term.astype(np.float32),
            final_obs=nfinal,
        )

    # -- telemetry ---------------------------------------------------------
    def drain_telemetry(self) -> int:
        """Relay the sharded backend's buffered per-worker span records
        into the installed telemetry session (envs/shard_pool.py); 0 for
        backends without worker processes."""
        fn = getattr(self._envs, "drain_telemetry", None)
        return 0 if fn is None else fn()

    def worker_stats(self) -> Optional[list[dict]]:
        """Per-worker step accounting (sharded backend only)."""
        fn = getattr(self._envs, "worker_stats", None)
        return None if fn is None else fn()

    # -- checkpointable state --------------------------------------------
    def get_state(self) -> dict[str, Any]:
        return {
            "obs_rms": self.obs_rms.state_dict(),
            "ret_rms": self.ret_rms.state_dict(),
            "returns": self._returns.copy(),
        }

    def set_state(self, state: dict[str, Any]) -> None:
        self.obs_rms.load_state_dict(state["obs_rms"])
        self.ret_rms.load_state_dict(state["ret_rms"])
        self._returns = np.asarray(state["returns"], np.float64).copy()

    def close(self) -> None:
        self._envs.close()
