"""Sharded multi-process host env pool (ISSUE 2 tentpole).

`HostEnvPool`'s gym backend steps E envs serially inside one
SyncVectorEnv, so a single slow simulator step stalls the whole batch
and a pool step costs E × per-env wall time (the host bound of SURVEY
§7.0/§7.2). `ShardedVecEnv` shards the E envs across W worker
processes — the GA3C / Accelerated-Methods batched-simulation design
(PAPERS.md 1611.06256, 1803.02811) — each worker holding its own
`gym.make` stack inside a per-shard SyncVectorEnv with SAME_STEP
autoreset, so step/reset/final_obs semantics are exactly the
single-process pool's. Per-step data moves through preallocated
shared-memory blocks:

    parent:   actions → shm, broadcast "step"        (one send per worker)
    worker w: SyncVectorEnv.step(act[lo:hi]) → obs / reward / terminated /
              truncated / final_obs slices written into shm[lo:hi]
    parent:   barrier (one ack per worker) → batched step output

One broadcast + one barrier per batch step; observations never pass
through pickle. Seeding is per-shard deterministic over GLOBAL env
indices: worker w seeds its SyncVectorEnv with [seed+lo .. seed+hi-1],
exactly the list one big SyncVectorEnv.reset(seed) derives, so a
sharded pool reproduces the single-process pool's trajectories
bit-for-bit at fixed seeds (tests/test_shard_pool.py).

Workers are SPAWNED, not forked: the parent has jax (and possibly the
axon TPU plugin) initialized, and forking a process with live XLA
threads can wedge the child. Spawn re-runs this container's axon site
hook, so the parent exports the disarm pair (JAX_PLATFORMS=cpu plus
empty PALLAS_AXON_POOL_IPS — the same pair as
`__graft_entry__.disarm_axon`, inlined here because the package cannot
import the repo-root entry module) around the spawns; workers never
touch a device.

Spawn's standard caveat applies: the constructing script must be
import-safe (pool construction behind `if __name__ == "__main__"` or
inside a function) — train.py and pytest both are.

Failure contract: a worker crash (env exception or process death)
surfaces as a RuntimeError from the pending barrier — never a hang.
Telemetry: workers buffer one span record per batch step (a bounded
deque), relayed to the parent once per collection block
(`drain_telemetry`, called by host_collect) and merged into
spans.jsonl under each worker's REAL pid — one Perfetto lane per
worker process; per-worker busy seconds also accumulate in a shared
stats block feeding `worker_stats()` and the pool-utilization gauge
registered with the resource sampler (telemetry/sampler.py
`register_gauge`).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import time
from typing import Any, Optional

import numpy as np


def make_host_env(env_id: str, env_kwargs: dict, pixel_preprocess: bool):
    """One gym env exactly as HostEnvPool's gym backend builds it (shared
    by the in-process SyncVectorEnv, the sharded workers, and the parent's
    space probe, so all three see identical spaces/wrappers)."""
    import gymnasium as gym

    e = gym.make(env_id, **env_kwargs)
    if pixel_preprocess:
        from actor_critic_tpu.envs.pixel_wrappers import PixelPreprocess

        e = PixelPreprocess(e)
    return e


def shard_bounds(num_envs: int, workers: int) -> list[tuple[int, int]]:
    """[lo, hi) global env-index range per worker; remainders go to the
    first shards so sizes differ by at most one."""
    base, extra = divmod(num_envs, workers)
    bounds, lo = [], 0
    for w in range(workers):
        hi = lo + base + (1 if w < extra else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def _shared_raw(ctx, dtype: np.dtype, shape: tuple[int, ...]):
    """Anonymous shared-memory block sized for (dtype, shape). RawArray
    (not named shared_memory): inheritable through Process args under
    spawn with no name-registry cleanup to leak."""
    n = max(int(np.prod(shape)), 1) * np.dtype(dtype).itemsize
    return ctx.RawArray("b", n)


def _np_view(raw, dtype: np.dtype, shape: tuple[int, ...]) -> np.ndarray:
    return np.frombuffer(raw, dtype=dtype).reshape(shape)


# Per-worker telemetry ring: one (epoch_start, dur_s) record per batch
# step, buffered locally and shipped to the parent on "drain" (once per
# collection block while a session is installed). Bounded so a run
# WITHOUT telemetry — which never drains — holds at most this many
# records per worker, the oldest rolling off.
_TELEMETRY_RING = 4096

# Phase name of the relayed records (the *_PHASE suffix keeps it visible
# to tests/test_span_names.py's canonical-vocabulary scan).
_WORKER_PHASE = "env_step_worker"


def _worker_main(
    conn, wid, env_id, env_kwargs, pixel_preprocess, lo, hi, raw, specs
):
    """Worker loop: own gym stack, commands in, shm slices out. Any
    exception is sent back as ("error", traceback) — the parent raises it
    at the barrier, so a crash is an error, not a hang."""
    import traceback
    from collections import deque

    try:
        from gymnasium.vector import AutoresetMode, SyncVectorEnv

        views = {k: _np_view(raw[k], *specs[k]) for k in raw}
        n = hi - lo
        envs = SyncVectorEnv(
            [
                (lambda: make_host_env(env_id, env_kwargs, pixel_preprocess))
                for _ in range(n)
            ],
            autoreset_mode=AutoresetMode.SAME_STEP,
        )
        stats = views["stats"]
        tel: deque = deque(maxlen=_TELEMETRY_RING)
        tel_dropped = 0
        while True:
            cmd, payload = conn.recv()
            if cmd == "reset":
                obs, _ = envs.reset(seed=payload)
                views["obs"][lo:hi] = obs
                conn.send(("ok", None))
            elif cmd == "drain":
                # Ship the buffered span records (wall-clock epoch start
                # + duration; time.time() is shared across processes on
                # one host, so the parent can place them on its tracer's
                # ts axis) and start a fresh buffer.
                conn.send(
                    ("ok", {"records": list(tel), "dropped": tel_dropped})
                )
                tel.clear()
                tel_dropped = 0
            elif cmd == "step":
                t_epoch = time.time()
                t0 = time.perf_counter()
                obs, rew, term, trunc, info = envs.step(
                    np.array(views["act"][lo:hi])
                )
                views["obs"][lo:hi] = obs
                views["reward"][lo:hi] = rew
                views["terminated"][lo:hi] = term
                views["truncated"][lo:hi] = trunc
                # Full numeric final_obs slice (pre-reset rows where done,
                # == obs elsewhere) — same contract as the native engine,
                # so the parent never unpacks gymnasium's object array.
                final = views["final_obs"]
                final[lo:hi] = obs
                fos = info.get("final_obs")
                if fos is not None:
                    for j, fo in enumerate(fos):
                        if fo is not None:
                            final[lo + j] = fo
                dt = time.perf_counter() - t0
                stats[wid, 0] += dt       # cumulative busy seconds
                stats[wid, 1] += n        # cumulative env steps
                stats[wid, 2] = dt        # last batch-step wall
                if len(tel) == tel.maxlen:
                    tel_dropped += 1
                tel.append((t_epoch, dt))
                conn.send(("ok", None))
            elif cmd == "close":
                envs.close()
                conn.send(("ok", None))
                return
    except (EOFError, KeyboardInterrupt):
        return  # parent went away; daemon worker just exits
    except Exception:
        try:
            conn.send(("error", traceback.format_exc()))
        except Exception:
            pass


class ShardedVecEnv:
    """E gym envs sharded over W spawned workers behind the SyncVectorEnv
    surface HostEnvPool consumes (`single_*_space`, `reset(seed=...)`,
    `step(actions) -> (obs, reward, term, trunc, info)`, `close()`).

    `info["final_obs"]` is a full [E, ...] numeric array in the env's
    native obs dtype (the native-engine convention), already correct for
    non-done rows.
    """

    def __init__(
        self,
        env_id: str,
        num_envs: int,
        workers: int,
        env_kwargs: Optional[dict] = None,
        pixel_preprocess: bool = False,
        step_timeout_s: float = 300.0,
        worker_env_kwargs: Optional[list[Optional[dict]]] = None,
    ):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        if workers > num_envs:
            raise ValueError(
                f"workers={workers} exceeds num_envs={num_envs}; an empty "
                "shard would idle a whole process"
            )
        self.num_envs = E = int(num_envs)
        self.num_workers = W = int(workers)
        env_kwargs = dict(env_kwargs or {})
        # Per-worker constructor overrides, merged over env_kwargs —
        # heterogeneous shards for straggler testbeds (one sleep-padded
        # worker among fast ones; bench async_decoupling, ISSUE 6) and
        # future per-shard scenario randomization. Overrides must not
        # change observation/action SPACES: the parent probes one env
        # with the BASE kwargs and sizes every shm block from it.
        if worker_env_kwargs is not None and len(worker_env_kwargs) != W:
            raise ValueError(
                f"worker_env_kwargs has {len(worker_env_kwargs)} entries "
                f"for workers={W}; need exactly one (or None) per worker"
            )
        self._worker_env_kwargs = [
            {**env_kwargs, **(worker_env_kwargs[w] or {})}
            if worker_env_kwargs is not None else env_kwargs
            for w in range(W)
        ]
        self._step_timeout_s = float(step_timeout_s)

        # Probe one env in-process for the spaces (wrappers included).
        probe = make_host_env(env_id, env_kwargs, pixel_preprocess)
        self.single_observation_space = probe.observation_space
        self.single_action_space = probe.action_space
        probe.close()
        obs_space = self.single_observation_space
        obs_dtype = np.dtype(obs_space.dtype)
        if hasattr(self.single_action_space, "n"):
            act_spec = (np.dtype(np.int64), (E,))
        else:
            # HostEnvPool delivers clipped/scaled float32 Box actions.
            act_spec = (np.dtype(np.float32), (E, *self.single_action_space.shape))
        specs: dict[str, tuple[np.dtype, tuple[int, ...]]] = {
            "act": act_spec,
            "obs": (obs_dtype, (E, *obs_space.shape)),
            "final_obs": (obs_dtype, (E, *obs_space.shape)),
            "reward": (np.dtype(np.float64), (E,)),
            "terminated": (np.dtype(np.bool_), (E,)),
            "truncated": (np.dtype(np.bool_), (E,)),
            "stats": (np.dtype(np.float64), (W, 3)),
        }
        ctx = mp.get_context("spawn")
        raw = {k: _shared_raw(ctx, dt, shp) for k, (dt, shp) in specs.items()}
        self._views = {k: _np_view(raw[k], *specs[k]) for k in specs}
        self._bounds = shard_bounds(E, W)
        self._conns: list[Any] = []
        self._procs: list[Any] = []
        # Spawned children re-run the axon site hook at interpreter start;
        # export the disarm pair for the spawn window so a worker can never
        # hang on the single-client TPU tunnel (pair documented in
        # __graft_entry__.disarm_axon).
        saved = {
            k: os.environ.get(k)
            for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")
        }
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        try:
            for w, (lo, hi) in enumerate(self._bounds):
                parent_conn, child_conn = ctx.Pipe()
                proc = ctx.Process(
                    target=_worker_main,
                    args=(
                        child_conn, w, env_id, self._worker_env_kwargs[w],
                        pixel_preprocess, lo, hi, raw, specs,
                    ),
                    daemon=True,
                    name=f"env-shard-{w}",
                )
                proc.start()
                child_conn.close()
                self._conns.append(parent_conn)
                self._procs.append(proc)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        self._closed = False
        self._gauge_prev = (time.monotonic(), 0.0)
        self._gauge_last_util = 0.0
        # The gauge is a stateful rate integrator with TWO independent
        # consumers since ISSUE 3 — the 5s sampler thread AND every
        # /metrics HTTP scrape (exporter → sample_row) — so its
        # read-modify-write needs a lock, and a scrape must not shrink
        # the sampler's utilization window to a meaningless sliver.
        import threading

        self._gauge_lock = threading.Lock()
        from actor_critic_tpu.telemetry import sampler as _sampler

        self._gauge_name = _sampler.register_gauge("host_pool", self._gauge)

    # -- parent⇄worker plumbing -------------------------------------------
    def _death_msg(self, w: int) -> str:
        rc = self._procs[w].exitcode
        return (
            f"env worker {w} died (exitcode={rc}) — the sharded pool is "
            "unusable; checkpoint-restart the run"
        )

    def _send(self, w: int, msg) -> None:
        try:
            self._conns[w].send(msg)
        except (BrokenPipeError, OSError):
            raise RuntimeError(self._death_msg(w)) from None

    def _await(self, w: int):
        conn, proc = self._conns[w], self._procs[w]
        deadline = time.monotonic() + self._step_timeout_s
        while True:
            try:
                if conn.poll(0.2):
                    kind, payload = conn.recv()
                    if kind == "error":
                        raise RuntimeError(
                            f"env worker {w} crashed:\n{payload}"
                        )
                    return payload
            except (EOFError, ConnectionResetError, OSError):
                raise RuntimeError(self._death_msg(w)) from None
            if not proc.is_alive() and not conn.poll(0.2):
                raise RuntimeError(self._death_msg(w))
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"env worker {w} gave no answer within "
                    f"{self._step_timeout_s:.0f}s (simulator wedged?)"
                )

    def _barrier(self) -> None:
        for w in range(self.num_workers):
            self._await(w)

    # -- SyncVectorEnv surface --------------------------------------------
    def reset(self, seed=None, options=None):
        if isinstance(seed, int):
            # SyncVectorEnv's int→list rule over GLOBAL indices, so shard
            # layout never changes which env gets which seed.
            seeds = [seed + i for i in range(self.num_envs)]
        elif seed is None:
            seeds = [None] * self.num_envs
        else:
            seeds = list(seed)
        for w, (lo, hi) in enumerate(self._bounds):
            self._send(w, ("reset", seeds[lo:hi]))
        self._barrier()
        return self._views["obs"].copy(), {}

    def step(self, actions: np.ndarray):
        self._views["act"][:] = actions
        for w in range(self.num_workers):
            self._send(w, ("step", None))
        self._barrier()
        v = self._views
        # Copies, not views: callers hold step outputs across the next
        # step, and the shm blocks are rewritten in place.
        return (
            v["obs"].copy(),
            v["reward"].copy(),
            v["terminated"].copy(),
            v["truncated"].copy(),
            {"final_obs": v["final_obs"].copy()},
        )

    def close(self) -> None:
        # Test-and-set under the gauge lock: close() can race itself
        # (training-loop teardown vs. an exception path unwinding), and
        # two callers passing the flag check would double-close the
        # worker pipes.
        with self._gauge_lock:
            if self._closed:
                return
            self._closed = True
        from actor_critic_tpu.telemetry import sampler as _sampler

        _sampler.unregister_gauge(self._gauge_name)
        for conn in self._conns:
            try:
                conn.send(("close", None))
            except (BrokenPipeError, OSError):
                pass
        for proc in self._procs:
            proc.join(timeout=5.0)
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=5.0)
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass

    # -- telemetry ---------------------------------------------------------
    def drain_telemetry(self) -> int:
        """Ship each worker's buffered per-step span records into the
        installed session's spans.jsonl with the worker's REAL pid, so
        Perfetto renders one lane per worker process (idle gaps between
        batch steps included) instead of the parent's synthetic busy-sum
        reconstruction. Called by host_collect once per collection block;
        returns the number of records merged (0 without a session)."""
        from actor_critic_tpu import telemetry

        s = telemetry.current()
        if s is None or self._closed:
            return 0
        # Consume EVERY worker's reply before emitting anything: an
        # emission failure mid-loop (e.g. spans.jsonl hitting ENOSPC)
        # must not leave unread "drain" acks in the pipes — the next
        # "step" barrier would consume a stale ack and every subsequent
        # exchange would read one-step-old shared memory.
        for w in range(self.num_workers):
            self._send(w, ("drain", None))
        payloads = [self._await(w) for w in range(self.num_workers)]
        batch = []
        for w, (lo, hi) in enumerate(self._bounds):
            payload = payloads[w]
            pid = self._procs[w].pid
            s.tracer.name_process(pid, f"env-shard-{w}")
            args = {"worker": w, "envs": hi - lo}
            batch.extend(
                (_WORKER_PHASE, t_epoch, dur, pid, 0, args)
                for t_epoch, dur in payload["records"]
            )
            if payload["dropped"]:
                telemetry.event(
                    "worker_telemetry_dropped",
                    worker=w, dropped=payload["dropped"],
                )
        # One locked write for the whole block's records (hot path:
        # runs on the training thread once per collection block).
        s.tracer.complete_foreign_many(batch)
        return len(batch)

    def worker_stats(self) -> list[dict]:
        stats = self._views["stats"]
        return [
            {
                "worker": w,
                "envs": hi - lo,
                "busy_s": round(float(stats[w, 0]), 4),
                "env_steps": int(stats[w, 1]),
                "last_step_s": round(float(stats[w, 2]), 6),
            }
            for w, (lo, hi) in enumerate(self._bounds)
        ]

    # Calls closer together than this reuse the previous utilization
    # instead of resetting the window: back-to-back /metrics scrapes (or
    # a scrape racing the sampler tick) would otherwise measure a
    # sliver-of-a-second window and report noise.
    _GAUGE_MIN_WINDOW_S = 1.0

    def _gauge(self) -> dict:
        """Pool-utilization row for the resource sampler AND /metrics
        scrapes: the busy fraction of the worker fleet over the window
        since the previous (window-resetting) call — the number that
        says whether the pool or the device is the bottleneck."""
        stats = self._views["stats"]
        busy = float(stats[:, 0].sum())
        with self._gauge_lock:
            now = time.monotonic()
            prev_t, prev_busy = self._gauge_prev
            dt = now - prev_t
            if dt >= self._GAUGE_MIN_WINDOW_S:
                util = (busy - prev_busy) / (dt * self.num_workers)
                self._gauge_last_util = round(min(max(util, 0.0), 1.0), 4)
                self._gauge_prev = (now, busy)
            util = self._gauge_last_util
        return {
            "workers": self.num_workers,
            "num_envs": self.num_envs,
            "env_steps": int(stats[:, 1].sum()),
            "busy_s": round(busy, 3),
            "utilization": util,
        }
