"""Pure-JAX Pendulum-v1 with exact gymnasium dynamics.

Gives the fused off-policy trainers (DDPG/TD3/SAC — rollout, HBM
replay, and updates in ONE XLA program, SURVEY.md §3.2) a real physical
continuous-control env on-device, complementing the analytic point-mass
testbed. Dynamics, reward (computed from the PRE-step state and the
clipped torque, as gymnasium does), reset distribution, torque/speed
clips, and the 200-step time limit match gymnasium 1.2.2's
`PendulumEnv` (verified numerically in tests/test_envs.py against the
installed gymnasium). The same dynamics also back the C++ engine
(native/vecenv.cpp) — this is the JAX twin for fused training.

Action convention: policies emit normalized actions in [-1, 1]
(tanh-Gaussian / clipped Gaussian); by default the env affine-maps them
onto the ±2.0 torque range — the same convention as
`HostEnvPool(scale_actions=True)` — so SAC's tanh actor has full
actuator authority. `make_pendulum(scale_actions=False)` takes raw
torques (clipped to ±2) for gymnasium-parity testing.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from actor_critic_tpu.envs.jax_env import EnvSpec, JaxEnv, auto_reset

GRAVITY = 10.0
MASS = 1.0
LENGTH = 1.0
DT = 0.05
MAX_SPEED = 8.0
MAX_TORQUE = 2.0
MAX_STEPS = 200


class PendulumState(NamedTuple):
    theta: jax.Array
    theta_dot: jax.Array
    t: jax.Array
    key: jax.Array


def _obs(s: PendulumState) -> jax.Array:
    return jnp.stack(
        [jnp.cos(s.theta), jnp.sin(s.theta), s.theta_dot]
    ).astype(jnp.float32)


def _angle_normalize(x: jax.Array) -> jax.Array:
    return ((x + jnp.pi) % (2.0 * jnp.pi)) - jnp.pi


def _reset(key: jax.Array) -> tuple[PendulumState, jax.Array]:
    key, sub = jax.random.split(key)
    vals = jax.random.uniform(sub, (2,), jnp.float32) * 2.0 - 1.0
    state = PendulumState(
        theta=vals[0] * jnp.pi,
        theta_dot=vals[1],
        t=jnp.zeros((), jnp.int32),
        key=key,
    )
    return state, _obs(state)


def make_pendulum(scale_actions: bool = True) -> JaxEnv:
    def _raw_step(state: PendulumState, action: jax.Array):
        a = action.reshape(())
        if scale_actions:
            u = jnp.clip(a, -1.0, 1.0) * MAX_TORQUE
        else:
            u = jnp.clip(a, -MAX_TORQUE, MAX_TORQUE)
        th, thdot = state.theta, state.theta_dot
        # Reward from the PRE-step state + clipped torque (gymnasium
        # returns -costs computed before integrating).
        costs = (
            _angle_normalize(th) ** 2 + 0.1 * thdot**2 + 0.001 * u**2
        )
        newthdot = thdot + (
            3.0 * GRAVITY / (2.0 * LENGTH) * jnp.sin(th)
            + 3.0 / (MASS * LENGTH**2) * u
        ) * DT
        newthdot = jnp.clip(newthdot, -MAX_SPEED, MAX_SPEED)
        newth = th + newthdot * DT
        t = state.t + 1

        nstate = PendulumState(newth, newthdot, t, state.key)
        terminated = jnp.zeros((), jnp.float32)  # never terminates
        truncated = (t >= MAX_STEPS).astype(jnp.float32)
        return nstate, _obs(nstate), -costs, terminated, truncated

    spec = EnvSpec(
        obs_shape=(3,), action_dim=1, discrete=False,
        episode_horizon=MAX_STEPS,
    )
    step = auto_reset(_reset, _raw_step, key_of_state=lambda s: s.key)
    return JaxEnv(spec=spec, reset=_reset, step=step)
