"""Pure-JAX Pendulum-v1 with exact gymnasium dynamics + scenario fleet.

Gives the fused off-policy trainers (DDPG/TD3/SAC — rollout, HBM
replay, and updates in ONE XLA program, SURVEY.md §3.2) a real physical
continuous-control env on-device, complementing the analytic point-mass
testbed. Dynamics, reward (computed from the PRE-step state and the
clipped torque, as gymnasium does), reset distribution, torque/speed
clips, and the 200-step time limit match gymnasium 1.2.2's
`PendulumEnv` (verified numerically in tests/test_envs.py against the
installed gymnasium). The same dynamics also back the C++ engine
(native/vecenv.cpp) — this is the JAX twin for fused training.

Action convention: policies emit normalized actions in [-1, 1]
(tanh-Gaussian / clipped Gaussian); by default the env affine-maps them
onto the ±max_torque range — the same convention as
`HostEnvPool(scale_actions=True)` — so SAC's tanh actor has full
actuator authority. `make_pendulum(scale_actions=False)` takes raw
torques (clipped to ±max_torque) for gymnasium-parity testing.

Scenario fleet (ISSUE 8): `make_pendulum(randomize=0.3)` (or per-param
ranges, e.g. `mass=(0.5, 2.0)` / `--env-set mass=0.5,2.0`) draws
per-instance gravity/mass/length/torque-scale in `reset`, stored in
`PendulumState.scenario`, so a vmapped fleet of thousands of different
pendulums steps — and feeds the quantized replay ring — inside one
fused XLA program; `auto_reset` re-draws per episode (envs/jax_env.py).
Defaults reproduce gymnasium exactly.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from actor_critic_tpu.envs.jax_env import (
    EnvSpec, JaxEnv, auto_reset, draw_scenario, scenario_ranges,
)

GRAVITY = 10.0
MASS = 1.0
LENGTH = 1.0
DT = 0.05
MAX_SPEED = 8.0
MAX_TORQUE = 2.0
MAX_STEPS = 200

SCENARIO_DEFAULTS = {
    "gravity": GRAVITY,
    "mass": MASS,
    "length": LENGTH,
    "max_torque": MAX_TORQUE,
}


class PendulumScenario(NamedTuple):
    """Per-instance physics + torque scale (f32 scalars in the state)."""

    gravity: jax.Array
    mass: jax.Array
    length: jax.Array
    max_torque: jax.Array


class PendulumState(NamedTuple):
    theta: jax.Array
    theta_dot: jax.Array
    t: jax.Array
    key: jax.Array
    scenario: PendulumScenario


def _obs(s: PendulumState) -> jax.Array:
    return jnp.stack(
        [jnp.cos(s.theta), jnp.sin(s.theta), s.theta_dot]
    ).astype(jnp.float32)


def _angle_normalize(x: jax.Array) -> jax.Array:
    return ((x + jnp.pi) % (2.0 * jnp.pi)) - jnp.pi


def make_pendulum(
    scale_actions: bool = True,
    randomize: float = 0.0,
    gravity=None,
    mass=None,
    length=None,
    max_torque=None,
) -> JaxEnv:
    ranges = scenario_ranges(
        SCENARIO_DEFAULTS, randomize,
        {"gravity": gravity, "mass": mass, "length": length,
         "max_torque": max_torque},
    )

    def _reset(key: jax.Array) -> tuple[PendulumState, jax.Array]:
        key, sub, skey = jax.random.split(key, 3)
        scenario = PendulumScenario(**draw_scenario(skey, ranges))
        vals = jax.random.uniform(sub, (2,), jnp.float32) * 2.0 - 1.0
        state = PendulumState(
            theta=vals[0] * jnp.pi,
            theta_dot=vals[1],
            t=jnp.zeros((), jnp.int32),
            key=key,
            scenario=scenario,
        )
        return state, _obs(state)

    def _raw_step(state: PendulumState, action: jax.Array):
        sc = state.scenario
        a = action.reshape(())
        if scale_actions:
            u = jnp.clip(a, -1.0, 1.0) * sc.max_torque
        else:
            u = jnp.clip(a, -sc.max_torque, sc.max_torque)
        th, thdot = state.theta, state.theta_dot
        # Reward from the PRE-step state + clipped torque (gymnasium
        # returns -costs computed before integrating).
        costs = (
            _angle_normalize(th) ** 2 + 0.1 * thdot**2 + 0.001 * u**2
        )
        newthdot = thdot + (
            3.0 * sc.gravity / (2.0 * sc.length) * jnp.sin(th)
            + 3.0 / (sc.mass * sc.length**2) * u
        ) * DT
        newthdot = jnp.clip(newthdot, -MAX_SPEED, MAX_SPEED)
        newth = th + newthdot * DT
        t = state.t + 1

        nstate = PendulumState(newth, newthdot, t, state.key, sc)
        terminated = jnp.zeros((), jnp.float32)  # never terminates
        truncated = (t >= MAX_STEPS).astype(jnp.float32)
        return nstate, _obs(nstate), -costs, terminated, truncated

    spec = EnvSpec(
        obs_shape=(3,), action_dim=1, discrete=False,
        episode_horizon=MAX_STEPS,
    )
    step = auto_reset(_reset, _raw_step, key_of_state=lambda s: s.key)
    return JaxEnv(spec=spec, reset=_reset, step=step)
