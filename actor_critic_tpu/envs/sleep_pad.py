"""Sleep-padded gym testbed env for host-pool scaling benchmarks/tests.

The sharded pool's win is overlapping per-env simulator WALL time, but
CI has no MuJoCo-scale simulator and the container may be single-core —
a CPU-bound env would show no multi-process speedup there. `SleepPadEnv`
pads every step with a `time.sleep(sleep_s)` (wall-bound, zero CPU), so
`bench/suite.py host_pool_scaling` measures real worker overlap on any
host. Dynamics are a deterministic drift on a 4-dim state, seeded
through gymnasium's `np_random`, so it also serves the sharded-vs-sync
trajectory-equivalence tests.

`crash_at_step > 0` raises inside `step()` once that many steps have run
in the env instance — the injection point for the worker-crash-surfaces-
as-error tests (a wedged pool must raise, never hang).

Make it from any process (sharded workers included) via gymnasium's
module-import id syntax — the module registers the env at import:

    gym.make("actor_critic_tpu.envs.sleep_pad:SleepPad-v0", sleep_s=0.002)
"""

from __future__ import annotations

import time
from typing import Optional

import gymnasium as gym
import numpy as np
from gymnasium import spaces

ENV_ID = "SleepPad-v0"
# The full id workers can gym.make with no prior registration import.
QUALIFIED_ENV_ID = f"{__name__}:{ENV_ID}"


class SleepPadEnv(gym.Env):
    metadata: dict = {"render_modes": []}

    def __init__(
        self,
        sleep_s: float = 0.0,
        horizon: int = 200,
        crash_at_step: int = 0,
    ):
        self.observation_space = spaces.Box(-np.inf, np.inf, (4,), np.float32)
        self.action_space = spaces.Discrete(2)
        self._sleep_s = float(sleep_s)
        self._horizon = int(horizon)
        self._crash_at_step = int(crash_at_step)
        self._t = 0
        self._lifetime_steps = 0
        self._state = np.zeros(4, np.float32)

    def reset(self, *, seed: Optional[int] = None, options=None):
        super().reset(seed=seed)
        self._t = 0
        self._state = self.np_random.uniform(-1.0, 1.0, size=4).astype(
            np.float32
        )
        return self._state.copy(), {}

    def step(self, action):
        self._lifetime_steps += 1
        if self._crash_at_step and self._lifetime_steps >= self._crash_at_step:
            raise RuntimeError(
                "SleepPadEnv: injected crash at lifetime step "
                f"{self._lifetime_steps} (crash_at_step={self._crash_at_step})"
            )
        if self._sleep_s > 0:
            time.sleep(self._sleep_s)
        self._t += 1
        drift = np.float32(0.01) * (np.float32(int(action)) * 2.0 - 1.0)
        self._state = (self._state + drift).astype(np.float32)
        reward = float(action)
        truncated = self._t >= self._horizon
        return self._state.copy(), reward, False, truncated, {}


CARTPOLE_ENV_ID = "SleepPadCartPole-v0"
QUALIFIED_CARTPOLE_ID = f"{__name__}:{CARTPOLE_ENV_ID}"


class SleepPadCartPoleEnv(gym.Env):
    """CartPole-v1 with a per-step wall-time pad: REAL dynamics (so a
    learner can be judged on eval return) under a simulator-shaped wall
    cost. The async-decoupling bench (`bench/suite.py
    async_decoupling`, ISSUE 6) pads one worker/actor to make a
    straggler while the rest run unpadded — lockstep collection slows
    to the straggler's pace at its sync barrier; the async queue does
    not. A plain delegating Env (not gym.Wrapper): registered entry
    points need a class-level `metadata` dict."""

    metadata: dict = {"render_modes": []}

    def __init__(self, sleep_s: float = 0.0):
        self._env = gym.make("CartPole-v1")
        self._sleep_s = float(sleep_s)
        self.observation_space = self._env.observation_space
        self.action_space = self._env.action_space

    def reset(self, *, seed: Optional[int] = None, options=None):
        return self._env.reset(seed=seed, options=options)

    def step(self, action):
        if self._sleep_s > 0:
            time.sleep(self._sleep_s)
        return self._env.step(action)

    def close(self):
        self._env.close()


if ENV_ID not in gym.registry:
    gym.register(
        id=ENV_ID,
        entry_point="actor_critic_tpu.envs.sleep_pad:SleepPadEnv",
    )
if CARTPOLE_ENV_ID not in gym.registry:
    gym.register(
        id=CARTPOLE_ENV_ID,
        entry_point="actor_critic_tpu.envs.sleep_pad:SleepPadCartPoleEnv",
    )
