from actor_critic_tpu.envs.jax_env import EnvSpec, JaxEnv, StepOutput, auto_reset
from actor_critic_tpu.envs.acrobot import make_acrobot
from actor_critic_tpu.envs.cartpole import make_cartpole
from actor_critic_tpu.envs.maze import make_maze
from actor_critic_tpu.envs.mixture import MixtureEnv, make_mixture, parse_mixture_spec
from actor_critic_tpu.envs.pendulum import make_pendulum
from actor_critic_tpu.envs.pong import make_pong
from actor_critic_tpu.envs.testbeds import (
    make_bandit,
    make_point_mass,
    make_two_state_mdp,
)

__all__ = [
    "EnvSpec",
    "JaxEnv",
    "MixtureEnv",
    "StepOutput",
    "auto_reset",
    "make_acrobot",
    "make_bandit",
    "make_cartpole",
    "make_maze",
    "make_mixture",
    "make_pendulum",
    "make_point_mass",
    "make_pong",
    "make_two_state_mdp",
    "parse_mixture_spec",
]
