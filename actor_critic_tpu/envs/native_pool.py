"""NativeVecEnv: the C++ batched env engine behind the vector-env API.

A drop-in for the gymnasium SyncVectorEnv inside `HostEnvPool` (same
SAME_STEP auto-reset semantics the pool already normalizes trainers
against): `reset(seed)` → (obs, info), `step(actions)` → (obs, reward,
terminated, truncated, info with `final_obs`). The entire batch steps in
ONE C call (native/vecenv.cpp), which on this 1-core host removes the
Python per-env loop that dominates gym stepping (SURVEY.md §7.2 item 2).

Supported env ids: CartPole-v1 and Acrobot-v1 (discrete), Pendulum-v1
and MountainCarContinuous-v0 (continuous) — exact gymnasium dynamics,
verified step-for-step against gymnasium in tests/test_native_pool.py.
"""

from __future__ import annotations

import ctypes

import numpy as np

_SPECS = {
    "CartPole-v1": dict(
        prefix="cartpole",
        state_dim=4, obs_dim=4, discrete=True, n_actions=2, max_steps=500,
        obs_high=np.array([4.8, np.inf, 0.41887903, np.inf], np.float32),
    ),
    "Pendulum-v1": dict(
        prefix="pendulum",
        state_dim=2, obs_dim=3, discrete=False, act_low=-2.0, act_high=2.0,
        max_steps=200,
        obs_high=np.array([1.0, 1.0, 8.0], np.float32),
    ),
    "MountainCarContinuous-v0": dict(
        prefix="mountaincar",
        state_dim=2, obs_dim=2, discrete=False, act_low=-1.0, act_high=1.0,
        max_steps=999,
        obs_low=np.array([-1.2, -0.07], np.float32),
        obs_high=np.array([0.6, 0.07], np.float32),
    ),
    "Acrobot-v1": dict(
        prefix="acrobot",
        state_dim=4, obs_dim=6, discrete=True, n_actions=3, max_steps=500,
        obs_high=np.array(
            [1.0, 1.0, 1.0, 1.0, 4 * np.pi, 9 * np.pi], np.float32
        ),
    ),
}


def supported(env_id: str) -> bool:
    return env_id in _SPECS


class NativeVecEnv:
    """Batched native envs with the gymnasium.vector API subset that
    `HostEnvPool` uses."""

    def __init__(self, env_id: str, num_envs: int):
        if env_id not in _SPECS:
            raise ValueError(
                f"native backend supports {sorted(_SPECS)}, got {env_id!r}"
            )
        from actor_critic_tpu import native

        self._lib = native.load()
        self._spec = _SPECS[env_id]
        self.env_id = env_id
        self.num_envs = num_envs

        import gymnasium as gym

        high = self._spec["obs_high"]
        low = self._spec.get("obs_low", -high)
        self.single_observation_space = gym.spaces.Box(low, high, dtype=np.float32)
        if self._spec["discrete"]:
            self.single_action_space = gym.spaces.Discrete(self._spec["n_actions"])
        else:
            self.single_action_space = gym.spaces.Box(
                self._spec["act_low"], self._spec["act_high"], (1,), np.float32
            )

        n, sd, od = num_envs, self._spec["state_dim"], self._spec["obs_dim"]
        self._state = np.zeros((n, sd), np.float64)  # gymnasium precision
        self._steps = np.zeros(n, np.int32)
        self._rng = np.zeros(1, np.uint64)
        self._obs = np.zeros((n, od), np.float32)
        self._reward = np.zeros(n, np.float32)
        self._term = np.zeros(n, np.uint8)
        self._trunc = np.zeros(n, np.uint8)
        self._final_obs = np.zeros((n, od), np.float32)

    def _p(self, a: np.ndarray, ctype=ctypes.c_float):
        return a.ctypes.data_as(ctypes.POINTER(ctype))

    def reset(self, seed: int | None = None):
        if seed is not None:
            self._rng[0] = np.uint64(seed) ^ np.uint64(0xDA3E39CB94B95BDB)
        fn = getattr(self._lib, self._spec["prefix"] + "_reset")
        fn(
            self._p(self._state, ctypes.c_double), self._p(self._obs),
            self.num_envs, self._p(self._rng, ctypes.c_uint64),
            self._p(self._steps, ctypes.c_int32),
        )
        return self._obs.copy(), {}

    def step(self, actions: np.ndarray):
        fn = getattr(self._lib, self._spec["prefix"] + "_step")
        if self._spec["discrete"]:
            acts = np.ascontiguousarray(actions, np.int64)
            act_ptr = self._p(acts, ctypes.c_int64)
        else:
            acts = np.ascontiguousarray(actions, np.float32).reshape(self.num_envs)
            act_ptr = self._p(acts)
        fn(
            self._p(self._state, ctypes.c_double), act_ptr, self.num_envs,
            self._p(self._rng, ctypes.c_uint64),
            self._p(self._steps, ctypes.c_int32),
            np.int32(self._spec["max_steps"]),
            self._p(self._obs), self._p(self._reward),
            self._p(self._term, ctypes.c_uint8),
            self._p(self._trunc, ctypes.c_uint8),
            self._p(self._final_obs),
        )
        term = self._term.astype(bool)
        trunc = self._trunc.astype(bool)
        info = {}
        if (term | trunc).any():
            # DELIBERATE deviation from gymnasium's list-of-Optional
            # convention: the engine fills final_obs for EVERY env (== obs
            # where the episode continued), so the whole dense [E, obs]
            # array is passed — no per-env Python loop on the hot path.
            # Consumers must use `terminated|truncated` (NOT row presence)
            # to know which episodes ended; HostEnvPool does exactly that.
            info["final_obs"] = self._final_obs.copy()
        return (
            self._obs.copy(), self._reward.copy(), term.copy(), trunc.copy(), info,
        )

    # test hook: force exact dynamics states
    def set_state(self, values: np.ndarray) -> None:
        values = np.ascontiguousarray(values, np.float64)
        self._lib.set_state(
            self._p(self._state, ctypes.c_double),
            self._p(values, ctypes.c_double),
            self.num_envs, self._spec["state_dim"],
        )
        self._steps[:] = 0

    def close(self) -> None:
        pass
