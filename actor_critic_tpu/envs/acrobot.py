"""Pure-JAX Acrobot-v1 with exact gymnasium dynamics + scenario fleet.

Third classic-control member of the scenario universe (ISSUE 11): the
underactuated double pendulum, RK4-integrated exactly as gymnasium
1.2.2's `AcrobotEnv` does (the "book" dynamics variant, one RK4 step of
`_dsdt` over dt=0.2, angle wrap to [-pi, pi], velocity clips at 4pi/9pi)
— verified numerically in tests/test_envs.py against the installed
gymnasium. Reward is -1 per step (0 on the terminating step), episodes
terminate when -cos(t1) - cos(t1 + t2) > 1 and truncate at 500 steps.

Scenario fleet: `make_acrobot(randomize=0.3)` (or per-param ranges /
`--env-set link_mass_2=0.5,2.0` strings) draws per-instance gravity,
link masses, link lengths, and a torque scale in `reset`, stored in
`AcrobotState.scenario`, so a vmapped fleet of thousands of different
acrobots steps inside one fused XLA program and `auto_reset` re-draws
per episode (envs/jax_env.py scenario docstring). Center-of-mass
positions track the drawn lengths as lc_i = l_i / 2 (gymnasium's
constants satisfy this at the defaults, so the unrandomized env
reproduces gymnasium bit-for-bit semantics); link inertia stays at the
gymnasium constant 1.0.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from actor_critic_tpu.envs.jax_env import (
    EnvSpec, JaxEnv, auto_reset, draw_scenario, scenario_ranges,
)

GRAVITY = 9.8
LINK_MASS_1 = 1.0
LINK_MASS_2 = 1.0
LINK_LENGTH_1 = 1.0
LINK_LENGTH_2 = 1.0
LINK_MOI = 1.0
TORQUE = 1.0  # |torque| of actions 0/2; action 1 is zero torque
DT = 0.2
MAX_VEL_1 = 4.0 * jnp.pi
MAX_VEL_2 = 9.0 * jnp.pi
MAX_STEPS = 500

SCENARIO_DEFAULTS = {
    "gravity": GRAVITY,
    "link_mass_1": LINK_MASS_1,
    "link_mass_2": LINK_MASS_2,
    "link_length_1": LINK_LENGTH_1,
    "link_length_2": LINK_LENGTH_2,
    "torque": TORQUE,
}


class AcrobotScenario(NamedTuple):
    """Per-instance physics (f32 scalars riding the env state)."""

    gravity: jax.Array
    link_mass_1: jax.Array
    link_mass_2: jax.Array
    link_length_1: jax.Array
    link_length_2: jax.Array
    torque: jax.Array


class AcrobotState(NamedTuple):
    theta1: jax.Array
    theta2: jax.Array
    dtheta1: jax.Array
    dtheta2: jax.Array
    t: jax.Array
    key: jax.Array
    scenario: AcrobotScenario


def _obs(s: AcrobotState) -> jax.Array:
    return jnp.stack([
        jnp.cos(s.theta1), jnp.sin(s.theta1),
        jnp.cos(s.theta2), jnp.sin(s.theta2),
        s.dtheta1, s.dtheta2,
    ]).astype(jnp.float32)


def _wrap(x: jax.Array) -> jax.Array:
    """Wrap an angle to [-pi, pi] (gymnasium's `wrap(x, -pi, pi)`)."""
    return ((x + jnp.pi) % (2.0 * jnp.pi)) - jnp.pi


def _dsdt(y: jax.Array, torque: jax.Array, sc: AcrobotScenario) -> jax.Array:
    """Time derivative of [theta1, theta2, dtheta1, dtheta2] under the
    gymnasium "book" dynamics, with the COM positions tied to half the
    link lengths (equal to gymnasium's constants at the defaults)."""
    m1, m2 = sc.link_mass_1, sc.link_mass_2
    l1 = sc.link_length_1
    lc1 = 0.5 * sc.link_length_1
    lc2 = 0.5 * sc.link_length_2
    i1 = i2 = jnp.float32(LINK_MOI)
    g = sc.gravity
    theta1, theta2, dtheta1, dtheta2 = y[0], y[1], y[2], y[3]
    d1 = (
        m1 * lc1**2
        + m2 * (l1**2 + lc2**2 + 2.0 * l1 * lc2 * jnp.cos(theta2))
        + i1 + i2
    )
    d2 = m2 * (lc2**2 + l1 * lc2 * jnp.cos(theta2)) + i2
    phi2 = m2 * lc2 * g * jnp.cos(theta1 + theta2 - jnp.pi / 2.0)
    phi1 = (
        -m2 * l1 * lc2 * dtheta2**2 * jnp.sin(theta2)
        - 2.0 * m2 * l1 * lc2 * dtheta2 * dtheta1 * jnp.sin(theta2)
        + (m1 * lc1 + m2 * l1) * g * jnp.cos(theta1 - jnp.pi / 2.0)
        + phi2
    )
    ddtheta2 = (
        torque + d2 / d1 * phi1
        - m2 * l1 * lc2 * dtheta1**2 * jnp.sin(theta2) - phi2
    ) / (m2 * lc2**2 + i2 - d2**2 / d1)
    ddtheta1 = -(d2 * ddtheta2 + phi1) / d1
    return jnp.stack([dtheta1, dtheta2, ddtheta1, ddtheta2])


def _rk4_step(y: jax.Array, torque: jax.Array, sc: AcrobotScenario) -> jax.Array:
    """One classical RK4 step over [0, DT] — gymnasium's `rk4` with a
    two-point time grid, which is exactly one RK4 update."""
    dt, dt2 = jnp.float32(DT), jnp.float32(DT / 2.0)
    k1 = _dsdt(y, torque, sc)
    k2 = _dsdt(y + dt2 * k1, torque, sc)
    k3 = _dsdt(y + dt2 * k2, torque, sc)
    k4 = _dsdt(y + dt * k3, torque, sc)
    return y + dt / 6.0 * (k1 + 2.0 * k2 + 2.0 * k3 + k4)


def _raw_step(state: AcrobotState, action: jax.Array):
    sc = state.scenario
    # AVAIL_TORQUE = [-1, 0, +1] scaled by the per-instance torque.
    torque = (action.astype(jnp.float32) - 1.0) * sc.torque
    y = jnp.stack([state.theta1, state.theta2, state.dtheta1, state.dtheta2])
    ns = _rk4_step(y, torque, sc)
    theta1 = _wrap(ns[0])
    theta2 = _wrap(ns[1])
    dtheta1 = jnp.clip(ns[2], -MAX_VEL_1, MAX_VEL_1)
    dtheta2 = jnp.clip(ns[3], -MAX_VEL_2, MAX_VEL_2)
    t = state.t + 1

    nstate = AcrobotState(
        theta1, theta2, dtheta1, dtheta2, t, state.key, sc
    )
    terminated = (
        -jnp.cos(theta1) - jnp.cos(theta2 + theta1) > 1.0
    ).astype(jnp.float32)
    truncated = (t >= MAX_STEPS).astype(jnp.float32) * (1.0 - terminated)
    # -1 per step until the terminating step, which earns 0 (gymnasium).
    reward = -(1.0 - terminated)
    return nstate, _obs(nstate), reward, terminated, truncated


def make_acrobot(
    randomize: float = 0.0,
    gravity=None,
    link_mass_1=None,
    link_mass_2=None,
    link_length_1=None,
    link_length_2=None,
    torque=None,
) -> JaxEnv:
    """Acrobot-v1, optionally as a domain-randomized scenario fleet.

    `randomize=r` draws each physics parameter per instance/episode in
    [default·(1−r), default·(1+r)]; the per-param kwargs pin ranges
    explicitly (a (lo, hi) pair, a "lo,hi" string via --env-set, or a
    bare number to fix the value). Defaults reproduce gymnasium exactly.
    """
    ranges = scenario_ranges(
        SCENARIO_DEFAULTS, randomize,
        {"gravity": gravity, "link_mass_1": link_mass_1,
         "link_mass_2": link_mass_2, "link_length_1": link_length_1,
         "link_length_2": link_length_2, "torque": torque},
    )

    def _reset(key: jax.Array) -> tuple[AcrobotState, jax.Array]:
        key, sub, skey = jax.random.split(key, 3)
        scenario = AcrobotScenario(**draw_scenario(skey, ranges))
        vals = jax.random.uniform(sub, (4,), jnp.float32, -0.1, 0.1)
        state = AcrobotState(
            theta1=vals[0], theta2=vals[1],
            dtheta1=vals[2], dtheta2=vals[3],
            t=jnp.zeros((), jnp.int32), key=key, scenario=scenario,
        )
        return state, _obs(state)

    spec = EnvSpec(
        obs_shape=(6,), action_dim=3, discrete=True,
        episode_horizon=MAX_STEPS,
    )
    step = auto_reset(_reset, _raw_step, key_of_state=lambda s: s.key)
    return JaxEnv(spec=spec, reset=_reset, step=step)
