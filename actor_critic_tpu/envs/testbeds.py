"""Analytic micro-environments with known optima (SURVEY.md §4).

Used by the integration ("learning") tests: each algorithm must drive these
to their known optimal policy/value in a few hundred steps. Pure JAX, same
protocol as cartpole.py — each env is a raw step wrapped by
`auto_reset`, so the reset/final_obs semantics live in exactly one place.

- `make_bandit(payouts)`: single-step bandit; optimal policy picks
  argmax(payouts); optimal V = max(payouts).
- `make_two_state_mdp()`: 2 states, 2 actions, deterministic transitions;
  always taking action 1 is optimal (reward 1 per step); with the
  truncation-bootstrap reward patch the critic's fixed point is the
  infinite-horizon V* = 1/(1-γ).
- `make_point_mass()`: 1-d continuous-action point mass; reward −(pos+a)²;
  optimal action = −pos; tests Gaussian/tanh policies.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from actor_critic_tpu.envs.jax_env import EnvSpec, JaxEnv, auto_reset


class _KeyState(NamedTuple):
    key: jax.Array
    t: jax.Array


def make_bandit(payouts=(0.2, 0.9, 0.4)) -> JaxEnv:
    """One-step episodes: obs is constant [1.0]; reward = payouts[action]."""
    payouts_arr = jnp.asarray(payouts, jnp.float32)
    obs0 = jnp.ones((1,), jnp.float32)

    def reset(key):
        key, _ = jax.random.split(key)
        return _KeyState(key=key, t=jnp.zeros((), jnp.int32)), obs0

    def raw_step(state, action):
        reward = payouts_arr[action]
        terminated = jnp.ones((), jnp.float32)
        truncated = jnp.zeros((), jnp.float32)
        return state, obs0, reward, terminated, truncated

    return JaxEnv(
        spec=EnvSpec(
            obs_shape=(1,), action_dim=len(payouts), discrete=True,
            can_truncate=False, episode_horizon=1,
        ),
        reset=reset,
        step=auto_reset(reset, raw_step, key_of_state=lambda s: s.key),
    )


class _TwoStateState(NamedTuple):
    s: jax.Array  # 0 or 1
    key: jax.Array
    t: jax.Array


def make_two_state_mdp(horizon: int = 8) -> JaxEnv:
    """Deterministic 2-state MDP, truncated at `horizon` steps.

    Transitions: next state == action (from either state).
    Rewards: r(s, a) = 1.0 if a == 1 else 0.0.
    Optimal policy: always a=1. Obs is one-hot of the state.
    """

    def obs_of(s):
        return jax.nn.one_hot(s, 2, dtype=jnp.float32)

    def reset(key):
        key, sub = jax.random.split(key)
        s = jax.random.bernoulli(sub).astype(jnp.int32)
        st = _TwoStateState(s=s, key=key, t=jnp.zeros((), jnp.int32))
        return st, obs_of(s)

    def raw_step(state, action):
        action = action.astype(jnp.int32)
        reward = action.astype(jnp.float32)
        t = state.t + 1
        nstate = _TwoStateState(s=action, key=state.key, t=t)
        terminated = jnp.zeros((), jnp.float32)
        truncated = (t >= horizon).astype(jnp.float32)
        return nstate, obs_of(action), reward, terminated, truncated

    return JaxEnv(
        spec=EnvSpec(
            obs_shape=(2,), action_dim=2, discrete=True,
            episode_horizon=horizon,
        ),
        reset=reset,
        step=auto_reset(reset, raw_step, key_of_state=lambda s: s.key),
    )


class _PointMassState(NamedTuple):
    pos: jax.Array
    key: jax.Array
    t: jax.Array


def make_point_mass(horizon: int = 16, pos_clip: float = 2.0) -> JaxEnv:
    """1-d continuous control: obs = [pos]; reward = −(pos+a)²; pos' = pos+a.

    Optimal action a* = −pos (within [−1, 1]); fixed-horizon episodes.
    Positions start uniform in [−0.5, 0.5] so a* is always reachable, and
    are clipped to ±pos_clip so the state space stays bounded — without
    the clip a bad early policy random-walks positions to ±horizon and
    off-policy critics spend their capacity fitting that divergent regime
    (the analytic testbeds are meant to be well-conditioned; SURVEY §4).
    """

    def reset(key):
        key, sub = jax.random.split(key)
        pos = jax.random.uniform(sub, (), jnp.float32, -0.5, 0.5)
        st = _PointMassState(pos=pos, key=key, t=jnp.zeros((), jnp.int32))
        return st, pos[None]

    def raw_step(state, action):
        a = jnp.clip(action.reshape(()), -1.0, 1.0)
        npos = jnp.clip(state.pos + a, -pos_clip, pos_clip)
        reward = -(npos**2)
        t = state.t + 1
        nstate = _PointMassState(pos=npos, key=state.key, t=t)
        terminated = jnp.zeros((), jnp.float32)
        truncated = (t >= horizon).astype(jnp.float32)
        return nstate, npos[None], reward, terminated, truncated

    return JaxEnv(
        spec=EnvSpec(
            obs_shape=(1,), action_dim=1, discrete=False,
            episode_horizon=horizon,
        ),
        reset=reset,
        step=auto_reset(reset, raw_step, key_of_state=lambda s: s.key),
    )
