"""Pure-JAX CartPole-v1 with exact gymnasium dynamics + scenario fleet.

Replaces the reference's host-stepped `gym.make("CartPole-v1")`
(BASELINE.json:7; reference mount empty, SURVEY.md §0) with an on-device
vmappable env so the A2C rollout+update is one fused XLA program — the
≥1M env-steps/sec north-star config (BASELINE.json:5).

Dynamics, thresholds, reset distribution, reward (+1 every step, incl.
the terminating one) and the 500-step time limit match gymnasium 1.2.2's
`CartPoleEnv` (verified numerically in tests/test_envs.py against the
installed gymnasium).

Scenario fleet (ISSUE 8): `make_cartpole(randomize=0.3)` (or per-param
ranges, e.g. `masspole=(0.05, 0.5)` / `--env-set masspole=0.05,0.5`)
draws per-INSTANCE physics — gravity, cart/pole masses, pole length,
force magnitude — in `reset` from the instance's own PRNG stream, stored
in `CartPoleState.scenario` so the vmapped fleet carries thousands of
different dynamics inside one XLA program and `auto_reset` re-draws a
fresh scenario each episode (envs/jax_env.py scenario docstring). The
default env draws every param at its gymnasium constant, so the parity
tests above keep passing bit-for-bit semantics.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from actor_critic_tpu.envs.jax_env import (
    EnvSpec, JaxEnv, StepOutput, auto_reset, draw_scenario, scenario_ranges,
)

GRAVITY = 9.8
MASSCART = 1.0
MASSPOLE = 0.1
TOTAL_MASS = MASSCART + MASSPOLE
LENGTH = 0.5  # half the pole's length
POLEMASS_LENGTH = MASSPOLE * LENGTH
FORCE_MAG = 10.0
TAU = 0.02
THETA_THRESHOLD = 12 * 2 * jnp.pi / 360
X_THRESHOLD = 2.4
MAX_STEPS = 500

SCENARIO_DEFAULTS = {
    "gravity": GRAVITY,
    "masscart": MASSCART,
    "masspole": MASSPOLE,
    "length": LENGTH,
    "force_mag": FORCE_MAG,
}


class CartPoleScenario(NamedTuple):
    """Per-instance physics (f32 scalars; rides the env state so the
    vmapped fleet is heterogeneous with no protocol change)."""

    gravity: jax.Array
    masscart: jax.Array
    masspole: jax.Array
    length: jax.Array
    force_mag: jax.Array


class CartPoleState(NamedTuple):
    x: jax.Array
    x_dot: jax.Array
    theta: jax.Array
    theta_dot: jax.Array
    t: jax.Array  # step count for the TimeLimit truncation
    key: jax.Array
    scenario: CartPoleScenario


def _obs(s: CartPoleState) -> jax.Array:
    return jnp.stack([s.x, s.x_dot, s.theta, s.theta_dot]).astype(jnp.float32)


def _raw_step(state: CartPoleState, action: jax.Array):
    sc = state.scenario
    total_mass = sc.masscart + sc.masspole
    polemass_length = sc.masspole * sc.length
    force = jnp.where(action == 1, sc.force_mag, -sc.force_mag).astype(
        jnp.float32
    )
    costheta = jnp.cos(state.theta)
    sintheta = jnp.sin(state.theta)
    temp = (force + polemass_length * state.theta_dot**2 * sintheta) / total_mass
    thetaacc = (sc.gravity * sintheta - costheta * temp) / (
        sc.length * (4.0 / 3.0 - sc.masspole * costheta**2 / total_mass)
    )
    xacc = temp - polemass_length * thetaacc * costheta / total_mass
    # gymnasium's default Euler integrator
    x = state.x + TAU * state.x_dot
    x_dot = state.x_dot + TAU * xacc
    theta = state.theta + TAU * state.theta_dot
    theta_dot = state.theta_dot + TAU * thetaacc
    t = state.t + 1

    nstate = CartPoleState(x, x_dot, theta, theta_dot, t, state.key, sc)
    terminated = (
        (jnp.abs(x) > X_THRESHOLD) | (jnp.abs(theta) > THETA_THRESHOLD)
    ).astype(jnp.float32)
    truncated = (t >= MAX_STEPS).astype(jnp.float32) * (1.0 - terminated)
    reward = jnp.ones((), jnp.float32)
    return nstate, _obs(nstate), reward, terminated, truncated


def make_cartpole(
    randomize: float = 0.0,
    gravity=None,
    masscart=None,
    masspole=None,
    length=None,
    force_mag=None,
) -> JaxEnv:
    """CartPole-v1, optionally as a domain-randomized scenario fleet.

    `randomize=r` draws each physics parameter per instance/episode in
    [default·(1−r), default·(1+r)]; the per-param kwargs pin ranges
    explicitly (a (lo, hi) pair, a "lo,hi" string via --env-set, or a
    bare number to fix the value). Defaults reproduce gymnasium exactly.
    """
    ranges = scenario_ranges(
        SCENARIO_DEFAULTS, randomize,
        {"gravity": gravity, "masscart": masscart, "masspole": masspole,
         "length": length, "force_mag": force_mag},
    )

    def _reset(key: jax.Array) -> tuple[CartPoleState, jax.Array]:
        key, sub, skey = jax.random.split(key, 3)
        scenario = CartPoleScenario(**draw_scenario(skey, ranges))
        vals = jax.random.uniform(sub, (4,), jnp.float32, -0.05, 0.05)
        state = CartPoleState(
            x=vals[0], x_dot=vals[1], theta=vals[2], theta_dot=vals[3],
            t=jnp.zeros((), jnp.int32), key=key, scenario=scenario,
        )
        return state, _obs(state)

    spec = EnvSpec(
        obs_shape=(4,), action_dim=2, discrete=True, episode_horizon=500
    )
    step = auto_reset(_reset, _raw_step, key_of_state=lambda s: s.key)
    return JaxEnv(spec=spec, reset=_reset, step=step)
