"""Pure-JAX CartPole-v1 with exact gymnasium dynamics.

Replaces the reference's host-stepped `gym.make("CartPole-v1")`
(BASELINE.json:7; reference mount empty, SURVEY.md §0) with an on-device
vmappable env so the A2C rollout+update is one fused XLA program — the
≥1M env-steps/sec north-star config (BASELINE.json:5).

Dynamics, thresholds, reset distribution, reward (+1 every step, incl.
the terminating one) and the 500-step time limit match gymnasium 1.2.2's
`CartPoleEnv` (verified numerically in tests/test_envs.py against the
installed gymnasium).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from actor_critic_tpu.envs.jax_env import EnvSpec, JaxEnv, StepOutput, auto_reset

GRAVITY = 9.8
MASSCART = 1.0
MASSPOLE = 0.1
TOTAL_MASS = MASSCART + MASSPOLE
LENGTH = 0.5  # half the pole's length
POLEMASS_LENGTH = MASSPOLE * LENGTH
FORCE_MAG = 10.0
TAU = 0.02
THETA_THRESHOLD = 12 * 2 * jnp.pi / 360
X_THRESHOLD = 2.4
MAX_STEPS = 500


class CartPoleState(NamedTuple):
    x: jax.Array
    x_dot: jax.Array
    theta: jax.Array
    theta_dot: jax.Array
    t: jax.Array  # step count for the TimeLimit truncation
    key: jax.Array


def _obs(s: CartPoleState) -> jax.Array:
    return jnp.stack([s.x, s.x_dot, s.theta, s.theta_dot]).astype(jnp.float32)


def _reset(key: jax.Array) -> tuple[CartPoleState, jax.Array]:
    key, sub = jax.random.split(key)
    vals = jax.random.uniform(sub, (4,), jnp.float32, -0.05, 0.05)
    state = CartPoleState(
        x=vals[0], x_dot=vals[1], theta=vals[2], theta_dot=vals[3],
        t=jnp.zeros((), jnp.int32), key=key,
    )
    return state, _obs(state)


def _raw_step(state: CartPoleState, action: jax.Array):
    force = jnp.where(action == 1, FORCE_MAG, -FORCE_MAG).astype(jnp.float32)
    costheta = jnp.cos(state.theta)
    sintheta = jnp.sin(state.theta)
    temp = (force + POLEMASS_LENGTH * state.theta_dot**2 * sintheta) / TOTAL_MASS
    thetaacc = (GRAVITY * sintheta - costheta * temp) / (
        LENGTH * (4.0 / 3.0 - MASSPOLE * costheta**2 / TOTAL_MASS)
    )
    xacc = temp - POLEMASS_LENGTH * thetaacc * costheta / TOTAL_MASS
    # gymnasium's default Euler integrator
    x = state.x + TAU * state.x_dot
    x_dot = state.x_dot + TAU * xacc
    theta = state.theta + TAU * state.theta_dot
    theta_dot = state.theta_dot + TAU * thetaacc
    t = state.t + 1

    nstate = CartPoleState(x, x_dot, theta, theta_dot, t, state.key)
    terminated = (
        (jnp.abs(x) > X_THRESHOLD) | (jnp.abs(theta) > THETA_THRESHOLD)
    ).astype(jnp.float32)
    truncated = (t >= MAX_STEPS).astype(jnp.float32) * (1.0 - terminated)
    reward = jnp.ones((), jnp.float32)
    return nstate, _obs(nstate), reward, terminated, truncated


def make_cartpole() -> JaxEnv:
    spec = EnvSpec(
        obs_shape=(4,), action_dim=2, discrete=True, episode_horizon=500
    )
    step = auto_reset(_reset, _raw_step, key_of_state=lambda s: s.key)
    return JaxEnv(spec=spec, reset=_reset, step=step)
