"""Host-side pixel preprocessing (the reference's Atari wrapper stack).

The reference genre preprocesses pixels with grayscale → 84x84 resize →
k-frame stack → reward clipping before the Nature CNN (SURVEY.md §2.1
"Env wrappers"; reference mount empty at survey, §0). ALE itself is not
installed in this image (SURVEY.md §7.0) — the IMPALA config uses the
pure-JAX Pong (envs/pong.py) whose observations are already in this
format — but the wrapper is provided for ANY host pixel env (e.g.
Box2D's CarRacing) so the CNN trainers run on real gym pixel tasks
through `HostEnvPool(..., pixel_preprocess=True)`.

Kept on the host on purpose: this is per-step image munging of data that
arrives from a host emulator anyway; the device-side analogue for
synthetic envs lives in the env itself (pong.py renders directly at
84x84 stacked).
"""

from __future__ import annotations

from collections import deque

import numpy as np

try:
    import cv2

    _HAS_CV2 = True
except Exception:  # pragma: no cover - cv2 is in the image, but stay safe
    _HAS_CV2 = False

import gymnasium as gym


def _to_gray(frame: np.ndarray) -> np.ndarray:
    if frame.ndim == 2:
        return frame
    # ITU-R 601 luma, same coefficients cv2 uses.
    return (
        frame[..., 0] * 0.299 + frame[..., 1] * 0.587 + frame[..., 2] * 0.114
    ).astype(np.uint8)


def _resize(frame: np.ndarray, size: int) -> np.ndarray:
    if frame.shape[:2] == (size, size):
        return frame
    if _HAS_CV2:
        return cv2.resize(frame, (size, size), interpolation=cv2.INTER_AREA)
    # Nearest-neighbour fallback (no cv2): index-sample the grid.
    h, w = frame.shape[:2]
    ys = (np.arange(size) * h // size).clip(0, h - 1)
    xs = (np.arange(size) * w // size).clip(0, w - 1)
    return frame[np.ix_(ys, xs)]


class PixelPreprocess(gym.Wrapper):
    """grayscale → size×size resize → `stack` frames on the channel axis
    (uint8 [size, size, stack]) → optional sign reward clip + action
    repeat. Matches the observation contract of envs/pong.py so the same
    CNN encoder consumes either."""

    def __init__(
        self,
        env: gym.Env,
        size: int = 84,
        stack: int = 4,
        action_repeat: int = 1,
        clip_reward: bool = True,
    ):
        super().__init__(env)
        self.size = size
        self.stack = stack
        self.action_repeat = max(action_repeat, 1)
        self.clip_reward = clip_reward
        self._frames: deque[np.ndarray] = deque(maxlen=stack)
        self.observation_space = gym.spaces.Box(
            0, 255, (size, size, stack), np.uint8
        )

    def _obs(self) -> np.ndarray:
        return np.stack(self._frames, axis=-1)

    def _push(self, frame: np.ndarray) -> None:
        self._frames.append(_resize(_to_gray(np.asarray(frame)), self.size))

    def reset(self, **kwargs):
        obs, info = self.env.reset(**kwargs)
        self._frames.clear()
        self._push(obs)
        while len(self._frames) < self.stack:
            self._frames.append(self._frames[-1])
        return self._obs(), info

    def step(self, action):
        total = 0.0
        terminated = truncated = False
        info: dict = {}
        for _ in range(self.action_repeat):
            obs, reward, terminated, truncated, info = self.env.step(action)
            total += float(reward)
            if terminated or truncated:
                break
        self._push(obs)
        if self.clip_reward:
            total = float(np.sign(total))
        return self._obs(), total, terminated, truncated, info
