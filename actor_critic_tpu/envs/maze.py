"""Procedurally generated obstacle-maze family, pure-JAX (ISSUE 11).

The fourth member of the scenario universe: a gridworld whose obstacle
LAYOUT is itself the scenario — every episode draws a fresh random
obstacle field, start cell, and goal cell from the instance's own PRNG
stream, so a vmapped fleet carries thousands of different mazes inside
one fused XLA program and `auto_reset` re-generates a new maze per
episode (the procedural-generation regime; envs/jax_env.py scenario
docstring). There is no host-side level bank: generation is a few
`jax.random` draws inside `reset`, which is what keeps a million-maze
fleet device-resident.

Mechanics: an N×N grid (static `size`, default 8) with Bernoulli
obstacles at per-instance `density`; 4 discrete actions (up/right/down/
left); moving into a wall or obstacle stays in place; reaching the goal
terminates with `goal_reward`, every step costs `step_cost`. Episodes
truncate at 8·N steps. Observations are egocentric and fixed-width
regardless of grid size: the 3×3 obstacle window around the agent
(out-of-bounds cells read as walls) plus normalized agent position and
goal offset — 13 floats.

Scenario parameters (`scenario_ranges`/`draw_scenario` protocol, same
as cartpole/pendulum/acrobot): `density`, `step_cost`, `goal_reward` —
`make_maze(randomize=0.3)` or per-param ranges / `--env-set
density=0.1,0.4` strings re-draw them per episode along with the
layout.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from actor_critic_tpu.envs.jax_env import (
    EnvSpec, JaxEnv, auto_reset, draw_scenario, scenario_ranges,
)

DENSITY = 0.25
STEP_COST = 0.05
GOAL_REWARD = 1.0

SCENARIO_DEFAULTS = {
    "density": DENSITY,
    "step_cost": STEP_COST,
    "goal_reward": GOAL_REWARD,
}

# (row, col) deltas for actions 0..3 = up/right/down/left.
_DELTAS = ((-1, 0), (0, 1), (1, 0), (0, -1))


class MazeScenario(NamedTuple):
    """Per-instance generation/reward knobs (f32 scalars)."""

    density: jax.Array
    step_cost: jax.Array
    goal_reward: jax.Array


class MazeState(NamedTuple):
    grid: jax.Array  # [N, N] f32, 1.0 = obstacle
    row: jax.Array
    col: jax.Array
    goal_row: jax.Array
    goal_col: jax.Array
    t: jax.Array
    key: jax.Array
    scenario: MazeScenario


def _obs(s: MazeState, size: int) -> jax.Array:
    # 3×3 egocentric obstacle window; out-of-bounds cells read as walls
    # so the policy sees the arena boundary the same way it sees
    # obstacles. dynamic_slice start (row-1+1, col-1+1) on the 1-padded
    # grid is just (row, col).
    padded = jnp.pad(s.grid, 1, constant_values=1.0)
    window = jax.lax.dynamic_slice(padded, (s.row, s.col), (3, 3))
    n = jnp.float32(size)
    feats = jnp.stack([
        s.row.astype(jnp.float32) / n,
        s.col.astype(jnp.float32) / n,
        (s.goal_row - s.row).astype(jnp.float32) / n,
        (s.goal_col - s.col).astype(jnp.float32) / n,
    ])
    return jnp.concatenate([window.reshape(9), feats]).astype(jnp.float32)


def make_maze(
    size: int = 8,
    randomize: float = 0.0,
    density=None,
    step_cost=None,
    goal_reward=None,
) -> JaxEnv:
    """Procedural obstacle maze, optionally with randomized generation
    parameters. `size` is static (it sets array shapes); the layout is
    re-generated every episode regardless of `randomize`."""
    if size < 3:
        raise ValueError(f"size must be >= 3, got {size}")
    max_steps = 8 * size
    ranges = scenario_ranges(
        SCENARIO_DEFAULTS, randomize,
        {"density": density, "step_cost": step_cost,
         "goal_reward": goal_reward},
    )

    def _reset(key: jax.Array) -> tuple[MazeState, jax.Array]:
        key, skey, gkey, pkey, qkey = jax.random.split(key, 5)
        scenario = MazeScenario(**draw_scenario(skey, ranges))
        dens = jnp.clip(scenario.density, 0.0, 0.9)
        grid = (
            jax.random.uniform(gkey, (size, size), jnp.float32) < dens
        ).astype(jnp.float32)
        pos = jax.random.randint(pkey, (2,), 0, size)
        goal = jax.random.randint(qkey, (2,), 0, size)
        # Distinct start/goal: shift a colliding goal diagonally (mod N)
        # instead of rejection-sampling (shape-static, branchless).
        same = jnp.all(pos == goal)
        goal = jnp.where(same, (goal + 1) % size, goal)
        # Start and goal cells are always free.
        grid = grid.at[pos[0], pos[1]].set(0.0)
        grid = grid.at[goal[0], goal[1]].set(0.0)
        state = MazeState(
            grid=grid, row=pos[0], col=pos[1],
            goal_row=goal[0], goal_col=goal[1],
            t=jnp.zeros((), jnp.int32), key=key, scenario=scenario,
        )
        return state, _obs(state, size)

    def _raw_step(state: MazeState, action: jax.Array):
        sc = state.scenario
        a = action.astype(jnp.int32) % 4
        deltas = jnp.asarray(_DELTAS, jnp.int32)
        nr = jnp.clip(state.row + deltas[a, 0], 0, size - 1)
        nc = jnp.clip(state.col + deltas[a, 1], 0, size - 1)
        blocked = state.grid[nr, nc] > 0.5
        row = jnp.where(blocked, state.row, nr)
        col = jnp.where(blocked, state.col, nc)
        t = state.t + 1
        nstate = MazeState(
            grid=state.grid, row=row, col=col,
            goal_row=state.goal_row, goal_col=state.goal_col,
            t=t, key=state.key, scenario=sc,
        )
        reached = (
            (row == state.goal_row) & (col == state.goal_col)
        ).astype(jnp.float32)
        reward = sc.goal_reward * reached - sc.step_cost
        terminated = reached
        truncated = (t >= max_steps).astype(jnp.float32) * (1.0 - terminated)
        return nstate, _obs(nstate, size), reward, terminated, truncated

    spec = EnvSpec(
        obs_shape=(13,), action_dim=4, discrete=True,
        episode_horizon=max_steps,
    )
    step = auto_reset(_reset, _raw_step, key_of_state=lambda s: s.key)
    return JaxEnv(spec=spec, reset=_reset, step=step)
