"""Data-parallel execution of the fused on-policy train step.

TPU-native replacement for the reference's MirroredStrategy/NCCL
data-parallel path (BASELINE.json:5; SURVEY.md §2.3-2.4 — reference mount
empty, §0). The fused trainer keeps its env batch *inside* `TrainState`,
so data parallelism here means sharding the state itself over the mesh:

    params / opt_state / update_step / avg_return  → replicated  (P())
    rollout (env states + obs), ep_return/length   → sharded     (P("dp"))
    key                                            → per-device  (P("dp"))

Each device then runs the whole fused program (rollout → GAE → grads) on
its shard of envs with its own PRNG stream; the single cross-device
communication is the gradient/metric `pmean` the trainer already does
over `axis_name` — which XLA lowers to an ICI all-reduce, exactly the
role NCCL plays in the reference.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from actor_critic_tpu.algos.common import TrainState
from actor_critic_tpu.parallel.mesh import DP_AXIS


def train_state_specs() -> TrainState:
    """Prefix-tree of PartitionSpecs for TrainState under dp sharding."""
    return TrainState(
        params=P(),
        opt_state=P(),
        rollout=P(DP_AXIS),
        key=P(DP_AXIS),
        update_step=P(),
        ep_return=P(DP_AXIS),
        ep_length=P(DP_AXIS),
        avg_return=P(),
    )


def impala_state_specs():
    """PartitionSpecs for the IMPALA trainer state: same dp layout, with
    the stale actor params replicated alongside the learner params."""
    from actor_critic_tpu.algos.impala import ImpalaTrainState

    return ImpalaTrainState(
        params=P(),
        actor_params=P(),
        opt_state=P(),
        rollout=P(DP_AXIS),
        key=P(DP_AXIS),
        update_step=P(),
        ep_return=P(DP_AXIS),
        ep_length=P(DP_AXIS),
        avg_return=P(),
    )


def distribute_state(state, mesh: Mesh, specs=None):
    """Place a host-built trainer state onto the mesh.

    The scalar PRNG key becomes a [ndev] batch (one independent stream per
    device); env-batch leaves are sharded over dp (num_envs must divide by
    the dp size); everything else is replicated. `specs` defaults to the
    on-policy TrainState layout; pass `impala_state_specs()` (or any
    matching prefix-tree of PartitionSpecs) for other state shapes.
    """
    ndev = mesh.shape[DP_AXIS]
    num_envs = state.ep_return.shape[0]
    if num_envs % ndev != 0:
        raise ValueError(f"num_envs={num_envs} not divisible by dp={ndev}")
    state = state._replace(key=jax.random.split(state.key, ndev))
    if specs is None:
        specs = train_state_specs()

    def expand(spec, subtree):
        return jax.tree.map(lambda _: NamedSharding(mesh, spec), subtree)

    shardings = jax.tree.map(
        expand, specs, state, is_leaf=lambda x: isinstance(x, P)
    )
    return jax.tree.map(jax.device_put, state, shardings)


def make_dp_train_step(
    train_step: Callable[[TrainState], tuple[TrainState, dict]],
    mesh: Mesh,
    specs=None,
) -> Callable[[TrainState], tuple[TrainState, dict]]:
    """shard_map + jit the fused train step over the dp axis (built once).

    `train_step` must be built with `axis_name=DP_AXIS` so its gradient
    pmean becomes the cross-device all-reduce. The per-device view of
    `key` is a [1] slice of the [ndev] key batch; the wrapper unwraps it.
    `specs` defaults to the on-policy TrainState layout.
    """
    shard_map = jax.shard_map

    if specs is None:
        specs = train_state_specs()

    def local_step(state: TrainState):
        state = state._replace(key=state.key[0])
        new_state, metrics = train_step(state)
        return new_state._replace(key=new_state.key[None]), metrics

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=0)
