"""Data-parallel execution of the fused on-policy train step.

TPU-native replacement for the reference's MirroredStrategy/NCCL
data-parallel path (BASELINE.json:5; SURVEY.md §2.3-2.4 — reference mount
empty, §0). The fused trainer keeps its env batch *inside* `TrainState`,
so data parallelism here means sharding the state itself over the mesh:

    params / opt_state / update_step / avg_return  → replicated  (P())
    rollout (env states + obs), ep_return/length   → sharded     (P("dp"))
    key                                            → per-device  (P("dp"))

Each device then runs the whole fused program (rollout → GAE → grads) on
its shard of envs with its own PRNG stream; the single cross-device
communication is the gradient/metric `pmean` the trainer already does
over `axis_name` — which XLA lowers to an ICI all-reduce, exactly the
role NCCL plays in the reference.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from actor_critic_tpu.algos.common import TrainState
from actor_critic_tpu.parallel.mesh import DP_AXIS


def train_state_specs() -> TrainState:
    """Prefix-tree of PartitionSpecs for TrainState under dp sharding."""
    return TrainState(
        params=P(),
        opt_state=P(),
        rollout=P(DP_AXIS),
        key=P(DP_AXIS),
        update_step=P(),
        ep_return=P(DP_AXIS),
        ep_length=P(DP_AXIS),
        avg_return=P(),
    )


def impala_state_specs():
    """PartitionSpecs for the IMPALA trainer state: same dp layout, with
    the stale actor params replicated alongside the learner params."""
    from actor_critic_tpu.algos.impala import ImpalaTrainState

    return ImpalaTrainState(
        params=P(),
        actor_params=P(),
        opt_state=P(),
        rollout=P(DP_AXIS),
        key=P(DP_AXIS),
        update_step=P(),
        ep_return=P(DP_AXIS),
        ep_length=P(DP_AXIS),
        avg_return=P(),
    )


def replay_specs():
    """PartitionSpecs for the HBM replay ring under dp (BASELINE.json:5
    'replay buffer lives in HBM as a sharded DeviceArray'): the storage's
    leading (capacity) axis is split over dp, so each device owns an
    independent sub-ring of capacity/ndev transitions fed by its own env
    shard and read by its own sampler — no collectives touch the ring.
    The cursor scalars stay replicated: every device inserts the same
    (static) batch size against the same local capacity each step, so
    their values evolve identically on all devices. The quantizer's
    running stats (ReplayState.quant, replay/quantize.py) are replicated
    too — unlike the cursors their inputs DIFFER per device (each shard
    sees its own envs), so `replay.add_batch(..., axis_name=dp)`
    pmean/pmax-syncs the batch moments, the one (tiny, item-shaped)
    collective the quantized ring adds."""
    from actor_critic_tpu.replay.buffer import ReplayState

    return ReplayState(storage=P(DP_AXIS), insert_pos=P(), size=P(), quant=P())


def offpolicy_state_specs():
    """PartitionSpecs for the DDPG/TD3 fused-trainer state under dp.

    Layout: params/targets/optimizers replicated (grads pmean per update,
    like the on-policy path); replay sharded per `replay_specs`; env batch
    and episode accounting sharded; the learner PRNG key per-device (one
    independent sampling/noise stream each). `env_steps` counts LOCAL
    per-device steps, so `warmup_steps` gates each device by its own
    collection count. Effective update batch = ndev × cfg.batch_size
    (each device samples its sub-ring; gradients are pmean-ed).
    """
    from actor_critic_tpu.algos.ddpg import LearnerState, OffPolicyState

    learner = LearnerState(
        actor_params=P(),
        critic_params=P(),
        target_actor=P(),
        target_critic=P(),
        actor_opt=P(),
        critic_opt=P(),
        replay=replay_specs(),
        key=P(DP_AXIS),
        update_count=P(),
    )
    return OffPolicyState(
        learner=learner,
        rollout=P(DP_AXIS),
        env_steps=P(),
        update_step=P(),
        ep_return=P(DP_AXIS),
        ep_length=P(DP_AXIS),
        avg_return=P(),
    )


def sac_state_specs():
    """PartitionSpecs for the SAC fused-trainer state under dp (same
    layout rationale as `offpolicy_state_specs`; log-α and its optimizer
    are replicated scalars)."""
    from actor_critic_tpu.algos.sac import SACLearnerState, SACState

    learner = SACLearnerState(
        actor_params=P(),
        critic_params=P(),
        target_critic=P(),
        actor_opt=P(),
        critic_opt=P(),
        log_alpha=P(),
        alpha_opt=P(),
        replay=replay_specs(),
        key=P(DP_AXIS),
        update_count=P(),
    )
    return SACState(
        learner=learner,
        rollout=P(DP_AXIS),
        env_steps=P(),
        update_step=P(),
        ep_return=P(DP_AXIS),
        ep_length=P(DP_AXIS),
        avg_return=P(),
    )


# Key accessors: the on-policy states carry their PRNG key at the top
# level; the off-policy states carry it inside `.learner`. distribute_state
# and make_dp_train_step use these to split/unwrap the per-device streams.

def _get_key(state):
    return state.learner.key if hasattr(state, "learner") else state.key


def _set_key(state, key):
    if hasattr(state, "learner"):
        return state._replace(learner=state.learner._replace(key=key))
    return state._replace(key=key)


def distribute_state(state, mesh: Mesh, specs=None):
    """Place a host-built trainer state onto the mesh.

    The scalar PRNG key (top-level or `.learner.key`) becomes a [ndev]
    batch (one independent stream per device); leaves under a P("dp")
    spec are sharded on their leading axis (which must divide by the dp
    size — env batch, replay capacity); everything else is replicated.
    `specs` defaults to the on-policy TrainState layout; pass
    `impala_state_specs()` / `offpolicy_state_specs()` /
    `sac_state_specs()` (or any matching prefix-tree of PartitionSpecs)
    for other state shapes.
    """
    ndev = mesh.shape[DP_AXIS]
    state = _set_key(state, jax.random.split(_get_key(state), ndev))
    if specs is None:
        specs = train_state_specs()

    def check_divisible(spec, subtree):
        if spec == P(DP_AXIS):
            for leaf in jax.tree.leaves(subtree):
                if leaf.shape[0] % ndev != 0:
                    raise ValueError(
                        f"dp-sharded leading axis {leaf.shape[0]} not "
                        f"divisible by dp={ndev} (num_envs and replay "
                        "capacity must divide the mesh size)"
                    )
        return spec

    jax.tree.map(
        check_divisible, specs, state, is_leaf=lambda x: isinstance(x, P)
    )

    def expand(spec, subtree):
        return jax.tree.map(lambda _: NamedSharding(mesh, spec), subtree)

    shardings = jax.tree.map(
        expand, specs, state, is_leaf=lambda x: isinstance(x, P)
    )
    return jax.tree.map(jax.device_put, state, shardings)


def make_dp_train_step(
    train_step: Callable[[TrainState], tuple[TrainState, dict]],
    mesh: Mesh,
    specs=None,
) -> Callable[[TrainState], tuple[TrainState, dict]]:
    """shard_map + jit the fused train step over the dp axis (built once).

    `train_step` must be built with `axis_name=DP_AXIS` so its gradient
    pmean becomes the cross-device all-reduce. The per-device view of
    the PRNG key (top-level or `.learner.key`) is a [1] slice of the
    [ndev] key batch; the wrapper unwraps it. `specs` defaults to the
    on-policy TrainState layout.
    """
    from actor_critic_tpu.parallel.mesh import shard_map

    if specs is None:
        specs = train_state_specs()

    def local_step(state):
        state = _set_key(state, _get_key(state)[0])
        new_state, metrics = train_step(state)
        return _set_key(new_state, _get_key(new_state)[None]), metrics

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=0)
