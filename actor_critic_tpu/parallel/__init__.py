from actor_critic_tpu.parallel.mesh import (
    DP_AXIS,
    MODEL_AXIS,
    MeshConfig,
    make_mesh,
    multihost_init,
    pmean,
    pmean_tree,
    psum,
)
from actor_critic_tpu.parallel.dp import (
    distribute_state,
    impala_state_specs,
    make_dp_train_step,
    train_state_specs,
)

__all__ = [
    "DP_AXIS",
    "MODEL_AXIS",
    "MeshConfig",
    "distribute_state",
    "impala_state_specs",
    "make_dp_train_step",
    "make_mesh",
    "multihost_init",
    "pmean",
    "pmean_tree",
    "psum",
    "train_state_specs",
]
