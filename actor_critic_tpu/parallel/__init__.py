from actor_critic_tpu.parallel.mesh import (
    DP_AXIS,
    MODEL_AXIS,
    MeshConfig,
    make_mesh,
    multihost_init,
    pmean,
    pmean_tree,
    psum,
)
from actor_critic_tpu.parallel.dp import (
    distribute_state,
    impala_state_specs,
    make_dp_train_step,
    train_state_specs,
)
from actor_critic_tpu.parallel.seqpar import (
    SP_AXIS,
    make_seqpar_fn,
    make_sp_mesh,
    seqpar_discounted_returns,
    seqpar_gae,
    seqpar_vtrace,
)

__all__ = [
    "DP_AXIS",
    "MODEL_AXIS",
    "SP_AXIS",
    "make_seqpar_fn",
    "make_sp_mesh",
    "seqpar_discounted_returns",
    "seqpar_gae",
    "seqpar_vtrace",
    "MeshConfig",
    "distribute_state",
    "impala_state_specs",
    "make_dp_train_step",
    "make_mesh",
    "multihost_init",
    "pmean",
    "pmean_tree",
    "psum",
    "train_state_specs",
]
