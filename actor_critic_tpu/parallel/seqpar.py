"""Sequence (time-axis) parallelism for trajectory scans.

The reference has no attention, so there is no ring-attention/Ulysses
counterpart to port (SURVEY.md §2.3, §5.7; reference mount empty at
survey, §0). Its long-sequence analogue is the trajectory-return scan:
GAE, discounted returns, and V-trace are all first-order linear
recurrences run in reverse over time,

    y_t = b_t + a_t * y_{t+1},        y_T = y_init.

That structure is exactly what makes a TPU-native *time-sharded*
implementation cheap: split T over a mesh axis "sp", and the recurrence
over a contiguous segment composes into a single affine map

    y_seg_start = B_seg + A_seg * y_next_seg_start,
    A_seg = prod(a_t over segment),  B_seg = local reverse scan @ 0 init,

so the cross-device dependency is one affine chain of length n_devices.
The implementation needs only:

  1. a halo exchange (`ppermute` shift by one along "sp") so each device
     sees the *next* segment's first value — the v_{t+1} lookahead that
     GAE's δ_t and V-trace's deltas require;
  2. a local reverse `lax.scan` (per device, O(T/D));
  3. an `all_gather` of the per-segment (A, B) summaries + a tiny
     replicated scan over the D segments to solve the boundary chain.

Collectives ride ICI; per-device work drops from O(T) to O(T/D). With
D=1 all of it degrades to the plain scans in `ops/returns.py`, which the
tests use as golden references (tests/test_seqpar.py, 8-device CPU mesh
per SURVEY.md §4).

All `seqpar_*` functions are written to be called INSIDE `shard_map`
with the time axis sharded over `axis_name`; `make_seqpar_fn` wraps one
of them into a jitted, mesh-ready callable for [T, ...] global arrays.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from actor_critic_tpu.ops import returns
from actor_critic_tpu.parallel import mesh as mesh_lib

SP_AXIS = "sp"


def _halo_from_next(x_first, bootstrap, axis_name):
    """Each device receives `x_first` from the device holding the NEXT
    time segment; the last device gets `bootstrap` instead.

    `ppermute` with perm [(i, i-1)] sends device i's value to i-1 and
    leaves unaddressed receivers (the last device) at zero, which the
    `where` on the axis index then replaces.
    """
    n = mesh_lib.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, i - 1) for i in range(1, n)]
    received = jax.lax.ppermute(x_first, axis_name, perm)
    return jnp.where(idx == n - 1, bootstrap, received)


def _solve_boundary_chain(a_seg, b_seg, y_init, axis_name):
    """Solve y_start_i = b_i + a_i * y_start_{i+1} over the device axis and
    return this device's INCOMING boundary y_start_{i+1} (y_init for the
    last device).

    The per-segment summaries are [batch...]-shaped; with D devices the
    gathered chain is [D, batch...] — tiny — so every device solves the
    whole chain redundantly (replicated compute beats a sequential
    D-step ppermute pipeline at these sizes, and XLA dedupes it).
    """
    n = mesh_lib.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    a_all = jax.lax.all_gather(a_seg, axis_name)  # [D, ...] in time order
    b_all = jax.lax.all_gather(b_seg, axis_name)

    def step(y_next, ab):
        a, b = ab
        y = b + a * y_next
        return y, y_next  # emit the INCOMING boundary for this segment

    _, y_in_all = jax.lax.scan(step, y_init, (a_all, b_all), reverse=True)
    return jnp.take(y_in_all, idx, axis=0)


def _local_affine_scan(a, b):
    """Reverse scan of y_t = b_t + a_t*y_{t+1} with y=0 past the segment,
    plus the suffix products P_t = prod_{s>=t} a_s. Returns (B_t, P_t)
    so the true solution is y_t = B_t + P_t * y_boundary_in."""

    def step(carry, ab):
        y, p = carry
        a_t, b_t = ab
        y = b_t + a_t * y
        p = a_t * p
        return (y, p), (y, p)

    ones = jnp.ones_like(b[0])
    (_, _), (B, Pr) = jax.lax.scan(
        step, (jnp.zeros_like(b[0]), ones), (a, b), reverse=True
    )
    return B, Pr


def seqpar_discounted_returns(rewards, dones, bootstrap_value, gamma, *, axis_name):
    """Time-sharded Monte-Carlo returns; matches
    `ops.returns.discounted_returns` on the gathered result."""
    a = gamma * (1.0 - dones.astype(rewards.dtype))
    B, Pr = _local_affine_scan(a, rewards)
    y_in = _solve_boundary_chain(Pr[0], B[0], bootstrap_value, axis_name)
    return B + Pr * y_in


def seqpar_gae(
    rewards, values, dones, bootstrap_value, gamma, lam, *, axis_name
):
    """Time-sharded GAE; matches `ops.returns.gae` on the gathered result.

    δ_t needs V(s_{t+1}) across the segment boundary → one halo exchange
    of each segment's first value.
    """
    dones = dones.astype(rewards.dtype)
    v_halo = _halo_from_next(values[0], bootstrap_value, axis_name)
    values_tp1 = jnp.concatenate([values[1:], v_halo[None]], axis=0)
    nonterm = 1.0 - dones
    deltas = rewards + gamma * values_tp1 * nonterm - values
    a = gamma * lam * nonterm
    B, Pr = _local_affine_scan(a, deltas)
    adv_in = _solve_boundary_chain(Pr[0], B[0], jnp.zeros_like(bootstrap_value), axis_name)
    advantages = B + Pr * adv_in
    return advantages, advantages + values


def seqpar_vtrace(
    target_log_probs,
    behaviour_log_probs,
    rewards,
    values,
    dones,
    bootstrap_value,
    gamma,
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
    lam: float = 1.0,
    *,
    axis_name,
):
    """Time-sharded V-trace; matches `ops.returns.vtrace` on the gathered
    result. Two boundary dependencies: V(x_{t+1}) for the deltas (halo of
    `values`) and vs_{t+1} for the pg advantages (the solved boundary
    itself, since vs_next_first = y_in + v_halo)."""
    dones = dones.astype(rewards.dtype)
    discounts = gamma * (1.0 - dones)
    # Same LOG_RATIO_CAP as ops.returns.vtrace — the gathered-equality
    # contract requires the capped ratio on both sides.
    rhos = jnp.exp(
        jnp.minimum(
            target_log_probs - behaviour_log_probs, returns.LOG_RATIO_CAP
        )
    )
    clipped_rhos = jnp.minimum(rho_bar, rhos)
    cs = lam * jnp.minimum(c_bar, rhos)

    v_halo = _halo_from_next(values[0], bootstrap_value, axis_name)
    values_tp1 = jnp.concatenate([values[1:], v_halo[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_tp1 - values)

    a = discounts * cs
    B, Pr = _local_affine_scan(a, deltas)
    y_in = _solve_boundary_chain(
        Pr[0], B[0], jnp.zeros_like(bootstrap_value), axis_name
    )
    vs_minus_v = B + Pr * y_in
    vs = vs_minus_v + values

    # vs at the next segment's first index; for the last device y_in is the
    # global init (0) and v_halo is the bootstrap, giving exactly bootstrap.
    vs_halo = y_in + v_halo
    vs_tp1 = jnp.concatenate([vs[1:], vs_halo[None]], axis=0)
    pg_advantages = clipped_rhos * (rewards + discounts * vs_tp1 - values)

    from actor_critic_tpu.ops.returns import VTraceOutput

    return VTraceOutput(vs=vs, pg_advantages=pg_advantages, clipped_rhos=clipped_rhos)


def make_sp_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the time axis (for standalone seq-parallel use;
    inside a larger program, carve "sp" out of the trainer's own mesh)."""
    devices = jax.devices() if devices is None else devices
    if n_devices is not None:
        devices = devices[:n_devices]
    return jax.make_mesh((len(devices),), (SP_AXIS,), devices=devices)


def make_seqpar_fn(fn, mesh: Mesh, n_time_sharded_args: int, axis_name: str = SP_AXIS):
    """Wrap a `seqpar_*` function into a jitted callable on global [T, ...]
    arrays.

    The first `n_time_sharded_args` positional args are sharded over the
    time axis (T must divide by mesh size); remaining positional args
    (bootstrap value, scalars) are replicated. Returns outputs sharded
    the same way, visible to the caller as global [T, ...] arrays.
    """
    time_spec = P(axis_name)
    rep = P()

    def wrapped(*args):
        sharded = args[:n_time_sharded_args]
        rest = args[n_time_sharded_args:]
        in_specs = (time_spec,) * len(sharded) + (rep,) * len(rest)

        shmapped = mesh_lib.shard_map(
            partial(fn, axis_name=axis_name),
            mesh=mesh,
            in_specs=in_specs,
            out_specs=time_spec,
            check_vma=False,
        )
        return shmapped(*args)

    return jax.jit(wrapped)
