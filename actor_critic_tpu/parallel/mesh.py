"""Device mesh + collectives — the distributed communication backend.

TPU-native replacement for the reference's tf.distribute
MirroredStrategy/NCCL gradient-all-reduce path and its Python-queue
actor↔learner transport (BASELINE.json:5,11; SURVEY.md §2.4 — reference
mount empty at survey, §0). Instead of wrapping a transport library, the
framework expresses parallelism as shardings over a `jax.sharding.Mesh`
and lets XLA insert collectives that ride ICI (intra-slice) or DCN
(multi-host, via `jax.distributed.initialize`).

Axes convention (SURVEY.md §2.3):
- "dp": data parallel — env batch and minibatches sharded; gradients
  `psum`-ed. The only axis the RL workloads need.
- "model": reserved stub for tensor parallelism (unused by these model
  sizes; kept so the mesh API doesn't change if TP is ever added).

All trainers are written against `axis_name=...` pmean/psum helpers that
degrade to no-ops off-mesh, so the same train-step code runs single-chip
and under `shard_map`.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
MODEL_AXIS = "model"

try:
    # jax >= 0.5 promotes shard_map to the top level with the
    # `check_vma` spelling; prefer it when present.
    shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        """Compat wrapper: jax 0.4.x exposes shard_map under
        `jax.experimental` and calls the replication check `check_rep`."""
        return _shard_map_exp(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )


def reshard(x, sharding):
    """Compat for `jax.sharding.reshard` (jax >= 0.6 explicit-mesh
    constraint API): on older jax the mesh axes are Auto-typed, where
    `with_sharding_constraint` expresses the same in-program
    redistribution."""
    try:
        return jax.sharding.reshard(x, sharding)
    except AttributeError:
        return jax.lax.with_sharding_constraint(x, sharding)


def axis_size(axis_name) -> int:
    """Static size of a mapped axis; `jax.lax.axis_size` where it exists,
    else the `psum(1, axis)` idiom (constant-folded to a python int)."""
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """How to lay the process's devices out as a mesh."""

    dp: int = -1  # -1 → all remaining devices
    model: int = 1


def make_mesh(cfg: MeshConfig = MeshConfig(), devices=None) -> Mesh:
    devices = jax.devices() if devices is None else devices
    n = len(devices)
    model = cfg.model
    dp = n // model if cfg.dp == -1 else cfg.dp
    if dp * model != n:
        raise ValueError(f"mesh {dp}x{model} != {n} devices")
    return jax.make_mesh((dp, model), (DP_AXIS, MODEL_AXIS), devices=devices)


def multihost_init(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host (DCN) initialization (SURVEY §5.8).

    Must be called before anything initializes the XLA backend (JAX's
    `distributed.initialize` raises otherwise), so the single-process
    check CANNOT use `jax.process_count()` — that call would itself
    initialize the backend. Instead we let JAX's own cluster
    auto-detection (SLURM, Open MPI, Cloud TPU pod metadata,
    JAX_COORDINATOR_ADDRESS, ...) decide: if it finds no cluster, its
    error is swallowed and the process runs single-host.

    With an explicit `coordinator` the init is NOT optional — failures
    propagate. Outside auto-detectable clusters (e.g. a hand-rolled
    launcher, or the two-process localhost exercise in
    tests/test_multihost.py) pass `num_processes`/`process_id` too;
    inside one, JAX infers them.
    """
    if coordinator is not None:
        kwargs = {}
        if num_processes is not None:
            kwargs["num_processes"] = num_processes
        if process_id is not None:
            kwargs["process_id"] = process_id
        jax.distributed.initialize(coordinator_address=coordinator, **kwargs)
        return
    try:
        jax.distributed.initialize()
    except RuntimeError:
        # Backend already initialized — a real misuse worth surfacing.
        raise
    except Exception as e:
        # No recognizable cluster environment: single-process no-op. The
        # exception is logged because a *detected-but-misconfigured*
        # cluster (malformed SLURM/pod env vars) lands here too, and
        # silently running N independent single-host trainings would be
        # much worse than a startup crash.
        import logging

        logging.getLogger(__name__).warning(
            "jax.distributed.initialize() failed (%s: %s); continuing "
            "single-host. If this job was meant to be multi-host, fix the "
            "cluster env or pass coordinator= explicitly.", type(e).__name__, e,
        )


# --- collective helpers: no-op when axis_name is None ---------------------
# axis_name may also be a TUPLE of mesh-axis names (lax.pmean/psum reduce
# over all of them in one collective — the sp×dp learner update uses this).

AxisName = Optional["str | tuple[str, ...]"]


def pmean(x, axis_name: AxisName):
    if axis_name is None:
        return x
    return jax.lax.pmean(x, axis_name)


def psum(x, axis_name: AxisName):
    if axis_name is None:
        return x
    return jax.lax.psum(x, axis_name)


def pmean_tree(tree, axis_name: AxisName):
    if axis_name is None:
        return tree
    return jax.tree.map(partial(jax.lax.pmean, axis_name=axis_name), tree)


# --- sharding helpers ------------------------------------------------------

def shard_batch_spec(mesh: Mesh) -> NamedSharding:
    """Sharding for a [B, ...] batch: B split over dp, rest replicated."""
    return NamedSharding(mesh, P(DP_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
