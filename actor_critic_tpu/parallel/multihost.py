"""Multi-host distributed actor–learner (ISSUE 9 tentpole).

Every parallel layer below this one (`parallel/dp.py`, `mesh.py`,
`seqpar.py`) stops at a single process. This module stands the PR 6
async actor–learner stack up under `jax.distributed`: each process runs
its own shard-pool actor fleet feeding its local `TrajQueue`, and the
per-process learner scales in one of two modes —

- **sync** (Accelerated Methods for Deep RL, arxiv 1803.02811): the
  V-trace-corrected update (`ppo.make_async_update_fn`) is shard_map-ed
  over the GLOBAL device mesh, each process contributing its local
  `[T, E_a]` block as one dp shard of a global `[T, P*E_a]` batch
  (`jax.make_array_from_process_local_data`), with params/optimizer
  replicated — the per-minibatch gradient pmean the update already does
  becomes the cross-process all-reduce, exactly how `parallel/dp.py`
  scales the fused step across local devices. The update is therefore a
  global barrier: the behavior-version counter advances in lockstep on
  every host (verified each iteration by an all-reduced counter +
  replicated-params fingerprint — `make_consistency_check`), so
  `max_staleness` keeps its fleet-wide meaning. A straggler host stalls
  the fleet — that is the measured cost the gossip mode removes.

- **gossip** (Gossip-based Actor-Learner Architectures, arxiv
  1906.04585): per-host learners update INDEPENDENTLY (no collective,
  no barrier) and exchange parameters peer-to-peer on a rotating ring
  schedule through a filesystem param mailbox: every `gossip_every`
  consumed blocks a host atomically publishes its `(version, params)`
  snapshot under `mailbox_dir/host<rank>/` and mixes in the latest
  snapshot a background `FileMailboxWriter` thread deposited from the
  scheduled peer (`gossip_peer` rotates the ring so weights diffuse
  through the whole fleet in O(P) rounds). `gossip_weight` is the
  mixing knob: `params ← (1-w)·own + w·peer`. A straggler host only
  serves stale params to its peers — the fleet never waits on it.

Version accounting across hosts: versions stay plain monotonic ints =
blocks consumed (the PR 6 contract). In sync mode the global barrier
makes every host's counter identical; in gossip mode each host counts
its own consumption and the peer lag (`gossip_lag`) is surfaced per
mix, so staleness is measured, never hidden.

The in-memory `ParamMailbox` carries the same frozen-snapshot contract
as `PolicyPublisher.publish` (ISSUE 7): `deposit` stores a read-only
copy, so the writer thread keeps no writable alias of what the learner
consumes and a racing in-place write crashes at its own site
(`analysis/racesan.exercise_mailbox` gates the pair in tier-1).

Everything is drivable on CPU: `distributed_init` turns on the gloo
CPU collectives implementation, and `scripts/launch_multihost.py`
spawns an N-process local cluster against a localhost coordinator — the
tier-1 smoke and the `multihost_scaling` bench run with no TPU present.
"""

# jaxlint: hot-module

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from actor_critic_tpu.algos.traj_queue import _snapshot_frozen
from actor_critic_tpu.parallel.mesh import DP_AXIS, multihost_init, shard_map
from actor_critic_tpu.utils import numguard


def distributed_init(
    coordinator: str,
    num_processes: int,
    process_id: int,
) -> None:
    """`jax.distributed.initialize` against an explicit coordinator,
    with the CPU backend's cross-process collectives enabled first
    (XLA:CPU refuses multi-process computations without an explicit
    collectives implementation; gloo is the in-tree one). Must run
    before anything initializes the XLA backend — same contract as
    `mesh.multihost_init`, which this wraps."""
    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip() in ("cpu", ""):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # non-CPU backends (TPU pods) bring their own transport
    multihost_init(
        coordinator=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh():
    """One-axis dp mesh over EVERY process's devices (the cross-process
    analogue of `mesh.make_mesh`)."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (DP_AXIS,))


def host_lane(rank: int) -> None:
    """Name this process's Perfetto lane `host<rank>` in the installed
    telemetry session (the PR 3 trace relay renders one lane per pid;
    the rank label is what makes a fleet trace readable)."""
    from actor_critic_tpu import telemetry

    sess = telemetry.current()
    if sess is not None:
        sess.tracer.name_process(os.getpid(), f"host{rank}")


# ---------------------------------------------------------------------------
# param mailbox: in-memory (latest-wins, frozen snapshots) + file transport
# ---------------------------------------------------------------------------


class ParamMailbox:
    """Thread-safe latest-wins store of one peer `(version, params)`
    snapshot — the per-host mailbox of the gossip exchange.

    Same frozen-snapshot contract as `PolicyPublisher.publish`
    (ISSUE 7): `deposit` copies the numpy leaves and flips
    `writeable = False`, so the depositing thread retains no writable
    alias of what the learner consumes, and an in-place write into a
    consumed tree crashes at the write site. `take` hands out the
    latest snapshot at most once (None until a newer deposit lands);
    `peek` reads without consuming.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._params: Any = None
        self._version = -1
        self._peer = -1
        self._taken = True
        self._deposits = 0
        # peer rank -> newest version accepted from THAT peer: versions
        # are per-peer consumption clocks and are NOT comparable across
        # peers — a slow host's version 5 can be fresher news than a
        # fast host's version 50, so the staleness drop must guard
        # per-peer regression only or the ring would permanently mute
        # every host slower than the fastest ever seen.
        self._peer_versions: dict[int, int] = {}

    def deposit(self, params: Any, version: int, peer: int) -> bool:
        """Store a frozen snapshot; a version the SAME peer already
        reached (<= its newest seen) is dropped so the learner never
        mixes that peer backwards — a different peer (the ring rotated)
        always wins. Returns True when the deposit became the mailbox's
        latest."""
        snapshot = _snapshot_frozen(params)  # copy OUTSIDE the lock
        with self._lock:
            if version <= self._peer_versions.get(int(peer), -1):
                return False
            self._peer_versions[int(peer)] = int(version)
            self._params = snapshot
            self._version = int(version)
            self._peer = int(peer)
            self._taken = False
            self._deposits += 1
            return True

    def take(self) -> Optional[tuple[int, int, Any]]:
        """(version, peer, frozen params) if a deposit landed since the
        last take, else None — the learner's once-per-gossip-round
        consume."""
        with self._lock:
            if self._taken or self._params is None:
                return None
            self._taken = True
            return self._version, self._peer, self._params

    def peek(self) -> Optional[tuple[int, int, Any]]:
        with self._lock:
            if self._params is None:
                return None
            return self._version, self._peer, self._params

    def stats(self) -> dict:
        with self._lock:
            return {
                "version": self._version,
                "peer": self._peer,
                "deposits": self._deposits,
            }


def params_file(mailbox_dir: str, rank: int) -> str:
    return os.path.join(mailbox_dir, f"host{rank}", "params.npz")


def write_params(mailbox_dir: str, rank: int, version: int, params: Any) -> str:
    """Atomically publish this host's `(version, params)` snapshot:
    flattened leaves into an .npz written next to the target, fsynced,
    and `os.replace`-d into place, so a peer reading concurrently sees
    either the previous complete snapshot or this one — never a torn
    file (and, post-crash, never a rename that outlived its data
    blocks). Latest-wins by construction (one file per host); the tmp
    name carries the pid so restarted/colliding writers in a shared
    directory can never interleave into one file."""
    import jax

    path = params_file(mailbox_dir, rank)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    leaves = jax.tree.leaves(params)
    payload = {f"leaf{i}": np.asarray(v) for i, v in enumerate(leaves)}
    # Finiteness gate (ISSUE 14): a nan/inf snapshot published here
    # diffuses through the gossip ring to EVERY peer within world-1
    # rounds and poisons each learner's mix_params — the one place a
    # single host's divergence becomes a fleet-wide one. Refuse the
    # publish; the mailbox keeps this host's previous good snapshot.
    numguard.check_finite(payload, "mailbox publish", name="params")
    payload["version"] = np.asarray(int(version), np.int64)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
        # fsync BEFORE the rename: without it a crash can leave the
        # rename durable while the data blocks are not — a zero-length
        # "complete" snapshot, the one torn shape atomic-rename alone
        # does not exclude.
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def _load_snapshot(path: str):
    """`(version, leaves)` of a published snapshot file, or None when
    it is absent, the read raced the very first publish's creation, or
    the file is torn/partial (a crashed or non-atomic writer): torn
    reads are retried on the next poll, never fatal. The ONE place the
    torn-file exception set lives — NB `np.load` raises
    `zipfile.BadZipFile` (NOT an OSError) on a truncated archive and
    `EOFError` on an empty one; the reverted PR 12 reader missed both
    and the mailbox writer thread died on the first torn snapshot."""
    import zipfile

    try:
        with np.load(path) as z:
            version = int(z["version"])
            leaves = [z[f"leaf{i}"] for i in range(len(z.files) - 1)]
    except (OSError, KeyError, ValueError, EOFError, zipfile.BadZipFile):
        return None
    return version, leaves


def read_params(mailbox_dir: str, rank: int, template: Any):
    """Latest published `(version, params)` of `rank`, rebuilt into
    `template`'s tree structure; None when absent/torn (the
    `_load_snapshot` tolerance contract)."""
    import jax

    out = _load_snapshot(params_file(mailbox_dir, rank))
    if out is None:
        return None
    version, leaves = out
    return version, jax.tree.unflatten(jax.tree.structure(template), leaves)


def read_version(mailbox_dir: str, rank: int) -> Optional[int]:
    """Version field alone of `rank`'s published snapshot — no params
    template needed, so observers (FleetMonitor, an LB health probe)
    can read a fleet's mailbox without knowing its tree structure;
    None when absent/torn (the `_load_snapshot` tolerance contract)."""
    out = _load_snapshot(params_file(mailbox_dir, rank))
    return None if out is None else out[0]


def gossip_peer(rank: int, world: int, round_: int) -> int:
    """Rotating ring schedule: at round r every host reads from the
    peer `1 + r mod (world-1)` ranks ahead, so over world-1 consecutive
    rounds each host hears from EVERY other host — parameters diffuse
    through the whole fleet without any global step."""
    if world < 2:
        raise ValueError("gossip needs at least 2 hosts")
    return (rank + 1 + round_ % (world - 1)) % world


def mix_params(own: Any, peer: Any, weight: float) -> Any:
    """Per-leaf convex mix `(1-w)·own + w·peer` (numpy trees; the
    gossip-averaging step of arxiv 1906.04585, weight = the mixing
    knob). Leaf dtypes are preserved."""
    import jax

    w = float(weight)
    return jax.tree.map(
        lambda a, b: ((1.0 - w) * a + w * b).astype(np.asarray(a).dtype),
        own, peer,
    )


class FileMailboxWriter:
    """The mailbox writer thread: polls the ring-scheduled peer's
    published snapshot file and deposits fresh versions into the local
    `ParamMailbox`. Polling runs OFF the learner thread so a slow/cold
    filesystem read never blocks an update; the learner only flips the
    current round (`set_round`) and takes deposits.

    The thread model (`analysis/thread_model.py`) learns this spawn as
    the `mailbox` role; the deposit path is lock-guarded inside
    ParamMailbox and the snapshot it stores is frozen, so the writer
    retains no writable alias (racesan's `exercise_mailbox` covers the
    publish/consume pair).
    """

    def __init__(
        self,
        mailbox_dir: str,
        rank: int,
        world: int,
        template: Any,
        mailbox: ParamMailbox,
        stop: threading.Event,
        poll_s: float = 0.05,
    ):
        self._dir = mailbox_dir
        self._rank = int(rank)
        self._world = int(world)
        self._template = template
        self._mailbox = mailbox
        self._stop = stop
        self._poll_s = float(poll_s)
        # jaxlint: thread-owned=caller (plain int rebound by the learner
        # thread via set_round; the writer thread only reads it and
        # tolerates a one-poll-stale round — it would just re-read the
        # previous peer's file once)
        self._round = 0
        # jaxlint: thread-owned=mailbox (single writer: poll_once is
        # only ever called from the mailbox thread's _run loop — or, in
        # fleetsan, from the scheduler with the thread never started —
        # and nothing else reads the per-peer clock)
        self._seen: dict[int, int] = {}
        self.error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name=f"mailbox-{rank}", daemon=True
        )

    def set_round(self, round_: int) -> None:
        """Advance the ring schedule (called by the learner at gossip
        boundaries; plain atomic rebind)."""
        self._round = int(round_)

    def start(self) -> "FileMailboxWriter":
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    def poll_once(self) -> bool:
        """ONE poll of the ring-scheduled peer: read its published
        snapshot, drop versions that peer already reached (versions are
        per-peer clocks — `self._seen` tracks the newest PER RANK so
        the ring rotating onto a slower peer still deposits its
        lower-numbered fresh news), deposit the rest. Returns True when
        a deposit landed. Factored out of the thread loop so fleetsan
        can drive the REAL consume logic under a deterministic
        scheduler (no thread, no wall-clock)."""
        peer = gossip_peer(self._rank, self._world, self._round)
        out = read_params(self._dir, peer, self._template)
        if out is None:
            return False
        version, params = out
        if version <= self._seen.get(peer, -1):
            return False
        if self._mailbox.deposit(params, version, peer):
            self._seen[peer] = version
            return True
        return False

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                self.poll_once()
                self._stop.wait(self._poll_s)
        except BaseException as e:  # surfaced by the learner loop
            self.error = e


class FleetMonitor:
    """Fleet-membership observability over the gossip mailbox (ROADMAP
    elastic-ops item (d), ISSUE 12 satellite): rank, world size, and
    per-peer last-publish age read from the shared `mailbox_dir` — the
    same files the exchange itself uses, so "this peer went quiet" is
    measured at the transport, not inferred. `snapshot()` feeds
    `/healthz` (serving gateway `--distributed`): a peer whose mailbox
    age exceeds `stale_after_s` (or that never published) marks the
    fleet degraded and the endpoint answers 503.

    Ages come from `os.stat` mtime — no parse, so a torn file still
    reports an age; the version field rides via `read_version` when
    the file parses (torn/absent -> None, the `read_params` tolerance
    contract — no params template needed)."""

    def __init__(
        self,
        mailbox_dir: str,
        rank: int,
        world: int,
        stale_after_s: float = 30.0,
    ):
        self.mailbox_dir = mailbox_dir
        self.rank = int(rank)
        self.world = int(world)
        self.stale_after_s = float(stale_after_s)

    def snapshot(self) -> dict:
        """{rank, world, stale_after_s, peers: {rank: {age_s, version,
        published}}, stale: [ranks], ok}. Peers = every OTHER rank of
        the fleet; `ok` iff none is stale."""
        now = time.time()
        peers: dict[str, dict] = {}
        stale: list[int] = []
        for peer in range(self.world):
            if peer == self.rank:
                continue
            path = params_file(self.mailbox_dir, peer)
            entry: dict = {"published": False, "age_s": None, "version": None}
            try:
                entry["age_s"] = round(now - os.stat(path).st_mtime, 3)
                entry["published"] = True
            except OSError:
                pass
            if entry["published"]:
                entry["version"] = read_version(self.mailbox_dir, peer)
            if not entry["published"] or entry["age_s"] > self.stale_after_s:
                stale.append(peer)
            peers[str(peer)] = entry
        return {
            "rank": self.rank,
            "world": self.world,
            "stale_after_s": self.stale_after_s,
            "peers": peers,
            "stale": stale,
            "ok": not stale,
        }


# ---------------------------------------------------------------------------
# sync mode: global-mesh data-parallel update + consistency check
# ---------------------------------------------------------------------------


def _block_spec(ndim: int):
    """PartitionSpec of one [T, E, ...] block array under the global dp
    mesh: the env axis (axis 1) is the shard axis — the cross-process
    extension of `dp.py`'s P("dp") leading-axis convention, shifted one
    axis because host blocks are time-major."""
    from jax.sharding import PartitionSpec as P

    return P(*(None, DP_AXIS) + (None,) * (ndim - 2))


def make_multihost_update_step(
    env_spec,
    cfg,
    mesh,
    correction: str = "vtrace",
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
):
    """The sync-mode learner program: `ppo.make_async_update_fn` with
    `axis_name=DP_AXIS`, shard_map-ed over the global mesh and jitted.

    Call it through `stage_global` arrays: params/opt/key replicated,
    block arrays dp-sharded on their env axis (each process contributes
    its own `[T, E_a]` block; the global batch is `[T, P*E_a]`). The
    per-minibatch gradient pmean inside `ppo_update` lowers to the
    cross-process all-reduce — the DCN analogue of `dp.py`'s ICI one.
    The raw uint32 key data is passed replicated and wrapped in-program
    (typed PRNG keys don't ride `make_array_from_process_local_data`).
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from actor_critic_tpu.algos import ppo

    update_fn = ppo.make_async_update_fn(
        env_spec, cfg, can_truncate=True, correction=correction,
        rho_bar=rho_bar, c_bar=c_bar, axis_name=DP_AXIS,
    )

    def local_step(
        params, opt_state, key_data, obs, action, log_prob, value, reward,
        done, terminated, final_obs, last_obs, progress,
    ):
        key = jax.random.wrap_key_data(key_data)
        return update_fn(
            params, opt_state, obs, action, log_prob, value, reward, done,
            terminated, final_obs, last_obs, key, progress=progress,
        )

    def specs_of(tree, spec):
        return jax.tree.map(lambda _: spec, tree)

    def build(params, opt_state, key_data, arrays, progress):
        in_specs = (
            specs_of(params, P()),
            specs_of(opt_state, P()),
            P(),                                    # key data (replicated)
            _block_spec(arrays["obs"].ndim),
            _block_spec(arrays["action"].ndim),
            _block_spec(2), _block_spec(2),         # log_prob, value
            _block_spec(2), _block_spec(2),         # reward, done
            _block_spec(2),                         # terminated
            _block_spec(arrays["final_obs"].ndim),
            P(*(DP_AXIS,) + (None,) * (arrays["last_obs"].ndim - 1)),
            P(),                                    # progress scalar
        )
        out_specs = (specs_of(params, P()), specs_of(opt_state, P()), P())
        fn = shard_map(
            local_step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
        return jax.jit(fn)

    # One program per run: specs depend only on static shapes, so build
    # lazily on first call and reuse (the blocks are PR 4 fixed-shape
    # buckets — steady state compiles nothing new).
    cache: dict = {}

    def update(params, opt_state, key_data, arrays, progress):
        if "fn" not in cache:
            cache["fn"] = build(params, opt_state, key_data, arrays, progress)
        return cache["fn"](
            params, opt_state, key_data, arrays["obs"], arrays["action"],
            arrays["log_prob"], arrays["value"], arrays["reward"],
            arrays["done"], arrays["terminated"], arrays["final_obs"],
            arrays["last_obs"], progress,
        )

    return update


def stage_global(mesh, arrays: dict[str, np.ndarray]) -> dict:
    """Per-process local block arrays → global dp-sharded arrays (env
    axis split across processes). The inputs must already be snapshots
    (the learner np.array-copies queue slots before staging)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    out = {}
    for name, value in arrays.items():
        if name == "last_obs":
            spec = P(*(DP_AXIS,) + (None,) * (value.ndim - 1))
        else:
            spec = _block_spec(value.ndim)
        out[name] = jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), value
        )
    return out


def replicate_global(mesh, tree):
    """Identical per-process host trees → one replicated global array
    tree (initial params/opt staging; afterwards the update's outputs
    stay resident as replicated global arrays)."""
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    return multihost_utils.host_local_array_to_global_array(tree, mesh, P())


def fetch_local(tree):
    """Per-process numpy view of a REPLICATED global array tree (each
    process holds a full copy as its addressable shard)."""
    import jax

    return jax.tree.map(
        lambda x: np.asarray(x.addressable_data(0)), tree
    )


def make_consistency_check(mesh) -> Callable[..., tuple]:
    """ONE jitted collective over a small per-process vector
    `(version, fingerprint, stop_vote)`; returns
    `(version_sum, fp_max, fp_min, vote_sum)` for the whole fleet.

    - `version_sum == n_devices * local_version` is the
      broadcast-counter check: the counter is a small integer, so the
      float32 psum is EXACT for any fleet size (no rounding below
      2^24) and equality holds iff every host carries the same value.
    - The fingerprint compares via `fp_max == fp_min == local` — a
      pmax/pmin pair instead of a sum, because summing N identical
      floats rounds for non-power-of-two N while min==max equality is
      bit-exact for ANY fleet size.
    - A nonzero `vote_sum` is the fleet-agreed stop signal: every host
      computes the same sum, so duration-bounded sync runs all break
      after the SAME iteration — no host is left alone at the next
      collective.

    The local contribution is staged with one row per LOCAL device
    (identical rows), so the dp-sharded placement works on hosts with
    any number of addressable devices (a pod host's 4/8 chips), not
    just the 1-device CPU cluster.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def reduce_fn(x):  # local [rows, 3]
        vsum = jax.lax.psum(x[0, 0], DP_AXIS)
        fp_max = jax.lax.pmax(x[0, 1], DP_AXIS)
        fp_min = jax.lax.pmin(x[0, 1], DP_AXIS)
        votes = jax.lax.psum(x[0, 2], DP_AXIS)
        return jnp.stack([vsum, fp_max, fp_min, votes])

    fn = jax.jit(
        shard_map(
            reduce_fn,
            mesh=mesh, in_specs=P(DP_AXIS, None), out_specs=P(),
            check_vma=False,
        )
    )
    sharding = NamedSharding(mesh, P(DP_AXIS, None))
    local_rows = max(1, len(jax.local_devices()))

    def check(version: float, fingerprint: float, vote: float) -> tuple:
        row = np.asarray([[version, fingerprint, vote]], np.float32)
        arr = jax.make_array_from_process_local_data(
            sharding, np.repeat(row, local_rows, axis=0)
        )
        out = np.asarray(fn(arr).addressable_data(0)).reshape(-1)
        return float(out[0]), float(out[1]), float(out[2]), float(out[3])

    return check


def params_fingerprint(tree) -> float:
    """Order-stable scalar digest of a numpy params tree (sum of leaf
    sums; replicated trees produce bit-identical floats on every host,
    so a psum equality check catches any divergence)."""
    import jax

    return float(
        sum(np.sum(np.asarray(leaf, np.float64)) for leaf in jax.tree.leaves(tree))
    )


# ---------------------------------------------------------------------------
# the per-process driver
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GossipConfig:
    """Gossip-mode knobs (ignored in sync mode)."""

    every: int = 1        # consumed blocks between exchanges
    weight: float = 0.5   # peer mixing weight in [0, 1]
    poll_s: float = 0.05  # mailbox writer poll cadence


def train_multihost(
    pools,
    cfg,
    num_iterations: int,
    *,
    rank: int,
    world: int,
    mode: str = "sync",
    duration_s: Optional[float] = None,
    seed: int = 0,
    log_every: int = 10,
    log_fn: Optional[Callable[[int, dict], None]] = None,
    queue_depth: int = 4,
    max_staleness: Optional[int] = 8,
    updates_per_block: int = 1,
    correction: str = "vtrace",
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
    gossip: GossipConfig = GossipConfig(),
    mailbox_dir: Optional[str] = None,
):
    """One process's share of the distributed actor–learner fleet.

    Each process runs `len(pools)` `ActorService` threads feeding its
    local `TrajQueue` (identical to `ppo.train_host_async`'s host side)
    and one learner consuming blocks per `mode` (module docstring).
    `seed` must be IDENTICAL across processes — initial params derive
    from it, and sync mode's replicated state assumes equal starts;
    actor RNG streams are decorrelated per (rank, actor) internally.

    With `duration_s` set the run is WALL-bounded instead of
    count-bounded (`num_iterations` becomes a hard cap, pass a large
    one): each learner consumes as many blocks as it can inside the
    window — the measurement mode of the `multihost_scaling` bench,
    where a straggler's effect shows up as blocks NOT consumed. In sync
    mode the stop decision is itself all-reduced (a vote riding the
    per-iteration consistency check), so every host exits after the
    same iteration and nobody strands at the next collective; gossip
    hosts stop on their own clock (no barrier to strand at).

    Sync mode requires `jax.distributed` initialized with `world`
    processes (`distributed_init`); gossip mode needs only
    `mailbox_dir` (a directory shared by all hosts — peer-to-peer
    exchange never enters a collective). Returns
    `(np_params, history, summary)`; history rows carry the queue/
    staleness gauges plus `version_sum`/`fingerprint_ok` (sync) or
    `gossip_mixes`/`gossip_lag` (gossip).
    """
    import jax

    from actor_critic_tpu import telemetry
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.algos.host_loop import (
        MergedEpisodeTracker,
        maybe_log,
    )
    from actor_critic_tpu.algos.traj_queue import (
        ActorService,
        PolicyPublisher,
        TrajQueue,
        consume_block,
        validate_pools,
    )
    from actor_critic_tpu.models import host_actor

    if mode not in ("sync", "gossip"):
        raise ValueError(f"unknown mode {mode!r}")
    if mode == "sync" and correction != "vtrace":
        raise ValueError(
            "sync mode shard_maps the V-trace-corrected update "
            "(make_async_update_fn); correction='none' is only "
            "available in gossip mode or the single-host async driver"
        )
    if mode == "gossip" and world > 1 and not mailbox_dir:
        raise ValueError("gossip mode needs a shared mailbox_dir")
    spec, E_a = validate_pools(pools)

    key = jax.random.key(seed)
    key, pkey = jax.random.split(key)
    params, opt_state = ppo.init_host_params(spec, cfg, pkey)
    np_params = jax.device_get(params)
    if not host_actor.supports_mirror(np_params):
        raise ValueError(
            "multi-host mode needs the numpy actor mirror (MLP torso)"
        )
    host_policy = host_actor.make_ppo_host_policy(spec, cfg)

    def make_act_fn(actor_params, rng):
        def act(o):
            action, logp, value = host_policy(actor_params, o, rng)
            return action, {"log_prob": logp, "value": value}

        return act

    queue = TrajQueue(
        depth=queue_depth, max_staleness=max_staleness,
        policy="drop_oldest", gauge_name=f"traj_queue_host{rank}",
    )
    publisher = PolicyPublisher(np_params, version=0)
    stop = threading.Event()
    actors = [
        ActorService(
            i, pool, queue, publisher, cfg.rollout_steps, make_act_fn,
            # Decorrelate across the fleet: rank strides by a large
            # prime over the per-actor prime stride.
            rng=np.random.default_rng(
                seed + 0x5EED + rank * 1_000_003 + i * 7919
            ),
            stop=stop,
        )
        for i, pool in enumerate(pools)
    ]

    mesh = update = check = None
    mailbox = writer = None
    local_update = None
    if mode == "sync":
        mesh = global_mesh()
        if mesh.devices.size < world:
            raise ValueError(
                f"sync mode: mesh has {mesh.devices.size} devices for "
                f"world={world} — was distributed_init called?"
            )
        update = make_multihost_update_step(
            spec, cfg, mesh, correction=correction,
            rho_bar=rho_bar, c_bar=c_bar,
        )
        check = make_consistency_check(mesh)
        params = replicate_global(mesh, jax.device_get(params))
        opt_state = replicate_global(mesh, jax.device_get(opt_state))
    else:
        local_update = ppo.make_async_update_step(
            spec, cfg, can_truncate=True, correction=correction,
            rho_bar=rho_bar, c_bar=c_bar,
        )
        if world > 1:
            mailbox = ParamMailbox()
            writer = FileMailboxWriter(
                mailbox_dir, rank, world, template=np_params,
                mailbox=mailbox, stop=stop, poll_s=gossip.poll_s,
            )
            # Publish the INITIAL params so peers' first reads succeed.
            write_params(mailbox_dir, rank, 0, np_params)
            writer.start()

    history: list = []
    trackers = MergedEpisodeTracker([a.tracker for a in actors])
    summary = {
        "rank": rank, "world": world, "mode": mode,
        "version_consistent": True, "fingerprint_consistent": True,
        "gossip_mixes": 0, "gossip_skips": 0, "gossip_lag_max": 0,
    }
    t_start = time.perf_counter()
    deadline = None if duration_s is None else t_start + float(duration_s)
    consumed_blocks = 0
    try:
        for a in actors:
            a.start()
        for it in range(num_iterations):
            telemetry.profiler_tick()
            for a in actors:
                if a.error is not None:
                    raise RuntimeError(
                        f"host {rank} actor {a.actor_id} died"
                    ) from a.error
            if writer is not None and writer.error is not None:
                raise RuntimeError(
                    f"host {rank} mailbox writer died"
                ) from writer.error
            with telemetry.span("iteration", it=it + 1):
                queue.set_consumer_version(it)
                with telemetry.span("queue_wait", it=it + 1):
                    block = consume_block(
                        queue, actors, context=f"host {rank} "
                    )
                staleness = max(it - block.version, 0)
                stop_after = False
                progress = np.float32(
                    min(it / cfg.anneal_iters, 1.0)
                    if cfg.anneal_iters > 0 else 0.0
                )
                extra = {}
                if mode == "sync":
                    with telemetry.span("host_to_device"):
                        # Snapshot the slot before release (the PR 6
                        # copy-on-transfer contract), then stage onto
                        # the global mesh.
                        # jaxlint: disable=host-sync (host-numpy copy of
                        # a queue slot — no device value is touched; the
                        # slot must be snapshotted before release
                        # rewrites it)
                        local = {
                            k: np.array(v) for k, v in block.arrays.items()
                        }
                        queue.release(block)
                        garrays = stage_global(mesh, local)
                    with telemetry.span("update", dispatch="async"):
                        for _ in range(updates_per_block):
                            key, ukey = jax.random.split(key)
                            # jaxlint: disable=donation-discipline
                            # (withheld: the replicated global-mesh
                            # trees feed the consistency check and the
                            # mailbox publish after the dispatch;
                            # donation is the ROADMAP kernel-level
                            # item's change, gated by perfsan)
                            params, opt_state, metrics = update(
                                params, opt_state,
                                # jaxlint: disable=host-sync (deliberate:
                                # the 2-word key data rides replicated as
                                # host numpy — typed PRNG keys don't
                                # cross make_array_from_process_local_data)
                                np.asarray(jax.random.key_data(ukey)),
                                garrays, progress,
                            )
                    np_params = fetch_local(params)
                    version = it + 1
                    # Broadcast-counter + replicated-params checks plus
                    # the stop vote, ONE collective (fp is the float32
                    # representative of the local digest; see
                    # make_consistency_check for why the counter uses
                    # an exact psum and the fingerprint a pmax/pmin
                    # equality).
                    fp = float(np.float32(params_fingerprint(np_params)))
                    vote = 1.0 if (
                        deadline is not None
                        and time.perf_counter() >= deadline
                    ) else 0.0
                    # jaxlint: disable=host-sync (deliberate: the
                    # consistency check IS a designed per-iteration
                    # barrier — sync mode's update is already a global
                    # collective, so this adds one tiny collective, not
                    # a new serialization)
                    vsum, fp_max, fp_min, votes = check(
                        float(version), fp, vote
                    )
                    stop_after = votes > 0
                    # jaxlint: disable=host-sync (python floats — the
                    # device sync happened inside `check` above)
                    v_ok = bool(vsum == mesh.devices.size * float(version))
                    fp_ok = bool(fp_max == fp_min == fp)
                    summary["version_consistent"] &= v_ok
                    summary["fingerprint_consistent"] &= fp_ok
                    extra.update(
                        version_sum=vsum, version_ok=v_ok,
                        fingerprint_ok=fp_ok,
                    )
                    # jaxlint: disable=host-sync (deliberate: scalar
                    # metric fetch after the update — the consistency
                    # check already fenced this iteration's dispatch)
                    metrics = {
                        k: np.asarray(v.addressable_data(0))
                        for k, v in metrics.items()
                    }
                else:
                    with telemetry.span("host_to_device"):
                        # jnp.array, NOT asarray: one copying transfer
                        # snapshots the slot (the PR 6 contract) —
                        # releasing only after it materializes.
                        # jaxlint: disable=transfer-discipline (the
                        # host plane's per-block upload by design —
                        # perfsan budgets the bytes)
                        arrays = {
                            k: jax.numpy.array(v)
                            for k, v in block.arrays.items()
                        }
                        queue.release(block)
                    kwargs = {}
                    if cfg.anneal_iters > 0:
                        # jaxlint: disable=transfer-discipline (scalar
                        # anneal progress — 4 bytes)
                        kwargs["progress"] = jax.numpy.asarray(progress)
                    with telemetry.span("update", dispatch="async"):
                        for _ in range(updates_per_block):
                            key, ukey = jax.random.split(key)
                            # jaxlint: disable=donation-discipline
                            # (withheld: gossip mixes and the mailbox
                            # publish read the input tree around the
                            # dispatch — the ROADMAP kernel-level item
                            # owns the donation change, perfsan-gated)
                            params, opt_state, metrics = local_update(
                                params, opt_state,
                                arrays["obs"], arrays["action"],
                                arrays["log_prob"], arrays["value"],
                                arrays["reward"], arrays["done"],
                                arrays["terminated"], arrays["final_obs"],
                                arrays["last_obs"], ukey, **kwargs,
                            )
                    # jaxlint: disable=transfer-discipline (deliberate:
                    # the gossip publish snapshot — one host fetch per
                    # block is the mailbox contract)
                    np_params = jax.device_get(params)
                    version = it + 1
                    stop_after = (
                        deadline is not None
                        and time.perf_counter() >= deadline
                    )
                    if mailbox is not None and version % gossip.every == 0:
                        round_ = version // gossip.every
                        writer.set_round(round_)
                        deposit = mailbox.take()
                        if deposit is not None:
                            peer_version, peer, peer_params = deposit
                            lag = max(version - peer_version, 0)
                            np_params = mix_params(
                                np_params, peer_params, gossip.weight
                            )
                            # jaxlint: disable=transfer-discipline
                            # (deliberate: re-placing the gossip-mixed
                            # params — once per gossip round, not per
                            # step)
                            params = jax.device_put(np_params)
                            summary["gossip_mixes"] += 1
                            summary["gossip_lag_max"] = max(
                                summary["gossip_lag_max"], lag
                            )
                            extra.update(
                                gossip_peer=peer, gossip_lag=lag
                            )
                        else:
                            summary["gossip_skips"] += 1
                        write_params(mailbox_dir, rank, version, np_params)

                publisher.publish(np_params, version=it)
                qs = queue.stats()
                extra.update(
                    env_steps=sum(a.steps_collected for a in actors),
                    consumed_env_steps=(it + 1) * cfg.rollout_steps * E_a,
                    block_actor=block.actor_id,
                    block_staleness=staleness,
                    queue_depth=qs["depth"],
                    queue_drops_full=qs["drops_full"],
                    queue_drops_stale=qs["drops_stale"],
                    learner_idle_s=qs["learner_idle_s"],
                )
                maybe_log(
                    it, log_every, metrics, trackers, history, log_fn,
                    extra=extra,
                    num_iterations=0 if deadline is not None else num_iterations,
                    force=it == 0,
                )
                consumed_blocks = it + 1
                if stop_after:
                    break
    finally:
        stop.set()
        for a in actors:
            a.join(timeout=30.0)
        if writer is not None:
            writer.join(timeout=5.0)
        queue.close()
    wall = time.perf_counter() - t_start
    consumed = consumed_blocks * cfg.rollout_steps * E_a
    summary.update(
        consumed_blocks=consumed_blocks,
        wall_s=round(wall, 3),
        consumed_env_steps=consumed,
        consumed_steps_per_s=round(consumed / wall, 1) if wall > 0 else 0.0,
        collected_env_steps=sum(a.steps_collected for a in actors),
        learner_idle_s=round(queue.stats()["learner_idle_s"], 3),
    )
    return np_params, history, summary
