"""TPU-native actor-critic RL framework (JAX/XLA/Flax).

A ground-up rebuild of the capabilities of the reference
`Jiths/Actor-Critic-Algs-on-Tensorflow` (spec: BASELINE.json:5-12; the
reference mount was empty at survey time, see SURVEY.md §0) designed
TPU-first:

- compute path: jit-compiled XLA programs (fused rollout+GAE+update),
- parallelism: `jax.sharding.Mesh` + `shard_map` with ICI collectives
  (replacing the reference's tf.distribute MirroredStrategy/NCCL path),
- off-policy replay: donated HBM ring buffer,
- environments: pure-JAX vmapped envs for throughput, host gymnasium/MuJoCo
  pools for continuous control.

Package layout (SURVEY.md §7.1):
    models/    encoders (MLP/CNN), policy/value heads, distributions
    ops/       pure math: GAE / λ-returns / V-trace (lax.scan + Pallas
               TPU kernels), polyak
    parallel/  device mesh + collectives (dp), sequence-parallel scans
               (sp), multi-host init
    envs/      JaxEnv protocol + pure-JAX envs; HostEnvPool for
               gym/MuJoCo (+pixel wrappers); native C++ engine bindings
    native/    first-party C++ batched env engine (ctypes ABI)
    replay/    HBM-resident ring replay buffer
    algos/     A2C, PPO, DDPG, TD3, SAC, IMPALA/A3C trainers + greedy eval
    utils/     checkpointing (orbax), logging (JSONL/TB), profiling
    telemetry/ unified run telemetry: Chrome-trace phase spans, resource
               sampler, health monitors (train.py --telemetry-dir)
"""

__version__ = "0.1.0"
