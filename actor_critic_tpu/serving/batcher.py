"""GA3C-style micro-batcher for the serving gateway (ISSUE 10
tentpole; arxiv 1611.06256).

Concurrent `POST /v1/act` handler threads enqueue requests into ONE
bounded queue; a single dispatcher thread drains it, groups rows by
policy id, and flushes each group through the policy's bucketed act
program — so N concurrent batch-1 requests cost one accelerator
dispatch at bucket(N), not N dispatches. The `max_wait_us` knob is the
p99/occupancy trade: the dispatcher holds the first request of a flush
at most that long while more rows accumulate.

Threading model (the jaxlint concurrency passes sweep this module):

- client (HTTP handler) threads: `submit` appends under `_cv`, then
  poll/block on the request's own `done` event;
- the single `serve-dispatcher` thread: drains `_pending` under `_cv`,
  dispatches OUTSIDE the lock (an XLA dispatch must not block
  enqueues), completes requests — or, with `max_inflight > 1`
  (ISSUE 17), hands each packed flush to one of `max_inflight`
  `serve-flight-*` worker threads through a 1-deep handoff queue, so
  flush N+1 PACKS while flush N is on device (the continuous-batching
  overlap; the handoff bound keeps at most `max_inflight` dispatches
  in flight plus one packed and waiting);
- metrics threads (sampler/exporter scrapes): read through
  `ServingMetrics.snapshot()` / `health()`, which lock or read
  GIL-atomic snapshots only.

Admission control (ISSUE 17): alongside the queue-capacity reject
(`QueueFull`), a burn-rate-aware shed path — when the queue is
saturated past `shed_queue_frac` of its capacity AND the target
policy's SLO burn rate is at/over `shed_burn_threshold`, `submit`
raises `Overloaded` (503) instead of queueing a request that would
blow its SLO anyway. Only SLO-classed policies shed at admission
(there is no budget to protect otherwise); sheds count on the
`record_shed` counter, rejects on `record_reject` — the two 503
flavors stay distinguishable downstream.

Requests are COPIED at submit (`np.array`) so the batcher owns every
payload: a client reusing its obs buffer after submit() must not be
able to tear a flush (the PR 6 zero-copy class — racesan's
`exercise_batcher` drives the aliasing variant to prove detection).

Import-light by design (numpy/threading/stdlib telemetry): racesan and
the unit tests exercise request/flush/hot-swap interleavings with a
stub engine and never pull jax — the telemetry modules imported here
(histo, session's current(), spans' flow id) are stdlib-only at import
time. Trace/span emission is a no-op unless a TelemetrySession is
installed, and is host-side JSON either way (the perfsan serving
budget holds with tracing on).
"""

from __future__ import annotations

import itertools
import math
import queue as _queue
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

import numpy as np

from actor_critic_tpu.serving.policy_store import PolicyStore
from actor_critic_tpu.telemetry import histo
from actor_critic_tpu.telemetry.session import current as _telemetry_current
from actor_critic_tpu.telemetry.spans import flow_id_of

# jaxlint: hot-module


class QueueFull(RuntimeError):
    """The bounded request queue is at capacity (gateway: HTTP 503)."""


class DispatcherDown(RuntimeError):
    """The dispatcher thread is not running (gateway: HTTP 503)."""


class Overloaded(RuntimeError):
    """Shed at admission (gateway: HTTP 503): the queue is saturated
    and the target policy is already burning its SLO error budget, so
    queueing would only manufacture another violation. Distinct from
    `QueueFull` — the queue still has room; the POLICY has no latency
    budget left (counted on the shed counter, not the reject one)."""


def _percentile(sorted_vals: list, p: float) -> float:
    """Linearly-interpolated percentile of an already-sorted list (0 if
    empty). Nearest-rank was fine at the full 2048-sample window but on
    a tiny cold-start window it degenerates — p99 of 10 samples IS the
    max, and one outlier becomes the reported truth (ISSUE 16
    satellite). Interpolating between the straddling ranks matches
    numpy's default 'linear' method; callers report the window size
    alongside so small-n rows read as what they are."""
    n = len(sorted_vals)
    if n == 0:
        return 0.0
    if n == 1:
        return float(sorted_vals[0])
    rank = (p / 100.0) * (n - 1)
    lo = int(math.floor(rank))
    hi = min(lo + 1, n - 1)
    frac = rank - lo
    return float(sorted_vals[lo]) * (1.0 - frac) + float(sorted_vals[hi]) * frac


# Per-policy SLO burn window: the burn-rate gauge is the violation
# fraction of the last this-many requests over the error budget — long
# enough to smooth single-flush noise, short enough that a regression
# moves the gauge within seconds at serving rates.
SLO_BURN_WINDOW = 512
# Error budget fraction an SLO class tolerates: burn 1.0 = violating at
# exactly budget rate; burn >> 1 = eating future budget (the alerting
# convention from the SRE workbook's multiwindow burn alerts).
SLO_ERROR_BUDGET = 0.01


class ServingMetrics:
    """Lock-guarded serving counters + windowed latency/throughput view
    (the `/metrics` serving gauge)."""

    def __init__(self, latency_window: int = 2048):
        self._lock = threading.Lock()
        self._lat_ms: deque = deque(maxlen=latency_window)
        self._recent: deque = deque(maxlen=latency_window)  # (t_done, rows)
        self._occupancy: deque = deque(maxlen=256)
        self._requests = 0
        self._actions = 0
        self._flushes = 0
        self._rejected = 0
        self._shed = 0
        self._errors = 0
        self._per_policy: dict[str, int] = {}
        # SLO layer (ISSUE 16): per-policy cumulative latency histograms
        # (mergeable across ranks — telemetry/histo.py), declared SLO
        # class, cumulative violation counters, and the burn window of
        # recent over-SLO flags the burn-rate gauge derives from.
        self._hist: dict[str, histo.Histogram] = {}
        self._slo_ms: dict[str, float] = {}
        self._slo_viol: dict[str, int] = {}
        self._slo_window: dict[str, deque] = {}

    def record_flush(
        self,
        policy_id: str,
        rows: int,
        requests: int,
        latencies_ms: list,
        occupancy: float,
        slo_ms: Optional[float] = None,
    ) -> None:
        now = time.monotonic()
        with self._lock:
            self._requests += requests
            self._actions += rows
            self._flushes += 1
            self._per_policy[policy_id] = (
                self._per_policy.get(policy_id, 0) + requests
            )
            self._lat_ms.extend(latencies_ms)
            self._recent.append((now, rows))
            self._occupancy.append(occupancy)
            hist = self._hist.get(policy_id)
            if hist is None:
                hist = self._hist[policy_id] = histo.Histogram()
            if slo_ms is not None:
                self._slo_ms[policy_id] = float(slo_ms)
                window = self._slo_window.get(policy_id)
                if window is None:
                    window = self._slo_window[policy_id] = deque(
                        maxlen=SLO_BURN_WINDOW
                    )
                over = [lat > slo_ms for lat in latencies_ms]
                window.extend(over)
                self._slo_viol[policy_id] = (
                    self._slo_viol.get(policy_id, 0) + sum(over)
                )
        # Histogram has its own lock; observing outside _lock keeps the
        # two critical sections short and never nested.
        hist.observe_many(latencies_ms)

    def record_reject(self) -> None:
        with self._lock:
            self._rejected += 1

    def record_shed(self) -> None:
        """One load-shedding 503 that was NOT a queue-capacity reject
        (request timeout, dispatcher down) — the admission-control leg's
        other shed path, counted separately so a saturated queue and a
        wedged dispatcher don't read as the same failure."""
        with self._lock:
            self._shed += 1

    def record_errors(self, n: int) -> None:
        with self._lock:
            self._errors += n

    def burn_rate(self, policy_id: str) -> Optional[float]:
        """Current SLO burn rate of one policy (violation fraction of
        the burn window over the error budget), or None when the policy
        has no SLO class / no window yet — the admission controller's
        shed signal, read per-submit so it must stay a cheap lock +
        window sum."""
        with self._lock:
            window = self._slo_window.get(policy_id)
            if not window:
                return None
            return (sum(window) / len(window)) / SLO_ERROR_BUDGET

    def snapshot(self) -> dict:
        """Flat numeric dict for the sampler gauge registry (the
        exporter flattens one level; per-policy request counters ride as
        `requests_<policy>` keys, SLO rows as `slo_*_<policy>`)."""
        with self._lock:
            lat = sorted(self._lat_ms)
            recent = list(self._recent)
            occ = list(self._occupancy)
            out = {
                "requests_total": self._requests,
                "actions_total": self._actions,
                "flushes_total": self._flushes,
                "rejected_total": self._rejected,
                "shed_total": self._shed,
                "errors_total": self._errors,
            }
            per_policy = dict(self._per_policy)
            slo_ms = dict(self._slo_ms)
            slo_viol = dict(self._slo_viol)
            slo_frac = {
                pid: (sum(w) / len(w) if w else 0.0)
                for pid, w in self._slo_window.items()
            }
        out["latency_p50_ms"] = round(_percentile(lat, 50), 3)
        out["latency_p99_ms"] = round(_percentile(lat, 99), 3)
        # The percentile window size rides along: a p99 over 7 samples
        # is a cold-start anecdote, not an SLO row, and the consumer
        # can only tell when n is visible (ISSUE 16 satellite).
        out["latency_window_n"] = len(lat)
        if occ:
            out["batch_occupancy"] = round(sum(occ) / len(occ), 4)
        if len(recent) >= 2:
            dt = recent[-1][0] - recent[0][0]
            if dt > 0:
                # Rows completed strictly after the window's first flush
                # (that flush timestamps the window start; counting its
                # rows would overstate the rate).
                out["actions_per_s"] = round(
                    sum(r for _, r in recent[1:]) / dt, 2
                )
        for pid, n in sorted(per_policy.items()):
            out[f"requests_{pid}"] = n
        if slo_viol:
            out["slo_violations_total"] = sum(slo_viol.values())
        burns = {}
        for pid, target in sorted(slo_ms.items()):
            burn = round(slo_frac.get(pid, 0.0) / SLO_ERROR_BUDGET, 3)
            burns[pid] = burn
            out[f"slo_ms_{pid}"] = target
            out[f"slo_violations_{pid}"] = slo_viol.get(pid, 0)
            out[f"slo_burn_{pid}"] = burn
        if burns:
            # Headline burn = the worst policy's: the fleet alert fires
            # on any class eating budget, not on a traffic-weighted mean
            # that lets a small policy burn invisibly.
            out["slo_burn"] = max(burns.values())
        return out

    def histogram_snapshots(self) -> dict[str, dict]:
        """{policy_id: cumulative-histogram snapshot} for the exporter
        (each snapshot carries its policy label and the metric base name
        so the renderer emits one `serving_latency_ms` family with
        per-policy label sets)."""
        with self._lock:
            hists = list(self._hist.items())
        out = {}
        for pid, hist in hists:
            snap = hist.snapshot(labels={"policy": pid})
            snap["metric"] = "latency_ms"
            out[pid] = snap
        return out


class _PendingRequest:
    """One enqueued act request; completed by the dispatcher."""

    __slots__ = ("policy_id", "obs", "rows", "result", "error", "done",
                 "t_enq", "trace_id", "t_enq_pc")

    def __init__(
        self, policy_id: str, obs: np.ndarray,
        trace_id: Optional[str] = None,
    ):
        self.policy_id = policy_id
        self.obs = obs
        self.rows = int(obs.shape[0])
        self.result = None  # (actions ndarray, policy version)
        self.error: Optional[BaseException] = None
        self.done = threading.Event()
        self.t_enq = time.monotonic()
        # Distributed-tracing hop state (ISSUE 16): the request id the
        # gateway minted/propagated, and the perf_counter enqueue stamp
        # the queue-wait span starts from (t_enq above is monotonic —
        # the latency metric's clock — while spans live on the tracer's
        # perf_counter axis).
        self.trace_id = trace_id
        self.t_enq_pc = time.perf_counter()


class MicroBatcher:
    """Bounded request queue + single dispatcher thread (module
    docstring). `start=False` leaves the dispatcher unstarted so a
    cooperative scheduler (racesan) can drive `_flush_once(block=False)`
    as an explicit participant."""

    def __init__(
        self,
        store: PolicyStore,
        max_wait_us: float = 2000.0,
        max_batch_rows: Optional[int] = None,
        queue_limit: int = 256,
        metrics: Optional[ServingMetrics] = None,
        start: bool = True,
        max_inflight: int = 1,
        shed_burn_threshold: Optional[float] = None,
        shed_queue_frac: float = 0.5,
    ):
        if queue_limit < 1:
            raise ValueError(f"queue_limit must be >= 1, got {queue_limit}")
        if max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1, got {max_inflight}"
            )
        if not (0.0 < shed_queue_frac <= 1.0):
            raise ValueError(
                f"shed_queue_frac must be in (0, 1], got {shed_queue_frac}"
            )
        self._store = store
        self.max_wait_s = float(max_wait_us) / 1e6
        self._max_batch_rows = max_batch_rows
        self.queue_limit = int(queue_limit)
        self.metrics = metrics or ServingMetrics()
        # Overlapped dispatch (ISSUE 17): >1 turns on the flight-worker
        # pool; 1 keeps the classic single-thread pack+dispatch loop
        # (and the racesan/sequential-baseline drive paths) unchanged.
        self.max_inflight = int(max_inflight)
        # Admission control: None disables the shed path entirely.
        self.shed_burn_threshold = (
            None if shed_burn_threshold is None else float(shed_burn_threshold)
        )
        self._shed_depth = max(1, int(self.queue_limit * shed_queue_frac))
        self._cv = threading.Condition()
        # Guarded by _cv: the request queue and the closed flag.
        self._pending: deque = deque()
        self._closed = False
        # jaxlint: thread-owned=dispatcher (single writer in the classic
        # mode; in overlap mode flight workers also stamp it — a plain
        # float rebind, GIL-atomic, and health() tolerates one-flush
        # staleness either way)
        self._last_flush_t = time.monotonic()
        # Flush sequence numbers for trace labels: itertools.count is
        # GIL-atomic, so concurrent flight workers each draw a unique
        # seq without a lock (the classic mode draws from the same
        # counter — one writer, same numbers as the old int += 1).
        self._flush_counter = itertools.count(1)
        self._flush_seq = 0  # latest drawn seq, for introspection only
        # Overlap-mode plumbing (built in start() when max_inflight>1):
        # a 1-deep handoff queue and the flight worker pool.
        self._handoff: Optional[_queue.Queue] = None
        self._flights: list[threading.Thread] = []
        self._flight_error: Optional[BaseException] = None
        # Span-emission target override: the owning gateway points this
        # at its _trace_session so dispatcher-side hops land in the same
        # session as the gateway-thread hops even when that session is
        # attached explicitly rather than installed as the global
        # current one. None -> fall back to the global.
        self.session_resolver: Optional[Callable[[], object]] = None
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    def start(self) -> "MicroBatcher":
        if self.max_inflight > 1:
            # 1-deep handoff: the dispatcher can pack ONE flush ahead
            # of the busy flights — exactly "dispatch N+1 packs while N
            # is on device", never an unbounded staging buffer that
            # would swallow the whole request queue into flights.
            self._handoff = _queue.Queue(maxsize=1)
            self._flights = [
                threading.Thread(
                    target=self._flight_run, name=f"serve-flight-{i}",
                    daemon=True,
                )
                for i in range(self.max_inflight)
            ]
            for t in self._flights:
                t.start()
        self._thread = threading.Thread(
            target=self._run, name="serve-dispatcher", daemon=True
        )
        self._thread.start()
        return self

    # -- client side --------------------------------------------------------

    def submit(
        self, obs, policy_id: Optional[str] = None, copy: bool = True,
        trace_id: Optional[str] = None,
    ) -> _PendingRequest:
        """Enqueue one act request of [n, *obs_shape] rows. Raises
        UnknownPolicy (404), ValueError (400: too many rows for the
        policy's largest bucket), QueueFull / DispatcherDown (503).
        `copy=False` exists ONLY for racesan's aliasing exerciser — the
        gateway always copies so the batcher owns the payload.
        `trace_id` threads the gateway's request id through the flush
        so the dispatcher can emit the queue-wait/dispatch hops of that
        request's trace."""
        handle = self._store.get(policy_id)
        obs = np.asarray(obs)
        if copy:
            obs = np.array(obs)
        limit = self._row_limit(handle)
        if obs.shape[0] > limit:
            raise ValueError(
                f"request of {obs.shape[0]} rows exceeds the largest "
                f"serving bucket ({limit}) — split it client-side"
            )
        req = _PendingRequest(handle.policy_id, obs, trace_id=trace_id)
        with self._cv:
            if self._closed or (
                self._thread is not None and not self._thread.is_alive()
            ):
                raise DispatcherDown("serving dispatcher is not running")
            if len(self._pending) >= self.queue_limit:
                self.metrics.record_reject()
                raise QueueFull(
                    f"request queue at capacity ({self.queue_limit})"
                )
            # Shed-vs-queue (module docstring): under saturation, an
            # SLO-classed policy already eating its error budget fails
            # fast instead of queueing another violation-to-be. The
            # _cv -> metrics-lock nesting matches record_reject above.
            if (
                self.shed_burn_threshold is not None
                and getattr(handle, "slo_ms", None) is not None
                and len(self._pending) >= self._shed_depth
            ):
                burn = self.metrics.burn_rate(handle.policy_id)
                if burn is not None and burn >= self.shed_burn_threshold:
                    self.metrics.record_shed()
                    raise Overloaded(
                        f"shedding {handle.policy_id!r}: queue depth "
                        f"{len(self._pending)}/{self.queue_limit} and SLO "
                        f"burn {burn:.2f} >= {self.shed_burn_threshold}"
                    )
            self._pending.append(req)
            self._cv.notify_all()
        return req

    def wait(self, req: _PendingRequest, timeout: Optional[float] = None):
        """Block for a submitted request; returns (actions, version)."""
        if not req.done.wait(timeout):
            raise TimeoutError(
                f"request not served within {timeout}s (queue depth "
                f"{self.queue_depth()})"
            )
        if req.error is not None:
            raise req.error
        return req.result

    # -- dispatcher side ----------------------------------------------------

    def _row_limit(self, handle) -> int:
        # Clamp to the engine's largest bucket: a max_batch_rows above
        # it would let the dispatcher pack a flush no bucket can hold,
        # failing every (individually valid) request in it.
        limit = int(getattr(handle.engine, "max_rows", 64))
        if self._max_batch_rows is not None:
            limit = min(limit, int(self._max_batch_rows))
        return limit

    def _run(self) -> None:
        if self._handoff is None:
            while self._flush_once(block=True):
                pass
            return
        # Overlap mode: THIS thread only packs — the single packer
        # keeps the grouping/ordering invariants of the classic loop —
        # and the flight pool dispatches. put() blocks once the pool is
        # saturated and one flush is staged, which is the backpressure
        # that stops the packer from inhaling the whole request queue.
        while True:
            packed = self._collect_once(block=True)
            if packed is not None:
                self._handoff.put(packed)
            with self._cv:
                if self._closed and not self._pending:
                    break
        for _ in self._flights:
            self._handoff.put(None)  # flight shutdown sentinels

    def _flight_run(self) -> None:
        try:
            while True:
                packed = self._handoff.get()
                if packed is None:
                    return
                self._dispatch_batch(*packed)
        except BaseException as e:  # surfaced through health()
            self._flight_error = e

    def _flush_once(self, block: bool = True) -> bool:
        """Collect one micro-batch and dispatch it inline (the classic
        single-thread loop; racesan drives this entry directly).
        Returns False once the batcher is closed AND drained (the
        dispatcher loop's exit), True otherwise — including empty
        non-blocking polls."""
        packed = self._collect_once(block=block)
        if packed is None:
            with self._cv:
                return not self._closed
        self._dispatch_batch(*packed)
        return True

    def _collect_once(self, block: bool = True):
        """Pack one micro-batch: `(batch, rows, limit, policy_id)`, or
        None when there is nothing to pack. Called only from the
        dispatcher thread (or racesan's scheduler via _flush_once) —
        the single packer is what lets `first` below survive the lock
        gap."""
        with self._cv:
            if block:
                while not self._pending and not self._closed:
                    self._cv.wait(0.05)
            if not self._pending:
                return None
            first = self._pending[0]
            policy_id = first.policy_id
        # Resolve the route OUTSIDE the queue lock: store.get takes the
        # store's lock, and nesting it under _cv would couple the
        # enqueue path to swap()'s critical section (racesan's batcher
        # exerciser deadlocks on exactly that nesting). Only the packer
        # pops, so `first` cannot vanish in between.
        route = self._store.get(policy_id)
        limit = self._row_limit(route)
        # Per-policy window (ISSUE 17 SLO classes): the handle's
        # max_wait_us overrides the batcher's global one.
        wait_us = getattr(route, "max_wait_us", None)
        wait_s = self.max_wait_s if wait_us is None else float(wait_us) / 1e6
        with self._cv:
            if block:
                # GA3C window: hold the flush up to max_wait past the
                # FIRST request's enqueue while more same-policy rows
                # accumulate toward the row budget.
                deadline = first.t_enq + wait_s
                while not self._closed:
                    rows = sum(
                        r.rows for r in self._pending
                        if r.policy_id == policy_id
                    )
                    if rows >= limit:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
            batch: list[_PendingRequest] = []
            rest: deque = deque()
            rows = 0
            while self._pending:
                r = self._pending.popleft()
                if r.policy_id == policy_id and (
                    not batch or rows + r.rows <= limit
                ):
                    batch.append(r)
                    rows += r.rows
                else:
                    rest.append(r)
            self._pending.extend(rest)
        return batch, rows, limit, policy_id

    def _dispatch_batch(
        self, batch: list, rows: int, limit: int, policy_id: str
    ) -> None:
        """Dispatch one packed micro-batch and complete its requests.
        Classic mode runs this on the dispatcher thread; overlap mode
        on a flight worker — everything here is either request-local,
        lock-guarded (metrics), or GIL-atomic (the flush counter, the
        last-flush stamp), and engine.act is safe to run concurrently
        across flights (jit dispatch is thread-safe; the sample-mode
        key counter is itertools.count)."""
        t_disp_pc = time.perf_counter()
        try:
            # Re-resolve the handle at flush time: a hot-swap that
            # landed while this flush waited serves the NEW version;
            # the handle is immutable, so params/version stay
            # consistent through the dispatch either way. Resolution
            # and concatenation stay INSIDE the try — once requests are
            # popped, any failure must complete them with the error,
            # never kill the dispatcher with callers left hanging.
            handle = self._store.get(policy_id)
            obs = (
                batch[0].obs
                if len(batch) == 1
                else np.concatenate([r.obs for r in batch], axis=0)
            )
            actions = handle.engine.act(handle.params, obs)
        except Exception as e:  # noqa: BLE001 — failures go to callers
            for r in batch:
                r.error = e
                r.done.set()
            self.metrics.record_errors(len(batch))
        else:
            now = time.monotonic()
            offset = 0
            latencies = []
            for r in batch:
                r.result = (actions[offset:offset + r.rows], handle.version)
                offset += r.rows
                latencies.append((now - r.t_enq) * 1e3)
                r.done.set()
            occupancy = rows / max(limit, 1)
            self.metrics.record_flush(
                handle.policy_id, rows, len(batch), latencies,
                occupancy=occupancy,
                slo_ms=getattr(handle, "slo_ms", None),
            )
            seq = next(self._flush_counter)
            self._flush_seq = seq
            self._emit_flush_trace(
                batch, handle, rows, occupancy, t_disp_pc,
                time.perf_counter(), seq,
            )
        self._last_flush_t = time.monotonic()

    def _emit_flush_trace(
        self, batch, handle, rows: int, occupancy: float,
        t_disp_pc: float, t_done_pc: float, seq: int,
    ) -> None:
        """Dispatcher-side hops of every traced request in one flush:
        a `serve_dispatch` span over the engine act, one
        `serve_queue_wait` span per request (enqueue stamp -> window
        close), and a flow STEP per trace id binding both to the
        request's gateway-thread track. Host-side JSON emission only —
        nothing here touches the device, so the perfsan serving budget
        (1 dispatch / 2 crossings per act) holds with tracing on. No-op
        without a session (gateway-attached via session_resolver, else
        one global read)."""
        resolver = self.session_resolver
        session = resolver() if resolver is not None \
            else _telemetry_current()
        if session is None:
            return
        tracer = session.tracer
        tracer.complete(
            "serve_dispatch", t_disp_pc, t_done_pc - t_disp_pc,
            {
                "policy": handle.policy_id, "version": handle.version,
                "rows": rows, "requests": len(batch),
                "occupancy": round(occupancy, 4), "flush": seq,
            },
        )
        for r in batch:
            if r.trace_id is None:
                continue
            tracer.complete(
                "serve_queue_wait", r.t_enq_pc,
                max(t_disp_pc - r.t_enq_pc, 0.0),
                {"trace": r.trace_id, "flush": seq,
                 "policy": r.policy_id},
            )
            # Flow step stamped INSIDE the dispatch span so the arrow
            # lands on the flush slice that served this request.
            tracer.flow(
                flow_id_of(r.trace_id), "t",
                ts_us=tracer.pc_to_us(t_disp_pc),
            )

    # -- introspection / lifecycle ------------------------------------------

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._pending)

    def health(self) -> dict:
        """Dispatcher liveness for /healthz: alive flag, queue depth,
        seconds since the last completed flush. In overlap mode a dead
        flight worker also reads as not-alive — a silently shrinking
        pool would otherwise serve at degraded depth forever."""
        alive = self._thread is not None and self._thread.is_alive()
        if self._flight_error is not None:
            alive = False
        with self._cv:
            depth = len(self._pending)
            closed = self._closed
        return {
            "alive": bool(alive and not closed),
            "queue_depth": depth,
            "last_flush_age_s": round(
                time.monotonic() - self._last_flush_t, 3
            ),
            "max_inflight": self.max_inflight,
        }

    def gauge(self) -> dict:
        """The sampler-registry serving gauge: metrics + live queue +
        per-policy latency-histogram snapshots (dict-valued entries the
        exporter recognizes by their `histogram` marker and renders as
        Prometheus `_bucket/_sum/_count`; plain numeric consumers skip
        them as before)."""
        out = self.metrics.snapshot()
        out["queue_depth"] = self.queue_depth()
        for pid, snap in self.metrics.histogram_snapshots().items():
            out[f"latency_ms_hist_{pid}"] = snap
        return out

    def close(self, timeout: float = 5.0) -> None:
        """Stop accepting requests, drain in-flight flushes, fail any
        stragglers (idempotent)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        # Flights exit on the sentinels the dispatcher sends after its
        # own drain — join AFTER the dispatcher so a drain in progress
        # finishes instead of stranding packed flushes.
        for t in self._flights:
            t.join(timeout)
        with self._cv:
            stranded = list(self._pending)
            self._pending.clear()
        for r in stranded:
            r.error = DispatcherDown("batcher closed before dispatch")
            r.done.set()
