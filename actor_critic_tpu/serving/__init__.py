"""Policy-serving gateway (ISSUE 10): the acting path as a production
inference service — GA3C-style micro-batching (arxiv 1611.06256) over
stdlib HTTP, AOT-warm bucket programs, multi-policy hot-swap, serving
metrics on /metrics. `scripts/serve.py` is the CLI; `bench/suite.py
serving_latency` is the SLO bench.

Importing this package registers the serving warmup planner
(`engine.make_act_program`) — `analysis/warmup.py`'s registry lint
covers `serving/` and validates against it.
"""

from actor_critic_tpu.serving.batcher import (
    DispatcherDown,
    MicroBatcher,
    Overloaded,
    QueueFull,
    ServingMetrics,
)
from actor_critic_tpu.serving.fleet_proxy import (
    FleetProxy,
    MailboxPolicySyncer,
)
from actor_critic_tpu.serving.engine import (
    DEFAULT_BUCKETS,
    PolicyEngine,
    abstract_params,
    init_params,
    make_act_program,
)
from actor_critic_tpu.serving.gateway import ServeGateway, standalone_metrics
from actor_critic_tpu.serving.policy_store import (
    PolicyHandle,
    PolicyStore,
    UnknownPolicy,
    export_policy_params,
    restore_policy_params,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "DispatcherDown",
    "FleetProxy",
    "MailboxPolicySyncer",
    "MicroBatcher",
    "Overloaded",
    "PolicyEngine",
    "PolicyHandle",
    "PolicyStore",
    "QueueFull",
    "ServeGateway",
    "ServingMetrics",
    "UnknownPolicy",
    "abstract_params",
    "export_policy_params",
    "init_params",
    "make_act_program",
    "restore_policy_params",
    "standalone_metrics",
]
