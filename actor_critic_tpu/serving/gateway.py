"""Policy-serving HTTP gateway (ISSUE 10 tentpole): micro-batched
act() over stdlib HTTP.

    POST /v1/act        {"obs": [[...], ...] | [...], "policy": "id"?}
                        -> {"actions": [...], "policy": id,
                            "version": n, "latency_ms": x}
                        One obs (shape == obs_shape) is auto-batched and
                        the reply unwrapped. 404 unknown policy, 400 bad
                        shape/JSON, 503 queue full / dispatcher down /
                        timed out.
    POST /v1/swap       {"policy": id, "checkpoint": dir, "step": n?}
                        Hot-swap a resident policy from a params-only
                        checkpoint (policy_store.export_policy_params)
                        without dropping in-flight requests.
    GET  /v1/policies   {"policies": {id: version}, "default": id}
    GET  /metrics       Prometheus text. With a TelemetrySession
                        attached this is the full exporter exposition
                        (the serving gauge rides the sampler registry);
                        standalone it renders the serving gauge alone
                        with the same metric names.
    GET  /healthz       Dispatcher liveness; 503 when the dispatcher
                        thread is dead or visibly stalled (non-empty
                        queue, no flush for `stall_after_s`).

Like the telemetry exporter, the server is a `ThreadingHTTPServer`
daemon bound to 127.0.0.1 by default — remote traffic arrives through
whatever tunnel/LB fronts the host. HTTP/1.1 keep-alive is on: a
closed-loop client reuses its connection, so the measured serving
latency is the gateway's, not per-request TCP setup.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import (
    BaseHTTPRequestHandler,
    HTTPServer,
    ThreadingHTTPServer,
)
from typing import Optional
from urllib.parse import urlparse

import numpy as np

from actor_critic_tpu.serving.batcher import (
    DispatcherDown,
    MicroBatcher,
    Overloaded,
    QueueFull,
)
from actor_critic_tpu.serving.policy_store import PolicyStore, UnknownPolicy
from actor_critic_tpu.telemetry import histo as _histo
from actor_critic_tpu.telemetry import sampler as _sampler
from actor_critic_tpu.telemetry.session import current as _telemetry_current
from actor_critic_tpu.telemetry.spans import flow_id_of
from actor_critic_tpu.utils.numguard import NonFiniteError

# Trace-id header (ISSUE 16): accepted on ingress (a caller/LB that
# already minted one keeps its id end-to-end), minted otherwise, and
# echoed on every /v1/act response.
TRACE_HEADER = "x-trace-id"
_TRACE_ID_MAX = 64  # a hostile header must not bloat every span row


def mint_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def standalone_metrics(batcher: MicroBatcher) -> str:
    """Prometheus text of the serving gauge alone (no session) — same
    metric names the exporter renders when the gauge rides the sampler
    registry, so dashboards survive either deployment. Histogram
    snapshots in the gauge render as `_bucket/_sum/_count` families
    (one family per metric, per-policy label sets)."""
    from actor_critic_tpu.telemetry import exporter as _exp

    rows: list[str] = []
    hist_rows: dict[str, list[str]] = {}
    for key, value in sorted(batcher.gauge().items()):
        if _histo.is_snapshot(value):
            name = _exp._metric_name(
                "serving", value.get("metric") or key
            )
            hist_rows.setdefault(name, []).extend(
                _histo.render_prometheus(name, value)
            )
            continue
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        name = _exp._metric_name("serving", key)
        rows.append(f"# TYPE {name} gauge")
        rows.append(_exp._line(name, value))
    for name in sorted(hist_rows):
        rows.append(f"# TYPE {name} histogram")
        rows.extend(hist_rows[name])
    return "\n".join(rows) + "\n"


class _Handler(BaseHTTPRequestHandler):
    # Keep-alive matters here (module docstring); requires accurate
    # Content-Length on every response, which _respond guarantees.
    protocol_version = "HTTP/1.1"
    # Nagle + delayed-ACK interact with small request/response packets
    # into ~40 ms per round trip on Linux loopback — two orders of
    # magnitude over the actual serving latency. Measured here: p50
    # dropped 40 ms -> ~3 ms with Nagle off both sides (the load
    # generator sets TCP_NODELAY on its sockets too).
    disable_nagle_algorithm = True
    # Fully buffer the response writer so status+headers+body leave as
    # one segment instead of one packet per send_header call.
    wbufsize = -1

    def log_message(self, *args) -> None:
        pass  # serving must not write per-request noise to the run's logs

    def _respond(
        self, status: int, content_type: str, payload: str,
        headers: Optional[dict] = None,
    ) -> None:
        data = payload.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(data)

    def _respond_json(
        self, status: int, body: dict, headers: Optional[dict] = None
    ) -> None:
        self._respond(
            status, "application/json",
            json.dumps(body, default=str) + "\n", headers,
        )

    def _read_body(self) -> Optional[dict]:
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length) if length else b""
            body = json.loads(raw or b"{}")
        except (ValueError, json.JSONDecodeError):
            return None
        return body if isinstance(body, dict) else None

    def do_POST(self) -> None:  # noqa: N802 (http.server contract)
        gw = self.server.gateway  # type: ignore[attr-defined]
        path = urlparse(self.path).path
        t_recv_pc = time.perf_counter()
        try:
            body = self._read_body()
            if body is None:
                self._respond_json(400, {"error": "body must be a JSON object"})
            elif path == "/v1/act":
                # Accept a caller-minted trace id (propagation across
                # an upstream LB/service mesh), mint otherwise; the id
                # is echoed as a header AND in the body so both curl
                # eyeballs and structured clients can follow it into
                # the trace.
                trace_id = (
                    self.headers.get(TRACE_HEADER) or mint_trace_id()
                )[:_TRACE_ID_MAX]
                status, out = gw.handle_act(
                    body, trace_id=trace_id, t_recv_pc=t_recv_pc
                )
                t_resp_pc = time.perf_counter()
                self._respond_json(
                    status, out, headers={TRACE_HEADER: trace_id}
                )
                gw.emit_respond_span(trace_id, t_resp_pc)
            elif path == "/v1/swap":
                self._respond_json(*gw.handle_swap(body))
            else:
                self._respond_json(404, {"error": f"no route {path!r}"})
        except Exception as e:  # the gateway must answer, never die
            try:
                self._respond_json(500, {"error": str(e)[:500]})
            except Exception:
                pass

    def do_GET(self) -> None:  # noqa: N802 (http.server contract)
        gw = self.server.gateway  # type: ignore[attr-defined]
        path = urlparse(self.path).path
        try:
            if path == "/metrics":
                self._respond(
                    200,
                    "text/plain; version=0.0.4; charset=utf-8",
                    gw.render_metrics(),
                )
            elif path == "/healthz":
                self._respond_json(*gw.healthz())
            elif path == "/v1/policies":
                self._respond_json(
                    200,
                    {"policies": gw.store.ids(),
                     "default": gw.store.default_id},
                )
            elif path == "/fleetz" and gw.aggregator is not None:
                self._respond_json(200, gw.aggregator.fleetz())
            elif path == "/fleetz/metrics" and gw.aggregator is not None:
                self._respond(
                    200,
                    "text/plain; version=0.0.4; charset=utf-8",
                    gw.aggregator.merged_metrics(),
                )
            else:
                routes = ["/v1/act (POST)", "/v1/swap (POST)",
                          "/v1/policies", "/metrics", "/healthz"]
                if gw.aggregator is not None:
                    routes += ["/fleetz", "/fleetz/metrics"]
                self._respond_json(
                    404, {"error": f"no route {path!r}", "routes": routes},
                )
        except Exception as e:
            try:
                self._respond_json(500, {"error": str(e)[:500]})
            except Exception:
                pass


class _ThreadedServer(ThreadingHTTPServer):
    # The stdlib default listen backlog of 5 SYN-drops a burst of
    # closed-loop clients into 1s/3s/7s TCP retransmit stalls — the
    # kernel accept queue must hold a saturating fleet instead.
    request_queue_size = 128
    daemon_threads = True


class _SequentialServer(HTTPServer):
    # Without keep-alive every request is a fresh connect, so the
    # backlog sees the WHOLE client fleet every cycle; the stall above
    # would otherwise dominate the baseline's measured latency.
    request_queue_size = 128


class _SequentialHandler(_Handler):
    """Handler for the single-threaded baseline server (`ServeGateway
    (threaded=False)` — the pre-GA3C architecture the SLO bench
    compares against): HTTP/1.0, no keep-alive, because with ONE server
    thread a kept-alive connection would starve every other client.
    Each request pays connect + parse + dispatch + respond end-to-end,
    sequentially — exactly 'sequential batch=1 request handling'."""

    protocol_version = "HTTP/1.0"


class ServeGateway:
    """Owns the HTTP server thread, the micro-batcher, and the serving
    gauge registration for one serving process. `port=0` binds an
    OS-assigned ephemeral port; the ACTUAL port is on `self.port` (and
    in `self.url`) so callers — the load generator, CI — never race for
    a fixed one.

    `threaded=False` swaps the concurrent server + micro-batcher for a
    single-threaded HTTP/1.0 server with a batch=1, zero-wait batcher:
    the sequential baseline the `serving_latency` bench measures the
    micro-batched gateway against."""

    def __init__(
        self,
        store: PolicyStore,
        port: int = 0,
        host: str = "127.0.0.1",
        session=None,
        max_wait_us: float = 2000.0,
        max_batch_rows: Optional[int] = None,
        queue_limit: int = 256,
        request_timeout_s: float = 30.0,
        stall_after_s: float = 5.0,
        batcher: Optional[MicroBatcher] = None,
        threaded: bool = True,
        fleet=None,
        aggregator=None,
        max_inflight: int = 1,
        shed_burn_threshold: Optional[float] = None,
        shed_queue_frac: float = 0.5,
    ):
        self.store = store
        self.session = session
        # Optional telemetry.fleet.FleetAggregator (ISSUE 16): when
        # attached, GET /fleetz serves the merged per-rank fleet view
        # and /fleetz/metrics the label-rolled-up Prometheus merge.
        self.aggregator = aggregator
        # Optional multihost.FleetMonitor (ISSUE 12 satellite): when
        # the gateway serves one host of a --distributed fleet,
        # /healthz surfaces rank/world/per-peer mailbox ages and goes
        # 503 when a peer's last gossip exchange is older than the
        # monitor's bound — the ROADMAP elastic-ops observability half.
        self.fleet = fleet
        self.threaded = bool(threaded)
        self.request_timeout_s = float(request_timeout_s)
        self.stall_after_s = float(stall_after_s)
        owns_batcher = batcher is None
        if not threaded and batcher is None:
            # Sequential baseline: one request per flush, no batching
            # window (waiting could only add latency — there is never a
            # second in-flight request to batch with).
            batcher = MicroBatcher(
                store, max_wait_us=0.0, max_batch_rows=1,
                queue_limit=queue_limit,
            )
        self.batcher = batcher or MicroBatcher(
            store,
            max_wait_us=max_wait_us,
            max_batch_rows=max_batch_rows,
            queue_limit=queue_limit,
            max_inflight=max_inflight,
            shed_burn_threshold=shed_burn_threshold,
            shed_queue_frac=shed_queue_frac,
        )
        # Dispatcher-side hops (serve_dispatch/serve_queue_wait) must
        # land in the SAME session as the gateway-thread hops, including
        # a session attached via `session=` without being installed as
        # the global current one.
        self.batcher.session_resolver = self._trace_session
        self._gauge_key = _sampler.register_gauge(
            "serving", self.batcher.gauge
        )
        try:
            if threaded:
                self._server = _ThreadedServer((host, int(port)), _Handler)
            else:
                self._server = _SequentialServer(
                    (host, int(port)), _SequentialHandler
                )
        except Exception:
            # Bind failure (e.g. EADDRINUSE): close() is unreachable
            # when __init__ raises, so the gauge registration and the
            # dispatcher thread we just created must not leak.
            _sampler.unregister_gauge(self._gauge_key)
            if owns_batcher:
                self.batcher.close(timeout=1.0)
            raise
        self._server.gateway = self  # type: ignore[attr-defined]
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="serve-gateway",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- route handlers (return (status, body); HTTP-free for tests) --------

    def _trace_session(self):
        """Span-emission target: the explicitly-attached session wins,
        else the process-installed one (tests drive either shape)."""
        return self.session if self.session is not None else \
            _telemetry_current()

    def emit_respond_span(self, trace_id: str, t_resp_pc: float) -> None:
        """`serve_respond` hop: response serialization + the socket
        write the handler just finished (called from do_POST AFTER the
        bytes left, so the span covers the real write)."""
        sess = self._trace_session()
        if sess is not None:
            sess.tracer.complete(
                "serve_respond", t_resp_pc,
                time.perf_counter() - t_resp_pc, {"trace": trace_id},
            )

    def handle_act(
        self, body: dict, trace_id: Optional[str] = None,
        t_recv_pc: Optional[float] = None,
    ) -> tuple[int, dict]:
        """One /v1/act request. `trace_id`/`t_recv_pc` come from the
        HTTP handler (header ingress + socket-read stamp); direct
        callers (tests, in-process clients) may omit both — an id is
        minted so the response/trace stay correlated either way."""
        t0_pc = time.perf_counter() if t_recv_pc is None else t_recv_pc
        tid = trace_id or mint_trace_id()
        status, out = self._act_traced(body, tid, t0_pc)
        if isinstance(out, dict):
            out.setdefault("trace", tid)
        sess = self._trace_session()
        if sess is not None:
            # Flow END first (its ts must land inside the serve_request
            # slice about to be emitted), then the request span itself.
            sess.tracer.flow(flow_id_of(tid), "f")
            sess.tracer.complete(
                "serve_request", t0_pc, time.perf_counter() - t0_pc,
                {"trace": tid, "status": status},
            )
        return status, out

    def _act_traced(
        self, body: dict, tid: str, t0_pc: float
    ) -> tuple[int, dict]:
        policy_id = body.get("policy")
        if "obs" not in body:
            return 400, {"error": "missing 'obs'"}
        try:
            handle = self.store.get(policy_id)
        except UnknownPolicy as e:
            return 404, {"error": str(e)}
        spec = getattr(handle.engine, "spec", None)
        try:
            obs = np.asarray(
                body["obs"],
                dtype=np.dtype(spec.obs_dtype) if spec else np.float32,
            )
        except (ValueError, TypeError) as e:
            return 400, {"error": f"bad obs payload: {e}"}
        single = False
        if spec is not None:
            shape = tuple(spec.obs_shape)
            if obs.shape == shape:
                obs, single = obs[None], True
            elif obs.shape[1:] != shape or obs.ndim != len(shape) + 1:
                return 400, {
                    "error": f"obs must be shaped {shape} or "
                    f"[n, *{shape}], got {tuple(obs.shape)}"
                }
        elif obs.ndim == 0:
            return 400, {"error": "obs must be at least rank 1"}
        sess = self._trace_session()
        if sess is not None:
            # Parse hop: socket read + JSON decode + obs validation.
            sess.tracer.complete(
                "serve_parse", t0_pc, time.perf_counter() - t0_pc,
                {"trace": tid},
            )
        t0 = time.monotonic()
        try:
            # Route by the RESOLVED id: the default route could be
            # repointed between validation above and submit, and obs
            # was validated against THIS handle's spec.
            req = self.batcher.submit(obs, handle.policy_id, trace_id=tid)
        except ValueError as e:  # oversized request
            return 400, {"error": str(e)}
        except QueueFull as e:  # submit() already counted the reject
            return 503, {"error": str(e)}
        except Overloaded as e:  # submit() already counted the shed
            return 503, {"error": str(e), "shed": True}
        except DispatcherDown as e:
            self.batcher.metrics.record_shed()
            return 503, {"error": str(e)}
        if sess is not None:
            # Flow START on this thread, stamped inside serve_request:
            # the dispatcher's flow STEP (batcher._emit_flush_trace)
            # links the flush that serves this request back here.
            sess.tracer.flow(flow_id_of(tid), "s")
        try:
            actions, version = self.batcher.wait(
                req, timeout=self.request_timeout_s
            )
        except (DispatcherDown, TimeoutError) as e:
            # A timed-out/dispatcherless request was SHED after
            # admission — distinct from the queue-capacity reject
            # counter (ISSUE 16 SLO layer).
            self.batcher.metrics.record_shed()
            return 503, {"error": str(e)}
        except Exception as e:
            # Dispatch-side flush failure relayed through wait() — the
            # server's fault, never a client 4xx (a ValueError here is
            # NOT the client's oversized request).
            return 500, {"error": str(e)[:500]}
        out = np.asarray(actions)
        if single:
            out = out[0]
        return 200, {
            "actions": out.tolist(),
            "policy": req.policy_id,
            "version": version,
            "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
        }

    def handle_swap(self, body: dict) -> tuple[int, dict]:
        policy_id, ckpt = body.get("policy"), body.get("checkpoint")
        if not policy_id or not ckpt:
            return 400, {"error": "need 'policy' and 'checkpoint'"}
        step = body.get("step")
        try:
            handle = self.store.swap_from_checkpoint(
                str(policy_id), str(ckpt), None if step is None else int(step)
            )
        except UnknownPolicy as e:
            return 404, {"error": str(e)}
        except FileNotFoundError as e:
            return 400, {"error": f"checkpoint restore failed: {e}"}
        except NonFiniteError as e:
            # The ISSUE 14 swap gate refusing a nan/inf checkpoint is a
            # deliberate 4xx (the client named bad input; the previous
            # policy version keeps serving), not a 500 server fault.
            return 422, {"error": str(e)}
        return 200, {"policy": handle.policy_id, "version": handle.version}

    def healthz(self) -> tuple[int, dict]:
        h = self.batcher.health()
        body = {
            "status": "ok",
            "dispatcher": h,
            "policies": self.store.ids(),
            "default": self.store.default_id,
        }
        stalled = (not h["alive"]) or (
            h["queue_depth"] > 0 and h["last_flush_age_s"] > self.stall_after_s
        )
        if self.fleet is not None:
            snap = self.fleet.snapshot()
            body["fleet"] = snap
            if not snap["ok"]:
                # A quiet peer degrades THIS host's health: the LB
                # fronting the fleet sees which members report a
                # partitioned/late mailbox, not just who died.
                stalled = True
        if stalled:
            body["status"] = "stalled"
            return 503, body
        return 200, body

    def render_metrics(self) -> str:
        if self.session is not None:
            from actor_critic_tpu.telemetry.exporter import render_metrics

            return render_metrics(self.session)
        return standalone_metrics(self.batcher)

    def close(self) -> None:
        _sampler.unregister_gauge(self._gauge_key)
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5.0)
        self.batcher.close()
