"""Horizontal serving scale-out (ISSUE 17 leg b): N gateway replicas
behind a tiny fronting proxy, plus mailbox-driven policy propagation.

`FleetProxy` is a stdlib HTTP reverse proxy for a fleet of
`ServeGateway` replicas: each incoming request is relayed to one
healthy replica over a kept-alive upstream connection (per handler
thread, so the measured hop is the relay, not TCP setup) and the
response is streamed back verbatim. Replica selection is least-loaded
(fewest relays currently in flight, the right policy when dispatch
walls vary) or round-robin; a background probe thread polls each
replica's `/healthz` and EVICTS members that fail `unhealthy_after`
consecutive probes — a 200 readmits immediately. Transport failures
mid-relay fail over to another healthy replica; application-level
answers (including a replica's 503 shed/reject) relay as-is — retrying
a shed would defeat the replica's admission control.

The proxy carries NO device state: zero dispatches, zero host<->device
crossings per hop (`perf_budgets.json: serving_proxy_hop` — perfsan
meters the whole relay against an all-zero budget).

`MailboxPolicySyncer` is the replica-to-replica version-update path:
the PR 9 filesystem mailbox transport (`multihost.write_params`'s
write→fsync→rename publish, `read_params`' torn-file tolerance)
carries `(version, params)` snapshots from a publisher — a training
learner, a canary promoter — into every replica's resident
`PolicyStore` via `store.swap` (→ `PolicyEngine.prepare_params` →
`checkpoint.uncommit`, so a propagated update never recompiles and a
replica never restarts to pick one up). Version regressions and torn
files are dropped at the read; fleetsan's replica-kill-mid-swap
schedule drives `poll_once` against real stores to prove a torn policy
is never served.

Import-light (stdlib + numpy via the store); nothing here touches jax
at import time.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import urlparse

from actor_critic_tpu.serving.policy_store import PolicyStore

# Response headers worth relaying upstream->client (everything else is
# hop-by-hop or re-derived by _respond's Content-Length).
_RELAY_HEADERS = ("content-type", "x-trace-id")


class NoHealthyReplica(RuntimeError):
    """Every replica is evicted or failed over (proxy: HTTP 503)."""


class _Replica:
    """One upstream gateway: URL, liveness, and load/relay counters.
    All mutable fields are guarded by the owning proxy's lock except
    the probe bookkeeping (`_probe_failures`), which only the probe
    thread writes."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        parsed = urlparse(self.url)
        if not parsed.hostname or not parsed.port:
            raise ValueError(
                f"replica URL must carry host and port, got {url!r}"
            )
        self.host = parsed.hostname
        self.port = parsed.port
        self.healthy = True
        self.inflight = 0
        self.forwards = 0
        self.transport_errors = 0
        self.evictions = 0
        # jaxlint: thread-owned=health (consecutive probe failures;
        # only the probe thread reads/writes it)
        self._probe_failures = 0

    def stats(self) -> dict:
        return {
            "url": self.url,
            "healthy": self.healthy,
            "inflight": self.inflight,
            "forwards": self.forwards,
            "transport_errors": self.transport_errors,
            "evictions": self.evictions,
        }


class _ProxyHandler(BaseHTTPRequestHandler):
    # Same socket discipline as the gateway handler: keep-alive,
    # Nagle off, fully-buffered writer (gateway.py's rationale).
    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True
    wbufsize = -1

    def log_message(self, *args) -> None:
        pass  # per-request noise stays out of the run's logs

    def _respond(
        self, status: int, payload: bytes,
        content_type: str = "application/json",
        headers: Optional[dict] = None,
    ) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(payload)

    def _relay(self, method: str) -> None:
        proxy = self.server.proxy  # type: ignore[attr-defined]
        path = self.path
        try:
            if method == "GET" and urlparse(path).path == "/proxyz":
                self._respond(
                    200, (json.dumps(proxy.stats()) + "\n").encode()
                )
                return
            body = b""
            length = int(self.headers.get("Content-Length", 0))
            if length:
                body = self.rfile.read(length)
            fwd_headers = {"Content-Type": "application/json"}
            trace = self.headers.get("x-trace-id")
            if trace:
                fwd_headers["x-trace-id"] = trace
            status, payload, headers = proxy.forward(
                method, path, body, fwd_headers
            )
            ctype = headers.pop(
                "content-type", "application/json"
            )
            self._respond(status, payload, content_type=ctype,
                          headers=headers)
        except NoHealthyReplica as e:
            self._respond(
                503, (json.dumps({"error": str(e)}) + "\n").encode()
            )
        except Exception as e:  # the proxy must answer, never die
            try:
                self._respond(
                    502, (json.dumps({"error": str(e)[:500]}) + "\n").encode()
                )
            except Exception:
                pass

    def do_GET(self) -> None:  # noqa: N802 (http.server contract)
        self._relay("GET")

    def do_POST(self) -> None:  # noqa: N802 (http.server contract)
        self._relay("POST")


class _ProxyServer(ThreadingHTTPServer):
    request_queue_size = 128  # gateway.py's backlog rationale
    daemon_threads = True


class FleetProxy:
    """Least-loaded/round-robin fronting proxy over gateway replicas
    (module docstring). `port=0` binds an ephemeral port; the actual
    one is on `self.port`/`self.url`."""

    def __init__(
        self,
        replicas: list[str],
        port: int = 0,
        host: str = "127.0.0.1",
        policy: str = "least_loaded",
        health_interval_s: float = 1.0,
        unhealthy_after: int = 2,
        timeout_s: float = 30.0,
        probe: bool = True,
    ):
        if not replicas:
            raise ValueError("FleetProxy needs at least one replica URL")
        if policy not in ("least_loaded", "round_robin"):
            raise ValueError(
                "policy must be 'least_loaded' or 'round_robin', got "
                f"{policy!r}"
            )
        self.policy = policy
        self.timeout_s = float(timeout_s)
        self.health_interval_s = float(health_interval_s)
        self.unhealthy_after = int(unhealthy_after)
        self._lock = threading.Lock()
        self._replicas = [_Replica(u) for u in replicas]
        self._rr = 0  # round-robin cursor, guarded by _lock
        self._relayed = 0
        self._failovers = 0
        # Per handler-thread upstream connection cache: {url: conn}.
        # Handler threads die with their client connection, taking
        # their upstreams along (ThreadingHTTPServer daemon threads).
        self._local = threading.local()
        self._stop = threading.Event()
        self._server = _ProxyServer((host, int(port)), _ProxyHandler)
        self._server.proxy = self  # type: ignore[attr-defined]
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="fleet-proxy",
            daemon=True,
        )
        self._thread.start()
        self._probe_thread = None
        if probe:
            self._probe_thread = threading.Thread(
                target=self._probe_run, name="fleet-proxy-health",
                daemon=True,
            )
            self._probe_thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- selection / relay ---------------------------------------------------

    def _select(self, tried: set) -> Optional[_Replica]:
        with self._lock:
            candidates = [
                r for r in self._replicas
                if r.healthy and r.url not in tried
            ]
            if not candidates:
                return None
            if self.policy == "least_loaded":
                rep = min(candidates, key=lambda r: r.inflight)
            else:
                rep = candidates[self._rr % len(candidates)]
                self._rr += 1
            rep.inflight += 1
            return rep

    def _conn_for(self, rep: _Replica) -> http.client.HTTPConnection:
        cache = getattr(self._local, "conns", None)
        if cache is None:
            cache = self._local.conns = {}
        conn = cache.get(rep.url)
        if conn is None:
            import socket

            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.timeout_s
            )
            conn.connect()
            # Nagle off on the upstream leg too (gateway rationale).
            conn.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
            cache[rep.url] = conn
        return conn

    def _drop_conn(self, rep: _Replica) -> None:
        cache = getattr(self._local, "conns", None)
        conn = cache.pop(rep.url, None) if cache else None
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def _mark_unhealthy(self, rep: _Replica) -> None:
        with self._lock:
            if rep.healthy:
                rep.healthy = False
                rep.evictions += 1

    def forward(
        self, method: str, path: str, body: bytes, headers: dict
    ) -> tuple[int, bytes, dict]:
        """Relay one request to a healthy replica; `(status, payload,
        relay-headers)`. Transport failures evict the replica and fail
        over (at most once per replica); raises NoHealthyReplica when
        the fleet is exhausted."""
        tried: set = set()
        while True:
            rep = self._select(tried)
            if rep is None:
                raise NoHealthyReplica(
                    f"no healthy replica for {method} {path} "
                    f"(tried {len(tried)}/{len(self._replicas)})"
                )
            tried.add(rep.url)
            try:
                conn = self._conn_for(rep)
                conn.request(method, path, body=body or None,
                             headers=headers)
                resp = conn.getresponse()
                payload = resp.read()  # drain for keep-alive reuse
                out_headers = {
                    k: v for k, v in resp.getheaders()
                    if k.lower() in _RELAY_HEADERS
                }
                if resp.will_close:
                    self._drop_conn(rep)
                with self._lock:
                    rep.forwards += 1
                    self._relayed += 1
                return resp.status, payload, out_headers
            except (OSError, http.client.HTTPException):
                # Transport-level failure: this replica is gone from
                # this hop's point of view — evict now (the probe
                # readmits it when /healthz answers again) and fail
                # over. Application errors never reach this branch.
                self._drop_conn(rep)
                self._mark_unhealthy(rep)
                with self._lock:
                    rep.transport_errors += 1
                    self._failovers += 1
            finally:
                with self._lock:
                    rep.inflight -= 1

    # -- health probing ------------------------------------------------------

    def probe_once(self) -> None:
        """One /healthz sweep over every replica (factored off the
        thread loop so tests can drive eviction/readmission without
        wall-clock waits). A 200 readmits immediately; anything else —
        including a refused connect — counts toward the consecutive-
        failure eviction bound."""
        for rep in self._replicas:
            ok = False
            try:
                conn = http.client.HTTPConnection(
                    rep.host, rep.port,
                    timeout=max(self.health_interval_s, 0.2),
                )
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                resp.read()
                ok = resp.status == 200
                conn.close()
            except Exception:
                ok = False
            if ok:
                rep._probe_failures = 0
                with self._lock:
                    rep.healthy = True
            else:
                rep._probe_failures += 1
                if rep._probe_failures >= self.unhealthy_after:
                    self._mark_unhealthy(rep)

    def _probe_run(self) -> None:
        while not self._stop.is_set():
            self.probe_once()
            self._stop.wait(self.health_interval_s)

    # -- introspection / lifecycle -------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "policy": self.policy,
                "relayed": self._relayed,
                "failovers": self._failovers,
                "healthy": sum(1 for r in self._replicas if r.healthy),
                "replicas": [r.stats() for r in self._replicas],
            }

    def close(self) -> None:
        self._stop.set()
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5.0)
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)


class MailboxPolicySyncer:
    """Replica-side policy-version consumer over the PR 9 mailbox
    transport (module docstring): polls the publisher rank's snapshot
    file and hot-swaps fresh versions into the local store. The swap
    routes through the engine's `prepare_params` (→
    `checkpoint.uncommit`), so a propagated update keeps the
    0-recompile serving contract; `numguard` inside `store.swap`
    refuses a non-finite snapshot with the previous version still
    serving.

    `poll_once` is factored off the thread loop so fleetsan can drive
    the REAL consume/swap logic under a deterministic scheduler (the
    `FileMailboxWriter.poll_once` pattern)."""

    def __init__(
        self,
        store: PolicyStore,
        policy_id: str,
        mailbox_dir: str,
        rank: int = 0,
        template: Any = None,
        poll_s: float = 0.05,
    ):
        from actor_critic_tpu.parallel import multihost

        self._multihost = multihost
        self._store = store
        self.policy_id = str(policy_id)
        self.mailbox_dir = mailbox_dir
        self.rank = int(rank)
        # Restore template: the resident params' tree structure (same
        # architecture by construction — the mailbox carries leaves).
        self._template = (
            template if template is not None
            else store.get(self.policy_id).params
        )
        self._poll_s = float(poll_s)
        # jaxlint: thread-owned=mailbox (newest version this replica
        # consumed; single writer — poll_once runs on the sync thread's
        # loop only, or under fleetsan's scheduler with the thread
        # never started. swaps() mirrors it for observers as a plain
        # GIL-atomic int read)
        self._seen = -1
        # jaxlint: thread-owned=mailbox (same single writer as _seen;
        # observers read the counter GIL-atomically via swaps())
        self._swaps = 0
        self.error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"policy-sync-{self.policy_id}",
            daemon=True,
        )

    def start(self) -> "MailboxPolicySyncer":
        self._thread.start()
        return self

    def poll_once(self) -> bool:
        """ONE poll of the publisher's snapshot: drop absent/torn reads
        (`read_params` tolerance) and version regressions, swap the
        rest into the store. Returns True when a swap landed."""
        out = self._multihost.read_params(
            self.mailbox_dir, self.rank, self._template
        )
        if out is None:
            return False
        version, params = out
        if version <= self._seen:
            return False
        self._store.swap(self.policy_id, params, version=version)
        self._seen = version
        self._swaps += 1
        return True

    @property
    def version(self) -> int:
        """Newest version this replica consumed (-1 before any)."""
        return self._seen

    @property
    def swaps(self) -> int:
        return self._swaps

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                self.poll_once()
                self._stop.wait(self._poll_s)
        except BaseException as e:  # surfaced by the owner's poll
            self.error = e

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
