"""Multi-policy residency and hot-swap for the serving gateway
(ISSUE 10).

Several checkpoints stay resident keyed by policy id; each is held as
an immutable `PolicyHandle` (id, version, prepared params, engine).
Hot-swap follows `PolicyPublisher`'s versioned frozen-snapshot handoff
(ISSUE 7): `swap` builds a NEW handle and atomically replaces the dict
entry — in-flight requests that already resolved the old handle keep
acting on the old params until their flush completes, so a swap never
drops or torn-reads a request. Params are normalized at install time by
the engine (`prepare_params` → `checkpoint.uncommit`'s safe-restore
path), which is what keeps a swap from recompiling (engine.py
docstring).

This module is import-light (numpy/threading only): the race sanitizer
exercises the store + batcher with a stub engine and never pulls jax.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional

from actor_critic_tpu.utils import numguard


class UnknownPolicy(KeyError):
    """Request named a policy id that is not resident."""


@dataclasses.dataclass(frozen=True)
class PolicyHandle:
    """One resident policy version. Immutable: a swap installs a new
    handle; holders of the old one keep a consistent (params, version)
    pair for as long as they need it."""

    policy_id: str
    version: int
    params: Any
    engine: Any  # PolicyEngine (or a duck-typed stub in tests/racesan)
    # SLO class target (milliseconds, ISSUE 16): requests answered
    # slower than this count against the policy's error budget in the
    # serving metrics' burn-rate gauge. None = no SLO class (nothing is
    # counted). Rides the handle so a hot-swap keeps the class and a
    # flush reads it with zero extra lookups.
    slo_ms: Optional[float] = None
    # Per-policy micro-batch window override (microseconds, ISSUE 17):
    # the OTHER half of the SLO class — a latency-tier policy trades
    # occupancy for a shorter hold window, a batch-tier one the
    # reverse. None = the batcher's global max_wait_us. Rides the
    # handle like slo_ms: a hot-swap keeps the class.
    max_wait_us: Optional[float] = None


class PolicyStore:
    """Thread-safe policy_id -> PolicyHandle map with a default route."""

    def __init__(self):
        self._lock = threading.Lock()
        self._handles: dict[str, PolicyHandle] = {}
        self._default: Optional[str] = None

    def register(
        self,
        policy_id: str,
        engine,
        params,
        version: int = 0,
        default: bool = False,
        prepare: bool = True,
        slo_ms: Optional[float] = None,
        max_wait_us: Optional[float] = None,
    ) -> PolicyHandle:
        """Install a new resident policy. The FIRST registration becomes
        the default route unless a later one claims `default=True`.
        `slo_ms` assigns the policy's SLO latency class (serve.py
        --slo-ms; None = unclassed); `max_wait_us` overrides the
        batcher's global micro-batch window for this policy's flushes
        (serve.py --max-wait-us ID=US)."""
        prepared = engine.prepare_params(params) if prepare else params
        handle = PolicyHandle(
            str(policy_id), int(version), prepared, engine,
            slo_ms=None if slo_ms is None else float(slo_ms),
            max_wait_us=None if max_wait_us is None else float(max_wait_us),
        )
        with self._lock:
            if handle.policy_id in self._handles:
                raise ValueError(
                    f"policy {handle.policy_id!r} already registered — "
                    "use swap() to replace its params"
                )
            self._handles[handle.policy_id] = handle
            if default or self._default is None:
                self._default = handle.policy_id
        return handle

    def swap(
        self,
        policy_id: str,
        params,
        version: Optional[int] = None,
        prepare: bool = True,
    ) -> PolicyHandle:
        """Hot-swap a resident policy's params (default: bump its
        version by one). Preparation (device placement + uncommit) runs
        OUTSIDE the lock — a multi-MB restore must not block the
        dispatcher's get() — then the handle is replaced atomically.

        Non-finite params refuse to install (`NonFiniteError`,
        ISSUE 14): a poisoned handle would serve nan actions to every
        client of the gateway from the next dispatch on. The refusal
        leaves the previous handle resident — in-flight and future
        requests keep acting on the last good version. The gate runs
        AFTER the handle resolution so an unknown policy id still
        surfaces as UnknownPolicy (a 404, not a misdirected 422), and
        the cheap lookup precedes the full-tree sweep."""
        old = self.get(policy_id)
        numguard.check_finite(params, "policy swap", name="params")
        prepared = old.engine.prepare_params(params) if prepare else params
        with self._lock:
            # Re-read under the lock: concurrent swaps must version off
            # the latest install, not this caller's possibly-stale read.
            cur = self._handles[old.policy_id]
            new_version = cur.version + 1 if version is None else int(version)
            # The SLO class (target AND window) survives the swap: it
            # classifies the route, not the checkpoint riding it.
            handle = PolicyHandle(
                cur.policy_id, new_version, prepared, cur.engine,
                slo_ms=cur.slo_ms, max_wait_us=cur.max_wait_us,
            )
            self._handles[cur.policy_id] = handle
        return handle

    def swap_from_checkpoint(
        self, policy_id: str, ckpt_dir: str, step: Optional[int] = None
    ) -> PolicyHandle:
        """Restore a params-only checkpoint and hot-swap it in, using
        the CURRENT resident params as the restore template (same
        architecture by construction)."""
        cur = self.get(policy_id)
        params = restore_policy_params(ckpt_dir, cur.params, step)
        return self.swap(policy_id, params)

    def get(self, policy_id: Optional[str] = None) -> PolicyHandle:
        """Resolve a handle (None -> the default route)."""
        with self._lock:
            pid = self._default if policy_id is None else str(policy_id)
            if pid is None or pid not in self._handles:
                raise UnknownPolicy(
                    f"no resident policy {policy_id!r} "
                    f"(resident: {sorted(self._handles)})"
                )
            return self._handles[pid]

    @property
    def default_id(self) -> Optional[str]:
        with self._lock:
            return self._default

    def ids(self) -> dict[str, int]:
        """{policy_id: current version} of every resident policy."""
        with self._lock:
            return {pid: h.version for pid, h in self._handles.items()}

    def __len__(self) -> int:
        with self._lock:
            return len(self._handles)


# -- params-only checkpoint helpers (lazy jax/orbax imports) ----------------


def export_policy_params(ckpt_dir: str, params, step: int = 0) -> None:
    """Write a params-only checkpoint a serving process can load
    (`scripts/serve.py --policy id=DIR`, or the gateway's /v1/swap)."""
    from actor_critic_tpu.utils.checkpoint import Checkpointer

    ckpt = Checkpointer(ckpt_dir, max_to_keep=2)
    ckpt.save(step, params, force=True)
    ckpt.close()


def restore_policy_params(ckpt_dir: str, template, step: Optional[int] = None):
    """Restore a params-only checkpoint into `template`'s structure.
    The Checkpointer already routes through `checkpoint.uncommit` when
    the persistent compile cache is live; `PolicyEngine.prepare_params`
    re-applies it unconditionally at install, so serving gets the
    uncommitted-restore path with or without a cache dir."""
    from actor_critic_tpu.utils.checkpoint import Checkpointer

    ckpt = Checkpointer(ckpt_dir)
    try:
        return ckpt.restore(template, step)
    finally:
        ckpt.close()
