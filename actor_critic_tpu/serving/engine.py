"""Bucketed, AOT-warm act programs for the policy-serving gateway
(ISSUE 10 tentpole).

A serving process dispatches ONE jitted act program per bucket size:
incoming micro-batches are padded to the smallest fitting bucket
(`compile_cache.pad_to_bucket`), so the distinct compiled programs are
bounded by `len(buckets)` no matter how request sizes mix — the same
shape-stabilization discipline the chunked trainer uses (ISSUE 4), now
pointed at traffic. Every bucket is compiled at startup, two ways:

- `register_warmup("engine.make_act_program", serving=True)`: the
  registry planner AOT-compiles each bucket from ABSTRACT params on the
  background warmup thread (persistent-cache prewarm, overlapping
  checkpoint restore), keyed off `WarmupContext.serving_buckets`;
- `PolicyEngine.warm(params)`: one concrete dispatch per bucket through
  the live jit, so the dispatch cache itself is hot before the gateway
  accepts traffic — steady-state serving is 0-recompile even with no
  persistent cache configured.

Param trees installed into the store are normalized by
`prepare_params`: `checkpoint.uncommit` re-places restored leaves as
uncommitted XLA-owned buffers, because committed (orbax-restored)
arrays lower byte-different HLO that would miss both the warmup's cache
entries and the live dispatch cache — a hot-swap would otherwise pay a
recompile on its first flush (the exact PR 4 failure mode, resurfacing
as a p99 spike).
"""

from __future__ import annotations

import itertools
from typing import Any, Optional

import numpy as np

from actor_critic_tpu.utils import compile_cache

# Serving act programs are tiny (one policy forward); a fine-grained
# ladder keeps padding waste low at small occupancy while the top end
# bounds rows-per-flush.
DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)

SUPPORTED_ALGOS = ("ppo", "ddpg", "td3", "sac")


def make_act_program(spec, cfg, algo: str = "ppo", sample: bool = False):
    """The jitted serving act program for one policy architecture:
    `(params, obs) -> actions` (greedy), or `(params, obs, key) ->
    actions` with `sample=True` (PPO only — the off-policy actors are
    deterministic and serve their greedy action). Built from the SAME
    network factories the trainers use, so a served action is bitwise
    the trainer's eval action for the same params/obs."""
    import jax

    if algo == "ppo":
        from actor_critic_tpu.algos import ppo

        if sample:
            net = ppo.make_network(spec, cfg)

            def act(params, obs, key):
                dist, _ = net.apply(params, obs)
                return dist.sample(key)

            return jax.jit(act)
        return jax.jit(ppo.make_greedy_act(spec, cfg))
    if sample:
        raise ValueError(
            f"sample-mode serving is PPO-only ({algo!r} serves a "
            "deterministic actor — its greedy action IS its policy)"
        )
    if algo in ("ddpg", "td3"):
        from actor_critic_tpu.algos import ddpg

        return jax.jit(ddpg.make_greedy_act(spec.action_dim, cfg))
    if algo == "sac":
        from actor_critic_tpu.algos import sac

        return jax.jit(sac.make_greedy_act(spec.action_dim, cfg))
    raise ValueError(
        f"unsupported serving algo {algo!r}; supported: {SUPPORTED_ALGOS}"
    )


def init_params(spec, cfg, algo: str = "ppo", seed: int = 0):
    """Freshly initialized params for this architecture (the tree the
    act program consumes — actor params only for the off-policy algos).
    Serves as the restore TEMPLATE for params-only checkpoints and as
    the --random-init policy for benches/demos."""
    import jax

    key = jax.random.key(seed)
    if algo == "ppo":
        from actor_critic_tpu.algos import ppo

        return ppo.init_host_params(spec, cfg, key)[0]
    if algo in ("ddpg", "td3"):
        from actor_critic_tpu.algos import ddpg

        return ddpg.init_learner(
            tuple(spec.obs_shape), spec.action_dim, cfg, key
        ).actor_params
    if algo == "sac":
        from actor_critic_tpu.algos import sac

        return sac.init_learner(
            tuple(spec.obs_shape), spec.action_dim, cfg, key
        ).actor_params
    raise ValueError(
        f"unsupported serving algo {algo!r}; supported: {SUPPORTED_ALGOS}"
    )


def abstract_params(spec, cfg, algo: str = "ppo"):
    """The act program's param tree as ShapeDtypeStructs (eval_shape —
    no device allocation), for AOT-compiling buckets before any
    checkpoint has been restored."""
    import jax

    return jax.eval_shape(lambda: init_params(spec, cfg, algo, 0))


class PolicyEngine:
    """Bucket-stabilized act dispatch for ONE policy architecture
    (spec + config + algo). Multiple resident policies of the same
    architecture share one engine — and therefore one set of compiled
    programs; hot-swapping params never changes the program.

    `act` may be called concurrently from the micro-batcher's flight
    workers (overlapped dispatch, ISSUE 17): jit dispatch is
    thread-safe, the mirror closes over frozen numpy, and the
    sample-mode flush counter is `itertools.count` (GIL-atomic) — no
    other engine state is written after construction/warmup, which
    happen on the owning thread before any dispatcher starts.

    `backend="auto"` (ISSUE 17) defers the XLA-vs-mirror choice to
    `resolve_backend(params)`: batch-1 dispatch walls of both paths
    are measured against concrete params and the faster one is fixed —
    batch-1 is the decisive shape because it is where the jit
    dispatch envelope dominates an MLP forward (the same trade the
    training loops make per-architecture, now measured per-host).
    """

    def __init__(
        self,
        spec,
        cfg,
        algo: str = "ppo",
        buckets: tuple[int, ...] = DEFAULT_BUCKETS,
        sample: bool = False,
        seed: int = 0,
        dispatch_pad_s: float = 0.0,
        backend: str = "xla",
    ):
        buckets = tuple(sorted({int(b) for b in buckets}))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets!r}")
        if backend not in ("xla", "mirror", "auto"):
            raise ValueError(
                f"backend must be 'xla', 'mirror' or 'auto', got {backend!r}"
            )
        self.spec = spec
        self.cfg = cfg
        self.algo = algo
        self.sample = bool(sample)
        self.buckets = buckets
        if backend == "auto" and self.sample:
            # Mirror serves greedy only, so there is nothing to choose.
            backend = "xla"
        self.backend = backend
        # resolve_backend's measurement record ({'backend', 'xla_ms',
        # 'mirror_ms'}); None until (unless) an auto choice runs.
        self.auto_choice: Optional[dict] = None
        if backend == "mirror":
            # CPU-only serving hosts: the numpy greedy mirror
            # (models/host_actor) beats a batch-1 XLA dispatch on
            # MLP-torso policies — the same trade the training loops
            # make. No compiled programs, so buckets only bound the
            # per-flush row budget (ragged batches dispatch as-is).
            if self.sample:
                raise ValueError(
                    "backend='mirror' serves greedy actions only"
                )
            from actor_critic_tpu.models import host_actor

            self._program = None
            self._mirror = host_actor.greedy_mirror_for(spec, cfg, algo)
        else:
            self._mirror = None
            self._program = make_act_program(
                spec, cfg, algo, sample=self.sample
            )
        # Testbed knob (sleep_pad.py's discipline, pointed at serving):
        # a fixed wall pad per DISPATCH models the host<->accelerator
        # round trip of a real serving deployment — the axon TPU tunnel
        # measures ~26 ms per act() round trip (models/host_actor.py) —
        # which a CPU-local jit dispatch (~0.3 ms) cannot exhibit. The
        # pad is per-dispatch, not per-row: exactly the fixed cost
        # GA3C-style micro-batching amortizes. Default 0 — real serving
        # never pads; `bench/suite.py serving_latency` sets it.
        self.dispatch_pad_s = float(dispatch_pad_s)
        self._seed = int(seed)
        self._base_key = None  # lazy: jax.random.key allocates on-device
        # jaxlint: thread-owned=dispatcher (itertools.count — next() is
        # GIL-atomic, so concurrent flight workers each draw a unique
        # flush key; the counter exists to give each sampled flush a
        # fresh fold_in key)
        self._flush_counter = itertools.count()

    @property
    def max_rows(self) -> int:
        """Largest bucket — the micro-batcher's per-flush row budget."""
        return self.buckets[-1]

    def prepare_params(self, params):
        """Install-normalize a param tree for serving. XLA backend:
        every leaf becomes an uncommitted, XLA-owned device buffer
        (`checkpoint.uncommit`), so a hot-swapped checkpoint lowers the
        same HLO as the warmed programs and steady-state stays
        0-recompile (numpy trees — e.g. a learner's published snapshot
        — are placed on device by the same path). Mirror backend: a
        frozen numpy snapshot (PolicyPublisher's contract) after a
        `supports_mirror` structure check."""
        if self.backend == "auto":
            raise RuntimeError(
                "backend='auto' is unresolved — call "
                "resolve_backend(params) before installing policies"
            )
        if self.backend == "mirror":
            import jax

            from actor_critic_tpu.models import host_actor

            # np.array COPIES (device_get of numpy input is a no-copy
            # alias): freezing must land on our snapshot, never the
            # caller's buffers — PolicyPublisher's contract verbatim.
            np_params = jax.tree.map(np.array, jax.device_get(params))
            if not host_actor.supports_mirror(np_params):
                raise ValueError(
                    "backend='mirror' needs an MLP-torso param tree "
                    "(conv torsos keep the XLA acting path)"
                )
            for leaf in jax.tree.leaves(np_params):
                leaf.flags.writeable = False
            return np_params
        from actor_critic_tpu.utils import checkpoint

        return checkpoint.uncommit(params)

    def resolve_backend(self, params, trials: int = 7) -> str:
        """Fix `backend='auto'` from measured batch-1 dispatch walls:
        time `trials` single-row acts through the compiled XLA bucket-1
        program and through the numpy greedy mirror (min-of-trials —
        the envelope floor, robust to scheduler noise), pick the
        faster, and record both walls on `self.auto_choice`. Params
        whose structure the mirror cannot serve (conv torsos) resolve
        to XLA without measuring. The bucket-1 compile happens OUTSIDE
        the timed region, so the choice compares steady-state
        dispatch, not compilation. Idempotent no-op on an already
        concrete backend; the testbed `dispatch_pad_s` is excluded
        (it pads both paths identically in act())."""
        if self.backend != "auto":
            return self.backend
        import time as _time

        import jax

        from actor_critic_tpu.models import host_actor

        obs = np.zeros(
            (1, *self.spec.obs_shape), np.dtype(self.spec.obs_dtype)
        )
        np_params = jax.tree.map(np.array, jax.device_get(params))
        if not host_actor.supports_mirror(np_params):
            self.backend = "xla"
            self.auto_choice = {"backend": "xla", "reason": "no mirror"}
            return self.backend
        for leaf in jax.tree.leaves(np_params):
            leaf.flags.writeable = False
        mirror = host_actor.greedy_mirror_for(self.spec, self.cfg, self.algo)
        from actor_critic_tpu.utils import checkpoint

        xla_params = checkpoint.uncommit(params)
        padded, _ = compile_cache.pad_to_bucket(obs, self.buckets)

        def xla_once():
            # jaxlint: disable=mask-propagation (timing-only dispatch:
            # the output is discarded after the wall-clock read, so the
            # junk lanes never feed math or a response)
            out = self._program(xla_params, jax.device_put(padded))
            return jax.device_get(out)

        xla_once()  # bucket-1 compile + dispatch-cache warm, untimed

        def wall(fn) -> float:
            best = float("inf")
            for _ in range(max(1, int(trials))):
                t0 = _time.perf_counter()
                fn()
                best = min(best, _time.perf_counter() - t0)
            return best

        xla_ms = wall(xla_once) * 1e3
        mirror_ms = wall(lambda: mirror(np_params, obs)) * 1e3
        if mirror_ms < xla_ms:
            self.backend = "mirror"
            self._mirror = mirror
        else:
            self.backend = "xla"
        self.auto_choice = {
            "backend": self.backend,
            "xla_ms": round(xla_ms, 4),
            "mirror_ms": round(mirror_ms, 4),
        }
        return self.backend

    def _key_for_flush(self):
        import jax

        if self._base_key is None:
            self._base_key = jax.random.key(self._seed)
        return jax.random.fold_in(self._base_key, next(self._flush_counter))

    def act(self, params, obs: np.ndarray) -> np.ndarray:
        """Dispatch one micro-batch: pad [n, *obs_shape] to its bucket,
        run the jitted program, return the first n actions as numpy.

        Both crossings are EXPLICIT (`jax.device_put` in,
        `jax.device_get` out — ISSUE 15 transfer discipline): the act
        path's transfer bytes are a serving-budget line item perfsan
        counts, and the dispatch runs clean under
        `jax.transfer_guard("disallow")` — an implicit coercion
        sneaking into this path fails the sanitizer instead of silently
        re-paying the tunnel."""
        import jax

        obs = np.asarray(obs, dtype=np.dtype(self.spec.obs_dtype))
        n = obs.shape[0]
        if self.backend == "mirror":
            out = self._mirror(params, obs)
        else:
            padded, _ = compile_cache.pad_to_bucket(obs, self.buckets)
            staged = jax.device_put(padded)
            if self.sample:
                out = self._program(
                    params, staged, self._key_for_flush()
                )
            else:
                out = self._program(params, staged)
            out = jax.device_get(out)
        if self.dispatch_pad_s > 0.0:
            import time

            time.sleep(self.dispatch_pad_s)  # modeled tunnel round trip
        return np.asarray(out)[:n]

    def warm(self, params) -> int:
        """Dispatch every bucket once with concrete params so the live
        jit cache is hot before traffic arrives (with the persistent
        cache enabled these re-traces HIT what the registry planner
        AOT-compiled). Returns the number of programs dispatched (0 for
        the mirror backend — nothing compiles)."""
        if self.backend == "mirror":
            return 0
        for b in self.buckets:
            self.act(params, np.zeros((b, *self.spec.obs_shape), np.float32))
        return len(self.buckets)

    def warmup_thunk(self, params_abs=None):
        """AOT-compile thunk over ABSTRACT params for the warmup
        registry: `.lower(...).compile()` of every bucket (plus the
        sample-mode key arg), feeding the persistent cache on the
        background warmup thread."""

        if self.backend == "mirror":
            return lambda: None  # nothing compiles on the mirror path

        def thunk():
            p_abs = params_abs
            if p_abs is None:
                p_abs = abstract_params(self.spec, self.cfg, self.algo)
            for b in self.buckets:
                obs = compile_cache.array_struct(
                    (b, *self.spec.obs_shape), self.spec.obs_dtype
                )
                if self.sample:
                    compile_cache.aot_compile(
                        self._program, p_abs, obs, compile_cache.key_struct()
                    )
                else:
                    compile_cache.aot_compile(self._program, p_abs, obs)

        return thunk


@compile_cache.register_warmup("engine.make_act_program", serving=True)
def _warmup_act_buckets(ctx) -> Optional[Any]:
    """Serving-side planner: AOT-compile every act bucket for the
    gateway's architecture. Runs only for serving contexts
    (ctx.serving_buckets non-empty — plan_warmup's registry gate)."""
    if not ctx.serving_buckets:
        return None
    engine = PolicyEngine(
        ctx.spec,
        ctx.cfg,
        algo=ctx.algo,
        buckets=ctx.serving_buckets,
        sample=ctx.serving_sample,
    )
    return engine.warmup_thunk()
