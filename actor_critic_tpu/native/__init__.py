"""Build + ctypes bindings for the native batched env engine (vecenv.cpp).

The shared library is compiled on first import with the system g++
(`-O3 -march=native`, autovectorized; no pybind11 in this image, so the
boundary is a plain C ABI over NumPy buffers — SURVEY.md §2.2) and cached
next to the source; it is rebuilt whenever vecenv.cpp is newer than the
cached .so. If no compiler is available, `load()` raises ImportError and
callers (envs/native_pool.py) surface a clear message — the gymnasium
backend remains the fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from functools import lru_cache

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "vecenv.cpp")
_LIB = os.path.join(_DIR, "_vecenv.so")

_u8p = ctypes.POINTER(ctypes.c_uint8)
_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_u64p = ctypes.POINTER(ctypes.c_uint64)
_f32p = ctypes.POINTER(ctypes.c_float)
_f64p = ctypes.POINTER(ctypes.c_double)


def _build() -> None:
    # Compile to a per-process temp path, then atomically rename: a
    # concurrent process must never dlopen a half-written .so.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    # -ffp-contract=off: gymnasium's NumPy arithmetic never fuses
    # multiply-adds, so FMA contraction (default under -O3) silently
    # breaks the engine's bit-parity contract — measured as a 1-ulp
    # velocity difference in MountainCar's force*power - cosTerm.
    cmd = [
        "g++", "-O3", "-march=native", "-ffp-contract=off",
        "-shared", "-fPIC", _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _LIB)
    except FileNotFoundError as e:
        raise ImportError(f"native vecenv needs g++ to build: {e}") from e
    except subprocess.CalledProcessError as e:
        raise ImportError(f"native vecenv build failed:\n{e.stderr}") from e
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


@lru_cache(maxsize=1)
def load() -> ctypes.CDLL:
    """The compiled engine, building (or rebuilding on source change)
    first if needed."""
    if (
        not os.path.exists(_LIB)
        or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
    ):
        _build()
    lib = ctypes.CDLL(_LIB)

    lib.cartpole_reset.argtypes = [_f64p, _f32p, ctypes.c_int, _u64p, _i32p]
    lib.cartpole_step.argtypes = [
        _f64p, _i64p, ctypes.c_int, _u64p, _i32p, ctypes.c_int32,
        _f32p, _f32p, _u8p, _u8p, _f32p,
    ]
    lib.pendulum_reset.argtypes = [_f64p, _f32p, ctypes.c_int, _u64p, _i32p]
    lib.pendulum_step.argtypes = [
        _f64p, _f32p, ctypes.c_int, _u64p, _i32p, ctypes.c_int32,
        _f32p, _f32p, _u8p, _u8p, _f32p,
    ]
    lib.mountaincar_reset.argtypes = [_f64p, _f32p, ctypes.c_int, _u64p, _i32p]
    lib.mountaincar_step.argtypes = [
        _f64p, _f32p, ctypes.c_int, _u64p, _i32p, ctypes.c_int32,
        _f32p, _f32p, _u8p, _u8p, _f32p,
    ]
    lib.acrobot_reset.argtypes = [_f64p, _f32p, ctypes.c_int, _u64p, _i32p]
    lib.acrobot_step.argtypes = [
        _f64p, _i64p, ctypes.c_int, _u64p, _i32p, ctypes.c_int32,
        _f32p, _f32p, _u8p, _u8p, _f32p,
    ]
    lib.set_state.argtypes = [_f64p, _f64p, ctypes.c_int, ctypes.c_int]
    for fn in (
        lib.cartpole_reset, lib.cartpole_step,
        lib.pendulum_reset, lib.pendulum_step,
        lib.mountaincar_reset, lib.mountaincar_step,
        lib.acrobot_reset, lib.acrobot_step, lib.set_state,
    ):
        fn.restype = None
    return lib
