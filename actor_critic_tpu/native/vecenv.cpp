// Native batched environment engine (first-party C++ runtime component).
//
// The reference's env stepping bottoms out in native dependency code —
// MuJoCo's C physics and ALE's C++ emulator under gym (SURVEY.md §2.2;
// reference mount empty at survey, §0). This is the build's first-party
// equivalent for the classic-control family: the WHOLE env batch steps
// in one C call (dynamics, reward, termination, SAME_STEP auto-reset),
// removing the Python per-env loop from the host hot path that matters
// on this 1-core host (SURVEY.md §7.2 item 2).
//
// Dynamics are exact gymnasium semantics (CartPole-v1 Euler integration
// and 12deg/2.4m termination with 500-step time limit; Pendulum-v1
// clipped-torque dynamics with 200-step limit) so trainers can swap
// backends without re-tuning. Layout: row-major; state is float64
// (gymnasium's precision) and observations float32.
//
// Built standalone:  g++ -O3 -shared -fPIC vecenv.cpp -o _vecenv.so
// (the Python side builds+caches automatically; see native/__init__.py)

#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

// splitmix64 — tiny, seedable, good enough for env-reset jitter.
inline uint64_t next_u64(uint64_t* s) {
  uint64_t z = (*s += 0x9E3779B97F4A7C15ull);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

inline float uniform(uint64_t* s, float lo, float hi) {
  // 24-bit mantissa uniform in [0,1)
  float u = (float)(next_u64(s) >> 40) * (1.0f / 16777216.0f);
  return lo + u * (hi - lo);
}

constexpr float kPi = 3.14159265358979323846f;

// ---- CartPole-v1 ---------------------------------------------------------
constexpr double kGravity = 9.8;
constexpr double kMassCart = 1.0;
constexpr double kMassPole = 0.1;
constexpr double kTotalMass = kMassCart + kMassPole;
constexpr double kLength = 0.5;  // half pole length
constexpr double kPoleMassLength = kMassPole * kLength;
constexpr double kForceMag = 10.0;
constexpr double kTau = 0.02;
constexpr double kThetaThreshold = 12.0 * 2.0 * 3.14159265358979323846 / 360.0;
constexpr double kXThreshold = 2.4;

inline void cartpole_reset_one(double* st, uint64_t* rng) {
  for (int k = 0; k < 4; ++k) st[k] = uniform(rng, -0.05f, 0.05f);
}

inline void obs_from_state(const double* st, float* obs, int d) {
  for (int k = 0; k < d; ++k) obs[k] = (float)st[k];
}

// ---- Pendulum-v1 ---------------------------------------------------------
constexpr double kPendG = 10.0;
constexpr double kPendM = 1.0;
constexpr double kPendL = 1.0;
constexpr double kPendDt = 0.05;
constexpr double kMaxSpeed = 8.0;
constexpr double kMaxTorque = 2.0;

inline double angle_normalize(double x) {
  const double pi = 3.14159265358979323846;
  double y = std::fmod(x + pi, 2.0 * pi);
  if (y < 0) y += 2.0 * pi;
  return y - pi;
}

inline void pendulum_reset_one(double* st, uint64_t* rng) {
  st[0] = uniform(rng, -kPi, kPi);   // theta
  st[1] = uniform(rng, -1.0f, 1.0f); // theta_dot
}

inline void pendulum_obs(const double* st, float* obs) {
  obs[0] = (float)std::cos(st[0]);
  obs[1] = (float)std::sin(st[0]);
  obs[2] = (float)st[1];
}

}  // namespace

extern "C" {

// state: [n,4] float64 (gymnasium precision); obs out: [n,4] float32
void cartpole_reset(double* state, float* obs, int n, uint64_t* rng,
                    int32_t* steps) {
  for (int i = 0; i < n; ++i) {
    cartpole_reset_one(state + 4 * i, rng);
    obs_from_state(state + 4 * i, obs + 4 * i, 4);
    steps[i] = 0;
  }
}

// One synchronous batch step with SAME_STEP auto-reset: where an episode
// ends, final_obs keeps the ending observation and obs/state hold the
// freshly reset episode (mirrors gymnasium.vector SAME_STEP semantics,
// which envs/host_pool.py already normalizes trainers against).
void cartpole_step(double* state, const int64_t* action, int n, uint64_t* rng,
                   int32_t* steps, int32_t max_steps, float* obs,
                   float* reward, uint8_t* terminated, uint8_t* truncated,
                   float* final_obs) {
  for (int i = 0; i < n; ++i) {
    double* st = state + 4 * i;
    const double force = action[i] == 1 ? kForceMag : -kForceMag;
    const double x = st[0], x_dot = st[1], th = st[2], th_dot = st[3];
    const double costh = std::cos(th);
    const double sinth = std::sin(th);
    const double temp =
        (force + kPoleMassLength * th_dot * th_dot * sinth) / kTotalMass;
    const double thetaacc =
        (kGravity * sinth - costh * temp) /
        (kLength * (4.0 / 3.0 - kMassPole * costh * costh / kTotalMass));
    const double xacc = temp - kPoleMassLength * thetaacc * costh / kTotalMass;
    // Euler, gymnasium order (positions first with OLD velocities),
    // double math to track gymnasium's float64 trajectories.
    st[0] = x + kTau * x_dot;
    st[1] = x_dot + kTau * xacc;
    st[2] = th + kTau * th_dot;
    st[3] = th_dot + kTau * thetaacc;
    steps[i] += 1;

    const bool term = st[0] < -kXThreshold || st[0] > kXThreshold ||
                      st[2] < -kThetaThreshold || st[2] > kThetaThreshold;
    const bool trunc = !term && steps[i] >= max_steps;
    reward[i] = 1.0f;
    terminated[i] = term;
    truncated[i] = trunc;
    obs_from_state(st, final_obs + 4 * i, 4);
    if (term || trunc) {
      cartpole_reset_one(st, rng);
      steps[i] = 0;
    }
    obs_from_state(st, obs + 4 * i, 4);
  }
}

// state: [n,2] float64; obs out: [n,3] float32 (cos, sin, thetadot)
void pendulum_reset(double* state, float* obs, int n, uint64_t* rng,
                    int32_t* steps) {
  for (int i = 0; i < n; ++i) {
    pendulum_reset_one(state + 2 * i, rng);
    pendulum_obs(state + 2 * i, obs + 3 * i);
    steps[i] = 0;
  }
}

void pendulum_step(double* state, const float* action, int n, uint64_t* rng,
                   int32_t* steps, int32_t max_steps, float* obs,
                   float* reward, uint8_t* terminated, uint8_t* truncated,
                   float* final_obs) {
  for (int i = 0; i < n; ++i) {
    double* st = state + 2 * i;
    double u = action[i];
    if (u > kMaxTorque) u = kMaxTorque;
    if (u < -kMaxTorque) u = -kMaxTorque;
    const double th = st[0];
    const double thdot = st[1];
    const double an = angle_normalize(th);
    const double cost = an * an + 0.1 * thdot * thdot + 0.001 * u * u;

    double newthdot =
        thdot + (3.0 * kPendG / (2.0 * kPendL) * std::sin(th) +
                 3.0 / (kPendM * kPendL * kPendL) * u) *
                    kPendDt;
    if (newthdot > kMaxSpeed) newthdot = kMaxSpeed;
    if (newthdot < -kMaxSpeed) newthdot = -kMaxSpeed;
    st[0] = th + newthdot * kPendDt;
    st[1] = newthdot;
    steps[i] += 1;

    const bool trunc = steps[i] >= max_steps;
    reward[i] = -cost;
    terminated[i] = 0;
    truncated[i] = trunc;
    pendulum_obs(st, final_obs + 3 * i);
    if (trunc) {
      pendulum_reset_one(st, rng);
      steps[i] = 0;
    }
    pendulum_obs(st, obs + 3 * i);
  }
}

// Test hook: deterministic state injection (bypasses RNG).
void set_state(double* state, const double* values, int n, int dim) {
  std::memcpy(state, values, (size_t)n * dim * sizeof(double));
}

}  // extern "C"
