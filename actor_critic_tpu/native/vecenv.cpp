// Native batched environment engine (first-party C++ runtime component).
//
// The reference's env stepping bottoms out in native dependency code —
// MuJoCo's C physics and ALE's C++ emulator under gym (SURVEY.md §2.2;
// reference mount empty at survey, §0). This is the build's first-party
// equivalent for the classic-control family: the WHOLE env batch steps
// in one C call (dynamics, reward, termination, SAME_STEP auto-reset),
// removing the Python per-env loop from the host hot path that matters
// on this 1-core host (SURVEY.md §7.2 item 2).
//
// Dynamics are exact gymnasium semantics — CartPole-v1 (Euler
// integration, 12deg/2.4m termination, 500-step limit), Pendulum-v1
// (clipped torque, 200 steps), MountainCarContinuous-v0 (inelastic left
// wall, +100 goal bonus minus raw-action penalty, 999 steps), and
// Acrobot-v1 (book dynamics, one RK4 step of dt=0.2, ±4π/±9π velocity
// clips, 500 steps) — so trainers can swap backends without re-tuning.
// Layout: row-major; state is float64 (gymnasium computes these envs in
// float64 — except MountainCar, whose float32 per-op arithmetic is
// emulated op-for-op in mountaincar_step) and observations float32.
//
// Built standalone:
//   g++ -O3 -ffp-contract=off -shared -fPIC vecenv.cpp -o _vecenv.so
// (-ffp-contract=off is load-bearing: FMA contraction breaks the
// bit-parity contract — see native/__init__.py. The Python side
// builds+caches automatically.)

#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

// splitmix64 — tiny, seedable, good enough for env-reset jitter.
inline uint64_t next_u64(uint64_t* s) {
  uint64_t z = (*s += 0x9E3779B97F4A7C15ull);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

inline float uniform(uint64_t* s, float lo, float hi) {
  // 24-bit mantissa uniform in [0,1)
  float u = (float)(next_u64(s) >> 40) * (1.0f / 16777216.0f);
  return lo + u * (hi - lo);
}

constexpr float kPi = 3.14159265358979323846f;
constexpr double kPiD = 3.14159265358979323846;  // double-precision math

// ---- CartPole-v1 ---------------------------------------------------------
constexpr double kGravity = 9.8;
constexpr double kMassCart = 1.0;
constexpr double kMassPole = 0.1;
constexpr double kTotalMass = kMassCart + kMassPole;
constexpr double kLength = 0.5;  // half pole length
constexpr double kPoleMassLength = kMassPole * kLength;
constexpr double kForceMag = 10.0;
constexpr double kTau = 0.02;
constexpr double kThetaThreshold = 12.0 * 2.0 * 3.14159265358979323846 / 360.0;
constexpr double kXThreshold = 2.4;

inline void cartpole_reset_one(double* st, uint64_t* rng) {
  for (int k = 0; k < 4; ++k) st[k] = uniform(rng, -0.05f, 0.05f);
}

inline void obs_from_state(const double* st, float* obs, int d) {
  for (int k = 0; k < d; ++k) obs[k] = (float)st[k];
}

// ---- Pendulum-v1 ---------------------------------------------------------
constexpr double kPendG = 10.0;
constexpr double kPendM = 1.0;
constexpr double kPendL = 1.0;
constexpr double kPendDt = 0.05;
constexpr double kMaxSpeed = 8.0;
constexpr double kMaxTorque = 2.0;

inline double angle_normalize(double x) {
  const double pi = 3.14159265358979323846;
  double y = std::fmod(x + pi, 2.0 * pi);
  if (y < 0) y += 2.0 * pi;
  return y - pi;
}

inline void pendulum_reset_one(double* st, uint64_t* rng) {
  st[0] = uniform(rng, -kPi, kPi);   // theta
  st[1] = uniform(rng, -1.0f, 1.0f); // theta_dot
}

inline void pendulum_obs(const double* st, float* obs) {
  obs[0] = (float)std::cos(st[0]);
  obs[1] = (float)std::sin(st[0]);
  obs[2] = (float)st[1];
}

// ---- MountainCarContinuous-v0 -------------------------------------------
constexpr double kMcMinPos = -1.2;
constexpr double kMcMaxPos = 0.6;
constexpr double kMcMaxSpeed = 0.07;
constexpr double kMcGoalPos = 0.45;
constexpr double kMcGoalVel = 0.0;
constexpr double kMcPower = 0.0015;

inline void mountaincar_reset_one(double* st, uint64_t* rng) {
  st[0] = uniform(rng, -0.6f, -0.4f);  // position
  st[1] = 0.0;                         // velocity
}

// ---- Acrobot-v1 ----------------------------------------------------------
// Double-pendulum swing-up, gymnasium's "book" dynamics (Sutton & Barto),
// RK4-integrated with one dt=0.2 step, velocities clipped to ±4π/±9π.
constexpr double kAcDt = 0.2;
constexpr double kAcM1 = 1.0, kAcM2 = 1.0;   // link masses
constexpr double kAcL1 = 1.0;                // link 1 length
constexpr double kAcLc1 = 0.5, kAcLc2 = 0.5; // link COM positions
constexpr double kAcI1 = 1.0, kAcI2 = 1.0;   // moments of inertia
constexpr double kAcG = 9.8;
constexpr double kAcMaxVel1 = 4.0 * kPiD;
constexpr double kAcMaxVel2 = 9.0 * kPiD;

inline void acrobot_reset_one(double* st, uint64_t* rng) {
  for (int k = 0; k < 4; ++k) st[k] = uniform(rng, -0.1f, 0.1f);
}

inline void acrobot_obs(const double* st, float* obs) {
  obs[0] = (float)std::cos(st[0]);
  obs[1] = (float)std::sin(st[0]);
  obs[2] = (float)std::cos(st[1]);
  obs[3] = (float)std::sin(st[1]);
  obs[4] = (float)st[2];
  obs[5] = (float)st[3];
}

// ds/dt of the torque-augmented state (gymnasium Acrobot._dsdt, book eqs).
inline void acrobot_dsdt(const double* s, double torque, double* ds) {
  const double th1 = s[0], th2 = s[1], dth1 = s[2], dth2 = s[3];
  const double d1 =
      kAcM1 * kAcLc1 * kAcLc1 +
      kAcM2 * (kAcL1 * kAcL1 + kAcLc2 * kAcLc2 +
               2.0 * kAcL1 * kAcLc2 * std::cos(th2)) +
      kAcI1 + kAcI2;
  const double d2 =
      kAcM2 * (kAcLc2 * kAcLc2 + kAcL1 * kAcLc2 * std::cos(th2)) + kAcI2;
  const double phi2 =
      kAcM2 * kAcLc2 * kAcG * std::cos(th1 + th2 - kPiD / 2.0);
  const double phi1 =
      -kAcM2 * kAcL1 * kAcLc2 * dth2 * dth2 * std::sin(th2) -
      2.0 * kAcM2 * kAcL1 * kAcLc2 * dth2 * dth1 * std::sin(th2) +
      (kAcM1 * kAcLc1 + kAcM2 * kAcL1) * kAcG * std::cos(th1 - kPiD / 2.0) +
      phi2;
  const double ddth2 =
      (torque + d2 / d1 * phi1 -
       kAcM2 * kAcL1 * kAcLc2 * dth1 * dth1 * std::sin(th2) - phi2) /
      (kAcM2 * kAcLc2 * kAcLc2 + kAcI2 - d2 * d2 / d1);
  const double ddth1 = -(d2 * ddth2 + phi1) / d1;
  ds[0] = dth1;
  ds[1] = dth2;
  ds[2] = ddth1;
  ds[3] = ddth2;
}

inline double wrap_pi(double x) {
  // gymnasium wrap(x, -π, π)
  const double diff = 2.0 * kPiD;
  while (x > kPiD) x -= diff;
  while (x < -kPiD) x += diff;
  return x;
}

// One RK4 step of size kAcDt on the 4-state with constant torque
// (gymnasium's rk4 over t=[0, 0.2]; the augmented torque slot has zero
// derivative, so it is simply threaded through).
inline void acrobot_rk4(double* st, double torque) {
  double k1[4], k2[4], k3[4], k4[4], tmp[4];
  acrobot_dsdt(st, torque, k1);
  for (int k = 0; k < 4; ++k) tmp[k] = st[k] + 0.5 * kAcDt * k1[k];
  acrobot_dsdt(tmp, torque, k2);
  for (int k = 0; k < 4; ++k) tmp[k] = st[k] + 0.5 * kAcDt * k2[k];
  acrobot_dsdt(tmp, torque, k3);
  for (int k = 0; k < 4; ++k) tmp[k] = st[k] + kAcDt * k3[k];
  acrobot_dsdt(tmp, torque, k4);
  for (int k = 0; k < 4; ++k)
    st[k] += kAcDt / 6.0 * (k1[k] + 2.0 * k2[k] + 2.0 * k3[k] + k4[k]);
}

}  // namespace

extern "C" {

// state: [n,4] float64 (gymnasium precision); obs out: [n,4] float32
void cartpole_reset(double* state, float* obs, int n, uint64_t* rng,
                    int32_t* steps) {
  for (int i = 0; i < n; ++i) {
    cartpole_reset_one(state + 4 * i, rng);
    obs_from_state(state + 4 * i, obs + 4 * i, 4);
    steps[i] = 0;
  }
}

// One synchronous batch step with SAME_STEP auto-reset: where an episode
// ends, final_obs keeps the ending observation and obs/state hold the
// freshly reset episode (mirrors gymnasium.vector SAME_STEP semantics,
// which envs/host_pool.py already normalizes trainers against).
void cartpole_step(double* state, const int64_t* action, int n, uint64_t* rng,
                   int32_t* steps, int32_t max_steps, float* obs,
                   float* reward, uint8_t* terminated, uint8_t* truncated,
                   float* final_obs) {
  for (int i = 0; i < n; ++i) {
    double* st = state + 4 * i;
    const double force = action[i] == 1 ? kForceMag : -kForceMag;
    const double x = st[0], x_dot = st[1], th = st[2], th_dot = st[3];
    const double costh = std::cos(th);
    const double sinth = std::sin(th);
    const double temp =
        (force + kPoleMassLength * th_dot * th_dot * sinth) / kTotalMass;
    const double thetaacc =
        (kGravity * sinth - costh * temp) /
        (kLength * (4.0 / 3.0 - kMassPole * costh * costh / kTotalMass));
    const double xacc = temp - kPoleMassLength * thetaacc * costh / kTotalMass;
    // Euler, gymnasium order (positions first with OLD velocities),
    // double math to track gymnasium's float64 trajectories.
    st[0] = x + kTau * x_dot;
    st[1] = x_dot + kTau * xacc;
    st[2] = th + kTau * th_dot;
    st[3] = th_dot + kTau * thetaacc;
    steps[i] += 1;

    const bool term = st[0] < -kXThreshold || st[0] > kXThreshold ||
                      st[2] < -kThetaThreshold || st[2] > kThetaThreshold;
    const bool trunc = !term && steps[i] >= max_steps;
    reward[i] = 1.0f;
    terminated[i] = term;
    truncated[i] = trunc;
    obs_from_state(st, final_obs + 4 * i, 4);
    if (term || trunc) {
      cartpole_reset_one(st, rng);
      steps[i] = 0;
    }
    obs_from_state(st, obs + 4 * i, 4);
  }
}

// state: [n,2] float64; obs out: [n,3] float32 (cos, sin, thetadot)
void pendulum_reset(double* state, float* obs, int n, uint64_t* rng,
                    int32_t* steps) {
  for (int i = 0; i < n; ++i) {
    pendulum_reset_one(state + 2 * i, rng);
    pendulum_obs(state + 2 * i, obs + 3 * i);
    steps[i] = 0;
  }
}

void pendulum_step(double* state, const float* action, int n, uint64_t* rng,
                   int32_t* steps, int32_t max_steps, float* obs,
                   float* reward, uint8_t* terminated, uint8_t* truncated,
                   float* final_obs) {
  for (int i = 0; i < n; ++i) {
    double* st = state + 2 * i;
    double u = action[i];
    if (u > kMaxTorque) u = kMaxTorque;
    if (u < -kMaxTorque) u = -kMaxTorque;
    const double th = st[0];
    const double thdot = st[1];
    const double an = angle_normalize(th);
    const double cost = an * an + 0.1 * thdot * thdot + 0.001 * u * u;

    double newthdot =
        thdot + (3.0 * kPendG / (2.0 * kPendL) * std::sin(th) +
                 3.0 / (kPendM * kPendL * kPendL) * u) *
                    kPendDt;
    if (newthdot > kMaxSpeed) newthdot = kMaxSpeed;
    if (newthdot < -kMaxSpeed) newthdot = -kMaxSpeed;
    st[0] = th + newthdot * kPendDt;
    st[1] = newthdot;
    steps[i] += 1;

    const bool trunc = steps[i] >= max_steps;
    reward[i] = -cost;
    terminated[i] = 0;
    truncated[i] = trunc;
    pendulum_obs(st, final_obs + 3 * i);
    if (trunc) {
      pendulum_reset_one(st, rng);
      steps[i] = 0;
    }
    pendulum_obs(st, obs + 3 * i);
  }
}

// state: [n,2] float64 (position, velocity); obs out: [n,2] float32
void mountaincar_reset(double* state, float* obs, int n, uint64_t* rng,
                       int32_t* steps) {
  for (int i = 0; i < n; ++i) {
    mountaincar_reset_one(state + 2 * i, rng);
    obs_from_state(state + 2 * i, obs + 2 * i, 2);
    steps[i] = 0;
  }
}

void mountaincar_step(double* state, const float* action, int n,
                      uint64_t* rng, int32_t* steps, int32_t max_steps,
                      float* obs, float* reward, uint8_t* terminated,
                      uint8_t* truncated, float* final_obs) {
  // Bit-exact emulation of gymnasium's float32 MountainCar arithmetic.
  // Unlike the other classic-control envs, gymnasium keeps this state
  // in float32 and (via NumPy 2 weak promotion) performs EACH velocity/
  // position update op in float32, while clamps assign python float64
  // constants and comparisons run in float64 — rounding only at the end
  // of the step is NOT equivalent (the wall/clip discontinuities
  // amplify a 1-ulp difference chaotically; measured ~0.55 obs
  // divergence within one 999-step episode). The mixed float/double
  // locals below mirror that op-for-op.
  for (int i = 0; i < n; ++i) {
    double* st = state + 2 * i;
    const float raw = action[i];
    const float pos_f = (float)st[0];
    const float vel_f = (float)st[1];
    // velocity += force*power - 0.0025*cos(3*position). The cos term is
    // python-float (double) math on the float32 product 3*position.
    // When the force clamps, python's min/max returns the PYTHON float
    // bound, so force*power - cosTerm is one double expression rounded
    // ONCE on the float32 +=; unclamped, force stays np.float32 and the
    // product/subtraction are separate float32 ops. The branches differ
    // by 1 ulp often enough (~each few hundred clamped steps) that
    // collapsing them breaks long-horizon parity.
    const double cos_term = 0.0025 * std::cos((double)(3.0f * pos_f));
    float delta_f;
    if (raw > 1.0f) {
      delta_f = (float)(1.0 * kMcPower - cos_term);
    } else if (raw < -1.0f) {
      delta_f = (float)(-1.0 * kMcPower - cos_term);
    } else {
      delta_f = (raw * (float)kMcPower) - (float)cos_term;
    }
    float vel1_f = vel_f + delta_f;
    // Clamps assign the python float64 constant; comparisons in double.
    double vel_d = (double)vel1_f;
    if (vel_d > kMcMaxSpeed) vel_d = kMcMaxSpeed;
    if (vel_d < -kMcMaxSpeed) vel_d = -kMcMaxSpeed;
    // position += velocity is a float32 op regardless of which branch
    // velocity took (weak promotion casts a python float back down).
    const float pos1_f = pos_f + (float)vel_d;
    double pos_d = (double)pos1_f;
    if (pos_d > kMcMaxPos) pos_d = kMcMaxPos;
    if (pos_d < kMcMinPos) pos_d = kMcMinPos;
    // `position == min_position` can only be true via the clamp branch
    // (-1.2 is not float32-representable), exactly as in gymnasium.
    if (pos_d == kMcMinPos && vel_d < 0.0) vel_d = 0.0;
    st[0] = (double)(float)pos_d;  // np.array([...], dtype=np.float32)
    st[1] = (double)(float)vel_d;
    steps[i] += 1;
    const double pos = pos_d;
    const double vel = vel_d;

    const bool term = pos >= kMcGoalPos && vel >= kMcGoalVel;
    const bool trunc = !term && steps[i] >= max_steps;
    // gymnasium penalizes the RAW action (not the clipped force) and
    // pays +100 on reaching the goal.
    reward[i] =
        (float)((term ? 100.0 : 0.0) - 0.1 * ((double)raw * (double)raw));
    terminated[i] = term;
    truncated[i] = trunc;
    obs_from_state(st, final_obs + 2 * i, 2);
    if (term || trunc) {
      mountaincar_reset_one(st, rng);
      steps[i] = 0;
    }
    obs_from_state(st, obs + 2 * i, 2);
  }
}

// state: [n,4] float64 (θ1, θ2, dθ1, dθ2); obs out: [n,6] float32
void acrobot_reset(double* state, float* obs, int n, uint64_t* rng,
                   int32_t* steps) {
  for (int i = 0; i < n; ++i) {
    acrobot_reset_one(state + 4 * i, rng);
    acrobot_obs(state + 4 * i, obs + 6 * i);
    steps[i] = 0;
  }
}

void acrobot_step(double* state, const int64_t* action, int n, uint64_t* rng,
                  int32_t* steps, int32_t max_steps, float* obs,
                  float* reward, uint8_t* terminated, uint8_t* truncated,
                  float* final_obs) {
  for (int i = 0; i < n; ++i) {
    double* st = state + 4 * i;
    const double torque = (double)(action[i] - 1);  // {0,1,2} → {-1,0,+1}
    acrobot_rk4(st, torque);
    st[0] = wrap_pi(st[0]);
    st[1] = wrap_pi(st[1]);
    if (st[2] > kAcMaxVel1) st[2] = kAcMaxVel1;
    if (st[2] < -kAcMaxVel1) st[2] = -kAcMaxVel1;
    if (st[3] > kAcMaxVel2) st[3] = kAcMaxVel2;
    if (st[3] < -kAcMaxVel2) st[3] = -kAcMaxVel2;
    steps[i] += 1;

    const bool term = -std::cos(st[0]) - std::cos(st[1] + st[0]) > 1.0;
    const bool trunc = !term && steps[i] >= max_steps;
    reward[i] = term ? 0.0f : -1.0f;
    terminated[i] = term;
    truncated[i] = trunc;
    acrobot_obs(st, final_obs + 6 * i);
    if (term || trunc) {
      acrobot_reset_one(st, rng);
      steps[i] = 0;
    }
    acrobot_obs(st, obs + 6 * i);
  }
}

// Test hook: deterministic state injection (bypasses RNG).
void set_state(double* state, const double* values, int n, int dim) {
  std::memcpy(state, values, (size_t)n * dim * sizeof(double));
}

}  // extern "C"
