"""Config system: named presets + `key=value` overrides (SURVEY.md §5.6).

The reference genre configures each algorithm through per-script argparse
flags (reference mount empty at survey, SURVEY.md §0). The TPU build
replaces that with frozen dataclass configs (each algorithm module owns
its own) plus this registry of named presets — one per reference config
in BASELINE.json:7-11 — and a typed `--set key=value` override parser, so
one `train.py` CLI drives every algorithm.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Optional, Union

from actor_critic_tpu.algos import a2c, ddpg, impala, ppo, sac


@dataclasses.dataclass(frozen=True)
class Preset:
    """A runnable training setup: algorithm + environment + config."""

    algo: str        # a2c | ppo | ddpg | td3 | sac | impala | a3c
    env: str         # "jax:<name>" (pure-JAX, fused) or "host:<gym id>"
    config: Any      # the algorithm's frozen config dataclass
    iterations: int  # default --iterations
    description: str
    # Keyword arguments for the ENV constructor (the jax:* maker, or
    # gym.make for host pools) — the difficulty/shape knobs that define
    # a runnable result, e.g. pong's opp_skill/frame_skip. CLI
    # `--env-set key=value` merges over these.
    env_kwargs: dict = dataclasses.field(default_factory=dict)


PRESETS: dict[str, Preset] = {
    # BASELINE.json:7 — the ≥1M env-steps/sec north-star config.
    # lr+entropy annealed to 0 over the run: the flat-coefficient config
    # oscillated at eval ≤429 and never converged (round-2 verdict #1).
    # Round 4 closed the last 10 points to the 475 solve bar in two
    # moves (scripts/a2c_anneal_sweep.py): double the rollout to T=64
    # (halves GAE truncation bias; solved 3/4 seeds at E=256 but still
    # ceilinged ~465 at E=4096), then scale lr with the 16× batch —
    # lr=3e-3 reaches greedy eval 491/500 at iters 300/400 at THIS
    # shape (E=4096, CPU calibration; 1.5e-3 and 2e-3 underfit at
    # 418-458). Certification (results/a2c_cartpole_solve_*, threshold
    # 475 on 2 consecutive independent evals): seeds 0/1 solve at iters
    # 300/325 (finals 491/500); seed 2 oscillates at this lr — a
    # measured A2C ceiling (no trust region), not a tuning gap: the
    # sweep also rejected normalize_adv (collapse), lr 2.5e-3 (noisier)
    # and max_grad_norm 0.25 (still 2/3); PPO (ppo_cartpole) is the
    # 3/3 solver. tests/test_a2c.py guards a reduced E=256 shape.
    "a2c_cartpole": Preset(
        algo="a2c",
        env="jax:cartpole",
        config=a2c.A2CConfig(
            num_envs=4096, rollout_steps=64, lr=3e-3,
            anneal_iters=400, lr_final=0.0,
            entropy_coef=0.01, entropy_coef_final=0.0,
        ),
        iterations=400,
        description="A2C on pure-JAX CartPole-v1, fully fused (BASELINE.json:7)",
    ),
    # BASELINE.json:7 again, tuned to SOLVE (greedy eval ≥475) rather than
    # maximize raw throughput: PPO's clipped updates + lr/entropy annealing
    # and long (T=128) rollouts converge where flat-coefficient A2C
    # oscillates (round-2 verdict #1). clip-ε is NOT annealed here.
    "ppo_cartpole": Preset(
        algo="ppo",
        env="jax:cartpole",
        config=ppo.PPOConfig(
            num_envs=256, rollout_steps=128, epochs=4, num_minibatches=8,
            lr=2.5e-4, entropy_coef=0.01, gae_lambda=0.95, gamma=0.99,
            anneal_iters=100, lr_final=0.0, entropy_coef_final=0.0,
        ),
        iterations=100,
        description="PPO on pure-JAX CartPole-v1, fused, solve-tuned (BASELINE.json:7)",
    ),
    # BASELINE.json:8 — continuous control via the host-env pool.
    "ppo_halfcheetah": Preset(
        algo="ppo",
        env="host:HalfCheetah-v5",
        config=ppo.PPOConfig(
            num_envs=8, rollout_steps=256, epochs=10, num_minibatches=32,
            entropy_coef=0.0, lr=3e-4,
            anneal_iters=1000, lr_final=0.0,
        ),
        iterations=1000,
        description="PPO-clip on MuJoCo HalfCheetah-v5 (BASELINE.json:8)",
    ),
    # BASELINE.json:9 — off-policy with the HBM replay ring.
    # Default budgets are the real 1M-env-step runs (64 steps/iter × 16k
    # iterations; 1 update per env step) with a 10k-step uniform-random
    # warmup — the standard TD3/SAC MuJoCo regime.
    "ddpg_walker2d": Preset(
        algo="ddpg",
        env="host:Walker2d-v5",
        config=ddpg.DDPGConfig(
            num_envs=1, steps_per_iter=64, updates_per_iter=64,
            warmup_steps=10_000,
        ),
        iterations=16_000,
        description="DDPG on MuJoCo Walker2d-v5 (BASELINE.json:9)",
    ),
    "td3_walker2d": Preset(
        algo="td3",
        env="host:Walker2d-v5",
        config=ddpg.td3_config(
            num_envs=1, steps_per_iter=64, updates_per_iter=64,
            warmup_steps=10_000,
        ),
        iterations=16_000,
        description="TD3 on MuJoCo Walker2d-v5 (BASELINE.json:9)",
    ),
    # BASELINE.json:10.
    "sac_humanoid": Preset(
        algo="sac",
        env="host:Humanoid-v5",
        config=sac.SACConfig(
            num_envs=1, steps_per_iter=64, updates_per_iter=64,
            warmup_steps=10_000,
        ),
        iterations=16_000,
        description="SAC on MuJoCo Humanoid-v5 (BASELINE.json:10)",
    ),
    # BASELINE.json:11 — ale-py is unavailable; the JAX-native Pong-like
    # pixel env stands in (SURVEY.md §2.2, envs/pong.py docstring).
    "impala_pong": Preset(
        algo="impala",
        env="jax:pong",
        config=impala.ImpalaConfig(
            num_envs=64, rollout_steps=20, actor_refresh_every=4
        ),
        iterations=2000,
        description="IMPALA/V-trace on JAX Pong-like pixels (BASELINE.json:11)",
    ),
    # The config-5 setup that PROVABLY LEARNS (round 3, BASELINE.md:
    # eval −3.78 → +2.41 over 51.2M decisions): same learner as
    # impala_pong, env at the learnable difficulty — opponent tracking
    # at half speed (placed shots score within ~100 steps instead of
    # hundreds), ALE-style frame_skip=4 (ball velocity visible in the
    # 2-frame stack), 36px frames. 40k iterations ≈ 51.2M decisions.
    # Entropy-collapse timing is strongly seed-dependent: eval crosses 0
    # anywhere in the ~27M–130M decision band (observed across seeds /
    # hosts — BASELINE.md's variance note), so plateau runs budget
    # 160k iterations ≈ 205M decisions.
    "impala_pong_learn": Preset(
        algo="impala",
        env="jax:pong",
        config=impala.ImpalaConfig(
            num_envs=64, rollout_steps=20, actor_refresh_every=4
        ),
        iterations=40_000,
        description="IMPALA on JAX Pong at the learnable difficulty "
        "(opp_skill=0.5, frame_skip=4, 36px — BASELINE.json:11)",
        env_kwargs={"opp_skill": 0.5, "frame_skip": 4, "size": 36},
    ),
    # ISSUE 11 — the scenario universe: a heterogeneous fleet of four
    # env TYPES (domain-randomized per instance AND per episode)
    # stepping inside one fused XLA program behind the padded shared
    # obs/action interface (envs/mixture.py). Pair with
    # `--curriculum "200:1,2,2,2;400:0,1,2,4" --eval-every 25` to shift
    # the type draw toward the harder members as CartPole-dominated
    # progress crosses the thresholds.
    "a2c_mixture": Preset(
        algo="a2c",
        env="mixture:cartpole,pendulum,acrobot,maze",
        config=a2c.A2CConfig(
            num_envs=1024, rollout_steps=32, lr=1e-3,
            anneal_iters=400, lr_final=0.0,
            entropy_coef=0.01, entropy_coef_final=0.0,
        ),
        iterations=400,
        description="A2C on the 4-type scenario-mixture fleet, fused "
        "(ISSUE 11 scenario universe)",
        env_kwargs={"randomize": 0.2},
    ),
    "a3c_pong": Preset(
        algo="a3c",
        env="jax:pong",
        config=impala.ImpalaConfig(
            num_envs=64, rollout_steps=20, actor_refresh_every=4,
            correction="none", lam=0.95,
        ),
        iterations=2000,
        description="A3C-style (no IS correction) on JAX Pong (BASELINE.json:11)",
    ),
}

# Algorithm name → config dataclass type, for --algo without --preset.
ALGO_CONFIGS: dict[str, Any] = {
    "a2c": a2c.A2CConfig,
    "ppo": ppo.PPOConfig,
    "ddpg": ddpg.DDPGConfig,
    "td3": ddpg.DDPGConfig,
    "sac": sac.SACConfig,
    "impala": impala.ImpalaConfig,
    "a3c": impala.ImpalaConfig,
}


def _coerce(raw: str, typ: Any) -> Any:
    """Parse a CLI string into the annotated field type."""
    origin = typing.get_origin(typ)
    if origin is Union:  # Optional[T]
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        if raw.lower() in ("none", "null"):
            return None
        return _coerce(raw, args[0])
    if origin is tuple:
        elem = typing.get_args(typ)[0]
        if raw.strip() == "":
            return ()
        return tuple(_coerce(p.strip(), elem) for p in raw.split(","))
    if typ is bool:
        if raw.lower() in ("1", "true", "yes", "on"):
            return True
        if raw.lower() in ("0", "false", "no", "off"):
            return False
        raise ValueError(f"not a bool: {raw!r}")
    if typ is int:
        return int(raw)
    if typ is float:
        return float(raw)
    if typ is str:
        return raw
    raise ValueError(f"unsupported field type {typ} for value {raw!r}")


def apply_overrides(config: Any, overrides: dict[str, str]) -> Any:
    """`dataclasses.replace` with string values coerced to field types.

    Unknown keys raise with the list of valid fields (typo safety).
    """
    if not overrides:
        return config
    hints = typing.get_type_hints(type(config))
    fields = {f.name for f in dataclasses.fields(config)}
    updates = {}
    for key, raw in overrides.items():
        if key not in fields:
            raise KeyError(
                f"{type(config).__name__} has no field {key!r}; "
                f"valid: {sorted(fields)}"
            )
        updates[key] = _coerce(raw, hints[key])
    return dataclasses.replace(config, **updates)


def parse_set_args(pairs: list[str]) -> dict[str, str]:
    """['lr=1e-3', 'hidden=64,64'] → {'lr': '1e-3', 'hidden': '64,64'}."""
    out: dict[str, str] = {}
    for pair in pairs:
        if "=" not in pair:
            raise ValueError(f"--set expects key=value, got {pair!r}")
        key, _, value = pair.partition("=")
        out[key.strip()] = value.strip()
    return out


def coerce_env_value(raw: str) -> Any:
    """Parse an `--env-set` value. Env-maker kwargs are not dataclass
    fields, so there is no annotation to coerce against — use literal
    syntax: bools/None by keyword, then int, then float, else string."""
    low = raw.lower()
    if low in ("true", "yes", "on"):
        return True
    if low in ("false", "no", "off"):
        return False
    if low in ("none", "null"):
        return None
    for typ in (int, float):
        try:
            return typ(raw)
        except ValueError:
            pass
    return raw


def parse_env_set_args(pairs: list[str]) -> dict[str, Any]:
    """['opp_skill=0.5', 'frame_skip=4'] → {'opp_skill': 0.5, 'frame_skip': 4}."""
    return {k: coerce_env_value(v) for k, v in parse_set_args(pairs).items()}


def default_config(algo: str) -> Any:
    """The algorithm's default config, with variant specialization applied
    (td3 → twin-Q/delay/smoothing; a3c → no importance correction)."""
    if algo not in ALGO_CONFIGS:
        raise KeyError(f"unknown algo {algo!r}; valid: {sorted(ALGO_CONFIGS)}")
    if algo == "td3":
        return ddpg.td3_config()
    cfg = ALGO_CONFIGS[algo]()
    if algo == "a3c":
        cfg = dataclasses.replace(cfg, correction="none")
    return cfg


def resolve(
    preset: Optional[str],
    algo: Optional[str],
    env: Optional[str],
    overrides: dict[str, str],
    env_overrides: Optional[dict[str, Any]] = None,
) -> Preset:
    """Resolve CLI selections into a concrete Preset.

    Either `--preset name` (optionally overridden by --algo/--env) or
    `--algo` + `--env` from scratch with that algorithm's default config.
    `env_overrides` (from --env-set) merge over the preset's env_kwargs;
    changing the env drops the preset's env_kwargs (they belong to the
    preset's env), keeping only the CLI ones.
    """
    env_overrides = env_overrides or {}
    if preset is not None:
        if preset not in PRESETS:
            raise KeyError(f"unknown preset {preset!r}; valid: {sorted(PRESETS)}")
        base = PRESETS[preset]
        algo = algo or base.algo
        base_env_kwargs = base.env_kwargs if env in (None, base.env) else {}
        env = env or base.env
        # Changing the algo drops the preset's config (it belongs to the
        # preset's algorithm) in favor of the new algo's specialized
        # defaults — so e.g. `--preset ddpg_walker2d --algo td3` really
        # runs TD3, not vanilla DDPG under a td3 label.
        cfg = base.config if algo == base.algo else default_config(algo)
        return Preset(
            algo=algo, env=env, config=apply_overrides(cfg, overrides),
            iterations=base.iterations, description=base.description,
            env_kwargs={**base_env_kwargs, **env_overrides},
        )
    if algo is None or env is None:
        raise ValueError("need --preset, or both --algo and --env")
    cfg = default_config(algo)
    return Preset(
        algo=algo, env=env, config=apply_overrides(cfg, overrides),
        iterations=1000, description=f"{algo} on {env}",
        env_kwargs=dict(env_overrides),
    )
