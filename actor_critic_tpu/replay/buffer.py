"""HBM-resident replay buffer — pure-functional ring over device arrays.

The reference keeps its off-policy transition store in host RAM and pays a
host→device copy on every `buffer.sample(B)` (SURVEY.md §3.2 boundary
analysis; reference mount empty, §0). The TPU-native design keeps the
whole ring IN HBM as a pytree of `[capacity, ...]` arrays that lives
inside the (donated) training state: inserts are index-scatters, sampling
is an on-device gather with on-device PRNG, and neither ever touches the
host (BASELINE.json:5 "off-policy replay buffer lives in HBM",
BASELINE.json:9).

Donation discipline (SURVEY.md §7.2 item 4): every function here is pure
and returns a new `ReplayState`; callers close over them inside a jitted
train step whose state argument is donated (`donate_argnums=0`), so XLA
updates the multi-GB storage in place instead of copying it each step
(verified by the buffer-pointer test in tests/test_replay.py).

Sharding: under data-parallel training each device holds an independent
shard of the ring (its own envs feed it, its own sampler reads it) — the
buffer needs no collectives. `parallel.dp.replay_specs()` builds the
PartitionSpec tree (storage's capacity axis split over dp, cursor
scalars replicated) and `parallel.dp.offpolicy_state_specs()` /
`sac_state_specs()` embed it in the full trainer-state layout; tested by
tests/test_parallel.py's off-policy dp cases on the 8-device CPU mesh.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class ReplayState(NamedTuple):
    """The ring: storage pytree of [capacity, ...] arrays + write cursor.

    `insert_pos` is the next slot to write; `size` counts valid entries
    (saturates at capacity once the ring has wrapped).
    """

    storage: Any
    insert_pos: jax.Array  # int32
    size: jax.Array  # int32


def capacity_of(state: ReplayState) -> int:
    """Static ring capacity (leading dim of every storage leaf)."""
    return jax.tree.leaves(state.storage)[0].shape[0]


def init(example_item: Any, capacity: int) -> ReplayState:
    """Allocate a zeroed ring shaped after one example item.

    `example_item` is a pytree of per-transition arrays (no batch axis);
    storage leaves get shape [capacity, *item_shape] and the item's dtype.
    """
    storage = jax.tree.map(
        lambda x: jnp.zeros((capacity, *jnp.shape(x)), jnp.asarray(x).dtype),
        example_item,
    )
    return ReplayState(
        storage=storage,
        insert_pos=jnp.zeros((), jnp.int32),
        size=jnp.zeros((), jnp.int32),
    )


def add_batch(state: ReplayState, batch: Any) -> ReplayState:
    """Insert a [B, ...] batch of transitions, wrapping around the ring.

    B is static (leaf shape). Indices are computed mod capacity so a
    batch can straddle the wrap point; XLA lowers the `.at[idx].set` to an
    in-place scatter when the state is donated. A batch larger than the
    ring keeps only its newest `capacity` rows — mod-indices would
    otherwise scatter duplicates in undefined order.
    """
    capacity = capacity_of(state)
    b = jax.tree.leaves(batch)[0].shape[0]
    if b > capacity:
        batch = jax.tree.map(lambda x: x[-capacity:], batch)
        b = capacity
    idx = (state.insert_pos + jnp.arange(b, dtype=jnp.int32)) % capacity
    storage = jax.tree.map(
        lambda s, x: s.at[idx].set(x.astype(s.dtype)), state.storage, batch
    )
    return ReplayState(
        storage=storage,
        insert_pos=(state.insert_pos + b) % capacity,
        size=jnp.minimum(state.size + b, capacity),
    )


def sample(state: ReplayState, key: jax.Array, batch_size: int) -> Any:
    """Uniform sample of `batch_size` transitions (with replacement).

    On-device RNG + gather: no host round-trip (SURVEY §3.2). Callers
    must not sample an empty buffer (standard warmup contract); the
    maximum(size, 1) guard only keeps the randint bounds legal under
    tracing.
    """
    idx = jax.random.randint(
        key, (batch_size,), 0, jnp.maximum(state.size, 1), dtype=jnp.int32
    )
    return jax.tree.map(lambda s: s[idx], state.storage)


def sample_sequences(
    state: ReplayState, key: jax.Array, batch_size: int, seq_len: int
) -> Any:
    """Sample `batch_size` sequences of `seq_len` consecutive INSERTS.

    Start offsets are drawn in insertion order relative to the oldest
    valid entry, so a window can wrap around the physical ring but never
    crosses the write-cursor seam (which would splice the newest and
    oldest transitions into a fabricated sequence). Callers ensure
    size >= seq_len. Returned leaves are [batch_size, seq_len, ...].
    Sequences may still span episode boundaries; consumers mask on their
    stored `done` flags — see `algos.ddpg` `DDPGConfig.nstep`, whose
    n-step TD target is the in-tree consumer (ADVICE: a sequence/R2D2
    style recurrent consumer would sit on the same call).
    """
    capacity = capacity_of(state)
    # Oldest valid entry: physical slot 0 until the ring fills, then the
    # slot the cursor is about to overwrite.
    oldest = jnp.where(state.size < capacity, 0, state.insert_pos)
    max_start = jnp.maximum(state.size - seq_len + 1, 1)
    start = jax.random.randint(key, (batch_size,), 0, max_start, dtype=jnp.int32)
    offsets = jnp.arange(seq_len, dtype=jnp.int32)
    idx = (oldest + start[:, None] + offsets[None, :]) % capacity
    return jax.tree.map(lambda s: s[idx], state.storage)
