"""HBM-resident replay buffer — pure-functional ring over device arrays.

The reference keeps its off-policy transition store in host RAM and pays a
host→device copy on every `buffer.sample(B)` (SURVEY.md §3.2 boundary
analysis; reference mount empty, §0). The TPU-native design keeps the
whole ring IN HBM as a pytree of `[capacity, ...]` arrays that lives
inside the (donated) training state: inserts are index-scatters, sampling
is an on-device gather with on-device PRNG, and neither ever touches the
host (BASELINE.json:5 "off-policy replay buffer lives in HBM",
BASELINE.json:9).

Quantized storage (ISSUE 8, HEPPO-GAE arxiv 2501.12703): every function
takes an optional per-leaf `codecs` spec (`replay/quantize.py`) — a
static pytree of codec-kind strings matching the transition structure.
`add_batch` folds the incoming batch into the running standardization
stats and encodes before the scatter; `sample`/`sample_sequences` decode
after the gather; with `codecs=None` (or all-`raw`) the ring behaves
exactly as before. The stats ride `ReplayState.quant` as ordinary
donated leaves, so they follow the state through donation, sharding and
checkpointing with no extra machinery.

Donation discipline (SURVEY.md §7.2 item 4): every function here is pure
and returns a new `ReplayState`; callers close over them inside a jitted
train step whose state argument is donated (`donate_argnums=0`), so XLA
updates the multi-GB storage in place instead of copying it each step
(verified by the buffer-pointer test in tests/test_replay.py — including
through the encode/decode codec wrappers).

Sharding: under data-parallel training each device holds an independent
shard of the ring (its own envs feed it, its own sampler reads it) — the
storage needs no collectives. `parallel.dp.replay_specs()` builds the
PartitionSpec tree (storage's capacity axis split over dp, cursor
scalars and quant stats replicated — `add_batch(..., axis_name=...)`
pmean/pmax-syncs the stats moments so they stay identical per device)
and `parallel.dp.offpolicy_state_specs()` / `sac_state_specs()` embed it
in the full trainer-state layout; tested by tests/test_parallel.py's
off-policy dp cases on the 8-device CPU mesh.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from actor_critic_tpu.replay import quantize


class ReplayState(NamedTuple):
    """The ring: storage pytree of [capacity, ...] arrays + write cursor.

    `insert_pos` is the next slot to write; `size` counts valid entries
    (saturates at capacity once the ring has wrapped). `quant` mirrors
    the storage structure with one `quantize.QuantStats` per leaf —
    live running mean/scale for `i8`-coded leaves, zero placeholders
    elsewhere, so the pytree structure is codec-independent (checkpoint
    templates and warmup eval_shapes never fork on `--replay-dtype`).
    """

    storage: Any
    insert_pos: jax.Array  # int32
    size: jax.Array  # int32
    quant: Any = None


def capacity_of(state: ReplayState) -> int:
    """Static ring capacity (leading dim of every storage leaf)."""
    return jax.tree.leaves(state.storage)[0].shape[0]


def _codec_tree(codecs: Optional[Any], example: Any) -> Any:
    return quantize.default_codecs(example) if codecs is None else codecs


def _guard_defaulted_codecs(state: ReplayState) -> None:
    """Refuse the codecs=None default against a ring that was built
    quantized: an all-`raw` spec would scatter/gather the int8/f16
    codes UNCHANGED — training would silently proceed on ~127x-scaled
    garbage with no dtype error anywhere. A caller that really wants a
    raw int8/f16 ring passes an explicit all-`raw` spec."""
    for leaf in jax.tree.leaves(state.storage):
        if leaf.dtype in (jnp.int8, jnp.float16):
            raise ValueError(
                "this replay ring holds quantized storage "
                f"(a {leaf.dtype} leaf) but no codec spec was passed — "
                "pass the same `codecs` used at replay.init "
                "(e.g. replay.offpolicy_codecs(cfg.replay_dtype)) so "
                "values are encoded/decoded, not read as raw codes"
            )


def init(example_item: Any, capacity: int, codecs: Optional[Any] = None) -> ReplayState:
    """Allocate a zeroed ring shaped after one example item.

    `example_item` is a pytree of per-transition arrays (no batch axis);
    storage leaves get shape [capacity, *item_shape] at the codec's
    storage dtype (the item's own dtype for `raw`).
    """
    codecs = _codec_tree(codecs, example_item)
    storage = jax.tree.map(
        lambda kind, x: jnp.zeros(
            (capacity, *jnp.shape(x)),
            quantize.storage_dtype(kind, jnp.asarray(x).dtype),
        ),
        codecs, example_item,
    )
    quant = jax.tree.map(quantize.init_stats, codecs, example_item)
    return ReplayState(
        storage=storage,
        insert_pos=jnp.zeros((), jnp.int32),
        size=jnp.zeros((), jnp.int32),
        quant=quant,
    )


def add_batch(
    state: ReplayState,
    batch: Any,
    codecs: Optional[Any] = None,
    axis_name: Optional[str] = None,
) -> ReplayState:
    """Insert a [B, ...] batch of transitions, wrapping around the ring.

    B is static (leaf shape). The batch first updates the running
    quantization stats (a no-op for stat-free codecs; `axis_name` syncs
    the moments across dp so replicated stats stay identical), then each
    leaf is encoded and scattered. Indices are computed mod capacity so
    a batch can straddle the wrap point; XLA lowers the `.at[idx].set`
    to an in-place scatter when the state is donated. A batch larger
    than the ring keeps only its newest `capacity` rows — mod-indices
    would otherwise scatter duplicates in undefined order.
    """
    if codecs is None:
        _guard_defaulted_codecs(state)
    codecs = _codec_tree(codecs, batch)
    capacity = capacity_of(state)
    b = jax.tree.leaves(batch)[0].shape[0]
    if b > capacity:
        batch = jax.tree.map(lambda x: x[-capacity:], batch)
        b = capacity
    quant = state.quant
    if quant is None:  # pre-quantizer state (e.g. a hand-built test tree)
        quant = jax.tree.map(
            lambda kind, x: quantize.init_stats(kind, x[0]), codecs, batch
        )
    # tree.map with the codec tree FIRST: codecs is a structure-prefix of
    # quant, so each mapped call receives one leaf's whole QuantStats.
    quant = jax.tree.map(
        lambda kind, stats, x: quantize.update_stats(
            kind, stats, x, axis_name=axis_name
        ),
        codecs, quant, batch,
    )
    idx = (state.insert_pos + jnp.arange(b, dtype=jnp.int32)) % capacity
    storage = jax.tree.map(
        lambda kind, stats, s, x: s.at[idx].set(
            quantize.encode(kind, stats, x, s.dtype)
        ),
        codecs, quant, state.storage, batch,
    )
    return ReplayState(
        storage=storage,
        insert_pos=(state.insert_pos + b) % capacity,
        size=jnp.minimum(state.size + b, capacity),
        quant=quant,
    )


def _decode_tree(state: ReplayState, codecs: Any, gathered: Any) -> Any:
    quant = state.quant
    if quant is None:
        quant = jax.tree.map(
            lambda kind, s: quantize.init_stats(kind, s[0]),
            codecs, state.storage,
        )
    return jax.tree.map(quantize.decode, codecs, quant, gathered)


def sample(
    state: ReplayState,
    key: jax.Array,
    batch_size: int,
    codecs: Optional[Any] = None,
) -> Any:
    """Uniform sample of `batch_size` transitions (with replacement).

    On-device RNG + gather + codec decode: no host round-trip (SURVEY
    §3.2). Callers must not sample an empty buffer (standard warmup
    contract); the maximum(size, 1) guard only keeps the randint bounds
    legal under tracing.
    """
    if codecs is None:
        _guard_defaulted_codecs(state)
    codecs = _codec_tree(codecs, state.storage)
    idx = jax.random.randint(
        key, (batch_size,), 0, jnp.maximum(state.size, 1), dtype=jnp.int32
    )
    return _decode_tree(state, codecs, jax.tree.map(lambda s: s[idx], state.storage))


def sample_sequences(
    state: ReplayState,
    key: jax.Array,
    batch_size: int,
    seq_len: int,
    codecs: Optional[Any] = None,
) -> Any:
    """Sample `batch_size` sequences of `seq_len` consecutive INSERTS.

    THE WINDOW CONTRACT (ISSUE 13 — pinned by tests/test_replay.py
    before the R2D2-style consumer builds on it):

    1. **Insertion order, never the seam.** Start offsets are drawn in
       insertion order relative to the OLDEST valid entry, so a window
       may wrap around the physical ring (its indices straddle slot
       capacity-1 → 0) but can never cross the write-cursor seam —
       which would splice the ring's newest transitions onto its oldest
       and fabricate a sequence no policy ever produced. Every returned
       window is `seq_len` transitions that were inserted consecutively.
    2. **Episode boundaries are the CONSUMER's job.** A window may
       contain `done == 1` anywhere inside it; this function returns it
       unmodified (truncating would make window shapes dynamic).
       Consumers mask using the stored `done` flags, with the shared
       alive-before-done convention: the step carrying `done` is the
       LAST valid step of its episode (its reward is the terminal
       reward), every later step in the window belongs to a different
       episode and must not contribute. In-tree consumers:
       `algos.ddpg.nstep_batch` (n-step TD prefix) and
       `data_plane.device_replay.sequence_window_mask` /
       `split_burn_in` (the R2D2-style burn-in/train split).
    3. **Env interleaving is the CALLER's job.** The ring stores
       flattened [K, E] rollouts, so consecutive inserts are one env's
       consecutive timesteps only when E == 1 (`DDPGConfig.nstep`
       enforces this); with E > 1 a window interleaves envs.

    Callers ensure size >= seq_len (the max_start clamp below only
    keeps randint's bounds legal under tracing — a smaller ring would
    silently clamp windows into zero-initialized slots). Returned
    leaves are [batch_size, seq_len, ...], codec-decoded like `sample`.
    """
    if codecs is None:
        _guard_defaulted_codecs(state)
    codecs = _codec_tree(codecs, state.storage)
    capacity = capacity_of(state)
    # Oldest valid entry: physical slot 0 until the ring fills, then the
    # slot the cursor is about to overwrite.
    oldest = jnp.where(state.size < capacity, 0, state.insert_pos)
    max_start = jnp.maximum(state.size - seq_len + 1, 1)
    start = jax.random.randint(key, (batch_size,), 0, max_start, dtype=jnp.int32)
    offsets = jnp.arange(seq_len, dtype=jnp.int32)
    idx = (oldest + start[:, None] + offsets[None, :]) % capacity
    return _decode_tree(state, codecs, jax.tree.map(lambda s: s[idx], state.storage))
