"""Quantized replay storage — per-leaf codecs behind dynamic standardization.

fp32 storage caps the HBM ring at a fraction of the transitions the same
memory could hold. HEPPO-GAE (arxiv 2501.12703) shows int8/fp16 storage
behind *dynamic standardization* — running mean/scale stats that encode
values into the quantized range as the data distribution reveals itself —
multiplies replay capacity with no measurable learning-quality loss, and
Accelerated Methods for Deep RL (arxiv 1803.02811) shows bigger,
better-mixed replay buys off-policy throughput and stability directly.

This module is the codec layer `replay/buffer.py` calls on every
`add_batch` (encode + stats update) and `sample`/`sample_sequences`
(decode after the gather). Everything is pure and shape-static, so the
donated in-place scatter/gather discipline — and jaxlint's
donation-aliasing guarantees — survive unchanged: encode produces the
quantized `[B, ...]` batch that `.at[idx].set` scatters, decode maps the
gathered rows back to float32, and the running stats ride `ReplayState`
(and therefore the checkpoint save tree) as ordinary donated leaves.

Codecs (per storage leaf, selected by a static string):

| kind      | storage      | stats            | decode error bound        |
|-----------|--------------|------------------|---------------------------|
| `raw`     | leaf dtype   | —                | exact                     |
| `f16`     | float16      | —                | ~2^-11 relative           |
| `i8`      | int8         | mean/scale EMA   | scale/127 per element     |
| `i8_unit` | int8         | — ([-1,1] fixed) | 1/127                     |
| `bool8`   | int8         | — ({0,1} exact)  | exact                     |

`i8` standardizes with a cumulative-average mean and a monotone
running-max scale (never shrinks), so entries encoded earlier decode
under stats that only *widen* — the drift error HEPPO-GAE's dynamic
standardization accepts, bounded here by the scale staying a superset of
every range it ever encoded against. Under data-parallel sharding the
batch moments are pmean/pmax-synced across the dp axis (`axis_name`
threaded from the trainer), so the stats stay bit-identical on every
device and `parallel.dp.replay_specs()` can replicate them.

Mode presets for the off-policy `OffPolicyTransition` ring
(`train.py --replay-dtype`):

- `fp32`  — everything raw (today's behavior; uint8 pixel obs already
  pass through untouched).
- `mixed` — obs/next_obs and reward `i8`-standardized, done/terminated
  `bool8`, actions kept fp32: a tanh-squashed policy concentrates
  actions near the bounds where int8 resolution is coarsest and the
  critic's action-gradient is steepest, so quantizing them is the one
  unsafe default (the HEPPO-GAE rationale). ~3.1x transitions per HBM
  byte at Pendulum shape.
- `int8`  — mixed plus `i8_unit` actions (bounded in [-1, 1] by the
  acting convention): the aggressive mode, ~4x at Pendulum shape;
  measured fine on the analytic testbeds, unsafe in general.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

# Leaves whose codec carries running stats.
STAT_KINDS = ("i8",)
KINDS = ("raw", "f16", "i8", "i8_unit", "bool8")
MODES = ("fp32", "mixed", "int8")

_EPS = 1e-6  # scale floor: an all-constant leaf must not divide by zero
_MEAN_SATURATE = 1 << 30  # count saturation, same rationale as env_steps

# Calibration window (transitions) for `i8` stats, after which mean and
# scale FREEZE. A ring decodes every entry with the CURRENT stats, so a
# mean that keeps drifting re-biases every previously-encoded entry by
# the full drift — measured in-session to cost DDPG point_mass ~2.7
# return while TD3/SAC merely tolerated it. Freezing after a short
# calibration phase bounds that drift to the calibration window (whose
# entries are the low-value random-warmup data) and makes decode exact-
# per-encode afterwards. The uniform-random warmup policy is the widest-
# coverage calibration set the run will ever see; later out-of-range
# values clip to ±scale (the HEPPO-GAE clipping regime).
CALIBRATION_TRANSITIONS = 4096


class QuantStats(NamedTuple):
    """Running standardization stats for one `i8` leaf (item-shaped, so
    obs quantize per-feature; scalar leaves carry scalar stats). Every
    leaf gets a QuantStats slot — non-stat codecs hold zeros-shaped
    placeholders — so the ReplayState pytree structure is uniform across
    modes and checkpoint templates never depend on the codec spec."""

    mean: jax.Array
    scale: jax.Array
    count: jax.Array  # int32 transitions absorbed (saturating)


def offpolicy_codecs(mode: str) -> Any:
    """The per-leaf codec spec for the DDPG/TD3/SAC transition ring.

    Returns an `OffPolicyTransition` of codec-kind strings (static —
    closed over by the jitted trainers, never traced).
    """
    from actor_critic_tpu.algos.common import OffPolicyTransition

    if mode not in MODES:
        raise ValueError(f"replay_dtype must be one of {MODES}, got {mode!r}")
    if mode == "fp32":
        k = dict(obs="raw", action="raw", reward="raw", next_obs="raw",
                 terminated="raw", done="raw")
    else:
        k = dict(
            obs="i8", next_obs="i8", reward="i8",
            terminated="bool8", done="bool8",
            action="i8_unit" if mode == "int8" else "raw",
        )
    return OffPolicyTransition(**k)


def default_codecs(example: Any) -> Any:
    """All-`raw` codec tree matching `example`'s structure (the
    pass-through spec `buffer.py` uses when callers give none)."""
    return jax.tree.map(lambda _: "raw", example)


def storage_dtype(kind: str, dtype) -> Any:
    """The ring dtype a codec stores its leaf at."""
    if kind == "raw":
        return dtype
    if kind == "f16":
        return jnp.float16
    if kind in ("i8", "i8_unit", "bool8"):
        return jnp.int8
    raise ValueError(f"unknown codec kind {kind!r}; valid: {KINDS}")


def init_stats(kind: str, example_leaf) -> QuantStats:
    """Zeroed stats slot for one leaf: item-shaped mean/scale for `i8`,
    scalar placeholders for everything else. scale seeds at the _EPS
    floor, NOT 1.0: the running max can only grow, so a 1.0 seed would
    permanently floor the quantization step at 1/127 and throw away
    almost all int8 resolution on leaves whose data magnitude sits well
    below 1 (sampling before the first add_batch is already outside the
    buffer contract, so no real decode sees the seed value)."""
    if kind in STAT_KINDS:
        shape = jnp.shape(example_leaf)
    else:
        shape = ()
    return QuantStats(
        mean=jnp.zeros(shape, jnp.float32),
        scale=jnp.full(shape, _EPS, jnp.float32),
        count=jnp.zeros((), jnp.int32),
    )


def update_stats(
    kind: str, stats: QuantStats, batch, axis_name=None
) -> QuantStats:
    """Fold one `[B, ...]` batch into the running stats (no-op for
    stat-free codecs).

    mean: cumulative average over transitions (early batches move it
    fast). scale: monotone running max of |x − mean| with an _EPS floor.
    Both FREEZE once `CALIBRATION_TRANSITIONS` transitions have been
    absorbed (branchless where-select): past calibration, every entry
    decodes through exactly the stats it was encoded with — no drift
    re-biasing of old ring entries. Under dp the batch moments are
    pmean/pmax-synced so all devices hold identical stats
    (replay_specs replicates them).
    """
    if kind not in STAT_KINDS:
        return stats
    x = batch.astype(jnp.float32)
    # Reduce over the batch axes (everything leading the item shape).
    item_ndim = stats.mean.ndim
    axes = tuple(range(x.ndim - item_ndim))
    b = 1
    for a in axes:
        b *= x.shape[a]
    batch_mean = jnp.mean(x, axis=axes)
    if axis_name is not None:
        batch_mean = jax.lax.pmean(batch_mean, axis_name)
    w = b / jnp.maximum(stats.count + b, 1).astype(jnp.float32)
    mean = stats.mean + (batch_mean - stats.mean) * w
    absmax = jnp.max(jnp.abs(x - mean), axis=axes)
    if axis_name is not None:
        absmax = jax.lax.pmax(absmax, axis_name)
    scale = jnp.maximum(jnp.maximum(stats.scale, absmax), _EPS)
    calibrating = stats.count < CALIBRATION_TRANSITIONS
    mean = jnp.where(calibrating, mean, stats.mean)
    scale = jnp.where(calibrating, scale, stats.scale)
    count = jnp.minimum(stats.count + b, jnp.int32(_MEAN_SATURATE))
    return QuantStats(mean=mean, scale=scale, count=count)


# jaxlint: disable=precision-discipline (audited fork: the STORAGE
# dtype forking on the codec is this function's contract — the ring
# allocates per-leaf storage via storage_dtype with the SAME kind, so
# no consumer ever sees a surprise dtype)
def encode(kind: str, stats: QuantStats, x, store_dtype) -> jax.Array:
    """One leaf batch → its stored representation (pure; the caller
    scatters the result into the donated ring).

    Saturating by construction (ISSUE 14, asserted by numsan's
    saturating-magnitude poisoner): out-of-range values clip to the
    codec's representable range BEFORE the narrowing cast — a float→int8
    cast of an unclipped value is implementation-defined and WRAPS on
    CPU (a 1e6 flag became a negative one), and a float32→float16 cast
    of |x| > 65504 overflows to inf, injecting the very non-finite the
    guards exist to keep out. For the int8 codecs a NaN input narrows
    deterministically to the range midpoint via nan_to_num (identity
    for every finite value, so all parity/roundtrip bounds are
    unchanged); the f16 codec stores NaN VERBATIM — deterministic
    propagation for the downstream divergence/commit gates to own,
    never a silent random int.
    The numpy mirror (`data_plane/codecs.np_encode`) applies the SAME
    rule so host-encode == device-encode stays bit-exact."""
    if kind == "raw":
        return x.astype(store_dtype)
    if kind == "f16":
        f16_max = float(jnp.finfo(jnp.float16).max)
        return jnp.clip(x, -f16_max, f16_max).astype(jnp.float16)
    if kind == "bool8":
        return jnp.round(
            jnp.clip(jnp.nan_to_num(x), 0.0, 1.0)
        ).astype(jnp.int8)
    if kind == "i8_unit":
        q = jnp.clip(
            jnp.nan_to_num(x.astype(jnp.float32)), -1.0, 1.0
        ) * 127.0
        return jnp.round(q).astype(jnp.int8)
    if kind == "i8":
        z = (x.astype(jnp.float32) - stats.mean) / stats.scale
        return jnp.round(
            jnp.clip(jnp.nan_to_num(z), -1.0, 1.0) * 127.0
        ).astype(jnp.int8)
    raise ValueError(f"unknown codec kind {kind!r}; valid: {KINDS}")


# jaxlint: disable=precision-discipline (audited fork: every quantized
# kind decodes to float32; `raw` alone passes the storage dtype through
# BY DESIGN — uint8 pixel obs must reach the encoder torso un-floated,
# and the buffer's all-raw default must be a bitwise no-op)
def decode(kind: str, stats: QuantStats, q) -> jax.Array:
    """Stored representation → float32 (identity for `raw`)."""
    if kind == "raw":
        return q
    if kind == "f16":
        return q.astype(jnp.float32)
    if kind == "bool8":
        return q.astype(jnp.float32)
    if kind == "i8_unit":
        return q.astype(jnp.float32) / 127.0
    if kind == "i8":
        return q.astype(jnp.float32) * (stats.scale / 127.0) + stats.mean
    raise ValueError(f"unknown codec kind {kind!r}; valid: {KINDS}")


# ---------------------------------------------------------------------------
# Capacity accounting (run_report Resources row, bench records)
# ---------------------------------------------------------------------------

def _item_bytes(leaf, dtype) -> int:
    n = 1
    for d in leaf.shape[1:]:  # drop the capacity axis
        n *= d
    return n * jnp.dtype(dtype).itemsize


def capacity_report(state, codecs=None) -> dict:
    """{capacity, bytes_per_transition, fp32_bytes_per_transition,
    capacity_multiplier, codec_mix} for one ring — the honest
    bytes-per-transition numbers behind every capacity claim. The fp32
    reference prices quantized leaves at 4 bytes/element and leaves
    `raw` leaves (incl. uint8 pixel obs) at their own dtype, so the
    multiplier never counts pass-through bytes as savings."""
    storage = state.storage
    if codecs is None:
        codecs = default_codecs(storage)
    leaves = jax.tree.leaves(storage)
    kinds = jax.tree.leaves(codecs)
    names = _leaf_names(codecs)
    stored = fp32 = 0
    mix = []
    for name, kind, leaf in zip(names, kinds, leaves):
        stored += _item_bytes(leaf, leaf.dtype)
        ref_dtype = leaf.dtype if kind == "raw" else jnp.float32
        fp32 += _item_bytes(leaf, ref_dtype)
        mix.append(f"{name}:{kind}")
    cap = leaves[0].shape[0]
    return {
        "capacity": int(cap),
        "bytes_per_transition": int(stored),
        "fp32_bytes_per_transition": int(fp32),
        "capacity_multiplier": round(fp32 / max(stored, 1), 2),
        "ring_bytes": int(cap * stored),
        "codec_mix": ",".join(mix),
    }


def _leaf_names(codecs) -> list[str]:
    """Dotted key path per codec leaf (for the codec_mix string)."""
    paths, _ = jax.tree_util.tree_flatten_with_path(codecs)
    out = []
    for path, _leaf in paths:
        out.append(
            ".".join(
                str(getattr(p, "name", getattr(p, "key", getattr(p, "idx", p))))
                for p in path
            )
            or "leaf"
        )
    return out
