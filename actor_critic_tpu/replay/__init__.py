from actor_critic_tpu.replay.buffer import (
    ReplayState,
    add_batch,
    capacity_of,
    init,
    sample,
    sample_sequences,
)

__all__ = [
    "ReplayState",
    "add_batch",
    "capacity_of",
    "init",
    "sample",
    "sample_sequences",
]
