from actor_critic_tpu.replay import quantize
from actor_critic_tpu.replay.buffer import (
    ReplayState,
    add_batch,
    capacity_of,
    init,
    sample,
    sample_sequences,
)
from actor_critic_tpu.replay.quantize import QuantStats, offpolicy_codecs

__all__ = [
    "QuantStats",
    "ReplayState",
    "add_batch",
    "capacity_of",
    "init",
    "offpolicy_codecs",
    "quantize",
    "sample",
    "sample_sequences",
]
