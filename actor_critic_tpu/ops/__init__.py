from actor_critic_tpu.ops.pallas_scan import (
    gae_auto,
    lambda_returns_auto,
    vtrace_auto,
)
from actor_critic_tpu.ops.polyak import hard_update, polyak_update
from actor_critic_tpu.ops.returns import (
    VTraceOutput,
    discounted_returns,
    gae,
    lambda_returns,
    n_step_returns,
    normalize_advantages,
    vtrace,
)

__all__ = [
    "VTraceOutput",
    "discounted_returns",
    "gae",
    "gae_auto",
    "hard_update",
    "lambda_returns",
    "lambda_returns_auto",
    "n_step_returns",
    "normalize_advantages",
    "polyak_update",
    "vtrace",
    "vtrace_auto",
]
