"""Return / advantage computations as reverse `lax.scan`s.

Covers the reference's GAE/λ-return scan (BASELINE.json:5,8) and IMPALA's
V-trace off-policy correction (BASELINE.json:11; reference mount empty at
survey, SURVEY.md §0). All functions:

- take time-major arrays `[T, ...]` (trailing batch axes broadcast freely,
  so the same code serves a single trajectory or a [T, E] vmapped batch),
- are pure and jit-safe: O(T) `lax.scan(reverse=True)`, static shapes,
  no Python control flow on traced values,
- treat `dones` as terminations (cut both the bootstrap and the trace);
  truncated episodes should bootstrap through — pass `terminations` here
  and handle truncation by patching rewards with `value` upstream.

TPU note (SURVEY.md §5.7): the scan is over the *time* axis, which stays
per-device; the batch axis is what gets sharded over the mesh.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

# Cap on log importance ratios before exp (ISSUE 14, nonfinite-hazard):
# exp(20) ≈ 4.9e8 — far above any ratio the ρ̄/c̄/PPO clips keep, far
# below f32 overflow. Without it, behavior/target drift overflows the
# ratio to inf and `inf × 0` advantage is nan — which no downstream
# `minimum(ρ̄, ·)` can repair (the clip happens AFTER the inf is born).
# Bit-identical for every in-range ratio, so golden/parity tests and
# the Pallas kernel (which applies the same cap) are unchanged.
LOG_RATIO_CAP = 20.0


def discounted_returns(
    rewards: jax.Array,
    dones: jax.Array,
    bootstrap_value: jax.Array,
    gamma: float,
) -> jax.Array:
    """Monte-Carlo returns G_t = r_t + γ·(1-d_t)·G_{t+1}, bootstrapped."""

    def step(g_next, x):
        r, d = x
        g = r + gamma * (1.0 - d) * g_next
        return g, g

    _, returns = jax.lax.scan(
        step, bootstrap_value, (rewards, dones.astype(rewards.dtype)), reverse=True
    )
    return returns


def gae(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    bootstrap_value: jax.Array,
    gamma: float,
    lam: float,
) -> tuple[jax.Array, jax.Array]:
    """Generalized Advantage Estimation (the reference's GAE/λ scan).

    Args:
      rewards: [T, ...] reward at each step.
      values: [T, ...] V(s_t) under the current critic.
      dones: [T, ...] 1.0 where the episode *terminated* at step t.
      bootstrap_value: [...] V(s_T) for the state after the last step.
      gamma, lam: discount and GAE-λ.

    Returns:
      (advantages, returns) each [T, ...], with returns = advantages + values
      (the λ-return targets for the critic).
    """
    dones = dones.astype(rewards.dtype)

    def step(carry, x):
        adv_next, v_next = carry
        r, v, d = x
        nonterm = 1.0 - d
        delta = r + gamma * v_next * nonterm - v
        adv = delta + gamma * lam * nonterm * adv_next
        return (adv, v), adv

    init = (jnp.zeros_like(bootstrap_value), bootstrap_value)
    _, advantages = jax.lax.scan(step, init, (rewards, values, dones), reverse=True)
    return advantages, advantages + values


def lambda_returns(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    bootstrap_value: jax.Array,
    gamma: float,
    lam: float,
) -> jax.Array:
    """TD(λ) return targets; equals `gae(...)[1]` (kept for clarity/tests)."""
    return gae(rewards, values, dones, bootstrap_value, gamma, lam)[1]


class VTraceOutput(NamedTuple):
    vs: jax.Array  # [T, ...] V-trace value targets
    pg_advantages: jax.Array  # [T, ...] policy-gradient advantages
    clipped_rhos: jax.Array  # [T, ...] min(rho_bar, π/μ)


def vtrace(
    target_log_probs: jax.Array,
    behaviour_log_probs: jax.Array,
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    bootstrap_value: jax.Array,
    gamma: float,
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
    lam: float = 1.0,
) -> VTraceOutput:
    """V-trace targets (IMPALA; BASELINE.json:11, PAPERS.md:6).

    vs_t = V(x_t) + δ_t + γ_t·c_t·(vs_{t+1} − V(x_{t+1}))
    δ_t  = ρ_t·(r_t + γ_t·V(x_{t+1}) − V(x_t))
    ρ_t  = min(ρ̄, π(a_t|x_t)/μ(a_t|x_t)),  c_t = λ·min(c̄, π/μ)

    with γ_t = γ·(1 − done_t). With π == μ and ρ̄, c̄ → ∞ this reduces to
    the λ-return (golden-tested in tests/test_returns.py).
    """
    dones = dones.astype(rewards.dtype)
    discounts = gamma * (1.0 - dones)
    rhos = jnp.exp(
        jnp.minimum(target_log_probs - behaviour_log_probs, LOG_RATIO_CAP)
    )
    clipped_rhos = jnp.minimum(rho_bar, rhos)
    cs = lam * jnp.minimum(c_bar, rhos)

    values_tp1 = jnp.concatenate([values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_tp1 - values)

    def step(acc, x):
        delta, disc, c = x
        acc = delta + disc * c * acc
        return acc, acc

    init = jnp.zeros_like(bootstrap_value)
    _, vs_minus_v = jax.lax.scan(step, init, (deltas, discounts, cs), reverse=True)
    vs = vs_minus_v + values

    vs_tp1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_advantages = clipped_rhos * (rewards + discounts * vs_tp1 - values)
    return VTraceOutput(vs=vs, pg_advantages=pg_advantages, clipped_rhos=clipped_rhos)


def n_step_returns(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    bootstrap_value: jax.Array,
    gamma: float,
    n: int,
) -> jax.Array:
    """n-step truncated returns G_t = Σ_{k<m} γ^k r_{t+k} + γ^m V(s_{t+m}),
    where m = min(n, T−t) and the sum stops at episode terminations.

    `values[t]` is V(s_t); `bootstrap_value` is V(s_T). O(T·n) with static
    n (small), fully branchless so it vmaps/jits cleanly.
    """
    T = rewards.shape[0]
    dones = dones.astype(rewards.dtype)
    vals_ext = jnp.concatenate([values, bootstrap_value[None]], axis=0)

    def single(t_idx):
        g = jnp.zeros_like(bootstrap_value)
        alive = jnp.ones_like(bootstrap_value)
        disc = 1.0
        for k in range(n):
            idx = jnp.minimum(t_idx + k, T - 1)
            valid = ((t_idx + k) < T).astype(rewards.dtype)
            g = g + disc * alive * valid * rewards[idx]
            alive = alive * (1.0 - dones[idx] * valid)
            disc = disc * gamma
        m = jnp.minimum(n, T - t_idx)
        boot_idx = jnp.minimum(t_idx + n, T)
        g = g + (gamma**m) * alive * vals_ext[boot_idx]
        return g

    return jax.vmap(single)(jnp.arange(T))


def normalize_advantages(
    advantages: jax.Array, axis_name=None, eps: float = 1e-8
) -> jax.Array:
    """Standard PPO advantage normalization over all leading axes.

    Under a dp `shard_map`, pass `axis_name` so the statistics are
    computed over the GLOBAL batch (pmean of mean and second moment) —
    otherwise per-shard stats would silently break the
    sharded-grad == full-batch-grad equivalence (tests/test_parallel.py).
    """
    mean = jnp.mean(advantages)
    sq = jnp.mean(advantages**2)
    if axis_name is not None:
        mean = jax.lax.pmean(mean, axis_name)
        sq = jax.lax.pmean(sq, axis_name)
    var = jnp.maximum(sq - mean**2, 0.0)
    return (advantages - mean) / (jnp.sqrt(var) + eps)
