"""Pallas TPU kernels for the hot trajectory scans (GAE, λ-returns, V-trace).

The fused trainers spend their non-matmul time in `lax.scan(reverse=True)`
over T with tiny per-step VPU work (ops/returns.py). These kernels run the
ENTIRE reverse time loop inside one Pallas program instead: the [T, E]
inputs for a block of environments sit in VMEM, the sequential recurrence
walks T in-kernel, and the env batch is tiled across the 128-lane axis —
one kernel launch, three input streams read once, two outputs written
once, no per-step XLA loop overhead (pallas_guide.md: Grid/BlockSpec,
Control Flow).

Env batches that are not a multiple of the 128-lane Mosaic tile are
zero-padded on the env axis before the launch and sliced back after: each
env column is an independent recurrence, so padded lanes compute junk that
is finite (all-zero inputs) and discarded. Only a T too long for any
VMEM-resident tile still falls back to lax.scan.

Numerics match `ops.returns.gae` / `ops.returns.vtrace` exactly (same
recurrences, f32 accumulation; golden-tested in tests/test_pallas_scan.py
via interpret mode on CPU and compiled on TPU).

Autodiff note: these are forward-only kernels. All trainers compute
advantage targets from rollout-time values with no gradient flowing
through the scan, so no custom VJP is defined; differentiating through
them raises, which is the desired loud failure.

Reference parity: the reference computes GAE on host NumPy per rollout
(SURVEY.md §3.1 [RECON]; reference mount empty at survey, §0) — there is
nothing to cite; this is the TPU-native replacement.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from actor_critic_tpu.ops import returns as _returns

# Preferred lane-axis tile for the env batch (4 VPU lane groups per row
# op); `_pick_block` shrinks it whenever T × tile would blow the VMEM
# budget, and extreme T falls back to the lax.scan implementation.
_DEFAULT_BLOCK_E = 512


def _use_interpret() -> bool:
    # Compiled Mosaic kernels need a real TPU; everywhere else (CPU test
    # mesh, debugging) the interpreter gives identical semantics.
    return jax.default_backend() not in ("tpu", "axon")


# Stay well under the ~16 MB/core VMEM so inputs, outputs, and carries
# coexist with pipeline double-buffering.
_VMEM_BUDGET_BYTES = 10 * 2**20


# Live (T, be) f32 blocks per op: inputs + outputs + carries.
# "lambda" rides the GAE kernel (same streams; the advantage output is
# simply discarded), so it prices identically.
_N_ARRAYS = {"gae": 7, "lambda": 7, "vtrace": 11}


def kernel_block(op: str, T: int, E: int, block_envs: int = _DEFAULT_BLOCK_E) -> int:
    """The env-lane tile the `op` ("gae" | "lambda" | "vtrace") kernel
    would use on a [T, E] f32 batch — 0 means the call would silently fall
    back to the lax.scan reference (T too long for any VMEM-resident tile;
    ragged/small E no longer falls back, it is lane-padded to the next
    128 multiple first). Public so benches and tests can ASSERT the kernel
    actually engages before attributing a measurement to it."""
    return _pick_block(E, block_envs, T, _N_ARRAYS[op])


def _pad_env(E: int) -> int:
    """E rounded up to the 128-lane f32 Mosaic tile the kernels run on."""
    return max(-(-E // 128) * 128, 128)


def _pick_block(E: int, block_e: int, T: int, n_arrays: int) -> int:
    """Env-lane tile that (a) divides the LANE-PADDED env batch (`_pad_env`
    — ragged E is zero-padded before launch, so the tile never sees a
    partial block), (b) is a multiple of the 128-lane f32 Mosaic tile, and
    (c) keeps n_arrays live (T, be) f32 blocks inside the VMEM budget.
    Returns 0 if no such tile exists (caller falls back to lax.scan)."""
    Ep = _pad_env(E)
    max_be = _VMEM_BUDGET_BYTES // (max(T, 1) * 4 * n_arrays)
    b = (min(block_e, Ep, max(max_be, 0)) // 128) * 128
    while b >= 128 and Ep % b:
        b -= 128
    return b if b >= 128 else 0


def _pad_lanes(Ep: int, *arrays: jax.Array) -> list[jax.Array]:
    """Zero-pad the trailing env axis of each [T, E] / [1, E] array to Ep
    lanes. Zeros are safe: every kernel recurrence is independent per env
    column, and all-zero inputs produce finite (all-zero or rho=1) junk in
    the padded lanes, which the caller slices away."""
    out = []
    for a in arrays:
        pad = Ep - a.shape[-1]
        out.append(jnp.pad(a, ((0, 0), (0, pad))) if pad else a)
    return out


def _gae_kernel(gamma, lam, r_ref, v_ref, d_ref, b_ref, adv_ref, ret_ref):
    T = r_ref.shape[0]

    def body(i, carry):
        adv, v_next = carry
        t = T - 1 - i
        r = r_ref[pl.ds(t, 1), :]
        v = v_ref[pl.ds(t, 1), :]
        nonterm = 1.0 - d_ref[pl.ds(t, 1), :]
        delta = r + gamma * v_next * nonterm - v
        adv = delta + gamma * lam * nonterm * adv
        adv_ref[pl.ds(t, 1), :] = adv
        ret_ref[pl.ds(t, 1), :] = adv + v
        return adv, v

    boot = b_ref[:]
    jax.lax.fori_loop(0, T, body, (jnp.zeros_like(boot), boot))


def gae(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    bootstrap_value: jax.Array,
    gamma: float,
    lam: float,
    *,
    block_envs: int = _DEFAULT_BLOCK_E,
) -> tuple[jax.Array, jax.Array]:
    """Drop-in for `ops.returns.gae` on [T, E] f32 batches via one Pallas
    kernel; any other shape/dtype falls back to the lax.scan version."""
    if rewards.ndim != 2 or rewards.dtype != jnp.float32:
        return _returns.gae(rewards, values, dones, bootstrap_value, gamma, lam)
    T, E = rewards.shape
    be = _pick_block(E, block_envs, T, _N_ARRAYS["gae"])  # 3 in + 2 out + 2 carry
    if be == 0:  # T too long for any VMEM-resident tile
        return _returns.gae(rewards, values, dones, bootstrap_value, gamma, lam)
    Ep = _pad_env(E)
    rewards, values, dones, boot = _pad_lanes(
        Ep,
        rewards,
        values,
        dones.astype(jnp.float32),
        bootstrap_value.reshape(1, E),
    )

    kernel = functools.partial(_gae_kernel, float(gamma), float(lam))
    row = lambda i: (0, i)  # block i owns rows [0,T), env cols [i*be,(i+1)*be)
    adv, ret = pl.pallas_call(
        kernel,
        grid=(Ep // be,),
        in_specs=[
            pl.BlockSpec((T, be), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((T, be), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((T, be), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, be), row, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((T, be), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((T, be), row, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, Ep), jnp.float32),
            jax.ShapeDtypeStruct((T, Ep), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(rewards, values, dones, boot)
    return (adv[:, :E], ret[:, :E]) if Ep != E else (adv, ret)


def lambda_returns(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    bootstrap_value: jax.Array,
    gamma: float,
    lam: float,
    *,
    block_envs: int = _DEFAULT_BLOCK_E,
) -> jax.Array:
    """Drop-in for `ops.returns.lambda_returns` via the GAE kernel — the
    λ-return IS the GAE return plane (adv + V), so the same fused program
    serves both; the advantage output is discarded."""
    if rewards.ndim != 2 or rewards.dtype != jnp.float32:
        return _returns.lambda_returns(
            rewards, values, dones, bootstrap_value, gamma, lam
        )
    return gae(
        rewards, values, dones, bootstrap_value, gamma, lam,
        block_envs=block_envs,
    )[1]


def gae_auto(*args, **kwargs):
    """`gae` via the Pallas kernel on real TPU backends, via `lax.scan`
    everywhere else (interpret mode is only for tests/debugging — a
    Python-interpreted time loop inside a training loop would crawl).
    The trainers import this as their single GAE entry point.

    Advantage/return targets are gradient-CONSTANTS in every consumer
    (the losses stop_gradient them at use), so inputs are detached here;
    that also keeps JAX from attempting to linearize through the
    forward-only kernel when an input (e.g. truncation-bootstrapped
    rewards) happens to carry a gradient path."""
    if _use_interpret():
        return _returns.gae(*args, **kwargs)
    return gae(*map(_detach, args), **kwargs)


def lambda_returns_auto(*args, **kwargs):
    """`lambda_returns` with the same backend dispatch (and input detach
    rationale) as `gae_auto`."""
    if _use_interpret():
        return _returns.lambda_returns(*args, **kwargs)
    return lambda_returns(*map(_detach, args), **kwargs)


def vtrace_auto(*args, **kwargs):
    """`vtrace` with the same backend dispatch (and input detach
    rationale) as `gae_auto`."""
    if _use_interpret():
        return _returns.vtrace(*args, **kwargs)
    return vtrace(*map(_detach, args), **kwargs)


def _detach(x):
    # Arrays/tracers only — scalar hyperparameters stay Python floats so
    # the kernels can bake them in as compile-time constants.
    return jax.lax.stop_gradient(x) if isinstance(x, (jax.Array, jnp.ndarray)) else x


def _vtrace_kernel(
    gamma, rho_bar, c_bar, lam,
    tlp_ref, blp_ref, r_ref, v_ref, d_ref, b_ref,
    vs_ref, pg_ref, rho_ref,
):
    T = tlp_ref.shape[0]

    def body(i, carry):
        acc, v_next, vs_next = carry
        t = T - 1 - i
        # Same LOG_RATIO_CAP as the lax reference — the kernel/fallback
        # parity contract requires the capped ratio on both sides.
        raw_rho = jnp.exp(jnp.minimum(
            tlp_ref[pl.ds(t, 1), :] - blp_ref[pl.ds(t, 1), :],
            _returns.LOG_RATIO_CAP,
        ))
        rho = jnp.minimum(rho_bar, raw_rho)
        # c clips the RAW ratio (independent of rho_bar) — matters when
        # c_bar > rho_bar (golden: ops/returns.vtrace).
        c = lam * jnp.minimum(c_bar, raw_rho)
        r = r_ref[pl.ds(t, 1), :]
        v = v_ref[pl.ds(t, 1), :]
        disc = gamma * (1.0 - d_ref[pl.ds(t, 1), :])
        delta = rho * (r + disc * v_next - v)
        acc = delta + disc * c * acc
        vs = acc + v
        vs_ref[pl.ds(t, 1), :] = vs
        pg_ref[pl.ds(t, 1), :] = rho * (r + disc * vs_next - v)
        rho_ref[pl.ds(t, 1), :] = rho
        return acc, v, vs

    boot = b_ref[:]
    jax.lax.fori_loop(0, T, body, (jnp.zeros_like(boot), boot, boot))


def vtrace(
    target_log_probs: jax.Array,
    behaviour_log_probs: jax.Array,
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    bootstrap_value: jax.Array,
    gamma: float,
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
    lam: float = 1.0,
    *,
    block_envs: int = _DEFAULT_BLOCK_E,
) -> _returns.VTraceOutput:
    """Drop-in for `ops.returns.vtrace` on [T, E] f32 batches via one
    Pallas kernel; other shapes/dtypes fall back to the lax.scan version."""
    if rewards.ndim != 2 or rewards.dtype != jnp.float32:
        return _returns.vtrace(
            target_log_probs, behaviour_log_probs, rewards, values, dones,
            bootstrap_value, gamma, rho_bar, c_bar, lam,
        )
    T, E = rewards.shape
    be = _pick_block(E, block_envs, T, _N_ARRAYS["vtrace"])  # 5 in + 3 out + 3 carry
    if be == 0:  # T too long for any VMEM-resident tile
        return _returns.vtrace(
            target_log_probs, behaviour_log_probs, rewards, values, dones,
            bootstrap_value, gamma, rho_bar, c_bar, lam,
        )
    Ep = _pad_env(E)
    tlp, blp, rewards, values, dones, boot = _pad_lanes(
        Ep,
        target_log_probs,
        behaviour_log_probs,
        rewards,
        values,
        dones.astype(jnp.float32),
        bootstrap_value.reshape(1, E),
    )

    kernel = functools.partial(
        _vtrace_kernel, float(gamma), float(rho_bar), float(c_bar), float(lam)
    )
    row = lambda i: (0, i)
    spec = pl.BlockSpec((T, be), row, memory_space=pltpu.VMEM)
    vs, pg, rho = pl.pallas_call(
        kernel,
        grid=(Ep // be,),
        in_specs=[spec] * 5 + [pl.BlockSpec((1, be), row, memory_space=pltpu.VMEM)],
        out_specs=[spec] * 3,
        out_shape=[jax.ShapeDtypeStruct((T, Ep), jnp.float32)] * 3,
        interpret=_use_interpret(),
    )(tlp, blp, rewards, values, dones, boot)
    if Ep != E:
        vs, pg, rho = vs[:, :E], pg[:, :E], rho[:, :E]
    return _returns.VTraceOutput(vs=vs, pg_advantages=pg, clipped_rhos=rho)
