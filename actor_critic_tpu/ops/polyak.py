"""Target-network utilities (DDPG/TD3/SAC; BASELINE.json:9-10)."""

from __future__ import annotations

import jax


def polyak_update(online_params, target_params, tau: float):
    """target ← (1−τ)·target + τ·online, elementwise over the pytree.

    τ is the *update* rate (e.g. 0.005), matching the DDPG/SAC convention.
    Pure function: callers re-bind the returned pytree (donation-friendly).
    """
    return jax.tree.map(
        lambda o, t: (1.0 - tau) * t + tau * o, online_params, target_params
    )


def hard_update(online_params, target_params):
    """target ← online (periodic hard sync, DQN-style). Returns the online
    pytree itself — JAX arrays are immutable, no copy is needed."""
    del target_params
    return online_params
