"""JSONL metrics logging (SURVEY.md §5.5).

The reference genre prints episode returns and writes TensorBoard
scalars via `tf.summary` (reference mount empty at survey, SURVEY.md
§0). The TPU build's primary sink is a machine-readable `metrics.jsonl`:
one JSON object per logging step with the iteration, wall-clock, env
steps, and every scalar the trainer reported. Metric values arrive as
device arrays already aggregated on-device (algos/metrics.py) — exactly
one host transfer per logged iteration.

TensorBoard export stays available two ways: convert JSONL afterwards
with `scripts/tb_export.py`, or pass `tensorboard_dir` here for live
writing (uses tf.summary lazily; gated so the framework never
hard-depends on TF).
"""

from __future__ import annotations

import os
import time
from typing import IO, Optional

from actor_critic_tpu.utils.cadence import finite_or_none
from actor_critic_tpu.utils.numguard import safe_json_row


class JsonlLogger:
    """Append-only JSONL metrics writer with optional stdout echo."""

    def __init__(
        self,
        path: Optional[str | os.PathLike] = "metrics.jsonl",
        echo: bool = False,
        tensorboard_dir: Optional[str] = None,
    ):
        self._fh: Optional[IO[str]] = None
        if path is not None:
            parent = os.path.dirname(os.fspath(path))
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._fh = open(path, "a", buffering=1)
        self._echo = echo
        self._t0 = time.time()
        self._tb = None
        if tensorboard_dir is not None:
            import tensorflow as tf  # installed; only imported on request

            self._tb = tf.summary.create_file_writer(tensorboard_dir)

    def log(self, iteration: int, metrics: dict, **extra) -> None:
        row = {
            "iter": int(iteration),
            "wall_s": round(time.time() - self._t0, 3),
        }
        for k, v in {**metrics, **extra}.items():
            if isinstance(v, (dict, list, tuple)):
                # Structured extras pass through as JSON containers;
                # safe_json_row scrubs any non-finite floats inside.
                row[k] = v
                continue
            try:
                float(v)
            except (TypeError, ValueError):
                row[k] = str(v)  # non-numeric values stringify
            else:
                # Numeric: non-finite floats become null (NaN/Inf are not
                # valid strict JSON) via the shared scrub.
                row[k] = finite_or_none(v)
        if self._fh is not None:
            # Belt (finite_or_none above) AND suspenders: extra values
            # injected through **extra can nest containers the scrub
            # above never saw; safe_json_row keeps the row serializable.
            self._fh.write(safe_json_row(row) + "\n")
        if self._echo:
            short = ", ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in row.items()
                if k != "wall_s"
            )
            print(f"[{row['wall_s']:9.1f}s] {short}", flush=True)
        if self._tb is not None:
            import tensorflow as tf

            with self._tb.as_default():
                for k, v in row.items():
                    # Integer scalars (iter, env_steps, episodes_finished)
                    # must export too — an isinstance(v, float) gate
                    # silently dropped them; bool is excluded (it passes
                    # an int check but isn't a scalar metric).
                    if isinstance(v, (int, float)) and not isinstance(v, bool):
                        tf.summary.scalar(k, float(v), step=int(iteration))

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
