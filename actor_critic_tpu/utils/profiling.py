"""Tracing / profiling / numerics-guard harness (SURVEY.md §5.1-5.2).

The reference genre's observability is TensorBoard scalar timings
[RECON; reference mount empty at survey, SURVEY.md §0]. The TPU build's
tools, in one place:

- `trace(logdir)`: profiler context producing TensorBoard/Perfetto
  traces of the XLA programs inside (view with `tensorboard --logdir` or
  ui.perfetto.dev).
- `named_scope`: re-export of `jax.named_scope` — trainers annotate loss
  terms so traces/HLO carry readable op names.
- `time_fn(fn, *args)`: dispatch-overhead-aware timing: warmup (compile)
  + `block_until_ready` fencing, returns seconds/call.
- `nan_guard(tree, name)`: jittable non-finite detector for dev runs —
  emits a host-side warning via `jax.debug.callback` (XLA has no cheap
  device-side abort; `jax.config.update("jax_debug_nans", True)` is the
  heavyweight alternative).
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp

named_scope = jax.named_scope

_log = logging.getLogger(__name__)


def start_trace(logdir: str) -> None:
    """Begin a profiler capture into `logdir` (Perfetto trace included).
    Split out of `trace` so windowed captures that cannot hold a context
    manager open across loop iterations (telemetry/profiler.py's
    on-demand `/profile?iters=N` window) share the same configuration."""
    jax.profiler.start_trace(logdir, create_perfetto_trace=True)


def stop_trace() -> None:
    """End the capture `start_trace` opened."""
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(logdir: str):
    """`with trace("runs/prof"):` around the iterations to profile."""
    start_trace(logdir)
    try:
        yield
    finally:
        stop_trace()


def time_fn(
    fn: Callable[..., Any],
    *args: Any,
    iters: int = 10,
    warmup: int = 2,
) -> float:
    """Mean seconds per `fn(*args)` call with device-completion fencing.

    `fn` should be jitted (or cheap); the warmup calls absorb compilation.
    All `iters` timed calls are dispatched back-to-back and fenced once —
    the per-call dispatch overhead is real throughput overhead, but a
    fence per call would measure tunnel latency instead of device time.
    """
    out = None
    for _ in range(max(warmup, 1)):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def nan_guard(tree: Any, name: str = "value") -> None:
    """Inside jit: log a host-side warning if any leaf has a non-finite
    element. Zero device-side control flow — one fused all-finite
    reduction plus a debug callback."""
    leaves = [x for x in jax.tree.leaves(tree) if jnp.issubdtype(
        jnp.asarray(x).dtype, jnp.floating)]
    if not leaves:
        return
    finite = jnp.all(
        jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves])
    )

    def _warn(ok):
        if not bool(ok):
            _log.warning("nan_guard: non-finite values detected in %s", name)

    jax.debug.callback(_warn, finite)
