"""Compile-once subsystem (ISSUE 4): persistent XLA compilation cache,
background AOT warmup, and shape-stabilized entry points.

Every cold start — and every `run_resumable.sh` retry leg — used to pay
full XLA compile before the first env step, and the PR 3 compile
listener could *name* a recompile storm but nothing prevented one. This
module is the prevention layer, three parts:

1. **Persistent compilation cache** (`enable_persistent_cache`): JAX's
   on-disk executable cache (`jax_compilation_cache_dir`) with the
   min-compile-time/min-entry-size floors dropped to zero so every
   program is cached. `train.py --compile-cache-dir` wires it; the
   default is a sidecar under the checkpoint dir (`<ckpt>/xla_cache`) so
   the legs of one resumable run share it. Hit/miss counts ride the
   `jax.monitoring` cache events into `cache_stats()` (exported at
   `/metrics`, attributed per-function in `run_report.py`).

2. **AOT warmup registry** (`register_warmup` / `start_warmup`): each
   jitted entry point in `algos/` registers a *planner* that derives the
   entry's abstract argument shapes from the env spec + config (via
   `jax.eval_shape`, no device allocation) and returns a thunk that
   `.lower(...).compile()`s it. `start_warmup` runs every applicable
   thunk on a background daemon thread while the env pool spawns/resets
   and the checkpoint restores, so time-to-first-step hides compile
   instead of serializing on it. Compiled executables land in the
   persistent cache; the training loop's own first dispatch then
   re-traces and *hits* the cache instead of compiling.
   `scripts/check_warmup_registry.py` (tier-1, via
   tests/test_warmup_registry.py) fails when a `jax.jit` entry point in
   `algos/` or `models/` is neither registered here nor listed in
   `EXEMPT` with a reason.

3. **Shape stabilization** (`make_chunked_step`, `pad_to_bucket`): the
   recompile sources the PR 3 attribution table exposed were variable
   *static* shapes — chiefly the chunked fused loop's tail/realignment
   dispatches, where every distinct k was its own XLA program. Partial
   chunks are now padded to the full-stride bucket and cut with an
   `n_valid` validity mask (a traced scalar), so a chunked run compiles
   exactly TWO programs (full + masked bucket) no matter how it is
   resumed or where it ends. `pad_to_bucket` is the generic batch-axis
   version for host-side callers that would otherwise feed a jitted
   entry point a ragged tail batch. Audit note: the fused eval program
   already masks episode tails in-shape (`common.evaluate`'s `alive`
   mask) and host pools always deliver full `[K, E]` blocks, so those
   paths carry no variable shapes to stabilize.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from functools import partial
from typing import Any, Callable, Optional

# ---------------------------------------------------------------------------
# Persistent compilation cache
# ---------------------------------------------------------------------------

# Process-global hit/miss counters fed by jax.monitoring's cache events.
# Like the telemetry compile counter, listeners cannot be unregistered,
# so registration is once-per-process and the counts only grow.
_CACHE_STATS = {"hits": 0, "misses": 0}
_stats_lock = threading.Lock()
_stats_installed = False
_enabled_dir: Optional[str] = None


def _on_cache_event(name: str, **kwargs) -> None:
    # Cache events fire from whichever thread compiles — the AOT warmup
    # runner overlaps the training thread — and an unlocked += on the
    # shared counters loses increments. Events are rare; the lock is
    # noise-level.
    if name.endswith("/cache_hits"):
        with _stats_lock:
            _CACHE_STATS["hits"] += 1
    elif name.endswith("/cache_misses"):
        with _stats_lock:
            _CACHE_STATS["misses"] += 1


def ensure_cache_stats_listener() -> bool:
    """Idempotently hook the persistent-cache hit/miss event stream."""
    global _stats_installed
    with _stats_lock:
        if _stats_installed:
            return True
        try:
            import jax.monitoring

            jax.monitoring.register_event_listener(_on_cache_event)
            _stats_installed = True
        except Exception:
            return False  # telemetry must never take a run down
        return True


def cache_stats() -> dict:
    """{'hits', 'misses'} of the persistent compilation cache since the
    listener was installed (zeros when the cache was never enabled)."""
    return dict(_CACHE_STATS)


def enabled_dir() -> Optional[str]:
    """The cache directory this process enabled, or None."""
    return _enabled_dir


def enable_persistent_cache(cache_dir: str | os.PathLike) -> str:
    """Point JAX's persistent compilation cache at `cache_dir` (created
    if absent) with the caching floors at zero, so EVERY compiled
    program is written and a later process (or a post-`clear_caches`
    re-trace in this one) deserializes instead of recompiling. Returns
    the absolute directory. Safe to call more than once; the last
    directory wins."""
    global _enabled_dir
    import jax

    cache_dir = os.path.abspath(os.fspath(cache_dir))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Floors down: the default 1s/min-size floors exist to avoid caching
    # trivial programs, but here the whole point is that leg N+1 skips
    # even the small compiles (dozens of sub-second utility jits add up
    # on a 1-core host).
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # flag spelling varies across jax versions; the dir + time
        # floor are the load-bearing settings
    _reset_jax_cache_state()
    ensure_cache_stats_listener()
    _enabled_dir = cache_dir
    return cache_dir


def _reset_jax_cache_state() -> None:
    """Drop jax's internal cache latches. `is_cache_used` and the cache
    handle are evaluated ONCE per process at the first compile — a
    process that compiled anything before `enable_persistent_cache`
    (test suites, import-time jits) would silently keep the cache
    disabled forever without this. Best-effort internal API."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass


class temporary_cache:
    """Context manager: enable the persistent cache at `cache_dir`, then
    restore the previous configuration on exit (for tests and benches —
    `train.py` uses the one-shot `enable_persistent_cache`)."""

    def __init__(self, cache_dir: str | os.PathLike):
        self._dir = cache_dir

    def __enter__(self) -> str:
        import jax

        self._prev = jax.config.jax_compilation_cache_dir
        self._prev_floors = {}
        for flag in ("jax_persistent_cache_min_compile_time_secs",
                     "jax_persistent_cache_min_entry_size_bytes"):
            try:
                self._prev_floors[flag] = getattr(jax.config, flag)
            except AttributeError:
                pass
        self._prev_enabled = _enabled_dir
        return enable_persistent_cache(self._dir)

    def __exit__(self, *exc) -> None:
        global _enabled_dir
        import jax

        jax.config.update("jax_compilation_cache_dir", self._prev)
        # The caching floors are process-global too — a caller with its
        # own cache configured must get its floors back, not keep the
        # cache-everything zeros.
        for flag, value in self._prev_floors.items():
            try:
                jax.config.update(flag, value)
            except Exception:
                pass
        # Re-latch from the restored config so later compiles in this
        # process don't keep using (or skipping) the temporary dir.
        _reset_jax_cache_state()
        _enabled_dir = self._prev_enabled


def resolve_cache_dir(
    cli_value: Optional[str], ckpt_dir: Optional[str]
) -> Optional[str]:
    """`--compile-cache-dir` policy: an explicit path wins; the default
    'auto' resolves to a `<ckpt-dir>/xla_cache` sidecar (so the legs of
    one `run_resumable.sh` run share a cache) or to disabled when the
    run has no checkpoint dir; 'none'/'off'/'' disable explicitly."""
    if cli_value is None or cli_value.lower() == "auto":
        return os.path.join(ckpt_dir, "xla_cache") if ckpt_dir else None
    if cli_value.lower() in ("", "none", "off"):
        return None
    return cli_value


# ---------------------------------------------------------------------------
# Shape stabilization
# ---------------------------------------------------------------------------

def bucket_size(n: int, buckets: tuple[int, ...]) -> int:
    """The smallest bucket >= n (buckets need not be sorted). Raises when
    n exceeds every bucket — a silent overflow would recompile, the exact
    failure this module exists to prevent."""
    if n < 0:
        raise ValueError(f"n must be >= 0, got {n}")
    fitting = [b for b in buckets if b >= n]
    if not fitting:
        raise ValueError(f"n={n} exceeds every bucket in {sorted(buckets)}")
    return min(fitting)


def pad_to_bucket(x, buckets: tuple[int, ...], axis: int = 0):
    """Zero-pad `x` along `axis` to the smallest fitting bucket size;
    returns (padded, valid_mask) where `valid_mask` is float32 [bucket]
    with 1.0 on real rows. Feeding jitted entry points bucketed batches
    instead of ragged tails bounds the distinct compiled programs to
    len(buckets) — pair with a masked reduction on the consumer side."""
    import numpy as np

    x = np.asarray(x)
    n = x.shape[axis]
    b = bucket_size(n, buckets)
    mask = np.zeros(b, np.float32)
    mask[:n] = 1.0
    if b == n:
        return x, mask
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, b - n)
    return np.pad(x, widths), mask


def make_chunked_step(raw_step: Callable, stride: int) -> Callable:
    """Shape-stabilized chunked dispatch: `(state, k) -> (state, metrics)`
    advancing k <= stride iterations of `raw_step` in ONE device
    program.

    Exactly two XLA programs ever compile, regardless of resume point or
    iteration count: the full-stride scan (the steady-state hot path,
    zero masking overhead) and ONE masked bucket for partial chunks —
    the tail/realignment dispatch is padded to the full stride and cut
    with a traced `n_valid` scalar, so every distinct partial k reuses
    the same executable (the old static-k design compiled a fresh
    program per distinct tail, the top recompile source in PR 3's
    attribution table). The masked program applies `raw_step` only to
    the first `n_valid` scan slots (the carry is held constant after),
    so results are bit-for-bit those of k sequential steps; metrics are
    the LAST VALID iteration's slice, matching the per-iteration loop's
    point-in-time logging semantics.
    """
    import jax
    import jax.numpy as jnp

    if stride < 1:
        raise ValueError(f"stride must be >= 1, got {stride}")

    @partial(jax.jit, donate_argnums=0)
    def full(s):
        s, ms = jax.lax.scan(lambda c, _: raw_step(c), s, None, length=stride)
        return s, jax.tree.map(lambda x: x[-1], ms)

    @partial(jax.jit, donate_argnums=0)
    def masked(s, n_valid):
        def body(c, i):
            new_c, m = raw_step(c)
            # cond lowers to select inside scan and round-trips typed
            # PRNG-key leaves (jnp.where on extended dtypes does not).
            c = jax.lax.cond(
                i < n_valid, lambda a, b: a, lambda a, b: b, new_c, c
            )
            return c, m
        s, ms = jax.lax.scan(body, s, jnp.arange(stride))
        last = jnp.maximum(n_valid, 1) - 1
        return s, jax.tree.map(lambda x: x[last], ms)

    def step(s, k: int):
        if k >= stride:
            return full(s)
        return masked(s, jnp.asarray(k, jnp.int32))

    # Exposed for AOT warmup (the registry compiles both programs with
    # abstract state so the run's first dispatch hits the cache).
    step.full = full
    step.masked = masked
    return step


# ---------------------------------------------------------------------------
# AOT warmup registry
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class WarmupContext:
    """Everything a planner needs to derive an entry point's abstract
    argument shapes for THIS run: the resolved algo/env/config plus the
    CLI knobs that change which programs will execute (chunking, eval
    cadence, overlap mirroring, resume)."""

    algo: str            # resolved preset algo (td3/a3c keep their alias)
    fused: bool          # jax:* fused trainer vs host pool
    spec: Any            # EnvSpec (env.spec / pool.spec)
    cfg: Any             # the algo's frozen config dataclass
    env: Any = None      # the JaxEnv (fused runs only)
    chunk: int = 1       # --chunk (fused runs)
    iterations: int = 0  # --iterations (tail-chunk prediction)
    eval_every: int = 0  # --eval-every (eval programs compile only if on)
    eval_envs: int = 4   # --eval-envs (host eval pool batch)
    overlap: bool = True  # host loops: numpy actor mirror enabled
    resume: bool = False  # --resume (realignment chunks possible)
    # Async actor–learner decoupling (ISSUE 6): actor count (0 =
    # lockstep) and the learner's staleness correction — together they
    # decide WHICH update program runs and at what [K, E_a] block shape
    # (E_a = num_envs // async_actors).
    async_actors: int = 0
    async_correction: str = "vtrace"
    # Device-resident data plane (ISSUE 13): "device" stages trajectory
    # blocks in a donated HBM ring (data_plane/ring.py) and the learner
    # gathers+decodes in-jit — a different update program (and an
    # enqueue program) than the host plane's, at the same block shapes.
    # plane_codec picks the ring's per-key quantize codecs; queue_depth
    # sizes the ring the warmup's abstract state must match.
    data_plane: str = "host"
    plane_codec: str = "fp32"
    queue_depth: int = 4
    # Policy-serving gateway (ISSUE 10): non-empty bucket sizes put the
    # context in SERVING mode — plan_warmup then runs only the planners
    # registered with `register_warmup(..., serving=True)` (the serving
    # act programs), and none of the training planners: a gateway
    # process must not spend startup compiling update programs it will
    # never dispatch. serving_sample picks the stochastic act program
    # over the greedy one.
    serving_buckets: tuple[int, ...] = ()
    serving_sample: bool = False


# name -> planner(ctx) -> Optional[() -> None].  A planner returns None
# when its entry point will not run under this context (wrong algo, host
# entry on a fused run, mirror-covered acting path, eval disabled ...).
# jaxlint: thread-owned=import (populated only by @register_warmup
# decorators running at module-import time under the import lock; the
# warmup thread and the registry lint only read it afterwards)
_REGISTRY: dict[str, Callable[[WarmupContext], Optional[Callable]]] = {}

# Planners that belong to the SERVING side of the registry (registered
# with `register_warmup(..., serving=True)`): plan_warmup runs exactly
# one side per context — serving planners for a gateway context
# (ctx.serving_buckets non-empty), training planners otherwise.
# jaxlint: thread-owned=import (same import-time population as _REGISTRY)
_SERVING_PLANNERS: set[str] = set()

# jax.jit sites in algos//models/ that the lint must NOT require a
# registration for, with the reason a reviewer needs. Keys are
# "<module>.<enclosing function>" as scripts/check_warmup_registry.py
# derives them.
EXEMPT: dict[str, str] = {
    "host_loop.fused_train_loop":
        "loop driver jitting the step passed in; warmed via the "
        "per-algo <algo>.make_train_step registration",
    "host_loop.off_policy_train_host":
        "jits the per-algo make_greedy_act factory, registered as "
        "<algo>.make_greedy_act",
    "ppo.train_host":
        "jits ppo.make_greedy_act, registered under that name",
    "impala.make_sp_update":
        "mesh-sharded multi-device program; built only by the explicit "
        "parallel drivers, outside train.py's warmup scope",
    "impala.make_sp_train_step":
        "mesh-sharded multi-device program; built only by the explicit "
        "parallel drivers, outside train.py's warmup scope",
}


def register_warmup(name: str, serving: bool = False):
    """Decorator: register `planner(ctx) -> thunk | None` under `name`
    ("<module>.<factory>", the key the registry lint checks).
    `serving=True` marks the planner as belonging to the serving side
    of the registry (see _SERVING_PLANNERS)."""

    def deco(planner):
        _REGISTRY[name] = planner
        if serving:
            _SERVING_PLANNERS.add(name)
        return planner

    return deco


def registered_warmups() -> tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


def plan_warmup(ctx: WarmupContext) -> list[tuple[str, Callable]]:
    """(name, compile-thunk) for every registered entry point applicable
    to this run. Planner errors are contained per entry — warmup is an
    optimization and must never take the run down — but NOT silent: a
    planner that raises (e.g. a factory signature drifted under it)
    leaves a stderr line and a `warmup_plan_error` telemetry event, so
    the entry losing its warmup is a visible regression, not a quiet
    return to first-dispatch compile."""
    import sys

    from actor_critic_tpu.telemetry import session as _session

    serving_ctx = bool(ctx.serving_buckets)
    out: list[tuple[str, Callable]] = []
    for name in sorted(_REGISTRY):
        # One registry side per context: a serving context runs only the
        # serving planners (training planners would compile update/eval
        # programs the gateway never dispatches), and vice versa.
        if (name in _SERVING_PLANNERS) != serving_ctx:
            continue
        try:
            thunk = _REGISTRY[name](ctx)
        except Exception as e:
            print(
                f"[compile_cache] warmup planner {name!r} failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr, flush=True,
            )
            try:
                _session.event(
                    "warmup_plan_error", entry=name, error=str(e)[:500]
                )
            except Exception:
                pass
            thunk = None
        if thunk is not None:
            out.append((name, thunk))
    return out


class WarmupRunner:
    """Background executor for one run's warmup plan.

    Runs each thunk on a daemon thread (XLA compilation releases the
    GIL, so it genuinely overlaps host-side env spawn/reset/restore),
    records per-entry compile wall + outcome, and emits a
    `warmup_compile` telemetry event per entry plus one `warmup_done`
    summary. `wait()` is for tests/benches; the training loop never
    joins it."""

    def __init__(self, plan: list[tuple[str, Callable]]):
        self._plan = plan
        # jaxlint: thread-owned=warmup (single writer: only the warmup
        # thread appends; benches/tests read AFTER wait() — the _done
        # Event's set/wait pair orders those appends before the read)
        self.results: list[dict] = []
        self._done = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="aot-warmup", daemon=True
        )

    def start(self) -> "WarmupRunner":
        self._thread.start()
        return self

    def _run(self) -> None:
        from actor_critic_tpu.telemetry import session as _session

        for name, thunk in self._plan:
            t0 = time.perf_counter()
            row = {"entry": name}
            try:
                thunk()
                row["compile_s"] = round(time.perf_counter() - t0, 4)
            except Exception as e:  # warmup must never take the run down
                row["error"] = str(e)[:500]
            self.results.append(row)
            try:
                _session.event("warmup_compile", **row)
            except Exception:
                pass
        self._done.set()
        try:
            _session.event(
                "warmup_done",
                entries=len(self._plan),
                errors=sum(1 for r in self.results if "error" in r),
                total_s=round(
                    sum(r.get("compile_s", 0.0) for r in self.results), 3
                ),
            )
        except Exception:
            pass

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done.is_set()


def start_warmup(ctx: WarmupContext) -> WarmupRunner:
    """Plan + launch the background AOT warmup for this run (callers
    that want to print/inspect the plan first use `plan_warmup` +
    `WarmupRunner` directly, as train.py does)."""
    return WarmupRunner(plan_warmup(ctx)).start()


# -- planner helpers (shared by the per-algo registrations) -----------------

def key_struct():
    """Abstract typed-PRNG-key scalar (ShapeDtypeStruct with key dtype)."""
    import jax

    return jax.eval_shape(lambda: jax.random.key(0))


def scalar_struct(dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct((), jnp.dtype(dtype))


def array_struct(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


def aot_compile(jitted, *args, **kwargs):
    """`.lower(...).compile()` — the compiled executable is not installed
    into the jit dispatch cache (JAX AOT contract), but with the
    persistent cache enabled the byproduct IS the cache entry the live
    dispatch will hit after its cheap re-trace."""
    return jitted.lower(*args, **kwargs).compile()


def jitted_thunk(fn: Callable, *args, **kwargs) -> Callable:
    """Warmup thunk for a function the training loop jits INLINE (e.g.
    the greedy factories): jit here, AOT-compile on call. Living in this
    module keeps the jit site out of algos/ — the registry lint scans
    there and planners must not register their own plumbing."""
    import jax

    jitted = jax.jit(fn)
    return lambda: aot_compile(jitted, *args, **kwargs)


def fused_state_struct(ctx: WarmupContext, init_state: Callable):
    """Abstract train state via eval_shape — no device allocation (a
    4096-env replay-carrying state would otherwise materialize twice)."""
    import jax

    return jax.eval_shape(
        partial(init_state, ctx.env, ctx.cfg), jax.random.key(0)
    )


def fused_step_thunk(ctx: WarmupContext, init_state: Callable,
                     make_train_step: Callable) -> Callable:
    """Warmup thunk for a fused train step under this run's dispatch
    shape: plain jit at chunk=1, else the full-stride program plus —
    only when a partial chunk can occur (tail or resume realignment) —
    the masked bucket."""
    import jax
    import jax.numpy as jnp

    state_abs = fused_state_struct(ctx, init_state)
    raw_step = make_train_step(ctx.env, ctx.cfg)
    if ctx.chunk <= 1:
        jitted = jax.jit(raw_step, donate_argnums=0)
        return lambda: aot_compile(jitted, state_abs)

    step = make_chunked_step(raw_step, ctx.chunk)
    need_masked = ctx.resume or (
        ctx.iterations > 0 and ctx.iterations % ctx.chunk != 0
    )

    def thunk():
        if ctx.iterations == 0 or ctx.iterations >= ctx.chunk:
            aot_compile(step.full, state_abs)
        if need_masked or ctx.iterations < ctx.chunk:
            aot_compile(step.masked, state_abs, scalar_struct(jnp.int32))

    return thunk


def fused_eval_thunk(ctx: WarmupContext, init_state: Callable,
                     make_eval_fn: Callable) -> Optional[Callable]:
    """Warmup thunk for the fused greedy-eval program (train.py jits it
    with static default num_envs/num_steps); None when eval is off."""
    import jax

    if ctx.eval_every <= 0:
        return None
    state_abs = fused_state_struct(ctx, init_state)
    ev = jax.jit(make_eval_fn(ctx.env, ctx.cfg), static_argnums=(2, 3))
    k = key_struct()
    return lambda: aot_compile(ev, state_abs, k)


def host_obs_struct(ctx: WarmupContext, leading: tuple[int, ...]):
    """[*leading, *obs_shape] in the dtype the pool actually delivers
    (float32, or uint8 for preserved pixel obs — host_pool casts float64
    MuJoCo obs before they reach any buffer)."""
    return array_struct((*leading, *ctx.spec.obs_shape), ctx.spec.obs_dtype)


def mirror_active(ctx: WarmupContext, params_abs) -> bool:
    """Whether the host loop will EXPLORE through the numpy mirror
    (models/host_actor) — in which case the jitted act entry point is
    constructed but never dispatched, and warming it would compile a
    program the run never runs. `supports_mirror` only inspects the
    param tree's structure, so the abstract tree suffices."""
    from actor_critic_tpu.models import host_actor

    return ctx.overlap and host_actor.supports_mirror(params_abs)


def greedy_mirror_active(params_abs) -> bool:
    """Whether host EVAL runs through the numpy mirror. Unlike exploring,
    the loops mirror eval whenever the params support it (overlap only
    gates acting), so the jitted greedy program never dispatches."""
    from actor_critic_tpu.models import host_actor

    return host_actor.supports_mirror(params_abs)


def register_fused_warmups(module: str, aliases, init_state: Callable,
                           make_train_step: Callable,
                           make_eval_fn: Callable) -> None:
    """Register the two fused-trainer entry points every algo shares:
    `<module>.make_train_step` (the per-dispatch program train.py jits —
    plain, or the chunked full+masked pair) and `<module>.make_eval_fn`
    (the greedy-eval program, when --eval-every is on)."""
    aliases = frozenset(aliases)

    @register_warmup(f"{module}.make_train_step")
    def _step(ctx):
        if not ctx.fused or ctx.algo not in aliases:
            return None
        return fused_step_thunk(ctx, init_state, make_train_step)

    @register_warmup(f"{module}.make_eval_fn")
    def _eval(ctx):
        if not ctx.fused or ctx.algo not in aliases:
            return None
        return fused_eval_thunk(ctx, init_state, make_eval_fn)


def register_offpolicy_warmups(module: str, aliases, *,
                               init_learner: Callable,
                               make_host_act_fn: Callable,
                               make_host_ingest_update: Callable,
                               make_greedy_act: Callable,
                               init_state: Callable,
                               make_train_step: Callable,
                               make_eval_fn: Callable) -> None:
    """Register the DDPG/TD3/SAC entry-point family: the host-path
    explore act / ingest+update / greedy-eval programs (skipping the
    ones the numpy mirror replaces) plus the shared fused pair."""
    aliases = frozenset(aliases)

    def _learner_abs(ctx):
        import jax

        return jax.eval_shape(
            partial(
                init_learner, tuple(ctx.spec.obs_shape),
                ctx.spec.action_dim, ctx.cfg,
            ),
            jax.random.key(0),
        )

    @register_warmup(f"{module}.make_host_act_fn")
    def _act(ctx):
        import numpy as np

        if ctx.fused or ctx.algo not in aliases or ctx.async_actors:
            return None  # async actors always act through the mirror
        actor_abs = _learner_abs(ctx).actor_params
        if mirror_active(ctx, actor_abs):
            return None  # the numpy mirror explores; never dispatched
        jitted = make_host_act_fn(ctx.spec.action_dim, ctx.cfg)
        obs = host_obs_struct(ctx, (ctx.cfg.num_envs,))
        return lambda: aot_compile(
            jitted, actor_abs, obs, key_struct(), scalar_struct(np.int32)
        )

    @register_warmup(f"{module}.make_host_ingest_update")
    def _ingest(ctx):
        import numpy as np

        if ctx.fused or ctx.algo not in aliases:
            return None
        if ctx.data_plane == "device" and ctx.async_actors:
            # ISSUE 13: the device plane dispatches
            # device_replay.make_device_ingest_update instead — the
            # argument-fed program would be a wasted warmup compile.
            return None
        from actor_critic_tpu.algos.common import OffPolicyTransition

        cfg = ctx.cfg
        # Async actor fleets feed per-actor [K, E/A] blocks (ISSUE 9
        # satellite: off-policy through ActorService); the lockstep
        # loop ingests the full [K, E] block.
        K = cfg.steps_per_iter
        E = cfg.num_envs // ctx.async_actors if ctx.async_actors else cfg.num_envs
        learner_abs = _learner_abs(ctx)
        traj = OffPolicyTransition(
            obs=host_obs_struct(ctx, (K, E)),
            action=array_struct((K, E, ctx.spec.action_dim), np.float32),
            reward=array_struct((K, E), np.float32),
            next_obs=host_obs_struct(ctx, (K, E)),
            terminated=array_struct((K, E), np.float32),
            done=array_struct((K, E), np.float32),
        )
        jitted = make_host_ingest_update(ctx.spec.action_dim, cfg)
        return lambda: aot_compile(
            jitted, learner_abs, traj, scalar_struct(np.int32)
        )

    @register_warmup(f"{module}.make_greedy_act")
    def _greedy(ctx):
        if ctx.fused or ctx.algo not in aliases or ctx.eval_every <= 0:
            return None
        actor_abs = _learner_abs(ctx).actor_params
        if greedy_mirror_active(actor_abs):
            return None  # eval mirrors on the host; never dispatched
        obs = host_obs_struct(ctx, (ctx.eval_envs,))
        return jitted_thunk(
            make_greedy_act(ctx.spec.action_dim, ctx.cfg), actor_abs, obs
        )

    register_fused_warmups(
        module, aliases, init_state, make_train_step, make_eval_fn
    )
