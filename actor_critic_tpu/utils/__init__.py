"""Shared utilities. The checkpoint re-exports resolve LAZILY (PEP 562):
`utils.checkpoint` pulls jax + orbax at import, and the jax-free modules
(`serving/policy_store.py`, `algos/traj_queue.py` — racesan's
queue/publisher exercisers depend on that) import siblings like
`utils.numguard` through this package, which must not cost them the
whole jax stack."""

from actor_critic_tpu.utils.logging import JsonlLogger

_CHECKPOINT_EXPORTS = ("Checkpointer", "checkpointed_train", "resume_or_init")

__all__ = [
    "Checkpointer",
    "JsonlLogger",
    "checkpointed_train",
    "resume_or_init",
]


def __getattr__(name):
    if name in _CHECKPOINT_EXPORTS:
        from actor_critic_tpu.utils import checkpoint

        return getattr(checkpoint, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
