from actor_critic_tpu.utils.checkpoint import (
    Checkpointer,
    checkpointed_train,
    resume_or_init,
)
from actor_critic_tpu.utils.logging import JsonlLogger

__all__ = [
    "Checkpointer",
    "JsonlLogger",
    "checkpointed_train",
    "resume_or_init",
]
