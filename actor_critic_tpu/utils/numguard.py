"""Numerics guards: finite-tree gates + NaN-safe JSON (ISSUE 14).

One non-finite value defeats every durability mechanism this repo has:
`json.dumps(..., allow_nan=False)` raises and the telemetry row is
silently dropped (the sampler/spans/session crash class), a checkpoint
commits poisoned params that every resume inherits, a published
snapshot diffuses NaN through the PR 9 gossip ring to the whole fleet,
and a gateway swap serves it to clients. This module is the ONE home of
the two counter-measures:

- **Finite-tree gates** (`check_finite` / `nonfinite_leaves`): a
  numpy-only sweep over a pytree's inexact leaves that names WHERE the
  poison sits (`params['w'][3]: nan`). The fragile sinks call it at
  their commit point — `Checkpointer.save`, `multihost.write_params`,
  `PolicyPublisher.publish`, `PolicyStore.swap` — so a poisoned tree is
  refused BEFORE it becomes durable/visible and the previous good
  snapshot stays in place. Integer/bool leaves are skipped without
  conversion (no device transfer, no false positives); denormals and
  merely-huge values pass (the gate refuses only nan/±inf — numsan's
  denormal poisoner exists to prove the gate does NOT over-fire).

- **NaN-safe JSON** (`safe_json_row`): strict-JSON serialization that
  maps non-finite floats to `null` instead of raising, and reports each
  offending key ONCE per process on stderr (a NaN loss gauge must not
  silently end resource sampling for the rest of the run — nor spam one
  line per 5 s tick). Every telemetry writer routes through here.

`analysis/numsan.py` poisons real trees against these gates (and
monkeypatches `check_finite` to a no-op to prove its detectors catch a
reverted gate); the `sink-guard` jaxlint pass statically requires the
gates' presence at the sink definitions.
"""

from __future__ import annotations

import json
import math
import sys
import threading

import numpy as np


class NonFiniteError(ValueError):
    """A finite-tree gate refused a tree carrying nan/±inf leaves."""


def _classify(v: float) -> str:
    if math.isnan(v):
        return "nan"
    return "inf" if v > 0 else "-inf"


def _walk(tree, path: str, out: list) -> None:
    if isinstance(tree, dict):
        for k, v in tree.items():
            _walk(v, f"{path}[{k!r}]", out)
        return
    if isinstance(tree, (list, tuple)):
        fields = getattr(type(tree), "_fields", None)
        for i, v in enumerate(tree):
            key = fields[i] if fields else i
            _walk(v, f"{path}.{key}" if fields else f"{path}[{i}]", out)
        return
    if isinstance(tree, (bool, int, str, bytes)) or tree is None:
        return
    if isinstance(tree, float):
        if not math.isfinite(tree):
            out.append((path, _classify(tree)))
        return
    dtype = getattr(tree, "dtype", None)
    if dtype is None:
        return
    # Integer/bool/key leaves cannot be non-finite: skip them before
    # np.asarray so a device-resident int ring never pays a transfer.
    # Unclassifiable dtypes (typed PRNG keys reaching here unpacked,
    # future extended dtypes) are skipped rather than crashing the
    # commit the gate protects.
    try:
        if not np.issubdtype(np.dtype(dtype), np.inexact):
            return
        arr = np.asarray(tree)
        finite = np.isfinite(arr)
    except TypeError:
        return
    if bool(np.all(finite)):
        return
    flat = arr.reshape(-1)
    bad = np.flatnonzero(~finite.reshape(-1))
    # First few positions are enough to localize the poison; the full
    # index list of a poisoned replay ring would be the real spam.
    for idx in bad[:3]:
        out.append((f"{path}[{int(idx)}]", _classify(float(flat[idx]))))
    if bad.size > 3:
        out.append((f"{path}", f"... {int(bad.size) - 3} more"))


def nonfinite_leaves(tree, name: str = "tree") -> list[tuple[str, str]]:
    """[(path, 'nan'|'inf'|'-inf'), ...] for every non-finite element of
    the pytree's float leaves (first few positions per leaf). Pure
    numpy/stdlib — importable from the jax-free serving/analysis
    modules."""
    out: list[tuple[str, str]] = []
    _walk(tree, name, out)
    return out


def check_finite(tree, what: str, name: str = "tree") -> None:
    """The commit-point gate: raise `NonFiniteError` naming the poisoned
    leaves when `tree` carries nan/±inf, else return silently. `what`
    names the refusing sink for the error message ("checkpoint state",
    "published params", ...)."""
    bad = nonfinite_leaves(tree, name)
    if bad:
        detail = ", ".join(f"{p}: {k}" for p, k in bad[:6])
        raise NonFiniteError(
            f"{what} refused: non-finite values at {detail} — a "
            "nan/inf tree must never become durable or visible to "
            "peers/clients (fix the producer; see scripts/numsan.py "
            "for the guard contract)"
        )


# ---------------------------------------------------------------------------
# NaN-safe JSON rows
# ---------------------------------------------------------------------------

# Keys already reported this process (once-per-key stderr contract).
# Module global mutated under the lock: telemetry writers call from
# sampler/span threads concurrently.
_reported: set[str] = set()
_reported_lock = threading.Lock()


def _scrub(value, key: str, bad: list):
    if isinstance(value, bool) or value is None:
        return value
    if isinstance(value, (int, str)):
        return value
    if isinstance(value, float):
        if math.isfinite(value):
            return value
        bad.append(key)
        return None
    if isinstance(value, dict):
        return {k: _scrub(v, f"{key}.{k}" if key else str(k), bad)
                for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_scrub(v, key, bad) for v in value]
    if isinstance(value, np.floating):
        f = float(value)
        if math.isfinite(f):
            return f
        bad.append(key)
        return None
    if isinstance(value, np.integer):
        return int(value)
    if isinstance(value, np.ndarray):
        if value.ndim == 0:
            return _scrub(value.item(), key, bad)
        # Small arrays riding a row (a per-type vector, a weights
        # stage) serialize as scrubbed lists — json.dumps has no
        # default for ndarray and a telemetry row must never crash.
        return _scrub(value.tolist(), key, bad)
    return value  # json.dumps's `default` (or the str fallback) handles it


def safe_json_row(row: dict, default=None) -> str:
    """One strict-JSON line for a telemetry/metrics row: non-finite
    floats (python or numpy, nested) become `null` and the offending key
    is reported ONCE per process on stderr — the row itself always
    serializes, so one NaN gauge can never end sampling/span emission
    for the rest of a run (the `allow_nan=False` sites this replaces
    raised ValueError and silently dropped the whole row)."""
    bad: list[str] = []
    clean = _scrub(row, "", bad)
    if bad:
        with _reported_lock:
            fresh = [k for k in bad if k not in _reported]
            _reported.update(fresh)
        for k in fresh:
            print(
                f"[numguard] non-finite value under key {k!r} written as "
                "null (reported once per key; fix the producer)",
                file=sys.stderr,
            )
    try:
        # jaxlint: disable=sink-guard (this IS the one audited
        # allow_nan=False site: every value above was just scrubbed
        # finite)
        return json.dumps(clean, allow_nan=False, default=default)
    except TypeError:
        # A foreign leaf (jax.Array, set, dataclass) with no `default`
        # supplied: stringify rather than crash the writer — the
        # never-take-the-run-down contract every telemetry sink keeps.
        # jaxlint: disable=sink-guard (same audited site, str fallback)
        return json.dumps(clean, allow_nan=False, default=str)
