"""Checkpoint / resume via orbax (SURVEY.md §5.3-5.4).

The reference genre saves with `tf.train.Saver` periodically and dies on
failure (reference mount empty at survey, SURVEY.md §0); the TPU build's
recovery story is checkpoint-restart: every K iterations the FULL
trainer state pytree — params, optimizer state, env/rollout state, PRNG
keys, step counters, normalizer stats — is saved asynchronously, and
`resume_or_init` restores the exact state so a restarted run is
bitwise-identical to an uninterrupted one (the trainers are pure
functions of their state; tested in tests/test_checkpoint.py).

JAX typed PRNG keys are packed to their raw uint32 `key_data` on save
and re-wrapped on restore (orbax stores plain arrays), keyed off the
template state's leaf types, so any trainer state NamedTuple works
unmodified.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

from actor_critic_tpu.utils import numguard


def _is_typed_key(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jax.dtypes.prng_key)


def pack_keys(state: Any) -> Any:
    """Replace typed PRNG key leaves with their raw uint32 key data."""
    return jax.tree.map(
        lambda x: jax.random.key_data(x) if _is_typed_key(x) else x, state
    )


@jax.jit
def _owned_copy(tree: Any) -> Any:
    """On-device clone: outputs are fresh jax-owned buffers (and, with
    uncommitted inputs, uncommitted)."""
    return jax.tree.map(jnp.copy, tree)


def uncommit(state: Any) -> Any:
    """Normalize a just-restored state for the compile-once contract
    (ISSUE 4): every leaf becomes an UNCOMMITTED, JAX-OWNED
    default-device array. Two distinct failure modes force this:

    - COMMITMENT: orbax restores committed arrays (explicit sharding),
      and jit bakes committed-arg shardings into the lowered module —
      a resumed process would lower byte-different HLO from a fresh one
      and MISS every persistent-cache entry the fresh leg or the AOT
      warmup wrote (verified: the restored-state module gains per-arg
      `mhlo.sharding` attributes). The host round-trip below restores
      the fresh leg's cache keys.
    - OWNERSHIP: device_put of host memory can alias it zero-copy, and
      DONATING such a buffer into a DESERIALIZED cached executable
      corrupts the glibc heap in this container ("corrupted
      double-linked list" → SIGSEGV one dispatch later; reproduced 6/6
      with restored states under a warm cache, clean 6/6 with fresh
      states or cold compiles). The `_owned_copy` clone reads the
      maybe-aliased buffers WITHOUT donation and emits buffers XLA
      allocated itself, which every downstream donating dispatch can
      safely consume.

    One host round-trip plus one on-device copy per restore buys the
    resumed leg a near-compile-free, crash-free start.

    Mesh-SHARDED states pass through untouched: the host round-trip
    would collapse their shards onto one device, and the dp/seqpar
    drivers that restore them manage placement explicitly (they sit
    outside train.py's compile-cache scope)."""
    for leaf in jax.tree.leaves(state):
        try:
            multi = len(leaf.sharding.device_set) > 1
        except AttributeError:
            multi = False
        if multi:
            return state
    placed = jax.tree.map(
        lambda x: (
            jax.device_put(jax.device_get(x))
            if isinstance(x, jax.Array)
            else x
        ),
        state,
    )
    return _owned_copy(placed)


def unpack_keys(restored: Any, template: Any) -> Any:
    """Re-wrap raw key data wherever `template` holds a typed key."""
    return jax.tree.map(
        lambda t, r: (
            jax.random.wrap_key_data(r, impl=jax.random.key_impl(t))
            if _is_typed_key(t)
            else r
        ),
        template,
        restored,
    )


class Checkpointer:
    """Thin wrapper over `ocp.CheckpointManager` for trainer states.

    Saves are async (the train loop keeps running while the write
    completes); `wait()` blocks, and `close()` waits + releases.
    Restored states are ownership/commitment-normalized (`uncommit`) so
    resumed processes share the fresh process's compilation-cache keys
    and never donate externally-aliased buffers into deserialized
    executables.
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
    ):
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(os.fspath(directory)),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
            ),
        )

    def save(
        self,
        step: int,
        state: Any,
        metrics: Optional[dict] = None,
        force: bool = False,
    ) -> bool:
        """Persist `state` (and optionally the latest scalar `metrics`)
        under `step`. Returns True if a save happened (the manager skips
        steps closer than `save_interval_steps`).

        Metrics ride along as a JSON item so a resume that finds nothing
        left to run can still report the run's final metrics instead of
        an empty dict (see `checkpointed_train`).

        Non-finite STATE refuses to commit (`NonFiniteError`, ISSUE 14):
        a NaN-poisoned params tree written to disk is inherited by every
        future resume — the previous good checkpoint must stay the
        latest instead. The gate sweeps packed (plain-array) leaves, so
        typed PRNG keys cost nothing; metrics may legitimately carry a
        non-finite loss (that IS the forensic record of a divergence)
        and are never refused.
        """
        packed = pack_keys(state)
        numguard.check_finite(packed, "checkpoint commit", name="state")
        m = {k: float(v) for k, v in (metrics or {}).items()}
        # The item is named `run_metrics` because newer orbax reserves
        # the bare name `metrics` for its own best-checkpoint tracking
        # and refuses Composite items using it.
        return self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(packed),
                run_metrics=ocp.args.JsonSave(m),
            ),
            force=force,
        )

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore the checkpoint at `step` (default: latest) into the
        structure/shardings of `template` (a concrete or abstract state).

        The returned leaves are normalized by `uncommit` — uncommitted,
        XLA-owned default-device arrays — so a resumed process lowers
        the same HLO (and hits the same persistent-compilation-cache
        entries) as a fresh one, and downstream donating dispatches
        never free buffers orbax/numpy still own."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError("no checkpoint to restore")
        packed = pack_keys(template)
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, packed)
        try:
            restored = self._mgr.restore(
                step, args=ocp.args.Composite(state=ocp.args.StandardRestore(abstract))
            )["state"]
        except ValueError as e:
            # Legacy layout ONLY: a bare StandardSave with no named items
            # (written before metrics rode along) makes orbax refuse
            # Composite args with its "unnamed checkpointable" signature.
            # Any other ValueError (e.g. template shape/dtype mismatch) is
            # a genuine failure and must surface as itself, not as a
            # confusing secondary error from the bare-form retry.
            msg = str(e)
            if not ("unnamed" in msg or "Composite" in msg):
                raise
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract)
            )
        # Normalized BEFORE key re-wrap (plain uint32 leaves throughout),
        # so typed keys come out of wrap_key_data uncommitted like a
        # fresh process's. Only while the persistent compile cache is
        # live: both failure modes uncommit guards against need a warm
        # cache (key mismatch / deserialized-executable donation), and
        # the normalization's transient 2x device materialization must
        # not be charged to cache-less restores of replay-ring-sized
        # states. (train.py enables the cache before any Checkpointer
        # exists, so the ordering holds.)
        from actor_critic_tpu.utils import compile_cache

        if compile_cache.enabled_dir() is not None:
            restored = uncommit(restored)
        return unpack_keys(restored, template)

    def restore_metrics(self, step: Optional[int] = None) -> dict:
        """The scalar metrics saved alongside the checkpoint at `step`
        (default: latest); {} if none were recorded."""
        if step is None:
            step = self.latest_step()
            if step is None:
                return {}
        for item in ("run_metrics", "metrics"):  # current name, then legacy
            try:
                out = self._mgr.restore(
                    step,
                    args=ocp.args.Composite(**{item: ocp.args.JsonRestore()}),
                )[item]
                return dict(out or {})
            except (FileNotFoundError, KeyError, ValueError) as e:
                import json

                if isinstance(e, json.JSONDecodeError):
                    # A truncated/corrupt metrics item is NOT "no
                    # metrics" — surface it.
                    raise
                # Legitimately absent under this name: fall through to
                # the legacy spelling (checkpoints written before the
                # orbax reserved-name rename), then to {} (legacy bare-
                # StandardSave layouts raise ValueError on Composite
                # args).
        return {}

    @property
    def directory(self) -> str:
        """The checkpoint root — sidecar files (e.g. the learned chunk
        wall) live next to the step directories."""
        return str(self._mgr.directory)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _read_chunk_wall(path: str) -> Optional[float]:
    """The persisted steady-state chunk wall seconds, or None (absent /
    unreadable / non-positive — all mean "nothing learned yet")."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    # Valid-but-foreign JSON (a bare number, a list) must read as
    # "nothing learned", not crash — this sidecar is advisory.
    wall = data.get("chunk_wall_s") if isinstance(data, dict) else None
    if isinstance(wall, (int, float)) and not isinstance(wall, bool):
        return float(wall) if wall > 0 else None
    return None


def _persist_chunk_wall(path: str, wall_s: float) -> None:
    """Record the largest steady-state (post-compile) chunk wall observed
    so a RESUMED process can widen its armed watchdog before its own
    chunk 1 — whose wall is compile-inflated and deliberately never
    ratcheted from."""
    prev = _read_chunk_wall(path)
    if prev is not None and prev >= wall_s:
        return
    try:
        with open(path, "w") as f:
            json.dump({"chunk_wall_s": round(float(wall_s), 3)}, f)
    except OSError:
        pass  # advisory sidecar; never take the run down


def _compile_probe() -> Optional[Callable[[], int]]:
    """A monotonically-increasing compile-event counter from the
    telemetry compile listener, or None when the listener isn't
    installed. The chunk-wall ratchet samples it around each dispatch to
    MEASURE whether the dispatch paid XLA compile, instead of guessing
    from 'first dispatch at this k' (tests monkeypatch this seam to pin
    either path)."""
    try:
        from actor_critic_tpu.telemetry import profiler
    except Exception:  # pragma: no cover - telemetry always importable
        return None
    if not profiler.introspection_active():
        return None
    return profiler.compile_event_count


def resume_or_init(ckpt: Checkpointer, init_state: Any) -> tuple[Any, int]:
    """(state, completed_iterations): the latest checkpoint if one exists,
    else the freshly-initialized state at iteration 0."""
    step = ckpt.latest_step()
    if step is None:
        return init_state, 0
    return ckpt.restore(init_state, step), step


def checkpointed_train(
    step_fn: Callable[..., tuple[Any, dict]],
    init_state: Any,
    num_iterations: int,
    ckpt: Optional[Checkpointer] = None,
    save_every: int = 0,
    log_fn: Optional[Callable[[int, dict], None]] = None,
    resume: bool = True,
    stride: int = 1,
) -> tuple[Any, dict]:
    """Restart-idempotent train loop (SURVEY.md §5.3).

    Resumes from the latest checkpoint (if any, and `resume`), runs the
    remaining iterations with `step_fn` — a jitted `(state) → (state,
    metrics)` when `stride == 1`, or `(state, k)` advancing k iterations
    per dispatch when `stride > 1` — saving on the `save_every` cadence
    (plus once at the end; `save_every<=0` means end-only) and calling
    `log_fn(it, metrics)` after each DISPATCH: that is every iteration
    at `stride == 1` but only once per chunk at `stride > 1`, with `it`
    jumping by the chunk size. Re-running after a mid-loop kill produces
    the same final state as an uninterrupted run, because the state
    pytree carries everything. With `ckpt=None` it is a plain train
    loop — the single implementation every caller shares.

    `stride > 1` is the chunked-dispatch mode: `step_fn` must then take
    `(state, k)` and advance k iterations in ONE device dispatch
    (a `lax.scan` over the per-iteration step). The counter advances by
    `min(stride, remaining)` per call, so arbitrary `num_iterations`
    and resume points work (the short tail chunk costs one extra
    compile). Save/log callbacks fire only at chunk boundaries — the
    caller is responsible for choosing cadences that are multiples of
    `stride` (train.py snaps them up and says so).
    """
    if ckpt is not None and resume:
        state, done = resume_or_init(ckpt, init_state)
    else:
        state, done = init_state, 0
    # A resume that finds the run already complete would otherwise return
    # {} and the caller's summary would silently lose all metrics. (Only
    # hit that case — a mid-run resume overwrites metrics on step one.)
    metrics: dict = (
        ckpt.restore_metrics(done)
        if (ckpt is not None and done and done >= num_iterations)
        else {}
    )
    from actor_critic_tpu import telemetry
    from actor_critic_tpu.utils import watchdog
    from actor_critic_tpu.utils.cadence import should_save

    chunk_wall_path = None
    if stride > 1 and ckpt is not None:
        chunk_wall_path = os.path.join(ckpt.directory, "chunk_wall.json")
        learned = _read_chunk_wall(chunk_wall_path)
        if learned is not None:
            # A resumed process recompiles from scratch and its first
            # dispatch is skipped by the ratchet below, so without this
            # the run would enter chunk 2 still on the CLI timeout even
            # when a previous leg proved chunks legitimately run longer.
            watchdog.ensure_timeout_at_least(3.0 * learned)

    it = done
    timed_k = None  # heuristic fallback: stride of the last compile-paid dispatch
    while it < num_iterations:
        # First chunk after a misaligned resume realigns to stride
        # boundaries (resume at it=1000, stride=64 → k=24 then 64s), so
        # the snapped log/eval/save cadences — which fire only when
        # `it % cadence == 0` — keep firing for the rest of the run.
        k = stride - it % stride if it % stride else stride
        k = min(k, num_iterations - it)
        watchdog.beat()  # progress heartbeat (utils/watchdog.py)
        # Dispatch boundary for any armed on-demand profile window
        # (telemetry/profiler.py; one "iter" here = one chunk at
        # stride > 1 — the capturable unit of fused work).
        telemetry.profiler_tick()
        compile_count = _compile_probe() if stride > 1 else None
        compiles_before = compile_count() if compile_count else 0
        t_dispatch = time.monotonic()
        # The span measures enqueue-to-return, not device wall: a jitted
        # call returns at dispatch, and fencing here would break the
        # async pipelining (the first sync lands in the log span).
        with telemetry.span("update", it=it + k, dispatch="async"):
            state, metrics = (
                step_fn(state, k) if stride > 1 else step_fn(state)
            )
        if stride > 1 and watchdog.armed():
            # A chunk that legitimately outlasts --stall-timeout must not
            # be misread as a stall on the NEXT chunk (one beat per chunk;
            # the kill/resume loop that never clears a chunk is ADVICE.md
            # round-4 #2). A jitted call returns at ENQUEUE time, so the
            # true chunk wall is only observable behind a block — block on
            # the (scalar) metrics, which complete with the chunk program;
            # only done while a watchdog is armed, so the unwatched path
            # keeps its async pipelining. A completed chunk is proof of
            # the real wall time — raise any armed watchdog to 3x that,
            # with headroom for cache misses on tail chunks.
            jax.block_until_ready(metrics)
            chunk_wall = time.monotonic() - t_dispatch
            if compile_count is not None:
                # MEASURED compile attribution (ISSUE 4): the telemetry
                # compile listener saw XLA compile during this dispatch.
                # (A persistent-cache hit also funnels through — its
                # near-zero wall makes the conservative grace extension
                # harmless.)
                paid_compile = compile_count() > compiles_before
            else:
                # Fallback heuristic (telemetry off): a dispatch with a
                # k this process hasn't timed yet paid compile — the
                # process's first chunk, the realignment chunk, the
                # short tail (~60s observed here).
                paid_compile = k != timed_k
                timed_k = k
            if paid_compile:
                # Ratcheting or persisting a compile-carrying wall would
                # bake compile time into 3x the stall timeout
                # permanently, weakening wedge detection for the rest of
                # the run and (via the sidecar) every future leg. Shield
                # the NEXT chunk with a temporary grace extension
                # instead; the first clean dispatch supplies the wall.
                watchdog.extend_grace(3.0 * chunk_wall)
            else:
                watchdog.ensure_timeout_at_least(3.0 * chunk_wall)
                if chunk_wall_path is not None:
                    _persist_chunk_wall(chunk_wall_path, chunk_wall)
        it += k
        if should_save(it, save_every, num_iterations):
            # The span is emitted even with ckpt=None (args record
            # whether a save actually ran): the checkpoint phase
            # boundary exists in every trace, so run reports can compare
            # checkpointed and checkpoint-free runs phase-for-phase.
            with telemetry.span("checkpoint", step=it, saved=ckpt is not None):
                if ckpt is not None:
                    # Sync before handing buffers to the async saver:
                    # donation would otherwise let the next step
                    # overwrite in-flight reads.
                    jax.block_until_ready(state)
                    ckpt.save(it, state, metrics=metrics, force=True)
        if log_fn is not None:
            with telemetry.span("log", it=it):
                log_fn(it, metrics)
    if ckpt is not None:
        ckpt.wait()
    return state, metrics
