"""Stall watchdog — failure DETECTION for long training runs (SURVEY.md
§5.3).

The axon TPU tunnel can wedge mid-run: every device call then blocks
forever in a futex wait, the process looks alive, and a 1-hour run
silently becomes a 0-progress hang (observed in-session 2026-07-30: a
SAC Humanoid run froze at iteration ~680 and burned 15 minutes before a
human noticed). Checkpoint/resume already makes runs restart-idempotent;
what was missing is the component that *notices* the hang and dies so a
retry loop can restart:

    python train.py ... --ckpt-dir runs/x --save-every 1000 --stall-timeout 300
    while [ $? -eq 42 ]; do python train.py ... --resume; done

A daemon thread watches a heartbeat the training loops touch every
collection step (`beat()` via `host_collect`); if no beat lands within
`timeout_s` the process prints a diagnosis and `os._exit(42)` — the only
reliable escape, since the main thread is stuck inside a C extension
call that Python exceptions cannot interrupt.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional

STALL_EXIT_CODE = 42

# jaxlint: thread-owned=main (arm/disarm — append/remove — happen only
# on the run-owning thread via start()/stop(); the watchdog daemon and
# /healthz threads only iterate, and a snapshot that is one
# arm/disarm stale is harmless for a heartbeat check)
_ACTIVE: list["StallWatchdog"] = []


def beat() -> None:
    """Touch every armed watchdog. Called from the hot host loops; a
    plain attribute write, so it is safe (and ~free) when none is armed."""
    for w in _ACTIVE:
        w.touch()


def armed() -> bool:
    """Whether any watchdog is currently armed (callers use this to skip
    watchdog-only work, e.g. the chunk-wall measurement block in
    checkpointed_train that would otherwise cost async pipelining)."""
    return bool(_ACTIVE)


def status() -> Optional[dict]:
    """Staleness snapshot of the armed watchdog for live introspection
    (telemetry/exporter.py's /healthz): seconds since the last heartbeat,
    the configured timeout, and whether the startup grace still shields
    firing. None when no watchdog is armed. With several armed (tests),
    reports the one CLOSEST TO FIRING — staleness relative to its own
    timeout, not raw staleness (a 200s-stale 10s-timeout watchdog fires
    long before a 300s-stale 600s-timeout one)."""
    if not _ACTIVE:
        return None
    now = time.monotonic()
    w = max(_ACTIVE, key=lambda w: (now - w._last) - w.timeout_s)
    return {
        "staleness_s": round(now - w._last, 3),
        "timeout_s": w.timeout_s,
        "in_grace": now <= w._grace_until,
    }


def extend_grace(secs: float) -> None:
    """Shield every armed watchdog from firing for the next `secs`
    seconds (raises the startup-grace deadline, never lowers it).

    For slow-but-legitimate windows that must NOT widen the PERMANENT
    stall timeout: chunked dispatch uses it after a compile-carrying
    dispatch (process-first, resume-realignment, or the tail chunk —
    each static k is its own XLA program), whose measured wall mixes
    compile time with run time. The temporary shield covers the next
    chunk; the first same-k dispatch then supplies a clean wall for the
    real `ensure_timeout_at_least` ratchet."""
    for w in _ACTIVE:
        w.extend_grace(secs)


def ensure_timeout_at_least(secs: float) -> None:
    """Raise every armed watchdog's timeout to at least `secs`.

    Chunked dispatch (`checkpointed_train(stride>1)`) beats once per
    chunk; a chunk whose legitimate wall time exceeds --stall-timeout
    would otherwise be killed as a stall on every chunk after the startup
    grace — a kill/resume loop that never clears a chunk (ADVICE.md
    round 4 #2). The loop calls this with a multiple of each COMPLETED
    dispatch's measured wall time: proof of real progress, so widening
    the stall definition to match is correct, and a genuine wedge is
    still detected within the widened window."""
    for w in _ACTIVE:
        if secs > w.timeout_s:
            print(
                f"[watchdog] chunk wall time requires stall timeout "
                f">= {secs:.0f}s; raising from {w.timeout_s:.0f}s",
                file=sys.stderr, flush=True,
            )
            w.timeout_s = float(secs)


class StallWatchdog:
    """Arms a daemon thread that kills the process (exit 42) if `touch()`
    isn't called for `timeout_s` seconds. Use as a context manager around
    a training run; `stop()` disarms."""

    def __init__(self, timeout_s: float, startup_grace_s: float = 600.0):
        """`startup_grace_s`: no firing during the first max(timeout,
        grace) seconds of THIS process — first-call XLA compilation
        blocks the host with no beats (observed ~60 s here, and a resume
        recompiles from scratch), so an early 'stall' would send the
        retry loop into a kill/recompile cycle that never progresses."""
        if timeout_s <= 0:
            raise ValueError("timeout_s must be > 0 (use no watchdog instead)")
        self.timeout_s = float(timeout_s)
        # jaxlint: thread-owned=main (extend_grace raises the deadline
        # from the run-owning thread only; the watchdog thread reads a
        # monotonic float — a racing raise-vs-raise would at worst keep
        # the LARGER deadline's shield, which is the safe direction)
        self._grace_until = time.monotonic() + max(timeout_s, startup_grace_s)
        self._last = time.monotonic()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._run, name="stall-watchdog", daemon=True
        )

    def touch(self) -> None:
        self._last = time.monotonic()

    def extend_grace(self, secs: float) -> None:
        """Push the no-fire grace deadline to at least `secs` from now
        (module-level `extend_grace` broadcasts to all armed instances)."""
        deadline = time.monotonic() + float(secs)
        if deadline > self._grace_until:
            self._grace_until = deadline

    def start(self) -> "StallWatchdog":
        _ACTIVE.append(self)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped = True
        if self in _ACTIVE:
            _ACTIVE.remove(self)

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        poll = min(5.0, self.timeout_s / 4)
        while not self._stopped:
            time.sleep(poll)
            now = time.monotonic()
            stalled = now - self._last
            if (
                not self._stopped
                and now > self._grace_until
                and stalled > self.timeout_s
            ):
                # Telemetry names the phase that was open when progress
                # stopped (the span stack is maintained even without a
                # --telemetry-dir session) and, with a session, writes a
                # durable `stall` event before the hard exit.
                try:
                    from actor_critic_tpu import telemetry

                    phase = telemetry.stall_report(stalled)
                except Exception:
                    phase = ""
                print(
                    f"[stall-watchdog] no training progress for "
                    f"{stalled:.0f}s (> {self.timeout_s:.0f}s) — device "
                    "tunnel presumed wedged; exiting "
                    f"{STALL_EXIT_CODE} so a retry loop can --resume "
                    f"from the last checkpoint{phase}",
                    file=sys.stderr,
                    flush=True,
                )
                sys.stderr.flush()
                os._exit(STALL_EXIT_CODE)
