"""Logging/checkpoint cadence policies — THE single definition shared by
the host loops, the fused loops, `checkpointed_train`, and the CLI.
A leaf module (no jax, no intra-package imports) so both `utils` and
`algos` can depend on it without layering inversions."""

from __future__ import annotations


def should_log(it: int, log_every: int, num_iterations: int) -> bool:
    """Every `log_every` iterations (when > 0) plus ALWAYS the first and
    final iterations; `log_every <= 0` means first+final only. `it` is
    1-based. Logging iteration 1 unconditionally means a long run
    produces evidence within one iteration instead of after `log_every`
    of them (round 1's 50-minute HalfCheetah attempt left a 0-row
    metrics file precisely because the first row waited for iteration
    10)."""
    if it == 1 or it == num_iterations:
        return True
    return log_every > 0 and it % log_every == 0


def should_save(it: int, save_every: int, num_iterations: int) -> bool:
    """Checkpoint cadence (1-based `it`): every `save_every` iterations
    (when > 0) plus always the final one."""
    if it == num_iterations:
        return True
    return save_every > 0 and it % save_every == 0


def finite_or_none(v):
    """float(v) if finite, else None — the strict-JSON scrub for metric
    values (NaN/Inf are not valid JSON; every sink shares this rule)."""
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if f == f and abs(f) != float("inf") else None
