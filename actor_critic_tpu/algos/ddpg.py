"""DDPG / TD3 — off-policy deterministic actor-critic, replay in HBM.

Capability parity with the reference's DDPG/TD3 Walker2d config
(BASELINE.json:9: "off-policy, HBM replay buffer, target nets"; reference
mount empty at survey, SURVEY.md §0). TD3 is DDPG plus three flags
(`twin_q`, `policy_delay`, `target_noise`) — one implementation, two
configs, matching how the reference layers TD3 over DDPG (SURVEY §2.1).

TPU-first structure (SURVEY §3.2 boundary fix): one jitted train step =

    lax.scan over K env steps: [actor fwd + noise → vmapped env.step]
    → replay.add_batch (in-HBM scatter, donated)
    → lax.scan over J updates: [replay.sample → critic TD step
         → (delayed) actor step + Polyak targets]

so replay storage, sampling RNG, and both optimizers never leave the
device. The reference's per-update host→device `buffer.sample(B)` copy
does not exist here. Delayed actor/target updates are branchless
`where`-selects (no `cond` inside the vmapped/scanned update loop).

For MuJoCo (host-stepped, SURVEY §7.2 item 2) `train_host` keeps the
same learner program and feeds it one [K, E] transition block per
iteration — a single host→device transfer.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from actor_critic_tpu import replay
from actor_critic_tpu.algos.common import (
    OffPolicyTransition,
    RolloutState,
    episode_metrics_update,
    init_rollout,
    offpolicy_rollout,
)
from actor_critic_tpu.algos.metrics import aggregate_metrics
from actor_critic_tpu.envs.jax_env import JaxEnv
from actor_critic_tpu.models.networks import DeterministicActor, QFunction, TwinQ
from actor_critic_tpu.ops.polyak import polyak_update
from actor_critic_tpu.parallel import mesh as pmesh


@dataclasses.dataclass(frozen=True)
class DDPGConfig:
    num_envs: int = 8
    steps_per_iter: int = 8      # K env steps per train_step call
    updates_per_iter: int = 8    # J gradient updates per train_step call
    buffer_capacity: int = 1_000_000
    batch_size: int = 256
    gamma: float = 0.99
    tau: float = 0.005
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    hidden: tuple[int, ...] = (256, 256)
    exploration_noise: float = 0.1  # behavior-policy Gaussian noise std
    warmup_steps: int = 1_000       # uniform-random action steps (per device)
    # --- TD3 extensions (BASELINE.json:9) ---
    twin_q: bool = False
    policy_delay: int = 1
    target_noise: float = 0.0       # target-policy smoothing std
    target_noise_clip: float = 0.5
    bf16_compute: bool = False
    # --- n-step returns (replay.sample_sequences consumer) ---
    # nstep > 1 samples length-n windows of consecutive inserts and
    # trains the critic on the n-step target
    #   G = Σ_{k<m} γ^k r_k  +  γ^m (1 − terminated_{m−1}) Q̄(s_m, π̄(s_m)),
    # where m is the window length up to the first episode end (done
    # cuts the sum; truncation bootstraps through, exactly like the
    # 1-step path). Requires num_envs == 1: the ring stores flattened
    # [K, E] rollouts, so consecutive inserts are one env's consecutive
    # timesteps only for a single env (replay.sample_sequences guards
    # the ring seam, not env interleaving).
    nstep: int = 1
    # --- quantized replay storage (ISSUE 8, replay/quantize.py) ---
    # "fp32" stores transitions as-is; "mixed" stores obs/rewards as
    # standardized int8 + done flags as int8 with actions kept fp32
    # (~3.1x transitions per HBM byte at Pendulum shape); "int8" also
    # quantizes the bounded actions (~4x, aggressive).
    replay_dtype: str = "fp32"


def td3_config(**overrides) -> DDPGConfig:
    """TD3 = DDPG + twin critics, delayed policy, target smoothing."""
    base = dict(twin_q=True, policy_delay=2, target_noise=0.2)
    base.update(overrides)
    return DDPGConfig(**base)


class LearnerState(NamedTuple):
    """Device-resident learner: params, targets, optimizers, replay ring."""

    actor_params: Any
    critic_params: Any
    target_actor: Any
    target_critic: Any
    actor_opt: Any
    critic_opt: Any
    replay: replay.ReplayState
    key: jax.Array
    update_count: jax.Array  # gradient updates so far (drives policy delay)


class OffPolicyState(NamedTuple):
    """Fused-trainer state: learner + on-device env batch + accounting."""

    learner: LearnerState
    rollout: RolloutState
    env_steps: jax.Array  # per-device env steps (warmup gating)
    update_step: jax.Array  # train_step calls
    ep_return: jax.Array
    ep_length: jax.Array
    avg_return: jax.Array


def _modules(action_dim: int, cfg: DDPGConfig):
    dtype = jnp.bfloat16 if cfg.bf16_compute else jnp.float32
    actor = DeterministicActor(action_dim, cfg.hidden, compute_dtype=dtype)
    critic = (
        TwinQ(cfg.hidden, compute_dtype=dtype)
        if cfg.twin_q
        else QFunction(cfg.hidden, compute_dtype=dtype)
    )
    return actor, critic


def _critic_q(critic, params, obs, action, cfg: DDPGConfig):
    """(q1, q2) from either critic flavor; q2 is None without twin-Q."""
    if cfg.twin_q:
        return critic.apply(params, obs, action)
    return critic.apply(params, obs, action), None


def init_learner(
    obs_shape: tuple[int, ...], action_dim: int, cfg: DDPGConfig, key: jax.Array
) -> LearnerState:
    actor, critic = _modules(action_dim, cfg)
    akey, ckey, lkey = jax.random.split(key, 3)
    dummy_obs = jnp.zeros((1, *obs_shape), jnp.float32)
    dummy_act = jnp.zeros((1, action_dim), jnp.float32)
    actor_params = actor.init(akey, dummy_obs)
    critic_params = critic.init(ckey, dummy_obs, dummy_act)
    example = OffPolicyTransition(
        obs=jnp.zeros(obs_shape, jnp.float32),
        action=jnp.zeros((action_dim,), jnp.float32),
        reward=jnp.zeros((), jnp.float32),
        next_obs=jnp.zeros(obs_shape, jnp.float32),
        terminated=jnp.zeros((), jnp.float32),
        done=jnp.zeros((), jnp.float32),
    )
    return LearnerState(
        actor_params=actor_params,
        critic_params=critic_params,
        # Targets start equal but must be distinct buffers: the fused
        # trainer donates its state, and XLA rejects aliased donations.
        target_actor=jax.tree.map(jnp.copy, actor_params),
        target_critic=jax.tree.map(jnp.copy, critic_params),
        actor_opt=optax.adam(cfg.actor_lr).init(actor_params),
        critic_opt=optax.adam(cfg.critic_lr).init(critic_params),
        replay=replay.init(
            example, cfg.buffer_capacity,
            replay.offpolicy_codecs(cfg.replay_dtype),
        ),
        key=lkey,
        update_count=jnp.zeros((), jnp.int32),
    )


def init_state(env: JaxEnv, cfg: DDPGConfig, key: jax.Array) -> OffPolicyState:
    key, lkey, rkey = jax.random.split(key, 3)
    learner = init_learner(env.spec.obs_shape, env.spec.action_dim, cfg, lkey)
    E = cfg.num_envs
    return OffPolicyState(
        learner=learner,
        rollout=init_rollout(env, rkey, E),
        env_steps=jnp.zeros((), jnp.int32),
        update_step=jnp.zeros((), jnp.int32),
        ep_return=jnp.zeros((E,)),
        ep_length=jnp.zeros((E,)),
        avg_return=jnp.zeros(()),
    )


def make_eval_fn(env: JaxEnv, cfg: "DDPGConfig"):
    """Greedy (noiseless actor) eval program (SURVEY.md §3.4); see
    common.make_greedy_eval for the shared contract."""
    from actor_critic_tpu.algos.common import make_greedy_eval

    actor, _ = _modules(env.spec.action_dim, cfg)
    return make_greedy_eval(
        env, lambda p, o: actor.apply(p, o), lambda s: s.learner.actor_params
    )


def make_explore_fn(action_dim: int, cfg: DDPGConfig):
    """Behavior policy: actor + clipped Gaussian noise; uniform actions
    during warmup (branchless `where` on the env-step counter)."""
    actor, _ = _modules(action_dim, cfg)

    def act(params, obs, key, env_steps):
        nkey, ukey = jax.random.split(key)
        a = actor.apply(params, obs)
        a = a + cfg.exploration_noise * jax.random.normal(nkey, a.shape)
        a = jnp.clip(a, -1.0, 1.0)
        rand = jax.random.uniform(ukey, a.shape, minval=-1.0, maxval=1.0)
        return jnp.where(env_steps < cfg.warmup_steps, rand, a)

    return act


def nstep_batch(
    seq: OffPolicyTransition, gamma: float
) -> tuple[OffPolicyTransition, jax.Array]:
    """[B, n] sequence windows → (1-step-shaped batch, bootstrap discount).

    The returned batch's `reward` carries the masked n-step return prefix
    G = Σ_{k<m} γ^k r_k (m = steps up to and including the first done;
    the done step's own reward counts — it is the terminal reward), and
    `next_obs`/`terminated` are the window-END transition's (first done
    step, else the last). The bootstrap discount is γ^m, so
    target = G + γ^m (1 − terminated_end) Q̄(next_obs_end, ·) matches the
    1-step TD shape exactly — truncations bootstrap through, terminations
    mask, episodes never splice (`replay.sample_sequences` consumer).
    """
    n = seq.reward.shape[1]
    d = seq.done.astype(jnp.float32)  # [B, n]
    alive_before = jnp.cumprod(
        jnp.concatenate([jnp.ones_like(d[:, :1]), 1.0 - d[:, :-1]], axis=1),
        axis=1,
    )
    gammas = gamma ** jnp.arange(n, dtype=jnp.float32)
    g = jnp.sum(seq.reward * alive_before * gammas, axis=1)
    any_done = jnp.max(d, axis=1) > 0
    end_idx = jnp.where(any_done, jnp.argmax(d, axis=1), n - 1)  # [B]

    def at_end(x):
        idx = end_idx.reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.take_along_axis(x, idx, axis=1)[:, 0]

    batch = OffPolicyTransition(
        obs=seq.obs[:, 0],
        action=seq.action[:, 0],
        reward=g,
        next_obs=at_end(seq.next_obs),
        terminated=at_end(seq.terminated),
        done=seq.done[:, 0],
    )
    return batch, gamma ** (end_idx.astype(jnp.float32) + 1.0)


def make_update_loop(
    action_dim: int,
    cfg: DDPGConfig,
    axis_name: Optional[str] = None,
) -> Callable[[LearnerState, jax.Array], tuple[LearnerState, dict[str, jax.Array]]]:
    """Build `(learner, do_update) → (learner, metrics)` running
    `cfg.updates_per_iter` sample→TD→(delayed) actor steps in one scan.

    `do_update` gates learning during warmup: grads are still computed
    (static program) but params/targets/optimizer state are `where`-kept.
    """
    actor, critic = _modules(action_dim, cfg)
    codecs = replay.offpolicy_codecs(cfg.replay_dtype)
    if cfg.nstep < 1:
        raise ValueError(f"nstep must be >= 1, got {cfg.nstep}")
    if cfg.nstep > 1 and cfg.num_envs != 1:
        raise ValueError(
            "nstep > 1 requires num_envs == 1: the replay ring stores "
            "flattened [K, E] rollouts, so consecutive inserts interleave "
            "envs unless E == 1 (see DDPGConfig.nstep)"
        )

    def critic_loss_fn(critic_params, target_q, batch: OffPolicyTransition):
        q1, q2 = _critic_q(critic, critic_params, batch.obs, batch.action, cfg)
        loss = jnp.mean((q1 - target_q) ** 2)
        if q2 is not None:
            loss = loss + jnp.mean((q2 - target_q) ** 2)
        return loss, jnp.mean(q1)

    def actor_loss_fn(actor_params, critic_params, obs):
        a = actor.apply(actor_params, obs)
        q1, _ = _critic_q(critic, critic_params, obs, a, cfg)
        return -jnp.mean(q1)

    def select(mask, new, old):
        return jax.tree.map(lambda n, o: jnp.where(mask, n, o), new, old)

    def one_update(ls: LearnerState, do_update: jax.Array):
        key, skey, tkey = jax.random.split(ls.key, 3)
        if cfg.nstep > 1:
            seq = replay.sample_sequences(
                ls.replay, skey, cfg.batch_size, cfg.nstep, codecs
            )
            batch, boot_discount = nstep_batch(seq, cfg.gamma)
        else:
            batch = replay.sample(ls.replay, skey, cfg.batch_size, codecs)
            boot_discount = cfg.gamma

        # --- TD target from target nets (+TD3 smoothing) ---
        next_a = actor.apply(ls.target_actor, batch.next_obs)
        if cfg.target_noise > 0.0:
            noise = jnp.clip(
                cfg.target_noise * jax.random.normal(tkey, next_a.shape),
                -cfg.target_noise_clip,
                cfg.target_noise_clip,
            )
            next_a = jnp.clip(next_a + noise, -1.0, 1.0)
        tq1, tq2 = _critic_q(critic, ls.target_critic, batch.next_obs, next_a, cfg)
        next_q = tq1 if tq2 is None else jnp.minimum(tq1, tq2)
        target_q = jax.lax.stop_gradient(
            batch.reward + boot_discount * (1.0 - batch.terminated) * next_q
        )

        # --- critic step (every update) ---
        (closs, q_mean), cgrads = jax.value_and_grad(critic_loss_fn, has_aux=True)(
            ls.critic_params, target_q, batch
        )
        cgrads = pmesh.pmean_tree(cgrads, axis_name)
        cupd, critic_opt = optax.adam(cfg.critic_lr).update(cgrads, ls.critic_opt)
        critic_params = optax.apply_updates(ls.critic_params, cupd)
        critic_params = select(do_update, critic_params, ls.critic_params)
        critic_opt = select(do_update, critic_opt, ls.critic_opt)

        # --- actor step + Polyak (every policy_delay-th update) ---
        do_actor = jnp.logical_and(
            do_update, (ls.update_count % cfg.policy_delay) == 0
        )
        aloss, agrads = jax.value_and_grad(actor_loss_fn)(
            ls.actor_params, critic_params, batch.obs
        )
        agrads = pmesh.pmean_tree(agrads, axis_name)
        aupd, actor_opt = optax.adam(cfg.actor_lr).update(agrads, ls.actor_opt)
        actor_params = optax.apply_updates(ls.actor_params, aupd)
        actor_params = select(do_actor, actor_params, ls.actor_params)
        actor_opt = select(do_actor, actor_opt, ls.actor_opt)
        target_actor = select(
            do_actor,
            polyak_update(actor_params, ls.target_actor, cfg.tau),
            ls.target_actor,
        )
        target_critic = select(
            do_actor,
            polyak_update(critic_params, ls.target_critic, cfg.tau),
            ls.target_critic,
        )

        new_ls = LearnerState(
            actor_params=actor_params,
            critic_params=critic_params,
            target_actor=target_actor,
            target_critic=target_critic,
            actor_opt=actor_opt,
            critic_opt=critic_opt,
            replay=ls.replay,
            key=key,
            update_count=ls.update_count + do_update.astype(jnp.int32),
        )
        metrics = {
            "critic_loss": closs,
            "actor_loss": aloss,
            "q_mean": q_mean,
        }
        return new_ls, metrics

    def update_loop(ls: LearnerState, do_update: jax.Array):
        def body(carry, _):
            return one_update(carry, do_update)

        ls, metrics = jax.lax.scan(body, ls, None, length=cfg.updates_per_iter)
        return ls, jax.tree.map(lambda m: m[-1], metrics)

    return update_loop


def make_train_step(
    env: JaxEnv,
    cfg: DDPGConfig,
    axis_name: Optional[str] = None,
) -> Callable[[OffPolicyState], tuple[OffPolicyState, dict[str, jax.Array]]]:
    """The fused collect→insert→update program (one jit dispatch)."""
    explore = make_explore_fn(env.spec.action_dim, cfg)
    update_loop = make_update_loop(env.spec.action_dim, cfg, axis_name)
    codecs = replay.offpolicy_codecs(cfg.replay_dtype)

    def train_step(state: OffPolicyState):
        ls = state.learner
        key, rkey = jax.random.split(ls.key)

        # --- collect K steps with the behavior policy ---
        rollout, env_steps, traj = offpolicy_rollout(
            env, explore, ls.actor_params, state.rollout, rkey,
            cfg.steps_per_iter, state.env_steps,
        )
        flat = jax.tree.map(lambda x: x.reshape(-1, *x.shape[2:]), traj)
        # axis_name syncs the quantizer's running stats across dp so the
        # replicated QuantStats leaves stay identical on every device.
        rbuf = replay.add_batch(ls.replay, flat, codecs, axis_name=axis_name)

        # --- J gradient updates (gated until warmup + one batch in ring) ---
        # The floor is max(batch_size, nstep): sample_sequences clamps a
        # length-n window's start so the window fits inside [0, size), and
        # a ring holding fewer than n inserts would clamp windows into
        # zero-initialized slots — the first updates would train on
        # fabricated transitions.
        do_update = jnp.logical_and(
            env_steps >= cfg.warmup_steps,
            rbuf.size >= max(cfg.batch_size, cfg.nstep),
        )
        ls, metrics = update_loop(
            ls._replace(replay=rbuf, key=key), do_update
        )

        # --- accounting ---
        ep_ret, ep_len, avg_ret, ep_metrics = episode_metrics_update(
            state.ep_return, state.ep_length, state.avg_return, traj
        )
        avg_ret = pmesh.pmean(avg_ret, axis_name)
        ep_metrics["avg_return_ema"] = avg_ret
        metrics = aggregate_metrics(metrics, ep_metrics, axis_name)

        new_state = OffPolicyState(
            learner=ls,
            rollout=rollout,
            env_steps=env_steps,
            update_step=state.update_step + 1,
            ep_return=ep_ret,
            ep_length=ep_len,
            avg_return=avg_ret,
        )
        return new_state, metrics

    return train_step


def train(
    env: JaxEnv,
    cfg: DDPGConfig,
    num_iterations: int,
    seed: int = 0,
    state: Optional[OffPolicyState] = None,
    log_every: int = 0,
    log_fn: Optional[Callable[[int, dict], None]] = None,
) -> tuple[OffPolicyState, dict[str, jax.Array]]:
    """Host loop around the fused step (single device), like a2c.train."""
    from actor_critic_tpu.algos.host_loop import fused_train_loop

    return fused_train_loop(
        make_train_step, init_state, env, cfg, num_iterations,
        seed=seed, state=state, log_every=log_every, log_fn=log_fn,
    )


# --------------------------------------------------------------------------
# Host-env path (MuJoCo Walker2d etc. — BASELINE.json:9)
# --------------------------------------------------------------------------

def make_host_act_fn(action_dim: int, cfg: DDPGConfig):
    """Jitted (params, obs, key, env_steps) → exploration action."""
    return jax.jit(make_explore_fn(action_dim, cfg))


def make_host_ingest_update(action_dim: int, cfg: DDPGConfig):
    """Jitted (learner, [K,E] transition block) → (learner, metrics).

    One host→device transfer per iteration; replay insert and the whole
    update loop stay on-device.
    """
    update_loop = make_update_loop(action_dim, cfg)
    codecs = replay.offpolicy_codecs(cfg.replay_dtype)

    @partial(jax.jit, donate_argnums=0)
    def ingest_update(ls: LearnerState, traj: OffPolicyTransition, env_steps):
        flat = jax.tree.map(lambda x: x.reshape(-1, *x.shape[2:]), traj)
        rbuf = replay.add_batch(ls.replay, flat, codecs)
        # Same max(batch_size, nstep) floor as the fused path: n-step
        # windows must never clamp into zero-initialized ring slots.
        do_update = jnp.logical_and(
            env_steps >= cfg.warmup_steps,
            rbuf.size >= max(cfg.batch_size, cfg.nstep),
        )
        return update_loop(ls._replace(replay=rbuf), do_update)

    return ingest_update


def make_device_ingest_update(
    action_dim: int, cfg: DDPGConfig, ring_codecs: dict
):
    """Device-data-plane ingest (ISSUE 13): the staged block is
    gathered + decoded from the HBM trajectory ring INSIDE the jitted
    program before the replay scatter and update loop — zero
    host→device transfers per consumed block. The update-gate floor is
    the host path's max(batch_size, nstep) (n-step windows must never
    clamp into zero-initialized ring slots)."""
    from actor_critic_tpu.data_plane import device_replay

    return device_replay.make_device_ingest_update(
        make_update_loop, action_dim, cfg, ring_codecs,
        min_size=max(cfg.batch_size, cfg.nstep),
    )


def make_greedy_act(action_dim: int, cfg: DDPGConfig):
    """Noiseless actor for host eval (host_loop.host_evaluate)."""
    actor, _ = _modules(action_dim, cfg)
    return lambda params, obs: actor.apply(params, obs)


def train_host(
    pool,
    cfg: DDPGConfig,
    num_iterations: int,
    seed: int = 0,
    log_every: int = 10,
    log_fn: Optional[Callable[[int, dict], None]] = None,
    eval_every: int = 0,
    eval_envs: int = 4,
    eval_steps: int = 1000,
    ckpt=None,
    save_every: int = 0,
    resume: bool = False,
    overlap: bool = True,
    save_replay: bool = True,
):
    """DDPG/TD3 on a HostEnvPool (host rollout, device learner).

    Recommended pool settings for off-policy MuJoCo: normalize_obs=False
    AND normalize_reward=False — running-stat obs normalization scales
    replayed transitions inconsistently as the stats drift (the critic
    then bootstraps across mixed frames; observed to destabilize SAC on
    Humanoid-v5), and TD targets want raw reward scale.
    `overlap` acts via the numpy host mirror with 1-update-stale params
    so device updates run during collection (host_loop docstring).
    Returns (learner, history).
    """
    from actor_critic_tpu.algos.host_loop import off_policy_train_host
    from actor_critic_tpu.models.host_actor import (
        make_ddpg_host_explore,
        make_ddpg_host_greedy,
    )

    return off_policy_train_host(
        pool, cfg, num_iterations,
        init_learner=init_learner,
        make_act_fn=make_host_act_fn,
        make_ingest_update=make_host_ingest_update,
        seed=seed, log_every=log_every, log_fn=log_fn,
        eval_every=eval_every, make_greedy_act=make_greedy_act,
        eval_envs=eval_envs, eval_steps=eval_steps,
        ckpt=ckpt, save_every=save_every, resume=resume,
        overlap=overlap, make_host_explore=make_ddpg_host_explore,
        make_host_greedy=make_ddpg_host_greedy,
        save_replay=save_replay,
    )


def train_host_async(
    pools,
    cfg: DDPGConfig,
    num_iterations: int,
    seed: int = 0,
    log_every: int = 10,
    log_fn: Optional[Callable[[int, dict], None]] = None,
    eval_every: int = 0,
    eval_envs: int = 4,
    eval_steps: int = 1000,
    queue_depth: int = 4,
    max_staleness: Optional[int] = None,
    data_plane: str = "host",
    plane_codec: str = "fp32",
    transfer_pad_s: float = 0.0,
    publish_hook: Optional[Callable[[int, object], None]] = None,
):
    """DDPG/TD3 with decoupled actor services (ISSUE 9 satellite; the
    PPO-only restriction of `--async-actors` lifted): one exploration
    thread per pool pushes [K, E_a] transition blocks through the
    bounded trajectory queue; the learner ingests each into the replay
    ring and updates — replay absorbs the behavior staleness natively,
    so there is no correction knob here. `data_plane="device"` stages
    the blocks encoded in HBM instead (ISSUE 13; see
    host_loop.off_policy_train_host_async). Returns (learner, history)."""
    from actor_critic_tpu.algos.host_loop import off_policy_train_host_async
    from actor_critic_tpu.models.host_actor import (
        make_ddpg_host_explore,
        make_ddpg_host_greedy,
    )

    return off_policy_train_host_async(
        pools, cfg, num_iterations,
        init_learner=init_learner,
        make_ingest_update=make_host_ingest_update,
        make_host_explore=make_ddpg_host_explore,
        make_host_greedy=make_ddpg_host_greedy,
        seed=seed, log_every=log_every, log_fn=log_fn,
        eval_every=eval_every, eval_envs=eval_envs, eval_steps=eval_steps,
        queue_depth=queue_depth, max_staleness=max_staleness,
        data_plane=data_plane, plane_codec=plane_codec,
        transfer_pad_s=transfer_pad_s,
        make_device_ingest_update=make_device_ingest_update,
        publish_hook=publish_hook,
    )


# -- AOT warmup registry (utils/compile_cache.py, ISSUE 4) ------------------
# Registers the host-path act / ingest+update / greedy programs (skipped
# where the numpy mirror replaces them) and the fused step/eval pair, so
# a background warmup compiles them while the env pool spawns/resets.
from actor_critic_tpu.utils import compile_cache as _compile_cache  # noqa: E402

_compile_cache.register_offpolicy_warmups(
    "ddpg", ("ddpg", "td3"),
    init_learner=init_learner,
    make_host_act_fn=make_host_act_fn,
    make_host_ingest_update=make_host_ingest_update,
    make_greedy_act=make_greedy_act,
    init_state=init_state,
    make_train_step=make_train_step,
    make_eval_fn=make_eval_fn,
)
