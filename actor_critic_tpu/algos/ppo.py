"""PPO-clip — fused rollout + in-jit epoch/minibatch updates.

Capability parity with the reference's PPO config (BASELINE.json:8:
"PPO-clip on MuJoCo HalfCheetah (GAE-λ, continuous Gaussian policy)";
reference mount empty at survey, SURVEY.md §0), built TPU-first:

- For pure-JAX envs the whole iteration (rollout scan → GAE → E epochs ×
  M minibatches of clipped-surrogate updates) is ONE jitted program; the
  epoch/minibatch loops are `lax.scan`s over shuffled index blocks, so
  XLA sees static shapes and a fixed-length loop nest (SURVEY §3.1).
- For host envs (MuJoCo via envs/host_pool.py) the same `ppo_update`
  is reused as a single jitted device program per iteration, with one
  host→device batch transfer (SURVEY §7.2 item 2).

Losses: clipped ratio surrogate, clipped value MSE, entropy bonus;
metrics include approx-KL and clip fraction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from actor_critic_tpu import telemetry
from actor_critic_tpu.algos.common import (
    TrainState,
    anneal_fraction,
    episode_metrics_update,
    gae_targets as gae,
    init_rollout,
    linear_anneal,
    rollout_scan,
    truncation_bootstrap_rewards,
)
from actor_critic_tpu.algos.metrics import aggregate_metrics
from actor_critic_tpu.envs.jax_env import JaxEnv
from actor_critic_tpu.models.networks import ActorCriticDiscrete, ActorCriticGaussian
from actor_critic_tpu.ops.returns import LOG_RATIO_CAP, normalize_advantages
from actor_critic_tpu.parallel import mesh as pmesh
from actor_critic_tpu.utils import compile_cache as _compile_cache


@dataclasses.dataclass(frozen=True)
class PPOConfig:
    num_envs: int = 64
    rollout_steps: int = 128  # T
    epochs: int = 4
    num_minibatches: int = 4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    vf_clip: float = 0.2  # <=0 disables value clipping
    lr: float = 3e-4
    value_coef: float = 0.5
    entropy_coef: float = 0.0
    max_grad_norm: float = 0.5
    hidden: tuple[int, ...] = (64, 64)
    normalize_adv: bool = True
    bf16_compute: bool = False
    # Linear annealing over the first `anneal_iters` iterations (0 = off):
    # lr → lr_final (per optimizer step, scaled by epochs×minibatches) and
    # clip_eps → clip_eps_final. Long MuJoCo runs (HalfCheetah → 3000)
    # want both; round-2 verdict carried this as a known gap.
    anneal_iters: int = 0
    lr_final: Optional[float] = None
    clip_eps_final: Optional[float] = None
    entropy_coef_final: Optional[float] = None


class PPOBatch(NamedTuple):
    """Flattened experience batch for the update loop ([B, ...])."""

    obs: jax.Array
    action: jax.Array
    log_prob_old: jax.Array
    value_old: jax.Array
    advantage: jax.Array
    ret: jax.Array


def make_network(env_spec, cfg: PPOConfig):
    dtype = jnp.bfloat16 if cfg.bf16_compute else jnp.float32
    if env_spec.discrete:
        return ActorCriticDiscrete(
            num_actions=env_spec.action_dim, hidden=cfg.hidden,
            pixel_obs=env_spec.pixel_obs, compute_dtype=dtype,
        )
    return ActorCriticGaussian(
        action_dim=env_spec.action_dim, hidden=cfg.hidden, compute_dtype=dtype
    )


def make_eval_fn(env: JaxEnv, cfg: "PPOConfig"):
    """Greedy (mode-action) eval program (SURVEY.md §3.4)."""
    from actor_critic_tpu.algos.common import make_mode_eval

    return make_mode_eval(env, make_network(env.spec, cfg))


def make_optimizer(cfg: PPOConfig) -> optax.GradientTransformation:
    lr = cfg.lr
    if cfg.anneal_iters > 0 and cfg.lr_final is not None:
        # The optimizer steps epochs×minibatches times per iteration, so
        # the schedule horizon is in optimizer steps, not iterations.
        lr = optax.linear_schedule(
            cfg.lr, cfg.lr_final,
            cfg.anneal_iters * cfg.epochs * cfg.num_minibatches,
        )
    return optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adam(lr, eps=1e-5),
    )


def clip_eps_at(cfg: PPOConfig, progress: Optional[jax.Array]) -> jax.Array:
    """Current clip-ε under the linear anneal; `progress` per the
    common.anneal_fraction contract."""
    return linear_anneal(cfg.clip_eps, cfg.clip_eps_final, progress)


def entropy_coef_at(cfg: PPOConfig, progress: Optional[jax.Array]) -> jax.Array:
    """Current entropy coefficient under the linear anneal."""
    return linear_anneal(cfg.entropy_coef, cfg.entropy_coef_final, progress)


def anneal_progress(cfg: PPOConfig, update_step: jax.Array) -> Optional[jax.Array]:
    """update_step → clipped [0, 1] anneal fraction (None when off)."""
    return anneal_fraction(update_step, cfg.anneal_iters)


def ppo_loss(
    params: Any,
    apply_fn: Callable,
    batch: PPOBatch,
    cfg: PPOConfig,
    axis_name: Optional[str] = None,
    clip_eps: Optional[jax.Array] = None,
    entropy_coef: Optional[jax.Array] = None,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Clipped-surrogate + clipped-value + entropy loss on a minibatch.
    `clip_eps`/`entropy_coef` override the cfg constants (annealing
    threads the current values through here)."""
    if clip_eps is None:
        clip_eps = jnp.asarray(cfg.clip_eps)
    if entropy_coef is None:
        entropy_coef = jnp.asarray(cfg.entropy_coef)
    dist, value = apply_fn(params, batch.obs)
    log_prob = dist.log_prob(batch.action)
    # All loss reductions carry an explicit fp32 accumulator: the network
    # heads already cast their outputs up, so this is bit-identical in
    # fp32 mode, and under --update-dtype bf16 it pins the precision-
    # discipline contract (bf16 compute, fp32 accumulation) at the site
    # where a future bf16-typed operand would otherwise narrow the sum.
    entropy = jnp.mean(dist.entropy(), dtype=jnp.float32)

    adv = batch.advantage
    if cfg.normalize_adv:
        adv = normalize_advantages(adv, axis_name)

    log_ratio = log_prob - batch.log_prob_old
    # LOG_RATIO_CAP (ISSUE 14): an unbounded ratio exp overflows to inf
    # under policy drift and inf × 0 advantage is nan — clipping the
    # RATIO two lines down is too late (the inf already happened). The
    # cap is bit-identical for every in-range ratio.
    ratio = jnp.exp(jnp.minimum(log_ratio, LOG_RATIO_CAP))
    surr1 = ratio * adv
    surr2 = jnp.clip(ratio, 1.0 - clip_eps, 1.0 + clip_eps) * adv
    pg_loss = -jnp.mean(jnp.minimum(surr1, surr2), dtype=jnp.float32)

    if cfg.vf_clip > 0:
        v_clipped = batch.value_old + jnp.clip(
            value - batch.value_old, -cfg.vf_clip, cfg.vf_clip
        )
        v_loss = 0.5 * jnp.mean(
            jnp.maximum((value - batch.ret) ** 2, (v_clipped - batch.ret) ** 2),
            dtype=jnp.float32,
        )
    else:
        v_loss = 0.5 * jnp.mean((value - batch.ret) ** 2, dtype=jnp.float32)

    loss = pg_loss + cfg.value_coef * v_loss - entropy_coef * entropy
    # Schulman's low-variance KL estimator: E[(r-1) - log r].
    approx_kl = jnp.mean((ratio - 1.0) - log_ratio, dtype=jnp.float32)
    clip_frac = jnp.mean((jnp.abs(ratio - 1.0) > clip_eps).astype(jnp.float32))
    return loss, {
        "loss": loss,
        "pg_loss": pg_loss,
        "v_loss": v_loss,
        "entropy": entropy,
        "approx_kl": approx_kl,
        "clip_frac": clip_frac,
    }


def should_unroll_update(env_spec, cfg: "PPOConfig") -> bool:
    """Default policy for `ppo_update(unroll=...)`: fully unroll the
    epoch/minibatch loop nest when the torso is a CNN, the backend is
    XLA:CPU (whose conv custom-call cannot fire inside a scan body —
    measured 37× slower), and the nest is small enough that straight-
    line compilation stays cheap. TPU/GPU always scan."""
    return (
        env_spec.pixel_obs
        and jax.default_backend() == "cpu"
        and cfg.epochs * cfg.num_minibatches <= 64
    )


def ppo_update(
    params: Any,
    opt_state: Any,
    batch: PPOBatch,
    key: jax.Array,
    apply_fn: Callable,
    opt: optax.GradientTransformation,
    cfg: PPOConfig,
    axis_name: Optional[str] = None,
    progress: Optional[jax.Array] = None,
    unroll: bool = False,
) -> tuple[Any, Any, dict[str, jax.Array]]:
    """E epochs × M shuffled minibatches of PPO updates, all in-jit.

    The batch size B must be divisible by num_minibatches. Under dp,
    each device shuffles its local shard; gradients pmean per minibatch
    (the ICI analogue of the reference's per-step NCCL all-reduce).
    `progress` is the anneal fraction in [0, 1] (clip-ε schedule).
    `unroll=True` fully unrolls the epoch/minibatch scans — identical
    math, straight-line XLA. Load-bearing on XLA:CPU with CNN torsos,
    where convolutions inside a scan body cannot use the fast conv
    custom-call and fall back to naive codegen (measured 37× slower on
    a 1280-sample pixel minibatch); TPU lowers scanned convs fine. Use
    `should_unroll_update` for the default policy.
    """
    B = batch.obs.shape[0]
    mb = B // cfg.num_minibatches
    if B % cfg.num_minibatches != 0:
        raise ValueError(f"batch {B} % minibatches {cfg.num_minibatches} != 0")

    clip_eps = clip_eps_at(cfg, progress)
    ent_coef = entropy_coef_at(cfg, progress)
    grad_fn = jax.value_and_grad(ppo_loss, has_aux=True)

    def minibatch_body(carry, idx):
        params, opt_state = carry
        mb_batch = jax.tree.map(lambda x: x[idx], batch)
        (_, metrics), grads = grad_fn(
            params, apply_fn, mb_batch, cfg, axis_name, clip_eps, ent_coef
        )
        grads = pmesh.pmean_tree(grads, axis_name)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), metrics

    def epoch_body(carry, ekey):
        perm = jax.random.permutation(ekey, B)
        idxs = perm.reshape(cfg.num_minibatches, mb)
        return jax.lax.scan(minibatch_body, carry, idxs, unroll=unroll)

    epoch_keys = jax.random.split(key, cfg.epochs)
    (params, opt_state), metrics = jax.lax.scan(
        epoch_body, (params, opt_state), epoch_keys, unroll=unroll
    )
    # metrics: [epochs, minibatches] — report the mean over the loop nest.
    metrics = jax.tree.map(jnp.mean, metrics)
    return params, opt_state, metrics


def init_state(env: JaxEnv, cfg: PPOConfig, key: jax.Array) -> TrainState:
    net = make_network(env.spec, cfg)
    opt = make_optimizer(cfg)
    key, pkey, rkey = jax.random.split(key, 3)
    dummy = jnp.zeros((1, *env.spec.obs_shape), env.spec.obs_dtype)
    params = net.init(pkey, dummy)
    rstate = init_rollout(env, rkey, cfg.num_envs)
    E = cfg.num_envs
    return TrainState(
        params=params,
        opt_state=opt.init(params),
        rollout=rstate,
        key=key,
        update_step=jnp.zeros((), jnp.int32),
        ep_return=jnp.zeros((E,)),
        ep_length=jnp.zeros((E,)),
        avg_return=jnp.zeros(()),
    )


def make_policy_step(env_spec, cfg: PPOConfig):
    """Jitted (params, obs, key) → (action, log_prob, value) for host loops."""
    net = make_network(env_spec, cfg)

    @jax.jit
    def policy_step(params, obs, key):
        dist, value = net.apply(params, obs)
        action = dist.sample(key)
        return action, dist.log_prob(action), value

    return policy_step


def make_host_update_fn(env_spec, cfg: PPOConfig, can_truncate: bool = True):
    """The UNJITTED per-iteration update body behind
    `make_host_update_step` — factored out (ISSUE 13) so the device
    data plane can inline it after its in-jit ring gather+decode
    (`make_device_update_step` with correction="none") and stay
    bit-identical to the lockstep program: one body, two dispatch
    wrappers, zero drift surface."""
    net = make_network(env_spec, cfg)
    opt = make_optimizer(cfg)
    apply_fn = net.apply

    def update(
        params, opt_state, obs, action, log_prob, value, reward, done,
        terminated, final_obs, last_obs, key,
        final_values=None, bootstrap_value=None, progress=None,
    ):
        T, E = reward.shape
        if bootstrap_value is None:
            _, bootstrap_value = apply_fn(params, last_obs)
        if can_truncate:
            if final_values is None:
                _, fv = apply_fn(
                    params, final_obs.reshape(T * E, *final_obs.shape[2:])
                )
                final_values = fv.reshape(T, E)
            truncated = done * (1.0 - terminated)
            rewards = reward + cfg.gamma * final_values * truncated
        else:
            rewards = reward
        advantages, returns = gae(
            rewards, value, done, bootstrap_value, cfg.gamma, cfg.gae_lambda
        )
        batch = PPOBatch(
            obs=obs.reshape(T * E, *obs.shape[2:]),
            action=action.reshape(T * E, *action.shape[2:]),
            log_prob_old=log_prob.reshape(T * E),
            value_old=value.reshape(T * E),
            advantage=advantages.reshape(T * E),
            ret=returns.reshape(T * E),
        )
        return ppo_update(
            params, opt_state, batch, key, apply_fn, opt, cfg,
            progress=progress, unroll=should_unroll_update(env_spec, cfg),
        )

    return update


def make_host_update_step(env_spec, cfg: PPOConfig, can_truncate: bool = True):
    """Jitted per-iteration update for host-collected trajectories.

    Takes time-major [T, E] arrays (one host→device transfer per
    iteration — SURVEY §3.1 boundary fix), computes truncation-aware GAE
    on-device, and runs the in-jit epoch/minibatch PPO update.

    `final_values`/`bootstrap_value` may be supplied externally (overlap
    mode computes them with the host mirror so EVERY value estimate in
    the GAE — per-step, truncation-bootstrap, and rollout bootstrap —
    comes from the same stale behavior params; passing None recomputes
    them in-jit with the current params, correct for the synchronous
    path where behavior == current).
    """
    return jax.jit(make_host_update_fn(env_spec, cfg, can_truncate))


def async_block_spec(
    spec, cfg: PPOConfig, actors: int, correction: str = "vtrace"
) -> dict:
    """dict[name → jax.ShapeDtypeStruct] of the [T, E_a] block an async
    ActorService pushes (E_a = num_envs // actors; actions are int64 —
    async acting is always the numpy mirror). The device trajectory
    ring's storage spec (`data_plane/ring.py`), shared by the drivers
    and the warmup planners so their signatures can never drift.
    `correction="none"` blocks additionally carry the mirror-computed
    `final_values`/`bootstrap_value` (the `block_extras` contract)."""
    import numpy as np

    actors = max(int(actors), 1)
    T = cfg.rollout_steps
    E = cfg.num_envs // actors
    s = _compile_cache.array_struct

    def obs_s(lead):
        return s((*lead, *spec.obs_shape), spec.obs_dtype)

    if spec.discrete:
        action = s((T, E), np.int64)  # mirror samples with np.argmax
    else:
        action = s((T, E, spec.action_dim), np.float32)
    out = {
        "obs": obs_s((T, E)),
        "action": action,
        "log_prob": s((T, E), np.float32),
        "value": s((T, E), np.float32),
        "reward": s((T, E), np.float32),
        "done": s((T, E), np.float32),
        "terminated": s((T, E), np.float32),
        "final_obs": obs_s((T, E)),
        "last_obs": obs_s((E,)),
    }
    if correction == "none":
        out["final_values"] = s((T, E), np.float32)
        out["bootstrap_value"] = s((E,), np.float32)
    return out


def make_device_update_step(
    env_spec,
    cfg: PPOConfig,
    ring_codecs: dict,
    can_truncate: bool = True,
    correction: str = "vtrace",
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
):
    """Device-data-plane learner program (ISSUE 13): ONE jitted dispatch
    gathers the consumed slot from the HBM trajectory ring, decodes it
    through the ring's codecs, and runs the update — the V-trace
    correction itself is `make_async_update_fn`'s body unchanged, and
    `correction="none"` inlines `make_host_update_fn`'s body, so with
    the all-raw fp32 codec the program computes bit-for-bit what the
    host plane's update computes (the depth-1 equivalence tests pin
    this). Signature: `(params, opt_state, ring_state, slot, key,
    progress=None)` — the slot index scalar is the ONLY thing the
    learner transfers per consumed block."""
    from actor_critic_tpu.data_plane import ring as dp_ring

    if correction == "none":
        body = make_host_update_fn(env_spec, cfg, can_truncate)
    else:
        body = make_async_update_fn(
            env_spec, cfg, can_truncate, correction, rho_bar, c_bar
        )

    @jax.jit
    def device_update(params, opt_state, ring_state, slot, key,
                      progress=None):
        b = dp_ring.gather_block(ring_state, slot, ring_codecs)
        kwargs = {}
        if correction == "none":
            kwargs["final_values"] = b["final_values"]
            kwargs["bootstrap_value"] = b["bootstrap_value"]
        if progress is not None:
            kwargs["progress"] = progress
        return body(
            params, opt_state, b["obs"], b["action"], b["log_prob"],
            b["value"], b["reward"], b["done"], b["terminated"],
            b["final_obs"], b["last_obs"], key, **kwargs,
        )

    return device_update


def init_host_params(env_spec, cfg: PPOConfig, key: jax.Array):
    net = make_network(env_spec, cfg)
    dummy = jnp.zeros((1, *env_spec.obs_shape), jnp.float32)
    params = net.init(key, dummy)
    opt_state = make_optimizer(cfg).init(params)
    return params, opt_state


def make_greedy_act(env_spec, cfg: PPOConfig):
    """Mode-action policy for host eval (host_loop.host_evaluate)."""
    net = make_network(env_spec, cfg)

    def act(params, obs):
        dist, _ = net.apply(params, obs)
        return dist.mode()

    return act


def train_host(
    pool,
    cfg: PPOConfig,
    num_iterations: int,
    seed: int = 0,
    log_every: int = 10,
    log_fn: Optional[Callable[[int, dict], None]] = None,
    eval_every: int = 0,
    eval_envs: int = 4,
    eval_steps: int = 1000,
    ckpt=None,
    save_every: int = 0,
    resume: bool = False,
    overlap: bool = True,
):
    """PPO on a HostEnvPool (MuJoCo etc.): host rollout, device update.

    With `eval_every > 0` a frozen-stats eval pool runs a greedy (mode
    action) episode sweep on that cadence; with `ckpt` the run is
    restart-idempotent on the device side (params/opt/PRNG/normalizer
    stats restore exactly; host envs restart fresh episodes — see
    host_loop.host_resume).

    With `overlap` (default) collection acts via the numpy host mirror
    (models/host_actor.py) using params ONE update stale, so the jitted
    epoch/minibatch update runs on-device while the next rollout is
    collected. The recorded log_prob/value come from the same (stale)
    behavior params, so the clipped importance ratio remains a correct
    off-policy estimator — the same staleness-with-correction design the
    IMPALA trainer formalizes. Returns (params, opt_state, history).
    """
    import numpy as np

    from actor_critic_tpu.algos.host_loop import (
        BlockBuffers,
        EpisodeTracker,
        host_ckpt_state,
        host_collect,
        host_evaluate,
        host_maybe_save,
        host_resume,
        maybe_log,
    )

    key = jax.random.key(seed)
    key, pkey = jax.random.split(key)
    params, opt_state = init_host_params(pool.spec, cfg, pkey)
    policy_step = make_policy_step(pool.spec, cfg)
    update = make_host_update_step(pool.spec, cfg, can_truncate=True)

    eval_pool = greedy = host_greedy = None
    if eval_every > 0:
        from actor_critic_tpu.models import host_actor

        eval_pool = pool.eval_pool(eval_envs)
        greedy = jax.jit(make_greedy_act(pool.spec, cfg))
        if host_actor.supports_mirror(jax.device_get(params)):
            # Mirror the mode policy on the host: a device round-trip per
            # eval step (~26 ms on the tunnel) would otherwise dominate
            # every eval sweep (host_actor.make_ppo_host_greedy).
            host_greedy = host_actor.make_ppo_host_greedy(pool.spec, cfg)

    start_it = 0
    if ckpt is not None and resume:
        template = host_ckpt_state(
            pool, params=params, opt_state=opt_state, key=key
        )
        restored, start_it = host_resume(ckpt, template, pool)
        if restored is not None:
            params = restored["params"]
            opt_state = restored["opt_state"]
            key = restored["key"]

    obs = pool.reset()
    tracker = EpisodeTracker(pool.num_envs)
    history: list = []
    # Double-buffered [T, E] block storage shared across iterations: the
    # async-dispatched transfer/update of block N overlaps collection of
    # block N+1 into the other buffer (host_loop.BlockBuffers).
    buffers = BlockBuffers(cfg.rollout_steps)

    host_policy = host_params = host_value = None
    if overlap:
        from actor_critic_tpu.models import host_actor

        np_params = jax.device_get(params)
        if host_actor.supports_mirror(np_params):
            host_policy = host_actor.make_ppo_host_policy(pool.spec, cfg)
            host_value = host_actor.make_ppo_host_value(pool.spec, cfg)
            host_params = np_params
            rng = np.random.default_rng(seed + 0x5EED)

    for it in range(start_it, num_iterations):
        # Iteration boundary for any armed on-demand profile window.
        telemetry.profiler_tick()
        with telemetry.span("iteration", it=it + 1):

            if host_policy is not None:

                def policy_act(o):
                    action, logp, value = host_policy(host_params, o, rng)
                    return action, {"log_prob": logp, "value": value}

            else:

                def policy_act(o):
                    nonlocal key
                    key, akey = jax.random.split(key)
                    # jaxlint: disable=transfer-discipline (deliberate:
                    # the non-mirror acting path uploads obs per step —
                    # same round trip the pragma below documents)
                    action, logp, value = policy_step(params, jnp.asarray(o), akey)
                    # jaxlint: disable=host-sync (deliberate: without a
                    # numpy mirror, acting round-trips the device and the
                    # pool needs concrete arrays — the non-overlap path)
                    return np.asarray(action), {
                        "log_prob": np.asarray(logp),
                        "value": np.asarray(value),
                    }

            obs, block = host_collect(
                pool, obs, cfg.rollout_steps, policy_act, tracker,
                buffers=buffers,
            )
            key, ukey = jax.random.split(key)
            with telemetry.span("host_to_device"):
                # jaxlint: disable=transfer-discipline (deliberate: the
                # lockstep per-block upload — one transfer per collected
                # block by design; perfsan budgets the bytes)
                arrays = {k: jnp.asarray(v) for k, v in block.items()}
            extra_values = {}
            if host_policy is not None:
                # All GAE value baselines from the SAME stale behavior params
                # as the recorded per-step values (mirror-computed host-side);
                # mixing parameter versions would bias the TD residuals at
                # truncation boundaries and the value-clip anchor.
                T_, E_ = block["reward"].shape
                fv = host_value(
                    host_params,
                    block["final_obs"].reshape(T_ * E_, *block["final_obs"].shape[2:]),
                ).reshape(T_, E_)
                # jaxlint: disable=transfer-discipline (part of the
                # same per-block upload: mirror-computed baselines ride
                # with the block)
                extra_values = dict(
                    final_values=jnp.asarray(fv),
                    bootstrap_value=jnp.asarray(host_value(host_params, obs)),
                )
                # Next rollout's acting params: this update's INPUT, fetched
                # before the dispatch (concrete — the previous update finished
                # during collection — so no wait); the update dispatched below
                # then overlaps the next rollout.
                # jaxlint: disable=transfer-discipline (deliberate: the
                # mirror's acting-params refresh — concrete, no wait)
                host_params = jax.device_get(params)
            if cfg.anneal_iters > 0:
                # jaxlint: disable=transfer-discipline (scalar anneal
                # progress — 4 bytes ride the dispatch)
                extra_values["progress"] = jnp.asarray(
                    min(it / cfg.anneal_iters, 1.0), jnp.float32
                )
            # Async dispatch: the span measures host-side enqueue only
            # (fencing here would cost the rollout/update overlap).
            with telemetry.span("update", dispatch="async"):
                # jaxlint: disable=donation-discipline,transfer-discipline
                # (donation withheld: the overlap path's mirror and the
                # resume template still read the input params tree
                # around the dispatch, and flipping donation re-lowers
                # every warmed update program — the ROADMAP kernel-level
                # item owns that change, gated by perfsan's budgets; the
                # jnp.asarray is the bootstrap obs riding the block
                # upload)
                params, opt_state, metrics = update(
                    params, opt_state,
                    arrays["obs"], arrays["action"], arrays["log_prob"],
                    arrays["value"], arrays["reward"], arrays["done"],
                    arrays["terminated"], arrays["final_obs"],
                    jnp.asarray(obs), ukey, **extra_values,
                )
            extra = {"env_steps": (it + 1) * cfg.rollout_steps * pool.num_envs}
            if eval_pool is not None and (it + 1) % eval_every == 0:
                if host_greedy is not None:
                    # device_get blocks until the in-flight update lands, so
                    # eval always sees the CURRENT params.
                    # jaxlint: disable=transfer-discipline (eval
                    # cadence, not the hot collect loop)
                    ev_params = jax.device_get(params)
                    # jaxlint: disable=transfer-discipline (mirror
                    # eval — np.asarray touches no device value)
                    eval_act = lambda o: np.asarray(host_greedy(ev_params, o))  # noqa: E731
                else:
                    # jaxlint: disable=transfer-discipline (eval
                    # cadence: greedy eval must hand gym concrete host
                    # actions, once per eval step)
                    eval_act = lambda o: np.asarray(  # noqa: E731
                        greedy(params, jnp.asarray(o))
                    )
                with telemetry.span("eval"):
                    extra["eval_return"] = host_evaluate(
                        eval_pool, eval_act, max_steps=eval_steps
                    )
            maybe_log(
                it, log_every, metrics, tracker, history, log_fn,
                extra=extra,
                num_iterations=num_iterations,
                # eval rows and the first post-resume iteration never drop
                force="eval_return" in extra or it == start_it,
            )
            host_maybe_save(
                ckpt, it + 1, save_every, num_iterations, pool, metrics,
                params=params, opt_state=opt_state, key=key,
            )
    if ckpt is not None:
        ckpt.wait()  # the final async save must be durable before return
    return params, opt_state, history


def make_async_update_fn(
    env_spec,
    cfg: PPOConfig,
    can_truncate: bool = True,
    correction: str = "vtrace",
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
    axis_name: Optional[str] = None,
):
    """The UNJITTED V-trace-corrected update body behind
    `make_async_update_step`, with an optional mesh `axis_name`: the
    multi-host learner (`parallel/multihost.py`) shard_maps this over
    the global dp mesh so the per-minibatch gradient pmean becomes the
    cross-process all-reduce — exactly how `parallel/dp.py` scales the
    fused step. Single-host callers leave `axis_name=None` (the pmean
    degrades to a no-op) and use `make_async_update_step`'s jit."""
    if correction != "vtrace":
        raise ValueError(f"unknown correction: {correction!r}")
    from actor_critic_tpu.algos.common import corrected_advantages

    net = make_network(env_spec, cfg)
    opt = make_optimizer(cfg)
    apply_fn = net.apply

    def async_update(
        params, opt_state, obs, action, log_prob, value, reward, done,
        terminated, final_obs, last_obs, key, progress=None,
    ):
        T, E = reward.shape
        flat_obs = obs.reshape(T * E, *obs.shape[2:])
        flat_act = action.reshape(T * E, *action.shape[2:])
        # Targets come from the LEARNER's params — that is the whole
        # correction: the trajectory was acted under older params.
        dist, values_cur = apply_fn(params, flat_obs)
        target_lp = jax.lax.stop_gradient(
            dist.log_prob(flat_act).reshape(T, E)
        )
        values_cur = jax.lax.stop_gradient(values_cur.reshape(T, E))
        _, bootstrap = apply_fn(params, last_obs)
        bootstrap = jax.lax.stop_gradient(bootstrap)
        if can_truncate:
            _, fv = apply_fn(
                params, final_obs.reshape(T * E, *final_obs.shape[2:])
            )
            fv = jax.lax.stop_gradient(fv.reshape(T, E))
            truncated = done * (1.0 - terminated)
            rewards = reward + cfg.gamma * fv * truncated
        else:
            rewards = reward
        pg_adv, vs, mean_rho = corrected_advantages(
            target_lp, log_prob, rewards, values_cur, done, bootstrap,
            cfg.gamma, cfg.gae_lambda, rho_bar=rho_bar, c_bar=c_bar,
            correction="vtrace",
        )
        batch = PPOBatch(
            obs=flat_obs,
            action=flat_act,
            log_prob_old=log_prob.reshape(T * E),
            value_old=value.reshape(T * E),
            advantage=pg_adv.reshape(T * E),
            ret=vs.reshape(T * E),
        )
        new_params, new_opt_state, metrics = ppo_update(
            params, opt_state, batch, key, apply_fn, opt, cfg,
            axis_name, progress=progress,
            unroll=should_unroll_update(env_spec, cfg),
        )
        metrics = dict(metrics, mean_rho=mean_rho)
        # Under a mesh axis the per-shard metric means differ (each
        # shard saw its own minibatches); reduce so the declared
        # replicated output really is replicated.
        metrics = pmesh.pmean_tree(metrics, axis_name)
        return new_params, new_opt_state, metrics

    return async_update


def make_async_update_step(
    env_spec,
    cfg: PPOConfig,
    can_truncate: bool = True,
    correction: str = "vtrace",
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
):
    """Staleness-corrected learner update for the async actor–learner
    path (ISSUE 6): same positional signature as `make_host_update_step`
    minus the mirror-value kwargs, on per-actor `[T, E_a]` blocks.

    `correction="vtrace"` re-evaluates π/V at the stored observations
    under the LEARNER's params and builds V-trace value targets and
    policy-gradient advantages from the recorded BEHAVIOR log-probs
    (`common.corrected_advantages`, the machinery shared with
    `impala.py`), then reuses the batch through the in-jit
    epoch/minibatch clipped-surrogate loop — IMPACT-style sample reuse
    with a clipped-target correction; the recorded behavior value stays
    the value-clip anchor. `correction="none"` returns
    `make_host_update_step` itself (identical program to the lockstep
    driver's — the depth-1 equivalence tests rely on this).
    """
    if correction == "none":
        return make_host_update_step(env_spec, cfg, can_truncate)
    return jax.jit(
        make_async_update_fn(
            env_spec, cfg, can_truncate, correction, rho_bar, c_bar
        )
    )


def train_host_async(
    pools,
    cfg: PPOConfig,
    num_iterations: int,
    seed: int = 0,
    log_every: int = 10,
    log_fn: Optional[Callable[[int, dict], None]] = None,
    eval_every: int = 0,
    eval_envs: int = 4,
    eval_steps: int = 1000,
    updates_per_block: int = 1,
    queue_depth: int = 4,
    max_staleness: Optional[int] = 8,
    correction: str = "vtrace",
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
    strict_lockstep: bool = False,
    ckpt=None,
    save_every: int = 0,
    resume: bool = False,
    data_plane: str = "host",
    plane_codec: str = "fp32",
    transfer_pad_s: float = 0.0,
    publish_hook: Optional[Callable[[int, Any], None]] = None,
):
    """Async actor–learner PPO on host env pools (ISSUE 6 tentpole).

    One `traj_queue.ActorService` thread per pool collects `[K, E_a]`
    blocks through the numpy actor mirror (behavior params refreshed
    from the `PolicyPublisher` once per block) and pushes them into a
    bounded `TrajQueue`; this (learner) thread drains the queue
    continuously — a straggler actor slows only its own contribution —
    and corrects behavior-version lag with V-trace targets
    (`make_async_update_step`), reusing each block for
    `updates_per_block` shuffled epoch/minibatch passes (IMPACT-style).
    A full queue drops its OLDEST block rather than blocking actors;
    `max_staleness` additionally drops blocks that aged past the bound
    while queued. `num_iterations` counts blocks consumed.

    Requires the numpy mirror (MLP torsos — every host-env PPO config);
    pixel pools must run the lockstep `train_host`. With `ckpt` the run
    checkpoints on the consumed-block cadence: the save tree carries
    the device state (params/opt/PRNG) plus ALL A per-actor pools'
    normalizer states (`host_loop.async_host_ckpt_state` — each actor
    pool runs independent running stats, so every one must round-trip),
    and `resume` restores them exactly; actor collection restarts fresh
    episodes, same contract as `train_host`. `--async-actors` must not
    change across a resume. `strict_lockstep` is the test hook:
    with one actor, `queue_depth=1`, `updates_per_block=1` and
    `correction="none"` the run is bit-for-bit `train_host`
    (tests/test_async_host.py).

    `data_plane="device"` (ISSUE 13) swaps the host-numpy TrajQueue for
    the HBM-resident `data_plane.DeviceTrajRing`: actors enqueue
    encoded blocks (`plane_codec` ∈ fp32/f16/int8 — one small
    host→device put at collection time, on the ACTOR thread), and the
    learner's jitted program gathers + decodes the slot in-jit — zero
    host→device transfers per consumed block. The fp32 codec at depth 1
    with `correction="none"` stays bitwise-equal to the host plane.
    `transfer_pad_s` is the tunnel-wall testbed knob (bench A/B): it
    pads every block transfer — the learner-side `jnp.array` on the
    host plane, the actor-side enqueue put on the device plane.

    Returns (params, opt_state, history).
    """
    import threading
    import time as _time

    import numpy as np

    from actor_critic_tpu.algos.host_loop import (
        MergedEpisodeTracker,
        async_host_ckpt_state,
        async_host_maybe_save,
        async_host_resume,
        host_evaluate,
        maybe_log,
    )
    from actor_critic_tpu.algos.traj_queue import (
        ActorService,
        PolicyPublisher,
        TrajQueue,
        consume_block,
        validate_pools,
    )
    from actor_critic_tpu.models import host_actor

    spec, E_a = validate_pools(pools)
    if updates_per_block < 1:
        raise ValueError("updates_per_block must be >= 1")
    if data_plane not in ("host", "device"):
        raise ValueError(
            f"data_plane must be 'host' or 'device', got {data_plane!r}"
        )
    use_device_plane = data_plane == "device"

    key = jax.random.key(seed)
    key, pkey = jax.random.split(key)
    params, opt_state = init_host_params(spec, cfg, pkey)
    np_params = jax.device_get(params)
    if not host_actor.supports_mirror(np_params):
        raise ValueError(
            "async actor–learner mode needs the numpy actor mirror "
            "(MLP torso; models/host_actor.py) — pixel pools must run "
            "the lockstep train_host"
        )
    host_policy = host_actor.make_ppo_host_policy(spec, cfg)
    host_value = host_actor.make_ppo_host_value(spec, cfg)
    host_greedy = host_actor.make_ppo_host_greedy(spec, cfg)
    if use_device_plane:
        from actor_critic_tpu.data_plane import ring as dp_ring

        queue = dp_ring.DeviceTrajRing(
            depth=queue_depth,
            block_spec=async_block_spec(spec, cfg, len(pools), correction),
            codec=plane_codec,
            max_staleness=None if strict_lockstep else max_staleness,
            policy="block" if strict_lockstep else "drop_oldest",
            transfer_pad_s=transfer_pad_s,
        )
        update = make_device_update_step(
            spec, cfg, queue.codecs, can_truncate=True,
            correction=correction, rho_bar=rho_bar, c_bar=c_bar,
        )
    else:
        queue = TrajQueue(
            depth=queue_depth,
            max_staleness=None if strict_lockstep else max_staleness,
            policy="block" if strict_lockstep else "drop_oldest",
        )
        update = make_async_update_step(
            spec, cfg, can_truncate=True, correction=correction,
            rho_bar=rho_bar, c_bar=c_bar,
        )

    def make_act_fn(actor_params, rng):
        def act(o):
            action, logp, value = host_policy(actor_params, o, rng)
            return action, {"log_prob": logp, "value": value}

        return act

    block_extras = None
    if correction == "none":
        # The lockstep update wants truncation/bootstrap values from the
        # SAME behavior params as the recorded per-step values (the
        # overlap-mode contract); the V-trace update recomputes every
        # value under the learner's params instead.
        def block_extras(actor_params, last_obs, block):
            T_, E_ = block["reward"].shape
            fv = host_value(
                actor_params,
                block["final_obs"].reshape(
                    T_ * E_, *block["final_obs"].shape[2:]
                ),
            ).reshape(T_, E_)
            return {
                "final_values": fv,
                "bootstrap_value": host_value(actor_params, last_obs),
            }

    start_it = 0
    if ckpt is not None and resume:
        # The device plane's checkpoint carries the ring's quantizer
        # stats ONLY (ring storage is transient collection data — the
        # strip_replay contract taken to its limit); resume reattaches
        # a fresh ring that re-encodes against the restored
        # standardization.
        try:
            ring_extra = (
                {"ring_quant": queue.quant_host()}
                if use_device_plane else {}
            )
            template = async_host_ckpt_state(
                pools, params=params, opt_state=opt_state, key=key,
                **ring_extra,
            )
            restored, start_it = async_host_resume(
                ckpt, template, pools, data_plane=data_plane
            )
            if restored is not None:
                params = restored["params"]
                opt_state = restored["opt_state"]
                key = restored["key"]
                np_params = jax.device_get(params)
                if use_device_plane:
                    queue.install_quant(restored["ring_quant"])
        except BaseException:
            # The queue now exists BEFORE resume (the ring's quant
            # template comes from it); a resume failure must not leak
            # its process-wide sampler gauge (and, for the device ring,
            # the HBM storage its stats closure pins).
            queue.close()
            raise

    publisher = PolicyPublisher(np_params, version=start_it)
    stop = threading.Event()
    actors = [
        ActorService(
            i, pool, queue, publisher, cfg.rollout_steps, make_act_fn,
            # Actor 0 reproduces the lockstep driver's rng stream; the
            # others offset by a large prime so no two actors (or their
            # pools' per-env seeds) collide.
            rng=np.random.default_rng(seed + 0x5EED + i * 7919),
            stop=stop, block_extras=block_extras, strict=strict_lockstep,
        )
        for i, pool in enumerate(pools)
    ]

    eval_pool = None
    if eval_every > 0:
        # Built from the LAST pool: in straggler layouts that is the
        # fast actor, so eval sweeps don't pay the straggler's pace.
        eval_pool = pools[-1].eval_pool(eval_envs)

    history: list = []
    metrics: dict = {}
    trackers = MergedEpisodeTracker([a.tracker for a in actors])
    try:
        if start_it < num_iterations:
            # A resume that finds the run complete starts NO actors:
            # collection would only churn the restored normalizer stats.
            for a in actors:
                a.start()
        for it in range(start_it, num_iterations):
            telemetry.profiler_tick()
            # Surface a dead actor's exception EVERY iteration, not only
            # once the queue drains — surviving actors would otherwise
            # keep the run "healthy" while collection silently degrades.
            for a in actors:
                if a.error is not None:
                    raise RuntimeError(
                        f"actor {a.actor_id} died"
                    ) from a.error
            with telemetry.span("iteration", it=it + 1):
                queue.set_consumer_version(it)
                with telemetry.span("queue_wait", it=it + 1):
                    block = consume_block(queue, actors)
                # Behavior params for the actors' NEXT blocks: this
                # update's INPUT params (concrete — the previous
                # dispatched update finished while blocks were being
                # collected), fetched BEFORE the dispatch below.
                # jaxlint: disable=transfer-discipline (deliberate: the
                # per-block behavior-params publish IS the async
                # contract — concrete by the overlap argument above)
                np_behavior = jax.device_get(params)
                publisher.publish(np_behavior, version=it)
                if publish_hook is not None:
                    # Serve-while-training (ISSUE 17): the same frozen-
                    # snapshot cadence feeds the resident serving
                    # policy. The publisher copies its own leaves, so
                    # the hook may hand this tree to PolicyStore.swap.
                    publish_hook(it, np_behavior)
                staleness = max(it - block.version, 0)
                kwargs = {}
                if cfg.anneal_iters > 0:
                    # jaxlint: disable=transfer-discipline (scalar
                    # anneal progress — 4 bytes ride the dispatch)
                    kwargs["progress"] = jnp.asarray(
                        min(it / cfg.anneal_iters, 1.0), jnp.float32
                    )
                if use_device_plane:
                    # Zero-transfer consume: the block already lives in
                    # HBM (the actor enqueued encoded bytes at
                    # collection time); the learner ships only the slot
                    # index and the update program gathers + decodes
                    # in-jit. The phase instant keeps the trace's
                    # host_to_device lane honest about the absence.
                    telemetry.instant("host_to_device", device_plane=True)
                    slot = np.int32(block.slot)
                    with telemetry.span("update", dispatch="async"):
                        for _ in range(updates_per_block):
                            key, ukey = jax.random.split(key)
                            params, opt_state, metrics = queue.run(
                                lambda state: update(
                                    params, opt_state, state, slot,
                                    ukey, **kwargs,
                                )
                            )
                    # Release AFTER the final dispatch against the slot:
                    # dispatch order is device execution order, so any
                    # later enqueue that overwrites it runs after the
                    # gathers (ring.py donation discipline).
                    queue.release(block)
                else:
                    with telemetry.span("host_to_device"):
                        if transfer_pad_s > 0:
                            _time.sleep(transfer_pad_s)  # tunnel testbed
                        # jnp.array, NOT asarray: the CPU backend may
                        # alias numpy buffers zero-copy, and releasing
                        # the slot below lets the next put() rewrite
                        # that memory while the dispatched update still
                        # reads it — the transfer must snapshot the
                        # block.
                        # jaxlint: disable=transfer-discipline (the
                        # host plane's per-block upload by design; the
                        # device branch above removes it — perfsan
                        # budgets both planes)
                        arrays = {
                            k: jnp.array(v) for k, v in block.arrays.items()
                        }
                    queue.release(block)
                    if correction == "none":
                        kwargs["final_values"] = arrays["final_values"]
                        kwargs["bootstrap_value"] = arrays["bootstrap_value"]
                    with telemetry.span("update", dispatch="async"):
                        for _ in range(updates_per_block):
                            key, ukey = jax.random.split(key)
                            # jaxlint: disable=donation-discipline
                            # (withheld: the publisher snapshots and the
                            # IMPACT-style surrogate reuse read the
                            # input tree around the dispatch; flipping
                            # donation re-lowers every warmed program —
                            # the ROADMAP kernel-level item owns it,
                            # gated by perfsan)
                            params, opt_state, metrics = update(
                                params, opt_state,
                                arrays["obs"], arrays["action"],
                                arrays["log_prob"], arrays["value"],
                                arrays["reward"], arrays["done"],
                                arrays["terminated"], arrays["final_obs"],
                                arrays["last_obs"], ukey, **kwargs,
                            )
                qs = queue.stats()
                extra = {
                    "env_steps": sum(a.steps_collected for a in actors),
                    "consumed_env_steps": (it + 1) * cfg.rollout_steps * E_a,
                    # Which actor fed this update — the per-row fairness
                    # signal (a straggler's id should be rare here).
                    "block_actor": block.actor_id,
                    "block_staleness": staleness,
                    "queue_depth": qs["depth"],
                    "queue_drops_full": qs["drops_full"],
                    "queue_drops_stale": qs["drops_stale"],
                    "learner_idle_s": qs["learner_idle_s"],
                }
                if eval_pool is not None and (it + 1) % eval_every == 0:
                    # Blocks on the in-flight update: eval sees CURRENT
                    # params, exactly like the lockstep drivers.
                    # jaxlint: disable=transfer-discipline (eval
                    # cadence, not the per-block consume path)
                    ev_params = jax.device_get(params)
                    with telemetry.span("eval"):
                        extra["eval_return"] = host_evaluate(
                            eval_pool,
                            # jaxlint: disable=host-sync (numpy mirror
                            # eval — ev_params/obs are host arrays, no
                            # device value is touched)
                            lambda o: np.asarray(host_greedy(ev_params, o)),
                            max_steps=eval_steps,
                        )
                maybe_log(
                    it, log_every, metrics, trackers, history, log_fn,
                    extra=extra, num_iterations=num_iterations,
                    force="eval_return" in extra or it == start_it,
                )
                async_host_maybe_save(
                    ckpt, it + 1, save_every, num_iterations, pools,
                    metrics, data_plane=data_plane,
                    params=params, opt_state=opt_state, key=key,
                    **(
                        {"ring_quant": queue.quant_host()}
                        if use_device_plane else {}
                    ),
                )
        if ckpt is not None:
            ckpt.wait()  # the final async save must be durable
    finally:
        stop.set()
        for a in actors:
            a.join(timeout=30.0)
        queue.close()
        if eval_pool is not None:
            eval_pool.close()
    return params, opt_state, history


def _abstract_host_params(spec, cfg: PPOConfig):
    """(params, opt_state) shape/dtype trees via eval_shape — the same
    constructor the host loop uses, no device allocation."""
    from functools import partial as _partial

    return jax.eval_shape(
        _partial(init_host_params, spec, cfg), jax.random.key(0)
    )


@_compile_cache.register_warmup("ppo.make_policy_step")
def _warmup_policy_step(ctx):
    if ctx.fused or ctx.algo != "ppo" or ctx.async_actors:
        return None  # async actors always act through the numpy mirror
    params_abs, _ = _abstract_host_params(ctx.spec, ctx.cfg)
    if _compile_cache.mirror_active(ctx, params_abs):
        return None  # the numpy mirror acts; this program never runs
    jitted = make_policy_step(ctx.spec, ctx.cfg)
    obs = _compile_cache.host_obs_struct(ctx, (ctx.cfg.num_envs,))
    key = _compile_cache.key_struct()
    return lambda: _compile_cache.aot_compile(jitted, params_abs, obs, key)


def _host_update_structs(ctx, E: int, mirror: bool):
    """Abstract argument structs of the host/async update programs at
    env-batch width E ([T, E] blocks; E_a = E // actors in async mode) —
    shared by the lockstep and async warmup planners so their
    signatures can never drift apart."""
    import numpy as np

    cfg, spec = ctx.cfg, ctx.spec
    T = cfg.rollout_steps
    params_abs, opt_abs = _abstract_host_params(spec, cfg)
    s = _compile_cache.array_struct
    if spec.discrete:
        # The mirror samples with np.argmax (int64); the device policy
        # with jax.random.categorical (int32) — the recorded block, and
        # therefore the update's signature, follows the acting path.
        action = s((T, E), np.int64 if mirror else np.int32)
    else:
        action = s((T, E, spec.action_dim), np.float32)
    args = [
        params_abs, opt_abs,
        _compile_cache.host_obs_struct(ctx, (T, E)),        # obs
        action,
        s((T, E), np.float32), s((T, E), np.float32),       # log_prob, value
        s((T, E), np.float32), s((T, E), np.float32),       # reward, done
        s((T, E), np.float32),                              # terminated
        _compile_cache.host_obs_struct(ctx, (T, E)),        # final_obs
        _compile_cache.host_obs_struct(ctx, (E,)),          # last_obs
        _compile_cache.key_struct(),
    ]
    return args


@_compile_cache.register_warmup("ppo.make_host_update_step")
def _warmup_host_update(ctx):
    if ctx.fused or ctx.algo != "ppo" or ctx.async_actors:
        # Async runs dispatch the [T, E_a] program registered under
        # ppo.make_async_update_step instead (even correction="none"
        # reuses this factory's program, but at the per-actor width).
        return None
    import numpy as np

    cfg = ctx.cfg
    T, E = cfg.rollout_steps, cfg.num_envs
    params_abs, _ = _abstract_host_params(ctx.spec, cfg)
    mirror = _compile_cache.mirror_active(ctx, params_abs)
    s = _compile_cache.array_struct
    args = _host_update_structs(ctx, E, mirror)
    kwargs = {}
    if mirror:
        kwargs["final_values"] = s((T, E), np.float32)
        kwargs["bootstrap_value"] = s((E,), np.float32)
    if cfg.anneal_iters > 0:
        kwargs["progress"] = s((), np.float32)
    jitted = make_host_update_step(ctx.spec, cfg, can_truncate=True)
    return lambda: _compile_cache.aot_compile(jitted, *args, **kwargs)


@_compile_cache.register_warmup("ppo.make_async_update_step")
def _warmup_async_update(ctx):
    """The async learner's corrected-update program ([T, E_a] blocks) —
    registered so cold starts keep the PR 4 warm-path win and the
    steady-state compile-count regression test stays at zero."""
    if (
        ctx.fused or ctx.algo != "ppo" or not ctx.async_actors
        or ctx.data_plane == "device"  # ISSUE 13: device plane runs
        # ppo.make_device_update_step instead — same correction, but
        # the block arrives via the in-jit ring gather, not arguments.
    ):
        return None
    import numpy as np

    cfg = ctx.cfg
    T = cfg.rollout_steps
    E_a = cfg.num_envs // ctx.async_actors
    s = _compile_cache.array_struct
    # Acting is always the numpy mirror in async mode → int64 actions.
    args = _host_update_structs(ctx, E_a, mirror=True)
    kwargs = {}
    if ctx.async_correction == "none":
        kwargs["final_values"] = s((T, E_a), np.float32)
        kwargs["bootstrap_value"] = s((E_a,), np.float32)
    if cfg.anneal_iters > 0:
        kwargs["progress"] = s((), np.float32)
    jitted = make_async_update_step(
        ctx.spec, cfg, can_truncate=True, correction=ctx.async_correction
    )
    return lambda: _compile_cache.aot_compile(jitted, *args, **kwargs)


@_compile_cache.register_warmup("ppo.make_device_update_step")
def _warmup_device_update(ctx):
    """The device-data-plane learner program (ISSUE 13): ring gather +
    codec decode + corrected update in one executable — warmed so the
    new plane keeps the steady-state-zero-recompile contract the host
    plane's program has."""
    if (
        ctx.fused or ctx.algo != "ppo" or not ctx.async_actors
        or ctx.data_plane != "device"
    ):
        return None
    import numpy as np

    from actor_critic_tpu.data_plane import codecs as np_codecs
    from actor_critic_tpu.data_plane import ring as dp_ring

    cfg = ctx.cfg
    block_spec = async_block_spec(
        ctx.spec, cfg, ctx.async_actors, ctx.async_correction
    )
    kinds = np_codecs.traj_codecs(ctx.plane_codec, block_spec)
    state_abs = dp_ring.abstract_ring_state(
        block_spec, ctx.queue_depth, kinds
    )
    params_abs, opt_abs = _abstract_host_params(ctx.spec, cfg)
    kwargs = {}
    if cfg.anneal_iters > 0:
        kwargs["progress"] = _compile_cache.array_struct((), np.float32)
    jitted = make_device_update_step(
        ctx.spec, cfg, kinds, can_truncate=True,
        correction=ctx.async_correction,
    )
    return lambda: _compile_cache.aot_compile(
        jitted, params_abs, opt_abs, state_abs,
        _compile_cache.scalar_struct(np.int32),
        _compile_cache.key_struct(), **kwargs,
    )


@_compile_cache.register_warmup("ppo.make_greedy_act")
def _warmup_greedy_act(ctx):
    if ctx.fused or ctx.algo != "ppo" or ctx.eval_every <= 0:
        return None
    params_abs, _ = _abstract_host_params(ctx.spec, ctx.cfg)
    if _compile_cache.greedy_mirror_active(params_abs):
        return None  # eval mirrors on the host; this program never runs
    obs = _compile_cache.host_obs_struct(ctx, (ctx.eval_envs,))
    return _compile_cache.jitted_thunk(
        make_greedy_act(ctx.spec, ctx.cfg), params_abs, obs
    )


@_compile_cache.register_warmup("ppo.make_train_step")
def _warmup_fused_step(ctx):
    if not ctx.fused or ctx.algo != "ppo":
        return None
    return _compile_cache.fused_step_thunk(ctx, init_state, make_train_step)


@_compile_cache.register_warmup("ppo.make_eval_fn")
def _warmup_fused_eval(ctx):
    if not ctx.fused or ctx.algo != "ppo":
        return None
    return _compile_cache.fused_eval_thunk(ctx, init_state, make_eval_fn)


def make_train_step(
    env: JaxEnv,
    cfg: PPOConfig,
    axis_name: Optional[str] = None,
) -> Callable[[TrainState], tuple[TrainState, dict[str, jax.Array]]]:
    """Fused PPO iteration for pure-JAX envs (same contract as a2c's)."""
    net = make_network(env.spec, cfg)
    opt = make_optimizer(cfg)
    apply_fn = net.apply

    def train_step(state: TrainState) -> tuple[TrainState, dict[str, jax.Array]]:
        key, rkey, ukey = jax.random.split(state.key, 3)

        new_rollout, traj = rollout_scan(
            env, apply_fn, state.params, state.rollout, rkey, cfg.rollout_steps
        )

        _, bootstrap_value = apply_fn(state.params, new_rollout.obs)
        T, E = traj.reward.shape
        if env.spec.can_truncate:
            _, final_values = apply_fn(
                state.params,
                traj.final_obs.reshape(T * E, *traj.final_obs.shape[2:]),
            )
            rewards = truncation_bootstrap_rewards(
                traj, final_values.reshape(T, E), cfg.gamma
            )
        else:
            rewards = traj.reward
        advantages, returns = gae(
            rewards, traj.value, traj.done, bootstrap_value, cfg.gamma, cfg.gae_lambda
        )

        batch = PPOBatch(
            obs=traj.obs.reshape(T * E, *traj.obs.shape[2:]),
            action=traj.action.reshape(T * E, *traj.action.shape[2:]),
            log_prob_old=traj.log_prob.reshape(T * E),
            value_old=traj.value.reshape(T * E),
            advantage=advantages.reshape(T * E),
            ret=returns.reshape(T * E),
        )
        new_params, new_opt_state, metrics = ppo_update(
            state.params, state.opt_state, batch, ukey, apply_fn, opt, cfg,
            axis_name, progress=anneal_progress(cfg, state.update_step),
            unroll=should_unroll_update(env.spec, cfg),
        )

        ep_ret, ep_len, avg_ret, ep_metrics = episode_metrics_update(
            state.ep_return, state.ep_length, state.avg_return, traj
        )
        avg_ret = pmesh.pmean(avg_ret, axis_name)
        ep_metrics["avg_return_ema"] = avg_ret
        metrics = aggregate_metrics(metrics, ep_metrics, axis_name)

        return (
            TrainState(
                params=new_params,
                opt_state=new_opt_state,
                rollout=new_rollout,
                key=key,
                update_step=state.update_step + 1,
                ep_return=ep_ret,
                ep_length=ep_len,
                avg_return=avg_ret,
            ),
            metrics,
        )

    return train_step
